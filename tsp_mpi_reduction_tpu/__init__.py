"""TPU-native framework with the capabilities of JZHeadley/TSP-MPI-Reduction.

A distributed Euclidean TSP solver: the plane is partitioned into rectangular
blocks, each block is solved exactly with Held-Karp dynamic programming, and
block tours are stitched together through a deterministic merge tree with a
2-opt-style edge-swap operator. The reference (/root/reference, C++/MPI) is the
behavioral oracle; this package re-designs every component TPU-first:

- blocks are a vmapped batch dimension (reference: one block per MPI message,
  tsp.cpp:159-195);
- the Held-Karp table is a dense ``[2^(n-1), n-1]`` HBM array swept by
  cardinality (reference: ``std::map`` keyed by bitmask, tsp.cpp:405-509);
- the merge is a broadcasted swap-cost matrix + argmin + gather splice
  (reference: O(n1*n2) rotate scan, tsp.cpp:202-269);
- the cross-rank reduction is an on-mesh merge tree under ``shard_map`` with
  ``ppermute``/``pmin`` collectives (reference: hand-rolled binary-tree
  MPI_Send/Recv, tsp.cpp:52-134).

Layout:
    ops/       numerics: glibc-rand replica, instance generator, distance,
               Held-Karp DP kernel, tour-merge operator
    models/    solver pipelines: blocked pipeline, branch-and-bound (TSPLIB)
    parallel/  mesh construction, sharding, distributed merge-tree reduction
    utils/     CLI compat surface, TSPLIB parser, timing, reporting
    native/    C++ runtime components (rand, generator, oracle) via ctypes
"""

__version__ = "0.1.0"

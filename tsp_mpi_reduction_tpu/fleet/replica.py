"""One serve replica: a subprocess speaking the JSONL serve contract.

A replica is the EXISTING ``SolveService`` running under the serve CLI
(``python -m tsp_mpi_reduction_tpu serve --in - --out -``): the front
writes request lines to its stdin and a reader thread parses response
lines off its stdout, resolving fleet tickets by fleet-assigned request
id. Stderr is also read: the serve CLI announces its ephemeral metrics
endpoint there (``metrics: http://127.0.0.1:PORT/metrics``), which is how
the supervisor learns each replica's scrape target without port
coordination; the last few other stderr lines are retained for death
diagnostics.

Liveness evidence this class maintains (all under one lock — request
threads, the reader threads, and the supervisor's monitor thread all
touch it):

- process state (``proc.poll()``);
- per-request in-flight table (fleet id -> dispatch timestamp) — the
  supervisor drains it on death so the front can re-dispatch;
- response-flow recency (``last_response_at``) — a wedged-but-alive
  process (SIGSTOP, a hung device dispatch) stops producing responses
  while ``poll()`` stays None;
- ``/metrics.json`` scrape totals + consecutive-failure count.

The command line is injectable (:class:`ReplicaSpec`) so tests can run a
lightweight stub replica without paying a jax import per process; the
front builds the real serve argv by default.
"""

from __future__ import annotations

import collections
import json
import re
import signal
import subprocess
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from ..resilience.faults import TransientFault

#: the serve CLI's stderr announcement of its bound metrics port
_METRICS_LINE = re.compile(r"metrics: http://127\.0\.0\.1:(\d+)/metrics")


@dataclass
class ReplicaSpec:
    """How to launch one replica process."""

    argv: List[str]
    env: Optional[Dict[str, str]] = None
    #: parse the metrics announcement off stderr and scrape
    #: ``/metrics.json`` as the second liveness probe (the real serve
    #: CLI); False for stub replicas without a metrics endpoint
    scrape: bool = True
    #: extra labels for stats (e.g. the backend) — informational only
    meta: Dict[str, str] = field(default_factory=dict)


def _summarize_metrics(data: Dict) -> Dict[str, int]:
    """Reduce a ``/metrics.json`` snapshot to the per-replica totals the
    fleet stats block (and ``obs_report --fleet``) renders."""

    def total(name: str, **want) -> int:
        out = 0.0
        for entry in data.get(name, {}).get("series", []):
            labels = entry.get("labels", {})
            if all(labels.get(k) == v for k, v in want.items()):
                out += entry.get("value", 0.0)
        return int(out)

    return {
        "responses": total("serve_responses_total"),
        "errors": total("serve_errors_total"),
        "deadline_misses": total("serve_deadline_misses_total"),
        "cache_hits": total("serve_cache_lookups_total", result="hit"),
        "cache_misses": total("serve_cache_lookups_total", result="miss"),
        # disk-tier traffic: a replica's shared hit means ANOTHER process
        # published the entry (its own publishes land in its L1 first) —
        # the cross-replica cache-serving evidence the fleet bench gates
        "shared_cache_hits": total(
            "fleet_shared_cache_ops_total", op="get", outcome="hit"
        ),
        "shared_cache_publishes": total(
            "fleet_shared_cache_ops_total", op="put", outcome="published"
        ),
    }


class Replica:
    """Process handle + pipes + liveness bookkeeping for one replica."""

    def __init__(
        self,
        idx: int,
        spec: ReplicaSpec,
        on_response: Callable[[str, Dict, "Replica"], None],
    ) -> None:
        self.idx = idx
        self.spec = spec
        self._on_response = on_response
        self._lock = threading.Lock()
        #: serializes stdin WRITES only — kept separate from the state
        #: lock because a pipe write can BLOCK (a wedged replica that
        #: stopped draining stdin, OS buffer full), and a blocked writer
        #: holding the state lock would also block the supervisor's
        #: probes, making the very wedge-kill that would unblock the
        #: write impossible (fleet-wide deadlock)
        self._write_lock = threading.Lock()
        self.proc: Optional[subprocess.Popen] = None
        self.generation = 0
        #: fleet id -> monotonic dispatch timestamp
        self.in_flight: Dict[str, float] = {}
        self.last_response_at: float = 0.0
        self.started_at: float = 0.0
        self.metrics_port: Optional[int] = None
        self.scrape_totals: Dict[str, int] = {}
        self.scrape_failures = 0
        self._last_scrape_attempt = 0.0
        self.restarts = 0
        self.restart_attempt = 0
        self.restart_due_at: Optional[float] = None
        self.suspected_wedged = False
        self.dispatched = 0
        self.answered = 0
        self._stderr_tail: "collections.deque[str]" = collections.deque(maxlen=8)

    # -- lifecycle -----------------------------------------------------------

    def spawn(self) -> None:
        """Start (or restart) the subprocess and its reader threads."""
        with self._lock:
            self.generation += 1
            gen = self.generation
            # a send() racing the death drain can slip one last entry in
            # (poll() lags a SIGKILL) — nothing will ever answer it, so
            # clear here or it ages into false wedge evidence forever
            self.in_flight.clear()
            self.metrics_port = None
            self.scrape_failures = 0
            self._last_scrape_attempt = 0.0
            self.suspected_wedged = False
            self.started_at = time.monotonic()
            self.last_response_at = self.started_at
            self.restart_due_at = None
            proc = subprocess.Popen(
                self.spec.argv,
                stdin=subprocess.PIPE,
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                text=True,
                bufsize=1,
                env=self.spec.env,
            )
            self.proc = proc
        threading.Thread(
            target=self._read_stdout, args=(proc, gen),
            name=f"fleet-r{self.idx}-out", daemon=True,
        ).start()
        threading.Thread(
            target=self._read_stderr, args=(proc, gen),
            name=f"fleet-r{self.idx}-err", daemon=True,
        ).start()

    def alive(self) -> bool:
        with self._lock:
            return (
                self.proc is not None
                and self.proc.poll() is None
                and not self.suspected_wedged
            )

    def kill(self) -> None:
        """SIGKILL the current process (also works on a SIGSTOPped one)."""
        with self._lock:
            proc = self.proc
        if proc is not None and proc.poll() is None:
            try:
                proc.kill()
            except OSError:
                pass

    def suspend(self) -> None:
        """SIGSTOP — the injected ``replica.hang``: alive to ``poll()``,
        silent to everything else."""
        with self._lock:
            proc = self.proc
        if proc is not None and proc.poll() is None:
            try:
                proc.send_signal(signal.SIGSTOP)
            except OSError:
                pass

    def terminate(self, grace_s: float = 2.0) -> None:
        """Graceful shutdown: close stdin (EOF ends the serve loop), then
        terminate/kill on a timeout."""
        with self._lock:
            proc = self.proc
        if proc is None:
            return
        try:
            if proc.stdin is not None:
                proc.stdin.close()
        except OSError:
            pass
        try:
            proc.wait(timeout=grace_s)
        except subprocess.TimeoutExpired:
            try:
                proc.terminate()
                proc.wait(timeout=1.0)
            except (subprocess.TimeoutExpired, OSError):
                proc.kill()

    # -- dispatch ------------------------------------------------------------

    def send(self, fleet_id: str, line: str) -> None:
        """Write one request line; raises :class:`TransientFault` when the
        pipe is gone (a dead replica — the dispatch retry absorbs it).

        The in-flight entry is recorded under the WRITE lock, just
        before the write: insertion order then equals stdin write order
        — which is the replica's answer order (run_jsonl preserves input
        order), so a null-id error answer attributes to the FIRST
        in-flight entry correctly. Recording before the write (not
        after) means a write that blocks on a wedged replica's full pipe
        leaves aged in-flight evidence for the wedge rule, whose SIGKILL
        then fails this write with EPIPE. A failed write un-records its
        own entry. Lock order is write->state, nowhere reversed."""
        with self._lock:
            proc = self.proc
            if proc is None or proc.poll() is not None or proc.stdin is None:
                raise TransientFault(f"replica {self.idx} is not running")
        with self._write_lock:
            with self._lock:
                self.in_flight[fleet_id] = time.monotonic()
            try:
                proc.stdin.write(line + "\n")
                proc.stdin.flush()
            except (BrokenPipeError, OSError, ValueError) as e:
                with self._lock:
                    self.in_flight.pop(fleet_id, None)
                raise TransientFault(f"replica {self.idx} pipe: {e}") from None
        with self._lock:
            self.dispatched += 1

    def running(self) -> bool:
        """Process-level liveness only (a wedged replica still runs)."""
        with self._lock:
            return self.proc is not None and self.proc.poll() is None

    def inflight_count(self) -> int:
        with self._lock:
            return len(self.in_flight)

    def restart_due(self, now: float) -> Optional[bool]:
        """None = no restart scheduled; else whether its backoff elapsed."""
        with self._lock:
            if self.restart_due_at is None:
                return None
            return now >= self.restart_due_at

    def schedule_restart(self, delay_for_attempt) -> Optional[int]:
        """Mark this replica dead and schedule its respawn after the
        backoff ``delay_for_attempt(attempt)``. Returns the attempt
        number, or None when a death is ALREADY being handled (the
        idempotence guard — the front's injected-kill path and the
        monitor can both observe one death)."""
        with self._lock:
            if self.restart_due_at is not None:
                return None
            self.restart_attempt += 1
            self.suspected_wedged = True  # out of the pick set until respawn
            self.restart_due_at = time.monotonic() + delay_for_attempt(
                self.restart_attempt
            )
            return self.restart_attempt

    def maybe_reset_backoff(self, now: float, healthy_after_s: float) -> None:
        """A replica that stayed healthy earns its backoff curve back."""
        with self._lock:
            if (
                self.restart_attempt
                and self.restart_due_at is None
                and now - self.started_at > healthy_after_s
            ):
                self.restart_attempt = 0

    def note_respawned(self) -> None:
        with self._lock:
            self.restarts += 1

    def drain_in_flight(self) -> List[str]:
        """Take every in-flight fleet id (death handling: the front
        re-dispatches or degrades each)."""
        with self._lock:
            fids = list(self.in_flight)
            self.in_flight.clear()
        return fids

    # -- liveness evidence ---------------------------------------------------

    def oldest_inflight_age(self, now: Optional[float] = None) -> Optional[float]:
        now = time.monotonic() if now is None else now
        with self._lock:
            if not self.in_flight:
                return None
            return now - min(self.in_flight.values())

    def response_idle_age(self, now: Optional[float] = None) -> float:
        now = time.monotonic() if now is None else now
        with self._lock:
            return now - self.last_response_at

    def age_since_spawn(self, now: Optional[float] = None) -> float:
        now = time.monotonic() if now is None else now
        with self._lock:
            return now - self.started_at

    def consecutive_scrape_failures(self) -> int:
        with self._lock:
            return self.scrape_failures

    def metrics_port_known(self) -> bool:
        with self._lock:
            return self.metrics_port is not None

    def scrape_due(self, now: float, interval_s: float) -> bool:
        """Claim the next scrape slot (rate limiting lives here so the
        monitor stays stateless): True at most once per ``interval_s``.
        A wedged replica's probe blocks its full HTTP timeout, so
        unthrottled per-tick scraping would stretch the whole fleet's
        monitor cycle — and the veto/stats only need ~1 Hz freshness."""
        with self._lock:
            if now - self._last_scrape_attempt < interval_s:
                return False
            self._last_scrape_attempt = now
            return True

    def scrape(self, timeout_s: float = 0.75) -> Optional[Dict[str, int]]:
        """Probe ``/metrics.json``. An unreachable or hanging endpoint —
        a SIGSTOPped replica accepts the TCP connect into the listen
        backlog and then never answers, which the timeout converts into
        probe evidence — returns None and counts a consecutive failure.
        An UNKNOWN port (not yet announced) also returns None but counts
        nothing: the supervisor's wedge veto requires a known endpoint,
        so an unannounced replica is judged by the timing rule alone."""
        with self._lock:
            port = self.metrics_port
        if port is None:
            return None
        import urllib.request

        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics.json", timeout=timeout_s
            ) as r:
                data = json.load(r)
            totals = _summarize_metrics(data)
        except Exception:  # noqa: BLE001 — any probe failure is evidence
            with self._lock:
                self.scrape_failures += 1
            return None
        with self._lock:
            self.scrape_totals = totals
            self.scrape_failures = 0
        return totals

    def snapshot(self) -> Dict:
        """JSON-ready state row for the front's stats ``fleet`` block."""
        with self._lock:
            proc = self.proc
            return {
                "index": self.idx,
                "pid": None if proc is None else proc.pid,
                "alive": proc is not None
                and proc.poll() is None
                and not self.suspected_wedged,
                "generation": self.generation,
                "restarts": self.restarts,
                "dispatched": self.dispatched,
                "answered": self.answered,
                "in_flight": len(self.in_flight),
                "metrics_port": self.metrics_port,
                "scrape": dict(self.scrape_totals),
                "meta": dict(self.spec.meta),
            }

    # -- reader threads ------------------------------------------------------

    def _read_stdout(self, proc: subprocess.Popen, gen: int) -> None:
        try:
            for line in proc.stdout:
                line = line.strip()
                if not line:
                    continue
                try:
                    resp = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if not isinstance(resp, dict):
                    continue
                fid = resp.get("id")
                with self._lock:
                    if self.generation != gen:
                        return  # a restarted replica owns the name now
                    if fid is None and self.in_flight:
                        # a replica-internal error answer (run_jsonl's
                        # catch-all emits {"id": null, "error": ...}).
                        # The serve contract answers in INPUT order and
                        # send() records entries in stdin WRITE order,
                        # so it belongs to the FIRST in-flight entry —
                        # attribute it there, or the entry would sit
                        # forever as false wedge evidence and the
                        # waiting ticket would burn its hop timeout for
                        # an answer that already arrived
                        fid = next(iter(self.in_flight))
                    if fid is not None:
                        self.in_flight.pop(fid, None)
                    self.last_response_at = time.monotonic()
                    self.answered += 1
                self._on_response(fid, resp, self)
        except (OSError, ValueError):
            pass  # torn pipe at death: the monitor handles the process

    def _read_stderr(self, proc: subprocess.Popen, gen: int) -> None:
        try:
            for line in proc.stderr:
                line = line.rstrip("\n")
                m = _METRICS_LINE.search(line) if self.spec.scrape else None
                with self._lock:
                    # same generation guard as the stdout reader: a
                    # killed process's buffered announcement must not
                    # write a DEAD port over the successor's (every
                    # scrape would then fail and disable the slow-vs-
                    # stuck veto for a healthy replica)
                    if self.generation != gen:
                        return
                    if m:
                        self.metrics_port = int(m.group(1))
                    elif line.strip():
                        self._stderr_tail.append(line)
        except (OSError, ValueError):
            pass

    def stderr_tail(self) -> List[str]:
        with self._lock:
            return list(self._stderr_tail)

"""Shared disk-backed solution-cache tier (ISSUE 11).

The serve instance cache (``serve.cache.SolutionCache``) is per-process:
a fleet of N replicas would each re-solve an instance the fleet as a
whole has already answered, and a restarted replica starts cold. This
module promotes the cache to a two-level tier:

- **L1**: each replica's existing in-process LRU, unchanged semantics;
- **L2**: one directory shared by every replica (and the front), one
  file per canonical key, published ATOMICALLY via the
  ``resilience/checkpoint.py`` recipe (``pack`` header + temp + fsync +
  ``os.replace``) so a reader never observes a half-written entry.

Failure posture mirrors ``read_with_fallback``: a torn, truncated, or
bit-rotted entry is DETECTED by the checkpoint header checksum and
skipped as a miss (counted in ``corrupt_skipped``) — never parsed into a
wrong tour. Concurrent publishers of the same key are arbitrated by the
PR 3 better-entry policy (:meth:`serve.cache.CacheEntry.better_than`):
a publish first reads the current entry and keeps the stronger one, so a
greedy answer racing a certified optimum can at worst waste a write of
the certified entry's own bytes — the replace is atomic, both images are
valid, and the next certified publish restores the stronger entry.

Entry file layout: the TSPCKPT1 container with the canonical key as the
integrity fingerprint, entry metadata (cost / certified_gap / tier) in
the JSON header, and the canonical CLOSED tour as an npz payload.
"""

from __future__ import annotations

import os
from typing import Dict, Optional

import numpy as np

from ..obs.metrics import REGISTRY as _REGISTRY
from ..resilience.checkpoint import (
    CheckpointError,
    npz_bytes,
    read_with_fallback,
    sweep_stale_tmp,
    write_atomic,
)
from ..resilience.faults import TransientFault
from ..serve.cache import CacheEntry, SolutionCache

#: on-disk entry suffix (one file per canonical key)
ENTRY_SUFFIX = ".entry"


class SharedCacheTier:
    """The disk (L2) tier: canonical key -> one atomic entry file.

    Thread- and process-safe by construction: reads never lock (the
    entry file is immutable between ``os.replace`` publishes), and
    writes go through the crash-safe checkpoint writer. Every failure
    degrades to a miss or a dropped publish — disk trouble must never
    cost a request its answer."""

    def __init__(self, root: str) -> None:
        import threading

        self.root = root
        os.makedirs(root, exist_ok=True)
        # a PERSISTENT shared dir reused across fleets accumulates one
        # orphaned temp per publisher SIGKILLed mid-publish (replica
        # kills are this subsystem's normal weather) — reap them here,
        # age-bounded so a concurrent booting replica's live publish is
        # never raced
        sweep_stale_tmp(root)
        # per-INSTANCE counters (stats must describe this tier object,
        # not every tier the process ever made), mirrored into the
        # process registry so a replica's /metrics.json scrape carries
        # them fleet-wide
        self._counts_lock = threading.Lock()
        self._counts: Dict[str, int] = {
            "hits": 0,
            "misses": 0,
            "corrupt_skipped": 0,
            "publishes": 0,
            "kept_better": 0,
            "dropped_puts": 0,
        }
        _REGISTRY.declare(
            "fleet_shared_cache_ops_total", "counter",
            "shared disk cache tier operations, by op/outcome",
        )

    def _count(self, name: str, op: str, outcome: str) -> None:
        with self._counts_lock:
            self._counts[name] += 1
        _REGISTRY.inc("fleet_shared_cache_ops_total", op=op, outcome=outcome)

    def _path(self, key: str) -> str:
        # keys are hex digests (serve.canonical) — safe as file names
        return os.path.join(self.root, key + ENTRY_SUFFIX)

    # -- read ----------------------------------------------------------------

    def get(self, key: str) -> Optional[CacheEntry]:
        entry, outcome = self._read(key)
        name = {"hit": "hits", "miss": "misses"}.get(outcome, "corrupt_skipped")
        self._count(name, "get", outcome)
        return entry

    def _read(self, key: str):
        """``(entry-or-None, outcome)`` without counting — shared by the
        client-facing :meth:`get` (which counts) and the publisher-side
        better-entry check (which must not inflate the hit/miss stats)."""
        path = self._path(key)
        if not os.path.exists(path):
            return None, "miss"
        try:
            header, payload, _, _ = read_with_fallback(path, keep=1)
            entry = _decode(header, payload, key)
        except (CheckpointError, KeyError, ValueError, OSError, TransientFault):
            # torn / truncated / bit-rotted / unreadable: skipped as a
            # miss, exactly the read_with_fallback posture — the entry is
            # re-published by whichever replica re-solves the instance
            return None, "corrupt_skipped"
        return entry, "hit"

    # -- write ---------------------------------------------------------------

    def put(self, key: str, entry: CacheEntry) -> None:
        """Publish ``entry`` unless the current disk entry is better
        (the L1 replacement policy, applied across processes). The
        read-check-publish window is racy by design: both racers hold
        valid entries, the replace is atomic, and the better-entry check
        on every future publish is what converges the file to the
        strongest known answer."""
        current, _ = self._read(key)
        if current is not None and not entry.better_than(current):
            self._count("kept_better", "put", "kept_better")
            return
        try:
            write_atomic(
                self._path(key),
                npz_bytes(tour=np.asarray(entry.tour, np.int32)),
                fingerprint=key,
                keep=1,
                extra_header={
                    "entry": {
                        "cost": float(entry.cost),
                        "certified_gap": (
                            None
                            if entry.certified_gap is None
                            else float(entry.certified_gap)
                        ),
                        "tier": str(entry.tier),
                    }
                },
            )
        except (OSError, TransientFault):
            # a failed publish is a dropped put (same degrade as the L1
            # cache.put seam): the next solve of the instance retries it
            self._count("dropped_puts", "put", "dropped")
            return
        self._count("publishes", "put", "published")

    # -- stats ---------------------------------------------------------------

    def stats(self) -> Dict[str, int]:
        with self._counts_lock:
            return dict(self._counts)


def _decode(header: Optional[Dict], payload: bytes, key: str) -> CacheEntry:
    """Entry file image -> CacheEntry; raises on any malformed field (the
    caller treats that as corrupt-skipped, not an error response)."""
    import io

    if header is None or header.get("fingerprint") != key:
        raise ValueError("entry header missing or keyed to a different instance")
    meta = header.get("entry")
    if not isinstance(meta, dict):
        raise ValueError("entry metadata block missing")
    with np.load(io.BytesIO(payload)) as z:
        tour = np.asarray(z["tour"], np.int32)
    if tour.ndim != 1 or tour.shape[0] < 2 or tour[0] != tour[-1]:
        raise ValueError("entry tour is not a closed tour")
    gap = meta.get("certified_gap")
    return CacheEntry(
        cost=float(meta["cost"]),
        tour=tour,
        certified_gap=None if gap is None else float(gap),
        tier=str(meta["tier"]),
    )


class TieredSolutionCache(SolutionCache):
    """L1 in-process LRU over the shared L2 disk tier.

    ``get``: L1 first; an L1 miss consults the disk tier and PROMOTES a
    hit into L1 (so a restarted replica warm-fills from the fleet's
    collective work one key at a time). ``put``: L1 plus a disk publish,
    each guarded by its own better-entry policy. The service-level
    provenance ("hit") is tier-agnostic — a cross-replica disk hit looks
    exactly like a local one to the client."""

    def __init__(self, capacity: int, root: str) -> None:
        super().__init__(capacity)
        self.shared = SharedCacheTier(root)

    def get(self, key: str) -> Optional[CacheEntry]:
        entry = super().get(key)
        if entry is not None:
            return entry
        entry = self.shared.get(key)
        if entry is not None:
            try:
                super().put(key, entry)  # promote; fires the cache.put seam
            except TransientFault:
                pass  # a failed promotion must not turn the hit into a miss
        return entry

    def put(self, key: str, entry: CacheEntry) -> None:
        super().put(key, entry)
        self.shared.put(key, entry)

    def stats(self) -> Dict[str, int]:
        return dict(super().stats(), shared=self.shared.stats())

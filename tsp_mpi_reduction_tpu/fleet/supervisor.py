"""Replica supervision: liveness probing, bounded-backoff restart,
death-driven re-dispatch hand-off (ISSUE 11 tentpole core).

The PR 4 scheduler watchdog generalized from a worker THREAD to a worker
PROCESS: one monitor thread walks the replica set every
``probe_interval_s`` and

- declares a replica **dead** when ``poll()`` returns (crash, injected
  ``replica.kill``, OOM-kill);
- declares it **wedged** when it is alive to ``poll()`` but has carried
  in-flight requests past ``wedge_timeout_s`` with no response flow in
  that window (SIGSTOP via ``replica.hang``, a hung device dispatch) —
  wedged replicas are SIGKILLed and handled as deaths, the PR 4
  stuck-worker recipe at process granularity;
- scrapes each healthy replica's ``/metrics.json`` (when announced) so
  the fleet stats block always carries fresh per-replica totals, and so
  a stalled HTTP endpoint contributes wedge evidence.

Restarts use the ``resilience/retry.py`` backoff curve per replica:
attempt k waits ``min(base * 2^(k-1), max)`` with the policy's seeded
jitter, so a crash-looping replica cannot spin the host, and a replica
that stays healthy for ``healthy_reset_s`` earns its backoff back. Every
respawn counts into ``HEALTH.fleet_replica_restarts`` and
``fleet_replica_restarts_total``.

On death the supervisor DRAINS the replica's in-flight table and hands
the fleet ids to the front's ``on_death`` callback, which aborts the
affected tickets so their waiting request threads re-dispatch
immediately (deadline-aware — see ``fleet.front``) instead of burning
their hop timeout against a corpse.
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

from ..obs.metrics import REGISTRY as _REGISTRY
from ..resilience.health import HEALTH
from ..resilience.retry import RetryPolicy
from .replica import Replica, ReplicaSpec

#: bounded death reasons (metric label cardinality stays fixed)
DEATH_REASONS = ("exit", "wedged", "injected_kill")


@dataclass
class SupervisorConfig:
    probe_interval_s: float = 0.2
    #: in-flight age AND response silence past this = wedged
    wedge_timeout_s: float = 30.0
    #: no wedge verdicts this soon after spawn: a cold replica (jax
    #: import + first XLA compile) is legitimately silent for seconds,
    #: and killing it re-pays the very startup that made it slow — the
    #: PR 4 lesson ("a long cold compile can't cascade phantom
    #: restarts") at process granularity. The shared fleet compile
    #: cache shrinks real restarts' exposure to this window.
    startup_grace_s: float = 30.0
    restart_backoff_base_s: float = 0.5
    restart_backoff_max_s: float = 10.0
    #: a replica alive this long after spawn resets its backoff curve
    healthy_reset_s: float = 30.0
    scrape_timeout_s: float = 0.75
    #: scrape cadence per replica (the probe tick can be much faster —
    #: a wedged replica's probe blocks its whole HTTP timeout, so
    #: scraping every tick would stall fleet-wide death detection)
    scrape_interval_s: float = 1.0


class ReplicaSupervisor:
    """Owns the replica set and the monitor thread."""

    def __init__(
        self,
        specs: List[ReplicaSpec],
        cfg: SupervisorConfig,
        on_response: Callable[[str, Dict, Replica], None],
        on_death: Callable[[Replica, List[str], str], None],
    ) -> None:
        self.cfg = cfg
        self._on_death = on_death
        self.replicas = [
            Replica(i, spec, on_response) for i, spec in enumerate(specs)
        ]
        #: the backoff curve (delay_s only — the supervisor schedules its
        #: own sleeps; RetryPolicy.call would block the monitor thread)
        self._backoff = RetryPolicy(
            max_attempts=1_000_000,
            base_delay_s=cfg.restart_backoff_base_s,
            max_delay_s=cfg.restart_backoff_max_s,
            seed=0,
        )
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        _REGISTRY.declare(
            "fleet_replica_restarts_total", "counter",
            "replica processes restarted by the fleet supervisor",
        )
        _REGISTRY.declare(
            "fleet_replica_deaths_total", "counter",
            "replica deaths observed by the supervisor, by reason",
        )

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        for rep in self.replicas:
            rep.spawn()
        self._thread = threading.Thread(
            target=self._monitor, name="fleet-supervisor", daemon=True
        )
        self._thread.start()

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        for rep in self.replicas:
            rep.terminate()

    def alive_count(self) -> int:
        return sum(1 for rep in self.replicas if rep.alive())

    def pick(self, exclude: Optional[Replica] = None) -> Optional[Replica]:
        """Least-loaded alive replica, preferring any over ``exclude``
        (a re-dispatch should land on a DIFFERENT replica when one
        exists — the excluded one just failed this request)."""
        alive = [rep for rep in self.replicas if rep.alive()]
        if not alive:
            return None
        preferred = [rep for rep in alive if rep is not exclude] or alive
        with_load = [(rep.inflight_count(), rep.idx, rep) for rep in preferred]
        return min(with_load)[2]

    def snapshot(self) -> List[Dict]:
        return [rep.snapshot() for rep in self.replicas]

    # -- injected faults (the front's seam translations) ---------------------

    def kill_replica(self, rep: Replica, reason: str = "injected_kill") -> None:
        """SIGKILL now and run death handling immediately — the chaos
        path must not wait a probe interval to start healing."""
        rep.kill()
        self._handle_death(rep, reason)

    def suspend_replica(self, rep: Replica) -> None:
        """SIGSTOP — detected later by the wedge rule, exactly like a
        real hang would be."""
        rep.suspend()

    # -- monitor loop --------------------------------------------------------

    def _monitor(self) -> None:
        while not self._stop.wait(self.cfg.probe_interval_s):
            now = time.monotonic()
            for rep in self.replicas:
                self._probe_one(rep, now)

    def _probe_one(self, rep: Replica, now: float) -> None:
        if not rep.running():
            due = rep.restart_due(now)
            if due is None:
                # death not yet handled (a crash the front didn't cause)
                self._handle_death(rep, "exit")
            elif due:
                self._respawn(rep)
            return
        # scrape FIRST (rate-limited): the totals feed the stats block,
        # and the probe's consecutive-failure count is the wedge rule's
        # second signal
        if rep.spec.scrape and rep.scrape_due(now, self.cfg.scrape_interval_s):
            rep.scrape(self.cfg.scrape_timeout_s)
        # wedge rule — in-flight work aged past the threshold AND no
        # response flow in that window (a long exact solve keeps
        # producing OTHER responses; a SIGSTOP produces nothing), with a
        # startup grace so a cold process is never killed for booting.
        # When the replica exposes a metrics endpoint, a RESPONSIVE
        # scrape vetoes the verdict: a replica paying a long first
        # compile still answers HTTP from its daemon thread, while a
        # SIGSTOPped (or truly hung) process times the probe out — so
        # slow stays alive and stuck gets killed, the PR 4 distinction
        # at process granularity.
        oldest = rep.oldest_inflight_age(now)
        wedged = (
            rep.age_since_spawn(now) > self.cfg.startup_grace_s
            and oldest is not None
            and oldest > self.cfg.wedge_timeout_s
            and rep.response_idle_age(now) > self.cfg.wedge_timeout_s
        )
        # the veto needs a KNOWN endpoint: before the replica announces
        # its port, the probe cannot distinguish slow from stuck, so the
        # timing rule stands alone (a replica hung before announcing
        # would otherwise be un-killable — scrape() counts no failures
        # while the port is unknown, and the veto would hold forever)
        if (
            wedged
            and rep.spec.scrape
            and rep.metrics_port_known()
            and rep.consecutive_scrape_failures() < 2
        ):
            wedged = False  # endpoint still answering: slow, not stuck
        if wedged:
            HEALTH.incr("stuck_restarts")
            rep.kill()
            self._handle_death(rep, "wedged")
            return
        rep.maybe_reset_backoff(now, self.cfg.healthy_reset_s)

    def _handle_death(self, rep: Replica, reason: str) -> None:
        """Schedule the backoff respawn and hand the in-flight work back
        to the front. Idempotent per death: a second observer finds the
        restart already scheduled and the in-flight table drained."""
        # jitter RNG seeded per (replica, attempt): deterministic replay
        # (chaos runs) without a monitor/request-thread-shared Random
        attempt = rep.schedule_restart(
            lambda k: self._backoff.delay_s(
                k, random.Random((rep.idx << 16) | k)
            )
        )
        if attempt is None:
            return  # already handled
        _REGISTRY.inc(
            "fleet_replica_deaths_total",
            reason=reason if reason in DEATH_REASONS else "exit",
        )
        fids = rep.drain_in_flight()
        self._on_death(rep, fids, reason)

    def _respawn(self, rep: Replica) -> None:
        rep.spawn()
        rep.note_respawned()
        HEALTH.incr("fleet_replica_restarts")
        _REGISTRY.inc("fleet_replica_restarts_total")

"""fleet/: self-healing horizontal serve fleet (ISSUE 11 tentpole).

One front process dispatching the serve JSONL contract across N
supervised ``SolveService`` replica subprocesses, over a shared
disk-backed solution-cache tier and one fleet-wide compile cache
(Clipper's layered front/worker architecture, PAPERS.md):

- ``shared_cache``  the instance cache promoted to an L1 (in-proc LRU)
                    + L2 (atomic-publish disk entries) tier shared by
                    every replica and the front
- ``replica``       one serve subprocess: pipes, liveness evidence,
                    metrics scrape target
- ``supervisor``    liveness probing, bounded-backoff restart, death
                    hand-off (the PR 4 watchdog at process granularity)
- ``front``         deadline-capped dispatch + re-dispatch with
                    first-writer-wins, graceful degradation, ``fleet``
                    CLI mode

Chaos seams: ``replica.kill`` / ``replica.hang`` / ``front.dispatch``
(``resilience.faults``). Tracing: one stitched span tree per fleet
request via per-request ``trace_parent`` tokens + a shared sink.
"""

from .front import FleetConfig, FleetFront, FleetTicket, fleet_cli
from .replica import Replica, ReplicaSpec
from .shared_cache import SharedCacheTier, TieredSolutionCache
from .supervisor import ReplicaSupervisor, SupervisorConfig

__all__ = [
    "FleetConfig",
    "FleetFront",
    "FleetTicket",
    "fleet_cli",
    "Replica",
    "ReplicaSpec",
    "SharedCacheTier",
    "TieredSolutionCache",
    "ReplicaSupervisor",
    "SupervisorConfig",
]

"""The fleet front: one JSONL endpoint over N supervised serve replicas.

Same line contract as ``serve/service.py`` (requests in, responses out,
input order preserved — ``run_jsonl`` drives a :class:`FleetFront`
exactly like a :class:`~..serve.service.SolveService`), but each request
is DISPATCHED to a replica subprocess instead of solved in-process
(Clipper's layered front/worker split, PAPERS.md).

Robustness contract per request:

- **deadline-capped dispatch retry**: every front→replica hop runs under
  one ``resilience/retry.py`` policy whose wall budget is the request's
  REMAINING deadline — a retried hop can never push a response past its
  deadline (PR 4's rung-retry cap discipline at fleet granularity);
- **deadline-aware re-dispatch**: when the dispatched replica dies
  (supervisor death callback aborts the hop immediately) or goes silent
  past ``hop_timeout_s``, the request is re-sent to a DIFFERENT replica
  under the same remaining budget, counted in
  ``fleet_redispatches_total``;
- **first-writer-wins**: the fleet id is stable across re-dispatches, so
  however many replicas eventually answer (a resurrected or un-wedged
  replica may finish the original hop late), exactly ONE response is
  emitted; late answers count into
  ``fleet_duplicate_answers_suppressed_total``;
- **graceful degradation**: with fewer than ``min_alive`` replicas up —
  or the deadline/attempts exhausted — the front answers LOCALLY from
  the shared cache tier (relabeled into the request's city order, the
  serve hit path) or a host greedy tour, counted per reason in
  ``fleet_degraded_answers_total{reason=}``; it never queues unboundedly
  against a dead fleet.

Tracing: the front's ``fleet.request`` root + per-hop ``front.dispatch``
spans carry the request-level ``trace_parent`` token to the replica
(the TSP_TRACE_PARENT encoding, threaded through the request line), and
replicas append to the SAME ``TSP_TRACE`` sink — one stitched span tree
per fleet request, front→replica→rung, with the replica's root span
announced at open so a mid-request kill cannot orphan its children.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

from ..obs import slo as _slo
from ..obs import tracing as _tracing
from ..obs.metrics import REGISTRY as _REGISTRY
from ..resilience.faults import FaultInjected, TransientFault
from ..resilience.faults import registry as _fault_registry
from ..resilience.health import HEALTH
from ..serve import canonical as canon
from ..serve.cache import CacheEntry
from ..serve.ladder import TIERS
from ..utils import reporting
from .replica import Replica, ReplicaSpec
from .shared_cache import TieredSolutionCache
from .supervisor import ReplicaSupervisor, SupervisorConfig

#: bounded degradation reasons (metric label cardinality stays fixed)
DEGRADED_REASONS = ("no_replicas", "deadline", "dispatch")


@dataclass
class FleetConfig:
    replicas: int = 2
    #: front request-thread pool width (run_jsonl reads this)
    threads: int = 8
    default_deadline_ms: float = 1000.0
    #: the shared disk cache tier every replica (and the front's local
    #: degraded path) reads/publishes; None = a fresh temp dir per fleet
    shared_cache_dir: Optional[str] = None
    #: one compile cache for the whole fleet (TSP_COMPILE_CACHE stamped
    #: into every replica env unless the caller already set it) — a
    #: restarted replica warm-starts instead of re-paying XLA compiles
    compile_cache_dir: Optional[str] = None
    cache_capacity: int = 4096
    quant_step: float = canon.DEFAULT_STEP
    #: dispatch attempts per request (1 = no re-dispatch)
    dispatch_attempts: int = 3
    #: per-hop wait before the front gives up on the dispatched replica
    #: and re-dispatches (always additionally capped by the remaining
    #: deadline)
    hop_timeout_s: float = 30.0
    #: fleet is DEGRADED below this many alive replicas
    min_alive: int = 1
    supervisor: SupervisorConfig = field(default_factory=SupervisorConfig)
    #: replica launch: None = the real serve CLI (built per index by
    #: :func:`default_replica_spec`); tests inject stub argv here
    replica_specs: Optional[List[ReplicaSpec]] = None
    #: extra argv appended to the default serve CLI (e.g. ["--warm", "8"])
    replica_args: tuple = ()
    backend: str = "auto"
    replica_threads: int = 4
    slos: Dict[str, Dict[str, float]] = field(
        default_factory=lambda: {k: dict(v) for k, v in _slo.DEFAULT_SLOS.items()}
    )


def default_replica_spec(
    cfg: FleetConfig,
    idx: int,
    shared_cache_dir: Optional[str] = None,
    compile_cache_dir: Optional[str] = None,
) -> ReplicaSpec:
    """The real thing: one serve CLI process on the shared cache tier,
    the shared compile cache, and the front's trace sink. The dir
    arguments are the front's RESOLVED paths (its owned temp dir when
    the config left them None) — cfg is read-only here."""
    shared_cache_dir = shared_cache_dir or cfg.shared_cache_dir
    compile_cache_dir = compile_cache_dir or cfg.compile_cache_dir
    argv = [
        sys.executable, "-m", "tsp_mpi_reduction_tpu", "serve",
        "--in", "-", "--out", "-",
        "--backend", cfg.backend,
        "--threads", str(cfg.replica_threads),
        "--default-deadline-ms", str(cfg.default_deadline_ms),
        "--metrics-port", "0",
    ]
    if shared_cache_dir:
        argv += ["--shared-cache", shared_cache_dir]
    argv += list(cfg.replica_args)
    env = dict(os.environ)
    if compile_cache_dir and "TSP_COMPILE_CACHE" not in os.environ:
        env["TSP_COMPILE_CACHE"] = compile_cache_dir
    trace_path = _tracing.TRACER.path
    if trace_path:
        # all replicas append whole flushed lines to the front's sink —
        # the PR 9 campaign-trace pattern (parent + chunk subprocesses
        # share one JSONL file; trace ids do the reconstruction)
        env["TSP_TRACE"] = trace_path
    return ReplicaSpec(argv=argv, env=env, scrape=True, meta={"backend": cfg.backend})


class _FleetDegraded(Exception):
    """Internal control flow: answer this request locally, now."""

    def __init__(self, reason: str):
        super().__init__(reason)
        self.reason = reason if reason in DEGRADED_REASONS else "dispatch"


class FleetTicket:
    """Per-request rendezvous between the dispatching front thread and
    whichever replica reader thread answers first."""

    def __init__(self, fleet_id: str):
        self.fleet_id = fleet_id
        self._lock = threading.Lock()
        self._hop_event: Optional[threading.Event] = None
        self._hop_aborted = False
        self.done = False
        self.result: Optional[Dict] = None
        self.hops_sent = 0
        self.replica: Optional[Replica] = None
        self.late_answers = 0

    def arm_hop(self, rep: Replica) -> None:
        """Install the hop target + a fresh event BEFORE the send: a
        replica death landing in the send window must find
        ``self.replica`` already pointing at it, or ``abort_hop`` would
        no-op and the request would burn its whole hop timeout against
        a corpse. (A send that then fails just abandons the armed hop —
        the next arm overwrites it.)"""
        with self._lock:
            self.replica = rep
            self._hop_aborted = False
            ev = threading.Event()
            if self.done:
                ev.set()  # answered between hops: wait returns instantly
            self._hop_event = ev

    def note_sent(self) -> None:
        """Count a hop that physically reached a replica (what the
        re-dispatch counter reports — a dead-pipe send is a dispatch
        retry, not a re-dispatch)."""
        with self._lock:
            self.hops_sent += 1

    def wait_hop(self, timeout_s: float) -> str:
        """``answered`` | ``failed`` (hop aborted: replica died) |
        ``timeout`` (silence past the hop budget)."""
        with self._lock:
            ev = self._hop_event
        if ev is not None:
            ev.wait(max(timeout_s, 0.0))
        with self._lock:
            if self.done:
                return "answered"
            return "failed" if self._hop_aborted else "timeout"

    def resolve(self, resp: Dict) -> bool:
        """First writer wins; False = suppressed late answer."""
        with self._lock:
            if self.done:
                self.late_answers += 1
                return False
            self.done = True
            self.result = resp
            if self._hop_event is not None:
                self._hop_event.set()
            return True

    def abort_hop(self, rep: Replica) -> None:
        """Fail the CURRENT hop iff it is on ``rep`` (a late death
        notification from a replica this ticket already left must not
        fail the successor hop)."""
        with self._lock:
            if self.replica is not rep:
                return
            if not self.done:
                self._hop_aborted = True
            if self._hop_event is not None:
                self._hop_event.set()


class FleetFront:
    """Duck-types the ``run_jsonl`` service surface: ``cfg.threads``,
    ``handle``, ``_record_error``, ``stats_json``, ``close``."""

    def __init__(self, cfg: Optional[FleetConfig] = None) -> None:
        self.cfg = cfg or FleetConfig()
        # resolved into FRONT state, never written back into cfg: a
        # caller-owned config reused for a second front must not inherit
        # (and then lose to close()'s rmtree) this front's temp dir
        self._owned_tmp = None
        self.shared_cache_dir = self.cfg.shared_cache_dir
        if self.shared_cache_dir is None:
            import tempfile

            self._owned_tmp = tempfile.mkdtemp(prefix="tsp_fleet_cache_")
            self.shared_cache_dir = self._owned_tmp
        self.compile_cache_dir = self.cfg.compile_cache_dir or os.path.join(
            self.shared_cache_dir, "compile_cache"
        )
        #: the front's own view of the shared tier: a small L1 over the
        #: same disk directory the replicas publish into — the degraded
        #: path answers certified cross-replica work without any replica
        self.cache = TieredSolutionCache(
            self.cfg.cache_capacity, self.shared_cache_dir
        )
        self.canon_cache = canon.CanonicalCache(self.cfg.cache_capacity)
        # None = the real serve CLI; an EXPLICIT empty list is a valid
        # zero-replica fleet (the degraded-mode surface, and the posture
        # a fleet is in after losing every replica)
        specs = (
            self.cfg.replica_specs
            if self.cfg.replica_specs is not None
            else [
                default_replica_spec(
                    self.cfg, i,
                    shared_cache_dir=self.shared_cache_dir,
                    compile_cache_dir=self.compile_cache_dir,
                )
                for i in range(self.cfg.replicas)
            ]
        )
        self.supervisor = ReplicaSupervisor(
            specs,
            self.cfg.supervisor,
            on_response=self._on_replica_response,
            on_death=self._on_replica_death,
        )
        self._lock = threading.Lock()
        self._inflight: Dict[str, FleetTicket] = {}
        self._fleet_seq = 0
        self.responses = 0
        self.errors = 0
        self.deadline_misses = 0
        self.redispatches = 0
        self.duplicates_suppressed = 0
        self.degraded: Dict[str, int] = {r: 0 for r in DEGRADED_REASONS}
        self.tier_counts: Dict[str, int] = {}
        self._health0 = HEALTH.snapshot()
        self._latency0 = _REGISTRY.snapshot(prefix="fleet_request_seconds")
        _REGISTRY.declare(
            "fleet_redispatches_total", "counter",
            "in-flight requests re-dispatched off a dead/wedged replica",
        )
        _REGISTRY.declare(
            "fleet_degraded_answers_total", "counter",
            "requests the front answered locally, by reason",
        )
        _REGISTRY.declare(
            "fleet_duplicate_answers_suppressed_total", "counter",
            "late replica answers dropped by first-writer-wins",
        )
        self.supervisor.start()

    # -- replica callbacks (reader / monitor threads) ------------------------

    def _on_replica_response(self, fid: Optional[str], resp: Dict, rep: Replica) -> None:
        with self._lock:
            ticket = self._inflight.get(fid) if fid is not None else None
        if ticket is None or not ticket.resolve(resp):
            # answered already (re-dispatch raced the original, or a
            # resurrected replica finished a drained hop): suppressed
            with self._lock:
                self.duplicates_suppressed += 1
            _REGISTRY.inc("fleet_duplicate_answers_suppressed_total")

    def _on_replica_death(self, rep: Replica, fids: List[str], reason: str) -> None:
        for fid in fids:
            with self._lock:
                ticket = self._inflight.get(fid)
            if ticket is not None:
                ticket.abort_hop(rep)

    # -- the run_jsonl surface -----------------------------------------------

    def _record_error(self) -> None:
        with self._lock:
            self.errors += 1
        _REGISTRY.inc("serve_errors_total")

    def handle(self, request: Dict) -> Dict:
        t0 = time.monotonic()
        req_id = request.get("id")
        try:
            deadline_ms = float(
                request.get("deadline_ms", self.cfg.default_deadline_ms)
            )
        except (TypeError, ValueError):
            self._record_error()
            return {"id": req_id, "error": "deadline_ms must be a number"}
        with _tracing.span("fleet.request", id=req_id) as root:
            resp = self._handle_traced(request, deadline_ms, t0)
            root.set("tier", resp.get("tier"))
            if "error" in resp:
                root.set("error", resp["error"])
            if resp.get("degraded"):
                root.set("degraded", resp["degraded"])
        if "error" in resp:
            # counted HERE for every producer — a replica's error answer
            # (malformed instance) and the local degraded path's alike —
            # so the front's stats never report a clean fleet while
            # clients receive error lines
            self._record_error()
            return resp
        # front-measured end-to-end accounting (the replica's own
        # latency_ms remains in the response for the hop-local view)
        latency_ms = (time.monotonic() - t0) * 1000.0
        missed = latency_ms > deadline_ms
        resp["fleet_latency_ms"] = round(latency_ms, 3)
        resp["deadline_missed"] = bool(missed)
        resp.setdefault("deadline_ms", deadline_ms)
        tier = resp.get("tier")
        tier = tier if tier in TIERS else "greedy"
        with self._lock:
            self.responses += 1
            if missed:
                self.deadline_misses += 1
            self.tier_counts[tier] = self.tier_counts.get(tier, 0) + 1
        _REGISTRY.inc("fleet_responses_total")
        if missed:
            _REGISTRY.inc("fleet_deadline_misses_total")
        # tier values pass through the fixed ladder set above — never a
        # raw response field (graftlint R13 bounds label cardinality)
        _REGISTRY.observe("fleet_request_seconds", latency_ms / 1000.0, tier=tier)
        return resp

    def _handle_traced(self, request: Dict, deadline_ms: float, t0: float) -> Dict:
        from ..resilience.retry import RetryPolicy

        req_id = request.get("id")

        def remaining() -> float:
            return deadline_ms / 1000.0 - (time.monotonic() - t0)

        with self._lock:
            self._fleet_seq += 1
            fid = f"f{self._fleet_seq}"
            ticket = FleetTicket(fid)
            self._inflight[fid] = ticket
        try:
            policy = RetryPolicy(
                max_attempts=max(self.cfg.dispatch_attempts, 1),
                base_delay_s=0.02,
                max_delay_s=0.5,
                seed=0,
            )
            resp = policy.call(
                lambda: self._dispatch_once(request, fid, ticket, remaining),
                budget_s=max(remaining(), 0.01),
            )
        except _FleetDegraded as e:
            return self._degraded_answer(request, e.reason)
        except TransientFault:
            # attempts or deadline budget exhausted: the request still
            # gets an answer, locally — reason says which ran out
            reason = "deadline" if remaining() <= 0.05 else "dispatch"
            return self._degraded_answer(request, reason)
        finally:
            with self._lock:
                self._inflight.pop(fid, None)
                redispatched = max(ticket.hops_sent - 1, 0)
                if redispatched:
                    self.redispatches += redispatched
            if redispatched:
                HEALTH.incr("fleet_redispatches", redispatched)
                _REGISTRY.inc("fleet_redispatches_total", redispatched)
        resp = dict(resp)
        resp["id"] = req_id  # un-remap the fleet id
        return resp

    def _dispatch_once(self, request, fid, ticket, remaining) -> Dict:
        """One hop: pick a replica, send, cross the chaos seams, wait.
        Raises TransientFault (retryable, deadline-budgeted) on any hop
        failure, _FleetDegraded when the fleet cannot take the request."""
        if self.supervisor.alive_count() < max(self.cfg.min_alive, 1):
            raise _FleetDegraded("no_replicas")
        rep = self.supervisor.pick(exclude=ticket.replica)
        if rep is None:
            raise _FleetDegraded("no_replicas")
        with _tracing.span("front.dispatch", replica=rep.idx, hop=ticket.hops_sent + 1) as hop:
            # the dispatch seam: a raise-mode fault is a failed hop the
            # bounded retry absorbs (counted like every other transient)
            _fault_registry().fire("front.dispatch")
            line = json.dumps(
                dict(
                    request,
                    id=fid,
                    trace_parent=_tracing.format_parent(hop.context),
                )
            )
            ticket.arm_hop(rep)
            rep.send(fid, line)
            ticket.note_sent()
            # chaos: kill/wedge the dispatch target mid-flight — the
            # injected failure is the REPLICA's, so the front translates
            # the seam's raise into the real process action and carries on
            try:
                _fault_registry().fire("replica.kill")
            except FaultInjected:
                self.supervisor.kill_replica(rep, reason="injected_kill")
            try:
                _fault_registry().fire("replica.hang")
            except FaultInjected:
                self.supervisor.suspend_replica(rep)
            outcome = ticket.wait_hop(
                min(max(remaining(), 0.0), self.cfg.hop_timeout_s)
            )
            hop.set("outcome", outcome)
            if outcome == "answered":
                return ticket.result
            # the replica KEEPS its in-flight entry on a timeout: the
            # request's bytes are still physically queued there, and an
            # entry that never resolves is exactly the wedge evidence
            # the supervisor's detector needs (a healthy-but-slow
            # replica eventually answers and its reader pops the entry;
            # first-writer-wins suppresses the late duplicate)
            raise TransientFault(f"hop {outcome} on replica {rep.idx}")

    # -- degraded answers ----------------------------------------------------

    def _degraded_answer(self, request: Dict, reason: str) -> Dict:
        req_id = request.get("id")
        with _tracing.span("fleet.degraded", reason=reason):
            try:
                xy = np.asarray(request["xy"], np.float64)
                ci = canon.canonicalize_cached(
                    xy, self.canon_cache, self.cfg.quant_step
                )
            except (KeyError, TypeError, ValueError) as e:
                # NOT counted here: handle() counts every error response
                # once at the top, whatever path produced it
                return {"id": req_id, "error": str(e)}
            try:
                entry = self.cache.get(ci.key)
            except TransientFault:
                entry = None
            if entry is not None:
                tour = canon.from_canonical_tour(entry.tour, ci)
                cost = canon.tour_length_np(tour, xy)
                tier, gap, provenance = entry.tier, entry.certified_gap, "hit"
            else:
                cost, tour = _greedy_tour_np(xy)
                tier, gap, provenance = "greedy", None, "miss"
                try:
                    self.cache.put(
                        ci.key,
                        CacheEntry(
                            cost=cost,
                            tour=canon.to_canonical_tour(tour, ci),
                            certified_gap=None,
                            tier="greedy",
                        ),
                    )
                except TransientFault:
                    pass
        with self._lock:
            self.degraded[reason] = self.degraded.get(reason, 0) + 1
        HEALTH.incr("fleet_degraded_answers")
        _REGISTRY.inc("fleet_degraded_answers_total", reason=reason)
        return {
            "id": req_id,
            "n": int(xy.shape[0]),
            "cost": float(cost),
            "tour": [int(c) for c in tour],
            "tier": tier,
            "certified_gap": None if gap is None else float(gap),
            "cache": provenance,
            "degraded": reason,
        }

    # -- stats / lifecycle ---------------------------------------------------

    def stats_json(self) -> str:
        with self._lock:
            responses, errors = self.responses, self.errors
            misses = self.deadline_misses
            tier_counts = dict(self.tier_counts)
            fleet_block = {
                "replica_count": len(self.supervisor.replicas),
                "alive": self.supervisor.alive_count(),
                "restarts_total": sum(
                    r.restarts for r in self.supervisor.replicas
                ),
                "redispatches_total": self.redispatches,
                "degraded_answers": dict(self.degraded),
                "duplicates_suppressed": self.duplicates_suppressed,
                "in_flight": len(self._inflight),
            }
        fleet_block["replicas"] = self.supervisor.snapshot()
        fleet_block["shared_cache"] = self.cache.shared.stats()
        lat = _REGISTRY.delta(self._latency0, prefix="fleet_request_seconds")
        hists_by_tier = {
            dict(key).get("tier", "?"): v
            for key, v in lat.data.get(
                "fleet_request_seconds", {}
            ).get("series", {}).items()
            if isinstance(v, dict)
        }
        return reporting.fleet_stats_json(
            responses=responses,
            errors=errors,
            deadline_misses=misses,
            tier_counts=tier_counts,
            fleet=fleet_block,
            cache=self.cache.stats(),
            health=HEALTH.delta_since(self._health0),
            slo=_slo.evaluate(hists_by_tier, self.cfg.slos),
            obs=reporting.obs_block(trace_path=_tracing.TRACER.path),
        )

    def close(self) -> None:
        self.supervisor.close()
        if self._owned_tmp is not None:
            # the front made this cache tree (shared tier + nested
            # compile cache) for its own lifetime — replicas are down
            # now, so reap it; a CALLER-provided dir is never touched
            import shutil

            shutil.rmtree(self._owned_tmp, ignore_errors=True)

    def __enter__(self) -> "FleetFront":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _greedy_tour_np(xy: np.ndarray):
    """Host nearest-neighbor, pure numpy INCLUDING the distance matrix
    (ops.distance imports jax at module level — the degraded path must
    not pay a cold jax import inside the very request handler that
    exists to answer fast when everything else is down). Same
    correctly-rounded sqrt(sum(diff*diff)) op order as
    ``distance_matrix_np``, so costs stay bit-comparable."""
    xy = np.asarray(xy, np.float64)
    n = int(xy.shape[0])
    diff = xy[:, None, :] - xy[None, :, :]
    d = np.sqrt(np.sum(diff * diff, axis=-1))
    if n == 1:
        return 0.0, np.asarray([0, 0], np.int32)
    if n == 2:
        return float(d[0, 1] + d[1, 0]), np.asarray([0, 1, 0], np.int32)
    visited = np.zeros(n, bool)
    visited[0] = True
    tour = [0]
    cur = 0
    cost = 0.0
    for _ in range(n - 1):
        masked = np.where(visited, np.inf, d[cur])
        nxt = int(np.argmin(masked))
        cost += float(d[cur, nxt])
        tour.append(nxt)
        visited[nxt] = True
        cur = nxt
    cost += float(d[cur, 0])
    tour.append(0)
    return cost, np.asarray(tour, np.int32)


# -- CLI -----------------------------------------------------------------------


def fleet_cli(argv: Optional[List[str]] = None) -> int:
    """``python -m tsp_mpi_reduction_tpu fleet`` — see README "Fleet
    serving"."""
    import argparse

    ap = argparse.ArgumentParser(
        prog="tsp-tpu fleet",
        description="front + N supervised serve replicas: JSONL in/out",
    )
    ap.add_argument("--in", dest="inp", default="-", metavar="FILE")
    ap.add_argument("--out", dest="outp", default="-", metavar="FILE")
    ap.add_argument("--replicas", type=int, default=2)
    ap.add_argument("--backend", default="auto", choices=["auto", "cpu", "tpu"])
    ap.add_argument("--threads", type=int, default=8,
                    help="front request-thread pool width")
    ap.add_argument("--replica-threads", type=int, default=4)
    ap.add_argument("--default-deadline-ms", type=float, default=1000.0)
    ap.add_argument("--shared-cache", default=None, metavar="DIR",
                    help="shared disk cache tier directory (default: a "
                    "fresh temp dir; pass one to persist across fleets)")
    ap.add_argument("--warm", default="",
                    help="forwarded to every replica's serve --warm")
    ap.add_argument("--min-alive", type=int, default=1)
    ap.add_argument("--hop-timeout-s", type=float, default=30.0)
    ap.add_argument("--dispatch-attempts", type=int, default=3)
    ap.add_argument("--stats", action="store_true",
                    help="print the front stats JSON line to stderr on exit")
    ap.add_argument("--trace", default=None, metavar="FILE",
                    help="span-trace JSONL sink shared by the front AND "
                    "every replica — one stitched tree per request "
                    "(render with tools/obs_report.py)")
    args = ap.parse_args(argv)

    if args.trace:
        _tracing.configure(args.trace)
    replica_args = []
    if args.warm.strip():
        replica_args += ["--warm", args.warm]
    cfg = FleetConfig(
        replicas=args.replicas,
        threads=args.threads,
        replica_threads=args.replica_threads,
        default_deadline_ms=args.default_deadline_ms,
        shared_cache_dir=args.shared_cache,
        backend=args.backend,
        min_alive=args.min_alive,
        hop_timeout_s=args.hop_timeout_s,
        dispatch_attempts=args.dispatch_attempts,
        replica_args=tuple(replica_args),
    )
    from contextlib import ExitStack

    from ..serve.service import run_jsonl

    front = FleetFront(cfg)
    try:
        with ExitStack() as stack:
            inp = sys.stdin if args.inp == "-" else stack.enter_context(open(args.inp))
            outp = (
                sys.stdout
                if args.outp == "-"
                # a live JSONL response stream, flushed per line — atomic
                # publish would defeat it (same waiver as serve_cli)
                else stack.enter_context(open(args.outp, "w"))  # graftlint: disable=R6
            )
            try:
                run_jsonl(inp, outp, service=front)
            finally:
                try:
                    outp.flush()
                except (OSError, ValueError):
                    pass
    finally:
        front.close()
    if args.stats:
        print(front.stats_json(), file=sys.stderr)
    return 0

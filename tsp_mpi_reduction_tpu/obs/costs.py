"""XLA cost attribution for the compiled hot entries (ISSUE 9 tentpole).

``perf.compile_cache`` owns every compiled executable in the process —
the AOT store hands it a ready ``Compiled`` at every miss, hit, and warm
call. This module turns that custody into *performance accounting*: at
capture time it reads ``Compiled.cost_analysis()`` (flops, bytes
accessed, transcendentals) and ``Compiled.memory_analysis()`` (argument /
output / temp bytes), derives the arithmetic intensity (flops per byte
moved) and a roofline utilization estimate against a coarse per-backend
peak table, mirrors everything as ``xla_entry_*{entry=…}`` registry
gauges, and exposes one JSON-ready ``obs.device_costs`` block that
``bnb_solve.py`` / the serve stats JSON / the bench artifacts stamp
(``utils.reporting.obs_block``).

The roofline numbers are *estimates from the model's own cost analysis*,
not measurements: XLA's flop counts are analytical, the peak table is a
coarse spec-sheet figure (override per host with ``TSP_PEAK_FLOPS`` /
``TSP_PEAK_BYTES_PER_S``), and the utilization estimate is the classic
``min(peak, intensity * bw) / peak`` attainable-fraction — good enough to
say "this entry is bandwidth-bound at ~0.4 intensity" (the ISSUE 8
expansion step) and to watch the ratio move across layout changes, not to
replace a profiler.

Capture never runs on a hot path — only at compile / AOT-load time, a
once-per-process-per-entry event — and every failure (older jaxlib,
backend without cost analysis, missing fields) degrades to "no block for
that entry" plus a counted ``xla_cost_capture_failures_total``.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Dict, Optional

from .metrics import REGISTRY

#: coarse per-backend peaks used for the roofline estimate. Values are
#: deliberately round spec-sheet figures (f32 for TPU — the engine's
#: screening dtype; one-socket AVX2 ballpark for CPU): the estimate's job
#: is the *shape* of the roofline (memory- vs compute-bound, order of
#: magnitude of attainable fraction), not benchmarking the host. Override
#: with TSP_PEAK_FLOPS / TSP_PEAK_BYTES_PER_S when a real machine's
#: numbers are known.
BACKEND_PEAKS: Dict[str, Dict[str, float]] = {
    # v5e f32 ~ 197 TFLOP/s bf16 -> ~49 TFLOP/s f32-ish; HBM ~ 819 GB/s
    "tpu": {"flops_per_s": 4.9e13, "bytes_per_s": 8.19e11},
    # one modern x86 socket, AVX2 f32 ~ 1 TFLOP/s; ~80 GB/s DRAM
    "cpu": {"flops_per_s": 1.0e12, "bytes_per_s": 8.0e10},
    # accelerator we have no table row for: order-of-magnitude GPU-ish
    "default": {"flops_per_s": 1.0e13, "bytes_per_s": 5.0e11},
}


def backend_peaks(backend: str) -> Dict[str, float]:
    """The peak row for ``backend`` with env overrides applied."""
    row = dict(BACKEND_PEAKS.get(backend, BACKEND_PEAKS["default"]))
    for env, key in (
        ("TSP_PEAK_FLOPS", "flops_per_s"),
        ("TSP_PEAK_BYTES_PER_S", "bytes_per_s"),
    ):
        val = os.environ.get(env, "").strip()
        if val:
            try:
                row[key] = float(val)
            except ValueError:
                pass  # a bad override must not take cost capture down
    return row


_lock = threading.Lock()
#: entry -> captured cost record (JSON-ready); process-global like STATS
_COSTS: Dict[str, Dict[str, Any]] = {}

#: schema version stamped into every record (and the on-disk memo the
#: compile cache keeps next to the AOT executables) — bump on any field
#: change so a stale memo from an older layout is re-captured
SCHEMA_VERSION = 1

_GAUGES = (
    ("flops", "xla_entry_flops"),
    ("bytes_accessed", "xla_entry_bytes_accessed"),
    ("peak_memory_bytes", "xla_entry_peak_memory_bytes"),
    ("arithmetic_intensity", "xla_entry_arithmetic_intensity"),
    ("roofline_utilization_est", "xla_entry_roofline_utilization"),
)


def _cost_dict(compiled) -> Optional[Dict[str, float]]:
    """Normalize ``Compiled.cost_analysis()`` across jax versions: 0.4.x
    returns a list with one dict per program, newer versions a dict."""
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    return ca if isinstance(ca, dict) else None


def capture(entry: str, compiled, backend: Optional[str] = None) -> Optional[Dict[str, Any]]:
    """Read the cost/memory analyses off a freshly compiled (or AOT-loaded)
    executable, derive the roofline estimate, store + mirror the record.
    Returns the record, or None when this backend/jaxlib cannot produce
    one (counted, never raised — cost capture is an observer)."""
    try:
        if backend is None:
            import jax

            backend = jax.default_backend()
        ca = _cost_dict(compiled)
        if not ca:
            raise ValueError("empty cost_analysis")
        flops = float(ca.get("flops", 0.0))
        bytes_accessed = float(ca.get("bytes accessed", 0.0))
        transcendentals = float(ca.get("transcendentals", 0.0))
        record: Dict[str, Any] = {
            "schema": SCHEMA_VERSION,
            "backend": backend,
            "flops": flops,
            "bytes_accessed": bytes_accessed,
            "transcendentals": transcendentals,
        }
        ma = None
        try:
            ma = compiled.memory_analysis()
        except Exception:  # noqa: BLE001 — memory stats are optional
            pass
        if ma is not None:
            arg_b = int(getattr(ma, "argument_size_in_bytes", 0))
            out_b = int(getattr(ma, "output_size_in_bytes", 0))
            tmp_b = int(getattr(ma, "temp_size_in_bytes", 0))
            alias_b = int(getattr(ma, "alias_size_in_bytes", 0))
            record.update(
                argument_bytes=arg_b,
                output_bytes=out_b,
                temp_bytes=tmp_b,
                alias_bytes=alias_b,
                # live-at-once estimate: args + outputs + scratch, minus
                # the donated (aliased) overlap counted twice
                peak_memory_bytes=max(arg_b + out_b + tmp_b - alias_b, 0),
            )
        return ingest(entry, _roofline(record))
    except Exception:  # noqa: BLE001 — capture must never fail a compile
        REGISTRY.inc("xla_cost_capture_failures_total", entry=entry)
        return None


def _roofline(record: Dict[str, Any]) -> Dict[str, Any]:
    """Derive intensity + attainable-fraction fields from a raw record."""
    peaks = backend_peaks(record.get("backend", "default"))
    flops = float(record.get("flops", 0.0))
    bytes_accessed = float(record.get("bytes_accessed", 0.0))
    intensity = flops / bytes_accessed if bytes_accessed > 0 else 0.0
    ridge = peaks["flops_per_s"] / peaks["bytes_per_s"]
    attainable = min(peaks["flops_per_s"], intensity * peaks["bytes_per_s"])
    record.update(
        arithmetic_intensity=round(intensity, 4),
        ridge_intensity=round(ridge, 4),
        roofline_utilization_est=(
            round(attainable / peaks["flops_per_s"], 6)
            if peaks["flops_per_s"] > 0
            else 0.0
        ),
        bound="compute" if intensity >= ridge else "memory",
        peak_flops_per_s=peaks["flops_per_s"],
        peak_bytes_per_s=peaks["bytes_per_s"],
    )
    return record


def ingest(entry: str, record: Dict[str, Any]) -> Dict[str, Any]:
    """Store a (possibly disk-memoized) cost record for ``entry`` and
    mirror the numeric headline fields as registry gauges. The compile
    cache calls this on warm processes with the record it persisted at
    compile time — XLA:CPU marks some hot entries unserializable, so a
    warm chunk never re-holds the ``Compiled`` the analysis came from."""
    if (
        record.get("schema") != SCHEMA_VERSION
        or "roofline_utilization_est" not in record
    ):
        # stale memo from an older field layout, or a raw flops/bytes
        # record that never went through capture(): (re-)derive the
        # intensity/roofline fields against the current peak table
        record = _roofline(dict(record, schema=SCHEMA_VERSION))
    with _lock:
        _COSTS[entry] = record
    for key, gauge in _GAUGES:
        if key in record:
            REGISTRY.set_gauge(gauge, float(record[key]), entry=entry)
    return record


def get(entry: str) -> Optional[Dict[str, Any]]:
    with _lock:
        rec = _COSTS.get(entry)
    return dict(rec) if rec is not None else None


def device_costs_block() -> Dict[str, Any]:
    """The ``obs.device_costs`` block: every captured entry's record plus
    the peak table the roofline estimates were computed against. Empty
    ``entries`` when nothing compiled through the cache yet (or the
    compile cache is disabled — capture rides its custody of Compiled)."""
    with _lock:
        entries = {k: dict(v) for k, v in sorted(_COSTS.items())}
    backends = sorted({v.get("backend", "default") for v in entries.values()})
    return {
        "schema": SCHEMA_VERSION,
        "entries": entries,
        "peaks": {b: backend_peaks(b) for b in backends},
    }


def reset_for_testing() -> None:
    with _lock:
        _COSTS.clear()
    for _key, gauge in _GAUGES:
        REGISTRY.clear_metric(gauge)
    REGISTRY.clear_metric("xla_cost_capture_failures_total")

"""Lightweight span tracing with propagated trace/span IDs.

One serve request produces one *trace*: a root ``serve.request`` span plus
children for each pipeline stage (canonicalize, cache lookup, queue wait,
flush, ladder rung, device dispatch, respond). Spans are emitted as JSONL
(one object per line, written at span END) to the sink configured by
:func:`configure` — or the ``TSP_TRACE`` env var — and reconstructed into
trees by :func:`build_trees` (tests assert no orphan spans; the
``tools/obs_report.py`` renderer prints them).

Span record schema::

    {"type": "span", "trace_id": "…", "span_id": "…", "parent_id": "…"|null,
     "name": "sched.flush", "ts": 1754300000.123,      # epoch start
     "dur_ms": 1.87, "attrs": {…},
     "events": [{"name": "fault_injected", "ts": …, "attrs": {…}}, …]}

Propagation: each thread carries a span stack (``threading.local``);
:func:`span` parents to the top of the stack. Cross-thread hops (request
thread → scheduler worker) carry an explicit ``(trace_id, span_id)``
context captured with :func:`current_context` — the worker then emits
completed spans directly via :func:`emit_span` without touching any
stack. Injected faults (``resilience.faults``) call :func:`add_event`,
annotating whatever span the firing thread currently has open, so a chaos
run's faults land in the same trace as the request they hit.

When no sink is configured (the default) every entry point is a cheap
no-op — :func:`span` yields a shared null span, :func:`add_event` returns
immediately — so production paths pay one attribute check.

``jax.profiler`` integration: :func:`step_annotation` wraps a B&B
expansion dispatch in ``jax.profiler.StepTraceAnnotation`` *only while* a
``device_trace`` capture is active (``utils.profiling``), so
TensorBoard/Perfetto timelines segment by B&B step at zero cost to
untraced runs.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from typing import Any, Dict, Iterator, List, Optional, Tuple

from . import enabled as _obs_enabled

#: (trace_id, span_id) — the cross-thread propagation token
SpanContext = Tuple[str, str]

#: the cross-PROCESS propagation contract (ISSUE 9): a parent process
#: stamps ``TSP_TRACE_PARENT=<trace_id>:<span_id>`` into a child's env,
#: and the child's driver opens its root span under that context — so a
#: chunked campaign's N subprocesses (retries, fallback restores, compile
#: phases included) reconstruct as ONE span tree instead of N trace
#: islands. ``bnb_chunked.py`` stamps it per chunk; ``bnb_solve.py`` (and
#: anything that calls :func:`parent_from_env`) honors it.
ENV_PARENT = "TSP_TRACE_PARENT"

_HEX = frozenset("0123456789abcdef")


def parse_parent_token(raw) -> Optional[SpanContext]:
    """Parse a ``<trace_id>:<span_id>`` propagation token (the
    ``TSP_TRACE_PARENT`` encoding) into a SpanContext, or None when
    missing or malformed — a garbled token must degrade to a fresh root
    trace, never crash a request. The fleet front stamps this token into
    each replica-bound request line (``trace_parent`` field), the same
    contract the env var carries process-to-process."""
    if not isinstance(raw, str):
        return None
    raw = raw.strip().lower()
    if not raw or ":" not in raw:
        return None
    trace_id, _, span_id = raw.partition(":")
    if not trace_id or not span_id:
        return None
    if not (set(trace_id) <= _HEX and set(span_id) <= _HEX):
        return None
    return (trace_id, span_id)


def parent_from_env() -> Optional[SpanContext]:
    """Parse ``TSP_TRACE_PARENT`` into a SpanContext, or None when unset
    or malformed."""
    return parse_parent_token(os.environ.get(ENV_PARENT, ""))


def format_parent(ctx: Optional[SpanContext]) -> Optional[str]:
    """The env-var encoding of a context (None in, None out — callers
    stamp the child env only when tracing is actually on)."""
    if ctx is None:
        return None
    return f"{ctx[0]}:{ctx[1]}"


def _new_id(nbytes: int = 8) -> str:
    return os.urandom(nbytes).hex()


class Span:
    """One in-flight span; mutate via :meth:`set` / :meth:`event`."""

    __slots__ = (
        "trace_id", "span_id", "parent_id", "name", "attrs", "events",
        "ts", "_t0",
    )

    def __init__(
        self,
        name: str,
        trace_id: str,
        parent_id: Optional[str],
        attrs: Optional[Dict[str, Any]] = None,
    ):
        self.name = name
        self.trace_id = trace_id
        self.span_id = _new_id()
        self.parent_id = parent_id
        self.attrs: Dict[str, Any] = dict(attrs or {})
        self.events: List[Dict[str, Any]] = []
        self.ts = time.time()
        self._t0 = time.perf_counter()

    @property
    def context(self) -> SpanContext:
        return (self.trace_id, self.span_id)

    def set(self, key: str, value: Any) -> None:
        self.attrs[key] = value

    def event(self, name: str, **attrs: Any) -> None:
        self.events.append(
            {"name": name, "ts": time.time(), "attrs": attrs}
        )

    def _record(self) -> Dict[str, Any]:
        return {
            "type": "span",
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "ts": round(self.ts, 6),
            "dur_ms": round((time.perf_counter() - self._t0) * 1000.0, 4),
            "attrs": self.attrs,
            "events": self.events,
        }


class _NullSpan:
    """Shared no-op stand-in when tracing is off (set/event swallow)."""

    __slots__ = ()
    trace_id = span_id = parent_id = ""
    context: Optional[SpanContext] = None

    def set(self, key: str, value: Any) -> None:
        pass

    def event(self, name: str, **attrs: Any) -> None:
        pass


NULL_SPAN = _NullSpan()


class Tracer:
    """JSONL span sink + per-thread span stacks."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._fh = None
        self._path: Optional[str] = None
        self._tls = threading.local()
        self._env_checked = False

    # -- configuration -------------------------------------------------------

    def configure(self, path: Optional[str]) -> None:
        """Point the tracer at a JSONL sink (append mode — a restarted
        service extends the log); None closes it."""
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None
            self._path = path
            self._env_checked = True
            if path:
                self._fh = open(path, "a", encoding="utf-8")

    def _maybe_env_configure(self) -> None:
        # lazy TSP_TRACE pickup, mirroring the faults registry: drivers
        # and benches opt in by env without touching every entry point
        if self._env_checked:
            return
        with self._lock:
            if self._env_checked:
                return
            self._env_checked = True
            path = os.environ.get("TSP_TRACE", "").strip()
            if path:
                self._path = path
                try:
                    self._fh = open(path, "a", encoding="utf-8")
                except OSError:
                    self._path = None

    @property
    def active(self) -> bool:
        self._maybe_env_configure()
        # double-checked: the lock-free read keeps the tracing-OFF fast
        # path (every span()/add_event gate) off the lock emit() holds
        # across file writes; the locked re-read below makes the
        # tracing-ON answer consistent with a concurrent configure()
        if self._fh is None:
            return False
        with self._lock:
            fh = self._fh
        return fh is not None and _obs_enabled()

    @property
    def path(self) -> Optional[str]:
        """The configured sink path, read under the lock (graftflow R9:
        ``configure`` rebinds it from whichever thread reconfigures)."""
        with self._lock:
            return self._path

    # -- stacks --------------------------------------------------------------

    def _stack(self) -> List[Span]:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def current(self) -> Optional[Span]:
        st = getattr(self._tls, "stack", None)
        return st[-1] if st else None

    # -- emission ------------------------------------------------------------

    def emit(self, record: Dict[str, Any]) -> None:
        if self._fh is None:
            return
        # encode OUTSIDE the lock: under load every request thread ends
        # ~8 spans, and json.dumps inside the critical section would
        # serialize them all on CPU work, not just on the file append
        line = json.dumps(record) + "\n"
        with self._lock:
            if self._fh is None:
                return
            try:
                self._fh.write(line)
                self._fh.flush()
            except (OSError, ValueError):
                pass  # a torn sink must never take a request down


TRACER = Tracer()


def configure(path: Optional[str]) -> None:
    TRACER.configure(path)


def current_span() -> Optional[Span]:
    return TRACER.current()


def current_context() -> Optional[SpanContext]:
    """The active span's (trace_id, span_id), for cross-thread handoff."""
    sp = TRACER.current()
    return sp.context if sp is not None else None


#: cap on the per-thread pending-event buffer (threads that never drain
#: — e.g. a watchdog firing faults with no span — must stay bounded)
_PENDING_CAP = 16


def add_event(name: str, **attrs: Any) -> None:
    """Annotate the calling thread's active span — the hook
    ``resilience.faults`` fires on every injected fault, so chaos events
    land inside the span they actually hit. On a thread with NO active
    span (the scheduler worker emits its spans retrospectively via
    :func:`emit_span`), the event parks in a per-thread pending buffer
    instead, for that thread to attach via :func:`drain_pending` — a
    worker-seam injection must not vanish from the trace."""
    sp = TRACER.current()
    if sp is not None:
        sp.event(name, **attrs)
        return
    if not TRACER.active:
        return
    pending = getattr(TRACER._tls, "pending", None)
    if pending is None:
        pending = TRACER._tls.pending = []
    if len(pending) < _PENDING_CAP:
        pending.append({"name": name, "ts": time.time(), "attrs": attrs})


def drain_pending() -> List[Dict[str, Any]]:
    """Take (and clear) the calling thread's parked events — spanless
    emitters attach them to their next :func:`emit_span`."""
    pending = getattr(TRACER._tls, "pending", None)
    if not pending:
        return []
    TRACER._tls.pending = []
    return pending


@contextlib.contextmanager
def span(
    name: str,
    parent: Optional[SpanContext] = None,
    announce: bool = False,
    **attrs: Any,
) -> Iterator[Any]:
    """Open a span: child of ``parent`` if given, else of the thread's
    current span, else the root of a fresh trace. Yields the Span (or the
    shared null span when tracing is off). An escaping exception is
    recorded as ``attrs.error`` and re-raised — degraded/failed requests
    still close their spans, so their trees stay complete.

    ``announce=True`` additionally emits a PROVISIONAL record (same
    span_id, ``partial: true``, zero duration) at span OPEN. The final
    record at close overwrites it in reconstruction (``build_trees``
    keys nodes by span_id, last record wins). This is the fleet
    contract: a replica process may be killed mid-request, and without
    the announcement its already-closed child spans (canonicalize,
    cache.lookup, …) would orphan — the provisional parent keeps every
    stitched tree complete even when the process that owned the real
    close dies."""
    if not TRACER.active:
        yield NULL_SPAN
        return
    if parent is not None:
        trace_id, parent_id = parent
    else:
        cur = TRACER.current()
        if cur is not None:
            trace_id, parent_id = cur.trace_id, cur.span_id
        else:
            trace_id, parent_id = _new_id(16), None
    sp = Span(name, trace_id, parent_id, attrs)
    if announce:
        TRACER.emit(
            {
                "type": "span",
                "trace_id": sp.trace_id,
                "span_id": sp.span_id,
                "parent_id": sp.parent_id,
                "name": name,
                "ts": round(sp.ts, 6),
                "dur_ms": 0.0,
                "attrs": dict(sp.attrs, partial=True),
                "events": [],
            }
        )
    stack = TRACER._stack()
    stack.append(sp)
    try:
        yield sp
    except BaseException as e:
        sp.set("error", f"{type(e).__name__}: {e}")
        raise
    finally:
        if stack and stack[-1] is sp:
            stack.pop()
        else:  # unbalanced exit (never expected): drop without corrupting
            try:
                stack.remove(sp)
            except ValueError:
                pass
        TRACER.emit(sp._record())


def emit_span(
    name: str,
    parent: Optional[SpanContext],
    ts: float,
    dur_s: float,
    attrs: Optional[Dict[str, Any]] = None,
    events: Optional[List[Dict[str, Any]]] = None,
) -> Optional[SpanContext]:
    """Emit a COMPLETED span directly (no thread stack) — the scheduler
    worker's path: it measures a flush that belongs to a request thread's
    trace, so it parents to the ticket's carried context. Returns the new
    span's context (for chaining a child), or None when tracing is off or
    there is no trace to attach to."""
    if parent is None or not TRACER.active:
        return None
    trace_id, parent_id = parent
    span_id = _new_id()
    TRACER.emit(
        {
            "type": "span",
            "trace_id": trace_id,
            "span_id": span_id,
            "parent_id": parent_id,
            "name": name,
            "ts": round(ts, 6),
            "dur_ms": round(dur_s * 1000.0, 4),
            "attrs": attrs or {},
            "events": events or [],
        }
    )
    return (trace_id, span_id)


# -- jax profiler step annotations --------------------------------------------

#: shared reusable null context (nullcontext instances are re-enterable);
#: the untraced hot path must not allocate one per dispatch
_NULL_CTX = contextlib.nullcontext()


def _null_annotation(step: int):
    return _NULL_CTX


def step_annotation(step: int):
    """``jax.profiler.StepTraceAnnotation`` for one B&B expansion dispatch,
    active ONLY while a ``device_trace`` capture is running (and obs is
    enabled) — untraced runs pay a single flag check per dispatch."""
    return step_annotation_factory()(step)


def step_annotation_factory():
    """Resolve the per-dispatch annotation ONCE per solve: ``device_trace``
    state cannot change inside a solve (the capture wraps the whole call),
    so the host loop binds ``ann = step_annotation_factory()`` before the
    loop and pays one call + a shared nullcontext per dispatch when no
    profiler is attached."""
    from ..utils import profiling

    if not (profiling.trace_active() and _obs_enabled()):
        return _null_annotation
    import jax

    return lambda step: jax.profiler.StepTraceAnnotation(
        "bnb_step", step_num=int(step)
    )


# -- reconstruction ------------------------------------------------------------


def read_trace(path: str) -> List[Dict[str, Any]]:
    """Parse a JSONL trace log; malformed lines are skipped (a crashed
    writer may leave a torn tail — the surviving spans still matter)."""
    spans: List[Dict[str, Any]] = []
    with open(path, encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(rec, dict) and rec.get("type") == "span":
                spans.append(rec)
    return spans


def read_traces(paths: List[str]) -> List[Dict[str, Any]]:
    """Stitch several JSONL sinks into one span list (a chunked campaign
    leaves the parent's spans and every chunk subprocess's spans in the
    SAME file via append mode, but retries/relocated sinks can split them
    — the reconstruction only needs the union; trace_ids do the rest).
    Unreadable files are skipped like malformed lines."""
    spans: List[Dict[str, Any]] = []
    for path in paths:
        try:
            spans.extend(read_trace(path))
        except OSError:
            continue
    return spans


def build_trees(spans: List[Dict[str, Any]]) -> Dict[str, Dict[str, Any]]:
    """Group spans into per-trace trees.

    Returns ``{trace_id: {"roots": [node…], "orphans": [span…],
    "spans": {span_id: node}}}`` where each node is ``{"span": record,
    "children": [node…]}``. An *orphan* is a span whose ``parent_id``
    names a span absent from its trace — the acceptance criterion is that
    a serve session produces none."""
    traces: Dict[str, Dict[str, Any]] = {}
    for rec in spans:
        t = traces.setdefault(
            rec["trace_id"], {"roots": [], "orphans": [], "spans": {}}
        )
        t["spans"][rec["span_id"]] = {"span": rec, "children": []}
    for t in traces.values():
        for node in t["spans"].values():
            pid = node["span"].get("parent_id")
            if pid is None:
                t["roots"].append(node)
            elif pid in t["spans"]:
                t["spans"][pid]["children"].append(node)
            else:
                t["orphans"].append(node["span"])
        for nodes in t["spans"].values():
            nodes["children"].sort(key=lambda nd: nd["span"]["ts"])
        t["roots"].sort(key=lambda nd: nd["span"]["ts"])
    return traces


def orphan_spans(spans: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Every span whose parent is missing from its own trace."""
    out: List[Dict[str, Any]] = []
    for t in build_trees(spans).values():
        out.extend(t["orphans"])
    return out

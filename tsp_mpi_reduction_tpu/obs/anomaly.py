"""StepSampler-fed solver anomaly sentinel (ISSUE 9 tentpole).

The B&B host loops already sample one telemetry row per dispatch
(``obs.timeseries.StepSampler``); this module watches that stream *live*
and fires health events when the search goes pathological:

``nodes_rate_collapse``
    the median nodes/sec of the newest ``window`` dispatches fell below
    ``collapse_ratio`` x the median of the preceding windows — the shape
    of a degraded relay (the ~65 ms/iteration post-readback mode), a
    wedged backend, or a frontier thrashing against its spill headroom.

``lb_stagnation``
    over the last ``lb_window`` dispatches the certified lower-bound
    floor gained less than ``lb_min_gain`` AND the incumbent did not
    improve AND the total open work (frontier + host reservoir) did not
    shrink — zero progress on both ends of the gap while the search is
    NOT draining toward a proof. The drain condition is load-bearing:
    within one solve the certified floor is clamped once at setup and
    cannot move, and the incumbent is legitimately flat for the entire
    prove-the-incumbent endgame — without it the detector fired on
    every healthy proof run longer than ``lb_window`` dispatches
    (reproduced on the TSP_BENCH=obs config). A draining frontier IS
    gap progress; only a search holding/growing its open set while both
    bounds sit still is stalled. This is the run-to-exhaustion signal
    the chunked driver's stall rule sees only at chunk granularity;
    here it fires mid-chunk, per dispatch.

Events go three places, all pre-existing consumer surfaces: the health
counter block (``resilience.health`` → ``health_events_total{event=…}`` —
the serve watchdog and the chunked driver already read health blocks),
the metrics registry (``bnb_anomalies_total{kind=…}``), and the active
span as a trace event (a campaign trace shows WHERE the collapse
happened). Each detector fires once per episode (re-arming only after
the signal recovers), so a long degraded stretch is one event, not one
per dispatch.

Overhead discipline: the sentinel exists only when obs is enabled
(``maybe()``, mirroring ``StepSampler.maybe``), and the per-dispatch
path is two list appends and a counter compare — ALL analysis (medians,
window anchors, stagnation spans) runs once per ``window`` samples. The
TSP_BENCH=obs <= 2% budget prices this in; the sentinel was rewritten
to this amortized shape after the first wiring measurably pushed the
bench over it.
"""

from __future__ import annotations

from collections import deque
from statistics import median as _median
from typing import Any, Dict, List, Optional

from . import enabled as _obs_enabled
from .metrics import REGISTRY

#: window medians kept as the collapse baseline (current window judged
#: against the median of the previous up-to-this-many window medians)
_BASELINE_WINDOWS = 4


def fire_anomaly(
    events: List[Dict[str, Any]], kind: str, step: int, **detail: Any
) -> Dict[str, Any]:
    """Record one anomaly event on every consumer surface at once: the
    caller's event list (-> ``BnBResult.anomalies``), the metrics
    registry (``bnb_anomalies_total{kind=…}``), the health counter block
    (the serve watchdog and the chunked driver already parse it), and
    the active trace span. Shared by every sentinel in this module so a
    new anomaly kind cannot silently miss a surface."""
    event = {"kind": kind, "step": int(step), **detail}
    events.append(event)
    REGISTRY.inc("bnb_anomalies_total", kind=kind)
    from ..resilience.health import HEALTH

    HEALTH.incr(f"anomaly_{kind}")
    from . import tracing as _tracing

    _tracing.add_event(f"anomaly_{kind}", **{"step": int(step), **detail})
    return event


def merge_summaries(*sentinels: Optional[Any]) -> Optional[Dict[str, Any]]:
    """One ``anomalies`` block from several sentinels (stall + rank
    starvation): events merged onto one step-ordered timeline. None when
    every sentinel is None (``TSP_OBS=off`` — the solver result's
    contract is that the whole block is absent, not empty)."""
    alive = [s for s in sentinels if s is not None]
    if not alive:
        return None
    events = sorted(
        (e for s in alive for e in s.events),
        key=lambda e: (e.get("step", 0), e.get("kind", "")),
    )
    return {"events": events, "fired": len(events)}


class StallSentinel:
    """Streaming detector over (nodes/sec, certified-LB-floor, incumbent)
    samples. Hot path: buffer the sample; every ``window`` samples, run
    both checks on the buffered window."""

    __slots__ = (
        "window", "collapse_ratio", "lb_window", "lb_min_gain", "min_rate",
        "_buf", "_count", "_last", "_medians", "_anchors",
        "_rate_alarmed", "_lb_alarmed", "events", "consumed",
    )

    def __init__(
        self,
        *,
        window: int = 16,
        collapse_ratio: float = 0.25,
        lb_window: int = 256,
        lb_min_gain: float = 1e-9,
        min_rate: float = 0.0,
    ) -> None:
        if window < 2:
            raise ValueError(f"window must be >= 2, got {window}")
        self.window = window
        self.collapse_ratio = collapse_ratio
        self.lb_window = lb_window
        self.lb_min_gain = lb_min_gain
        #: rates at/below this never enter a window median (a dispatch
        #: that popped nothing is a refill boundary, not a healthy rate)
        self.min_rate = min_rate
        self._buf: List[float] = []  # current window's rates (hot append)
        self._count = 0  # samples in the current window (hot compare)
        #: newest sample: (step, lb_floor, incumbent, open_nodes)
        self._last: tuple = (0, float("-inf"), float("inf"), 0)
        self._medians: deque = deque(maxlen=_BASELINE_WINDOWS)
        #: one (step, lb_floor, incumbent) anchor per completed window;
        #: stagnation compares the oldest vs newest anchor, so the span
        #: covers ~lb_window dispatches at window-granular anchors
        self._anchors: deque = deque(maxlen=max(2, lb_window // window))
        self._rate_alarmed = False
        self._lb_alarmed = False
        #: fired events, newest-last: [{"kind", "step", ...detail}]
        self.events: List[Dict[str, Any]] = []
        #: sampler-ring rows already consumed (see :meth:`consume`)
        self.consumed = 0

    @classmethod
    def maybe(cls, **kw) -> Optional["StallSentinel"]:
        """A sentinel when obs is enabled, else None (one is-None check
        per dispatch under ``TSP_OBS=off`` — same contract as the
        sampler it rides next to)."""
        return cls(**kw) if _obs_enabled() else None

    # -- the per-dispatch feed ----------------------------------------------

    def feed(
        self,
        step: int,
        nodes_per_s: float,
        lb_floor: float,
        incumbent: float,
        open_nodes: int = 0,
    ) -> Optional[List[Dict[str, Any]]]:
        """Positional hot-path feed (direct callers / :meth:`observe`):
        returns None except at a window boundary, where it returns the
        events fired by the flush. ``open_nodes`` = total open work
        (frontier + reservoir) — the stagnation check's drain signal.
        All analysis (medians, anchors, stagnation spans) is amortized
        into that once-per-``window`` flush."""
        if nodes_per_s > self.min_rate:
            self._buf.append(nodes_per_s)
        self._count += 1
        if self._count < self.window:
            return None
        self._last = (step, lb_floor, incumbent, open_nodes)
        return self._flush_window()

    def consume(self, sampler) -> Optional[List[Dict[str, Any]]]:
        """Ring-fed batch path (what ``StepSampler.sample`` uses): pull
        every row appended to the sampler's ring since the last consume
        and run the window checks. The sampler calls this only when a
        full window has accrued, so the PER-DISPATCH sentinel cost is
        one attribute load + one integer compare — the second Python
        call per dispatch that :meth:`feed` used to be was about half
        the telemetry budget on the TSP_BENCH=obs gate. Semantics match
        feed(): same min-rate filter, same window cadence, and the
        window's newest row provides the (step, lb_floor, incumbent,
        open-work) anchor."""
        total, cap, rows = sampler._total, sampler.capacity, sampler._rows
        # rows older than the ring still holds cannot be replayed
        start = max(self.consumed, total - cap)
        wrapped = len(rows) == cap
        mr = self.min_rate
        buf = self._buf
        r = None
        for i in range(start, total):
            r = rows[i % cap] if wrapped else rows[i]
            rate = r[3]  # timeseries.COLUMNS: nodes_per_s
            if rate > mr:
                buf.append(rate)
        self._count += total - start
        self.consumed = total
        if self._count < self.window or r is None:
            return None
        # step, lb_floor, incumbent, frontier + reservoir (open work)
        self._last = (r[0], r[8], r[7], r[4] + r[9])
        return self._flush_window()

    def observe(
        self,
        *,
        step: int,
        nodes_per_s: float,
        lb_floor: float,
        incumbent: float = float("inf"),
        open_nodes: int = 0,
    ) -> List[Dict[str, Any]]:
        """Keyword wrapper over :meth:`feed` (tests / direct callers);
        returns the events fired by this sample (usually empty)."""
        return self.feed(step, nodes_per_s, lb_floor, incumbent, open_nodes) or []

    def _flush_window(self) -> List[Dict[str, Any]]:
        self._count = 0
        fired: List[Dict[str, Any]] = []
        step = self._last[0]
        if self._buf:
            cur = _median(self._buf)
            self._buf = []
            if len(self._medians) == self._medians.maxlen:
                fired.extend(self._check_rate(step, cur))
            self._medians.append(cur)
        self._anchors.append(self._last)
        if len(self._anchors) == self._anchors.maxlen:
            fired.extend(self._check_lb(step))
        return fired

    def _fire(self, kind: str, step: int, **detail: Any) -> Dict[str, Any]:
        return fire_anomaly(self.events, kind, step, **detail)

    def _check_rate(self, step: int, cur: float) -> List[Dict[str, Any]]:
        baseline = _median(self._medians)
        collapsed = baseline > 0 and cur < self.collapse_ratio * baseline
        if collapsed and not self._rate_alarmed:
            self._rate_alarmed = True
            return [self._fire(
                "nodes_rate_collapse", step,
                recent_median=round(cur, 3),
                baseline_median=round(baseline, 3),
                ratio=round(cur / baseline, 4),
            )]
        if not collapsed:
            self._rate_alarmed = False  # episode over: re-arm
        return []

    def _check_lb(self, step: int) -> List[Dict[str, Any]]:
        step0, lb0, inc0, open0 = self._anchors[0]
        step1, lb1, inc1, open1 = self._anchors[-1]
        span_steps = step1 - step0
        # total stagnation only: a flat certified floor is NORMAL mid-DFS
        # (within one solve it CANNOT move — it is clamped once at setup),
        # and a flat incumbent is the entire prove-the-incumbent endgame.
        # The verdict therefore also needs the open work (frontier +
        # reservoir) to be holding/growing: a draining open set IS gap
        # progress, and without this condition the detector fired on
        # every healthy proof run longer than lb_window dispatches.
        floor_flat = (
            lb0 > float("-inf") and (lb1 - lb0) < self.lb_min_gain
        )
        inc_flat = not (inc1 < inc0 - self.lb_min_gain)
        not_draining = open1 >= open0
        stagnant = span_steps > 0 and floor_flat and inc_flat and not_draining
        if stagnant and not self._lb_alarmed:
            self._lb_alarmed = True
            return [self._fire(
                "lb_stagnation", step,
                lb_floor=round(lb1, 6),
                gain=round(lb1 - lb0, 9),
                over_steps=int(span_steps),
                open_nodes=int(open1),
            )]
        if not stagnant:
            self._lb_alarmed = False
        return []

    def summary(self) -> Dict[str, Any]:
        """JSON-ready tail for the solver result / driver payload."""
        return {
            "events": list(self.events),
            "fired": len(self.events),
        }


class RankStarvationSentinel:
    """Per-rank starvation-episode detector over the rank-resolved
    telemetry windows (ISSUE 10).

    Fed once per ``obs.rankview.RankSampler`` window with the per-rank
    occupancy snapshot and the per-rank nodes expanded IN that window. A
    rank is *starving* in a window when the mesh as a whole expanded
    work but that rank's share fell below ``starve_ratio`` x its fair
    share (total / num_ranks) — in the SPMD engine every rank runs the
    same dispatches, so under-expansion can only mean the rank HAD no
    work: the stranded-rank shape the VERDICT r4 ring-balance autopsy
    measured (12,554x max/min node imbalance, one rank pinned at 7
    nodes for a 238k-node run).

    Episode semantics match the stall sentinel: ``rank_starvation``
    fires once per rank per episode, after ``patience`` consecutive
    starving windows, and re-arms only when the rank recovers — a rank
    stranded for an hour is one event, not one per window. Events go
    through :func:`fire_anomaly` (health counters, registry, live
    span), with the rank id in the event detail (bounded label: ranks
    come from ``range(num_ranks)``).

    A drained mesh (zero nodes everywhere — the proof endgame or the
    terminal window) is not starvation: nobody is being starved when
    there is nothing to eat; streaks hold but never grow across such
    windows.
    """

    __slots__ = (
        "num_ranks", "starve_ratio", "patience",
        "_streak", "_alarmed", "episodes_per_rank", "events",
    )

    def __init__(
        self,
        num_ranks: int,
        *,
        starve_ratio: float = 0.1,
        patience: int = 2,
    ) -> None:
        if num_ranks < 1:
            raise ValueError(f"num_ranks must be >= 1, got {num_ranks}")
        if patience < 1:
            raise ValueError(f"patience must be >= 1, got {patience}")
        self.num_ranks = num_ranks
        self.starve_ratio = starve_ratio
        self.patience = patience
        self._streak = [0] * num_ranks
        self._alarmed = [False] * num_ranks
        #: fired-episode count per rank (rank_balance's starvation column)
        self.episodes_per_rank = [0] * num_ranks
        #: fired events, newest-last (merge_summaries folds them into the
        #: solver's anomalies block beside the stall sentinel's)
        self.events: List[Dict[str, Any]] = []

    @classmethod
    def maybe(cls, num_ranks: int, **kw) -> Optional["RankStarvationSentinel"]:
        """A sentinel when obs is enabled, else None — same contract as
        the samplers it rides next to."""
        return cls(num_ranks, **kw) if _obs_enabled() else None

    def observe_window(
        self, step: int, occupancy, nodes
    ) -> List[Dict[str, Any]]:
        """One completed sampling window: per-rank occupancy (current
        rows) and per-rank nodes expanded within the window. Returns the
        events fired by this window (usually empty). Called once per
        window by ``RankSampler.sample`` — never per dispatch."""
        fired: List[Dict[str, Any]] = []
        if self.num_ranks < 2:
            return fired  # a 1-rank mesh cannot starve anyone
        total = float(sum(nodes))
        if total <= 0:
            return fired  # drained/idle mesh: hold streaks, fire nothing
        fair = total / self.num_ranks
        cut = self.starve_ratio * fair
        for r in range(self.num_ranks):
            if float(nodes[r]) < cut:
                self._streak[r] += 1
                if self._streak[r] >= self.patience and not self._alarmed[r]:
                    self._alarmed[r] = True
                    self.episodes_per_rank[r] += 1
                    fired.append(fire_anomaly(
                        self.events, "rank_starvation", step,
                        rank=r,
                        window_nodes=int(nodes[r]),
                        fair_share=round(fair, 1),
                        mesh_nodes=int(total),
                        windows=self._streak[r],
                        occupancy=int(occupancy[r]),
                    ))
            else:
                self._streak[r] = 0
                self._alarmed[r] = False  # episode over: re-arm
        return fired

    def summary(self) -> Dict[str, Any]:
        """JSON-ready tail (same shape as the stall sentinel's)."""
        return {
            "events": list(self.events),
            "fired": len(self.events),
        }

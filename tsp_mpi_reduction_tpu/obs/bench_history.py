"""Benchmark history + median/MAD regression sentinel (ISSUE 9 tentpole).

Every ``TSP_BENCH=*`` run (and every ``tools/tpu_bench.sh`` leg) appends
one fingerprinted record to ``bench_history.jsonl``: what was measured
(``metric``/``value``/``unit`` — the same headline every ``BENCH_*.json``
artifact carries), and under which conditions (git rev, jax+jaxlib
version, backend, a hash of the bench's config knobs). The one-shot
artifacts stay — they are the *latest* full evidence — but the history is
what gives the repo a perf *trajectory*: ``tools/bench_check.py`` (and
``make bench-check``, chained into the default ``make``) compares the
newest sample of every governed metric against the median of its prior
samples under the same (backend, config) conditions, with a MAD-scaled
noise floor, and FAILS on a regression instead of letting it age
invisibly inside a JSON file.

Append discipline: one ``O_APPEND`` write of one complete line under an
``flock`` — the same crash-safety posture as ``write_json_atomic``
(``resilience.checkpoint``), adapted to an append-only log: a writer
killed mid-call leaves either no line or a whole line (the lock orders
concurrent writers; a torn tail from a hard kill is skipped by the
reader, exactly like the trace JSONL reader).

Detection model (:func:`check`): per metric, per (backend, config_hash,
host-fingerprint) group — samples from different hardware classes or
configs never vote on each other (:func:`host_fingerprint` hashes
arch + cores + CPU model, NOT the hostname, so ephemeral CI containers
on one hardware pool still share a history) — the newest value
regresses when its direction-adjusted deviation
from the median of the PRIOR samples exceeds
``max(rel_threshold * |median|, abs_threshold, mad_k * 1.4826 * MAD)``.
The MAD term is the noise floor: a metric that historically wobbles ±8%
is not failed for a 5% dip, while a historically flat one is. Below
``min_samples`` prior samples the verdict is ``insufficient`` — recorded,
never failing — so a fresh clone's first benches pass while the history
accretes.
"""

from __future__ import annotations

import hashlib
import json
import os
import subprocess
import sys
import time
from statistics import median as _median
from dataclasses import dataclass
from typing import Any, Dict, Iterable, List, Optional

SCHEMA_VERSION = 1

#: default history file, repo-root-relative; override (or disable with
#: "off") via this env var — tests point it at a tmp dir, benches in CI
#: at the checked-in file
ENV_VAR = "TSP_BENCH_HISTORY"
_DISABLED = ("off", "0", "none", "disabled")
DEFAULT_PATH = "bench_history.jsonl"


def resolve_history_path(default_dir: Optional[str] = None) -> Optional[str]:
    """The configured history path, or None when appending is disabled."""
    val = os.environ.get(ENV_VAR)
    if val is None:
        base = default_dir if default_dir is not None else os.getcwd()
        return os.path.join(base, DEFAULT_PATH)
    val = val.strip()
    if not val or val.lower() in _DISABLED:
        return None
    return val


# -- fingerprinting ------------------------------------------------------------


def git_rev(cwd: Optional[str] = None) -> Optional[str]:
    """Short rev of the working tree the bench ran in (None outside git —
    the record is still useful, just unpinned)."""
    try:
        out = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, timeout=10.0,
            cwd=cwd or os.path.dirname(os.path.abspath(__file__)),
        )
    except (OSError, subprocess.TimeoutExpired):
        return None
    rev = out.stdout.strip()
    return rev if out.returncode == 0 and rev else None


def config_hash(config: Dict[str, Any]) -> str:
    """Stable digest of the bench's config knobs: two records compare only
    when they measured the same thing the same way."""
    payload = json.dumps(config, sort_keys=True, default=str)
    return hashlib.blake2b(payload.encode(), digest_size=8).hexdigest()


def host_fingerprint() -> str:
    """Digest of the measuring HARDWARE class: arch + logical cores + CPU
    model string. Grouping on this (not the hostname) keeps the promise
    that samples from different machines never vote on each other, while
    still letting ephemeral CI containers on the same hardware pool
    accrete one shared history — a container hostname is random per run
    and would pin every group at min_samples forever."""
    model = ""
    try:
        with open("/proc/cpuinfo", encoding="utf-8") as fh:
            for line in fh:
                if line.lower().startswith(("model name", "hardware", "cpu model")):
                    model = line.partition(":")[2].strip()
                    break
    except OSError:
        pass
    import platform

    payload = f"{platform.machine()}|{os.cpu_count()}|{model}"
    return hashlib.blake2b(payload.encode(), digest_size=6).hexdigest()


def _jax_versions() -> Dict[str, Optional[str]]:
    """jax/jaxlib versions WITHOUT importing jax (a parent bench spawner
    must never initialize a backend): read them only if already loaded."""
    jax = sys.modules.get("jax")
    jaxlib = sys.modules.get("jaxlib")
    return {
        "jax": getattr(jax, "__version__", None),
        "jaxlib": getattr(jaxlib, "__version__", None),
    }


def make_record(
    mode: str,
    artifact: Dict[str, Any],
    *,
    config: Optional[Dict[str, Any]] = None,
    backend: Optional[str] = None,
    ts: Optional[float] = None,
) -> Dict[str, Any]:
    """One history line from a bench artifact: the headline triple plus
    the fingerprint. ``config`` is whatever knob dict the bench considers
    identity-defining (instance, k, reps, ...); ``backend`` defaults to
    the live jax backend when jax is already imported, else "unknown"."""
    if backend is None:
        jax = sys.modules.get("jax")
        try:
            backend = jax.default_backend() if jax is not None else "unknown"
        except Exception:  # noqa: BLE001 — a dead backend is not a reason to drop history
            backend = "unknown"
    cfg = dict(config or {})
    rec: Dict[str, Any] = {
        "schema": SCHEMA_VERSION,
        "ts": round(time.time(), 3) if ts is None else ts,
        "mode": mode,
        "metric": artifact.get("metric"),
        "value": artifact.get("value"),
        "unit": artifact.get("unit"),
        "ok": artifact.get("ok"),
        "vs_baseline": artifact.get("vs_baseline"),
        "git_rev": git_rev(),
        "backend": backend,
        "host": host_fingerprint(),
        "config": cfg,
        "config_hash": config_hash(cfg),
    }
    rec.update(_jax_versions())
    return rec


# -- the locked append ---------------------------------------------------------


def append(path: str, record: Dict[str, Any]) -> None:
    """Append one record as one line: ``O_APPEND`` + ``flock`` so
    concurrent benches (tpu_bench.sh legs, parallel CI shards on a shared
    checkout) interleave whole lines, never bytes. Raises only for a
    non-dict record; IO errors are swallowed — history is an observer."""
    if not isinstance(record, dict):
        raise TypeError(f"history record must be a dict, got {type(record).__name__}")
    line = json.dumps(record) + "\n"
    try:
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
        try:
            try:
                import fcntl

                fcntl.flock(fd, fcntl.LOCK_EX)
            except (ImportError, OSError):
                pass  # no flock (non-POSIX): O_APPEND alone is still line-atomic for short lines
            os.write(fd, line.encode())
            os.fsync(fd)
        finally:
            os.close(fd)
    except OSError:
        pass


def read(path: str) -> List[Dict[str, Any]]:
    """All parseable records, file order (== append order). Malformed
    lines (a torn tail from a hard kill) are skipped, like read_trace."""
    records: List[Dict[str, Any]] = []
    try:
        with open(path, encoding="utf-8") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if isinstance(rec, dict) and rec.get("metric") is not None:
                    records.append(rec)
    except OSError:
        return []
    return records


# -- regression detection ------------------------------------------------------


@dataclass(frozen=True)
class MetricRule:
    """Per-metric regression policy.

    ``direction``: "lower" = smaller is better (wall ms), "higher" =
    bigger is better (nodes/sec, speedup ratios). ``rel_threshold`` /
    ``abs_threshold``: the explicit tolerance band; ``mad_k`` scales the
    history's own MAD (x1.4826 = sigma-consistent) into a noise floor so
    a naturally jittery metric does not cry wolf. ``min_samples``: prior
    samples required before the rule can FAIL anything."""

    direction: str = "lower"
    rel_threshold: float = 0.20
    abs_threshold: float = 0.0
    mad_k: float = 3.0
    min_samples: int = 4

    def __post_init__(self):
        if self.direction not in ("lower", "higher"):
            raise ValueError(f"direction must be lower|higher, got {self.direction!r}")


#: the governed metrics: every bench mode's headline. Throughput/wall
#: thresholds sit at 15% — tight enough that a 20% slowdown FAILS (the
#: acceptance bar), loose enough that the documented ±8% host drift
#: passes; the MAD floor widens the band further on metrics whose own
#: history proves noisier than that. Subprocess-startup ratios get 30%
#: (cold-start wall is the jitteriest thing this repo measures).
DEFAULT_RULES: Dict[str, MetricRule] = {
    "pipeline_16x100_wall_ms": MetricRule(direction="lower", rel_threshold=0.15),
    "bnb_eil51_nodes_per_sec": MetricRule(direction="higher", rel_threshold=0.15),
    "bnb_burma14_nodes_per_sec": MetricRule(direction="higher", rel_threshold=0.15),
    "sharded_spill_transfer_bytes_per_round": MetricRule(
        direction="lower", rel_threshold=0.15
    ),
    "serve_microbatch_vs_sequential_throughput": MetricRule(
        direction="higher", rel_threshold=0.15
    ),
    "compile_once_warm_start": MetricRule(direction="higher", rel_threshold=0.30),
    "fused_vs_reference_expansion_step": MetricRule(
        direction="higher", rel_threshold=0.15
    ),
    # percentage near zero: relative bands are meaningless, use absolute
    # (obs overhead may drift 0.5% -> 1.4% without failing; 0.5% -> 4%
    # fails — the <=2% acceptance is the bench's own gate, this one
    # catches creep across commits)
    "obs_overhead": MetricRule(
        direction="lower", rel_threshold=0.0, abs_threshold=2.5, min_samples=4
    ),
    # marginal per-dispatch hook cost in us: a per-pair wall-diff
    # estimate, so the band is absolute (position/cache noise is ~±2 us
    # at the bench's dispatch sizes) — catches a hook regression (an
    # added registry call or host sync per dispatch is +1-10 us) that
    # a wall ratio dilutes away at coarse dispatch granularity
    "obs_us_per_dispatch": MetricRule(
        direction="lower", rel_threshold=0.0, abs_threshold=4.0, min_samples=4
    ),
    "atomic_checkpoint_overhead": MetricRule(
        direction="lower", rel_threshold=0.0, abs_threshold=5.0, min_samples=4
    ),
    # rank-resolved telemetry hook (ISSUE 10, TSP_BENCH=shard): same
    # absolute-band rationale as obs_overhead — a percentage near zero
    # has no meaningful relative band; 0.4% -> 1.2% drift passes, a
    # creep past ~3% (hook grew past the <=2% design budget + noise)
    # fails the build
    "shard_rank_obs_overhead": MetricRule(
        direction="lower", rel_threshold=0.0, abs_threshold=2.5, min_samples=4
    ),
    # marginal rank-hook cost per host dispatch in us (the due() compare
    # amortizing one [R, K] collective per window): absolute band wide
    # enough for dispatch-size noise, tight enough that an accidental
    # per-dispatch collective (window=1 regression) jumps the series
    "shard_rank_us_per_dispatch": MetricRule(
        direction="lower", rel_threshold=0.0, abs_threshold=8.0, min_samples=4
    ),
    # fleet robustness (ISSUE 11, TSP_BENCH=fleet): fraction of the chaos
    # leg's requests answered EXACTLY ONCE with a valid tour while
    # replicas are killed/hung mid-flight — a COUNTER estimator, not a
    # wall ratio (host noise makes <5% wall gates unmeasurable here;
    # RPS/p99 ride the artifact unguarded). The healthy value is 1.0 and
    # MAD over an all-1.0 history is 0, so the tiny absolute band is the
    # whole gate: any dropped or duplicated request fails the build.
    "fleet_chaos_answered_rate": MetricRule(
        direction="higher", rel_threshold=0.0, abs_threshold=0.001, min_samples=4
    ),
    # iteration-level serving (ISSUE 13, TSP_BENCH=serve): the
    # mixed-workload continuous-batching ratio — short-request completion
    # throughput with a head-of-line proof preempted into slices vs run
    # to completion. A wall ratio on a contended host, so a relative band
    "serve_service_ratio": MetricRule(direction="higher", rel_threshold=0.15),
    # fraction of feasible-tight-deadline requests answered by an exact
    # rung (certified_gap == 0): a COUNTER estimator whose healthy value
    # is 1.0 (MAD over an all-1.0 history is 0) — the small absolute band
    # is the whole gate, any tier-routing regression fails the build
    "serve_tight_deadline_exact_rate": MetricRule(
        direction="higher", rel_threshold=0.0, abs_threshold=0.02, min_samples=4
    ),
    # adaptive load balance (ISSUE 15, TSP_BENCH=balance): the adaptive
    # leg's per-rank node imbalance (nodes max / max(min, 1)) on the
    # skewed single-rank-seeded config. Healthy values sit near 1-3 and a
    # ratio near 1 has no meaningful relative band, so the band is
    # absolute: a controller regression that strands a rank again (the
    # static-ring regime measures in the hundreds here) jumps the series
    # far past it
    "shard_balance_imbalance": MetricRule(
        direction="lower", rel_threshold=0.0, abs_threshold=5.0, min_samples=4
    ),
    # the repartition's traffic price: moved slab bytes per expanded node
    # on the adaptive leg. Relative band — the healthy value scales with
    # instance/config, and a silent doubling (escalation stuck on steal,
    # dead-band broken) is the regression being guarded
    "shard_steal_bytes_per_node": MetricRule(
        direction="lower", rel_threshold=0.50, min_samples=4
    ),
}


@dataclass
class Verdict:
    metric: str
    group: str  # "backend/config_hash"
    status: str  # "ok" | "regression" | "insufficient" | "no_value"
    latest: Optional[float] = None
    median: Optional[float] = None
    mad: Optional[float] = None
    allowed: Optional[float] = None
    deviation: Optional[float] = None
    samples: int = 0
    detail: str = ""

    def as_dict(self) -> Dict[str, Any]:
        return {k: v for k, v in self.__dict__.items()}


def check(
    records: Iterable[Dict[str, Any]],
    rules: Optional[Dict[str, MetricRule]] = None,
) -> List[Verdict]:
    """Evaluate the NEWEST sample of every governed metric against its
    prior samples, per (backend, config_hash) group. Returns one Verdict
    per (metric, group) that has at least one sample; callers fail on any
    ``status == "regression"``."""
    rules = DEFAULT_RULES if rules is None else rules
    groups: Dict[tuple, List[Dict[str, Any]]] = {}
    for rec in records:
        metric = rec.get("metric")
        if metric not in rules:
            continue
        # host rides the group key: per-dispatch hook costs and wall
        # metrics vary severalfold across hardware classes — a fresh
        # clone on a slower machine must start its own history, not be
        # failed against the author's laptop (records from pre-host
        # schema versions group under "?" and age out naturally)
        key = (
            metric,
            rec.get("backend", "unknown"),
            rec.get("config_hash", ""),
            rec.get("host", "?"),
        )
        groups.setdefault(key, []).append(rec)

    verdicts: List[Verdict] = []
    for (metric, backend, cfg, host), recs in sorted(groups.items()):
        rule = rules[metric]
        group = f"{backend}/{cfg or '-'}/{host}"
        vals = [
            float(r["value"])
            for r in recs
            if isinstance(r.get("value"), (int, float))
        ]
        if not vals:
            verdicts.append(Verdict(metric, group, "no_value",
                                    samples=len(recs),
                                    detail="no numeric samples"))
            continue
        latest, prior = vals[-1], vals[:-1]
        if len(prior) < rule.min_samples:
            verdicts.append(Verdict(
                metric, group, "insufficient", latest=latest,
                samples=len(vals),
                detail=f"{len(prior)} prior < min_samples={rule.min_samples}",
            ))
            continue
        med = _median(prior)
        mad = _median([abs(v - med) for v in prior])
        allowed = max(
            rule.rel_threshold * abs(med),
            rule.abs_threshold,
            rule.mad_k * 1.4826 * mad,
        )
        # positive deviation == worse, whatever the metric's direction
        deviation = (latest - med) if rule.direction == "lower" else (med - latest)
        status = "regression" if deviation > allowed else "ok"
        verdicts.append(Verdict(
            metric, group, status, latest=latest, median=round(med, 6),
            mad=round(mad, 6), allowed=round(allowed, 6),
            deviation=round(deviation, 6), samples=len(vals),
            detail=(
                f"latest {latest:g} vs median {med:g} "
                f"({'+' if deviation >= 0 else ''}{deviation:g} worse, "
                f"allowed {allowed:g})"
            ),
        ))
    return verdicts


def load_rules(path: str) -> Dict[str, MetricRule]:
    """Rules from a JSON file ``{metric: {direction, rel_threshold, ...}}``
    MERGED over the defaults (a project tunes thresholds without
    restating the whole table; ``null`` drops a default metric)."""
    with open(path, encoding="utf-8") as fh:
        raw = json.load(fh)
    rules = dict(DEFAULT_RULES)
    for metric, spec in raw.items():
        if spec is None:
            rules.pop(metric, None)
            continue
        rules[metric] = MetricRule(**spec)
    return rules

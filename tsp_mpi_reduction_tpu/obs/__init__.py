"""Unified telemetry layer (ISSUE 6): metrics, tracing, time-series.

Three pillars, one package, stdlib-only on the hot paths:

- :mod:`.metrics` — the process-global :data:`~.metrics.REGISTRY` of
  counters / gauges / histograms with labeled series, snapshot/delta
  semantics, and two exporters (structured JSON + Prometheus text
  exposition, optionally over an HTTP endpoint). Every pre-existing
  signal source — ``resilience.health`` counters, ``SpillStats``,
  compile-cache hit/miss/seconds, scheduler occupancy/flush counters,
  ``PhaseTimer`` phases — now records into (or mirrors onto) this one
  registry, so ``service_stats_json`` and the driver JSON are views over
  a single source of truth instead of three bespoke builders.
- :mod:`.tracing` — lightweight span tracing with propagated trace/span
  IDs. One serve request yields a span tree (request → canonicalize →
  cache lookup → queue wait → flush → ladder rung → device dispatch →
  respond) emitted as JSONL; injected faults surface as span events on
  whatever span was active when the seam fired. ``device_trace`` runs
  additionally gain ``jax.profiler.StepTraceAnnotation`` per expansion
  dispatch so TensorBoard/Perfetto timelines segment by B&B step.
- :mod:`.timeseries` — a ring-buffered per-dispatch sampler in the B&B
  host loops (nodes/sec, frontier occupancy, spill bytes each way,
  incumbent/certified-floor trajectory) flushed into the solver result
  and driver JSON; ``tools/obs_report.py`` renders both artifacts.

The performance-accounting layer (ISSUE 9) consumes those pillars:

- :mod:`.costs` — XLA cost attribution per compiled hot entry
  (``Compiled.cost_analysis``/``memory_analysis`` at compile/AOT-load
  time, roofline utilization estimate vs a per-backend peak table) →
  the ``obs.device_costs`` stats block + ``xla_entry_*`` gauges.
- :mod:`.bench_history` — fingerprinted ``bench_history.jsonl`` records
  appended by every ``TSP_BENCH`` run + the median/MAD regression
  detector behind ``make bench-check``.
- :mod:`.slo` — per-tier serve latency objectives: session-window
  attainment + error-budget burn rate from the tier-labeled latency
  histograms (the stats ``slo`` block).
- :mod:`.anomaly` — the ``StepSampler``-fed stall sentinel (nodes/sec
  collapse, certified-LB stagnation) firing health events mid-solve,
  plus the per-rank ``rank_starvation`` sentinel (ISSUE 10).
- :mod:`.rankview` — rank-resolved telemetry for the sharded search
  (ISSUE 10): a per-window ``RankSampler`` ring fed by one small
  ``[R, K]`` device stats row (``parallel.reduce.make_rank_stats``),
  imbalance accounting (occupancy CV, straggler score, starved ranks)
  stamped as ``rank_series`` + ``obs.rank_balance`` into the driver
  payload, and bounded rank-labeled registry gauges.
- :mod:`.tracing` additionally propagates across PROCESSES via the
  ``TSP_TRACE_PARENT=<trace_id>:<span_id>`` env contract, so a chunked
  campaign reconstructs as one span tree.

Gating: ``TSP_OBS=off`` disables the *optional-overhead* telemetry —
span tracing, the per-step sampler, profiler step annotations, phase
mirroring. Plain registry counters stay on regardless: they replace the
pre-existing health/cache/scheduler counters, which correctness paths
and stats JSON depend on, and cost one locked dict add per *event*
(request / flush / dispatch), never per in-kernel step. graftlint rule
R8 enforces that no recording call ever sits inside jit-traced code.
"""

from __future__ import annotations

import os
from typing import Optional

#: env knob: "off"/"0"/"false"/"none" disables tracing + sampler +
#: annotations + phase mirroring (counters stay on; see module docstring)
ENV_VAR = "TSP_OBS"
_OFF = ("off", "0", "false", "none", "disabled")

_override: Optional[bool] = None


def enabled() -> bool:
    """Is the optional-overhead telemetry (tracing/sampler/mirroring) on?"""
    if _override is not None:
        return _override
    return os.environ.get(ENV_VAR, "on").strip().lower() not in _OFF


def set_enabled(value: Optional[bool]) -> None:
    """Programmatic override for benches/tests (None = back to the env)."""
    global _override
    _override = value

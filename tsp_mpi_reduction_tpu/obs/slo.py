"""Serve latency SLOs: windowed attainment + burn rate from histograms.

Clipper (PAPERS.md) frames serving as *meeting explicit latency
objectives*, not just reporting latencies. This module closes that gap:
``ServiceConfig.slos`` declares one objective per answering tier
(``{"target_ms": …, "goal": …}`` — e.g. "99% of greedy answers inside
50 ms"), and :func:`evaluate` computes, from the tier-labeled
``serve_request_seconds`` histograms the service already records, the
session-window attainment and the error-budget burn rate:

    attainment = P(latency <= target)           (bucket-interpolated)
    burn_rate  = (1 - attainment) / (1 - goal)  (1.0 = exactly on budget,
                                                 >1 = burning faster than
                                                 the objective allows)

The window is the serve session (the stats JSON's existing delta
semantics): ``SolveService`` snapshots the histograms at start and
evaluates the delta, so back-to-back sessions in one process judge their
OWN traffic. Attainment inside the bucket containing the target is
linearly interpolated — precise enough for objectives that sit between
bucket edges, and honest about it (``interpolated: true`` in the block).

The block rides ``service_stats_json`` (``slo`` key) so deadline-ladder
tuning, the ORCA-style scheduler work (ROADMAP), and fleet-level health
checks all read verdicts, not raw histograms.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Any, Dict, Optional

#: default per-tier objectives: generous enough that a healthy CPU serve
#: session passes, tight enough that a wedged worker or a cold-compile
#: stampede shows up as burn > 1. Tiers answer different budgets, so the
#: objectives scale with the rung.
DEFAULT_SLOS: Dict[str, Dict[str, float]] = {
    "greedy": {"target_ms": 50.0, "goal": 0.999},
    "pipeline": {"target_ms": 1000.0, "goal": 0.99},
    "bnb": {"target_ms": 10_000.0, "goal": 0.95},
}


def hist_attainment(hist: Dict[str, Any], target_s: float) -> Optional[float]:
    """Fraction of observations at or under ``target_s``, from a
    bucket-counts histogram dict (``obs.metrics._Hist.as_dict`` shape).
    Linear interpolation inside the bucket the target falls in; None when
    the histogram is empty (no verdict without traffic)."""
    count = int(hist.get("count", 0))
    if count <= 0:
        return None
    buckets = list(hist.get("buckets", ()))
    counts = list(hist.get("counts", ()))
    attained = 0.0
    lo = 0.0
    for edge, c in zip(buckets, counts):
        if target_s >= edge:
            attained += c
            lo = edge
            continue
        # target inside (lo, edge]: assume uniform spread in the bucket
        width = edge - lo
        frac = (target_s - lo) / width if width > 0 else 0.0
        attained += c * max(0.0, min(1.0, frac))
        break
    else:
        # target beyond the last finite edge: the +Inf bucket's
        # observations are all ABOVE it — conservatively not attained
        # unless the target is infinite
        pass
    return min(attained / count, 1.0)


class BurnMeter:
    """LIVE windowed burn-rate estimator — the admission-control signal.

    :func:`evaluate` judges a whole serve session retrospectively from
    histograms; admission control needs the burn rate NOW, over recent
    traffic only, so a bad first minute doesn't shed requests an hour
    later. This keeps a bounded deque of the last ``window`` latencies
    per answering tier and computes the same burn definition over it:

        burn = (fraction of recent answers over target) / (1 - goal)

    ``burn() is None`` until ``min_count`` answers have landed in a
    tier's window — no shedding on no evidence. The serve scheduler
    reads :meth:`burn` to prioritize a burning tier's queue and the
    ladder reads it to shed/degrade new admissions to that tier
    (``LadderConfig.slo_shed_burn``); :meth:`snapshot` rides the stats
    JSON ``admission`` block. Thread-safe; O(window) reads on arrays of
    ~tens of floats."""

    def __init__(
        self,
        slos: Optional[Dict[str, Dict[str, float]]] = None,
        window: int = 64,
        min_count: int = 8,
    ) -> None:
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        self.slos = DEFAULT_SLOS if slos is None else slos
        self.window = window
        self.min_count = max(int(min_count), 1)
        self._lock = threading.Lock()
        self._lat: Dict[str, deque] = {}

    def observe(self, tier: str, latency_s: float) -> None:
        with self._lock:
            dq = self._lat.get(tier)
            if dq is None:
                dq = self._lat[tier] = deque(maxlen=self.window)
            dq.append(float(latency_s))

    def burn(self, tier: str) -> Optional[float]:
        """Live error-budget burn rate for ``tier``; None without an
        objective or with fewer than ``min_count`` windowed answers."""
        obj = self.slos.get(tier)
        if obj is None:
            return None
        with self._lock:
            dq = self._lat.get(tier)
            if dq is None or len(dq) < self.min_count:
                return None
            lat = list(dq)
        target_s = float(obj["target_ms"]) / 1000.0
        missed = sum(1 for v in lat if v > target_s)
        budget = max(1.0 - float(obj["goal"]), 1e-9)
        return (missed / len(lat)) / budget

    def snapshot(self) -> Dict[str, Any]:
        """Per-tier ``{requests, burn_rate}`` over the live window (the
        stats JSON ``admission.burn`` block)."""
        with self._lock:
            sizes = {t: len(dq) for t, dq in self._lat.items()}
        out: Dict[str, Any] = {}
        for tier in sorted(set(self.slos) | set(sizes)):
            b = self.burn(tier)
            out[tier] = {
                "requests": sizes.get(tier, 0),
                "burn_rate": round(b, 4) if b is not None else None,
            }
        return out


def evaluate(
    hists_by_tier: Dict[str, Dict[str, Any]],
    slos: Optional[Dict[str, Dict[str, float]]] = None,
) -> Dict[str, Any]:
    """Per-tier SLO verdicts from tier-labeled latency histograms.

    ``hists_by_tier``: tier -> histogram dict (the session-window delta).
    Tiers with an objective but no traffic report ``requests: 0`` and no
    verdict; tiers with traffic but no objective are listed unjudged, so
    a new rung never silently escapes accounting."""
    slos = DEFAULT_SLOS if slos is None else slos
    out: Dict[str, Any] = {}
    for tier in sorted(set(slos) | set(hists_by_tier)):
        obj = slos.get(tier)
        hist = hists_by_tier.get(tier)
        requests = int(hist.get("count", 0)) if hist else 0
        row: Dict[str, Any] = {"requests": requests}
        if obj is None:
            row["objective"] = None
            out[tier] = row
            continue
        target_s = float(obj["target_ms"]) / 1000.0
        goal = float(obj["goal"])
        row["target_ms"] = float(obj["target_ms"])
        row["goal"] = goal
        attainment = hist_attainment(hist, target_s) if hist else None
        if attainment is None:
            row.update(attainment=None, burn_rate=None, ok=None)
        else:
            budget = max(1.0 - goal, 1e-9)
            burn = (1.0 - attainment) / budget
            row.update(
                attainment=round(attainment, 6),
                burn_rate=round(burn, 4),
                ok=attainment >= goal,
                interpolated=True,
            )
        out[tier] = row
    return out

"""Process-global metrics registry: labeled counters/gauges/histograms.

One :data:`REGISTRY` per process. Series are identified by (metric name,
sorted label items); recording is a locked dict update — cheap enough for
per-request / per-flush / per-dispatch call sites, and graftlint R8 keeps
it out of jit-traced code (where it would either force a recompile or
silently record a trace-time constant).

Snapshot/delta semantics: :meth:`MetricsRegistry.snapshot` is a frozen
point-in-time view; :meth:`MetricsRegistry.delta` subtracts a prior
snapshot from the live registry (counters and histograms subtract, gauges
report their current value), which is how back-to-back serve sessions and
repeated tests stop seeing each other's counts (ISSUE 6 satellite 1).

Exporters: :meth:`Snapshot.as_dict` (structured JSON — the stats-JSON
building block) and :func:`to_prometheus` (text exposition format, served
over HTTP by :func:`serve_metrics_http` for the serve CLI's
``--metrics-port``).
"""

from __future__ import annotations

import json
import threading
import time
from typing import Any, Dict, Iterable, List, Mapping, Optional, Tuple

LabelKey = Tuple[Tuple[str, str], ...]

#: default latency buckets (seconds) — wide enough for compile costs
DEFAULT_BUCKETS = (
    0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0, 60.0,
)

_KINDS = ("counter", "gauge", "histogram")


def _label_key(labels: Mapping[str, Any]) -> LabelKey:
    # the serve hot path records 4-6 series per request, almost all with
    # zero or one label — skip the sort (and its genexp frame) for those
    if not labels:
        return ()
    if len(labels) == 1:
        [(k, v)] = labels.items()
        return ((k, str(v)),)
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


class _Hist:
    __slots__ = ("buckets", "counts", "sum", "count")

    def __init__(self, buckets: Tuple[float, ...]):
        self.buckets = buckets
        self.counts = [0] * (len(buckets) + 1)  # +1 = the +Inf bucket
        self.sum = 0.0
        self.count = 0

    def observe(self, value: float) -> None:
        i = 0
        for i, b in enumerate(self.buckets):  # noqa: B007 — index reused
            if value <= b:
                break
        else:
            i = len(self.buckets)
        self.counts[i] += 1
        self.sum += value
        self.count += 1

    def as_dict(self) -> Dict[str, Any]:
        return {
            "buckets": list(self.buckets),
            "counts": list(self.counts),
            "sum": self.sum,
            "count": self.count,
        }


class _Metric:
    __slots__ = ("name", "kind", "help", "buckets", "series")

    def __init__(self, name: str, kind: str, help: str, buckets):
        self.name = name
        self.kind = kind
        self.help = help
        self.buckets = buckets
        #: LabelKey -> float (counter/gauge) or _Hist (histogram)
        self.series: Dict[LabelKey, Any] = {}


class Snapshot:
    """Frozen point-in-time view of (a subset of) the registry.

    ``data`` maps metric name -> {"kind", "help", "series": {LabelKey:
    float | hist-dict}}. Values are plain Python — safe to hold across
    further recording.
    """

    def __init__(self, data: Dict[str, Dict[str, Any]], taken_at: float):
        self.data = data
        self.taken_at = taken_at

    def value(self, name: str, **labels: Any) -> float:
        m = self.data.get(name)
        if m is None:
            return 0.0
        v = m["series"].get(_label_key(labels), 0.0)
        return v["sum"] if isinstance(v, dict) else v

    def series(self, name: str) -> Dict[LabelKey, Any]:
        m = self.data.get(name)
        return dict(m["series"]) if m else {}

    def as_dict(self) -> Dict[str, Any]:
        """JSON-ready nested form: name -> kind/help + a list of
        ``{"labels": {...}, "value"|"hist": ...}`` series entries."""
        out: Dict[str, Any] = {}
        for name in sorted(self.data):
            m = self.data[name]
            entries: List[Dict[str, Any]] = []
            for key in sorted(m["series"]):
                v = m["series"][key]
                entry: Dict[str, Any] = {"labels": dict(key)}
                if isinstance(v, dict):
                    entry["hist"] = v
                else:
                    entry["value"] = v
                entries.append(entry)
            out[name] = {"kind": m["kind"], "help": m["help"], "series": entries}
        return out

    def to_json(self) -> str:
        return json.dumps(self.as_dict())


class MetricsRegistry:
    """Thread-safe registry. Metric kind is fixed by first use (or an
    explicit :meth:`declare`); recording under a different kind raises —
    a silent kind flip would corrupt every scraper downstream."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}

    # -- declaration / recording --------------------------------------------

    def _metric(
        self, name: str, kind: str, help: str = "", buckets=None
    ) -> _Metric:
        m = self._metrics.get(name)
        if m is None:
            m = _Metric(name, kind, help, tuple(buckets or DEFAULT_BUCKETS))
            self._metrics[name] = m
        elif m.kind != kind:
            raise ValueError(
                f"metric {name!r} is a {m.kind}, not a {kind} — one name, "
                "one kind (declare() it once if the first-use site is "
                "ambiguous)"
            )
        return m

    def declare(
        self, name: str, kind: str, help: str = "", buckets=None
    ) -> None:
        if kind not in _KINDS:
            raise ValueError(f"unknown metric kind {kind!r} (one of {_KINDS})")
        with self._lock:
            m = self._metric(name, kind, help, buckets)
            if help and not m.help:
                m.help = help

    def inc(self, name: str, value: float = 1, **labels: Any) -> None:
        """Add to a counter series (monotone; negative increments raise)."""
        if value < 0:
            raise ValueError(f"counter {name!r} increment must be >= 0")
        key = _label_key(labels)
        with self._lock:
            m = self._metric(name, "counter")
            m.series[key] = m.series.get(key, 0.0) + value

    def set_gauge(self, name: str, value: float, **labels: Any) -> None:
        key = _label_key(labels)
        with self._lock:
            m = self._metric(name, "gauge")
            m.series[key] = float(value)

    def observe(self, name: str, value: float, **labels: Any) -> None:
        """Record one histogram observation."""
        key = _label_key(labels)
        with self._lock:
            m = self._metric(name, "histogram")
            h = m.series.get(key)
            if h is None:
                h = m.series[key] = _Hist(m.buckets)
            h.observe(float(value))

    # -- reads ---------------------------------------------------------------

    def value(self, name: str, **labels: Any) -> float:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                return 0.0
            v = m.series.get(_label_key(labels))
            if v is None:
                return 0.0
            return v.sum if isinstance(v, _Hist) else v

    def series(self, name: str) -> Dict[LabelKey, float]:
        """Scalar view of one metric's series (histograms report sums)."""
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                return {}
            return {
                k: (v.sum if isinstance(v, _Hist) else v)
                for k, v in m.series.items()
            }

    def snapshot(self, prefix: Optional[str] = None) -> Snapshot:
        with self._lock:
            data: Dict[str, Dict[str, Any]] = {}
            for name, m in self._metrics.items():
                if prefix is not None and not name.startswith(prefix):
                    continue
                data[name] = {
                    "kind": m.kind,
                    "help": m.help,
                    "series": {
                        k: (v.as_dict() if isinstance(v, _Hist) else v)
                        for k, v in m.series.items()
                    },
                }
        return Snapshot(data, time.time())

    def delta(self, since: Snapshot, prefix: Optional[str] = None) -> Snapshot:
        """Live registry minus ``since``: counters/histograms subtract
        (clamped at zero, so a mid-window reset cannot go negative),
        gauges report their current value. Series absent from ``since``
        count in full."""
        now = self.snapshot(prefix)
        for name, m in now.data.items():
            if m["kind"] == "gauge":
                continue
            old = since.data.get(name, {}).get("series", {})
            for key, v in m["series"].items():
                prev = old.get(key)
                if prev is None:
                    continue
                if isinstance(v, dict):
                    v["sum"] = max(v["sum"] - prev.get("sum", 0.0), 0.0)
                    v["count"] = max(v["count"] - prev.get("count", 0), 0)
                    v["counts"] = [
                        max(a - b, 0)
                        for a, b in zip(v["counts"], prev.get("counts", []))
                    ] if prev.get("counts") else v["counts"]
                else:
                    m["series"][key] = max(v - prev, 0.0)
        return now

    # -- lifecycle -----------------------------------------------------------

    def clear_metric(self, name: str) -> None:
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                m.series.clear()

    def reset_for_testing(self, prefix: Optional[str] = None) -> None:
        """Drop every series (optionally only names under ``prefix``) —
        the snapshot boundary repeated tests need (conftest wires this
        per-test for the health metrics)."""
        with self._lock:
            for name, m in self._metrics.items():
                if prefix is None or name.startswith(prefix):
                    m.series.clear()


#: the process-global registry every layer records into
REGISTRY = MetricsRegistry()


def hist_quantile(hist: Mapping[str, Any], q: float) -> Optional[float]:
    """Bucket-interpolated quantile from a histogram dict
    (:meth:`_Hist.as_dict` shape — also what :meth:`Snapshot.series`
    yields for histograms). Linear interpolation inside the bucket the
    quantile falls in, the same honesty trade as ``obs.slo``'s
    attainment; observations in the +Inf bucket clamp to the last finite
    edge (a quantile cannot invent an upper bound the histogram never
    recorded). None when empty or ``q`` is out of (0, 1]."""
    count = int(hist.get("count", 0))
    if count <= 0 or not 0.0 < q <= 1.0:
        return None
    buckets = list(hist.get("buckets", ()))
    counts = list(hist.get("counts", ()))
    rank = q * count
    cum = 0.0
    lo = 0.0
    for edge, c in zip(buckets, counts):
        if cum + c >= rank:
            frac = (rank - cum) / c if c > 0 else 0.0
            return lo + (edge - lo) * frac
        cum += c
        lo = edge
    return buckets[-1] if buckets else None


# -- Prometheus text exposition ------------------------------------------------


def _prom_labels(key: LabelKey) -> str:
    if not key:
        return ""
    esc = lambda s: s.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")  # noqa: E731
    return "{" + ",".join(f'{k}="{esc(v)}"' for k, v in key) + "}"


def to_prometheus(snapshot: Optional[Snapshot] = None) -> str:
    """Render a snapshot (default: a fresh one of :data:`REGISTRY`) in the
    Prometheus text exposition format (version 0.0.4)."""
    snap = snapshot or REGISTRY.snapshot()
    lines: List[str] = []
    for name in sorted(snap.data):
        m = snap.data[name]
        if m["help"]:
            lines.append(f"# HELP {name} {m['help']}")
        lines.append(f"# TYPE {name} {m['kind']}")
        for key in sorted(m["series"]):
            v = m["series"][key]
            if isinstance(v, dict):  # histogram
                cum = 0
                edges = list(v["buckets"]) + ["+Inf"]
                for edge, c in zip(edges, v["counts"]):
                    cum += c
                    lk = key + (("le", str(edge)),)
                    lines.append(f"{name}_bucket{_prom_labels(lk)} {cum}")
                lines.append(f"{name}_sum{_prom_labels(key)} {v['sum']}")
                lines.append(f"{name}_count{_prom_labels(key)} {v['count']}")
            else:
                out = int(v) if float(v).is_integer() else v
                lines.append(f"{name}{_prom_labels(key)} {out}")
    return "\n".join(lines) + "\n"


# -- HTTP exporter -------------------------------------------------------------


def serve_metrics_http(port: int, host: str = "127.0.0.1"):
    """Start a daemon-thread HTTP server exposing ``/metrics`` (Prometheus
    text) and ``/metrics.json`` (the structured snapshot).

    Port 0 binds an EPHEMERAL port — the supported spelling for tests and
    multi-instance runs, which were colliding on fixed ports; read the
    actually-bound port back from ``server.port`` (it also surfaces in
    the serve stats ``obs.metrics_port``). Returns the server; callers
    ``.close()`` it on exit — :meth:`close` is the graceful shutdown:
    ``shutdown()`` stops the serve loop AND ``server_close()`` releases
    the listening socket, so the port is immediately rebindable (bare
    ``shutdown()``, the old contract, leaked the socket until GC)."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler API
            if self.path.split("?")[0] == "/metrics":
                body = to_prometheus().encode()
                ctype = "text/plain; version=0.0.4; charset=utf-8"
            elif self.path.split("?")[0] == "/metrics.json":
                body = REGISTRY.snapshot().to_json().encode()
                ctype = "application/json"
            else:
                self.send_error(404)
                return
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *args):  # noqa: D102 — silence per-scrape spam
            pass

    class MetricsServer(ThreadingHTTPServer):
        #: in-flight scrape threads must not block process exit
        daemon_threads = True

        @property
        def port(self) -> int:
            """The BOUND port (== the requested one unless it was 0)."""
            return self.server_address[1]

        def close(self) -> None:
            self.shutdown()
            self.server_close()

        def __enter__(self) -> "MetricsServer":
            return self

        def __exit__(self, *exc) -> None:
            self.close()

    server = MetricsServer((host, port), Handler)
    threading.Thread(
        target=server.serve_forever, name="obs-metrics-http", daemon=True
    ).start()
    return server


# -- solver fold helper --------------------------------------------------------


def fold_bnb_solve(nodes: int, wall_s: float, spill_stats) -> None:
    """Fold one finished B&B solve's totals into the registry (called once
    per solve from ``models.branch_bound`` — never from inside the loop,
    and never from jit-traced code; graftlint R8 enforces the latter)."""
    REGISTRY.inc("bnb_nodes_expanded_total", int(nodes))
    REGISTRY.inc("bnb_solve_seconds_total", float(max(wall_s, 0.0)))
    REGISTRY.inc("bnb_solves_total")
    REGISTRY.inc("bnb_spill_rounds_total", spill_stats.rounds)
    REGISTRY.inc("bnb_spill_events_total", spill_stats.events)
    REGISTRY.inc("bnb_spill_full_merges_total", spill_stats.full_merges)
    REGISTRY.inc(
        "bnb_spill_bytes_total", spill_stats.bytes_to_host, direction="to_host"
    )
    REGISTRY.inc(
        "bnb_spill_bytes_total",
        spill_stats.bytes_to_device,
        direction="to_device",
    )

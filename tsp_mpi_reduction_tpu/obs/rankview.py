"""Rank-resolved telemetry for the sharded search (ISSUE 10 tentpole).

The PR 6/9 telemetry stack sees the solver as one process: the
``StepSampler`` samples global aggregates, ``SpillStats`` folds bytes
across ranks, and the stall sentinel watches one pooled series. The open
mesh refactor (ROADMAP: 2D ``(search_ranks, request_batch)``) and the
Orca-style continuous-batching item both need to know *which rank* is
starved, spilling, or straggling before committing to a partitioning —
Orca feeds iteration-level scheduling with per-worker occupancy signals,
Clipper operates layered systems through per-replica metrics. This
module makes every sharded run a load-balance report:

- :class:`RankSampler` — a ring of per-window ``[R]`` vectors: frontier
  occupancy, alive (incumbent-open) rows, nodes expanded, host reservoir
  depth, spill events/bytes each way, and each rank's best open bound.
  The device-side columns arrive as ONE small ``[R, K]`` f32 row from
  ``parallel.reduce.make_rank_stats`` — the same single-readback
  pattern as ``make_rank_alive_min``; everything else is host-side
  arithmetic the sharded loop already owns. The gather runs once per
  ``window`` host-loop dispatches (default 8, ``TSP_RANK_WINDOW``), so
  the per-dispatch cost is one counter compare — the ``TSP_BENCH=shard``
  bench meters the whole hook and gates it <= 2%.
- :class:`~.anomaly.RankStarvationSentinel` (obs.anomaly) — attached as
  ``.watch``; each completed window feeds it, and a rank whose share of
  the window's expansion work collapses fires ``rank_starvation`` once
  per episode.
- :func:`rank_balance` — the imbalance accounting block stamped into
  the driver payload as ``obs.rank_balance``: per-rank totals plus
  occupancy coefficient-of-variation, straggler rank/score, starved
  ranks and episode counts.
- :func:`fold_rank_view` — end-of-solve registry export as
  rank-labeled gauges/counters. Rank labels are drawn from
  ``range(num_ranks)`` — a BOUNDED set (graftlint R13 recognizes
  range-loop labels as bounded cardinality).

``tools/obs_report.py --ranks`` renders the series as a per-rank
occupancy heatmap + totals table.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Sequence

from . import anomaly as _anomaly
from . import enabled as _obs_enabled
from .metrics import REGISTRY

#: row layout of :meth:`RankSampler.series` rows — ``step`` is a scalar,
#: every other column is a per-rank [R] list
RANK_COLUMNS = (
    "step",             # cumulative expansion-step counter at sample time
    "occupancy",        # live frontier rows per rank (post spill/refill)
    "alive",            # rows the incumbent has not closed, per rank
    "nodes",            # nodes expanded by THIS window, per rank
    "reservoir",        # host reservoir depth per rank
    "spill_events",     # spill/refill exchange events this window, per rank
    "spill_to_host",    # bytes spilled host-ward this window, per rank
    "spill_to_device",  # bytes refilled device-ward this window, per rank
    "best_bound",       # per-rank best open bound (null when drained)
)

#: env knob for the sampling window (host-loop dispatches per sample)
ENV_WINDOW = "TSP_RANK_WINDOW"
_DEFAULT_WINDOW = 8


class RankSampler:
    """Ring-buffered per-rank sampler for the sharded host loop.

    Hot-path contract mirrors ``StepSampler``: the solver guards every
    call on the handle (``maybe()`` returns None under ``TSP_OBS=off``),
    calls :meth:`due` once per dispatch (one increment + compare), and
    only on a True verdict pays for the ``[R, K]`` device gather +
    :meth:`sample`. Cumulative inputs (nodes, spill counters) are
    differenced against the previous sample internally, so the solver
    hands over the arrays it already maintains.
    """

    __slots__ = (
        "num_ranks", "capacity", "window", "_rows", "_total", "_since",
        "_prev", "watch",
    )

    #: native self-meter accumulator handle (class-level, None = off):
    #: the ``TSP_BENCH=shard`` bench sets this to a one-element ``[ns]``
    #: list and the SOLVER bills the whole rank hook into it — the
    #: due() compare, the [R, K] stats-row gather/readback, and the
    #: sample() body — at its own call site (the expensive part, the
    #: collective dispatch, lives outside this class, so in-method
    #: self-timing would systematically under-count)
    METER_NS: Optional[List[int]] = None

    def __init__(
        self, num_ranks: int, capacity: int = 256, window: int = _DEFAULT_WINDOW
    ) -> None:
        if num_ranks < 1:
            raise ValueError(f"num_ranks must be >= 1, got {num_ranks}")
        if capacity < 1:
            raise ValueError(f"sampler capacity must be >= 1, got {capacity}")
        if window < 1:
            raise ValueError(f"sampling window must be >= 1, got {window}")
        self.num_ranks = num_ranks
        self.capacity = capacity
        self.window = window
        self._rows: List[tuple] = []
        self._total = 0
        self._since = 0  # dispatches since the last recorded sample
        #: previous CUMULATIVE (nodes, spill_events, to_host, to_device)
        zeros = (0,) * num_ranks
        self._prev = [zeros, zeros, zeros, zeros]
        #: attached starvation sentinel (``maybe()`` wires one); fed once
        #: per completed window from :meth:`sample`
        self.watch: Optional[_anomaly.RankStarvationSentinel] = None

    @classmethod
    def maybe(
        cls,
        num_ranks: int,
        capacity: int = 256,
        window: Optional[int] = None,
    ) -> Optional["RankSampler"]:
        """A sampler (with its starvation watch) when obs is enabled,
        else None — ``TSP_OBS=off`` costs one is-None check per dispatch,
        the same contract as ``StepSampler.maybe``."""
        if not _obs_enabled():
            return None
        if window is None:
            try:
                window = int(os.environ.get(ENV_WINDOW, "") or _DEFAULT_WINDOW)
            except ValueError:
                window = _DEFAULT_WINDOW
        s = cls(num_ranks, capacity, max(window, 1))
        s.watch = _anomaly.RankStarvationSentinel(num_ranks)
        return s

    # -- cadence -------------------------------------------------------------

    def due(self) -> bool:
        """Advance the per-dispatch tick; True when this dispatch should
        pay for a sample (the first dispatch, then every ``window``-th).
        The caller performs the device gather and calls :meth:`sample`
        only on True — this split keeps the collective out of the
        per-dispatch path."""
        self._since += 1
        return self._total == 0 or self._since >= self.window

    def pending(self) -> bool:
        """Dispatches have passed since the last sample — the solver
        flushes one final sample at loop exit so the series' tail (and
        the window deltas) cover the whole run."""
        return self._since > 0

    # -- recording -----------------------------------------------------------

    def sample(
        self,
        step: int,
        occupancy: Sequence,
        alive: Sequence,
        nodes_total: Sequence,
        reservoir: Sequence,
        spill_events_total: Sequence,
        spill_to_host_total: Sequence,
        spill_to_device_total: Sequence,
        best_bound: Sequence,
    ) -> None:
        """Record one window. ``occupancy``/``alive``/``best_bound`` are
        current snapshots (the ``[R, K]`` device row + host reservoir
        state); ``*_total`` are CUMULATIVE per-rank counters — the delta
        against the previous sample is what lands in the row, so each
        row reads "what happened in this window"."""
        occ = tuple(int(v) for v in occupancy)
        alv = tuple(int(v) for v in alive)
        res = tuple(int(v) for v in reservoir)
        bb = tuple(float(v) for v in best_bound)
        cum = [
            tuple(int(v) for v in nodes_total),
            tuple(int(v) for v in spill_events_total),
            tuple(int(v) for v in spill_to_host_total),
            tuple(int(v) for v in spill_to_device_total),
        ]
        prev = self._prev
        deltas = [
            tuple(c - p for c, p in zip(cur, prv))
            for cur, prv in zip(cum, prev)
        ]
        self._prev = cum
        row = (int(step), occ, alv, deltas[0], res,
               deltas[1], deltas[2], deltas[3], bb)
        rows = self._rows
        if len(rows) < self.capacity:
            rows.append(row)
        else:
            rows[self._total % self.capacity] = row
        self._total += 1
        self._since = 0
        w = self.watch
        if w is not None:
            w.observe_window(step, occ, deltas[0])

    def __len__(self) -> int:
        return len(self._rows)

    def series(self) -> Dict[str, Any]:
        """JSON-ready artifact: rows oldest-first plus ring/window
        accounting — the driver payload's ``rank_series`` block."""
        if self._total <= self.capacity:
            raw = list(self._rows)
        else:
            pivot = self._total % self.capacity
            raw = self._rows[pivot:] + self._rows[:pivot]

        rows = [
            [
                r[0], list(r[1]), list(r[2]), list(r[3]), list(r[4]),
                list(r[5]), list(r[6]), list(r[7]),
                # +inf = drained rank: null is the strict-JSON encoding
                [round(b, 6) if -1e308 < b < 1e308 else None for b in r[8]],
            ]
            for r in raw
        ]
        return {
            "columns": list(RANK_COLUMNS),
            "ranks": self.num_ranks,
            "window": self.window,
            "rows": rows,
            "samples_total": self._total,
            "samples_dropped": max(self._total - self.capacity, 0),
        }


# -- imbalance accounting ------------------------------------------------------


def _cv(values: Sequence[float]) -> float:
    """Population coefficient of variation (std/mean); 0 for an empty or
    all-zero vector — a drained mesh is balanced, not undefined."""
    vals = [float(v) for v in values]
    if not vals:
        return 0.0
    mean = sum(vals) / len(vals)
    if mean == 0:
        return 0.0
    var = sum((v - mean) ** 2 for v in vals) / len(vals)
    return (var ** 0.5) / abs(mean)


def rank_balance(
    series: Optional[Dict[str, Any]],
    nodes_per_rank: Sequence,
    *,
    spill_events: Optional[Sequence] = None,
    spill_bytes_to_host: Optional[Sequence] = None,
    spill_bytes_to_device: Optional[Sequence] = None,
    reservoir: Optional[Sequence] = None,
    events: Optional[List[Dict[str, Any]]] = None,
) -> Dict[str, Any]:
    """The imbalance accounting block (``obs.rank_balance``).

    ``nodes_per_rank`` and the spill vectors are the solver's
    authoritative whole-run totals (the series' window deltas only cover
    what the ring still holds); occupancy statistics come from the
    series rows. The *straggler* is the rank carrying the most work: in
    the SPMD engine every dispatch runs lockstep, so the overloaded rank
    is the one everyone else idles behind — ``straggler_score`` is its
    node count over the mesh mean (1.0 = perfectly balanced). Starved
    ranks are read from the ``rank_starvation`` events.
    """
    nodes = [int(v) for v in nodes_per_rank]
    ranks = len(nodes)
    total = sum(nodes)
    mean = total / ranks if ranks else 0.0
    occ_mean: List[float] = [0.0] * ranks
    if series and series.get("rows"):
        idx = series["columns"].index("occupancy")
        cols = [r[idx] for r in series["rows"]]
        occ_mean = [
            round(sum(c[r] for c in cols) / len(cols), 2)
            for r in range(ranks)
        ]
    starve_events = [
        e for e in (events or []) if e.get("kind") == "rank_starvation"
    ]
    starved = sorted({int(e["rank"]) for e in starve_events})
    straggler = max(range(ranks), key=lambda r: nodes[r]) if ranks else 0
    out: Dict[str, Any] = {
        "ranks": ranks,
        "nodes_per_rank": nodes,
        "nodes_total": total,
        "nodes_cv": round(_cv(nodes), 4),
        "nodes_max_min_ratio": (
            round(max(nodes) / max(min(nodes), 1), 2) if nodes else 0.0
        ),
        "occupancy_mean": occ_mean,
        "occupancy_cv": round(_cv(occ_mean), 4),
        "straggler_rank": int(straggler),
        "straggler_score": round(nodes[straggler] / mean, 3) if mean else 0.0,
        "starved_ranks": starved,
        "starvation_episodes": len(starve_events),
    }
    if spill_events is not None:
        out["spill_events_per_rank"] = [int(v) for v in spill_events]
    if spill_bytes_to_host is not None:
        out["spill_bytes_to_host_per_rank"] = [
            int(v) for v in spill_bytes_to_host
        ]
    if spill_bytes_to_device is not None:
        out["spill_bytes_to_device_per_rank"] = [
            int(v) for v in spill_bytes_to_device
        ]
    if reservoir is not None:
        out["reservoir_per_rank"] = [int(v) for v in reservoir]
    return out


def fold_rank_view(balance: Dict[str, Any]) -> None:
    """Fold one finished sharded solve's rank view into the registry —
    called once per solve from ``models.branch_bound``, never per
    dispatch, never inside jit (R8). Rank labels come from
    ``range(num_ranks)``: bounded cardinality by construction (the set
    can never outgrow the mesh), which graftlint R13 recognizes."""
    # hoisted name arg: R13's range exemption covers configuration-shaped
    # range arguments (names/constants/attributes), not call expressions
    num_ranks = int(balance["ranks"])
    for r in range(num_ranks):
        REGISTRY.inc(
            "bnb_rank_nodes_total", balance["nodes_per_rank"][r], rank=r
        )
        REGISTRY.set_gauge(
            "bnb_rank_occupancy_mean", balance["occupancy_mean"][r], rank=r
        )
        if "spill_bytes_to_host_per_rank" in balance:
            REGISTRY.inc(
                "bnb_rank_spill_bytes_total",
                balance["spill_bytes_to_host_per_rank"][r],
                rank=r, direction="to_host",
            )
        if "spill_bytes_to_device_per_rank" in balance:
            REGISTRY.inc(
                "bnb_rank_spill_bytes_total",
                balance["spill_bytes_to_device_per_rank"][r],
                rank=r, direction="to_device",
            )
        if "spill_events_per_rank" in balance:
            REGISTRY.inc(
                "bnb_rank_spill_events_total",
                balance["spill_events_per_rank"][r], rank=r,
            )
    REGISTRY.set_gauge("bnb_rank_occupancy_cv", balance["occupancy_cv"])
    REGISTRY.set_gauge("bnb_rank_nodes_cv", balance["nodes_cv"])
    REGISTRY.set_gauge("bnb_rank_straggler_score", balance["straggler_score"])

"""Ring-buffered per-dispatch sampler for the B&B host loops.

One row per host-loop iteration (i.e. per device dispatch — NEVER per
in-kernel step, which would mean a readback per step, the exact transfer
pattern the device-resident engine exists to avoid): expansion progress,
nodes/sec, frontier occupancy, spill bytes each way, and the incumbent /
certified-floor trajectory. The ring keeps the newest ``capacity`` rows
(``samples_dropped`` records how many older ones rolled off), so a
multi-hour proof campaign costs bounded memory.

The series flushes into ``BnBResult.series`` → ``bnb_solve.py`` /
``bnb_chunked.py`` JSON, and ``tools/obs_report.py`` renders it.

This series is mesh-GLOBAL (one row per dispatch, aggregates folded
across ranks). Its rank-resolved sibling — per-rank occupancy, nodes,
spill and best-bound vectors, one row per sampling window — lives in
``obs.rankview.RankSampler`` and flushes as ``BnBResult.rank_series``
(ISSUE 10); the two share the driver payload and the report tool
(``--series`` / ``--ranks``).
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

from . import enabled as _obs_enabled

#: row layout, in order — kept in the JSON artifact so readers can zip
COLUMNS = (
    "step",            # cumulative expansion-step counter (solver `it`)
    "wall_s",          # seconds since the search loop started
    "nodes",           # nodes expanded by THIS dispatch
    "nodes_per_s",     # this dispatch's expansion rate
    "frontier",        # live frontier rows after the dispatch (+ spill)
    "spill_to_host",   # bytes spilled host-ward by this iteration
    "spill_to_device", # bytes refilled device-ward by this iteration
    "incumbent",       # best tour cost so far
    "lb_floor",        # certified lower-bound floor (root/resume clamp)
    "reservoir",       # rows parked in the host spill reservoir — with
                       # `frontier` this is the TOTAL open work, the
                       # signal that separates a draining proof phase
                       # from a wedged search (obs.anomaly)
)


class StepSampler:
    #: slotted: the per-dispatch hot path touches five attributes; slot
    #: access keeps its in-situ footprint (the cost that matters — the
    #: hook runs cold-cache between jax dispatches) at the floor
    __slots__ = (
        "capacity", "_rows", "_total", "row_bytes", "frontier_layout",
        "sentinel",
    )

    #: native self-meter (class-level, None = off): when TSP_BENCH=obs
    #: prices the telemetry it sets this to a one-element ``[ns]`` list
    #: and ``sample`` accumulates its own inclusive time into it. A
    #: wrapper-based meter is NOT equivalent here: the wrapping frame +
    #: argument re-packing costs ~1.5 us per call in situ — most of the
    #: budget it is supposed to measure — while this is one is-None
    #: check when off and two ``perf_counter_ns`` calls when on.
    METER_NS: Optional[List[int]] = None

    def __init__(self, capacity: int = 512):
        if capacity < 1:
            raise ValueError(f"sampler capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self._rows: List[List[Any]] = []
        self._total = 0
        #: bytes per packed frontier node row, set once by the solver —
        #: the spill_to_host/spill_to_device columns count ACTUAL packed
        #: bytes (they shrank ~3x with the v2 int8-packed layout), so the
        #: series records the divisor that converts them to node counts
        self.row_bytes: Optional[int] = None
        #: engine row-layout version the bytes were measured under
        self.frontier_layout: Optional[int] = None
        #: optional attached ``obs.anomaly.StallSentinel``: when set,
        #: sample() hands it the ring to batch-consume once per full
        #: window (``StallSentinel.consume``) — per dispatch the
        #: sentinel costs one compare, not a second Python call
        #: (measured on the TSP_BENCH=obs <= 2% budget)
        self.sentinel: Optional[Any] = None

    @classmethod
    def maybe(cls, capacity: int = 512) -> Optional["StepSampler"]:
        """A sampler when obs is enabled, else None (the solver guards
        each sample call on the returned handle, so ``TSP_OBS=off`` pays
        one `is None` check per dispatch)."""
        return cls(capacity) if _obs_enabled() else None

    def sample(
        self,
        step: int,
        wall_s: float,
        nodes: int,
        nodes_per_s: float,
        frontier: int,
        spill_to_host: int = 0,
        spill_to_device: int = 0,
        incumbent: float = float("inf"),
        lb_floor: float = float("-inf"),
        reservoir: int = 0,
    ) -> None:
        # hot path (once per host-loop iteration): store raw values only;
        # all rounding/JSON-sanitizing happens once, in series(). The
        # solver calls this POSITIONALLY — a 9-kwarg call costs ~1 us
        # more than positional in situ (dict build + unpack, cold-cache),
        # which is real money against the TSP_BENCH=obs <= 2% budget.
        m = StepSampler.METER_NS
        if m is not None:
            t_meter = time.perf_counter_ns()
        rows = self._rows
        row = (
            step, wall_s, nodes, nodes_per_s, frontier,
            spill_to_host, spill_to_device, incumbent, lb_floor, reservoir,
        )
        if len(rows) < self.capacity:
            rows.append(row)
        else:
            rows[self._total % self.capacity] = row
        total = self._total + 1
        self._total = total
        # sentinel rides the ring this sampler already keeps: one compare
        # per dispatch here, one batch consume per full window there — a
        # second per-dispatch Python call was ~half the telemetry budget
        sn = self.sentinel
        if sn is not None and total - sn.consumed >= sn.window:
            sn.consume(self)
        if m is not None:
            m[0] += time.perf_counter_ns() - t_meter

    def __len__(self) -> int:
        return len(self._rows)

    def series(self) -> Dict[str, Any]:
        """JSON-ready artifact: rows oldest-first plus ring accounting."""
        if self._total <= self.capacity:
            raw = list(self._rows)
        else:
            pivot = self._total % self.capacity
            raw = self._rows[pivot:] + self._rows[:pivot]

        def _finite(x: float) -> Optional[float]:
            # ±inf (no incumbent yet / no certified floor) would emit
            # non-strict JSON (`Infinity`); null is the honest encoding
            x = float(x)
            return x if -1e308 < x < 1e308 else None

        rows = [
            [
                int(r[0]), round(float(r[1]), 6), int(r[2]),
                round(float(r[3]), 3), int(r[4]), int(r[5]), int(r[6]),
                _finite(r[7]), _finite(r[8]), int(r[9]),
            ]
            for r in raw
        ]
        return {
            "columns": list(COLUMNS),
            "rows": rows,
            "samples_total": self._total,
            "samples_dropped": max(self._total - self.capacity, 0),
            # packed-row provenance: spill byte columns / row_bytes =
            # rows moved; None when the producer never set it
            "row_bytes": self.row_bytes,
            "frontier_layout": self.frontier_layout,
        }

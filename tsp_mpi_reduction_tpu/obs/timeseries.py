"""Ring-buffered per-dispatch sampler for the B&B host loops.

One row per host-loop iteration (i.e. per device dispatch — NEVER per
in-kernel step, which would mean a readback per step, the exact transfer
pattern the device-resident engine exists to avoid): expansion progress,
nodes/sec, frontier occupancy, spill bytes each way, and the incumbent /
certified-floor trajectory. The ring keeps the newest ``capacity`` rows
(``samples_dropped`` records how many older ones rolled off), so a
multi-hour proof campaign costs bounded memory.

The series flushes into ``BnBResult.series`` → ``bnb_solve.py`` /
``bnb_chunked.py`` JSON, and ``tools/obs_report.py`` renders it.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from . import enabled as _obs_enabled

#: row layout, in order — kept in the JSON artifact so readers can zip
COLUMNS = (
    "step",            # cumulative expansion-step counter (solver `it`)
    "wall_s",          # seconds since the search loop started
    "nodes",           # nodes expanded by THIS dispatch
    "nodes_per_s",     # this dispatch's expansion rate
    "frontier",        # live frontier rows after the dispatch (+ spill)
    "spill_to_host",   # bytes spilled host-ward by this iteration
    "spill_to_device", # bytes refilled device-ward by this iteration
    "incumbent",       # best tour cost so far
    "lb_floor",        # certified lower-bound floor (root/resume clamp)
)


class StepSampler:
    def __init__(self, capacity: int = 512):
        if capacity < 1:
            raise ValueError(f"sampler capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self._rows: List[List[Any]] = []
        self._total = 0
        #: bytes per packed frontier node row, set once by the solver —
        #: the spill_to_host/spill_to_device columns count ACTUAL packed
        #: bytes (they shrank ~3x with the v2 int8-packed layout), so the
        #: series records the divisor that converts them to node counts
        self.row_bytes: Optional[int] = None
        #: engine row-layout version the bytes were measured under
        self.frontier_layout: Optional[int] = None

    @classmethod
    def maybe(cls, capacity: int = 512) -> Optional["StepSampler"]:
        """A sampler when obs is enabled, else None (the solver guards
        each sample call on the returned handle, so ``TSP_OBS=off`` pays
        one `is None` check per dispatch)."""
        return cls(capacity) if _obs_enabled() else None

    def sample(
        self,
        *,
        step: int,
        wall_s: float,
        nodes: int,
        nodes_per_s: float,
        frontier: int,
        spill_to_host: int = 0,
        spill_to_device: int = 0,
        incumbent: float = float("inf"),
        lb_floor: float = float("-inf"),
    ) -> None:
        # hot path (once per host-loop iteration): store raw values only;
        # all rounding/JSON-sanitizing happens once, in series()
        row = (
            step, wall_s, nodes, nodes_per_s, frontier,
            spill_to_host, spill_to_device, incumbent, lb_floor,
        )
        if len(self._rows) < self.capacity:
            self._rows.append(row)
        else:
            self._rows[self._total % self.capacity] = row
        self._total += 1

    def __len__(self) -> int:
        return len(self._rows)

    def series(self) -> Dict[str, Any]:
        """JSON-ready artifact: rows oldest-first plus ring accounting."""
        if self._total <= self.capacity:
            raw = list(self._rows)
        else:
            pivot = self._total % self.capacity
            raw = self._rows[pivot:] + self._rows[:pivot]

        def _finite(x: float) -> Optional[float]:
            # ±inf (no incumbent yet / no certified floor) would emit
            # non-strict JSON (`Infinity`); null is the honest encoding
            x = float(x)
            return x if -1e308 < x < 1e308 else None

        rows = [
            [
                int(r[0]), round(float(r[1]), 6), int(r[2]),
                round(float(r[3]), 3), int(r[4]), int(r[5]), int(r[6]),
                _finite(r[7]), _finite(r[8]),
            ]
            for r in raw
        ]
        return {
            "columns": list(COLUMNS),
            "rows": rows,
            "samples_total": self._total,
            "samples_dropped": max(self._total - self.capacity, 0),
            # packed-row provenance: spill byte columns / row_bytes =
            # rows moved; None when the producer never set it
            "row_bytes": self.row_bytes,
            "frontier_layout": self.frontier_layout,
        }

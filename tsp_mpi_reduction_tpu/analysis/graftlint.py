"""graftlint: AST lint pass for the JAX hazard classes this repo actually hits.

The engine's real failure modes are JAX-specific, not generic Python bugs —
ADVICE.md round 5 recorded three live defects (a ``take==0`` node-discard
exactness bug, an every-spill full-reservoir merge, and a full-physical-buffer
host round-trip on every spill) that neither pyflakes-style linting nor the
test suite caught. Each belongs to a hazard class that is mechanically
detectable from the AST:

  R1  host-pull-in-hot-loop: ``np.asarray`` / ``np.array`` / ``jax.device_get``
      / ``.copy()`` applied to a device buffer inside a loop body or a known
      hot-path function — every occurrence is a device->host transfer on the
      search's critical path (the exact shape of ADVICE round-5 item 3).
  R2  round-trip-reupload: ``jnp.asarray(x)`` / ``jax.device_put(x)`` where
      ``x`` was pulled from the device earlier in the same function — the
      down-modify-up pattern; a sliced ``buf.at[:k].set(...)`` uploads only
      the mutated prefix instead of the whole physical buffer.
  R3  branch-on-jitted-output: Python ``if``/``while`` on a value returned by
      a jitted callee without an explicit ``float()``/``int()``/``bool()``
      scalar conversion — a silent sync today, a tracer leak the moment the
      enclosing code is itself traced.
  R4  jnp-in-python-loop: ``jnp``/``lax`` calls inside a Python ``for`` body —
      the loop unrolls at trace time (compile-time blowup) or relaunches
      kernels per iteration; ``lax.scan``/``fori_loop``/``vmap`` keep it one
      kernel.
  R5  early-return-drops-state: a function overwrites ``self.<attr>`` state,
      computes locals from it, then has an early ``return None`` path that
      writes nothing back — the ``_partition`` ``take==0`` bug class, where
      ``self.chunks`` was cleared and the merged alive rows silently dropped.
  R6  non-atomic-durable-write: ``open(path, "w")`` / ``np.savez*`` straight
      to a final path in a scope with no ``os.replace`` — a crash mid-write
      leaves a truncated artifact a later reader chokes on (the pre-resilience
      checkpoint bug: a killed ``np.savez_compressed`` destroyed the
      campaign's only snapshot). Writes to in-memory buffers (``io.BytesIO``)
      and temp-named paths are exempt, as is any scope that ``os.replace``-
      publishes (the temp-file-then-rename pattern); use
      ``resilience.checkpoint.write_json_atomic``/``write_atomic``.
  R7  jit-frontier-no-donation: a ``jax.jit`` entry point whose parameters
      include a ``Frontier``/reservoir-sized buffer (a param annotated
      ``Frontier`` or named ``fr``/``fr_stacked``/``frontier``/``nodes``)
      without ``donate_argnums``/``donate_argnames`` — every dispatch then
      COPIES the multi-hundred-MB buffer instead of aliasing it in place
      (the compile-once PR's donation invariant). Detected forms: jit
      decorators (bare, called, or ``partial(jax.jit, ...)``) and module
      assignments ``x = jax.jit(f, ...)`` / ``partial(jax.jit, ...)(f)``
      where ``f`` is a lambda or a function defined in the same file.
      A harness that legitimately re-dispatches the SAME buffer opts out
      with an inline disable (see ``_expand_loop_ref``).

Escape hatches (both are honored, in this order):

- ``# graftlint: disable=R1,R4`` on the flagged line, the line above, or the
  ``def`` line of the enclosing function (which disables for the whole body);
  bare ``# graftlint: disable`` silences every rule.
- a checked-in baseline (``graftlint_baseline.json`` next to this module):
  accepted pre-existing sites, keyed by (path, rule, scope, code-text) so
  line-number churn never invalidates it. ``--write-baseline`` regenerates.

``# graftlint: hot`` on a ``def`` line marks that function as a hot path, so
R1 applies to its whole body (not only lexical loop bodies); the functions in
``DEFAULT_HOT_PATHS`` — the reservoir spill/refill machinery this repo knows
is on the per-spill critical path — are treated as hot by default.

The pass is stdlib-only (``ast`` + ``tokenize``): it must run in CI and the
sweep harness before any JAX import, and must never drag device state into a
lint step.
"""

from __future__ import annotations

import ast
import io
import json
import pathlib
import re
import tokenize
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

RULES = {
    "R1": "device->host pull inside a hot loop",
    "R2": "whole-buffer re-upload of a host round-trip",
    "R3": "Python control flow on a jitted callee's output",
    "R4": "jnp call inside a Python for loop",
    "R5": "early return None drops mutated self state",
    "R6": "non-atomic write of a durable artifact",
    "R7": "jit frontier entry without buffer donation",
    "R8": "metric/trace recording inside jit-traced code",
    "R13": "unbounded metric-label cardinality at a registry call site",
}

#: functions whose WHOLE body R1 treats as a hot loop: the reservoir
#: spill/refill machinery runs once per spill event inside the solve loop,
#: so a host pull here is a per-spill transfer even without a lexical loop.
DEFAULT_HOT_PATHS = frozenset(
    {
        "exchange",
        "refill",
        "_keep_live_only",
        "spill_refill",
        "_fetch_live_rows",  # the ONE accepted live-prefix fetch site
        "_apply_keeps",
        "_rank_counts",
        "_expand_loop",
    }
)

#: attribute names that name device-resident buffers in this codebase
#: (Frontier / PaddedTour fields) — ``np.asarray(fr.nodes)`` is a device
#: pull even though ``fr`` itself is just a parameter name to the AST.
DEVICE_ATTRS = frozenset({"nodes", "count", "overflow", "ids", "length", "cost"})

#: modules whose calls produce device arrays
_DEVICE_MODULES = ("jnp", "jax")
#: host-pull callables (R1) — dotted names
_HOST_PULL_CALLS = frozenset(
    {"np.asarray", "np.array", "numpy.asarray", "numpy.array", "jax.device_get"}
)
#: device-upload callables (R2)
_UPLOAD_CALLS = frozenset(
    {"jnp.asarray", "jnp.array", "jax.device_put", "jax.numpy.asarray"}
)
#: scalar conversions that launder a jitted output for host control flow (R3)
_SCALAR_CONVERSIONS = frozenset({"float", "int", "bool", "len"})
_SCALAR_CONVERSION_ATTRS = frozenset(
    {"np.asarray", "np.array", "numpy.asarray", "numpy.array", "np.float64"}
)
#: call roots that count as "jnp work" inside a for loop (R4)
_JNP_ROOTS = frozenset({"jnp", "lax"})
#: numpy artifact writers that publish durable bytes to a path (R6)
_DURABLE_NP_WRITES = frozenset(
    {
        "np.save",
        "np.savez",
        "np.savez_compressed",
        "numpy.save",
        "numpy.savez",
        "numpy.savez_compressed",
    }
)
#: in-memory buffer factories whose "writes" are not durable (R6 exempt)
_BUFFER_FACTORIES = frozenset(
    {"io.BytesIO", "BytesIO", "io.StringIO", "StringIO"}
)
#: calls that make the enclosing scope an atomic-publish pattern (R6)
_ATOMIC_PUBLISH_CALLS = frozenset({"os.replace", "os.rename"})
#: parameter names that denote a frontier/reservoir-sized device buffer
#: (R7); a parameter ANNOTATED ``Frontier`` counts regardless of name
_FRONTIER_PARAMS = frozenset({"fr", "fr_stacked", "frontier", "nodes"})
#: jit spellings R7 recognizes as entry-point wrappers
_JIT_NAMES = ("jax.jit", "jit", "pjit", "jax.pjit")
#: the kwargs that satisfy R7 (either donation spelling)
_DONATE_KWARGS = ("donate_argnums", "donate_argnames")
#: obs recorder receivers (R8): the dotted ROOT names the telemetry
#: layer's globals/modules are bound to across the codebase, however
#: aliased at import
_OBS_RECORDER_ROOTS = frozenset(
    {
        "REGISTRY", "_REGISTRY", "HEALTH", "TRACER", "STATS",
        "metrics", "tracing", "timeseries", "obs",
        "_obs_metrics", "_obs_tracing", "_obs_series",
        "_metrics", "_tracing",
    }
)
#: recorder method names (R8) — only flagged on the roots above, so a
#: jit body's `fr.nodes.at[i].set(...)` or an estimator's `.observe`
#: never false-positives
_OBS_RECORDER_VERBS = frozenset(
    {
        "inc", "set_gauge", "observe", "incr", "incr_fault",
        "add_event", "record", "sample", "span", "emit_span", "event",
        "fold_bnb_solve", "step_annotation",
    }
)
#: bare-name recorder calls (``from obs.tracing import span``)
_OBS_BARE_CALLS = frozenset({"span", "add_event", "emit_span"})
#: registry receivers R13 governs — label kwargs at these call sites must
#: have BOUNDED value sets; the registry keeps one series per distinct
#: label tuple forever, so an f-string / loop-variable / per-request
#: label is an unbounded-memory + scrape-size leak
_R13_REGISTRY_ROOTS = frozenset({"REGISTRY", "_REGISTRY"})
#: recording verbs whose keyword args are label values
_R13_RECORD_VERBS = frozenset({"inc", "set_gauge", "observe"})
#: kwargs of those verbs that are NOT labels
_R13_NON_LABEL_KWARGS = frozenset({"value"})
#: names that hold a per-request payload (a label drawn from one has
#: request-cardinality by construction)
_R13_REQUEST_NAMES = frozenset({"request", "req"})
#: higher-order tracers (R8): a function passed here by name is traced
#: exactly like a jit body
_TRACED_HOF_NAMES = frozenset(
    {
        "shard_map", "jax.lax.scan", "lax.scan", "jax.lax.while_loop",
        "lax.while_loop", "jax.lax.fori_loop", "lax.fori_loop",
        "jax.lax.cond", "lax.cond", "jax.vmap", "vmap",
    }
)


@dataclass(frozen=True)
class Violation:
    path: str  # repo-relative posix path
    line: int
    rule: str
    scope: str  # qualified function name, or "<module>"
    code: str  # stripped source of the flagged line
    message: str

    @property
    def fingerprint(self) -> str:
        """Line-number-free identity used by the baseline (stable across
        unrelated edits; moves with the code text itself)."""
        return f"{self.path}::{self.rule}::{self.scope}::{self.code}"

    def render(self) -> str:
        return (
            f"{self.path}:{self.line}: {self.rule} [{self.scope}] "
            f"{self.message}\n    {self.code}"
        )


def _dotted(node: ast.AST) -> Optional[str]:
    """``a.b.c`` -> "a.b.c" for Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _walk_own(root: ast.AST):
    """ast.walk over ``root``'s OWN code: nested function/lambda bodies are
    pruned (ast.walk's flat iteration would attribute their statements to
    the enclosing scope — they get their own visit instead)."""
    stack = [root]
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                continue
            stack.append(child)


def _root_name(node: ast.AST) -> Optional[str]:
    """Leftmost Name of an attribute/subscript/call chain."""
    while True:
        if isinstance(node, ast.Attribute):
            node = node.value
        elif isinstance(node, ast.Subscript):
            node = node.value
        elif isinstance(node, ast.Call):
            node = node.func
        elif isinstance(node, ast.Name):
            return node.id
        else:
            return None


class _Directives:
    """Per-line ``# graftlint: ...`` comment directives, via tokenize (the
    AST drops comments). ``disable[line]`` is a rule set; ``{"*"}`` = all."""

    def __init__(self, source: str):
        self.disable: Dict[int, Set[str]] = {}
        self.hot_lines: Set[int] = set()
        try:
            tokens = tokenize.generate_tokens(io.StringIO(source).readline)
            for tok in tokens:
                if tok.type != tokenize.COMMENT:
                    continue
                # the directive may trail prose in the same comment:
                # "# one fetch per spill  # graftlint: disable=R1"
                marker = tok.string.find("graftlint:")
                if marker < 0:
                    continue
                body = tok.string[marker + len("graftlint:"):].strip()
                if body.startswith("disable"):
                    _, _, spec = body.partition("=")
                    rules = (
                        {r.strip().split()[0] for r in spec.split(",") if r.strip()}
                        if "=" in body
                        else {"*"}
                    )
                    self.disable.setdefault(tok.start[0], set()).update(rules)
                elif body.startswith("hot"):
                    self.hot_lines.add(tok.start[0])
        except tokenize.TokenError:
            pass

    def suppressed(self, line: int, rule: str, def_line: Optional[int]) -> bool:
        for ln in (line, line - 1, def_line):
            if ln is None:
                continue
            rules = self.disable.get(ln)
            if rules and ("*" in rules or rule in rules):
                return True
        return False


def _frontier_param(args: ast.arguments) -> Optional[str]:
    """The first parameter naming/annotating a frontier-sized buffer, or
    None. Annotation ``Frontier`` (any dotting) counts regardless of the
    parameter's name; otherwise the name itself must be one of
    ``_FRONTIER_PARAMS``."""
    for a in list(args.posonlyargs) + list(args.args) + list(args.kwonlyargs):
        if a.arg in _FRONTIER_PARAMS:
            return a.arg
        if a.annotation is not None:
            ann = _dotted(a.annotation) or ""
            if ann.rsplit(".", 1)[-1] == "Frontier":
                return a.arg
    return None


def _jit_call_parts(node: ast.AST) -> Tuple[bool, list]:
    """Is ``node`` a jit wrapper expression, and with which keywords?

    Recognizes the bare name (``@jax.jit``), the configured call
    (``jax.jit(f, ...)`` / ``@jax.jit(...)``) and the partial form
    (``partial(jax.jit, ...)``). Returns ``(is_jit, keywords)``.
    """
    if _dotted(node) in _JIT_NAMES:
        return True, []
    if isinstance(node, ast.Call):
        name = _dotted(node.func)
        if name in _JIT_NAMES:
            return True, node.keywords
        if name in ("partial", "functools.partial") and node.args:
            if _dotted(node.args[0]) in _JIT_NAMES:
                return True, node.keywords
    return False, []


def _frontier_param_funcs(tree: ast.Module) -> Dict[str, str]:
    """function name -> its frontier param, for every def in the module
    (lets R7 resolve ``x = jax.jit(f, ...)`` assignments to f's params)."""
    out: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            p = _frontier_param(node.args)
            if p is not None:
                out[node.name] = p
    return out


def _jitted_names(tree: ast.Module) -> Set[str]:
    """Module-level names bound to jitted callables: ``f = jax.jit(...)``
    assignments and ``@jax.jit`` / ``@partial(jax.jit, ...)`` decorated defs."""

    def is_jit_call(node: ast.AST) -> bool:
        if not isinstance(node, ast.Call):
            return False
        name = _dotted(node.func)
        if name in ("jax.jit", "jit", "pjit", "jax.pjit"):
            return True
        # partial(jax.jit, ...) / functools.partial(jax.jit, ...)
        if name in ("partial", "functools.partial") and node.args:
            return _dotted(node.args[0]) in ("jax.jit", "jit")
        return False

    jitted: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and is_jit_call(node.value):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    jitted.add(tgt.id)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if is_jit_call(dec) or _dotted(dec) in ("jax.jit", "jit"):
                    jitted.add(node.name)
    return jitted


def _traced_callee_names(tree: ast.Module) -> Set[str]:
    """Function names whose BODIES are jit-traced (R8): defs decorated
    with jit, defs passed by name to ``jax.jit(f, ...)`` assignments, and
    defs handed to the traced higher-order operators (shard_map, lax.scan
    / while_loop / cond / fori_loop, vmap). Name-matched per module — a
    linter-grade overapproximation."""
    traced: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if _jit_call_parts(dec)[0]:
                    traced.add(node.name)
        elif isinstance(node, ast.Call):
            name = _dotted(node.func)
            takes_fn = name in _JIT_NAMES or name in _TRACED_HOF_NAMES
            if not takes_fn and isinstance(node.func, ast.Call):
                takes_fn = _jit_call_parts(node.func)[0]
            if takes_fn:
                for arg in node.args:
                    if isinstance(arg, ast.Name):
                        traced.add(arg.id)
    return traced


class _FileLinter(ast.NodeVisitor):
    def __init__(
        self,
        path: str,
        source: str,
        tree: ast.Module,
        rules: Set[str],
        hot_paths: Set[str],
    ):
        self.path = path
        self.lines = source.splitlines()
        self.tree = tree
        self.rules = rules
        self.hot_paths = hot_paths
        self.directives = _Directives(source)
        self.jitted = _jitted_names(tree)
        self.traced_callees = _traced_callee_names(tree)
        self.frontier_funcs = _frontier_param_funcs(tree)
        self.violations: List[Violation] = []
        # lexical state
        self.scope: List[str] = []
        self.def_lines: List[int] = []
        self.loop_depth = 0
        self.for_depth = 0
        self.hot = False
        #: is the current scope's code jit-TRACED (R8)? nested defs
        #: inside a traced function inherit (lax.scan bodies etc.)
        self.jit_scope = False
        self.device_names: Set[str] = set()  # assigned from jnp./jax. calls
        self.pulled_names: Set[str] = set()  # assigned from host pulls
        self.tainted: Set[str] = set()  # assigned raw from jitted callees
        self.buffer_names: Set[str] = set()  # assigned from io.BytesIO etc.
        self.loop_targets: Set[str] = set()  # names bound by enclosing fors (R13)
        #: the subset of loop_targets bound by ``for x in range(...)``
        #: loops — bounded cardinality by construction (R13 exemption:
        #: rank ids from range(num_ranks) can never outgrow the mesh)
        self.bounded_targets: Set[str] = set()
        #: does the current scope os.replace-publish (the atomic pattern)?
        self.atomic_scope = self._scope_is_atomic(tree)

    # -- reporting ---------------------------------------------------------

    def _emit(self, node: ast.AST, rule: str, message: str) -> None:
        if rule not in self.rules:
            return
        line = getattr(node, "lineno", 1)
        def_line = self.def_lines[-1] if self.def_lines else None
        if self.directives.suppressed(line, rule, def_line):
            return
        code = (
            self.lines[line - 1].strip() if 0 < line <= len(self.lines) else ""
        )
        scope = ".".join(self.scope) if self.scope else "<module>"
        self.violations.append(
            Violation(self.path, line, rule, scope, code, message)
        )

    # -- scope bookkeeping -------------------------------------------------

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._visit_func(node)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._visit_func(node)

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self.scope.append(node.name)
        self.generic_visit(node)
        self.scope.pop()

    def _visit_func(self, node) -> None:
        saved = (
            self.hot,
            self.loop_depth,
            self.for_depth,
            self.device_names,
            self.pulled_names,
            self.tainted,
            self.buffer_names,
            self.atomic_scope,
            self.jit_scope,
            self.loop_targets,
            self.bounded_targets,
        )
        self.scope.append(node.name)
        self.def_lines.append(node.lineno)
        self.hot = node.name in self.hot_paths or any(
            ln in self.directives.hot_lines
            for ln in range(node.lineno, node.body[0].lineno)
        )
        # R8 scope: a traced def, or any def nested inside one (a scan /
        # while_loop body defined inline in a jitted function is traced
        # with it)
        self.jit_scope = self.jit_scope or node.name in self.traced_callees
        self.loop_depth = 0
        self.for_depth = 0
        self.device_names = set()
        self.pulled_names = set()
        self.tainted = set()
        self.buffer_names = set()
        self.loop_targets = set()
        self.bounded_targets = set()
        self.atomic_scope = self._scope_is_atomic(node)
        self._check_r5(node)
        self._check_r7_def(node)
        for child in node.body:
            self.visit(child)
        self.def_lines.pop()
        self.scope.pop()
        (
            self.hot,
            self.loop_depth,
            self.for_depth,
            self.device_names,
            self.pulled_names,
            self.tainted,
            self.buffer_names,
            self.atomic_scope,
            self.jit_scope,
            self.loop_targets,
            self.bounded_targets,
        ) = saved

    # -- loops -------------------------------------------------------------

    def visit_For(self, node: ast.For) -> None:
        self._check_r4(node)
        self.loop_depth += 1
        self.for_depth += 1
        # R13: names this loop binds are loop-variable labels in its body;
        # a ``for x in range(...)`` target is BOUNDED (the label set can
        # never outgrow the range — rank ids from range(num_ranks) are
        # the canonical case), any other iterable is not, and an inner
        # non-range loop re-binding a bounded name strips the exemption
        saved_targets = set(self.loop_targets)
        saved_bounded = set(self.bounded_targets)
        targets = self._target_names([node.target])
        self.loop_targets.update(targets)
        if self._iter_is_bounded(node.iter):
            self.bounded_targets.update(targets)
        else:
            self.bounded_targets.difference_update(targets)
        self.generic_visit(node)
        self.loop_targets = saved_targets
        # On exit, adjust only THIS loop's own targets: a range loop's
        # revert to their pre-loop status (conservative — the var holds
        # the last range value, but the pre-loop binding is what the
        # rest of the scope was written against), a non-range loop's
        # stay STRIPPED (the name still holds an element of the
        # unbounded iterable; Python loop vars outlive the loop). Strips
        # of OTHER names made inside the body persist — a wholesale
        # snapshot restore would resurrect a name an inner
        # `for r in requests:` rebind had stripped.
        bounded_iter = self._iter_is_bounded(node.iter)
        for t in targets:
            if bounded_iter and t in saved_bounded:
                self.bounded_targets.add(t)
            else:
                self.bounded_targets.discard(t)
        self.for_depth -= 1
        self.loop_depth -= 1

    @staticmethod
    def _iter_is_bounded(it: ast.AST) -> bool:
        """Is this for-loop iterable a bounded label source? ``range(...)``
        (and ``enumerate(range(...))``) over configuration-shaped
        arguments — names, constants, attributes, arithmetic thereof
        (``range(num_ranks)``, ``range(2 * R)``). A range whose argument
        embeds a CALL — ``range(len(requests))``,
        ``range(queue.qsize())`` — is sized by DATA, so its label set
        grows with the process's traffic: exactly the unbounded
        cardinality R13 exists to catch, not exempt."""
        if (
            isinstance(it, ast.Call)
            and isinstance(it.func, ast.Name)
            and it.func.id == "enumerate"
            and it.args
        ):
            it = it.args[0]
        if not (
            isinstance(it, ast.Call)
            and isinstance(it.func, ast.Name)
            and it.func.id == "range"
        ):
            return False
        return not any(
            isinstance(sub, ast.Call)
            for arg in it.args
            for sub in ast.walk(arg)
        )

    def visit_While(self, node: ast.While) -> None:
        self._check_r3_test(node)
        self.loop_depth += 1
        self.generic_visit(node)
        self.loop_depth -= 1

    def visit_If(self, node: ast.If) -> None:
        self._check_r3_test(node)
        self.generic_visit(node)

    # -- assignments: taint / device / pulled tracking ----------------------

    def visit_Assign(self, node: ast.Assign) -> None:
        self._track_assignment(node.targets, node.value)
        self._check_r7_assign(node)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self.generic_visit(node)

    def _target_names(self, targets) -> List[str]:
        names: List[str] = []
        for tgt in targets:
            if isinstance(tgt, ast.Name):
                names.append(tgt.id)
            elif isinstance(tgt, (ast.Tuple, ast.List)):
                names.extend(self._target_names(tgt.elts))
        return names

    def _track_assignment(self, targets, value) -> None:
        names = self._target_names(targets)
        if not names:
            return
        for group in (
            self.device_names,
            self.pulled_names,
            self.tainted,
            self.buffer_names,
        ):
            group.difference_update(names)  # rebinding clears prior status
        if self._is_device_producer(value):
            self.device_names.update(names)
        if self._is_host_pull(value):
            self.pulled_names.update(names)
        if self._is_raw_jitted_call(value):
            self.tainted.update(names)
        if (
            isinstance(value, ast.Call)
            and _dotted(value.func) in _BUFFER_FACTORIES
        ):
            self.buffer_names.update(names)

    def _is_device_producer(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Call):
            name = _dotted(node.func) or ""
            root = name.split(".", 1)[0]
            if root in _DEVICE_MODULES and name not in (
                "jax.device_get",
            ):
                return True
            # buf.at[...].set(...) produces a new device buffer
            if name.endswith(".set") and ".at" in name:
                return True
        return False

    def _is_host_pull(self, node: ast.AST) -> bool:
        if not isinstance(node, ast.Call):
            return False
        name = _dotted(node.func)
        if name in _HOST_PULL_CALLS:
            return True
        # np.asarray(...).copy() chains
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr == "copy"
            and self._is_host_pull(node.func.value)
        ):
            return True
        return False

    def _is_raw_jitted_call(self, node: ast.AST) -> bool:
        """A call to a known-jitted callee NOT wrapped in a scalar
        conversion; subscripts of such calls stay raw."""
        if isinstance(node, ast.Subscript):
            return self._is_raw_jitted_call(node.value)
        if not isinstance(node, ast.Call):
            return False
        name = _dotted(node.func)
        if name in _SCALAR_CONVERSIONS or name in _SCALAR_CONVERSION_ATTRS:
            return False
        return name in self.jitted

    # -- calls: R1 / R2 ------------------------------------------------------

    def visit_Call(self, node: ast.Call) -> None:
        name = _dotted(node.func)
        self._check_r8(node, name)
        self._check_r13(node, name)
        in_hot = self.loop_depth > 0 or self.hot
        if in_hot and name in _HOST_PULL_CALLS and node.args:
            if self._is_device_expr(node.args[0]):
                self._emit(
                    node,
                    "R1",
                    f"{name}() pulls a device buffer to host inside a hot "
                    "loop — hoist it out or keep the data on device",
                )
        if (
            in_hot
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "copy"
            and not node.args
            and self._is_device_expr(node.func.value)
        ):
            self._emit(
                node,
                "R1",
                ".copy() of a device buffer inside a hot loop — a full "
                "host materialization per iteration",
            )
        # R2 only fires in hot contexts: a one-time down-compute-up round
        # trip in setup code is legitimate; per-spill/per-iteration
        # re-uploads of a whole pulled buffer are the hazard
        if in_hot and name in _UPLOAD_CALLS and node.args:
            arg = node.args[0]
            if isinstance(arg, ast.Name) and arg.id in self.pulled_names:
                self._emit(
                    node,
                    "R2",
                    f"{name}({arg.id}) re-uploads a buffer pulled from the "
                    "device in this function — write the mutated slice back "
                    "in place with buf.at[:k].set(...) instead",
                )
        self._check_r6(node, name)
        self.generic_visit(node)

    # -- R8: metric/trace recording inside jit-traced code -------------------

    def _check_r8(self, node: ast.Call, name: Optional[str]) -> None:
        """A registry/tracer recording call in a jit-traced body runs at
        TRACE time, not run time: it records once, as a compile-time
        constant (silently wrong counts), and if it ever closed over
        traced values it would force a host callback or recompile. The
        telemetry layer records around dispatches, never inside them."""
        if "R8" not in self.rules or not self.jit_scope or name is None:
            return
        root, _, _rest = name.partition(".")
        verb = name.rsplit(".", 1)[-1]
        hit = (
            root in _OBS_RECORDER_ROOTS and verb in _OBS_RECORDER_VERBS
            if "." in name
            else name in _OBS_BARE_CALLS
        )
        if hit:
            self._emit(
                node,
                "R8",
                f"{name}() records host-side telemetry inside jit-traced "
                "code — under trace this runs ONCE at compile time "
                "(recording a constant, not the runtime series) or forces "
                "a recompile/callback; move the recording to the host "
                "loop around the dispatch",
            )

    # -- R13: unbounded metric-label cardinality -----------------------------

    @staticmethod
    def _unwrap_str_call(node: ast.AST) -> ast.AST:
        """``str(x)`` around a label value changes nothing about its
        cardinality — look through one conversion layer."""
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == "str"
            and node.args
        ):
            return node.args[0]
        return node

    def _r13_label_hazard(self, value: ast.AST) -> Optional[str]:
        """Why this label value has unbounded cardinality, or None."""
        value = self._unwrap_str_call(value)
        if isinstance(value, ast.JoinedStr):
            return "an f-string label mints a new series per formatted value"
        if (
            isinstance(value, ast.Name)
            and value.id in self.loop_targets
            # range()-bound loop vars are a bounded set (per-rank gauges
            # labeled from range(num_ranks) must never trip the rule)
            and value.id not in self.bounded_targets
        ):
            return (
                f"loop variable {value.id!r} as a label mints one series "
                "per iteration"
            )
        root = _root_name(value)
        if root in _R13_REQUEST_NAMES and isinstance(
            value, (ast.Subscript, ast.Attribute)
        ):
            return (
                "a per-request field as a label mints one series per "
                "distinct request"
            )
        if (
            isinstance(value, ast.Call)
            and isinstance(value.func, ast.Attribute)
            and value.func.attr == "get"
            and _root_name(value.func.value) in _R13_REQUEST_NAMES
        ):
            return (
                "a per-request field as a label mints one series per "
                "distinct request"
            )
        return None

    def _check_r13(self, node: ast.Call, name: Optional[str]) -> None:
        """The registry keeps one series per distinct label tuple FOREVER
        (that is what makes delta/scrape semantics work), so a label
        value drawn from an unbounded set — an f-string, a loop
        variable, a per-request field — is a memory leak and a scrape
        explosion. Bounded label sets (tier names, entry names, seam
        names, literal strings, module constants) are the contract."""
        if "R13" not in self.rules or name is None or "." not in name:
            return
        root, _, _rest = name.partition(".")
        verb = name.rsplit(".", 1)[-1]
        if root not in _R13_REGISTRY_ROOTS or verb not in _R13_RECORD_VERBS:
            return
        for kw in node.keywords:
            if kw.arg is None or kw.arg in _R13_NON_LABEL_KWARGS:
                continue
            why = self._r13_label_hazard(kw.value)
            if why:
                self._emit(
                    node,
                    "R13",
                    f"label {kw.arg!r} at {name}() has unbounded "
                    f"cardinality: {why} — label with a value from a "
                    "fixed set (tier/entry/seam name) and put the "
                    "variable part in the metric VALUE or a span attr",
                )

    # -- R6: non-atomic write of a durable artifact --------------------------

    def _scope_is_atomic(self, root: ast.AST) -> bool:
        """Does this scope's own code os.replace/os.rename — i.e. follow
        the write-temp-then-publish pattern that makes its writes safe?"""
        for sub in _walk_own(root):
            if (
                isinstance(sub, ast.Call)
                and _dotted(sub.func) in _ATOMIC_PUBLISH_CALLS
            ):
                return True
        return False

    @staticmethod
    def _tempish_token(text: str) -> bool:
        """TOKEN-boundary temp matching: split on non-alphanumerics and
        require a segment that IS tmp/temp (or a tmp*/tempfile/tempdir
        compound). Substring matching would silently exempt durable
        writes through names like ``attempt``/``template``/``temperature``
        — exactly the false negatives an exemption rule must not have."""
        for seg in re.split(r"[^a-z0-9]+", text.lower()):
            if seg in ("tmp", "temp", "tempfile", "tempdir", "mkdtemp", "mkstemp"):
                return True
            if seg.startswith("tmp"):  # tmpfile, tmpdir, tmp2, ...
                return True
        return False

    def _is_tempish(self, node: ast.AST) -> bool:
        """Heuristic: does this path expression name a TEMP location?
        tempfile-derived values, names/attributes/strings with a tmp/temp
        token — a crash leaves garbage nobody will ever read back."""
        for sub in ast.walk(node):
            if isinstance(sub, ast.Name):
                if self._tempish_token(sub.id):
                    return True
            elif isinstance(sub, ast.Attribute):
                if self._tempish_token(sub.attr):
                    return True
            elif isinstance(sub, ast.Constant) and isinstance(sub.value, str):
                if self._tempish_token(sub.value):
                    return True
        return False

    def _check_r6(self, node: ast.Call, name: Optional[str]) -> None:
        if "R6" not in self.rules or self.atomic_scope:
            return
        if name in _DURABLE_NP_WRITES and node.args:
            target = node.args[0]
            if self._is_buffer_target(target) or self._is_tempish(target):
                return
            self._emit(
                node,
                "R6",
                f"{name}() writes a durable artifact straight to its final "
                "path — a crash mid-write leaves a truncated file; write to "
                "a temp file and os.replace() it into place "
                "(resilience.checkpoint.write_atomic)",
            )
        elif name == "open" and node.args:
            mode = node.args[1] if len(node.args) > 1 else None
            for kw in node.keywords:
                if kw.arg == "mode":
                    mode = kw.value
            if not (
                isinstance(mode, ast.Constant)
                and isinstance(mode.value, str)
                and any(c in mode.value for c in "wx")
            ):
                return
            if self._is_tempish(node.args[0]):
                return
            self._emit(
                node,
                "R6",
                f"open(..., {mode.value!r}) publishes a durable artifact "
                "non-atomically — a crash mid-write leaves a truncated "
                "file; write to a temp file and os.replace() it into place "
                "(resilience.checkpoint.write_json_atomic)",
            )

    def _is_buffer_target(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Name):
            return node.id in self.buffer_names
        return (
            isinstance(node, ast.Call)
            and _dotted(node.func) in _BUFFER_FACTORIES
        )

    def _is_device_expr(self, node: ast.AST) -> bool:
        """Heuristic: does this expression name a device buffer?"""
        if isinstance(node, ast.Name):
            return node.id in self.device_names
        if isinstance(node, ast.Attribute):
            return node.attr in DEVICE_ATTRS or self._is_device_expr(node.value)
        if isinstance(node, ast.Subscript):
            return self._is_device_expr(node.value)
        if isinstance(node, ast.Call):
            return self._is_device_producer(node) or self._is_host_pull(
                node
            ) and any(
                self._is_device_expr(a) for a in node.args
            )
        return False

    # -- R3: control flow on jitted outputs ---------------------------------

    def _check_r3_test(self, node) -> None:
        if "R3" not in self.rules:
            return
        naked = self._naked_tainted_names(node.test)
        for name in sorted(naked):
            self._emit(
                node,
                "R3",
                f"Python {type(node).__name__.lower()} on `{name}`, a raw "
                "output of a jitted callee — convert with float()/int()/"
                "bool() first (tracer-leak risk if this code is ever traced)",
            )

    def _naked_tainted_names(self, test: ast.AST) -> Set[str]:
        """Tainted Names in a test expression not wrapped in a scalar
        conversion call."""
        naked: Set[str] = set()

        def walk(node: ast.AST) -> None:
            if isinstance(node, ast.Call):
                name = _dotted(node.func)
                if name in _SCALAR_CONVERSIONS or name in _SCALAR_CONVERSION_ATTRS:
                    return  # converted — whatever is inside is laundered
                if self._is_raw_jitted_call(node):
                    naked.add(name or "<call>")
            if isinstance(node, ast.Name) and node.id in self.tainted:
                naked.add(node.id)
            for child in ast.iter_child_nodes(node):
                walk(child)

        walk(test)
        return naked

    # -- R4: jnp work in a python for loop ----------------------------------

    def _check_r4(self, node: ast.For) -> None:
        if "R4" not in self.rules:
            return
        for sub in _walk_own(node):
            if isinstance(sub, ast.Call):
                name = _dotted(sub.func) or ""
                root = name.split(".", 1)[0]
                is_jnp = root in _JNP_ROOTS or name.startswith(
                    ("jax.lax.", "jax.numpy.", "jax.nn.")
                )
                if is_jnp:
                    # anchor on the for statement so a loop-line disable
                    # covers every jnp call in the body
                    self._emit(
                        node,
                        "R4",
                        f"{name}() (line {sub.lineno}) inside a Python for "
                        "loop — the loop unrolls at trace time / relaunches "
                        "kernels; use lax.scan, lax.fori_loop, or vmap",
                    )
                    return  # one violation per loop

    # -- R5: early return None drops mutated self state ----------------------

    def _check_r5(self, func) -> None:
        if "R5" not in self.rules:
            return
        body = func.body
        # lexical positions of self.<attr> OVERWRITES and write-backs
        overwrites: List[int] = []
        writebacks: List[int] = []
        assigns: List[int] = []  # local name bindings
        returns_none: List[ast.Return] = []
        last_stmt_line = body[-1].lineno if body else func.lineno

        for node in _walk_own(func):
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if (
                        isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"
                    ):
                        overwrites.append(node.lineno)
                        writebacks.append(node.lineno)
                    elif isinstance(tgt, ast.Name):
                        assigns.append(node.lineno)
            elif isinstance(node, ast.Call):
                # self.X.append(...) / extend / insert / update write-backs
                name = _dotted(node.func) or ""
                if name.startswith("self.") and name.rsplit(".", 1)[-1] in (
                    "append",
                    "extend",
                    "insert",
                    "update",
                    "add",
                ):
                    writebacks.append(node.lineno)
            elif isinstance(node, ast.Return):
                is_none = node.value is None or (
                    isinstance(node.value, ast.Constant)
                    and node.value.value is None
                )
                if is_none and node.lineno < last_stmt_line:
                    returns_none.append(node)

        if not overwrites:
            return
        first_ow = min(overwrites)
        for ret in returns_none:
            if ret.lineno <= first_ow:
                continue
            # state computed after the overwrite but before the return?
            computed = [ln for ln in assigns if first_ow < ln < ret.lineno]
            if not computed:
                continue
            # any write-back strictly between overwrite and return clears it
            saved = [ln for ln in writebacks if first_ow < ln < ret.lineno]
            if saved:
                continue
            self._emit(
                ret,
                "R5",
                "early `return None` after overwriting self state with "
                "locals computed but never written back — mutated state is "
                "dropped (the _partition take==0 bug class)",
            )

    # -- R7: jit frontier entry without buffer donation -----------------------

    def _r7_emit(self, node: ast.AST, param: str) -> None:
        self._emit(
            node,
            "R7",
            f"jit entry takes a frontier-sized buffer (param `{param}`) "
            "without donate_argnums/donate_argnames — every dispatch "
            "copies the reservoir-scale buffer instead of aliasing it in "
            "place; donate the frontier, or disable R7 on a harness that "
            "intentionally re-dispatches the same buffer",
        )

    def _check_r7_def(self, node) -> None:
        if "R7" not in self.rules:
            return
        param = _frontier_param(node.args)
        if param is None:
            return
        for dec in node.decorator_list:
            is_jit, kws = _jit_call_parts(dec)
            if not is_jit:
                continue
            if not any(kw.arg in _DONATE_KWARGS for kw in kws):
                self._r7_emit(node, param)
            return  # at most one jit decorator matters

    def _check_r7_assign(self, node: ast.Assign) -> None:
        if "R7" not in self.rules:
            return
        val = node.value
        if not isinstance(val, ast.Call) or not val.args:
            return
        # jax.jit(f, ...) — keywords on the jit call itself
        is_jit, kws = _jit_call_parts(val.func) if isinstance(
            val.func, ast.Call
        ) else (False, [])
        if _dotted(val.func) in _JIT_NAMES:
            is_jit, kws = True, val.keywords
        if not is_jit:
            return
        fn_expr = val.args[0]
        param: Optional[str] = None
        if isinstance(fn_expr, ast.Lambda):
            param = _frontier_param(fn_expr.args)
        elif isinstance(fn_expr, ast.Name):
            param = self.frontier_funcs.get(fn_expr.id)
        if param is None:
            return
        if not any(kw.arg in _DONATE_KWARGS for kw in kws):
            self._r7_emit(node, param)

    # -- driver --------------------------------------------------------------

    def run(self) -> List[Violation]:
        for node in self.tree.body:
            self.visit(node)
        return self.violations


# -- public API ---------------------------------------------------------------


def lint_text(
    source: str,
    path: str = "<string>",
    rules: Optional[Iterable[str]] = None,
    hot_paths: Optional[Iterable[str]] = None,
) -> List[Violation]:
    """Lint one source string; returns violations (disable comments already
    honored, baseline NOT applied)."""
    tree = ast.parse(source, filename=path)
    linter = _FileLinter(
        path,
        source,
        tree,
        set(rules) if rules is not None else set(RULES),
        set(hot_paths) if hot_paths is not None else set(DEFAULT_HOT_PATHS),
    )
    return linter.run()


def _iter_py_files(paths: Sequence[pathlib.Path]) -> List[pathlib.Path]:
    files: List[pathlib.Path] = []
    for p in paths:
        if p.is_dir():
            files.extend(sorted(p.rglob("*.py")))
        elif p.suffix == ".py":
            files.append(p)
    return files


def lint_paths(
    paths: Sequence[pathlib.Path],
    root: pathlib.Path,
    rules: Optional[Iterable[str]] = None,
) -> List[Violation]:
    """Lint every .py under ``paths``; violation paths are ``root``-relative."""
    out: List[Violation] = []
    for f in _iter_py_files(paths):
        try:
            source = f.read_text()
        except (OSError, UnicodeDecodeError):
            continue
        try:
            rel = f.resolve().relative_to(root.resolve()).as_posix()
        except ValueError:
            rel = f.as_posix()
        try:
            out.extend(lint_text(source, rel, rules=rules))
        except SyntaxError:
            continue
    return out


# -- baseline -----------------------------------------------------------------


@dataclass
class BaselineResult:
    new: List[Violation] = field(default_factory=list)
    accepted: List[Violation] = field(default_factory=list)
    stale: List[str] = field(default_factory=list)


def load_baseline(path: pathlib.Path) -> Dict[str, int]:
    if not path.exists():
        return {}
    data = json.loads(path.read_text())
    return {str(k): int(v) for k, v in data.get("entries", {}).items()}


def write_baseline(path: pathlib.Path, violations: Sequence[Violation]) -> None:
    counts: Dict[str, int] = {}
    for v in violations:
        counts[v.fingerprint] = counts.get(v.fingerprint, 0) + 1
    path.write_text(
        json.dumps(
            {
                "comment": (
                    "graftlint accepted-site baseline: pre-existing "
                    "violations keyed path::rule::scope::code (line-free). "
                    "Regenerate with: python -m tsp_mpi_reduction_tpu.analysis "
                    "--write-baseline"
                ),
                "version": 1,
                "entries": dict(sorted(counts.items())),
            },
            indent=2,
        )
        + "\n"
    )


def apply_baseline(
    violations: Sequence[Violation], baseline: Dict[str, int]
) -> BaselineResult:
    """Split violations into new vs baseline-accepted; surplus occurrences of
    a baselined fingerprint count as new."""
    budget = dict(baseline)
    res = BaselineResult()
    for v in violations:
        if budget.get(v.fingerprint, 0) > 0:
            budget[v.fingerprint] -= 1
            res.accepted.append(v)
        else:
            res.new.append(v)
    res.stale = sorted(k for k, n in budget.items() if n > 0)
    return res


def collect_scopes(tree: ast.Module) -> Set[str]:
    """Every qualified def/class scope a module defines, dotted exactly as
    ``_FileLinter`` qualifies violation scopes ("Cls.meth",
    "solve_sharded.spill_refill", ...), plus "<module>"."""
    out: Set[str] = {"<module>"}

    def walk(node: ast.AST, prefix: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ):
                q = f"{prefix}.{child.name}" if prefix else child.name
                out.add(q)
                walk(child, q)
            else:
                walk(child, prefix)

    walk(tree, "")
    return out


def find_dead_scopes(baseline: Dict[str, int], root: pathlib.Path) -> List[str]:
    """Baseline fingerprints whose file or enclosing scope no longer exists
    in the source — stale DEBT, not just stale line numbers: the code the
    entry was accepted for is gone, so the entry can never be repaid and
    only masks a future violation that happens to reuse the fingerprint.
    ``make lint`` fails on these (delete the entry or regenerate the
    baseline). Fingerprints are ``path::rule::scope::code``; each
    referenced file is parsed once."""
    scopes_by_path: Dict[str, Optional[Set[str]]] = {}
    dead: List[str] = []
    for fp in baseline:
        parts = fp.split("::", 3)
        if len(parts) != 4:
            dead.append(fp)  # unparseable fingerprint: treat as dead debt
            continue
        path, _rule, scope, _code = parts
        if path not in scopes_by_path:
            try:
                source = (root / path).read_text()
                scopes_by_path[path] = collect_scopes(ast.parse(source))
            except (OSError, SyntaxError, UnicodeDecodeError):
                scopes_by_path[path] = None
        known = scopes_by_path[path]
        if known is None or scope not in known:
            dead.append(fp)
    return sorted(dead)

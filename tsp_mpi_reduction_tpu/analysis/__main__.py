"""CLI: ``python -m tsp_mpi_reduction_tpu.analysis [paths...]``.

Exit status 0 when the tree is clean modulo the checked-in baseline,
1 when new violations exist, 2 on usage errors. Runs stdlib-only (no JAX
import), so it is safe as the first stage of ``make lint`` / the sweep
harness even on machines with no accelerator runtime.
"""

from __future__ import annotations

import argparse
import pathlib
import sys

from .graftlint import (
    RULES,
    apply_baseline,
    find_dead_scopes,
    lint_paths,
    load_baseline,
    write_baseline,
)

_PKG_DIR = pathlib.Path(__file__).resolve().parent.parent  # the package
_REPO_ROOT = _PKG_DIR.parent
_DEFAULT_BASELINE = pathlib.Path(__file__).resolve().parent / "graftlint_baseline.json"
#: default lint surface: the package plus the perf-harness scripts that sit
#: on the same hot paths (tests are excluded — their fixtures intentionally
#: contain violating snippets)
_DEFAULT_TARGETS = [_PKG_DIR, _REPO_ROOT / "tools", _REPO_ROOT / "bench.py"]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="graftlint", description="JAX-hazard lint (rules R1-R7)"
    )
    ap.add_argument(
        "paths",
        nargs="*",
        type=pathlib.Path,
        help="files/dirs to lint (default: the package, tools/, bench.py)",
    )
    ap.add_argument(
        "--rules",
        default=",".join(sorted(RULES)),
        help="comma-separated rule subset (default: all)",
    )
    ap.add_argument(
        "--baseline",
        type=pathlib.Path,
        default=_DEFAULT_BASELINE,
        help="baseline JSON of accepted sites",
    )
    ap.add_argument(
        "--no-baseline",
        action="store_true",
        help="report every violation, ignoring the baseline",
    )
    ap.add_argument(
        "--write-baseline",
        action="store_true",
        help="accept the current violations as the new baseline",
    )
    ap.add_argument(
        "--quiet", action="store_true", help="summary line only"
    )
    args = ap.parse_args(argv)

    rules = {r.strip() for r in args.rules.split(",") if r.strip()}
    unknown = rules - set(RULES)
    if unknown:
        print(f"graftlint: unknown rule(s): {', '.join(sorted(unknown))}")
        return 2

    if args.paths:
        # an explicit path that doesn't exist is a usage error, not a
        # clean run — a typo'd CI invocation must not turn the gate green
        missing = [p for p in args.paths if not p.exists()]
        if missing:
            print(
                "graftlint: no such path(s): "
                + ", ".join(str(p) for p in missing)
            )
            return 2
        targets = list(args.paths)
    else:
        targets = [p for p in _DEFAULT_TARGETS if p.exists()]
    violations = lint_paths(targets, root=_REPO_ROOT, rules=rules)

    if args.write_baseline:
        if args.paths and args.baseline == _DEFAULT_BASELINE:
            # a partial lint surface must not clobber the repo-wide
            # baseline (it would drop every accepted site outside `paths`)
            print(
                "graftlint: refusing --write-baseline for explicit paths "
                "into the default baseline; pass --baseline PATH"
            )
            return 2
        write_baseline(args.baseline, violations)
        print(
            f"graftlint: baseline written to {args.baseline} "
            f"({len(violations)} accepted sites)"
        )
        return 0

    baseline = {} if args.no_baseline else load_baseline(args.baseline)
    res = apply_baseline(violations, baseline)
    # stale-DEBT detector: a baseline entry whose file/scope is gone from
    # the source can never be repaid — it only masks a future violation
    # that happens to reuse the fingerprint. Fail, don't warn. A dead
    # entry necessarily also matched no violation, so drop it from the
    # (warn-only) stale list — one entry, one verdict.
    dead = find_dead_scopes(baseline, _REPO_ROOT)
    stale = [fp for fp in res.stale if fp not in set(dead)]

    if not args.quiet:
        for v in res.new:
            print(v.render())
        for fp in stale:
            print(f"graftlint: stale baseline entry (fixed? regenerate): {fp}")
    for fp in dead:
        print(
            "graftlint: DEAD baseline entry (scope gone from source — "
            f"delete it or regenerate the baseline): {fp}"
        )
    print(
        f"graftlint: {len(res.new)} new, {len(res.accepted)} baselined, "
        f"{len(stale)} stale, {len(dead)} dead baseline entries "
        f"({len(targets)} target(s), rules {','.join(sorted(rules))})"
    )
    return 1 if (res.new or dead) else 0


if __name__ == "__main__":
    sys.exit(main())

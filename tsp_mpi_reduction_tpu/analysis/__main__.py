"""CLI: ``python -m tsp_mpi_reduction_tpu.analysis [paths...]``.

Runs BOTH analysis passes over the same surface against one shared
baseline: graftlint (per-node AST rules R1-R8 + R13) and graftflow (the
interprocedural dataflow rules R9-R12). Exit status 0 when the tree is
clean modulo the checked-in baseline, 1 when new violations or dead
baseline entries exist, 2 on usage errors. Runs stdlib-only (no JAX
import), so it is safe as the first stage of ``make lint`` / the sweep
harness even on machines with no accelerator runtime.

Machine-readable outputs:

- ``--json``: one JSON object on stdout with PER-RULE new/baselined
  counts plus stale/dead fingerprints — the Makefile ratchet (and
  ``tools/lint_report.py``) can then distinguish "new R9 finding" from
  "stale baseline entry" without scraping the text report.
- ``--sarif PATH``: the combined run's NEW findings as a SARIF 2.1.0 log
  (CI annotation ingestion; rule catalog embedded).
"""

from __future__ import annotations

import json
import pathlib
import sys

from .graftflow import FLOW_RULES, flow_project
from .graftlint import (
    RULES,
    _iter_py_files,
    apply_baseline,
    find_dead_scopes,
    lint_text,
    load_baseline,
    write_baseline,
)

ALL_RULES = {**RULES, **FLOW_RULES}

_PKG_DIR = pathlib.Path(__file__).resolve().parent.parent  # the package
_REPO_ROOT = _PKG_DIR.parent
_DEFAULT_BASELINE = pathlib.Path(__file__).resolve().parent / "graftlint_baseline.json"
#: default lint surface: the package plus the perf-harness scripts that sit
#: on the same hot paths (tests are excluded — their fixtures intentionally
#: contain violating snippets)
_DEFAULT_TARGETS = [_PKG_DIR, _REPO_ROOT / "tools", _REPO_ROOT / "bench.py"]


def run_analyses(targets, rules):
    """Both passes over ``targets``; one combined, ordered violation list.

    The surface is read ONCE and the {path: source} map is fed to both
    passes — the two-pass gate must not pay double file I/O + ast.parse
    (the <= 10 s wall budget is a tier-1 acceptance)."""
    lint_rules = rules & set(RULES)
    flow_rules = rules & set(FLOW_RULES)
    sources = {}
    for f in _iter_py_files(targets):
        try:
            source = f.read_text()
        except (OSError, UnicodeDecodeError):
            continue
        try:
            rel = f.resolve().relative_to(_REPO_ROOT.resolve()).as_posix()
        except ValueError:
            rel = f.as_posix()
        sources[rel] = source
    violations = []
    if lint_rules:
        for rel, source in sources.items():
            try:
                violations.extend(lint_text(source, rel, rules=lint_rules))
            except SyntaxError:
                continue
    if flow_rules:
        violations.extend(flow_project(sources, rules=flow_rules))
    violations.sort(key=lambda v: (v.path, v.line, v.rule))
    return violations


def _per_rule_counts(res) -> dict:
    out = {rid: {"new": 0, "baselined": 0} for rid in sorted(ALL_RULES)}
    for v in res.new:
        out.setdefault(v.rule, {"new": 0, "baselined": 0})["new"] += 1
    for v in res.accepted:
        out.setdefault(v.rule, {"new": 0, "baselined": 0})["baselined"] += 1
    return out


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="graftlint",
        description="JAX-hazard lint: graftlint (R1-R8, R13) + graftflow (R9-R12)",
    )
    ap.add_argument(
        "paths",
        nargs="*",
        type=pathlib.Path,
        help="files/dirs to lint (default: the package, tools/, bench.py)",
    )
    ap.add_argument(
        "--rules",
        default=",".join(sorted(ALL_RULES)),
        help="comma-separated rule subset (default: all of R1-R12)",
    )
    ap.add_argument(
        "--baseline",
        type=pathlib.Path,
        default=_DEFAULT_BASELINE,
        help="baseline JSON of accepted sites (shared by both passes)",
    )
    ap.add_argument(
        "--no-baseline",
        action="store_true",
        help="report every violation, ignoring the baseline",
    )
    ap.add_argument(
        "--write-baseline",
        action="store_true",
        help="accept the current violations as the new baseline",
    )
    ap.add_argument(
        "--json",
        action="store_true",
        help="machine-readable summary with per-rule counts on stdout",
    )
    ap.add_argument(
        "--sarif",
        type=pathlib.Path,
        default=None,
        metavar="PATH",
        help="write NEW findings as a SARIF 2.1.0 log for CI annotations",
    )
    ap.add_argument(
        "--quiet", action="store_true", help="summary line only"
    )
    args = ap.parse_args(argv)

    rules = {r.strip() for r in args.rules.split(",") if r.strip()}
    unknown = rules - set(ALL_RULES)
    if unknown:
        print(f"graftlint: unknown rule(s): {', '.join(sorted(unknown))}")
        return 2

    if args.paths:
        # an explicit path that doesn't exist is a usage error, not a
        # clean run — a typo'd CI invocation must not turn the gate green
        missing = [p for p in args.paths if not p.exists()]
        if missing:
            print(
                "graftlint: no such path(s): "
                + ", ".join(str(p) for p in missing)
            )
            return 2
        targets = list(args.paths)
    else:
        targets = [p for p in _DEFAULT_TARGETS if p.exists()]
    violations = run_analyses(targets, rules)

    if args.write_baseline:
        if args.json or args.sarif is not None:
            # --write-baseline short-circuits reporting: honoring the
            # combination silently (no SARIF file, non-JSON stdout) would
            # break whatever pipeline asked for it — refuse loudly
            print(
                "graftlint: --write-baseline cannot be combined with "
                "--json/--sarif (it writes the baseline and exits)"
            )
            return 2
        if args.paths and args.baseline == _DEFAULT_BASELINE:
            # a partial lint surface must not clobber the repo-wide
            # baseline (it would drop every accepted site outside `paths`)
            print(
                "graftlint: refusing --write-baseline for explicit paths "
                "into the default baseline; pass --baseline PATH"
            )
            return 2
        write_baseline(args.baseline, violations)
        print(
            f"graftlint: baseline written to {args.baseline} "
            f"({len(violations)} accepted sites)"
        )
        return 0

    baseline = {} if args.no_baseline else load_baseline(args.baseline)
    res = apply_baseline(violations, baseline)
    # stale-DEBT detector: a baseline entry whose file/scope is gone from
    # the source can never be repaid — it only masks a future violation
    # that happens to reuse the fingerprint. Fail, don't warn. A dead
    # entry necessarily also matched no violation, so drop it from the
    # (warn-only) stale list — one entry, one verdict. Applies to both
    # passes: the fingerprints share one grammar and one file.
    dead = find_dead_scopes(baseline, _REPO_ROOT)
    stale = [fp for fp in res.stale if fp not in set(dead)]

    if args.sarif is not None:
        from .sarif import write_sarif

        write_sarif(args.sarif, res.new, ALL_RULES)

    if args.json:
        print(
            json.dumps(
                {
                    "new": len(res.new),
                    "baselined": len(res.accepted),
                    "stale": stale,
                    "dead": dead,
                    "per_rule": _per_rule_counts(res),
                    "targets": len(targets),
                    "rules": sorted(rules),
                    "violations": [
                        {
                            "path": v.path,
                            "line": v.line,
                            "rule": v.rule,
                            "scope": v.scope,
                            "message": v.message,
                        }
                        for v in res.new
                    ],
                },
                indent=2,
            )
        )
        return 1 if (res.new or dead) else 0

    if not args.quiet:
        for v in res.new:
            print(v.render())
        for fp in stale:
            print(f"graftlint: stale baseline entry (fixed? regenerate): {fp}")
    for fp in dead:
        print(
            "graftlint: DEAD baseline entry (scope gone from source — "
            f"delete it or regenerate the baseline): {fp}"
        )
    print(
        f"graftlint: {len(res.new)} new, {len(res.accepted)} baselined, "
        f"{len(stale)} stale, {len(dead)} dead baseline entries "
        f"({len(targets)} target(s), rules {','.join(sorted(rules))})"
    )
    return 1 if (res.new or dead) else 0


if __name__ == "__main__":
    sys.exit(main())

"""Static analysis + runtime contracts for the JAX engine.

Three layers, one goal — stop the ADVICE.md hazard classes from regressing
silently:

- ``graftlint``: stdlib-only per-node AST lint (rules R1-R8) over the
  package; syntactic pass.
- ``graftflow``: stdlib-only interprocedural DATAFLOW lint (rules R9-R12)
  over the same surface — per-function CFG walks plus a project-wide
  call/thread-reachability graph (lock-discipline races, use-after-donate,
  static-arg recompile risk, shard_map axis-name drift). Shares
  graftlint's disable-comment grammar and baseline file.
- ``contracts``: cheap runtime shape/dtype contracts on the Frontier /
  PaddedTour boundaries plus a jit recompilation guard for fixed-shape hot
  loops (wired into tier-1 tests).

The CLI ``python -m tsp_mpi_reduction_tpu.analysis`` (wired into
``make lint``) runs BOTH static passes against the one shared baseline;
``--json`` adds per-rule counts, ``--sarif PATH`` emits SARIF 2.1.0.

``graftlint``/``graftflow`` must stay importable without JAX (they run
before any backend exists), so this package init deliberately does NOT
import ``contracts`` eagerly — import it as ``from
tsp_mpi_reduction_tpu.analysis import contracts`` where needed.
"""

from .graftflow import (  # noqa: F401
    FLOW_RULES,
    flow_paths,
    flow_project,
    flow_text,
)
from .graftlint import (  # noqa: F401
    RULES,
    Violation,
    apply_baseline,
    lint_paths,
    lint_text,
    load_baseline,
    write_baseline,
)

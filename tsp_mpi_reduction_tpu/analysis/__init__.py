"""Static analysis + runtime contracts for the JAX engine.

Two layers, one goal — stop the ADVICE.md hazard classes from regressing
silently:

- ``graftlint``: stdlib-only AST lint (rules R1-R5) over the package; CLI is
  ``python -m tsp_mpi_reduction_tpu.analysis`` (wired into ``make lint``).
- ``contracts``: cheap runtime shape/dtype contracts on the Frontier /
  PaddedTour boundaries plus a jit recompilation guard for fixed-shape hot
  loops (wired into tier-1 tests).

``graftlint`` must stay importable without JAX (it runs before any backend
exists), so this package init deliberately does NOT import ``contracts``
eagerly — import it as ``from tsp_mpi_reduction_tpu.analysis import
contracts`` where needed.
"""

from .graftlint import (  # noqa: F401
    RULES,
    Violation,
    apply_baseline,
    lint_paths,
    lint_text,
    load_baseline,
    write_baseline,
)

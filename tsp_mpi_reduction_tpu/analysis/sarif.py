"""SARIF 2.1.0 emitter for graftlint + graftflow findings.

SARIF (Static Analysis Results Interchange Format, OASIS standard) is the
lingua franca CI systems ingest for inline code annotations — one schema,
every viewer. This module renders a violation list as one ``run`` of one
``tool.driver`` ("graftlint" — the combined R1-R12 gate), with the rule
catalog embedded so viewers can show per-rule help without this repo.

Kept deliberately minimal-but-valid against the 2.1.0 schema: required
properties only, plus ``snippet``/``uriBaseId`` which every renderer uses.
Stdlib-only, like the passes themselves.
"""

from __future__ import annotations

import json
import pathlib
from typing import Dict, Mapping, Optional, Sequence

from .graftlint import Violation

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = (
    "https://docs.oasis-open.org/sarif/sarif/v2.1.0/errata01/os/schemas/"
    "sarif-schema-2.1.0.json"
)


def to_sarif(
    violations: Sequence[Violation],
    rules: Mapping[str, str],
    tool_name: str = "graftlint",
    base_uri: Optional[str] = None,
) -> Dict:
    """Render ``violations`` as a SARIF 2.1.0 log dict.

    ``rules`` is the id -> short-description catalog (the combined
    ``RULES`` + ``FLOW_RULES`` map); every rule is emitted in the driver
    catalog even when clean, so CI trend lines keep stable rule indices.
    """
    rule_ids = sorted(rules)
    rule_index = {rid: i for i, rid in enumerate(rule_ids)}
    results = []
    for v in violations:
        results.append(
            {
                "ruleId": v.rule,
                "ruleIndex": rule_index.get(v.rule, -1),
                "level": "error",
                "message": {"text": f"[{v.scope}] {v.message}"},
                "locations": [
                    {
                        "physicalLocation": {
                            "artifactLocation": {
                                "uri": v.path,
                                **(
                                    {"uriBaseId": "SRCROOT"}
                                    if base_uri
                                    else {}
                                ),
                            },
                            "region": {
                                "startLine": v.line,
                                "snippet": {"text": v.code},
                            },
                        }
                    }
                ],
                # the baseline's line-free identity, so CI can dedupe
                # across pushes exactly like the ratchet does
                "partialFingerprints": {"graftlint/v1": v.fingerprint},
            }
        )
    run: Dict = {
        "tool": {
            "driver": {
                "name": tool_name,
                "informationUri": (
                    "https://github.com/tsp-mpi-reduction-tpu"
                    "#static-analysis--runtime-contracts"
                ),
                "rules": [
                    {
                        "id": rid,
                        "shortDescription": {"text": rules[rid]},
                    }
                    for rid in rule_ids
                ],
            }
        },
        "columnKind": "utf16CodeUnits",
        "results": results,
    }
    if base_uri:
        run["originalUriBaseIds"] = {"SRCROOT": {"uri": base_uri}}
    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [run],
    }


def write_sarif(
    path: pathlib.Path,
    violations: Sequence[Violation],
    rules: Mapping[str, str],
    tool_name: str = "graftlint",
    base_uri: Optional[str] = None,
) -> None:
    """Serialize :func:`to_sarif` to ``path`` (UTF-8, trailing newline).

    The write is small and non-durable (CI artifact, regenerated every
    run), so a plain write is fine — and the tmp-suffix keeps graftlint
    R6 satisfied by construction when callers pass temp paths."""
    doc = to_sarif(violations, rules, tool_name=tool_name, base_uri=base_uri)
    path.write_text(json.dumps(doc, indent=2) + "\n")

"""Runtime contracts: boundary shape/dtype asserts + a jit recompilation guard.

The static pass (``graftlint``) catches hazard *patterns*; this layer catches
the two failure classes that only exist at runtime:

- **Boundary corruption.** ``Frontier`` and ``PaddedTour`` are bare
  NamedTuples of arrays — nothing stops a caller from handing a float64
  buffer, a transposed node matrix, or a row width that doesn't invert to a
  valid ``(n, W)`` layout. ``check_frontier`` / ``check_padded_tour`` verify
  the structural invariants using METADATA ONLY (shape + dtype — no device
  sync, safe inside a trace), so they are cheap enough to stay on in
  production. ``TSP_CONTRACTS=strict`` adds value-level checks (count within
  the buffer, length within capacity) that sync concrete arrays to host —
  test-suite territory. ``TSP_CONTRACTS=off`` disables everything.

- **Silent recompilation.** A fixed-shape hot loop that re-jits every call
  turns a microsecond dispatch into a multi-second compile — and nothing in
  JAX fails loudly when it happens (the round-5 TPU campaign found exactly
  this through wall-clock archaeology). ``RecompilationGuard`` snapshots the
  jit caches of named entry points (via ``jitted._cache_size()``) and raises
  ``RecompilationError`` when a guarded region compiles more entries than its
  budget. Tier-1 runs the B&B expand loop under a zero-budget guard after
  warmup, so a shape leak (weak-typed scalar, python float promoted per
  iteration, changed static arg) fails the suite instead of shipping a 100x
  slowdown.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Mapping, Optional, Tuple

import jax
import numpy as np


class ContractError(ValueError):
    """A structural invariant on a kernel boundary was violated.

    Subclasses ValueError so existing callers that wrap kernel entry
    points in ``except ValueError`` (e.g. the CLI's clean exit-2 path)
    treat contract failures like any other input-validation error."""


class RecompilationError(ContractError):
    """A guarded fixed-shape region triggered unexpected jit compiles."""


def level() -> str:
    """Contract level: "off", "on" (default; metadata checks only), or
    "strict" (adds value checks that sync concrete arrays)."""
    val = os.environ.get("TSP_CONTRACTS", "on").strip().lower()
    return val if val in ("off", "on", "strict") else "on"


def _is_concrete(x: Any) -> bool:
    return not isinstance(x, jax.core.Tracer)


def _shape(x: Any) -> tuple:
    return tuple(np.shape(x))


def _dtype(x: Any) -> np.dtype:
    """dtype of an array/tracer, mapping plain python scalars through
    numpy's defaults (a bare float cost is a legal scalar leaf)."""
    dt = getattr(x, "dtype", None)
    return np.dtype(dt) if dt is not None else np.dtype(type(x))


def _frontier_layout(cols: int) -> Optional[Tuple[int, int]]:
    """Invert the v2 packed-row width ``cols = ceil(n/4) + ceil(n/32) + 4``
    to the inclusive ``(n_lo, n_hi)`` range of consistent city counts, or
    None if no n fits (mirrors models.branch_bound._layout, duplicated so
    the analysis package never imports the engine). Byte-packing makes n
    ambiguous within one path-word cell, but the cell itself is unique —
    see the engine's _layout docstring."""
    lo = hi = None
    for n in range(1, min((cols - 5) * 4, 32 * (cols - 5)) + 1):
        if (n + 3) // 4 + (n + 31) // 32 + 4 == cols:
            lo = n if lo is None else lo
            hi = n
    return None if lo is None else (lo, hi)


def _fail(where: str, msg: str) -> None:
    prefix = f"{where}: " if where else ""
    raise ContractError(f"contract violation: {prefix}{msg}")


def check_frontier(fr, *, n: Optional[int] = None, where: str = ""):
    """Validate a Frontier's structural invariants; returns ``fr``.

    Accepts single-device ``[F, cols]`` node buffers and sharded stacked
    ``[R, F, cols]`` ones. Metadata-only by default (tracer-safe).
    """
    lv = level()
    if lv == "off":
        return fr
    nodes, count, overflow = fr.nodes, fr.count, fr.overflow
    if nodes.ndim not in (2, 3):
        _fail(where, f"Frontier.nodes must be [F, cols] or [R, F, cols], got {nodes.shape}")
    if nodes.dtype != np.int32:
        _fail(where, f"Frontier.nodes must be int32 packed rows, got {nodes.dtype}")
    cols = nodes.shape[-1]
    rng = _frontier_layout(cols)
    if rng is None:
        _fail(where, f"Frontier row width {cols} inverts to no valid (n, W) layout")
    if n is not None and not rng[0] <= n <= rng[1]:
        _fail(
            where,
            f"Frontier row width {cols} encodes n in [{rng[0]}, {rng[1]}], "
            f"expected n={n}",
        )
    want_count_shape = () if nodes.ndim == 2 else nodes.shape[:1]
    if tuple(count.shape) != want_count_shape:
        _fail(where, f"Frontier.count shape {count.shape}, expected {want_count_shape}")
    if not np.issubdtype(count.dtype, np.integer):
        _fail(where, f"Frontier.count must be integer, got {count.dtype}")
    if overflow.dtype != np.bool_:
        _fail(where, f"Frontier.overflow must be bool, got {overflow.dtype}")
    if lv == "strict" and _is_concrete(count) and _is_concrete(nodes):
        cnt = np.asarray(count)
        rows = nodes.shape[-2]
        if (cnt < 0).any() or (cnt > rows).any():
            _fail(where, f"Frontier.count {cnt} outside [0, {rows}] buffer rows")
    return fr


def check_frontier_packed(fr, n: int, *, where: str = ""):
    """Value-level contract on the v2 int8-packed row layout (ISSUE 8):
    every LIVE row's packed path must be well-formed for instance size
    ``n`` — city-id bytes < n at prefix positions below ``depth``, and
    pad lanes past n all zero (the invariant that keeps the byte-set
    kernels exact and the host pack/unpack bit-stable). Runs the cheap
    structural :func:`check_frontier` first; the byte checks are
    STRICT-level only (they unpack concrete arrays — test territory,
    like the other value checks). Returns ``fr``.
    """
    lv = level()
    if lv == "off":
        return fr
    check_frontier(fr, n=n, where=where)
    if (
        lv != "strict"
        or not _is_concrete(fr.nodes)
        or not _is_concrete(fr.count)
    ):
        return fr
    nodes = np.asarray(fr.nodes)
    counts = np.atleast_1d(np.asarray(fr.count))
    rows2d = nodes.reshape(-1, nodes.shape[-2], nodes.shape[-1])
    pw = (n + 3) // 4
    for r in range(rows2d.shape[0]):
        live = rows2d[r, : int(counts[r])]
        if not live.size:
            continue
        words = np.ascontiguousarray(live[:, :pw]).view(np.uint32)
        shifts = (np.arange(4, dtype=np.uint32) * 8)
        lanes = ((words[:, :, None] >> shifts) & np.uint32(0xFF)).reshape(
            live.shape[0], -1
        )
        depth = live[:, -4]
        pos = np.arange(lanes.shape[1])[None, :]
        in_prefix = pos < depth[:, None]
        if (lanes[in_prefix & (pos < n)] >= n).any():
            _fail(
                where,
                f"packed path carries a city id >= n={n} inside a live "
                "prefix (corrupt byte-packed row)",
            )
        if lanes[:, n:].any():
            _fail(
                where,
                f"packed path pad lanes past n={n} are non-zero "
                "(byte-set wrote outside the prefix)",
            )
    return fr


def check_exchange_count(count, capacity: int, *, where: str = ""):
    """Value-level contract on a reservoir exchange/refill boundary: the
    kept (on-device) row count must lie in ``[0, capacity // 2]`` — the
    best-half invariant every reservoir path promises. A count above it
    re-arms exactly the capacity pressure the reservoir exists to shed
    (the next inner batch could overflow-drop children); a negative one
    corrupts every downstream masked scan. Host ints only — no device
    sync — so it stays on at the default level.
    """
    if level() == "off":
        return count
    lim = max(capacity // 2, 0)
    if not 0 <= int(count) <= lim:
        _fail(
            where,
            f"exchange kept {int(count)} rows, outside [0, {lim}] "
            f"(capacity {capacity})",
        )
    return count


def check_donated(buf, *, where: str = ""):
    """Post-dispatch contract on a DONATED buffer: the caller's old
    reference must be consumed (``is_deleted``), i.e. the dispatch really
    aliased the frontier in place and any later re-read of the stale
    handle would raise instead of returning old bytes. Metadata-only (no
    device sync), so it stays on at the default level.

    Failing here means donation silently did NOT happen — the jit lost its
    ``donate_argnames`` (graftlint R7 guards the static side of this), the
    buffer was an unexpected alias of another live input, or the backend
    refused the donation — and the multi-hundred-MB buffer is being copied
    per dispatch again.
    """
    if level() == "off":
        return buf
    deleted = getattr(buf, "is_deleted", None)
    if deleted is not None and not deleted():
        _fail(
            where,
            "donated frontier buffer is still live after the dispatch — "
            "donation did not alias (per-dispatch full-buffer copy)",
        )
    return buf


def check_padded_tour(t, *, capacity: Optional[int] = None, where: str = ""):
    """Validate a PaddedTour's structural invariants; returns ``t``.

    Accepts scalar tours (``ids [P]``) and batched ones (``ids [..., P]``
    with matching-batch length/cost), as produced by the vmapped folds.
    """
    lv = level()
    if lv == "off":
        return t
    ids, length, cost = t.ids, t.length, t.cost
    if len(_shape(ids)) < 1:
        _fail(where, f"PaddedTour.ids must have a capacity axis, got shape {_shape(ids)}")
    if _dtype(ids) != np.int32:
        _fail(where, f"PaddedTour.ids must be int32 city ids, got {_dtype(ids)}")
    batch = _shape(ids)[:-1]
    if _shape(length) != batch:
        _fail(where, f"PaddedTour.length shape {_shape(length)} != batch {batch}")
    if _shape(cost) != batch:
        _fail(where, f"PaddedTour.cost shape {_shape(cost)} != batch {batch}")
    if not np.issubdtype(_dtype(length), np.integer):
        _fail(where, f"PaddedTour.length must be integer, got {_dtype(length)}")
    if not np.issubdtype(_dtype(cost), np.floating):
        _fail(where, f"PaddedTour.cost must be floating, got {_dtype(cost)}")
    if capacity is not None and _shape(ids)[-1] != capacity:
        _fail(where, f"PaddedTour capacity {_shape(ids)[-1]}, expected {capacity}")
    if lv == "strict" and _is_concrete(length):
        ln = np.asarray(length)
        if (ln < 0).any() or (ln > ids.shape[-1]).any():
            _fail(where, f"PaddedTour.length {ln} outside [0, {ids.shape[-1]}]")
    return t


# -- recompilation guard ------------------------------------------------------


def jit_cache_size(fn) -> Optional[int]:
    """Number of compiled entries in a jitted callable's cache, or None if
    the callable doesn't expose one (plain python function, older jax)."""
    size = getattr(fn, "_cache_size", None)
    if size is None:
        return None
    try:
        return int(size())
    except Exception:
        return None


class RecompilationGuard:
    """Fail when named jit entry points compile more than ``limit`` new
    cache entries inside the guarded region.

    >>> with RecompilationGuard({"expand": _expand_step}, limit=0):
    ...     for _ in range(100):
    ...         fr, *_ = _expand_step(fr, ...)   # fixed shapes: 0 compiles

    Entry points must be jitted callables (``jax.jit`` / ``pjit`` results —
    anything exposing ``_cache_size()``). A fixed-shape loop warmed up
    before entry must stay at zero misses; ``limit`` budgets intentional
    first-call compiles when warmup happens inside the region.
    """

    def __init__(self, entries: Mapping[str, Any], limit: int = 0):
        unknown = [k for k, fn in entries.items() if jit_cache_size(fn) is None]
        if unknown:
            raise ValueError(
                f"not jitted callables (no _cache_size): {', '.join(unknown)}"
            )
        self.entries = dict(entries)
        self.limit = int(limit)
        self._before: Dict[str, int] = {}

    def __enter__(self) -> "RecompilationGuard":
        self._before = {k: jit_cache_size(fn) for k, fn in self.entries.items()}
        return self

    def misses(self) -> Dict[str, int]:
        """New cache entries per entry point since ``__enter__``."""
        return {
            k: jit_cache_size(fn) - self._before.get(k, 0)
            for k, fn in self.entries.items()
        }

    def check(self) -> None:
        over = {k: m for k, m in self.misses().items() if m > self.limit}
        if over:
            detail = ", ".join(
                f"{k}: {m} new compile(s) (budget {self.limit})"
                for k, m in sorted(over.items())
            )
            raise RecompilationError(
                "fixed-shape region recompiled — a shape/dtype/static-arg is "
                f"churning per call: {detail}"
            )

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc_type is None:  # don't mask the region's own exception
            self.check()

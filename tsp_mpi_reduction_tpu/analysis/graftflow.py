"""graftflow: flow-aware, interprocedural dataflow lint (rules R9-R12).

graftlint (the sibling pass) judges one AST node at a time; the hazard
classes below are invisible at that altitude because the *defect* is a
relationship between program points — an attribute access and the lock
that guards it three methods away, a buffer use and the jit dispatch that
consumed it ten lines earlier, a collective and the shard_map wrapper one
closure out. graftflow builds a small per-project dataflow IR instead:

- a **module index** (imports resolved across the lint surface, classes
  with their lock attributes and field types, every function/method with
  its qualified scope);
- a **call graph** with cheap type resolution (``self.m()``,
  ``self.field.m()`` via constructor/annotation field types, bare and
  module-aliased calls, locals assigned ``ClassName(...)``);
- a **thread-reachability closure** seeded at thread entry points
  (``threading.Thread(target=...)`` and ``executor.submit(f, ...)``
  with a function reference);
- per-function **flow state**: lexically-held locks (propagated into
  ``*_locked`` helpers via the intersection of held-locks at their call
  sites) and a donated-binding lattice walked over the statement graph.

The four rules (RacerD's compositional lock-consistency analysis and
NeuraLint's framework-pitfall graph rules are the ancestry — PAPERS.md):

  R9  lock-discipline race: each class's ``attribute -> guarding lock``
      map is inferred from writes performed while holding a lock
      (``with self._lock:`` blocks, plus methods only ever called with
      the lock held). In a class whose methods run on more than one
      thread (it spawns threads, or is reachable from a thread entry
      point), any access to a guarded attribute without that lock is a
      data race — including cross-object reads like
      ``self.ladder.tier_counts`` from another class. ``__init__`` is
      exempt (pre-publication), and the double-checked-locking idiom (an
      unlocked read re-checked under the same lock in the same method)
      is recognized, not flagged.
  R10 use-after-donate: a call into a donating jit entry (the R7
      registry: ``donate_argnums``/``donate_argnames``) CONSUMES the
      argument buffer — jax deletes the caller's handle. Any later use
      of that binding on any path that isn't a rebind from the call's
      result is flagged. ``contracts.check_donated`` is the sanctioned
      post-dispatch consumer check and is exempt. This makes the PR 5
      consumed-handle contract (today a runtime check on whatever paths
      a test happens to execute) a compile-time guarantee.
  R11 static-arg recompile risk: a jit entry's ``static_argnames`` /
      ``static_argnums`` parameter bound at a call site to an unhashable
      value (list/dict/set displays, comprehensions, numpy/jnp arrays —
      a TypeError at dispatch) or a per-call-varying one (f-strings,
      loop variables of unbounded loops — one silent XLA recompile per
      distinct value: the static sibling of the RecompilationGuard).
      Loop variables of bounded literal/range loops are the sanctioned
      precompile pattern (``scheduler.precompile``) and stay quiet.
  R12 collective/axis-name consistency: ``psum``/``ppermute``/
      ``all_gather``/``axis_index``/``pcast_varying``/... inside a
      ``shard_map`` body must name an axis the wrapping site declares
      (``P(...)`` specs, resolved through module constants like
      ``RANK_AXIS`` across files). A drifted axis name is an obscure
      trace-time error today and a wrong-mesh collective after the
      ROADMAP's 2D-mesh refactor. Sites whose axis names cannot be
      resolved statically are skipped, never guessed.

Escape hatches and baseline are SHARED with graftlint: the same
``# graftlint: disable=R9`` comment grammar (same line, line above, or
the ``def`` line), and the same ``graftlint_baseline.json`` fingerprint
machinery — one gate, one ratchet, one zero-entry contract.

Like graftlint, this pass is stdlib-only (``ast`` + ``tokenize``) and
must never import jax: it runs first in ``make lint`` on machines with
no accelerator runtime.

Known over-approximations (deliberate, lint-grade): method resolution is
name+type-based with no inheritance walk; objects handed around as bare
parameters are untyped (their classes are only checked when reached some
other way); module-global locks guarding module-global state are out of
the class-attribute model. Each limitation loses findings, not precision.
"""

from __future__ import annotations

import ast
import pathlib
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .graftlint import (
    Violation,
    _Directives,
    _dotted,
    _iter_py_files,
    _jit_call_parts,
    _traced_callee_names,
)

FLOW_RULES = {
    "R9": "thread-shared attribute accessed outside its guarding lock",
    "R10": "use of a buffer binding after it was donated to a jit entry",
    "R11": "jit static arg bound to an unhashable or per-call-varying value",
    "R12": "collective axis name not declared by the enclosing shard_map",
}

#: lock/condition factories whose targets become guard attributes
_LOCK_FACTORIES = frozenset(
    {"Lock", "RLock", "Condition"}
)
#: collective -> positional index of its axis-name argument
_AXIS_ARG = {
    "psum": 1, "pmin": 1, "pmax": 1, "pmean": 1, "ppermute": 1,
    "all_gather": 1, "psum_scatter": 1, "pbroadcast": 1, "all_to_all": 1,
    "pshuffle": 1, "pswapaxes": 1, "axis_index": 0, "axis_size": 0,
    "pcast_varying": 1,
}
#: spellings of the partition-spec constructor inside in_specs/out_specs
_SPEC_NAMES = frozenset({"P", "PartitionSpec"})
#: dotted-name suffixes exempt from R10 use checks (the sanctioned
#: post-dispatch consumer check reads the DELETED handle on purpose)
_DONATE_CHECK_SUFFIX = "check_donated"
#: numpy/array-producing roots whose results are unhashable (R11)
_ARRAY_ROOTS = frozenset({"np", "numpy", "jnp", "jax"})
#: method names that MUTATE their receiver (R9: ``self.q.append(x)`` is a
#: write to ``q`` for guard inference, like ``self.d[k] = v``)
_MUTATORS = frozenset(
    {
        "append", "extend", "insert", "add", "update", "pop", "popitem",
        "popleft", "appendleft", "extendleft", "remove", "discard",
        "clear", "setdefault", "sort", "reverse", "move_to_end",
    }
)


# -- IR dataclasses -----------------------------------------------------------


@dataclass
class Access:
    """One ``self.attr`` (or ``self.field.attr``) touch inside a method."""

    attr: str
    write: bool
    line: int
    method: str  # method name within the class
    held: frozenset  # lock attrs lexically held
    node: ast.AST
    #: for cross-object accesses: the ``self.<field>`` the attr hangs off
    via_field: Optional[str] = None


@dataclass
class ClassInfo:
    name: str
    module: "ModuleInfo"
    node: ast.ClassDef
    lock_attrs: Set[str] = field(default_factory=set)
    #: self.<field> -> (module_path, class_name) when statically known
    field_types: Dict[str, Tuple[str, str]] = field(default_factory=dict)
    methods: Dict[str, ast.AST] = field(default_factory=dict)
    properties: Set[str] = field(default_factory=set)
    accesses: List[Access] = field(default_factory=list)
    #: method -> [(callee method, held locks at the call)] intra-class
    intra_calls: Dict[str, List[Tuple[str, frozenset]]] = field(
        default_factory=dict
    )
    #: method -> locks certainly held at EVERY call site (fixpoint)
    entry_locks: Dict[str, frozenset] = field(default_factory=dict)
    spawns_threads: bool = False

    @property
    def qual(self) -> Tuple[str, str]:
        return (self.module.path, self.name)


@dataclass
class FuncInfo:
    qualname: str  # "Cls.meth" / "func" / "outer.inner"
    node: ast.AST
    module: "ModuleInfo"
    params: List[str]
    cls: Optional[ClassInfo] = None

    @property
    def key(self) -> Tuple[str, str]:
        return (self.module.path, self.qualname)


@dataclass
class JitEntry:
    """One jit-wrapped callable the project defines (R10/R11 registry)."""

    name: str  # binding name within its defining scope
    module: "ModuleInfo"
    params: Optional[List[str]]  # wrapped callable's params, if resolvable
    donate_names: Set[str] = field(default_factory=set)
    donate_nums: Set[int] = field(default_factory=set)
    static_names: Set[str] = field(default_factory=set)
    static_nums: Set[int] = field(default_factory=set)

    def donated_positions(self) -> Set[int]:
        out = set(self.donate_nums)
        if self.params:
            out |= {
                i for i, p in enumerate(self.params) if p in self.donate_names
            }
        return out

    def static_params(self) -> Set[str]:
        out = set(self.static_names)
        if self.params:
            out |= {
                p for i, p in enumerate(self.params) if i in self.static_nums
            }
        return out

    @property
    def donating(self) -> bool:
        return bool(self.donate_names or self.donate_nums)

    @property
    def has_statics(self) -> bool:
        return bool(self.static_names or self.static_nums)


@dataclass
class ModuleInfo:
    path: str  # repo-relative posix path
    dotted: str  # dotted module name relative to the lint root
    source: str
    tree: ast.Module
    directives: _Directives
    #: alias -> dotted module name ("canon" -> "pkg.serve.canonical")
    module_aliases: Dict[str, str] = field(default_factory=dict)
    #: local name -> (dotted module, symbol) for from-imports
    symbol_imports: Dict[str, Tuple[str, str]] = field(default_factory=dict)
    classes: Dict[str, ClassInfo] = field(default_factory=dict)
    functions: Dict[str, FuncInfo] = field(default_factory=dict)
    #: module-level NAME = "string constant"
    str_consts: Dict[str, str] = field(default_factory=dict)
    #: module-level NAME = ClassName(...) instance types
    global_types: Dict[str, Tuple[str, str]] = field(default_factory=dict)
    #: jit entries by binding name (module scope and function-local)
    jit_entries: Dict[str, JitEntry] = field(default_factory=dict)
    traced_callees: Set[str] = field(default_factory=set)


# -- project construction -----------------------------------------------------


def _module_dotted(path: str) -> str:
    parts = path[:-3].split("/") if path.endswith(".py") else path.split("/")
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


def _resolve_relative(mod: ModuleInfo, level: int, name: str) -> str:
    """``from ..x import y`` inside ``mod`` -> dotted target module."""
    base = mod.dotted.split(".")
    if not mod.path.endswith("__init__.py"):
        base = base[:-1]  # the module's package
    if level > 1:
        base = base[: len(base) - (level - 1)]
    return ".".join([p for p in base if p] + ([name] if name else []))


class Project:
    """The whole lint surface parsed once; modules keyed by repo-relative
    path AND by dotted name (for import resolution)."""

    def __init__(self) -> None:
        self.modules: Dict[str, ModuleInfo] = {}
        self.by_dotted: Dict[str, ModuleInfo] = {}
        #: (module_path, qualname) -> FuncInfo
        self.functions: Dict[Tuple[str, str], FuncInfo] = {}
        #: function key -> set of callee function keys
        self.call_edges: Dict[Tuple[str, str], Set[Tuple[str, str]]] = {}
        #: thread entry points (function keys)
        self.thread_roots: Set[Tuple[str, str]] = set()
        self.reachable: Set[Tuple[str, str]] = set()

    # -- loading -------------------------------------------------------------

    def add_module(self, path: str, source: str) -> Optional[ModuleInfo]:
        try:
            tree = ast.parse(source, filename=path)
        except SyntaxError:
            return None
        mod = ModuleInfo(
            path=path,
            dotted=_module_dotted(path),
            source=source,
            tree=tree,
            directives=_Directives(source),
        )
        mod.traced_callees = _traced_callee_names(tree)
        self.modules[path] = mod
        self.by_dotted[mod.dotted] = mod
        return mod

    def finalize(self) -> None:
        for mod in self.modules.values():
            self._scan_imports(mod)
            self._scan_toplevel(mod)
        for mod in self.modules.values():
            self._index_functions(mod)
        for mod in self.modules.values():
            self._scan_jit_entries(mod)
        for mod in self.modules.values():
            for cls in mod.classes.values():
                _ClassScanner(self, cls).scan()
        for mod in self.modules.values():
            self._scan_calls(mod)
        self._compute_entry_locks()
        self._compute_reachability()

    # -- imports / module-level bindings -------------------------------------

    def _scan_imports(self, mod: ModuleInfo) -> None:
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.asname:
                        mod.module_aliases[a.asname] = a.name
                    else:
                        # `import a.b.c` binds the ROOT package `a`
                        root = a.name.split(".")[0]
                        mod.module_aliases[root] = root
            elif isinstance(node, ast.ImportFrom):
                target = _resolve_relative(
                    mod, node.level, node.module or ""
                ) if node.level else (node.module or "")
                for a in node.names:
                    local = a.asname or a.name
                    # `from pkg import sub` can bind a MODULE
                    sub = f"{target}.{a.name}" if target else a.name
                    mod.symbol_imports[local] = (target, a.name)
                    mod.module_aliases.setdefault(local, sub)

    def _scan_toplevel(self, mod: ModuleInfo) -> None:
        for node in mod.tree.body:
            if isinstance(node, ast.ClassDef):
                mod.classes[node.name] = ClassInfo(node.name, mod, node)
            elif isinstance(node, ast.Assign) and len(node.targets) == 1:
                tgt = node.targets[0]
                if not isinstance(tgt, ast.Name):
                    continue
                if isinstance(node.value, ast.Constant) and isinstance(
                    node.value.value, str
                ):
                    mod.str_consts[tgt.id] = node.value.value
                elif isinstance(node.value, ast.Call):
                    cls = self._resolve_class_name(
                        mod, _dotted(node.value.func)
                    )
                    if cls is not None:
                        mod.global_types[tgt.id] = cls

    def _index_functions(self, mod: ModuleInfo) -> None:
        def walk(node: ast.AST, prefix: str, cls: Optional[ClassInfo]):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    q = f"{prefix}.{child.name}" if prefix else child.name
                    params = [
                        a.arg
                        for a in (
                            list(child.args.posonlyargs)
                            + list(child.args.args)
                        )
                    ]
                    owner = cls if prefix and cls and prefix == cls.name else (
                        cls if cls and not prefix else None
                    )
                    fi = FuncInfo(q, child, mod, params, owner)
                    mod.functions[q] = fi
                    self.functions[fi.key] = fi
                    if cls is not None and prefix == cls.name:
                        cls.methods[child.name] = child
                        for dec in child.decorator_list:
                            if _dotted(dec) == "property":
                                cls.properties.add(child.name)
                    walk(child, q, cls)
                elif isinstance(child, ast.ClassDef):
                    c = mod.classes.get(child.name)
                    walk(child, child.name if c else prefix, c or cls)
                else:
                    walk(child, prefix, cls)

        walk(mod.tree, "", None)

    # -- name resolution ------------------------------------------------------

    def _resolve_class_name(
        self, mod: ModuleInfo, dotted: Optional[str]
    ) -> Optional[Tuple[str, str]]:
        """Resolve a constructor-call name to a (module_path, class) in
        this project, through from-imports and module aliases."""
        if not dotted:
            return None
        head, _, rest = dotted.partition(".")
        if not rest:
            if head in mod.classes:
                return (mod.path, head)
            imp = mod.symbol_imports.get(head)
            if imp is not None:
                target = self.by_dotted.get(imp[0])
                if target is not None and imp[1] in target.classes:
                    return (target.path, imp[1])
            return None
        # mod_alias.ClassName
        target_name = mod.module_aliases.get(head)
        if target_name is not None and "." not in rest:
            target = self.by_dotted.get(target_name)
            if target is not None and rest in target.classes:
                return (target.path, rest)
        return None

    def _resolve_function(
        self, mod: ModuleInfo, dotted: Optional[str]
    ) -> Optional[Tuple[str, str]]:
        """Resolve a call name to a project function key (module functions
        and imported symbols; not methods)."""
        if not dotted:
            return None
        head, _, rest = dotted.partition(".")
        if not rest:
            if head in mod.functions:
                return (mod.path, head)
            imp = mod.symbol_imports.get(head)
            if imp is not None:
                target = self.by_dotted.get(imp[0])
                if target is not None and imp[1] in target.functions:
                    return (target.path, imp[1])
            return None
        target_name = mod.module_aliases.get(head)
        if target_name is not None and "." not in rest:
            target = self.by_dotted.get(target_name)
            if target is not None and rest in target.functions:
                return (target.path, rest)
        return None

    def _resolve_jit_entry(
        self, mod: ModuleInfo, fn: Optional[FuncInfo], dotted: Optional[str]
    ) -> Optional[JitEntry]:
        if not dotted:
            return None
        head, _, rest = dotted.partition(".")
        if not rest:
            if head in mod.jit_entries:
                return mod.jit_entries[head]
            imp = mod.symbol_imports.get(head)
            if imp is not None:
                target = self.by_dotted.get(imp[0])
                if target is not None:
                    return target.jit_entries.get(imp[1])
            return None
        target_name = mod.module_aliases.get(head)
        if target_name is not None and "." not in rest:
            target = self.by_dotted.get(target_name)
            if target is not None:
                return target.jit_entries.get(rest)
        return None

    def resolve_str(self, mod: ModuleInfo, node: ast.AST) -> Optional[str]:
        """Resolve an axis-name expression to a string constant, through
        module-level constants and cross-module from-imports."""
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return node.value
        name = _dotted(node)
        if not name:
            return None
        head, _, rest = name.partition(".")
        if not rest:
            if head in mod.str_consts:
                return mod.str_consts[head]
            imp = mod.symbol_imports.get(head)
            if imp is not None:
                target = self.by_dotted.get(imp[0])
                if target is not None:
                    return target.str_consts.get(imp[1])
            return None
        target_name = mod.module_aliases.get(head)
        if target_name is not None and "." not in rest:
            target = self.by_dotted.get(target_name)
            if target is not None:
                return target.str_consts.get(rest)
        return None

    # -- jit-entry registry (R10/R11) ----------------------------------------

    def _scan_jit_entries(self, mod: ModuleInfo) -> None:
        def params_of(fn_node) -> List[str]:
            return [
                a.arg
                for a in (
                    list(fn_node.args.posonlyargs) + list(fn_node.args.args)
                )
            ]

        def entry_from_kws(name, params, kws) -> Optional[JitEntry]:
            e = JitEntry(name=name, module=mod, params=params)
            for kw in kws:
                if kw.arg in ("donate_argnames", "static_argnames"):
                    vals = self._const_str_tuple(mod, kw.value)
                    if vals is None:
                        continue
                    (
                        e.donate_names
                        if kw.arg == "donate_argnames"
                        else e.static_names
                    ).update(vals)
                elif kw.arg in ("donate_argnums", "static_argnums"):
                    nums = _const_int_tuple(kw.value)
                    if nums is None:
                        continue
                    (
                        e.donate_nums
                        if kw.arg == "donate_argnums"
                        else e.static_nums
                    ).update(nums)
            return e if (e.donating or e.has_statics) else None

        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    is_jit, kws = _jit_call_parts(dec)
                    if not is_jit:
                        continue
                    e = entry_from_kws(node.name, params_of(node), kws)
                    if e is not None:
                        mod.jit_entries[node.name] = e
                    break
            elif isinstance(node, ast.Assign) and len(node.targets) == 1:
                tgt = node.targets[0]
                if not isinstance(tgt, ast.Name):
                    continue
                val = node.value
                kws: list = []
                wrapped: Optional[ast.AST] = None
                if isinstance(val, ast.Call):
                    is_jit, jkws = _jit_call_parts(val.func)
                    if is_jit and val.args:
                        # partial(jax.jit, ...)(f): kwargs live on the
                        # partial; jax.jit(f, ...): kwargs on the call
                        kws = list(jkws) + list(val.keywords)
                        wrapped = val.args[0]
                    else:
                        is_jit2, jkws2 = _jit_call_parts(val)
                        if is_jit2:
                            # bare partial(jax.jit, ...) binding (rare)
                            kws = list(jkws2) + list(val.keywords)
                if wrapped is None and not kws:
                    continue
                params = None
                if isinstance(wrapped, ast.Lambda):
                    params = [a.arg for a in wrapped.args.args]
                elif isinstance(wrapped, ast.Name):
                    fi = mod.functions.get(wrapped.id)
                    if fi is None:
                        # nested scope: match by bare name
                        for q, f in mod.functions.items():
                            if q.rsplit(".", 1)[-1] == wrapped.id:
                                fi = f
                                break
                    if fi is not None:
                        params = fi.params
                e = entry_from_kws(tgt.id, params, kws)
                if e is not None:
                    mod.jit_entries[tgt.id] = e

    def _const_str_tuple(
        self, mod: ModuleInfo, node: ast.AST
    ) -> Optional[List[str]]:
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return [node.value]
        if isinstance(node, (ast.Tuple, ast.List)):
            out = []
            for el in node.elts:
                if isinstance(el, ast.Constant) and isinstance(el.value, str):
                    out.append(el.value)
                else:
                    return None
            return out
        if isinstance(node, ast.Name):
            # module-level NAME = ("a", "b", ...) constant tuples
            for stmt in mod.tree.body:
                if (
                    isinstance(stmt, ast.Assign)
                    and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)
                    and stmt.targets[0].id == node.id
                ):
                    return self._const_str_tuple(mod, stmt.value)
        return None

    # -- call graph / threads -------------------------------------------------

    def _scan_calls(self, mod: ModuleInfo) -> None:
        for fi in mod.functions.values():
            edges: Set[Tuple[str, str]] = set()
            # closures see enclosing scopes' typed locals: merge outer
            # functions' types (inner bindings shadow outer ones)
            local_types: Dict[str, Tuple[str, str]] = {}
            parts = fi.qualname.split(".")
            for i in range(1, len(parts) + 1):
                outer = mod.functions.get(".".join(parts[:i]))
                if outer is not None:
                    local_types.update(
                        _local_var_types(self, mod, outer.node)
                    )
            for sub in ast.walk(fi.node):
                if not isinstance(sub, ast.Call):
                    continue
                self._edge_for_call(mod, fi, sub, local_types, edges)
                self._maybe_thread_root(mod, fi, sub, local_types)
            self.call_edges[fi.key] = edges

    def global_instance_type(
        self, mod: ModuleInfo, base: ast.AST
    ) -> Optional[Tuple[str, str]]:
        """Type of a module-global instance expression: ``NAME`` (local or
        from-imported) or ``mod_alias.NAME``."""
        if isinstance(base, ast.Name):
            t = mod.global_types.get(base.id)
            if t is not None:
                return t
            imp = mod.symbol_imports.get(base.id)
            if imp is not None:
                target = self.by_dotted.get(imp[0])
                if target is not None:
                    return target.global_types.get(imp[1])
            return None
        if (
            isinstance(base, ast.Attribute)
            and isinstance(base.value, ast.Name)
        ):
            target_name = mod.module_aliases.get(base.value.id)
            if target_name is not None:
                target = self.by_dotted.get(target_name)
                if target is not None:
                    return target.global_types.get(base.attr)
        return None

    def _method_key(
        self, cls_key: Tuple[str, str], meth: str
    ) -> Optional[Tuple[str, str]]:
        mod = self.modules.get(cls_key[0])
        if mod is None:
            return None
        cls = mod.classes.get(cls_key[1])
        if cls is None or meth not in cls.methods:
            return None
        return (mod.path, f"{cls.name}.{meth}")

    def _edge_for_call(self, mod, fi, call, local_types, edges) -> None:
        func = call.func
        if isinstance(func, ast.Attribute):
            base = func.value
            meth = func.attr
            # self.m(...)
            if isinstance(base, ast.Name) and base.id == "self" and fi.cls:
                key = self._method_key(fi.cls.qual, meth)
                if key:
                    edges.add(key)
                return
            # self.field.m(...)
            if (
                isinstance(base, ast.Attribute)
                and isinstance(base.value, ast.Name)
                and base.value.id == "self"
                and fi.cls is not None
            ):
                ftype = fi.cls.field_types.get(base.attr)
                if ftype:
                    key = self._method_key(ftype, meth)
                    if key:
                        edges.add(key)
                return
            # var.m(...) with a known local type
            if isinstance(base, ast.Name):
                vtype = local_types.get(base.id)
                if vtype:
                    key = self._method_key(vtype, meth)
                    if key:
                        edges.add(key)
                    return
            # GLOBAL.m(...) / mod_alias.GLOBAL.m(...) on a typed
            # module-level instance (the TRACER/REGISTRY singletons)
            gtype = self.global_instance_type(mod, base)
            if gtype:
                key = self._method_key(gtype, meth)
                if key:
                    edges.add(key)
                return
            # mod_alias.f(...)
            key = self._resolve_function(mod, _dotted(func))
            if key:
                edges.add(key)
            return
        if isinstance(func, ast.Name):
            # nested defs resolve innermost-scope-first (closure calls)
            key = self._nested_or_module_function(mod, fi, func.id)
            if key:
                edges.add(key)
                return
        name = _dotted(func)
        # constructor call -> __init__ edge
        cls_key = self._resolve_class_name(mod, name)
        if cls_key:
            key = self._method_key(cls_key, "__init__")
            if key:
                edges.add(key)

    def _nested_or_module_function(
        self, mod: ModuleInfo, fi: FuncInfo, name: str
    ) -> Optional[Tuple[str, str]]:
        prefix = fi.qualname
        while True:
            q = f"{prefix}.{name}" if prefix else name
            if q in mod.functions:
                return (mod.path, q)
            if not prefix:
                break
            prefix = prefix.rsplit(".", 1)[0] if "." in prefix else ""
        return self._resolve_function(mod, name)

    def _fn_ref_key(
        self, mod, fi, node, local_types
    ) -> Optional[Tuple[str, str]]:
        """Resolve a function REFERENCE expression (not a call) to a
        project function key — thread targets and executor submissions."""
        if isinstance(node, ast.Attribute):
            base = node.value
            if isinstance(base, ast.Name) and base.id == "self" and fi.cls:
                return self._method_key(fi.cls.qual, node.attr)
            if isinstance(base, ast.Name):
                vtype = local_types.get(base.id) or mod.global_types.get(
                    base.id
                )
                if vtype:
                    return self._method_key(vtype, node.attr)
            return self._resolve_function(mod, _dotted(node))
        if isinstance(node, ast.Name):
            # nested def in the same enclosing scope first
            prefix = fi.qualname
            while True:
                q = f"{prefix}.{node.id}" if prefix else node.id
                if q in mod.functions:
                    return (mod.path, q)
                if not prefix:
                    break
                prefix = prefix.rsplit(".", 1)[0] if "." in prefix else ""
            return self._resolve_function(mod, node.id)
        return None

    def _maybe_thread_root(self, mod, fi, call, local_types) -> None:
        name = _dotted(call.func) or ""
        is_thread = name.rsplit(".", 1)[-1] == "Thread"
        is_submit = (
            isinstance(call.func, ast.Attribute)
            and call.func.attr == "submit"
            and self._executor_receiver(mod, call.func.value, local_types)
        )
        target_expr = None
        if is_thread:
            for kw in call.keywords:
                if kw.arg == "target":
                    target_expr = kw.value
        elif is_submit and call.args:
            target_expr = call.args[0]
        else:
            return
        if target_expr is None:
            return
        key = self._fn_ref_key(mod, fi, target_expr, local_types)
        if key is not None:
            self.thread_roots.add(key)
            if is_thread and fi.cls is not None:
                fi.cls.spawns_threads = True

    def _executor_receiver(self, mod, recv, local_types) -> bool:
        """Is ``.submit``'s receiver plausibly a thread-pool executor?
        A project class's own ``submit`` (the micro-batch scheduler's
        takes DATA) must not turn its first argument into a phantom
        thread root, so: never a project-typed receiver, and the
        receiver's name must say executor/pool (stdlib executors are
        invisible to the index, names are the only signal left)."""
        if isinstance(recv, ast.Name):
            rtype = local_types.get(recv.id) or mod.global_types.get(recv.id)
            if rtype is not None:
                return False  # a project class: its submit takes work items
        path = (_attr_path(recv) or "").rsplit(".", 1)[-1].lower()
        return "pool" in path or "executor" in path or path == "ex"

    # -- fixpoints ------------------------------------------------------------

    def _compute_entry_locks(self) -> None:
        """A method only ever called with lock L held effectively holds L
        for its whole body (``*_locked`` helpers). Intersection over call
        sites, iterated to fixpoint."""
        for mod in self.modules.values():
            for cls in mod.classes.values():
                entry = {m: None for m in cls.methods}  # None = no info yet
                for _ in range(len(cls.methods) + 1):
                    changed = False
                    incoming: Dict[str, Optional[frozenset]] = {
                        m: None for m in cls.methods
                    }
                    for caller, calls in cls.intra_calls.items():
                        caller_entry = entry.get(caller) or frozenset()
                        for callee, held in calls:
                            eff = frozenset(held) | caller_entry
                            cur = incoming.get(callee)
                            incoming[callee] = (
                                eff if cur is None else (cur & eff)
                            )
                    for m in cls.methods:
                        new = incoming[m] or frozenset()
                        if entry[m] != new:
                            entry[m] = new
                            changed = True
                    if not changed:
                        break
                cls.entry_locks = {
                    m: (v or frozenset()) for m, v in entry.items()
                }

    def _compute_reachability(self) -> None:
        seen = set(self.thread_roots)
        frontier = list(seen)
        while frontier:
            key = frontier.pop()
            for nxt in self.call_edges.get(key, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    frontier.append(nxt)
        self.reachable = seen

    def concurrent_classes(self) -> Set[Tuple[str, str]]:
        out: Set[Tuple[str, str]] = set()
        for mod in self.modules.values():
            for cls in mod.classes.values():
                if cls.spawns_threads:
                    out.add(cls.qual)
                    continue
                for m in cls.methods:
                    if (mod.path, f"{cls.name}.{m}") in self.reachable:
                        out.add(cls.qual)
                        break
        return out


def _const_int_tuple(node: ast.AST) -> Optional[List[int]]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for el in node.elts:
            if isinstance(el, ast.Constant) and isinstance(el.value, int):
                out.append(el.value)
            else:
                return None
        return out
    return None


def _local_var_types(
    project: Project, mod: ModuleInfo, fn_node: ast.AST
) -> Dict[str, Tuple[str, str]]:
    """name -> class for locals assigned ``ClassName(...)`` (also through
    ``a or ClassName(...)``) and parameters annotated with a project class
    (``Optional[X]`` unwrapped)."""
    out: Dict[str, Tuple[str, str]] = {}

    def class_of_expr(expr) -> Optional[Tuple[str, str]]:
        if isinstance(expr, ast.Call):
            return project._resolve_class_name(mod, _dotted(expr.func))
        if isinstance(expr, ast.BoolOp):
            for v in expr.values:
                c = class_of_expr(v)
                if c is not None:
                    return c
        return None

    if isinstance(fn_node, (ast.FunctionDef, ast.AsyncFunctionDef)):
        for a in list(fn_node.args.posonlyargs) + list(fn_node.args.args):
            ann = a.annotation
            if isinstance(ann, ast.Subscript):  # Optional[X] / Dict[...]
                ann = ann.slice
            c = project._resolve_class_name(mod, _dotted(ann)) if ann else None
            if c is not None:
                out[a.arg] = c
    for sub in ast.walk(fn_node):
        if isinstance(sub, ast.Assign) and len(sub.targets) == 1:
            tgt = sub.targets[0]
            if isinstance(tgt, ast.Name):
                c = class_of_expr(sub.value)
                if c is not None:
                    out[tgt.id] = c
    return out


# -- class scanning (R9 IR) ----------------------------------------------------


class _ClassScanner:
    """Collects a class's lock attrs, field types, and every ``self.*``
    access with the lexically-held lock set."""

    def __init__(self, project: Project, cls: ClassInfo):
        self.project = project
        self.cls = cls
        self.mod = cls.module

    def scan(self) -> None:
        # pass 1: lock attrs + field types (any method may declare them)
        for name, meth in self.cls.methods.items():
            for sub in ast.walk(meth):
                if not isinstance(sub, ast.Assign):
                    continue
                for tgt in sub.targets:
                    if not (
                        isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"
                    ):
                        continue
                    val = sub.value
                    if isinstance(val, ast.Call):
                        callee = _dotted(val.func) or ""
                        if callee.rsplit(".", 1)[-1] in _LOCK_FACTORIES:
                            self.cls.lock_attrs.add(tgt.attr)
                            continue
                        ftype = self.project._resolve_class_name(
                            self.mod, _dotted(val.func)
                        )
                        if ftype is not None:
                            self.cls.field_types[tgt.attr] = ftype
                    elif isinstance(val, ast.Name):
                        # self.x = param — use the param's annotation
                        types = _local_var_types(self.project, self.mod, meth)
                        ftype = types.get(val.id)
                        if ftype is not None:
                            self.cls.field_types[tgt.attr] = ftype
        # pass 2: accesses + intra-class calls, per method
        for name, meth in self.cls.methods.items():
            self.cls.intra_calls.setdefault(name, [])
            self._walk_stmt_list(meth.body, name, frozenset())

    # recursive statement walker tracking `with self.<lock>:` scopes
    def _walk_stmt_list(self, stmts, method: str, held: frozenset) -> None:
        for stmt in stmts:
            self._walk_stmt(stmt, method, held)

    def _walk_stmt(self, stmt, method: str, held: frozenset) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # nested def: runs later, the lexical lock is NOT guaranteed
            self._walk_stmt_list(stmt.body, method, frozenset())
            return
        if isinstance(stmt, ast.With):
            acquired = set()
            for item in stmt.items:
                self._visit_expr(item.context_expr, method, held)
                ce = item.context_expr
                if (
                    isinstance(ce, ast.Attribute)
                    and isinstance(ce.value, ast.Name)
                    and ce.value.id == "self"
                    and ce.attr in self.cls.lock_attrs
                ):
                    acquired.add(ce.attr)
            self._walk_stmt_list(stmt.body, method, held | acquired)
            return
        # visit this statement's own expressions, then child statements
        for fname, value in ast.iter_fields(stmt):
            if isinstance(value, list):
                if value and isinstance(value[0], ast.stmt):
                    self._walk_stmt_list(value, method, held)
                else:
                    for v in value:
                        if isinstance(v, ast.expr):
                            self._visit_expr(v, method, held)
                        elif isinstance(v, ast.excepthandler):
                            self._walk_stmt_list(v.body, method, held)
            elif isinstance(value, ast.expr):
                self._visit_expr(value, method, held)

    def _visit_expr(self, expr, method: str, held: frozenset) -> None:
        writeish = _writeish_attr_ids(expr)
        for sub in ast.walk(expr):
            if isinstance(sub, (ast.Lambda,)):
                continue
            if isinstance(sub, ast.Call):
                self._record_call(sub, method, held)
            elif isinstance(sub, ast.Attribute):
                self._record_attr(sub, method, held, writeish)

    def _record_call(self, call: ast.Call, method: str, held) -> None:
        func = call.func
        if (
            isinstance(func, ast.Attribute)
            and isinstance(func.value, ast.Name)
            and func.value.id == "self"
            and func.attr in self.cls.methods
        ):
            self.cls.intra_calls.setdefault(method, []).append(
                (func.attr, held)
            )

    def _record_attr(
        self, node: ast.Attribute, method: str, held, writeish
    ) -> None:
        base = node.value
        write = isinstance(node.ctx, (ast.Store, ast.Del)) or (
            id(node) in writeish
        )
        # self.attr
        if isinstance(base, ast.Name) and base.id == "self":
            if node.attr in self.cls.lock_attrs:
                return
            self.cls.accesses.append(
                Access(
                    attr=node.attr,
                    write=write,
                    line=node.lineno,
                    method=method,
                    held=held,
                    node=node,
                )
            )
            return
        # self.field.attr (cross-object)
        if (
            isinstance(base, ast.Attribute)
            and isinstance(base.value, ast.Name)
            and base.value.id == "self"
        ):
            self.cls.accesses.append(
                Access(
                    attr=node.attr,
                    write=write,
                    line=node.lineno,
                    method=method,
                    held=held,
                    node=node,
                    via_field=base.attr,
                )
            )


def _enclosing_scope(mod: ModuleInfo, node: ast.AST) -> str:
    """Qualified name of the innermost indexed def whose span contains
    ``node`` (baseline-compatible), or "<module>"."""
    line = getattr(node, "lineno", 0)
    best: Optional[FuncInfo] = None
    for fi in mod.functions.values():
        start = fi.node.lineno
        end = getattr(fi.node, "end_lineno", start)
        if start <= line <= end:
            if best is None or start > best.node.lineno:
                best = fi
    return best.qualname if best is not None else "<module>"


def _writeish_attr_ids(expr: ast.AST) -> Set[int]:
    """ids of Attribute nodes mutated THROUGH: ``self.d[k] = v`` stores
    into the object ``self.d`` names, and ``self.q.append(x)`` mutates
    ``self.q`` — both count as writes for lock-guard inference."""
    out: Set[int] = set()
    for sub in ast.walk(expr):
        if isinstance(sub, ast.Subscript) and isinstance(
            sub.ctx, (ast.Store, ast.Del)
        ):
            if isinstance(sub.value, ast.Attribute):
                out.add(id(sub.value))
        elif (
            isinstance(sub, ast.Call)
            and isinstance(sub.func, ast.Attribute)
            and sub.func.attr in _MUTATORS
            and isinstance(sub.func.value, ast.Attribute)
        ):
            out.add(id(sub.func.value))
    return out


# -- the linter ----------------------------------------------------------------


class FlowLinter:
    def __init__(self, project: Project, rules: Set[str]):
        self.project = project
        self.rules = rules
        self.violations: List[Violation] = []
        self._seen: Set[Tuple[str, int, str, str]] = set()

    # -- emission -------------------------------------------------------------

    def _emit(
        self,
        mod: ModuleInfo,
        node: ast.AST,
        rule: str,
        scope: str,
        message: str,
        def_line: Optional[int] = None,
    ) -> None:
        if rule not in self.rules:
            return
        line = getattr(node, "lineno", 1)
        key = (mod.path, line, rule, message)
        if key in self._seen:
            return
        if mod.directives.suppressed(line, rule, def_line):
            return
        lines = mod.source.splitlines()
        code = lines[line - 1].strip() if 0 < line <= len(lines) else ""
        self._seen.add(key)
        self.violations.append(
            Violation(mod.path, line, rule, scope, code, message)
        )

    def _def_line_of(self, mod: ModuleInfo, scope: str) -> Optional[int]:
        fi = mod.functions.get(scope)
        return fi.node.lineno if fi is not None else None

    # -- R9 -------------------------------------------------------------------

    def check_r9(self) -> None:
        concurrent = self.project.concurrent_classes()
        guarded: Dict[Tuple[str, str], Dict[str, Set[str]]] = {}
        # inference pass: locked WRITES outside __init__ define guards
        for mod in self.project.modules.values():
            for cls in mod.classes.values():
                gmap: Dict[str, Set[str]] = {}
                for acc in cls.accesses:
                    if acc.via_field is not None or acc.method == "__init__":
                        continue
                    eff = acc.held | cls.entry_locks.get(
                        acc.method, frozenset()
                    )
                    if acc.write and eff:
                        gmap.setdefault(acc.attr, set()).update(eff)
                guarded[cls.qual] = gmap
        # double-checked-locking suppression: an unlocked read whose
        # method (or a direct intra-class callee — the faults registry's
        # ``fire`` -> ``_cross`` fast path) re-reads the attribute under
        # the guarding lock is the sanctioned lock-free pre-check
        for mod in self.project.modules.values():
            for cls in mod.classes.values():
                if cls.qual not in concurrent:
                    continue
                gmap = guarded[cls.qual]
                if not gmap:
                    continue
                locked_by_method: Dict[str, Set[str]] = {}
                for acc in cls.accesses:
                    if acc.via_field is None and not acc.write:
                        eff = acc.held | cls.entry_locks.get(
                            acc.method, frozenset()
                        )
                        if acc.attr in gmap and eff & gmap[acc.attr]:
                            locked_by_method.setdefault(
                                acc.method, set()
                            ).add(acc.attr)
                locked_reads: Set[Tuple[str, str]] = set()
                for m in cls.methods:
                    attrs = set(locked_by_method.get(m, ()))
                    for callee, _held in cls.intra_calls.get(m, ()):
                        attrs |= locked_by_method.get(callee, set())
                    for a in attrs:
                        locked_reads.add((m, a))
                for acc in cls.accesses:
                    if acc.via_field is not None or acc.method == "__init__":
                        continue
                    locks = gmap.get(acc.attr)
                    if not locks:
                        continue
                    eff = acc.held | cls.entry_locks.get(
                        acc.method, frozenset()
                    )
                    if eff & locks:
                        continue
                    if (
                        not acc.write
                        and (acc.method, acc.attr) in locked_reads
                    ):
                        continue  # double-checked locking idiom
                    scope = f"{cls.name}.{acc.method}"
                    lock_names = ", ".join(sorted(f"self.{n}" for n in locks))
                    verb = "write to" if acc.write else "read of"
                    self._emit(
                        mod,
                        acc.node,
                        "R9",
                        scope,
                        f"{verb} `self.{acc.attr}` without {lock_names} — "
                        f"every other mutation of this attribute holds the "
                        f"lock, and {cls.name} runs on multiple threads "
                        "(data race: lost updates / torn reads)",
                        def_line=self._def_line_of(mod, scope),
                    )
        # cross-object pass: self.field.attr where field's class guards attr
        for mod in self.project.modules.values():
            for cls in mod.classes.values():
                for acc in cls.accesses:
                    if acc.via_field is None:
                        continue
                    ftype = cls.field_types.get(acc.via_field)
                    if ftype is None or ftype not in concurrent:
                        continue
                    fmod = self.project.modules.get(ftype[0])
                    fcls = fmod.classes.get(ftype[1]) if fmod else None
                    if fcls is None:
                        continue
                    if acc.attr in fcls.methods or acc.attr in fcls.properties:
                        continue
                    locks = guarded.get(ftype, {}).get(acc.attr)
                    if not locks:
                        continue
                    # only contexts that can run concurrently with the
                    # target object's threads are flagged
                    accessor_key = (mod.path, f"{cls.name}.{acc.method}")
                    if (
                        cls.qual not in concurrent
                        and accessor_key not in self.project.reachable
                    ):
                        continue
                    scope = f"{cls.name}.{acc.method}"
                    lock_names = ", ".join(sorted(locks))
                    self._emit(
                        mod,
                        acc.node,
                        "R9",
                        scope,
                        f"unlocked {'write to' if acc.write else 'read of'} "
                        f"`self.{acc.via_field}.{acc.attr}`, an attribute "
                        f"{ftype[1]} guards with `self.{lock_names}` — "
                        f"take a snapshot through a locked accessor on "
                        f"{ftype[1]} instead of reaching into its state",
                        def_line=self._def_line_of(mod, scope),
                    )
        self._check_global_instances(concurrent, guarded)

    def _check_global_instances(self, concurrent, guarded) -> None:
        """Accesses to guarded attributes of module-global instances
        (``TRACER.path`` through any import alias) from thread-shared
        contexts."""
        for mod in self.project.modules.values():
            for fi in mod.functions.values():
                in_concurrent_cls = (
                    fi.cls is not None and fi.cls.qual in concurrent
                )
                if fi.key not in self.project.reachable and not in_concurrent_cls:
                    continue
                writeish = _writeish_attr_ids(fi.node)
                for sub in ast.walk(fi.node):
                    if not isinstance(sub, ast.Attribute):
                        continue
                    gtype = self._global_instance_type(mod, sub.value)
                    if gtype is None or gtype not in concurrent:
                        continue
                    gmod = self.project.modules.get(gtype[0])
                    gcls = gmod.classes.get(gtype[1]) if gmod else None
                    if gcls is None:
                        continue
                    if (
                        sub.attr in gcls.methods
                        or sub.attr in gcls.properties
                    ):
                        continue
                    locks = guarded.get(gtype, {}).get(sub.attr)
                    if not locks:
                        continue
                    # accesses inside the owning class itself were already
                    # judged (with lock context) by the within-class pass
                    if fi.cls is not None and fi.cls.qual == gtype:
                        continue
                    write = isinstance(sub.ctx, (ast.Store, ast.Del)) or (
                        id(sub) in writeish
                    )
                    base = _attr_path(sub.value) or "<global>"
                    self._emit(
                        mod,
                        sub,
                        "R9",
                        fi.qualname,
                        f"unlocked {'write to' if write else 'read of'} "
                        f"`{base}.{sub.attr}`, an attribute {gtype[1]} "
                        f"guards with `self.{', '.join(sorted(locks))}` — "
                        f"go through a locked accessor on {gtype[1]}",
                        def_line=fi.node.lineno,
                    )

    def _global_instance_type(
        self, mod: ModuleInfo, base: ast.AST
    ) -> Optional[Tuple[str, str]]:
        return self.project.global_instance_type(mod, base)

    # -- R10 ------------------------------------------------------------------

    def check_r10(self) -> None:
        for mod in self.project.modules.values():
            for fi in mod.functions.values():
                bare = fi.qualname.rsplit(".", 1)[-1]
                if bare in mod.traced_callees:
                    continue  # traced bodies: donation is inlined by XLA
                local_entries = _local_jit_entries(self.project, mod, fi)
                _DonationScan(self, mod, fi, local_entries).run()

    # -- R11 ------------------------------------------------------------------

    def check_r11(self) -> None:
        for mod in self.project.modules.values():
            for fi in mod.functions.values():
                bare = fi.qualname.rsplit(".", 1)[-1]
                if bare in mod.traced_callees:
                    continue
                local_entries = _local_jit_entries(self.project, mod, fi)
                self._r11_function(mod, fi, local_entries)

    def _r11_function(self, mod, fi, local_entries) -> None:
        unhashable_locals: Dict[str, str] = {}
        for sub in ast.walk(fi.node):
            if isinstance(sub, ast.Assign) and len(sub.targets) == 1:
                tgt = sub.targets[0]
                if isinstance(tgt, ast.Name):
                    kind = _unhashable_kind(sub.value)
                    if kind is not None:
                        unhashable_locals[tgt.id] = kind
                    else:
                        unhashable_locals.pop(tgt.id, None)

        def walk(node, loop_vars: Dict[str, bool]):
            # loop_vars: name -> True when the loop's iterable is UNBOUNDED
            if isinstance(node, ast.For):
                unbounded = not _bounded_iter(node.iter)
                names = {
                    n.id: unbounded
                    for n in ast.walk(node.target)
                    if isinstance(n, ast.Name)
                }
                inner = dict(loop_vars)
                inner.update(names)
                for child in ast.iter_child_nodes(node):
                    walk(child, inner)
                return
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if node is not fi.node:
                    return  # nested defs get their own visit
            if isinstance(node, ast.Call):
                self._r11_call(mod, fi, node, local_entries, loop_vars,
                               unhashable_locals)
            for child in ast.iter_child_nodes(node):
                walk(child, loop_vars)

        walk(fi.node, {})

    def _r11_call(
        self, mod, fi, call, local_entries, loop_vars, unhashable_locals
    ) -> None:
        entry = self._entry_for_call(mod, fi, call, local_entries)
        if entry is None or not entry.has_statics:
            return
        statics = entry.static_params()
        bindings: List[Tuple[str, ast.AST]] = []
        if entry.params:
            for i, arg in enumerate(call.args):
                if isinstance(arg, ast.Starred):
                    break
                if i < len(entry.params):
                    bindings.append((entry.params[i], arg))
        else:
            for i, arg in enumerate(call.args):
                if i in entry.static_nums:
                    bindings.append((f"#{i}", arg))
                    statics.add(f"#{i}")
        for kw in call.keywords:
            if kw.arg is not None:
                bindings.append((kw.arg, kw.value))
        for pname, expr in bindings:
            if pname not in statics:
                continue
            kind = _unhashable_kind(expr)
            reason = None
            if kind is not None:
                reason = f"an unhashable {kind} (TypeError at dispatch)"
            elif isinstance(expr, ast.JoinedStr):
                reason = (
                    "an f-string — a distinct value per call means one "
                    "silent XLA recompile per call"
                )
            elif isinstance(expr, ast.Name):
                if expr.id in unhashable_locals:
                    reason = (
                        f"`{expr.id}`, bound to an unhashable "
                        f"{unhashable_locals[expr.id]} above"
                    )
                elif loop_vars.get(expr.id):
                    reason = (
                        f"loop variable `{expr.id}` of an unbounded loop — "
                        "one recompile per distinct iterate (bounded "
                        "literal/range loops are the sanctioned precompile "
                        "pattern)"
                    )
            if reason is not None:
                self._emit(
                    mod,
                    expr,
                    "R11",
                    fi.qualname,
                    f"static arg `{pname}` of jit entry `{entry.name}` is "
                    f"{reason}; statics key the compile cache — pass "
                    "hashable, low-cardinality values",
                    def_line=fi.node.lineno,
                )

    def _entry_for_call(
        self, mod, fi, call, local_entries
    ) -> Optional[JitEntry]:
        name = _dotted(call.func)
        if name and name in local_entries:
            return local_entries[name]
        return self.project._resolve_jit_entry(mod, fi, name)

    # -- R12 ------------------------------------------------------------------

    def check_r12(self) -> None:
        for mod in self.project.modules.values():
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Call):
                    continue
                name = _dotted(node.func) or ""
                if name.rsplit(".", 1)[-1] != "shard_map":
                    continue
                self._r12_site(mod, node)

    def _r12_site(self, mod: ModuleInfo, call: ast.Call) -> None:
        declared: Set[str] = set()
        for kw in call.keywords:
            if kw.arg not in ("in_specs", "out_specs"):
                continue
            for sub in ast.walk(kw.value):
                if isinstance(sub, ast.Call):
                    cname = (_dotted(sub.func) or "").rsplit(".", 1)[-1]
                    if cname in _SPEC_NAMES:
                        for arg in sub.args:
                            elts = (
                                arg.elts
                                if isinstance(arg, (ast.Tuple, ast.List))
                                else [arg]
                            )
                            for el in elts:
                                s = self.project.resolve_str(mod, el)
                                if s is not None:
                                    declared.add(s)
        if not declared:
            return  # axis names not statically resolvable: never guess
        body = call.args[0] if call.args else None
        body_def = None
        fi = None
        if isinstance(body, ast.Lambda):
            body_def = body
        elif isinstance(body, ast.Name):
            fi = mod.functions.get(body.id)
            if fi is None:
                for q, f in mod.functions.items():
                    if q.rsplit(".", 1)[-1] == body.id:
                        fi = f
                        break
            body_def = fi.node if fi is not None else None
        if body_def is None:
            return
        # scope must be a name collect_scopes can re-derive, or the
        # baseline ratchet would flag an accepted entry as dead debt:
        # the body def's QUALIFIED name, or (for lambdas) the qualified
        # enclosing def of the shard_map call itself
        scope = (
            fi.qualname if fi is not None else _enclosing_scope(mod, call)
        )
        def_line = getattr(body_def, "lineno", None)
        for sub in ast.walk(body_def):
            if not isinstance(sub, ast.Call):
                continue
            cname = (_dotted(sub.func) or "").rsplit(".", 1)[-1]
            if cname not in _AXIS_ARG:
                continue
            axis_expr = None
            for kw in sub.keywords:
                if kw.arg == "axis_name":
                    axis_expr = kw.value
            if axis_expr is None:
                pos = _AXIS_ARG[cname]
                if pos < len(sub.args):
                    axis_expr = sub.args[pos]
            if axis_expr is None:
                continue
            elts = (
                axis_expr.elts
                if isinstance(axis_expr, (ast.Tuple, ast.List))
                else [axis_expr]
            )
            for el in elts:
                s = self.project.resolve_str(mod, el)
                if s is not None and s not in declared:
                    self._emit(
                        mod,
                        sub,
                        "R12",
                        scope,
                        f"{cname}(..., axis_name={s!r}) inside a shard_map "
                        f"body whose wrapping site declares axes "
                        f"{sorted(declared)} — the collective would target "
                        "an axis the mesh program never bound (trace-time "
                        "error, or the wrong axis after a mesh refactor)",
                        def_line=def_line,
                    )

    # -- driver ---------------------------------------------------------------

    def run(self) -> List[Violation]:
        if "R9" in self.rules:
            self.check_r9()
        if "R10" in self.rules:
            self.check_r10()
        if "R11" in self.rules:
            self.check_r11()
        if "R12" in self.rules:
            self.check_r12()
        self.violations.sort(key=lambda v: (v.path, v.line, v.rule))
        return self.violations


def _local_jit_entries(
    project: Project, mod: ModuleInfo, fi: FuncInfo
) -> Dict[str, JitEntry]:
    """jit entries bound to LOCAL names inside ``fi`` (the sharded solver
    builds its donating ``step``/``step_loop`` callables per-mesh)."""
    out: Dict[str, JitEntry] = {}
    for sub in ast.walk(fi.node):
        if not (isinstance(sub, ast.Assign) and len(sub.targets) == 1):
            continue
        tgt = sub.targets[0]
        if not isinstance(tgt, ast.Name):
            continue
        val = sub.value
        if not isinstance(val, ast.Call):
            continue
        is_jit, jkws = _jit_call_parts(val.func)
        kws = list(jkws) + list(val.keywords)
        if not is_jit:
            is_jit, kws2 = _jit_call_parts(val)
            kws = list(kws2)
        if not is_jit:
            continue
        e = JitEntry(name=tgt.id, module=mod, params=None)
        for kw in kws:
            if kw.arg == "donate_argnames":
                vals = project._const_str_tuple(mod, kw.value)
                if vals:
                    e.donate_names.update(vals)
            elif kw.arg == "donate_argnums":
                nums = _const_int_tuple(kw.value)
                if nums:
                    e.donate_nums.update(nums)
            elif kw.arg == "static_argnames":
                vals = project._const_str_tuple(mod, kw.value)
                if vals:
                    e.static_names.update(vals)
            elif kw.arg == "static_argnums":
                nums = _const_int_tuple(kw.value)
                if nums:
                    e.static_nums.update(nums)
        if val.args and isinstance(val.args[0], ast.Name):
            wfi = mod.functions.get(val.args[0].id)
            if wfi is None:
                for q, f in mod.functions.items():
                    if q.rsplit(".", 1)[-1] == val.args[0].id:
                        wfi = f
                        break
            if wfi is not None:
                e.params = wfi.params
        if e.donating or e.has_statics:
            out[tgt.id] = e
    return out


def _unhashable_kind(expr: ast.AST) -> Optional[str]:
    if isinstance(expr, ast.List):
        return "list display"
    if isinstance(expr, ast.Dict):
        return "dict display"
    if isinstance(expr, ast.Set):
        return "set display"
    if isinstance(expr, ast.ListComp):
        return "list comprehension"
    if isinstance(expr, ast.SetComp):
        return "set comprehension"
    if isinstance(expr, ast.DictComp):
        return "dict comprehension"
    if isinstance(expr, ast.Call):
        name = _dotted(expr.func) or ""
        root = name.split(".", 1)[0]
        if root in _ARRAY_ROOTS and name.rsplit(".", 1)[-1] in (
            "array", "asarray", "zeros", "ones", "arange", "full",
        ):
            return f"{name}() array"
    return None


def _bounded_iter(expr: ast.AST) -> bool:
    """Is a for-loop's iterable a bounded literal (tuple/list/set display,
    ``range(...)``, or ``enumerate(<bounded>)``)? Loop vars over these are
    the deliberate warm-every-bucket pattern, not a recompile storm."""
    if isinstance(expr, (ast.Tuple, ast.List, ast.Set, ast.Constant)):
        return True
    if isinstance(expr, ast.Call):
        name = (_dotted(expr.func) or "").rsplit(".", 1)[-1]
        if name == "range":
            return True
        if name in ("enumerate", "sorted", "reversed", "zip") and expr.args:
            return all(_bounded_iter(a) for a in expr.args)
    return False


# -- R10 donation scan ---------------------------------------------------------


class _DonationScan:
    """Forward walk of one function's statement graph tracking donated
    bindings (dotted paths). A use of a donated path that isn't the
    sanctioned ``check_donated`` call is a violation; rebinding kills."""

    def __init__(self, linter: FlowLinter, mod, fi, local_entries):
        self.linter = linter
        self.mod = mod
        self.fi = fi
        self.local_entries = local_entries
        self.emitted: Set[Tuple[int, str]] = set()

    def run(self) -> None:
        # loops re-run their own bodies against the joined back-edge
        # state (see _stmt), so one top-level pass suffices
        self._block(list(self.fi.node.body), {}, emit=True)

    # state: dict donated_path -> (donor entry name, donor line); None
    # return value = every path through the block terminated
    def _block(self, stmts, state, emit: bool):
        cur = dict(state)
        for stmt in stmts:
            cur = self._stmt(stmt, cur, emit)
            if cur is None:
                return None
        return cur

    def _join(self, *states):
        live = [s for s in states if s is not None]
        if not live:
            return None
        out: Dict[str, Tuple[str, int]] = {}
        for s in live:
            out.update(s)
        return out

    def _stmt(self, stmt, state, emit):
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            return state
        if isinstance(stmt, ast.Assign):
            self._expr(stmt.value, state, emit)
            for tgt in stmt.targets:
                self._kill_target(tgt, state)
            return state
        if isinstance(stmt, ast.AnnAssign):
            if stmt.value is not None:
                self._expr(stmt.value, state, emit)
            self._kill_target(stmt.target, state)
            return state
        if isinstance(stmt, ast.AugAssign):
            self._expr(stmt.value, state, emit)
            self._use_check(stmt.target, state, emit)
            self._kill_target(stmt.target, state)
            return state
        if isinstance(stmt, ast.Expr):
            self._expr(stmt.value, state, emit)
            return state
        if isinstance(stmt, ast.Return):
            if stmt.value is not None:
                self._expr(stmt.value, state, emit)
            return None
        if isinstance(stmt, (ast.Raise, ast.Break, ast.Continue)):
            if isinstance(stmt, ast.Raise) and stmt.exc is not None:
                self._expr(stmt.exc, state, emit)
            return None
        if isinstance(stmt, ast.If):
            self._expr(stmt.test, state, emit)
            a = self._block(stmt.body, state, emit)
            b = self._block(stmt.orelse, state, emit)
            return self._join(a, b)
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._expr(stmt.iter, state, emit)
            self._kill_target(stmt.target, state)
            once = self._block(stmt.body, state, emit=False)
            looped = self._join(state, once)
            body_out = self._block(stmt.body, looped or state, emit)
            els = self._block(
                stmt.orelse, self._join(state, body_out) or {}, emit
            )
            return self._join(state, body_out, els)
        if isinstance(stmt, ast.While):
            self._expr(stmt.test, state, emit)
            once = self._block(stmt.body, state, emit=False)
            looped = self._join(state, once)
            body_out = self._block(stmt.body, looped or state, emit)
            els = self._block(
                stmt.orelse, self._join(state, body_out) or {}, emit
            )
            return self._join(state, body_out, els)
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._expr(item.context_expr, state, emit)
                if item.optional_vars is not None:
                    self._kill_target(item.optional_vars, state)
            return self._block(stmt.body, state, emit)
        if isinstance(stmt, ast.Try):
            body_out = self._block(stmt.body, state, emit)
            handler_in = self._join(state, body_out) or dict(state)
            h_outs = [
                self._block(h.body, handler_in, emit) for h in stmt.handlers
            ]
            else_out = (
                self._block(stmt.orelse, body_out, emit)
                if body_out is not None
                else None
            )
            merged = self._join(body_out if not stmt.orelse else else_out,
                                *h_outs)
            if stmt.finalbody:
                merged = self._block(
                    stmt.finalbody, merged or dict(state), emit
                )
            return merged
        if isinstance(stmt, (ast.Delete,)):
            for tgt in stmt.targets:
                self._kill_target(tgt, state)
            return state
        # fallback: visit any expressions hanging off the statement
        for _f, value in ast.iter_fields(stmt):
            if isinstance(value, ast.expr):
                self._expr(value, state, emit)
            elif isinstance(value, list):
                for v in value:
                    if isinstance(v, ast.expr):
                        self._expr(v, state, emit)
        return state

    # -- expressions ----------------------------------------------------------

    def _expr(self, expr, state, emit) -> None:
        """Check uses inside ``expr``, then apply any donations its calls
        perform (arguments are evaluated before the dispatch consumes)."""
        self._use_check(expr, state, emit)
        for sub in ast.walk(expr):
            if isinstance(sub, ast.Call):
                self._apply_donation(sub, state)

    def _use_check(self, expr, state, emit) -> None:
        if not state:
            return

        def walk(node, exempt: bool):
            if isinstance(node, ast.Call):
                name = _dotted(node.func) or ""
                ex = exempt or name.endswith(_DONATE_CHECK_SUFFIX)
                for child in ast.iter_child_nodes(node):
                    walk(child, ex)
                return
            if isinstance(node, (ast.FunctionDef, ast.Lambda)):
                return
            path = _attr_path(node)
            if path is not None:
                hit = self._match(path, state)
                if hit is not None and not exempt:
                    self._flag(node, path, hit, emit)
                return  # don't descend: the chain is matched as a whole
            for child in ast.iter_child_nodes(node):
                walk(child, exempt)

        walk(expr, False)

    def _match(self, path: str, state):
        """A use hits when the used path IS a donated path, extends one
        (``fr.nodes.shape`` after ``fr.nodes``), or is a donated path's
        root object (``fr`` after ``fr`` itself was donated)."""
        for donated, info in state.items():
            if path == donated or path.startswith(donated + "."):
                return (donated, info)
        return None

    def _flag(self, node, path, hit, emit) -> None:
        if not emit:
            return
        donated, (entry, line) = hit
        key = (getattr(node, "lineno", 0), path)
        if key in self.emitted:
            return
        self.emitted.add(key)
        self.linter._emit(
            self.mod,
            node,
            "R10",
            self.fi.qualname,
            f"`{path}` is used after being DONATED to jit entry "
            f"`{entry}` (line {line}) — the dispatch consumed the buffer "
            "(jax deletes the handle); rebind from the call's result "
            "before any further use",
            def_line=self.fi.node.lineno,
        )

    def _kill_target(self, tgt, state) -> None:
        if isinstance(tgt, (ast.Tuple, ast.List)):
            for el in tgt.elts:
                self._kill_target(el, state)
            return
        if isinstance(tgt, ast.Starred):
            self._kill_target(tgt.value, state)
            return
        path = _attr_path(tgt)
        if path is None:
            return
        for donated in list(state):
            if (
                donated == path
                or donated.startswith(path + ".")
                or path.startswith(donated + ".")
            ):
                del state[donated]

    # -- donation application -------------------------------------------------

    def _apply_donation(self, call: ast.Call, state) -> None:
        entry = self._entry_for_call(call)
        if entry is not None and entry.donating:
            positions = entry.donated_positions()
            for i, arg in enumerate(call.args):
                donate = i in positions
                if not donate and entry.params and i < len(entry.params):
                    donate = entry.params[i] in entry.donate_names
                if donate:
                    self._mark(arg, entry.name, call.lineno, state)
            for kw in call.keywords:
                if kw.arg is not None and kw.arg in entry.donate_names:
                    self._mark(kw.value, entry.name, call.lineno, state)
            return
        # wrapper pattern: a donating entry passed BY NAME alongside a
        # tuple of its arguments (the AOT dispatch helper)
        ref = None
        for arg in call.args:
            name = _dotted(arg)
            if name is None:
                continue
            cand = (
                self.local_entries.get(name)
                or self.linter.project._resolve_jit_entry(
                    self.mod, self.fi, name
                )
            )
            if cand is not None and cand.donating:
                ref = cand
                break
        if ref is None:
            return
        for arg in call.args:
            prefix = _tuple_prefix(arg)
            if prefix is None:
                continue
            positions = ref.donated_positions()
            for i, el in enumerate(prefix):
                if i in positions:
                    self._mark(el, ref.name, call.lineno, state)
            break

    def _entry_for_call(self, call: ast.Call) -> Optional[JitEntry]:
        name = _dotted(call.func)
        if name and name in self.local_entries:
            return self.local_entries[name]
        return self.linter.project._resolve_jit_entry(
            self.mod, self.fi, name
        )

    def _mark(self, expr, entry_name: str, line: int, state) -> None:
        for path in _donated_paths(expr):
            state[path] = (entry_name, line)


def _attr_path(node: ast.AST) -> Optional[str]:
    """``a.b.c`` -> "a.b.c" for pure Name/Attribute LOAD chains."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _donated_paths(expr: ast.AST) -> List[str]:
    """Paths consumed when ``expr`` lands in a donated position: a bare
    name, an attribute chain, or names wrapped in ``tuple(...)`` /
    ``list(...)`` / tuple displays."""
    path = _attr_path(expr)
    if path is not None:
        return [path]
    if isinstance(expr, ast.Call):
        name = (_dotted(expr.func) or "").rsplit(".", 1)[-1]
        if name in ("tuple", "list") and len(expr.args) == 1:
            return _donated_paths(expr.args[0])
        return []
    if isinstance(expr, (ast.Tuple, ast.List)):
        out: List[str] = []
        for el in expr.elts:
            out.extend(_donated_paths(el))
        return out
    if isinstance(expr, ast.Starred):
        return _donated_paths(expr.value)
    return []


def _tuple_prefix(expr: ast.AST) -> Optional[List[ast.AST]]:
    """The statically-known leading elements of a tuple expression:
    ``(a, b, c)`` or ``(a, b) + rest`` -> [a, b, ...]."""
    if isinstance(expr, ast.Tuple):
        return list(expr.elts)
    if isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.Add):
        left = _tuple_prefix(expr.left)
        return left
    return None


# -- public API ----------------------------------------------------------------


def flow_project(
    sources: Dict[str, str], rules: Optional[Iterable[str]] = None
) -> List[Violation]:
    """Analyze a {path: source} project (disable comments honored,
    baseline NOT applied)."""
    project = Project()
    for path, src in sources.items():
        project.add_module(path, src)
    project.finalize()
    linter = FlowLinter(
        project, set(rules) if rules is not None else set(FLOW_RULES)
    )
    return linter.run()


def flow_text(
    source: str,
    path: str = "<string>",
    rules: Optional[Iterable[str]] = None,
) -> List[Violation]:
    """Analyze one source string as a single-module project."""
    return flow_project({path: source}, rules=rules)


def flow_paths(
    paths: Sequence[pathlib.Path],
    root: pathlib.Path,
    rules: Optional[Iterable[str]] = None,
) -> List[Violation]:
    """Analyze every .py under ``paths`` as ONE project (imports resolve
    across files); violation paths are ``root``-relative."""
    project = Project()
    for f in _iter_py_files(paths):
        try:
            source = f.read_text()
        except (OSError, UnicodeDecodeError):
            continue
        try:
            rel = f.resolve().relative_to(root.resolve()).as_posix()
        except ValueError:
            rel = f.as_posix()
        project.add_module(rel, source)
    project.finalize()
    linter = FlowLinter(
        project, set(rules) if rules is not None else set(FLOW_RULES)
    )
    return linter.run()

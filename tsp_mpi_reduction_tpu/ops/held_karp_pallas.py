"""Pallas TPU kernel for the Held-Karp min-plus relaxation (the hot op).

Each cardinality step of the dense DP (ops/held_karp.py, the TPU redesign
of the reference's map-based loops, tsp.cpp:442-481) spends its cycles in

    cand[j, k, m'] = g[j, m'] + d_t[k, m']
    new_cost[j, k]   = min_{m'} cand[j, k, m']
    new_parent[j, k] = argmin_{m'} cand[j, k, m']

a min-plus "matmul" between the gathered predecessor costs ``g`` and the
transposed distance block ``d_t``. This module implements that contraction
as a Pallas kernel: ``g`` tiles stream HBM->VMEM once and both reductions
(min and argmin) happen in registers per tile, instead of materializing the
``[J, K, M]`` candidate tensor. Lanes are padded to 128 with +inf, which is
absorbed by the min; rows whose mask excludes every predecessor stay +inf
and keep argmin==0 — exactly the jnp path's semantics, so the kernel is a
drop-in replacement validated bit-for-bit in tests (interpret mode on CPU,
compiled on TPU).

Both kernels here are OPT-IN via ``held_karp.set_impl("pallas"|"fused")``:
the ``auto`` policy always resolves to the compacted jnp path, which
measured fastest on a v5e (see the impl table in held_karp.py). They are
kept as the framework's kernel path, bit-exact-tested against the default.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LANES = 128  # TPU lane width; m <= 17 always fits one lane tile
_ROW_TILE = 256


def _relax_kernel(g_ref, dt_ref, cost_ref, parent_ref, *, m: int):
    """One row-tile: min-plus contract ``g`` with every d_t row.

    g_ref:    [TJ, 128] gathered predecessor costs (+inf beyond column m)
    dt_ref:   [R8, 128] d_t rows (R8 = m padded to sublanes; +inf padding)
    cost_ref / parent_ref: [TJ, 128] outputs (columns >= m are scratch)
    """
    g = g_ref[:]
    for k in range(m):  # static unroll: m-1 <= 16 iterations  # graftlint: disable=R4
        cand = g + dt_ref[k, :][None, :]
        cost_ref[:, k] = jnp.min(cand, axis=1)
        parent_ref[:, k] = jnp.argmin(cand, axis=1).astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("interpret",))
def relax_minplus(
    g: jnp.ndarray, d_t: jnp.ndarray, interpret: bool = False
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Min-plus relaxation for one step: ``g`` [J, M] x ``d_t`` [M, M].

    Returns (new_cost [J, M], new_parent [J, M] int32) where column k holds
    ``min/argmin over m' of g[j, m'] + d_t[k, m']``. Ties break to the
    first (smallest) m', matching ``jnp.argmin`` in the reference path.
    """
    j, m = g.shape
    dtype = g.dtype
    inf = jnp.asarray(jnp.inf, dtype)

    jp = -(-j // _ROW_TILE) * _ROW_TILE
    rows8 = max(8, -(-m // 8) * 8)
    g_pad = jnp.full((jp, LANES), inf, dtype).at[:j, :m].set(g)
    dt_pad = jnp.full((rows8, LANES), inf, dtype).at[:m, :m].set(d_t)

    cost, parent = pl.pallas_call(
        functools.partial(_relax_kernel, m=m),
        grid=(jp // _ROW_TILE,),
        in_specs=[
            pl.BlockSpec((_ROW_TILE, LANES), lambda i: (i, 0)),
            pl.BlockSpec((rows8, LANES), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((_ROW_TILE, LANES), lambda i: (i, 0)),
            pl.BlockSpec((_ROW_TILE, LANES), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((jp, LANES), dtype),
            jax.ShapeDtypeStruct((jp, LANES), jnp.int32),
        ],
        interpret=interpret,
    )(g_pad, dt_pad)
    return cost[:j, :m], parent[:j, :m]


def relax_reference(g: jnp.ndarray, d_t: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """The jnp formulation the kernel replaces (for parity tests)."""
    cand = g[:, None, :] + d_t[None, :, :]
    return jnp.min(cand, axis=-1), jnp.argmin(cand, axis=-1).astype(jnp.int32)


# ---------------------------------------------------------------------------
# Dense-sweep relaxation: the flagship kernel of the f32 speed path.
#
# Layout [m, 2^m] with the mask axis on lanes (held_karp._solve_one_dense).
# The kernel receives the current table and the bit-swapped predecessor
# table G (G[b, mask] = cost[b, mask ^ (1<<b)], prepared by XLA as 15
# reshape+flips), keeps both tile-resident in VMEM, and produces ALL m
# output rows per tile read — where the XLA fusion re-reads G for every
# endpoint k. Membership bits and popcounts are derived in-register from a
# lane-index iota (no bit tables in memory), and no parent/argmin work
# happens in the hot loop at all: parents are recomputed exactly during
# backtracking (held_karp._backtrack_recompute) from the finished table.
# ---------------------------------------------------------------------------

_DENSE_TILE = 2048  # lanes per tile: [16, 2048] f32 x 3 buffers = 384 KB VMEM


def _relax_dense_kernel(
    c_ref, cost_ref, g_ref, dsub_ref, out_ref, *, m: int, rows: int, tile: int
):
    """One [rows, tile] tile of the dense relaxation at cardinality ``c``.

    c_ref:    [1] int32 in SMEM — current subset cardinality
    cost_ref: [rows, tile] current DP table (rows >= m are padding)
    g_ref:    [rows, tile] bit-swapped predecessor costs (+inf padded rows)
    dsub_ref: [rows, rows] distance block, d_sub[b, k] (+inf padded rows)
    out_ref:  [rows, tile] updated table
    """
    j = pl.program_id(0)
    c = c_ref[0]
    inf = jnp.asarray(jnp.inf, cost_ref.dtype)

    # mask value per lane, bit index per sublane — both from iota, no memory
    # (int32 arithmetic throughout: Mosaic rejects some bool-vector casts)
    mask2d = jax.lax.broadcasted_iota(jnp.int32, (rows, tile), 1) + j * tile
    b2d = jax.lax.broadcasted_iota(jnp.int32, (rows, tile), 0)
    bits_i = jax.lax.shift_right_logical(mask2d, b2d) & 1  # int32 0/1
    in_range = b2d < m
    g = jnp.where((bits_i == 1) & in_range, g_ref[:], inf)  # b in mask
    popc = jnp.sum(jnp.where(in_range, bits_i, 0), axis=0)

    cost = cost_ref[:]
    mask_row = mask2d[0]
    upd_c = popc == c  # [tile] masks of this cardinality
    for k in range(m):  # static unroll, <= 17 rows  # graftlint: disable=R4
        cand = g + dsub_ref[:, k][:, None]
        mn = jnp.min(cand, axis=0)  # [tile]
        upd = upd_c & (((mask_row >> k) & 1) == 0)  # endpoint k outside mask
        out_ref[k, :] = jnp.where(upd, mn, cost[k, :])
    for k in range(m, rows):  # padding rows pass through
        out_ref[k, :] = cost[k, :]


@functools.partial(jax.jit, static_argnames=("m", "interpret"))
def relax_dense(
    cost: jnp.ndarray,
    g: jnp.ndarray,
    d_sub: jnp.ndarray,
    c: jnp.ndarray,
    m: int,
    interpret: bool = False,
) -> jnp.ndarray:
    """One dense DP step: update all popcount-``c`` states of ``cost``.

    Args:
      cost: [16, S] padded table (rows >= m ignored/passed through).
      g:    [16, S] bit-swapped predecessor table (rows >= m must be +inf).
      d_sub: [16, 16] padded distance block, d_sub[b, k] = d(b+1, k+1).
      c: scalar int32 cardinality of this step.
      m: number of non-anchor cities (n - 1).
    """
    rows, s = cost.shape
    tile = min(_DENSE_TILE, s)
    return pl.pallas_call(
        functools.partial(_relax_dense_kernel, m=m, rows=rows, tile=tile),
        grid=(s // tile,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((rows, tile), lambda i: (0, i)),
            pl.BlockSpec((rows, tile), lambda i: (0, i)),
            pl.BlockSpec((rows, rows), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((rows, tile), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((rows, s), cost.dtype),
        interpret=interpret,
    )(c.reshape(1).astype(jnp.int32), cost, g, d_sub)

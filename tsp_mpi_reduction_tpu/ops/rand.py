"""Bit-exact replica of glibc's default ``rand()`` (TYPE_3 additive-feedback).

The reference's entire instance is determined by ``srand(0)`` + a strictly
ordered sequence of ``rand()`` calls (tsp.cpp:273, assignment2.h:86-91), so a
bit-exact replica of glibc's generator is the determinism root of oracle
parity (SURVEY.md quirk #2, build plan step 2).

Algorithm (public, documented in glibc's stdlib/random_r.c and widely
described): a 31-word additive-feedback generator with taps at lags 3 and 31.

    seed 0 is mapped to 1;
    r[0]   = seed
    r[i]   = 16807 * r[i-1] mod 2147483647      for i in [1, 31)
    r[i]   = r[i-31]                            for i in [31, 34)
    r[i]   = (r[i-31] + r[i-3]) mod 2^32        for i >= 34
    the first 310 post-warmup words are discarded; each output is the next
    r[i] >> 1 (a 31-bit value).

Tests validate this replica against the committed golden stream
(goldens/glibc_rand_seed0.json) and against the live libc via ctypes.
"""

from __future__ import annotations

import numpy as np

_MOD31 = 2147483647  # 2^31 - 1
_MASK32 = 0xFFFFFFFF


class GlibcRand:
    """Sequential replica of glibc ``srand``/``rand``.

    >>> rng = GlibcRand(0)
    >>> rng.next()  # first value of the reference's stream
    1804289383
    """

    def __init__(self, seed: int = 0):
        self.seed(seed)

    def seed(self, seed: int) -> None:
        seed = seed & _MASK32
        if seed == 0:
            seed = 1
        r = [0] * 344
        r[0] = seed
        # glibc runs the Lehmer seeding step on int32 words with C division
        # semantics (truncation toward zero), which differs from a plain
        # unsigned `16807*r % (2^31-1)` when the seed's int32 value is negative.
        word = seed - (1 << 32) if seed >= (1 << 31) else seed
        for i in range(1, 31):
            hi = int(word / 127773)  # trunc toward zero, like C integer division
            lo = word - hi * 127773
            word = 16807 * lo - 2836 * hi
            if word < 0:
                word += _MOD31
            r[i] = word
        for i in range(31, 34):
            r[i] = r[i - 31]
        for i in range(34, 344):
            r[i] = (r[i - 31] + r[i - 3]) & _MASK32
        # keep only the sliding window needed for the lag-31 recurrence
        self._window = r[344 - 31:]  # last 31 words

    def next(self) -> int:
        w = self._window
        val = (w[0] + w[28]) & _MASK32  # lags: i-31 is w[0], i-3 is w[28]
        w.pop(0)
        w.append(val)
        return val >> 1

    def fill(self, n: int) -> np.ndarray:
        """Next ``n`` outputs as an int64 array (values fit in 31 bits)."""
        out = np.empty(n, dtype=np.int64)
        w = self._window
        for i in range(n):
            val = (w[0] + w[28]) & _MASK32
            w.pop(0)
            w.append(val)
            out[i] = val >> 1
        return out

    def frand(self, fmin: float, fmax: float) -> float:
        """Replica of the reference's ``fRand`` (assignment2.h:86-91).

        ``f = (double)rand() / RAND_MAX; return fMin + f * (fMax - fMin)``.
        """
        f = float(self.next()) / float(_MOD31)
        return fmin + f * (fmax - fmin)

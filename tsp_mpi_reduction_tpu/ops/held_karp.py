"""Dense Held-Karp exact TSP solver, designed for the MXU/VPU.

The reference solves each block with Held-Karp DP over a ``std::map`` keyed by
a (visited-set bitmask, endpoint) pair, with O(log) lookups inside four nested
loops (tsp.cpp:405-508, assignment2.h:146-182). This module is the TPU-first
redesign (SURVEY.md §7 step 3):

- the DP table is a dense ``[2^(n-1) + 1, n-1]`` array resident in HBM
  (array index IS the key: row = visited bitmask over cities 1..n-1, column =
  endpoint); the ``+1`` row is write-off scratch for padded lanes;
- masks are processed grouped by popcount (a mask only depends on masks with
  one fewer bit), so each of the n-2 sequential steps updates every mask of
  that cardinality as one batched gather + broadcasted min-plus reduction —
  no data-dependent control flow, fully static shapes under ``jit``;
- blocks are a ``vmap`` batch dimension (the reference sends one block per
  MPI message instead, tsp.cpp:159-195);
- path reconstruction is a ``lax.scan`` over a dense parent-pointer table
  (the reference stores full path vectors in every map entry).

Semantics notes for oracle parity (verified against goldens):

- The reference's cardinality-2 pass recomputes its seeded states through a
  missing-key lookup (``operator[]`` default cost 0, tsp.cpp:464), but
  ``map::insert`` refuses the duplicate keys (tsp.cpp:478), so the seeded
  values win and the uniform recurrence used here is exact for n >= 3.
- Ties break toward the smallest predecessor city (the reference's strict
  ``<`` over ascending ``m``, tsp.cpp:457-471); ``argmin``'s
  first-occurrence convention matches.
- Float64 additions occur in the same dependency order as the C++ oracle, so
  costs are bit-exact; float32 is the TPU speed mode.
"""

from __future__ import annotations

import contextlib
import functools
from dataclasses import dataclass
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .distance import distance_matrix


@dataclass(frozen=True)
class HeldKarpPlan:
    """Static (trace-time) schedule for one block size ``n``.

    All arrays are host-side numpy, embedded as constants in the jaxpr:
      scatter_idx  [S, maxNc]     row to write per mask lane (scratch if pad)
      prev_idx     [S, maxNc, M]  row of the predecessor state per (mask, m)
      member       [S, maxNc, M]  whether city m is in the mask
    where S = n-2 cardinality steps, M = n-1, maxNc = max_c C(M, c).
    """

    n: int
    scatter_idx: np.ndarray
    prev_idx: np.ndarray
    member: np.ndarray
    dp_states: int  # number of (mask, endpoint) states computed
    dp_transitions: int  # number of candidate relaxations (the nodes/sec unit)


#: Largest supported block size. The reference refuses n > 16 outright
#: (tsp.cpp:289-295, exit 1337); we allow slight headroom, but beyond 18 the
#: O(2^n) plan constants and candidate tensors reach multi-GB scale, so the
#: cap keeps the "fail cleanly up front" promise honest.
MAX_BLOCK_CITIES = 18

#: DP implementation:
#:   "compact" — masks compacted by popcount; gathered predecessors; the
#:              candidate tensor is [maxNc, m, m] (minimal FLOPs, but the
#:              gather/scatter and the 15-wide lane axis underuse the VPU);
#:   "dense"  — full [m, 2^m] table each step with the mask axis on lanes;
#:              the predecessor lookup C[mask ^ (1<<b), b] becomes a
#:              reshape+flip (bit-swap), NO gathers/scatters at all
#:              (~4x the FLOPs of compact, far better TPU utilization);
#:   "pallas" — compact layout with the min-plus contraction in a Pallas
#:              kernel (ops/held_karp_pallas.py); kept for the kernel path,
#:              measured slower than "dense" on v5e;
#:   "auto"   — "compact" everywhere, per measurement: on a v5e (remote,
#:              ~71 ms RTT included) solving 100x16-city blocks f32 takes
#:              compact 180 ms, dense 232 ms, fused 226 ms, pallas 246 ms
#:              — XLA's fusion of the compacted DP beats the alternatives
#:              at these shapes, so the kernels stay opt-in.
#:   "fused"  — dense layout with the relaxation in a Pallas kernel
#:              (held_karp_pallas.relax_dense): the table stays VMEM-tile-
#:              resident, membership/popcount come from in-register iota,
#:              and parent pointers are not stored at all (recomputed
#:              exactly during backtracking).
_IMPL = "auto"
_IMPLS = ("auto", "compact", "dense", "fused", "jnp", "pallas")


def set_impl(impl: str) -> None:
    """Select the DP implementation.

    Accepted: "auto" (default policy), "compact", "dense", "fused",
    "pallas", and "jnp" (alias of "compact").
    """
    global _IMPL
    if impl not in _IMPLS:
        raise ValueError(f"impl must be one of {_IMPLS}, got {impl!r}")
    _IMPL = "compact" if impl == "jnp" else impl


@contextlib.contextmanager
def use_impl(impl: str):
    """Scoped :func:`set_impl`: restores the previous selection on exit."""
    global _IMPL
    prev = _IMPL
    set_impl(impl)
    try:
        yield
    finally:
        _IMPL = prev


def _effective_impl(dtype) -> str:
    del dtype
    return "compact" if _IMPL == "auto" else _IMPL


@functools.lru_cache(maxsize=None)
def build_plan(n: int) -> HeldKarpPlan:
    if not 3 <= n <= MAX_BLOCK_CITIES:
        raise ValueError(
            f"Held-Karp block size must be in [3, {MAX_BLOCK_CITIES}], got {n}"
        )
    m = n - 1
    scratch = 1 << m
    by_card: dict[int, list[int]] = {c: [] for c in range(1, m)}
    for mask in range(1, 1 << m):
        c = bin(mask).count("1")
        if c < m:
            by_card[c].append(mask)
    max_nc = max(len(v) for v in by_card.values()) if by_card else 1

    steps = m - 1
    scatter_idx = np.full((steps, max_nc), scratch, dtype=np.int32)
    prev_idx = np.full((steps, max_nc, m), scratch, dtype=np.int32)
    member = np.zeros((steps, max_nc, m), dtype=bool)
    states = transitions = 0
    for s, c in enumerate(range(1, m)):
        masks = by_card[c]
        for j, mask in enumerate(masks):
            scatter_idx[s, j] = mask
            for bit in range(m):
                if mask & (1 << bit):
                    prev_idx[s, j, bit] = mask ^ (1 << bit)
                    member[s, j, bit] = True
        # endpoints outside the mask get real states; each relaxes over |mask|
        states += len(masks) * (m - c)
        transitions += len(masks) * (m - c) * c
    # closing pass: m states, one relaxation each (tsp.cpp:483-499)
    states += m
    transitions += m
    return HeldKarpPlan(n, scatter_idx, prev_idx, member, states, transitions)


def _solve_one(
    d: jnp.ndarray,
    plan: HeldKarpPlan,
    dtype: jnp.dtype,
    use_pallas: bool = False,
    interpret: bool = False,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Solve one block given its ``[n, n]`` distance matrix.

    Returns (cost scalar, closed tour ``[n+1]`` of block-local indices).
    """
    n = plan.n
    m = n - 1
    scratch = 1 << m
    inf = jnp.asarray(jnp.inf, dtype)

    d = d.astype(dtype)
    d_sub = d[1:, 1:]  # distances among cities 1..n-1, indexed 0..m-1
    d_seed = d[0, 1:]  # city 0 -> i (the reference's distances[0][i])
    d_back = d[1:, 0]  # i -> city 0 for tour closing

    cost = jnp.full((scratch + 1, m), inf, dtype)
    cost = cost.at[0].set(d_seed)  # state (visited=empty, endpoint i)
    parent = jnp.full((scratch + 1, m), -1, jnp.int32)

    d_t = d_sub.T  # d_t[k, m'] = d(m'+1, k+1), the relaxation edge

    def step(carry, xs):
        cost_t, parent_t = carry
        sc_idx, pv_idx, mem = xs
        # g[j, m'] = cost of predecessor state (mask \ {m'}, m')
        g = cost_t[pv_idx, jnp.arange(m)[None, :]]
        g = jnp.where(mem, g, inf)
        if use_pallas:
            from .held_karp_pallas import relax_minplus

            new_cost, new_parent = relax_minplus(g, d_t, interpret=interpret)
        else:
            cand = g[:, None, :] + d_t[None, :, :]  # [maxNc, k, m']
            new_cost = jnp.min(cand, axis=-1)
            new_parent = jnp.argmin(cand, axis=-1).astype(jnp.int32)
        cost_t = cost_t.at[sc_idx].set(new_cost)
        parent_t = parent_t.at[sc_idx].set(new_parent)
        return (cost_t, parent_t), None

    (cost, parent), _ = jax.lax.scan(
        step,
        (cost, parent),
        (
            jnp.asarray(plan.scatter_idx),
            jnp.asarray(plan.prev_idx),
            jnp.asarray(plan.member),
        ),
    )

    # close the tour: min over m' of cost[FULL \ {m'}, m'] + d(m'+1, 0)
    full = (1 << m) - 1
    totals = cost[_close_rows(m), jnp.arange(m)] + d_back
    best = jnp.argmin(totals).astype(jnp.int32)
    final_cost = totals[best]

    # backtrack endpoints via parent pointers (newest-to-oldest)
    def back(carry, _):
        mask, end = carry
        p = parent[mask, end]
        return (mask & ~(1 << p), p), end

    init = (full ^ (1 << best), best)
    _, ends = jax.lax.scan(back, init, None, length=m)
    return final_cost, _assemble_tour(ends)


def _close_rows(m: int) -> jnp.ndarray:
    """Masks ``FULL \\ {b}`` indexing the tour-closing states, b = 0..m-1."""
    full = (1 << m) - 1
    return jnp.asarray(
        np.array([full ^ (1 << b) for b in range(m)], dtype=np.int32)
    )


def _assemble_tour(ends: jnp.ndarray) -> jnp.ndarray:
    """Endpoint backtrack (newest→oldest) → closed tour ``[0, .., 0]``.

    ``+1`` converts DP endpoint index to city number (city 0 is the anchor,
    excluded from the DP state; reference path layout tsp.cpp:501-505).
    Shared by every impl so the layout stays bit-identical across them.
    """
    return jnp.concatenate(
        [
            jnp.zeros((1,), jnp.int32),
            jnp.flip(ends).astype(jnp.int32) + 1,
            jnp.zeros((1,), jnp.int32),
        ]
    )


@functools.lru_cache(maxsize=None)
def _dense_tables(n: int):
    """Host constants for the dense sweep: popcount and bit-membership."""
    m = n - 1
    s = 1 << m
    masks = np.arange(s, dtype=np.uint32)
    popc = np.zeros(s, dtype=np.int32)
    for b in range(m):
        popc += ((masks >> b) & 1).astype(np.int32)
    bit_in = np.stack([((masks >> b) & 1).astype(bool) for b in range(m)])
    return popc, bit_in  # [S], [m, S]


def _backtrack_recompute(
    cost_t: jnp.ndarray, d_sub: jnp.ndarray, m: int, best: jnp.ndarray
) -> jnp.ndarray:
    """Reconstruct the tour from the finished [rows, 2^m] cost table.

    Parent pointers are re-derived instead of stored: the parent of state
    (mask, e) is ``argmin over b in mask of cost[b, mask ^ (1<<b)] +
    d_sub[b, e]`` — by construction the exact argmin the forward step
    computed (same finalized values, same first-occurrence tie-break), so
    the recovered tour is bit-identical to the stored-parent paths.
    """
    inf = jnp.asarray(jnp.inf, cost_t.dtype)
    full = (1 << m) - 1
    bvec = jnp.arange(m)

    def back(carry, _):
        mask, e = carry
        vals = cost_t[bvec, mask ^ (1 << bvec)] + d_sub[:m, e]
        cand = jnp.where(((mask >> bvec) & 1) == 1, vals, inf)
        p = jnp.argmin(cand).astype(jnp.int32)
        return (mask & ~(1 << p), p), e

    init = (full ^ (1 << best), best)
    _, ends = jax.lax.scan(back, init, None, length=m)
    return _assemble_tour(ends)


def _solve_one_dense(
    d: jnp.ndarray,
    n: int,
    dtype: jnp.dtype,
    use_kernel: bool = False,
    interpret: bool = False,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Dense-sweep Held-Karp: full [rows, 2^m] table, zero gathers.

    Same recurrence and tie-breaks as :func:`_solve_one` (bit-identical
    results, see tests), but every step updates the WHOLE table with the
    popcount-c rows selected by a mask: the predecessor read
    ``C[b, mask ^ (1<<b)]`` is a reshape+flip over bit ``b`` (a regular
    strided permute the TPU does at full bandwidth), and the relaxation is
    a broadcasted add + min with the 2^m mask axis on lanes. No parent
    table is materialized — parents are recomputed exactly during
    backtracking (:func:`_backtrack_recompute`).

    ``use_kernel`` switches the relaxation to the Pallas kernel
    (``held_karp_pallas.relax_dense``, impl name "fused"); otherwise XLA
    fuses the jnp formulation (impl name "dense").
    """
    m = n - 1
    s = 1 << m
    rows = 16 if m <= 16 else 24
    inf = jnp.asarray(jnp.inf, dtype)

    d = d.astype(dtype)
    d_sub = jnp.full((rows, rows), inf, dtype).at[:m, :m].set(d[1:, 1:])
    d_seed = d[0, 1:]
    d_back = d[1:, 0]

    cost = jnp.full((rows, s), inf, dtype).at[:m, 0].set(d_seed)
    inf_row = jnp.full((s,), jnp.inf, dtype)

    if use_kernel:
        from .held_karp_pallas import relax_dense
    else:
        popc_np, bit_in_np = _dense_tables(n)
        popc = jnp.asarray(popc_np)
        bit_in = jnp.asarray(
            np.concatenate(
                [bit_in_np, np.zeros((rows - m, s), dtype=bool)], axis=0
            )
        )

    def bitswap(row: jnp.ndarray, b: int) -> jnp.ndarray:
        """row'[mask] = row[mask ^ (1 << b)] as a reshape+flip."""
        return jnp.flip(row.reshape(s >> (b + 1), 2, 1 << b), axis=1).reshape(s)

    def step(cost_t, c):
        g = jnp.stack(
            [bitswap(cost_t[b], b) for b in range(m)] + [inf_row] * (rows - m)
        )
        if use_kernel:
            return relax_dense(cost_t, g, d_sub, c, m, interpret), None
        gm = jnp.where(bit_in, g, inf)  # predecessor b must be in the mask
        cand = gm[None, :, :] + d_sub.T[:, :, None]  # [k, b, S]
        new_cost = jnp.min(cand, axis=1)
        upd = (popc == c)[None, :] & ~bit_in  # popcount-c masks, k outside
        return jnp.where(upd, new_cost, cost_t), None

    cost, _ = jax.lax.scan(step, cost, jnp.arange(1, m))

    totals = cost[jnp.arange(m), _close_rows(m)] + d_back
    best = jnp.argmin(totals).astype(jnp.int32)
    return totals[best], _backtrack_recompute(cost, d_sub, m, best)


@functools.partial(jax.jit, static_argnames=("n", "dtype", "impl", "interpret"))
def _solve_blocks_impl(
    d: jnp.ndarray, n: int, dtype, impl: str = "compact", interpret: bool = False
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    if not 3 <= n <= MAX_BLOCK_CITIES:
        raise ValueError(
            f"Held-Karp block size must be in [3, {MAX_BLOCK_CITIES}], got {n}"
        )
    if impl in ("dense", "fused"):
        use_kernel = impl == "fused"
        return jax.vmap(
            lambda b: _solve_one_dense(b, n, dtype, use_kernel, interpret)
        )(d)
    plan = build_plan(n)
    use_pallas = impl == "pallas"
    return jax.vmap(
        lambda b: _solve_one(b, plan, dtype, use_pallas, interpret)
    )(d)


def solve_blocks_from_dists(dists, dtype=jnp.float64) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Exactly solve a batch of blocks from ``[B, n, n]`` distance matrices.

    For bit-exact oracle parity, pass host-computed float64 matrices
    (:func:`..distance.distance_matrix_np`) — see the FMA note there.

    Returns:
      costs ``[B]`` and closed tours ``[B, n+1]`` of block-local city indices
      (``tour[0] == tour[-1] == 0``), matching the reference's path layout
      (tsp.cpp:501-505).
    """
    require_x64_if_float64(dtype)
    dists = jnp.asarray(dists)
    if dists.ndim != 3 or dists.shape[1] != dists.shape[2]:
        raise ValueError(f"expected [B, n, n] distance matrices, got {dists.shape}")
    n = int(dists.shape[1])
    impl, interpret = _dispatch_config(dtype)
    return _solve_blocks_impl(dists, n, jnp.dtype(dtype), impl, interpret)


def _dispatch_config(dtype) -> Tuple[str, bool]:
    """The (impl, interpret) pair the block solver will actually dispatch
    with for ``dtype`` — ONE selection shared by the dispatch path and
    the precompile warmup, so a warmed executable is by construction the
    same config the first real flush runs (a drifted duplicate would make
    precompile report success while every first flush still pays the cold
    compile). Raises for configs the dispatch path would reject (f64
    through a Mosaic kernel on real TPU).
    """
    impl = _effective_impl(dtype)
    # The Pallas kernels lower through Mosaic, which exists only for TPU;
    # every other platform (CPU CI, a hypothetical GPU) runs them in
    # interpret mode rather than hitting a lowering error.
    interpret = (
        impl in ("pallas", "fused")
        and "tpu" not in jax.devices()[0].platform.lower()
    )
    if not interpret and impl in ("pallas", "fused") and (
        jnp.dtype(dtype) == jnp.float64
    ):
        # Mosaic cannot lower f64 kernels; fail with a clear remedy instead
        # of a lowering error deep inside pallas_call.
        raise ValueError(
            f"impl {impl!r} cannot compile float64 on TPU (Mosaic has no f64 "
            "support); use dtype=float32 (speed mode), or impl='compact'/"
            "'dense' for float64 parity"
        )
    return impl, interpret


def warm_blocks(n: int, batch: int, dtype=jnp.float32) -> float:
    """AOT-compile the block solver for one ``[batch, n, n]`` bucket
    WITHOUT executing anything — the serve scheduler's precompile warmup
    (and the compile bench) call this per configured shape bucket so the
    first real flush pays a dispatch, not the classic serving recompile
    storm. Rides the AOT serialized-executable store when the perf cache
    is enabled (``perf.compile_cache``), else a plain ``lower().compile()``
    that still populates jax's persistent compilation cache. Returns the
    wall seconds spent warming."""
    from ..perf import compile_cache as _perf_cache

    require_x64_if_float64(dtype)
    dtype = jnp.dtype(dtype)
    impl, interpret = _dispatch_config(dtype)
    sd = jax.ShapeDtypeStruct((batch, n, n), dtype)
    return _perf_cache.warm_entry(
        f"hk_blocks_n{n}_b{batch}_{dtype.name}_{impl}",
        _solve_blocks_impl,
        (sd,),
        {"n": n, "dtype": dtype, "impl": impl, "interpret": interpret},
    )


def require_x64_if_float64(dtype) -> None:
    """Refuse to silently downcast a float64 parity request to float32.

    Without ``jax_enable_x64`` JAX truncates every float64 array to float32
    with only a warning; downstream tie-breaks then diverge materially from
    the oracle (not 1-ULP drift). Fail loudly instead.
    """
    if jnp.dtype(dtype) == jnp.float64 and not jax.config.jax_enable_x64:
        raise RuntimeError(
            "float64 (oracle-parity) mode needs jax_enable_x64: call "
            'jax.config.update("jax_enable_x64", True) at startup, or pass '
            "dtype='float32' for TPU speed mode"
        )


def solve_blocks(xy, dtype=jnp.float64) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Exactly solve a batch of blocks from ``[B, n, 2]`` coordinates.

    Distances are computed on device (fast path; 1-ULP FMA caveat vs the
    oracle — use :func:`solve_blocks_from_dists` with host matrices for
    bit-exact parity). City 0 anchors the tour, as in the reference.
    """
    xy = jnp.asarray(xy)
    if xy.ndim != 3 or xy.shape[-1] != 2:
        raise ValueError(f"expected [B, n, 2] coords, got {xy.shape}")
    return solve_blocks_from_dists(distance_matrix(xy.astype(jnp.dtype(dtype))), dtype)

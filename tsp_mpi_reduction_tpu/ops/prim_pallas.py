"""Pallas TPU kernel for the B&B engine's vmapped Prim MST chain.

The per-node MST re-bound (models/branch_bound._mst_conn) is the
expansion step's dominant cost after the round-4 packed-frontier work:
n-1 SEQUENTIAL fori iterations of tiny [k, n] ops, ~58 us each on a
v5e — latency-bound on op-issue overhead, not compute (the on-chip step
attribution in BENCHMARKS.md; a lax.fori unroll was tried and rejected).
This module runs the ENTIRE chain inside one Pallas kernel: the loop
state (intree/mind/closest/deg/tot) lives in registers/VMEM for a
[TK, LW] row-tile and the n-1 iterations execute back-to-back with no
XLA op boundaries.

Bit-exactness contract (validated against _mst_conn in
tests/test_prim_pallas.py, INTERPRET mode): identical (tot, deg) —
  - same sequential f32 accumulation order for ``tot``;
  - same argmin/argmax tie-breaking (first index) in interpret mode.
    COMPILED Mosaic argmin breaks ties differently: when an MST has
    equal-weight edge choices the DEGREES (and thus the mini-ascent
    subgradients and search trajectory) can differ from the jnp chain —
    every choice is an MST of identical total weight, so the value and
    the bound stay certified (same documented effect as the Boruvka
    kernel; eil51 expands 153,897 vs prim's 153,747 nodes, both proving
    426). Runs remain deterministic per backend;
  - lane padding to LW columns carries unvis=False -> +inf edge rows,
    which can never win an argmin that has any finite candidate, and the
    all-inf case picks index 0 in both paths;
  - the dbar row select uses a one-hot f32 matmul against a ZERO-padded
    dbar tile: one-hot weights are exactly 0.0/1.0, so each output
    element is one exact f32 pass-through plus exact zeros (the MXU's
    bf16x3 f32 emulation reconstructs b_hi + b_lo = b exactly for
    a = 1.0) — no rounding enters the bound.

Like ops/held_karp_pallas.py, the kernel is OPT-IN
(``--mst-kernel=prim_pallas`` / TSP_BENCH_MST_KERNEL) and falls back to
interpret mode off-TPU so the parity tests run on CPU. COMPILED use is
limited to n <= 128: n=200 B&B runs crashed the TPU worker on this
image with BOTH this kernel and the jnp prim (so the n>128-on-relay
config is the hazard, not Mosaic) — a worker crash can forfeit the
chip grant, so prim_chain refuses it loudly; n > 128 stays on the jnp
'prim' kernel.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

ROW_TILE = 128  # k rows per grid step


def _lanes_for(n: int) -> int:
    if n <= 128:
        return 128
    if n <= 256:
        return 256
    raise ValueError(f"prim_pallas supports n <= 256, got {n}")


def _prim_kernel(unvis_ref, dbar_ref, lam_ref, tot_ref, deg_ref, *,
                 n: int, has_lam: bool):
    """One [TK, LW] row-tile: the full n-1 step Prim chain.

    unvis_ref: [TK, LW] int32 0/1 (0 beyond column n)
    dbar_ref:  [LW, LW] f32, ZERO-padded outside [n, n]
    lam_ref:   [TK, LW] f32 per-lane potential deltas (zeros if unused)
    tot_ref:   [TK, LW] f32 out — MST value broadcast across lanes
    deg_ref:   [TK, LW] i32 out — per-vertex MST degree counts
    """
    tk, lw = unvis_ref.shape
    unvis = unvis_ref[:] != 0
    dbar = dbar_ref[:]
    lam = lam_ref[:] if has_lam else None
    big = jnp.float32(jnp.inf)
    col = jax.lax.broadcasted_iota(jnp.int32, (tk, lw), 1)
    colf = col.astype(jnp.float32)

    # Mosaic hygiene (each bisected as a compiler crash on this image):
    # every loop-state tensor stays rank-2; boolean planes never ride
    # the fori carry (intree is int32); and index planes never broadcast
    # as int32 through the carry — ``closest`` holds vertex ids as f32
    # (exact: ids < 256 << 2^24), with one-hot tests against a float iota
    def onehot(idx2):  # idx2: [TK, 1] int32
        return col == idx2

    def edge_rows(u2):
        # dbar[u] via one-hot f32 matmul (exact — see module docstring)
        oh = onehot(u2).astype(jnp.float32)
        # HIGHEST precision is REQUIRED for exactness: the default dot
        # truncates f32 operands to bf16 (one pass), which rounds values
        # with >8 mantissa bits (e.g. 647 -> 648) and would corrupt the
        # certified bound; the 3-pass path reconstructs b_hi + b_lo = b
        # exactly under one-hot weights
        base = jax.lax.dot_general(
            oh, dbar, (((1,), (0,)), ((), ())),
            precision=jax.lax.Precision.HIGHEST,
            preferred_element_type=jnp.float32,
        )
        if lam is None:
            return base
        lam_u = jnp.sum(jnp.where(onehot(u2), lam, 0.0), axis=1,
                        keepdims=True)
        return base + lam_u + lam

    # Mosaic's index-reductions only lower for f32 operands; 1.0/0.0
    # argmax picks the first unvisited column exactly like bool argmax
    start = jnp.argmax(unvis.astype(jnp.float32), axis=1).astype(
        jnp.int32
    )[:, None]
    oh_start = onehot(start)
    intree = oh_start.astype(jnp.int32)
    mind = jnp.where(unvis, edge_rows(start), big)
    startf = jnp.sum(jnp.where(oh_start, colf, 0.0), axis=1, keepdims=True)
    closest = startf + colf * 0.0  # [TK, lw], every column = start id
    deg = jnp.zeros((tk, lw), jnp.int32)
    tot = jnp.zeros((tk, 1), jnp.float32)

    def body(_, carry):
        intree, mind, closest, deg, tot = carry
        cand = jnp.where(intree != 0, big, mind)
        u = jnp.argmin(cand, axis=1).astype(jnp.int32)[:, None]
        oh_u = onehot(u)
        uf = jnp.sum(jnp.where(oh_u, colf, 0.0), axis=1, keepdims=True)
        wu = jnp.min(cand, axis=1, keepdims=True)
        fin = jnp.isfinite(wu)
        tot = tot + jnp.where(fin, wu, 0.0)
        parf = jnp.sum(jnp.where(oh_u, closest, 0.0), axis=1, keepdims=True)
        oh_par = colf == parf
        one = fin.astype(jnp.int32)
        deg = deg + (oh_u.astype(jnp.int32) + oh_par.astype(jnp.int32)) * one
        intree = jnp.maximum(intree, oh_u.astype(jnp.int32))
        row = jnp.where(unvis, edge_rows(u), big)
        better = row < mind
        closest = jnp.where(better, uf, closest)
        mind = jnp.minimum(mind, row)
        return intree, mind, closest, deg, tot

    _, _, _, deg, tot = jax.lax.fori_loop(
        0, n - 1, body, (intree, mind, closest, deg, tot)
    )
    tot_ref[:] = jnp.broadcast_to(tot, (tk, lw))
    deg_ref[:] = deg


@functools.partial(jax.jit, static_argnames=("n", "has_lam", "interpret"))
def _prim_chain_padded(unvis_p, dbar_p, lam_p, n: int, has_lam: bool,
                       interpret: bool):
    kp, lw = unvis_p.shape
    grid = kp // ROW_TILE
    kernel = functools.partial(_prim_kernel, n=n, has_lam=has_lam)
    tot, deg = pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((ROW_TILE, lw), lambda i: (i, 0)),
            pl.BlockSpec((lw, lw), lambda i: (0, 0)),
            pl.BlockSpec((ROW_TILE, lw), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((ROW_TILE, lw), lambda i: (i, 0)),
            pl.BlockSpec((ROW_TILE, lw), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((kp, lw), jnp.float32),
            jax.ShapeDtypeStruct((kp, lw), jnp.int32),
        ],
        interpret=interpret,
    )(unvis_p, dbar_p, lam_p)
    return tot[:, 0], deg


def prim_chain(
    dbar: jnp.ndarray,
    unvis: jnp.ndarray,
    n: int,
    lam: Optional[jnp.ndarray] = None,
    interpret: Optional[bool] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(tot [k], deg [k, n]) of MST(U) per lane — the fori-loop portion of
    branch_bound._mst_conn, bit-identical, as one Pallas dispatch."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if not interpret and n > 128:
        # the 256-lane (n<=256) variant is interpret-validated, but
        # n=200 B&B runs CRASH the TPU worker on this image ("TPU worker
        # process crashed", 2026-07-31) — observed with BOTH this kernel
        # and the plain jnp prim, so the fault is the n>128 config on
        # this relay rather than Mosaic specifically. A worker crash can
        # forfeit the chip grant, so refuse loudly rather than risk it;
        # n > 128 stays on the jnp 'prim' kernel (CPU-validated path)
        raise ValueError(
            f"prim_pallas is limited to n <= 128 on compiled TPU (got "
            f"n={n}); use --mst-kernel=prim for larger instances"
        )
    k = unvis.shape[0]
    lw = _lanes_for(n)
    kp = max((k + ROW_TILE - 1) // ROW_TILE, 1) * ROW_TILE
    unvis_p = jnp.zeros((kp, lw), jnp.int32).at[:k, :n].set(
        unvis.astype(jnp.int32)
    )
    dbar_p = jnp.zeros((lw, lw), jnp.float32).at[:n, :n].set(
        dbar.astype(jnp.float32)
    )
    has_lam = lam is not None
    if has_lam:
        lam_p = jnp.zeros((kp, lw), jnp.float32).at[:k, :n].set(
            lam.astype(jnp.float32)
        )
    else:
        lam_p = jnp.zeros((kp, lw), jnp.float32)
    tot, deg = _prim_chain_padded(unvis_p, dbar_p, lam_p, n, has_lam,
                                  bool(interpret))
    return tot[:k], deg[:k, :n]

"""Tour-merge operator: broadcasted 2-opt edge-swap on padded tours.

The reference's ``mergeBlocks`` (tsp.cpp:202-269) merges two closed tours by
scanning every edge pair with a double rotate loop (O(n1*n2) rotations of
``std::vector``), picking the 2-opt reconnection with minimal
``swapPairCost`` (tsp.cpp:197-200), then splicing tour 2 *reversed* into
tour 1 at the chosen edge. This module is the TPU-first redesign (SURVEY.md
§7 step 4): one broadcasted ``[L1, L2]`` swap-cost matrix gathered from a
resident distance matrix, a row-major ``argmin``, and a gather-based splice —
all fixed shapes, vmappable and scannable.

Replicated semantics (bit-exact vs goldens; quirks intentional):

- Edge lists include the zero-length wrap edge ``(tour[L-1], tour[0])`` of
  the closed representation (the reference's rotate scan walks all ``L``
  positions including the closing duplicate, tsp.cpp:212-227).
- Tie-break: first (i, j) in i-major, j-minor order wins (strict ``<`` in the
  scan; row-major ``argmin`` first-occurrence matches).
- The merged cost is **formulaic** — ``cost1 + cost2 + bestSwapCost``
  (tsp.cpp:263) — and the spliced path is never re-measured, so reported
  cost can differ from true path length (SURVEY.md quirk #4). Reproduced.
- The splice appends tour 2 reversed after the first city of tour 1 whose id
  matches either endpoint of the chosen left edge (tsp.cpp:244-259), with
  tour 2 rotated so the chosen right-edge head lands at the append boundary
  (tsp.cpp:236-241).
- Deviation: 2-city tours make the reference's rotate-until-match spin
  forever (SURVEY.md quirk #6, verified hang). This implementation requires
  both operands to have >= 3 cities and the caller-facing pipeline rejects
  ``n < 3`` up front instead of hanging.

Distances are *gathered* from a caller-provided global ``[N, N]`` matrix
(device-resident; host-computed float64 for oracle parity, see
``ops.distance.distance_matrix_np``) rather than recomputed per pair.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from ..analysis import contracts


class PaddedTour(NamedTuple):
    """A closed tour in a fixed-size buffer.

    ids:   [P] int32 global city ids; entries past ``length`` are padding
           (kept at 0 — always a valid gather index).
    length: scalar int32, number of valid entries INCLUDING the closing
           duplicate (a closed tour of k cities has length k+1).
    cost:  scalar float, the accumulated (formulaic) tour cost.
    """

    ids: jnp.ndarray
    length: jnp.ndarray
    cost: jnp.ndarray


def _tour_edges(t1: PaddedTour, t2: PaddedTour):
    """Edge endpoint ids of both closed tours: (a, b) = tour-1 edges,
    (r1, r2) = tour-2 edges, with padding successors clamped to 0."""
    i1 = jnp.arange(t1.ids.shape[0])
    i2 = jnp.arange(t2.ids.shape[0])
    # closed-tour successor: (i+1) mod length, padding lanes clamped to 0
    nxt1 = jnp.where(i1 + 1 >= t1.length, 0, i1 + 1)
    nxt2 = jnp.where(i2 + 1 >= t2.length, 0, i2 + 1)
    return t1.ids, t1.ids[nxt1], t2.ids, t2.ids[nxt2]


def _merge_from_sc(t1: PaddedTour, t2: PaddedTour, sc: jnp.ndarray) -> PaddedTour:
    """Shared merge tail: mask invalid lanes of the [P1, P2] swap-cost
    matrix, take its first minimum in i-major order (the reference's
    tie-break), splice, and apply the formulaic cost (tsp.cpp:263:
    (cost1 + cost2) + bestSwapCost)."""
    p2 = t2.ids.shape[0]
    i1 = jnp.arange(t1.ids.shape[0])
    i2 = jnp.arange(p2)
    valid = (i1[:, None] < t1.length) & (i2[None, :] < t2.length)
    sc = jnp.where(valid, sc, jnp.asarray(jnp.inf, sc.dtype))

    flat = jnp.argmin(sc.reshape(-1))  # first minimum in i-major, j-minor order
    i_star = (flat // p2).astype(jnp.int32)
    j_star = (flat - i_star * p2).astype(jnp.int32)
    best_swap = sc.reshape(-1)[flat]

    out, out_len = _splice(t1.ids, t1.length, t2.ids, t2.length, i_star, j_star)
    return PaddedTour(out, out_len, (t1.cost + t2.cost) + best_swap)


def merge_tours(t1: PaddedTour, t2: PaddedTour, dist: jnp.ndarray) -> PaddedTour:
    """Merge ``t2`` into ``t1``; result lives in ``t1``-sized buffer.

    Caller must guarantee ``t1.length + t2.length - 1 <= P1`` and both
    operands hold >= 3 distinct cities (see module docstring).
    """
    contracts.check_padded_tour(t1, where="merge_tours.t1")
    contracts.check_padded_tour(t2, where="merge_tours.t2")
    a, b, r1, r2 = _tour_edges(t1, t2)
    # swapPairCost (tsp.cpp:197-200), left-to-right addition order:
    # ((d(a, r2) + d(b, r1)) - d(a, b)) - d(r1, r2)
    # d(a,b) depends only on i and d(r1,r2) only on j, so gather those once
    d_ab = dist[a, b]
    d_r = dist[r1, r2]
    sc = (
        dist[a[:, None], r2[None, :]] + dist[b[:, None], r1[None, :]] - d_ab[:, None]
    ) - d_r[None, :]
    return _merge_from_sc(t1, t2, sc)


def _splice(ids1, len1, ids2, len2, i_star, j_star):
    """The reference's splice (tsp.cpp:229-259): insert tour 2, reversed
    and rotated so the chosen right-edge head lands at the boundary, after
    the first position of tour 1 whose id matches either endpoint of the
    chosen left edge. Returns (ids, length) in tour 1's buffer size.

    - The reference rotates until the HEAD VALUE matches the chosen
      right-edge head cities2[j_star] (tsp.cpp:236-239), i.e. it stops at
      the FIRST occurrence of that id in the POPPED vector — identical to
      the positional index on duplicate-free closed tours (where
      ids2[len2-1] == ids2[0]), but not when ids repeat (possible only
      under --compat-bugs corrupted operands, SURVEY.md quirk #5).
    - Value absent from the popped vector => the real reference spins
      forever (quirk #6 mechanism); fall back to the positional index —
      we cannot (and should not) emulate a hang.
    """
    p1 = ids1.shape[0]
    p2 = ids2.shape[0]
    i1 = jnp.arange(p1)
    i2 = jnp.arange(p2)
    l2p = len2 - 1  # tour 2 with its closing duplicate popped
    vj = ids2[j_star]
    match2 = (ids2 == vj) & (i2 < l2p)
    first = jnp.argmax(match2).astype(jnp.int32)
    p2_rot = jnp.where(
        match2.any(), first, jnp.where(j_star >= l2p, 0, j_star)
    )
    a_id = ids1[i_star]
    b_id = ids1[jnp.where(i_star + 1 >= len1, 0, i_star + 1)]

    match = ((ids1 == a_id) | (ids1 == b_id)) & (i1 < len1)
    q = jnp.argmax(match).astype(jnp.int32)  # first matching position

    out_len = len1 + l2p
    t = jnp.arange(p1)
    # source-2 positions walk backwards from the right-edge head (reversed
    # rotated order, tsp.cpp:241-257): rr[u] = ids2[(p2_rot - u) mod l2p]
    u = t - q - 1
    src2 = jnp.mod(p2_rot - u, jnp.maximum(l2p, 1))
    from_t1_head = t <= q
    from_t2 = (~from_t1_head) & (t <= q + l2p)
    idx1 = jnp.where(from_t1_head, t, jnp.maximum(t - l2p, 0))
    out = jnp.where(from_t2, ids2[jnp.clip(src2, 0, p2 - 1)], ids1[jnp.clip(idx1, 0, p1 - 1)])
    out = jnp.where(t < out_len, out, 0).astype(jnp.int32)
    return out, out_len


def make_padded(ids, length, cost, capacity: int) -> PaddedTour:
    """Place a tour (global ids, valid ``length``) into a ``capacity`` buffer."""
    ids = jnp.asarray(ids, jnp.int32)
    pad = capacity - ids.shape[0]
    if pad < 0:
        raise ValueError(f"tour of size {ids.shape[0]} exceeds capacity {capacity}")
    buf = jnp.pad(ids, (0, pad))
    lane = jnp.arange(capacity)
    buf = jnp.where(lane < length, buf, 0)
    return contracts.check_padded_tour(
        PaddedTour(buf, jnp.asarray(length, jnp.int32), cost),
        capacity=capacity,
        where="make_padded",
    )


def fold_tours(
    tours: jnp.ndarray, costs: jnp.ndarray, dist: jnp.ndarray, capacity: int | None = None
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Sequential left fold of per-block tours, as rank-local merging does.

    Replicates main()'s local reduction (tsp.cpp:348-352): repeatedly merge
    the accumulated tour with the next block's tour, in block order. Runs as
    a ``lax.scan`` with the accumulator in a fixed ``capacity`` buffer.

    Args:
      tours: ``[B, L]`` closed tours of global city ids (L = n+1).
      costs: ``[B]`` per-tour costs.
      dist: ``[N, N]`` global distance matrix to gather from.
      capacity: accumulator buffer size; defaults to the exact final length
        ``B * (L - 1) + 1``.

    Returns:
      (ids ``[capacity]``, length scalar, cost scalar) of the folded tour.
    """
    tours = jnp.asarray(tours, jnp.int32)
    costs = jnp.asarray(costs)
    b, l = tours.shape
    if capacity is None:
        capacity = b * (l - 1) + 1
    acc = make_padded(tours[0], l, costs[0], capacity)
    if b == 1:
        return acc.ids, acc.length, acc.cost

    def step(carry, xs):
        ids2, cost2 = xs
        t2 = PaddedTour(ids2, jnp.asarray(l, jnp.int32), cost2)
        return merge_tours(carry, t2, dist), None

    acc, _ = jax.lax.scan(step, acc, (tours[1:], costs[1:]))
    return acc.ids, acc.length, acc.cost


def merge_tours_xy(
    t1: PaddedTour, t2: PaddedTour, xy: jnp.ndarray
) -> PaddedTour:
    """``merge_tours`` computing distances FROM COORDINATES instead of
    gathering a resident [N, N] matrix.

    The gather formulation reads ~4*L1*L2 random elements of ``dist`` per
    merge — scalar-rate loads on TPU that dominate the whole fold. Here
    each tour's coordinates are gathered once (L rows), and the four
    distance blocks of the swap cost become broadcasted norm computations
    (pure VPU math, no random access). Distances use the same
    ``ops.distance.edge_length`` formula in the same dtype — verified
    bit-identical to the gather path on CPU (tests/test_merge.py). On TPU
    the inline recompute sits in a different fusion context than the
    standalone distance_matrix kernel, so XLA's FMA contraction may round
    individual distances +-1 ULP (ops/distance.py docstring) and flip an
    argmin tie; treat TPU results as equivalent-quality, not bit-equal.

    ``xy``: [N, 2] city coordinates in the cost dtype.
    """
    from .distance import edge_length

    a, b, r1, r2 = _tour_edges(t1, t2)
    xa, xb = xy[a], xy[b]  # [p1, 2] — one row gather per tour position
    x1, x2 = xy[r1], xy[r2]  # [p2, 2]
    d_ab = edge_length(xa, xb)  # [p1]
    d_r = edge_length(x1, x2)  # [p2]
    sc = (
        edge_length(xa[:, None, :], x2[None, :, :])
        + edge_length(xb[:, None, :], x1[None, :, :])
        - d_ab[:, None]
    ) - d_r[None, :]
    return _merge_from_sc(t1, t2, sc)


def fold_tours_tree(
    tours: jnp.ndarray, costs: jnp.ndarray, dist: jnp.ndarray
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Tree-shaped fold: log2(B) rounds of vmapped pairwise merges.

    The speed-path alternative to ``fold_tours``'s B-1 sequential scan
    steps: round t merges pairs (2i, 2i+1) of the surviving tours in ONE
    vmapped kernel, halving the count — ~log2(B) kernel launches instead of
    a B-step sequential dependency chain, which is what dominates wall
    time on TPU (each scan step is far too small to fill the chip).

    Buffers are sized per round (a round-t tour holds at most ``t1_len +
    t2_len - 1`` cities), so early rounds stay tiny; total arithmetic
    matches the sequential fold's O(B^2 n^2) but with B/2^t-way batch
    parallelism per round.

    The merge operator is non-associative, so the folded tour/cost differ
    (legitimately) from the sequential fold's — the reference itself uses
    BOTH shapes: a sequential fold within a rank (tsp.cpp:348-352) and a
    binary tree across ranks (MPI_ManualReduce, tsp.cpp:52-134). Byte
    parity against the oracle therefore requires ``fold_tours``; this
    fold mirrors the reference's cross-rank tree.

    Args/returns: as ``fold_tours``, except the returned ids buffer is
    sized ``2^ceil(log2 B) * (L-1) + 1`` (capacities double per round) —
    larger than the exact final length for non-power-of-two B. Consumers
    must slice by the returned ``length``; entries past it are zero.
    """
    return _fold_tree(tours, costs, dist, merge_tours)


def fold_tours_tree_xy(
    tours: jnp.ndarray, costs: jnp.ndarray, xy: jnp.ndarray
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """``fold_tours_tree`` built on ``merge_tours_xy``: swap costs are
    computed from the [N, 2] coordinates instead of gathered from a
    resident [N, N] matrix — the TPU speed path (the 4*L1*L2 random
    gathers per merge are scalar-rate on TPU and dominate the fold's wall
    time; the coordinate form is pure vectorized math)."""
    return _fold_tree(tours, costs, xy, merge_tours_xy)


def _fold_tree(tours, costs, ctx, merge_fn):
    tours = jnp.asarray(tours, jnp.int32)
    b, l = tours.shape
    cur = [
        PaddedTour(tours[i], jnp.asarray(l, jnp.int32), costs[i]) for i in range(b)
    ]
    vmerge = jax.vmap(merge_fn, in_axes=(0, 0, None))
    while len(cur) > 1:
        pairs = len(cur) // 2
        # output buffer: every surviving tour padded to the merged size
        out_cap = int(cur[0].ids.shape[0] + cur[1].ids.shape[0] - 1)
        left = jax.tree.map(lambda *x: jnp.stack(x), *cur[0 : 2 * pairs : 2])
        right = jax.tree.map(lambda *x: jnp.stack(x), *cur[1 : 2 * pairs : 2])
        pad = out_cap - left.ids.shape[1]
        left = PaddedTour(
            jnp.pad(left.ids, ((0, 0), (0, pad))), left.length, left.cost
        )
        merged = vmerge(left, right, ctx)
        nxt = [jax.tree.map(lambda x: x[i], merged) for i in range(pairs)]
        if len(cur) % 2:
            odd = cur[-1]
            opad = out_cap - int(odd.ids.shape[0])
            nxt.append(
                PaddedTour(jnp.pad(odd.ids, (0, opad)), odd.length, odd.cost)
            )
        cur = nxt
    acc = cur[0]
    return acc.ids, acc.length, acc.cost

"""Held-Karp 1-tree lower bound (BASELINE.json stretch config).

The classic Held-Karp bound: for node potentials pi, the reduced costs
``d̄[i,j] = d[i,j] + pi[i] + pi[j]`` satisfy ``tour_d = tour_d̄ - 2*sum(pi)``
for every Hamiltonian tour, and every tour is a 1-tree, so

    w(pi) = onetree(d̄) - 2*sum(pi)  <=  optimal tour cost.

``held_karp_potentials`` maximizes ``w`` by subgradient ascent (the 1-tree
degree surplus ``deg - 2`` is a subgradient). Everything is dense, static-
shape jax: Prim's MST as a ``lax.fori_loop`` over [n, n] matrices (the
scatter/min updates vectorize over lanes), so the whole ascent jits into
one device program — the "Held-Karp 1-tree lower bound on TPU" stretch.

The potentials then strengthen the B&B node bound without changing the
expansion kernel's shape (models.branch_bound): the per-city weight
``min_out`` becomes ``min_out(d̄) - 2*pi`` plus a per-child adjustment
``pi[child] - pi[0]`` — still one add per child.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp

INF = jnp.inf


def mst_cost_degrees(d: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Prim's MST over a dense symmetric matrix -> (cost, degrees).

    ``d``: [m, m] edge costs with +inf on the diagonal (and on any
    forbidden edge). The tree is rooted at vertex 0 of the matrix.
    Static shapes: one fori_loop step per added vertex, each step a
    masked argmin + two scatter updates.
    """
    m = d.shape[0]
    in_tree = jnp.zeros(m, bool).at[0].set(True)
    mindist = d[0]
    closest = jnp.zeros(m, jnp.int32)  # arg of mindist: nearest in-tree vertex
    deg = jnp.zeros(m, jnp.int32)

    def body(_, carry):
        in_tree, mindist, closest, deg, cost = carry
        cand = jnp.where(in_tree, INF, mindist)
        u = jnp.argmin(cand).astype(jnp.int32)
        w = cand[u]
        deg = deg.at[u].add(1).at[closest[u]].add(1)
        in_tree = in_tree.at[u].set(True)
        better = ~in_tree & (d[u] < mindist)
        mindist = jnp.where(better, d[u], mindist)
        closest = jnp.where(better, u, closest)
        return in_tree, mindist, closest, deg, cost + w

    _, _, _, deg, cost = jax.lax.fori_loop(
        0, m - 1, body, (in_tree, mindist, closest, deg, jnp.asarray(0.0, d.dtype))
    )
    return cost, deg


def one_tree_cost_degrees(d: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """1-tree value and degrees: MST over vertices 1..n-1 plus the two
    cheapest edges incident to vertex 0. ``d``: [n, n], inf diagonal."""
    n = d.shape[0]
    mst_cost, mst_deg = mst_cost_degrees(d[1:, 1:])
    # one top_k supplies both values and endpoints (indices), keeping the
    # summed e0 and the degree bumps consistent under ties
    neg_vals, idx = jax.lax.top_k(-d[0, 1:], 2)
    e0 = -neg_vals.sum()
    ends = idx.astype(jnp.int32) + 1
    deg = jnp.zeros(n, jnp.int32).at[0].set(2)
    deg = deg.at[1:].add(mst_deg)
    deg = deg.at[ends].add(1)
    return mst_cost + e0, deg


@partial(jax.jit, static_argnames=("steps",))
def held_karp_potentials(
    d: jnp.ndarray, steps: int = 100
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Subgradient ascent on the 1-tree bound -> (pi, best_bound).

    Step size: geometric annealing ``t_k = t0 * decay^k`` with
    ``t0 = bound / (2n)`` (Held-Karp's heuristic scale) and ``decay``
    chosen so the step shrinks by 1e-3 over the FULL horizon — a fixed
    decay (the classic 0.95) makes steps vanish after ~200 iterations and
    wastes any larger budget. Keeps the best (pi, w) seen — ``w`` is not
    monotone along the ascent.
    """
    n = d.shape[0]
    if n < 3:  # MST over n-1 vertices + two 0-incident edges
        raise ValueError(f"1-tree bound needs n >= 3 cities, got {n}")
    d = jnp.where(jnp.eye(n, dtype=bool), INF, d)
    pi0 = jnp.zeros(n, d.dtype)
    w0, _ = one_tree_cost_degrees(d)
    t0 = jnp.maximum(w0, 1.0) / (2.0 * n)
    decay = jnp.asarray(1e-3, d.dtype) ** (1.0 / max(steps, 1))

    def body(i, carry):
        pi, best_pi, best_w = carry
        pp = pi[:, None] + pi[None, :]
        w, deg = one_tree_cost_degrees(d + pp)
        w = w - 2.0 * pi.sum()
        improved = w > best_w
        best_pi = jnp.where(improved, pi, best_pi)
        best_w = jnp.maximum(best_w, w)
        g = (deg - 2).astype(d.dtype)
        t = t0 * (decay ** i)
        return pi + t * g, best_pi, best_w

    _, best_pi, best_w = jax.lax.fori_loop(
        0, steps, body, (pi0, pi0, jnp.asarray(-INF, d.dtype))
    )
    return best_pi, best_w


def one_tree_np(d64, pi64):
    """Host float64 1-tree -> (w(pi), degrees). Numpy twin of
    ``one_tree_cost_degrees`` + the ``- 2*sum(pi)`` correction, used by the
    host-side ascent (``held_karp_potentials_np``)."""
    import numpy as np

    d64 = np.asarray(d64, np.float64)
    pi64 = np.asarray(pi64, np.float64)
    n = d64.shape[0]
    dbar = d64 + pi64[:, None] + pi64[None, :]
    np.fill_diagonal(dbar, np.inf)
    sub = dbar[1:, 1:]
    m = n - 1
    in_tree = np.zeros(m, bool)
    in_tree[0] = True
    mindist = sub[0].copy()
    closest = np.zeros(m, np.int64)
    deg = np.zeros(n, np.int64)
    cost = 0.0
    for _ in range(m - 1):
        cand = np.where(in_tree, np.inf, mindist)
        u = int(np.argmin(cand))
        cost += cand[u]
        deg[u + 1] += 1
        deg[closest[u] + 1] += 1
        in_tree[u] = True
        better = ~in_tree & (sub[u] < mindist)
        mindist = np.where(better, sub[u], mindist)
        closest = np.where(better, u, closest)
    ends = np.argsort(dbar[0, 1:], kind="stable")[:2]
    e0 = dbar[0, 1:][ends].sum()
    deg[0] += 2
    deg[ends + 1] += 1
    return float(cost + e0 - 2.0 * pi64.sum()), deg


def held_karp_potentials_np(d64, steps: int = 400):
    """Host float64 subgradient ascent -> (pi, best_w). Numpy twin of
    ``held_karp_potentials`` (same t0/decay schedule, best-seen tracking).

    Exists so bound setup can run with ZERO device work: on this image's
    remote-TPU relay the first device->host transfer permanently degrades
    dispatch latency (see models.branch_bound docstring), so the B&B's
    fast path must build its bounds without ever touching the device.
    Also f64 end to end, which the device ascent (f32, Mosaic) is not.
    """
    import numpy as np

    d64 = np.asarray(d64, np.float64)
    n = d64.shape[0]
    if n < 3:
        raise ValueError(f"1-tree bound needs n >= 3 cities, got {n}")
    pi = np.zeros(n)
    w0, _ = one_tree_np(d64, pi)
    t0 = max(w0, 1.0) / (2.0 * n)
    decay = 1e-3 ** (1.0 / max(steps, 1))
    best_pi, best_w = pi.copy(), -np.inf
    t = t0
    for _ in range(steps):
        w, deg = one_tree_np(d64, pi)
        if w > best_w:
            best_w = w
            best_pi = pi.copy()
        pi = pi + t * (deg - 2)
        t *= decay
    return best_pi, best_w


def one_tree_value_np(d64, pi64) -> float:
    """Host float64 re-evaluation of ``w(pi)`` for given potentials.

    The on-device ascent runs in float32, whose rounding can OVERstate the
    1-tree value — unusable as a certified lower bound. This recomputes
    ``onetree(d + pi_i + pi_j) - 2*sum(pi)`` with numpy float64 (Prim's
    O(n^2)), so the reported root bound is true to ~1e-12 relative.
    """
    import numpy as np

    d64 = np.asarray(d64, np.float64)
    pi64 = np.asarray(pi64, np.float64)
    n = d64.shape[0]
    dbar = d64 + pi64[:, None] + pi64[None, :]
    np.fill_diagonal(dbar, np.inf)
    # Prim over vertices 1..n-1
    sub = dbar[1:, 1:]
    m = n - 1
    in_tree = np.zeros(m, bool)
    in_tree[0] = True
    mindist = sub[0].copy()
    cost = 0.0
    for _ in range(m - 1):
        cand = np.where(in_tree, np.inf, mindist)
        u = int(np.argmin(cand))
        cost += cand[u]
        in_tree[u] = True
        mindist = np.minimum(mindist, sub[u])
    e0 = np.sort(dbar[0, 1:])[:2].sum()
    return float(cost + e0 - 2.0 * pi64.sum())


# NOTE: the B&B weight/adjustment arrays derived from these potentials
# (weights[u] = min reduced outgoing edge - 2*pi[u], bound_adj[v] =
# pi[v] - pi[0]) are built in models.branch_bound._bound_setup, which owns
# the float32 quantization/slack logic that makes them certified bounds.

"""Fused Pallas push for the B&B expansion step (ISSUE 8 tentpole).

STEP_PROFILE_FINE_TPU.json prices the push scatter at 4.5-6.9 ms of a
~5 ms TPU expansion step while the pop gather and the two-level sort
cost ~0.3 ms each: the step is not compute-bound, it is bound on the
memory traffic of materializing the [k*n, C] candidate-row block —
write it, gather-compact it, write the compacted block again — of
which typically >90% is garbage (most candidates are pruned). This is
the FlashAttention situation (Dao et al., NeurIPS '22, PAPERS.md): the
win is not FLOPs but never materializing the intermediate.

``push_rows`` is that fusion: ONE Pallas kernel that walks the popped
parents and, per parent, builds each surviving child's packed node row
IN REGISTERS/VMEM (int8-packed path byte-set + visited-mask word OR +
the four scalar columns) and stores it directly at its prefix-sum slot
in the frontier buffer — which is input/output-ALIASED, so the push is
a true in-place write riding the engine's donation discipline (lint R7
/ contracts.check_donated cover the jit entry that traces this call).
The candidate block never exists; per step the kernel reads ~k rows +
four [k, n] scalar planes and writes exactly the pushed rows.

Division of labor with ``models.branch_bound._expand_step`` (the ONE
dispatch both kernels live inside):

- the bound screen, completion/incumbent reduction, push flags, and the
  destination prefix-sum stay in XLA — [k] / [k, n] scalar planes, two
  orders of magnitude smaller than row traffic, and sharing them is
  what makes the fused and reference paths BIT-IDENTICAL by
  construction (same flags, same slots, same float columns; only the
  row materialization + write differ);
- this kernel replaces the cand-concat + compacting gather + block
  write — the measured dominant cost.

Ordering support: the destination slots come in pre-computed, so both
``push_order`` modes (two-level best-first sort, natural prefix-sum)
work unchanged through the fused path.

Like ops/prim_pallas.py and ops/held_karp_pallas.py the kernel is
OPT-IN (``--step-kernel=fused`` / TSP_BENCH_STEP_KERNEL) and falls
back to interpret mode off-TPU, where the parity suite
(tests/test_expand_pallas.py) pins fused == reference bit-exactness.
COMPILED use additionally requires the frontier buffer to fit the
conservative VMEM budget below (the kernel addresses the whole buffer
as one block; the HBM-resident DMA variant is future work and must be
validated on-chip first — no TPU was attached when this kernel
landed), and is refused loudly otherwise, mirroring prim_chain's
n > 128 refusal.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

#: city ids per int32 path word — MUST match branch_bound.PATH_PACK
#: (duplicated to keep ops -> models import direction clean; pinned by
#: tests/test_expand_pallas.py)
PATH_PACK = 4

#: compiled-mode ceiling on the aliased frontier block, in bytes. The
#: kernel maps the whole [F, C] buffer as one block; ~16 MB VMEM/core
#: minus working tiles leaves roughly this. Interpret mode is unlimited.
VMEM_BUDGET_BYTES = 12 * (1 << 20)


def _set_bit_words(n: int) -> np.ndarray:
    """[n, W] int32 words: OR-ing row j into a visited mask visits city
    j (same table as branch_bound._mask_consts, int32 view)."""
    w = (n + 31) // 32
    out = np.zeros((n, w), np.uint32)
    out[np.arange(n), np.arange(n) // 32] = np.uint32(1) << (
        np.arange(n) % 32
    ).astype(np.uint32)
    return out.view(np.int32)


def _push_kernel(
    nodes_ref, parents_ref, dest_ref, ccost_ref, cbound_ref, csum_ref,
    setbit_ref, out_ref, *, n: int, pw: int, w: int, f_phys: int,
    copy_input: bool,
):
    """One grid step = one popped parent: build its n candidate child
    rows and store the pushed ones at their destination slots.

    nodes_ref/out_ref: [F, C] aliased frontier buffer
    parents_ref:       [1, C] this parent's packed row
    dest_ref:          [1, n] absolute destination row per child
                       (>= f_phys = pruned: not stored)
    ccost/cbound/csum: [1, n] child float columns as int32 bit patterns
                       (bitcast OUTSIDE the kernel, so the stored bits
                       are exactly the reference path's)
    setbit_ref:        [n, W] visited-mask OR table
    ``copy_input``:    seed the output from the input ONCE, at grid
                       step 0, so rows the push never touches persist.
                       Required in BOTH modes: input_output_aliases
                       aliases the HBM buffers, not the VMEM output
                       block — without the seed, compiled copy-out
                       would replace every un-pushed row with
                       uninitialized VMEM contents. Under the alias the
                       seed is an HBM->VMEM->HBM round trip of bytes
                       that are already correct — the price of the
                       whole-buffer-block form; the future HBM/DMA
                       variant writes rows directly and drops it.
    """
    if copy_input:
        @pl.when(pl.program_id(0) == 0)
        def _():
            out_ref[:] = nodes_ref[:]

    row = parents_ref[0, :]
    pathw = row[:pw]  # [pw] packed path words
    maskw = row[pw : pw + w]  # [w] visited mask words
    depth = row[pw + w]  # scalar int32
    dpos = jnp.minimum(depth, n - 1)
    wsel = dpos // PATH_PACK
    shift = (dpos % PATH_PACK) * 8

    # child path words: parent words with child c's id byte-set at the
    # prefix position — the packed-layout analog of the reference's
    # [k, n, n] broadcast+where, built at [n, pw] instead
    widx = jax.lax.broadcasted_iota(jnp.int32, (n, pw), 1)
    cities = jax.lax.broadcasted_iota(jnp.int32, (n, 1), 0)
    pwb = jnp.broadcast_to(pathw[None, :], (n, pw))
    neww = (pwb & ~(0xFF << shift)) | (cities << shift)
    child_pathw = jnp.where(widx == wsel, neww, pwb)
    child_maskw = jnp.broadcast_to(maskw[None, :], (n, w)) | setbit_ref[...]

    tile = jnp.concatenate(
        [
            child_pathw,
            child_maskw,
            jnp.full((n, 1), depth + 1, jnp.int32),
            ccost_ref[0, :][:, None],
            cbound_ref[0, :][:, None],
            csum_ref[0, :][:, None],
        ],
        axis=1,
    )  # [n, C]

    def body(c, carry):
        dst = dest_ref[0, c]

        @pl.when(dst < f_phys)
        def _():
            out_ref[pl.ds(dst, 1), :] = jax.lax.dynamic_slice(
                tile, (c, 0), (1, tile.shape[1])
            )

        return carry

    jax.lax.fori_loop(0, n, body, 0)


def push_rows(
    nodes: jnp.ndarray,
    parents: jnp.ndarray,
    dest: jnp.ndarray,
    ccost: jnp.ndarray,
    cbound: jnp.ndarray,
    csum: jnp.ndarray,
    n: int,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """Fused in-place push: returns ``nodes`` with every child whose
    ``dest`` row is < F written as a freshly built packed node row.

    nodes:   [F, C] int32 packed frontier buffer (ALIASED in place)
    parents: [k, C] int32 popped parent rows
    dest:    [k, n] int32 absolute destination rows (>= F = don't push)
    ccost/cbound/csum: [k, n] float32 child columns (bitcast to int32
             bit patterns here — stored bits match the reference path)
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    f_phys, cols = int(nodes.shape[0]), int(nodes.shape[1])
    k = int(parents.shape[0])
    pw = (n + PATH_PACK - 1) // PATH_PACK
    w = (n + 31) // 32
    if pw + w + 4 != cols:
        raise ValueError(
            f"frontier row width {cols} does not match n={n} "
            f"(expected {pw + w + 4})"
        )
    if not interpret and f_phys * cols * 4 > VMEM_BUDGET_BYTES:
        # compiled mode maps the whole aliased buffer as one block; a
        # buffer past the VMEM budget needs the (unvalidated) HBM/DMA
        # variant — refuse loudly, as prim_chain does for n > 128
        raise ValueError(
            f"fused step kernel: frontier buffer {f_phys}x{cols} int32 "
            f"({f_phys * cols * 4} bytes) exceeds the compiled VMEM "
            f"budget ({VMEM_BUDGET_BYTES}); lower capacity/k or use "
            "--step-kernel=reference"
        )
    setbit = jnp.asarray(_set_bit_words(n))
    bits = functools.partial(jax.lax.bitcast_convert_type, new_dtype=jnp.int32)
    # Every mode seeds the output from the input at grid step 0
    # (copy_input in _push_kernel): the kernel writes only pushed rows,
    # and neither interpret mode (no alias declared — its emulation of
    # input_output_aliases on this jax 0.4.37 cannot be validated
    # off-chip) nor compiled mode (the alias pairs the HBM buffers, not
    # the VMEM output block) preserves untouched rows by itself. The
    # alias is declared only when compiled, where it is load-bearing
    # for the in-place push; the engine-level donation at the
    # _expand_step dispatch is unaffected either way.
    kernel = functools.partial(
        _push_kernel, n=n, pw=pw, w=w, f_phys=f_phys, copy_input=True,
    )
    alias = {} if interpret else {0: 0}
    return pl.pallas_call(
        kernel,
        grid=(k,),
        in_specs=[
            pl.BlockSpec((f_phys, cols), lambda i: (0, 0)),
            pl.BlockSpec((1, cols), lambda i: (i, 0)),
            pl.BlockSpec((1, n), lambda i: (i, 0)),
            pl.BlockSpec((1, n), lambda i: (i, 0)),
            pl.BlockSpec((1, n), lambda i: (i, 0)),
            pl.BlockSpec((1, n), lambda i: (i, 0)),
            pl.BlockSpec((n, w), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((f_phys, cols), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((f_phys, cols), jnp.int32),
        input_output_aliases=alias,
        interpret=bool(interpret),
    )(
        nodes, parents, dest.astype(jnp.int32), bits(ccost), bits(cbound),
        bits(csum), setbit,
    )

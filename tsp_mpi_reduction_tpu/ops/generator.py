"""Blocked-instance generator: bit-exact replica of the reference's semantics.

Reference behavior being replicated (all quirks intentional, SURVEY.md §5):

- ``getBlocksPerDim`` (tsp.cpp:136-157): near-square factorization — perfect
  square -> sqrt x sqrt, else smallest divisor >= 2 times cofactor (a prime p
  factors as p x 1).
- ``distributeCities`` (tsp.cpp:373-403): for block ``i`` of ``rows x cols``
  blocks, ``row = i / rows`` (integer division) and
  ``col = cols - (i % cols) - 1``; each city draws x then y via ``fRand``
  (assignment2.h:86-91) over ``[row*xspb, (row+1)*xspb] x [col*yspb,
  (col+1)*yspb]``. City ids are global and sequential in generation order.
- **float32 spacing quirk** (tsp.cpp:378-379): ``xSpacePerBlock =
  gridDimX / (float)numBlocksInRow`` is C ``float`` arithmetic; the products
  ``row * xSpacePerBlock`` are float32 too, only the final fRand mix runs in
  double. Replicated here with ``np.float32``.
- **grid-spill quirk** (SURVEY.md quirk #3): because ``row`` ranges up to
  ``cols - 1`` but is scaled by ``gridDimX / rows``, non-square factorizations
  place cities outside the nominal grid. Reproduced faithfully — it changes
  every downstream cost.

Blocks are returned as dense arrays (ids ``[B, n]`` int32, coords ``[B, n, 2]``
float64): the TPU framework's instances are *born sharded* — there is no analog
of the reference's rank-0 scatter (tsp.cpp:159-195).
"""

from __future__ import annotations

import math
from typing import Tuple

import numpy as np

from .rand import GlibcRand


def is_square(x: int) -> bool:
    """Replica of ``ISSQUARE`` (assignment2.h:11): float sqrt residue test."""
    s = math.sqrt(x)
    return s - math.floor(s) == 0.0


def get_blocks_per_dim(num_blocks: int) -> Tuple[int, int]:
    """Near-square factorization (tsp.cpp:136-157): returns (rows, cols)."""
    if is_square(num_blocks):
        r = int(math.sqrt(num_blocks))
        return r, r
    divisor = 2
    while num_blocks % divisor != 0:
        divisor += 1
    return divisor, num_blocks // divisor


def generate_blocked_cities(
    num_cities_per_block: int,
    rows: int,
    cols: int,
    grid_dim_x: int,
    grid_dim_y: int,
    seed: int = 0,
) -> Tuple[np.ndarray, np.ndarray]:
    """Generate ``rows*cols`` blocks of cities, bit-exact vs the oracle.

    Returns ``(ids, xy)`` with shapes ``[B, n]`` int32 and ``[B, n, 2]``
    float64, matching the reference's generation order (block-major,
    city-minor, x before y — tsp.cpp:384-399).
    """
    n = num_cities_per_block
    num_blocks = rows * cols
    rng = GlibcRand(seed)

    # float32 spacing, as in the reference (tsp.cpp:378-379)
    xspb = np.float32(grid_dim_x) / np.float32(rows)
    yspb = np.float32(grid_dim_y) / np.float32(cols)

    # raw 31-bit rand stream, two draws per city in x,y order
    raw = rng.fill(2 * num_blocks * n).astype(np.float64) / float(2147483647)
    raw = raw.reshape(num_blocks, n, 2)

    i = np.arange(num_blocks)
    row = i // rows  # (i - i % rows) / rows == i // rows (tsp.cpp:391)
    col = (cols - (i % cols)) - 1  # tsp.cpp:393

    # fRand(fmin, fmax) = fmin + f * (fmax - fmin), bounds are float32 products
    # widened to double at the call (tsp.cpp:394-395)
    x_lo = (row.astype(np.float32) * xspb).astype(np.float64)
    x_hi = ((row + 1).astype(np.float32) * xspb).astype(np.float64)
    y_lo = (col.astype(np.float32) * yspb).astype(np.float64)
    y_hi = ((col + 1).astype(np.float32) * yspb).astype(np.float64)

    xy = np.empty((num_blocks, n, 2), dtype=np.float64)
    xy[:, :, 0] = x_lo[:, None] + raw[:, :, 0] * (x_hi - x_lo)[:, None]
    xy[:, :, 1] = y_lo[:, None] + raw[:, :, 1] * (y_hi - y_lo)[:, None]

    ids = np.arange(num_blocks * n, dtype=np.int32).reshape(num_blocks, n)
    return ids, xy


def generate_instance(
    num_cities_per_block: int,
    num_blocks: int,
    grid_dim_x: int,
    grid_dim_y: int,
    seed: int = 0,
) -> Tuple[np.ndarray, np.ndarray]:
    """Full instance as ``main()`` builds it (tsp.cpp:312-314)."""
    rows, cols = get_blocks_per_dim(num_blocks)
    return generate_blocked_cities(
        num_cities_per_block, rows, cols, grid_dim_x, grid_dim_y, seed
    )

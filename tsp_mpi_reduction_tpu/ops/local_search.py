"""Device-side tour local search: jitted 2-opt / Or-opt sweeps.

The reference's only tour-quality device is the pairwise merge heuristic
(mergeBlocks, tsp.cpp:202-269), whose reported cost is formulaic and whose
output is never re-optimized. This module adds what a TPU makes cheap:
best-improvement 2-opt where every candidate reversal is scored at once as
a broadcasted [n, n] delta matrix (two gathers + adds on the VPU), applied
via an index remap — no data-dependent shapes, so the full
improve-until-converged loop jits into one ``lax.while_loop`` program and
``vmap``s over tour batches.

Used for: B&B incumbent seeding on large TSPLIB instances
(models.branch_bound), optional post-merge polish in the pipeline, and as
the per-segment kernel of the ring sequence-parallel improver
(parallel.seq_improve).
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp

INF = jnp.inf


def _reversal_deltas(t: jnp.ndarray, d: jnp.ndarray, closed: bool) -> jnp.ndarray:
    """Delta cost of reversing t[i+1..j] for every edge pair (i < j).

    ``t``: [n] open tour order. Edges are (t[i], t[i+1]) for i < n-1, plus
    the wrap edge (t[n-1], t[0]) when ``closed``. Reversing the segment
    between edges i and j replaces d(a_i,b_i)+d(a_j,b_j) with
    d(a_i,a_j)+d(b_i,b_j). Invalid pairs are +inf.
    """
    n = t.shape[0]
    nxt = jnp.concatenate([t[1:], t[:1]])
    a, b = t, nxt  # edge i = (a[i], b[i]); edge n-1 is the wrap edge
    da = d[a[:, None], a[None, :]] + d[b[:, None], b[None, :]]
    db = d[a, b][:, None] + d[a, b][None, :]
    delta = da - db
    i_ = jnp.arange(n)[:, None]
    j_ = jnp.arange(n)[None, :]
    valid = j_ >= i_ + 2  # adjacent edges -> no-op reversal
    if closed:
        # wrap edge participates, but pair (0, n-1) is the identity again
        valid = valid & ~((i_ == 0) & (j_ == n - 1))
    else:
        valid = valid & (j_ <= n - 2)  # open path: no wrap edge
    return jnp.where(valid, delta, INF)


@partial(jax.jit, static_argnames=("closed", "max_iters"))
def two_opt_sweep(
    t: jnp.ndarray, d: jnp.ndarray, closed: bool = True, max_iters: int = 512
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Best-improvement 2-opt until converged -> (tour', total_delta).

    ``t``: [n] int32 tour order (open layout; the closing edge t[-1]->t[0]
    is implied when ``closed``). For ``closed=False`` the endpoints are
    pinned (used for path segments inside the ring improver).
    """
    n = t.shape[0]
    ar = jnp.arange(n)

    def cond(carry):
        _, go, it, _ = carry
        return go & (it < max_iters)

    def body(carry):
        t, _, it, acc = carry
        delta = _reversal_deltas(t, d, closed)
        flat = jnp.argmin(delta.reshape(-1))
        i, j = flat // n, flat % n
        dbest = delta.reshape(-1)[flat]
        improve = dbest < -1e-6
        # reverse t[i+1..j] via an index remap (identity when not improving)
        in_seg = (ar >= i + 1) & (ar <= j)
        src = jnp.where(in_seg & improve, j - ar + i + 1, ar)
        return t[src], improve, it + 1, acc + jnp.where(improve, dbest, 0.0)

    # derive the initial carries from ``t`` so their varying-axis type
    # matches the body outputs under shard_map (see shard_map vma docs)
    zero = t[0] * 0
    t, _, _, acc = jax.lax.while_loop(
        cond, body, (t, zero == 0, zero, zero.astype(d.dtype))
    )
    return t, acc


@partial(jax.jit, static_argnames=("closed",))
def tour_length(t: jnp.ndarray, d: jnp.ndarray, closed: bool = True) -> jnp.ndarray:
    """Length of tour order ``t`` under distance matrix ``d``."""
    seg = d[t[:-1], t[1:]].sum()
    return seg + (d[t[-1], t[0]] if closed else 0.0)


def two_opt_batch(tours: jnp.ndarray, d: jnp.ndarray, closed: bool = True):
    """``vmap`` of :func:`two_opt_sweep` over a [B, n] batch, shared ``d``."""
    return jax.vmap(lambda t: two_opt_sweep(t, d, closed))(tours)

"""Device-side tour local search: jitted 2-opt / Or-opt sweeps.

The reference's only tour-quality device is the pairwise merge heuristic
(mergeBlocks, tsp.cpp:202-269), whose reported cost is formulaic and whose
output is never re-optimized. This module adds what a TPU makes cheap:
best-improvement 2-opt where every candidate reversal is scored at once as
a broadcasted [n, n] delta matrix (two gathers + adds on the VPU), applied
via an index remap — no data-dependent shapes, so the full
improve-until-converged loop jits into one ``lax.while_loop`` program and
``vmap``s over tour batches.

Used for: B&B incumbent seeding on large TSPLIB instances
(models.branch_bound), optional post-merge polish in the pipeline, and as
the per-segment kernel of the ring sequence-parallel improver
(parallel.seq_improve).
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp

INF = jnp.inf


def _reversal_deltas(t: jnp.ndarray, d: jnp.ndarray, closed: bool) -> jnp.ndarray:
    """Delta cost of reversing t[i+1..j] for every edge pair (i < j).

    ``t``: [n] open tour order. Edges are (t[i], t[i+1]) for i < n-1, plus
    the wrap edge (t[n-1], t[0]) when ``closed``. Reversing the segment
    between edges i and j replaces d(a_i,b_i)+d(a_j,b_j) with
    d(a_i,a_j)+d(b_i,b_j). Invalid pairs are +inf.
    """
    n = t.shape[0]
    nxt = jnp.concatenate([t[1:], t[:1]])
    a, b = t, nxt  # edge i = (a[i], b[i]); edge n-1 is the wrap edge
    # d[a_i, a_j] as TWO row gathers (d[a] then columns via transpose)
    # instead of an n*n random ELEMENT gather — element gathers pay
    # per-element on TPU and dominated the polish fold's 34.5 s wall;
    # and since b is a's cyclic successor, d[b_i, b_j] is just the
    # (-1, -1) roll of the same permuted matrix
    daa = d[a].T[a].T
    dbb = jnp.roll(daa, (-1, -1), (0, 1))
    da = daa + dbb
    db = d[a, b][:, None] + d[a, b][None, :]
    delta = da - db
    i_ = jnp.arange(n)[:, None]
    j_ = jnp.arange(n)[None, :]
    valid = j_ >= i_ + 2  # adjacent edges -> no-op reversal
    if closed:
        # wrap edge participates, but pair (0, n-1) is the identity again
        valid = valid & ~((i_ == 0) & (j_ == n - 1))
    else:
        valid = valid & (j_ <= n - 2)  # open path: no wrap edge
    return jnp.where(valid, delta, INF)


@partial(jax.jit, static_argnames=("closed", "max_iters"))
def two_opt_sweep(
    t: jnp.ndarray, d: jnp.ndarray, closed: bool = True, max_iters: int = 512
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Best-improvement 2-opt until converged -> (tour', total_delta).

    ``t``: [n] int32 tour order (open layout; the closing edge t[-1]->t[0]
    is implied when ``closed``). For ``closed=False`` the endpoints are
    pinned (used for path segments inside the ring improver).
    """
    n = t.shape[0]
    ar = jnp.arange(n)
    thr = _improve_threshold(d)

    def cond(carry):
        _, go, it, _ = carry
        return go & (it < max_iters)

    def body(carry):
        t, _, it, acc = carry
        delta = _reversal_deltas(t, d, closed)
        flat = jnp.argmin(delta.reshape(-1))
        i, j = flat // n, flat % n
        dbest = delta.reshape(-1)[flat]
        improve = dbest < thr
        # reverse t[i+1..j] via an index remap (identity when not improving)
        in_seg = (ar >= i + 1) & (ar <= j)
        src = jnp.where(in_seg & improve, j - ar + i + 1, ar)
        return t[src], improve, it + 1, acc + jnp.where(improve, dbest, 0.0)

    # derive the initial carries from ``t`` so their varying-axis type
    # matches the body outputs under shard_map (see shard_map vma docs)
    zero = t[0] * 0
    t, _, _, acc = jax.lax.while_loop(
        cond, body, (t, zero == 0, zero, zero.astype(d.dtype))
    )
    return t, acc


def _improve_threshold(d: jnp.ndarray) -> jnp.ndarray:
    """Accept-move threshold scaled to the distance magnitude.

    Delta entries are f32/f64 sums of four ``d`` entries, so their rounding
    noise scales with ``max(d)``; a fixed absolute epsilon would let noise
    moves churn (and break the sweeps' monotone-termination property) on
    large-coordinate instances. Improvements below ~32 ulp of the largest
    edge are noise-level and skipped.
    """
    finite = jnp.where(jnp.isfinite(d), d, 0.0)
    return -(32.0 * jnp.finfo(d.dtype).eps * jnp.max(finite) + 1e-9)


def _relocation_deltas(t: jnp.ndarray, d: jnp.ndarray, L: int) -> jnp.ndarray:
    """Delta of moving the length-``L`` segment at position i to after
    position j, for every (i, j) on a closed tour. Invalid pairs are +inf.

    Segments may not wrap the linear layout (i + L <= n); the insertion
    edge may be the closing edge (j = n-1). delta = (bridge the gap left
    behind) + (splice into edge j) - (removed edges).
    """
    n = t.shape[0]
    ar = jnp.arange(n)
    pred = t[(ar - 1) % n]  # t[i-1]
    seg_end = t[(ar + L - 1) % n]
    succ = t[(ar + L) % n]
    jnxt = t[(ar + 1) % n]
    remove = d[pred, succ] - d[pred, t] - d[seg_end, succ]  # [i]
    # both [n, n] terms come from ONE permuted matrix d_tt[i, j] =
    # d[t[i], t[j]] (two row gathers — see _reversal_deltas on why
    # element gathers are avoided): d[t[j], t[i]] is its transpose and
    # d[seg_end_i, jnxt_j] its cyclic (-(L-1), -1) roll
    d_tt = d[t].T[t].T
    splice = (
        d_tt.T  # d[t[j], t[i]] at [i, j]
        + jnp.roll(d_tt, (-(L - 1), -1), (0, 1))
        - d[t, jnxt][None, :]
    )
    delta = remove[:, None] + splice
    i_ = ar[:, None]
    j_ = ar[None, :]
    # j may not touch the segment or its predecessor edge (identity/overlap)
    valid = ((j_ - (i_ - 1)) % n > L) & (i_ + L <= n)
    return jnp.where(valid, delta, INF)


def _apply_relocation(t: jnp.ndarray, i, L: int, j) -> jnp.ndarray:
    """Move segment t[i:i+L] to sit after position j (linear layout)."""
    ar = jnp.arange(t.shape[0])
    # forward (j >= i+L): the gap closes leftward, block lands at j-L+1..j
    src_f = jnp.where((ar >= i) & (ar <= j - L), ar + L, ar)
    src_f = jnp.where((ar >= j - L + 1) & (ar <= j), i + (ar - (j - L + 1)), src_f)
    # backward (j <= i-2): block lands at j+1..j+L, the gap closes rightward
    src_b = jnp.where((ar >= j + 1) & (ar <= j + L), i + (ar - j - 1), ar)
    src_b = jnp.where((ar >= j + L + 1) & (ar <= i + L - 1), ar - L, src_b)
    return t[jnp.where(j >= i, src_f, src_b)]


@partial(jax.jit, static_argnames=("max_iters",))
def or_opt_sweep(
    t: jnp.ndarray, d: jnp.ndarray, max_iters: int = 256
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Best-improvement Or-opt (relocate segments of length 1-3) on a
    closed tour until converged -> (tour', total_delta).

    Complements 2-opt: relocation moves are not reachable by reversals, so
    alternating the two sweeps (:func:`polish`) escapes each other's local
    optima.
    """
    n = t.shape[0]
    lengths = (1, 2, 3)
    thr = _improve_threshold(d)

    def cond(carry):
        _, go, it, _ = carry
        return go & (it < max_iters)

    def body(carry):
        t, _, it, acc = carry
        deltas = jnp.stack([_relocation_deltas(t, d, L) for L in lengths])
        flat = jnp.argmin(deltas.reshape(-1))
        dbest = deltas.reshape(-1)[flat]
        li = flat // (n * n)
        i = (flat // n) % n
        j = flat % n
        improve = dbest < thr
        cands = [_apply_relocation(t, i, L, j) for L in lengths]
        moved = jnp.select([li == x for x in range(len(lengths))], cands, t)
        t = jnp.where(improve, moved, t)
        return t, improve, it + 1, acc + jnp.where(improve, dbest, 0.0)

    zero = t[0] * 0
    t, _, _, acc = jax.lax.while_loop(
        cond, body, (t, zero == 0, zero, zero.astype(d.dtype))
    )
    return t, acc


@partial(jax.jit, static_argnames=("max_rounds",))
def polish(
    t: jnp.ndarray, d: jnp.ndarray, max_rounds: int = 8
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Alternate 2-opt and Or-opt sweeps until neither improves.

    Returns (tour', total_delta). Each constituent sweep is monotone, so
    the combined loop is monotone and terminates.
    """

    def cond(carry):
        _, go, r, _ = carry
        return go & (r < max_rounds)

    def body(carry):
        t, _, r, acc = carry
        t, d1 = two_opt_sweep(t, d, closed=True)
        t, d2 = or_opt_sweep(t, d)
        # each applied move cleared the scaled threshold, so any progress
        # at all shows up as a strictly negative sum (exact 0.0 otherwise)
        improved = (d1 + d2) < 0
        return t, improved, r + 1, acc + d1 + d2

    zero = t[0] * 0
    t, _, _, acc = jax.lax.while_loop(
        cond, body, (t, zero == 0, zero, zero.astype(d.dtype))
    )
    return t, acc


@partial(jax.jit, static_argnames=("closed",))
def tour_length(t: jnp.ndarray, d: jnp.ndarray, closed: bool = True) -> jnp.ndarray:
    """Length of tour order ``t`` under distance matrix ``d``."""
    seg = d[t[:-1], t[1:]].sum()
    return seg + (d[t[-1], t[0]] if closed else 0.0)


def two_opt_batch(tours: jnp.ndarray, d: jnp.ndarray, closed: bool = True):
    """``vmap`` of :func:`two_opt_sweep` over a [B, n] batch, shared ``d``."""
    return jax.vmap(lambda t: two_opt_sweep(t, d, closed))(tours)

"""Pairwise Euclidean distances as one broadcasted op.

Reference: ``computeDistanceMatrix`` (assignment2.h:184-200) builds the dense
n x n matrix with a double loop of ``sqrt(pow(dx,2) + pow(dy,2))``; here it is
a single broadcasted pairwise-norm that XLA tiles onto the VPU/MXU. The op
sequence (square dx, square dy, add, sqrt — each correctly rounded) matches
the C library's, so results are bit-exact vs the oracle in float64.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np


def distance_matrix(xy: jnp.ndarray) -> jnp.ndarray:
    """``[..., n, 2]`` coords -> ``[..., n, n]`` Euclidean distances (device).

    This is the TPU speed path. NOTE: under ``jit`` XLA may contract the
    ``dx*dx + dy*dy`` multiply-add into an FMA, which skips one intermediate
    rounding; results can differ from the C oracle by 1 ULP. Bit-exact parity
    runs therefore use :func:`distance_matrix_np` on the host instead (the
    contraction is an LLVM-level decision that survives
    ``optimization_barrier`` and bitcast round-trips).
    """
    diff = xy[..., :, None, :] - xy[..., None, :, :]
    return jnp.sqrt(jnp.sum(diff * diff, axis=-1))


def distance_matrix_np(xy: np.ndarray) -> np.ndarray:
    """Host (numpy) distance matrix, bit-exact vs the C oracle in float64.

    numpy's multiply/add/sqrt are correctly rounded and applied in the same
    dependency order as the reference's ``sqrt(pow(dx,2) + pow(dy,2))``
    (assignment2.h:141-144, 196); verified identical to a g++/glibc build on
    oracle coordinates.
    """
    xy = np.asarray(xy, dtype=np.float64)
    diff = xy[..., :, None, :] - xy[..., None, :, :]
    return np.sqrt(np.sum(diff * diff, axis=-1))


def edge_length(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Distance between point arrays ``a`` and ``b`` (``[..., 2]`` each).

    Device-side; same 1-ULP FMA caveat as :func:`distance_matrix`.
    """
    diff = a - b
    return jnp.sqrt(jnp.sum(diff * diff, axis=-1))

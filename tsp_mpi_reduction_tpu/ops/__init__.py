"""Numeric building blocks: RNG replica, instance generator, DP solver, merge."""

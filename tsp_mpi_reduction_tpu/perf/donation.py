"""Donating in-place writes for the device frontier buffer.

The physical node buffer is the engine's dominant allocation (capacity +
k*n padding rows of packed int32 — hundreds of MB at kroA100 scale). The
host-side spill writebacks used ``nodes.at[:take].set(keep)`` OUTSIDE jit,
which XLA lowers to copy-the-buffer-then-scatter: a full-buffer
materialization per spill even though only the kept prefix changes. These
helpers run the same update under ``jit`` with the buffer DONATED, so XLA
aliases the output onto the input allocation and writes only the updated
rows in place (verified by pointer identity in tests/test_perf.py).

Donated inputs are consumed: jax marks the caller's array deleted, so an
accidental re-read raises instead of silently using stale data —
``analysis.contracts.check_donated`` turns that invariant into an explicit
post-dispatch contract at the solver call sites.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


@partial(jax.jit, donate_argnums=(0,))
def set_rows_donated(nodes: jnp.ndarray, rows: jnp.ndarray) -> jnp.ndarray:
    """``nodes[:rows.shape[0]] = rows`` with ``nodes`` donated (aliased in
    place). Row-count shapes are few per solve (each distinct kept-slice
    height compiles one tiny dynamic_update_slice — the same per-shape
    cost the previous out-of-jit ``.at[].set`` already paid, minus the
    whole-buffer copy)."""
    start = jnp.zeros((), jnp.int32)
    return jax.lax.dynamic_update_slice(nodes, rows, (start, start))


@partial(jax.jit, donate_argnums=(0,))
def set_rank_rows_donated(
    nodes: jnp.ndarray, ridx: jnp.ndarray, block: jnp.ndarray
) -> jnp.ndarray:
    """Sharded-stack analog: write ``block`` ([len(ridx), t, width]) into
    rank rows ``ridx`` at column prefix ``[:t]``, donating the stacked
    buffer. One rectangular scatter, zero whole-buffer copies."""
    return nodes.at[ridx, : block.shape[1]].set(block)

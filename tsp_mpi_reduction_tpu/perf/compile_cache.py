"""Persistent executable cache: compile each hot entry ONCE per config.

Three layers, all rooted in one repo-managed directory (``TSP_COMPILE_CACHE``;
``off``/``0`` disables, unset uses ``~/.cache/tsp_mpi_reduction_tpu/jax_cache``):

1. **jax persistent compilation cache** (:func:`enable`): every
   ``jit``/``lower().compile()`` in the process consults the on-disk cache,
   so a fresh process (each ``bnb_chunked.py`` chunk, every CLI run) pays a
   cache *load* instead of the full XLA compile. Unlike the pre-PR
   ``enable_persistent_cache`` this is enabled on CPU too — XLA:CPU
   reload works (measured 2.8 s -> 0.2 s on ``_expand_loop``) and the chunk
   relay is exactly the workload that re-pays it per process.

2. **AOT serialized-executable store** (:func:`aot_load_or_compile`): the
   named hot entries (``_expand_loop``/``_solve_device``, the Held-Karp
   vmap buckets) are additionally stored as serialized XLA executables
   keyed by (entry, static-arg config, arg shapes/dtypes, jax+jaxlib
   version, backend). A hit skips BOTH the XLA compile and the Python
   re-trace (``deserialize_and_load`` returns a ready ``Compiled``).
   XLA:CPU cannot serialize every executable (thunk-runtime symbol
   references — observed on the real expansion kernel), so the store
   self-validates at write time: an executable that does not survive a
   serialize/deserialize round-trip is marked unsupported and the entry
   permanently falls back to layer 1. Load failures degrade the same way;
   a stale or corrupt file can never produce a wrong executable because
   the key covers every compile-relevant input and the XLA loader rejects
   mismatched payloads loudly.

3. **host-setup memo** (:func:`ascent_memo_get`/``put``): the f64 root
   Held-Karp ascent is deterministic in (distance matrix, bound mode,
   steps) and costs hundreds of ms per chunk process; the resulting
   potentials are memoized next to the executables so a resumed chunk's
   setup is a file read. Values are bit-identical by construction (same
   pure-numpy computation, same inputs), so results cannot drift.

All counters live in :data:`STATS` and are surfaced through
``bnb_solve.py`` / the serve stats JSON (``utils.reporting``).
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Tuple

import numpy as np

from ..obs import costs as _obs_costs
from ..obs import tracing as _obs_tracing
from ..obs.metrics import REGISTRY as _REGISTRY

#: env knob: the cache directory; "off"/"0"/"none" disables every layer
ENV_VAR = "TSP_COMPILE_CACHE"
_DISABLED = ("off", "0", "none", "disabled")


def default_cache_dir() -> str:
    return os.path.join(
        os.path.expanduser("~"), ".cache", "tsp_mpi_reduction_tpu", "jax_cache"
    )


def resolve_cache_dir() -> Optional[str]:
    """The configured cache dir, or None when caching is disabled."""
    val = os.environ.get(ENV_VAR)
    if val is None:
        return default_cache_dir()
    val = val.strip()
    if not val or val.lower() in _DISABLED:
        return None
    return val


@dataclass
class CompileCacheStats:
    """Process-global compile-cache accounting (thread-safe).

    ``aot_*`` counters cover the serialized-executable store;
    ``compile_seconds_paid`` is wall actually spent in ``lower().compile()``
    (a jax-persistent-cache hit makes it small without being zero);
    ``compile_seconds_saved`` is the sum of the recorded compile cost of
    every AOT store hit — the "would have paid" evidence the tentpole
    wants measured, not asserted."""

    aot_hits: int = 0
    aot_misses: int = 0
    aot_errors: int = 0
    aot_unsupported: int = 0
    compile_seconds_paid: float = 0.0
    compile_seconds_saved: float = 0.0
    ascent_memo_hits: int = 0
    ascent_memo_misses: int = 0
    canonical_sorts_saved: int = 0
    entries: Dict[str, Dict[str, float]] = field(default_factory=dict)
    _lock: threading.Lock = field(
        default_factory=threading.Lock, repr=False, compare=False
    )

    def record(
        self, name: str, outcome: str, seconds: float = 0.0
    ) -> None:
        # mirror onto the obs registry (ENTRY-labeled, so chunked
        # campaigns attribute compile cost per entry per chunk process —
        # the stats-JSON compile block reads back from here)
        _REGISTRY.inc(
            "compile_cache_outcomes_total", 1, entry=name, outcome=outcome
        )
        if outcome == "hit":
            _REGISTRY.inc(
                "compile_seconds_total", max(seconds, 0.0),
                entry=name, kind="saved",
            )
        elif outcome == "miss":
            _REGISTRY.inc(
                "compile_seconds_total", max(seconds, 0.0),
                entry=name, kind="paid",
            )
        with self._lock:
            e = self.entries.setdefault(
                name, {"hits": 0, "misses": 0, "errors": 0, "seconds": 0.0}
            )
            if outcome == "hit":
                self.aot_hits += 1
                e["hits"] += 1
                self.compile_seconds_saved += seconds
            elif outcome == "miss":
                self.aot_misses += 1
                e["misses"] += 1
                self.compile_seconds_paid += seconds
            elif outcome == "unsupported":
                self.aot_unsupported += 1
                e["errors"] += 1
            else:
                self.aot_errors += 1
                e["errors"] += 1
            e["seconds"] += seconds

    def incr(self, counter: str, n: int = 1) -> None:
        _REGISTRY.inc("compile_cache_events_total", n, event=counter)
        with self._lock:
            setattr(self, counter, getattr(self, counter) + n)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "dir": enabled_dir(),
                "aot_hits": self.aot_hits,
                "aot_misses": self.aot_misses,
                "aot_errors": self.aot_errors,
                "aot_unsupported": self.aot_unsupported,
                "compile_seconds_paid": round(self.compile_seconds_paid, 3),
                "compile_seconds_saved": round(self.compile_seconds_saved, 3),
                "ascent_memo_hits": self.ascent_memo_hits,
                "ascent_memo_misses": self.ascent_memo_misses,
                "canonical_sorts_saved": self.canonical_sorts_saved,
                "entries": {
                    k: dict(v) for k, v in sorted(self.entries.items())
                },
            }


STATS = CompileCacheStats()

_enable_lock = threading.Lock()
_enabled_dir: Optional[str] = None


def enabled_dir() -> Optional[str]:
    """The directory :func:`enable` activated, or None (library default:
    the cache is OPT-IN per process — drivers/benches/serve call enable(),
    plain library imports never touch the filesystem)."""
    return _enabled_dir


def enable(platform: Optional[str] = None) -> Optional[str]:
    """Point jax at the persistent compilation cache; idempotent.

    Returns the active cache dir, or None when disabled (``TSP_COMPILE_CACHE``
    set to off, or the dir cannot be created). ``platform`` is accepted for
    the legacy ``enable_persistent_cache(platform)`` call shape; the cache
    is enabled for every platform now — CPU reload was measured 13x faster
    than the cold compile on ``_expand_loop``, and the chunk relay re-pays
    the compile per process precisely on CPU fallbacks too.
    """
    del platform
    global _enabled_dir
    with _enable_lock:
        if _enabled_dir is not None:
            return _enabled_dir
        cache_dir = resolve_cache_dir()
        if cache_dir is None:
            return None
        import jax

        try:
            os.makedirs(cache_dir, exist_ok=True)
            jax.config.update("jax_compilation_cache_dir", cache_dir)
            jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.1)
        except (OSError, AttributeError, ValueError):
            return None  # unwritable dir / older jax: run uncached
        _enabled_dir = cache_dir
        return cache_dir


# -- cache keys ----------------------------------------------------------------

#: frontier node-row layout version. CANONICAL here (the engine
#: re-exports it: models.branch_bound.FRONTIER_LAYOUT_VERSION) so the
#: AOT key can carry it without a perf -> models import cycle. v2 =
#: int8-packed tour prefix (ISSUE 8); bump on ANY packed-row layout
#: change — a stale executable compiled for a previous layout would
#: read garbage columns from a donated buffer.
FRONTIER_LAYOUT_VERSION = 2



def _leaf_sig(x: Any) -> str:
    shape = tuple(getattr(x, "shape", np.shape(x)))
    dtype = getattr(x, "dtype", None)
    return f"{shape}:{np.dtype(dtype) if dtype is not None else type(x).__name__}"


def entry_key(
    name: str,
    args: Tuple[Any, ...],
    statics: Dict[str, Any],
    *,
    backend: Optional[str] = None,
    jax_version: Optional[str] = None,
) -> str:
    """Content key for one AOT entry: any change to the static-arg config,
    an arg shape or dtype, the jax/jaxlib version pair, or the backend
    yields a different key — a stale executable can never be loaded for a
    config it was not compiled for (tested in tests/test_perf.py).

    ``backend``/``jax_version`` default to the live process values; tests
    override them to prove invalidation without reinstalling jax.
    """
    import jax

    if jax_version is None:
        import jaxlib

        jax_version = f"{jax.__version__}+{jaxlib.__version__}"
    if backend is None:
        backend = jax.default_backend()
    leaves = jax.tree_util.tree_leaves(args)
    parts = [
        "v1",
        f"layout{FRONTIER_LAYOUT_VERSION}",
        name,
        jax_version,
        backend,
        ";".join(_leaf_sig(x) for x in leaves),
        ";".join(f"{k}={statics[k]!r}" for k in sorted(statics)),
    ]
    h = hashlib.blake2b(digest_size=16)
    h.update("\x1f".join(parts).encode())
    return h.hexdigest()


# -- AOT serialized-executable store ------------------------------------------


#: in-process memo of AOT executables already loaded (or compiled +
#: stored) this process: (enabled_dir, entry_key) -> (Compiled, saved_s).
#: Before this existed every solve() re-read and re-DESERIALIZED the
#: executable from disk (cProfile: ~4 ms/solve at the bench config, plus
#: a redundant cost re-capture) — the memo makes the second solve of a
#: process as cheap as the second dispatch. Keyed on the dir so tests
#: that monkeypatch ``_enabled_dir`` to a fresh tmp_path stay isolated.
_AOT_LOADED: Dict[Tuple[Optional[str], str], Tuple[Any, float]] = {}


def _aot_paths(key: str) -> Tuple[str, str, str]:
    base = os.path.join(_enabled_dir or "", "aot")
    return (
        os.path.join(base, f"{key}.jaxexec"),
        os.path.join(base, f"{key}.meta.json"),
        os.path.join(base, f"{key}.unsupported"),
    )


def _cost_memo_path(key: str) -> str:
    return os.path.join(_enabled_dir or "", "aot", f"{key}.costs.json")


def _cost_memo_put(key: str, entry: str) -> None:
    """Persist the cost record captured at compile time next to the AOT
    executable: cost analysis is a pure function of (entry config,
    backend), and warm processes often never hold a ``Compiled`` again —
    XLA:CPU marks the real hot entries unserializable, so without the
    memo every warm chunk's ``obs.device_costs`` block would be empty."""
    rec = _obs_costs.get(entry)
    if rec is None:
        return
    try:
        _atomic_write(_cost_memo_path(key), json.dumps(rec).encode())
    except OSError:
        pass  # the memo is an observer's convenience, never load-bearing


def _cost_memo_get(key: str, entry: str) -> None:
    """Rehydrate a prior process's captured costs for ``entry`` (no-op
    when absent/corrupt or already captured live this process)."""
    if _obs_costs.get(entry) is not None:
        return
    try:
        with open(_cost_memo_path(key), encoding="utf-8") as f:
            rec = json.load(f)
    except (OSError, ValueError):
        return
    if isinstance(rec, dict) and rec:
        _obs_costs.ingest(entry, rec)


def _abstract(args: Tuple[Any, ...]):
    """Concrete example args -> ShapeDtypeStructs (pytree-preserving).

    Mesh shardings are carried through: lowering a ``shard_map`` entry
    from bare shapes would bake fully-replicated input layouts into the
    executable, and the sharded solver's real (``NamedSharding``-placed)
    arrays would then fail the ``Compiled`` call's aval check on every
    dispatch. Single-device placements are deliberately dropped — solve()
    entries keep compiling exactly as before."""
    import jax
    from jax.sharding import NamedSharding

    def _sd(x):
        sharding = getattr(x, "sharding", None)
        return jax.ShapeDtypeStruct(
            tuple(getattr(x, "shape", np.shape(x))),
            np.dtype(getattr(x, "dtype", None) or np.asarray(x).dtype),
            sharding=sharding if isinstance(sharding, NamedSharding) else None,
        )

    return jax.tree_util.tree_map(_sd, args)


def _compile_entry(fn, args, statics, timer_name: Optional[str] = None):
    """``fn.lower(...).compile()`` with wall accounting. Consults (and
    populates) the jax persistent compilation cache, so a warm process
    pays the cache load, not the XLA compile."""
    entry = (timer_name or "").partition(".")[2] or None
    t0 = time.perf_counter()
    # compile phases join the span tree (a chunked campaign's trace shows
    # which chunk paid which entry's compile); no-op without a sink
    with _obs_tracing.span("compile", entry=entry or "?"):
        compiled = fn.lower(*_abstract(args), **statics).compile()
    dt = time.perf_counter() - t0
    if timer_name:
        from ..utils.profiling import COMPILE_TIMER

        COMPILE_TIMER.add(timer_name, dt)
        # entry-labeled registry series (satellite: COMPILE_TIMER's flat
        # phase dict folded compile cost into whichever consumer read it
        # first; the labeled counter gives every consumer delta reads)
        kind, _, entry = timer_name.partition(".")
        _REGISTRY.inc(
            "compile_phase_seconds_total", dt, entry=entry or kind, phase=kind
        )
    if entry:
        # cost attribution at the one moment we hold the Compiled (ISSUE
        # 9): flops/bytes/memory + roofline estimate -> obs.device_costs
        _obs_costs.capture(entry, compiled)
    return compiled, dt


def aot_load_or_compile(
    name: str,
    fn,
    args: Tuple[Any, ...],
    statics: Optional[Dict[str, Any]] = None,
):
    """Load the serialized executable for ``(name, config)`` or compile,
    validate, and store it. Returns a ready-to-call ``Compiled`` (dynamic
    args only — statics are baked in), or None when the cache is disabled
    or the entry is already marked unserializable on this backend
    (callers then use the plain jit dispatch, which still rides the
    layer-1 cache). A first-time serialization failure still returns the
    freshly compiled executable — only the cross-process store is off.

    The store is advisory, never authoritative: every failure path —
    unreadable file, deserialize error, backend without executable
    serialization — degrades to a fresh ``lower().compile()`` and counts
    itself in :data:`STATS`.
    """
    statics = statics or {}
    if _enabled_dir is None:
        return None
    key = entry_key(name, args, statics)
    memo = _AOT_LOADED.get((_enabled_dir, key))
    if memo is not None:
        loaded, saved = memo
        # same outcome the disk reload would have recorded, without the
        # per-solve file read + deserialize + cost re-capture
        STATS.record(name, "hit", saved)
        if _obs_costs.get(name) is None:
            # costs were reset in-process (tests / serve session deltas):
            # rehydrate from the compile-time sidecar memo
            _cost_memo_get(key, name)
        return loaded
    exec_path, meta_path, unsupported_path = _aot_paths(key)
    if os.path.exists(unsupported_path):
        STATS.record(name, "unsupported")
        # the jit path this falls back to never hands us a Compiled, so
        # the warm process reads the compile-time cost memo instead
        _cost_memo_get(key, name)
        return None
    from jax.experimental.serialize_executable import (
        deserialize_and_load,
        serialize,
    )

    if os.path.exists(exec_path):
        try:
            t0 = time.perf_counter()
            with _obs_tracing.span("aot_load", entry=name):
                with open(exec_path, "rb") as f:
                    payload = f.read()
                with open(meta_path) as f:
                    meta = json.load(f)
                loaded = deserialize_and_load(
                    payload,
                    _tree_from_meta(meta["in_tree"]),
                    _tree_from_meta(meta["out_tree"]),
                )
            saved = float(meta.get("compile_seconds", 0.0))
            STATS.record(name, "hit", saved)
            from ..utils.profiling import COMPILE_TIMER

            load_s = time.perf_counter() - t0
            COMPILE_TIMER.add(f"aot_load.{name}", load_s)
            _REGISTRY.inc(
                "compile_phase_seconds_total", load_s,
                entry=name, phase="aot_load",
            )
            # a deserialized executable still answers cost_analysis on
            # most backends; the memo covers the ones where it doesn't
            if _obs_costs.capture(name, loaded) is None:
                _cost_memo_get(key, name)
            _AOT_LOADED[(_enabled_dir, key)] = (loaded, saved)
            return loaded
        except Exception:  # noqa: BLE001 — any load failure = recompile
            STATS.record(name, "error")
            # fall through to the compile path; leave the file for a
            # backend that can read it (the key is backend-specific, so
            # this branch means THIS backend wrote something it cannot
            # re-read — overwrite below after re-validation)

    compiled, dt = _compile_entry(fn, args, statics, timer_name=f"compile.{name}")
    # persist the fresh capture for the warm processes that will only
    # ever see a hit/unsupported marker (see _cost_memo_put)
    _cost_memo_put(key, name)
    try:
        payload, in_tree, out_tree = serialize(compiled)
        # write-time self-validation: XLA:CPU serializes some executables
        # it cannot deserialize (thunk-runtime symbol refs — observed on
        # the real expansion kernel); such an entry would make every warm
        # start pay a failed load. Round-trip NOW and mark unsupported.
        reloaded = deserialize_and_load(payload, in_tree, out_tree)
        del reloaded
        _atomic_write(exec_path, payload)
        _atomic_write(
            meta_path,
            json.dumps(
                {
                    "entry": name,
                    "compile_seconds": dt,
                    "in_tree": _tree_to_meta(in_tree),
                    "out_tree": _tree_to_meta(out_tree),
                }
            ).encode(),
        )
        STATS.record(name, "miss", dt)
        # later solves in THIS process reuse the compiled executable
        # directly (recorded as hits, like the disk reload they replace)
        _AOT_LOADED[(_enabled_dir, key)] = (compiled, dt)
    except Exception:  # noqa: BLE001 — serialization is best-effort
        STATS.record(name, "unsupported", dt)
        try:
            _atomic_write(unsupported_path, b"")
        except OSError:
            pass
        # the in-process executable is still perfectly valid — only the
        # cross-process store is off for this entry; later processes see
        # the marker and go straight to the jit path
    return compiled


def load_or_build(
    name: str,
    fn,
    args: Tuple[Any, ...],
    statics: Optional[Dict[str, Any]] = None,
):
    """Like :func:`aot_load_or_compile` but ALWAYS returns a ready
    ``Compiled`` — when the cache is disabled (the library default) or
    the entry is marked unserializable, it still ``lower().compile()``s
    through the layer-1 persistent cache instead of returning None.

    For callers that precompile a SET of entries at setup and then
    dispatch whichever one each round picks (the sharded solver's
    per-balance-action executables, ISSUE 15): a mid-solve action switch
    must never pay a fresh trace/compile, so "cache off" cannot mean
    "compile lazily inside the timed loop"."""
    compiled = aot_load_or_compile(name, fn, args, statics)
    if compiled is None:
        compiled, _ = _compile_entry(
            fn, args, statics or {}, timer_name=f"compile.{name}"
        )
    return compiled


def warm_entry(
    name: str,
    fn,
    args: Tuple[Any, ...],
    statics: Optional[Dict[str, Any]] = None,
) -> float:
    """Precompile one entry without executing anything (serve warmup /
    bench legs). Tries the AOT store first; otherwise ``lower().compile()``
    through the layer-1 cache. Returns the wall seconds spent."""
    t0 = time.perf_counter()
    if aot_load_or_compile(name, fn, args, statics) is None:
        _compile_entry(fn, args, statics or {}, timer_name=f"compile.{name}")
    return time.perf_counter() - t0


def _tree_to_meta(tree) -> str:
    """PyTreeDefs don't JSON-serialize; pickle them through base64 (the
    payload next to them is already a pickle — same trust domain)."""
    import base64
    import pickle

    return base64.b64encode(pickle.dumps(tree)).decode()


def _tree_from_meta(blob: str):
    import base64
    import pickle

    return pickle.loads(base64.b64decode(blob.encode()))


def _atomic_write(path: str, payload: bytes) -> None:
    """Crash-safe publish (same discipline as resilience.checkpoint: a
    writer killed mid-write must not leave a truncated cache entry that
    poisons every later warm start)."""
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(payload)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


# -- host-setup memo (deterministic f64 ascent potentials) ---------------------

#: in-process LRU over the same keys as the disk memo, ALWAYS on (the
#: disk tier needs TSP_COMPILE_CACHE). Added for iteration-level serving
#: (ISSUE 13): a preempted B&B slice resumes through ``solve()`` again,
#: and re-running the ~400-step root ascent per resume costs more than
#: the slice itself — a same-process resume must pay a dict lookup.
_ASCENT_MEM_CAP = 32
_ascent_mem: "OrderedDict[str, np.ndarray]" = OrderedDict()
_ascent_mem_lock = threading.Lock()


def _ascent_path(key: str) -> str:
    return os.path.join(_enabled_dir or "", "setup", f"{key}.npy")


def ascent_key(d: np.ndarray, bound: str, steps: int) -> str:
    h = hashlib.blake2b(digest_size=16)
    a = np.ascontiguousarray(np.asarray(d, np.float64))
    h.update(f"ascent-v1:{bound}:{steps}:{a.shape}".encode())
    h.update(a.tobytes())
    return h.hexdigest()


def ascent_memo_reset_memory() -> None:
    """Drop the in-process ascent LRU (tests/conftest.py per-test
    boundary — the always-on memory tier must not leak hits across
    tests that assert cold-memo behavior)."""
    with _ascent_mem_lock:
        _ascent_mem.clear()


def _ascent_mem_get(key: str) -> Optional[np.ndarray]:
    with _ascent_mem_lock:
        pi = _ascent_mem.get(key)
        if pi is not None:
            _ascent_mem.move_to_end(key)
    return pi


def _ascent_mem_put(key: str, pi: np.ndarray) -> None:
    with _ascent_mem_lock:
        _ascent_mem[key] = pi
        _ascent_mem.move_to_end(key)
        while len(_ascent_mem) > _ASCENT_MEM_CAP:
            _ascent_mem.popitem(last=False)


def ascent_memo_get(d: np.ndarray, bound: str, steps: int) -> Optional[np.ndarray]:
    """Memoized f64 root-ascent potentials, or None. The key covers the
    exact distance bytes + bound mode + step count, and the stored value
    is the byte-exact output of the same deterministic computation — a
    hit cannot change any solver result. Two tiers: the in-process LRU
    (always on), then the disk memo (when enabled)."""
    key = ascent_key(d, bound, steps)
    pi = _ascent_mem_get(key)
    if pi is not None:
        STATS.incr("ascent_memo_hits")
        return pi.copy()
    if _enabled_dir is None:
        return None
    path = _ascent_path(key)
    if not os.path.exists(path):
        STATS.incr("ascent_memo_misses")
        return None
    try:
        pi = np.load(path)
    except (OSError, ValueError):
        STATS.incr("ascent_memo_misses")
        return None
    if pi.shape != (np.asarray(d).shape[0],):
        STATS.incr("ascent_memo_misses")  # key collision paranoia: recompute
        return None
    STATS.incr("ascent_memo_hits")
    pi = np.asarray(pi, np.float64)
    _ascent_mem_put(key, pi.copy())
    return pi


def ascent_memo_put(d: np.ndarray, bound: str, steps: int, pi: np.ndarray) -> None:
    pi = np.asarray(pi, np.float64)
    key = ascent_key(d, bound, steps)
    _ascent_mem_put(key, pi.copy())
    if _enabled_dir is None:
        return
    import io

    buf = io.BytesIO()
    np.save(buf, pi)
    try:
        _atomic_write(_ascent_path(key), buf.getvalue())
    except OSError:
        pass  # memo is an optimization; never fail a solve over it


def stats_dict() -> Dict[str, Any]:
    """The compile-cache counter block for driver/serve stats JSON."""
    return STATS.snapshot()


def compile_phase_seconds() -> Dict[str, Dict[str, float]]:
    """Per-entry compile/AOT-load seconds, read from the obs registry's
    ``compile_phase_seconds_total{entry=…, phase=…}`` series:
    ``{entry: {phase: seconds}}``. Each chunk process starts a fresh
    registry, so a chunked campaign's per-chunk JSON attributes compile
    cost to the chunk that actually paid it."""
    out: Dict[str, Dict[str, float]] = {}
    for key, v in _REGISTRY.series("compile_phase_seconds_total").items():
        labels = dict(key)
        entry = labels.get("entry", "?")
        out.setdefault(entry, {})[labels.get("phase", "?")] = round(v, 4)
    return out

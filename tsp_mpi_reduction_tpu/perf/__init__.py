"""Compile-once execution layer (ISSUE 5 tentpole).

The measured cost structure of the engine inverted: STEP_PROFILE_FINE_TPU
records 49-111 s of XLA compile per component against ~0.3-6.9 ms per
expansion step, every ``bnb_chunked.py`` chunk is a fresh process that
re-paid the full JIT, and the serve layer kept only 1.56x of the
scheduler's raw 3.76x micro-batch speedup because the host path around
the frozen kernel dominated. This package makes the compile a one-time
cost:

- :mod:`.compile_cache` — the persistent executable cache: jax's on-disk
  compilation cache pointed at a repo-managed dir (``TSP_COMPILE_CACHE``),
  an explicit AOT ``lower().compile()`` + serialized-executable store for
  the named hot entries, a deterministic host-setup memo (the f64 root
  ascent), and hit/miss/compile-seconds counters surfaced through the
  driver/serve stats JSON.
- :mod:`.donation` — donating in-place writes for the multi-hundred-MB
  frontier buffer: the spill writeback and sharded keep-slice scatter
  alias the existing device allocation instead of copying it per call
  (``_expand_loop``/``_solve_device`` donate their frontier argument at
  the jit level; these helpers cover the host-side ``.at[].set`` sites).
"""

from .compile_cache import (  # noqa: F401
    STATS,
    aot_load_or_compile,
    ascent_memo_get,
    ascent_memo_put,
    enable,
    enabled_dir,
    entry_key,
    resolve_cache_dir,
    stats_dict,
    warm_entry,
)
from .donation import set_rows_donated, set_rank_rows_donated  # noqa: F401

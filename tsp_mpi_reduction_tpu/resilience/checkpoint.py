"""Crash-safe checkpoint store: atomic publish, integrity header, rotation.

The legacy B&B checkpoint path was a bare ``np.savez_compressed(path)`` —
a crash mid-write left a truncated file that ``np.load`` could not read,
and the campaign's only snapshot was gone. This store fixes all three
failure classes:

- **atomicity**: payloads are written to a same-directory temp file,
  fsync'd, and ``os.replace``'d into place — a reader never observes a
  half-written final path;
- **integrity**: every file carries a header (magic, format version,
  instance fingerprint, payload length, blake2b checksum) so a torn or
  bit-rotted snapshot is DETECTED on read instead of exploding inside
  ``np.load`` — or worse, resuming silently wrong;
- **rotation**: the last ``keep`` good snapshots are retained
  (``path``, ``path.1``, ..., newest first), and
  :func:`read_with_fallback` walks them newest-to-oldest, returning the
  newest VALID snapshot instead of raising on the first corrupt one.
  Each fallback counts into ``HEALTH.fallback_restores``.

File layout::

    b"TSPCKPT1" | u32 header_len | header JSON | payload bytes

Legacy headerless files (bare ``.npz``, zip magic ``PK``) are still
readable — integrity is then whatever ``np.load`` can make of them.

Fault seams: ``ckpt.write`` filters the full file image before publish
(``truncate`` publishes the torn image THEN raises, modeling a writer
killed after the rename was queued; ``corrupt`` publishes silently);
``ckpt.read`` filters each candidate's bytes during restore.
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import struct
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from .faults import FaultInjected, registry
from .health import HEALTH

MAGIC = b"TSPCKPT1"
#: the process umask, captured once at import (single-threaded) — mkstemp
#: creates 0600 files, but a PUBLISHED snapshot/cache entry must carry
#: the same permissions the old ``open(path + ".tmp", "wb")`` writer gave
#: it, or a store shared between users turns read-denied after this
#: writer touches it
_UMASK = os.umask(0)
os.umask(_UMASK)
FORMAT_VERSION = 1
#: rotation depth: how many good snapshots survive (env-overridable)
DEFAULT_KEEP = 3
_LEGACY_ZIP_MAGIC = b"PK"


class CheckpointError(RuntimeError):
    """A checkpoint file failed integrity verification."""


def default_keep() -> int:
    try:
        return max(1, int(os.environ.get("TSP_CKPT_KEEP", DEFAULT_KEEP)))
    except ValueError:
        return DEFAULT_KEEP


def instance_fingerprint(d) -> str:
    """Content hash of a distance matrix: shape + exact float64 bytes.
    Deterministic across processes, so a resumed chunk can verify it is
    continuing the SAME instance before any solver state is touched."""
    a = np.ascontiguousarray(np.asarray(d, np.float64))
    h = hashlib.blake2b(digest_size=16)
    h.update(str(a.shape).encode())
    h.update(a.tobytes())
    return h.hexdigest()


def _checksum(payload: bytes) -> str:
    return hashlib.blake2b(payload, digest_size=16).hexdigest()


def pack(
    payload: bytes,
    fingerprint: Optional[str] = None,
    extra_header: Optional[Dict] = None,
) -> bytes:
    """``extra_header``: additional JSON-serializable header keys (e.g.
    the engine's ``frontier_layout`` version). Purely informational —
    readers ignore keys they don't know, so old snapshots (without them)
    and old readers (seeing new ones) both keep working; the reserved
    integrity keys above cannot be overridden."""
    header_dict = dict(extra_header or {})
    header_dict.update(
        {
            "version": FORMAT_VERSION,
            "fingerprint": fingerprint,
            "payload_len": len(payload),
            "checksum": _checksum(payload),
        }
    )
    header = json.dumps(header_dict).encode()
    return MAGIC + struct.pack(">I", len(header)) + header + payload


def _parse_header(blob: bytes) -> Tuple[Optional[Dict], int]:
    """Returns ``(header, payload_offset)``; header None for legacy bare
    npz. Raises CheckpointError on a torn/unrecognized image."""
    if blob[:2] == _LEGACY_ZIP_MAGIC:
        return None, 0
    if len(blob) < len(MAGIC) + 4 or blob[: len(MAGIC)] != MAGIC:
        raise CheckpointError("unrecognized checkpoint image (bad magic)")
    (hlen,) = struct.unpack(">I", blob[len(MAGIC) : len(MAGIC) + 4])
    start = len(MAGIC) + 4
    if len(blob) < start + hlen:
        raise CheckpointError("truncated checkpoint header")
    try:
        header = json.loads(blob[start : start + hlen])
    except (ValueError, UnicodeDecodeError) as e:
        raise CheckpointError(f"unparseable checkpoint header: {e}") from None
    if header.get("version") != FORMAT_VERSION:
        raise CheckpointError(
            f"unsupported checkpoint format version {header.get('version')!r}"
        )
    return header, start + hlen


def unpack(blob: bytes) -> Tuple[Optional[Dict], bytes]:
    """Verify and split a file image into ``(header, payload)``. Raises
    :class:`CheckpointError` on any integrity violation."""
    header, off = _parse_header(blob)
    payload = blob[off:]
    if header is None:
        return None, payload
    if len(payload) != header["payload_len"]:
        raise CheckpointError(
            f"truncated checkpoint payload: {len(payload)} bytes, "
            f"header promises {header['payload_len']}"
        )
    if _checksum(payload) != header["checksum"]:
        raise CheckpointError("checkpoint payload checksum mismatch")
    return header, payload


def read_header(path: str) -> Optional[Dict]:
    """Header of ``path`` without touching the payload (cheap pre-flight
    for fingerprint checks). None for legacy headerless files."""
    with open(path, "rb") as f:
        prefix = f.read(len(MAGIC) + 4)
        if prefix[:2] == _LEGACY_ZIP_MAGIC:
            return None
        if len(prefix) < len(MAGIC) + 4 or prefix[: len(MAGIC)] != MAGIC:
            raise CheckpointError("unrecognized checkpoint image (bad magic)")
        (hlen,) = struct.unpack(">I", prefix[len(MAGIC) :])
        raw = f.read(hlen)
    if len(raw) < hlen:
        raise CheckpointError("truncated checkpoint header")
    try:
        return json.loads(raw)
    except (ValueError, UnicodeDecodeError) as e:
        raise CheckpointError(f"unparseable checkpoint header: {e}") from None


def rotation_paths(path: str, keep: Optional[int] = None) -> List[str]:
    """Candidate snapshot paths, newest first."""
    keep = default_keep() if keep is None else keep
    return [path] + [f"{path}.{i}" for i in range(1, keep)]


def write_atomic(
    path: str,
    payload: bytes,
    *,
    fingerprint: Optional[str] = None,
    keep: Optional[int] = None,
    extra_header: Optional[Dict] = None,
) -> None:
    """Publish a snapshot crash-safely: temp file + fsync + rotation shift
    + ``os.replace``. The previous ``keep - 1`` good snapshots survive as
    ``path.1 ... path.{keep-1}``. ``extra_header``: see :func:`pack`."""
    import tempfile

    keep = default_keep() if keep is None else max(1, keep)
    blob = pack(payload, fingerprint, extra_header)
    blob, injected = registry().filter_bytes("ckpt.write", blob)
    # UNIQUE same-directory temp name (mkstemp), not a fixed `path.tmp`:
    # the shared fleet cache tier publishes the same final path from
    # MULTIPLE processes concurrently, and a fixed temp name lets racer
    # B truncate the file racer A is about to os.replace into place — a
    # torn image at the final path, exactly what this writer exists to
    # prevent. With unique temps every publish replaces a fully-written,
    # fsync'd image; racers just decide who wins the rename.
    fd, tmp = tempfile.mkstemp(
        dir=os.path.dirname(os.path.abspath(path)),
        prefix=os.path.basename(path) + ".tmp.",
    )
    try:
        os.fchmod(fd, 0o666 & ~_UMASK)  # umask semantics, not mkstemp's 0600
        with os.fdopen(fd, "wb") as f:
            f.write(blob)
            f.flush()
            os.fsync(f.fileno())
        # rotation shift: path -> path.1 -> ... (oldest dropped). Done
        # before the publish so the newest PREVIOUS snapshot is always
        # recoverable.
        chain = rotation_paths(path, keep)
        for older, newer in zip(reversed(chain[1:]), reversed(chain[:-1])):
            if os.path.exists(newer):
                os.replace(newer, older)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    _fsync_dir(os.path.dirname(os.path.abspath(path)))
    if injected == "truncate":
        # the torn image reached the final path (writer "killed" after the
        # rename was queued) — now crash, as the real failure would
        raise FaultInjected("ckpt.write", "truncate", registry().hits("ckpt.write"))


#: per-directory throttle for :func:`maybe_sweep_stale_tmp` (the hot
#: shared-cache lookup path must not pay a listdir per read)
_SWEEP_SEEN: Dict[str, float] = {}
_SWEEP_LOCK = None  # created lazily to keep this module import-light


def maybe_sweep_stale_tmp(dirname: str, min_interval_s: float = 300.0) -> int:
    """Throttled :func:`sweep_stale_tmp`: at most one real sweep per
    directory per ``min_interval_s`` per process — the read path calls
    this freely (one dict lookup when throttled)."""
    global _SWEEP_LOCK
    if _SWEEP_LOCK is None:
        import threading

        _SWEEP_LOCK = threading.Lock()
    now = time.monotonic()
    with _SWEEP_LOCK:
        last = _SWEEP_SEEN.get(dirname)
        if last is not None and now - last < min_interval_s:
            return 0
        _SWEEP_SEEN[dirname] = now
    return sweep_stale_tmp(dirname)


def sweep_stale_tmp(dirname: str, max_age_s: float = 60.0) -> int:
    """Remove orphaned ``*.tmp.*`` files (a writer SIGKILLed between
    :func:`write_atomic`'s mkstemp and its ``os.replace`` leaves one —
    the price of the unique temp names concurrent publishers need).
    Only files older than ``max_age_s`` go: a live publisher's temp
    exists for milliseconds, so the age bound can never race one.
    Returns the number removed. Call on opening a long-lived store
    directory (the fleet's shared cache tier does)."""
    removed = 0
    try:
        names = os.listdir(dirname)
    except OSError:
        return 0
    now = time.time()
    for name in names:
        if ".tmp." not in name:
            continue
        path = os.path.join(dirname, name)
        try:
            if now - os.stat(path).st_mtime > max_age_s:
                os.unlink(path)
                removed += 1
        except OSError:
            continue  # vanished / racing sweeper: someone else got it
    return removed


def _fsync_dir(dirname: str) -> None:
    try:
        fd = os.open(dirname, os.O_RDONLY)
    except OSError:
        return  # platform without directory fds: best effort
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def read_with_fallback(
    path: str, *, keep: Optional[int] = None
) -> Tuple[Optional[Dict], bytes, str, int]:
    """Read the newest VALID snapshot in the rotation chain.

    Returns ``(header, payload, source_path, fallbacks)`` where
    ``fallbacks`` counts newer candidates that were skipped as missing or
    corrupt. Raises :class:`CheckpointError` when no candidate survives
    verification (the per-candidate reasons are in the message)."""
    from .retry import RetryPolicy

    def read_candidate(cand: str) -> bytes:
        with open(cand, "rb") as f:
            blob = f.read()
        return registry().filter_bytes("ckpt.read", blob)[0]

    # reading a store is the natural sweep point for temps orphaned by
    # SIGKILLed writers (chunk campaigns resume every chunk; the fleet
    # cache tier also sweeps at its own init) — THROTTLED, because the
    # shared cache tier routes every L2 lookup through here and a
    # listdir per read would scale lookup cost with directory size;
    # age-bounded, so a concurrent writer's live temp is never raced
    maybe_sweep_stale_tmp(os.path.dirname(os.path.abspath(path)))
    # a TRANSIENT read failure (flaky storage, an injected ckpt.read
    # raise) is retried before the candidate is written off — falling
    # back a rotation step over a hiccup would silently discard progress
    read_retry = RetryPolicy(max_attempts=2, base_delay_s=0.005, seed=0)
    failures: List[str] = []
    for idx, cand in enumerate(rotation_paths(path, keep)):
        try:
            blob = read_retry.call(lambda c=cand: read_candidate(c))
            header, payload = unpack(blob)
        except FileNotFoundError:
            failures.append(f"{cand}: missing")
            continue
        except (CheckpointError, OSError, FaultInjected) as e:
            failures.append(f"{cand}: {e}")
            continue
        if idx > 0:
            HEALTH.incr("fallback_restores")
        return header, payload, cand, idx
    raise CheckpointError(
        f"no valid checkpoint in rotation of {path!r}: " + "; ".join(failures)
    )


def write_json_atomic(path: str, obj, *, indent: Optional[int] = 1) -> None:
    """Atomic publish for durable JSON artifacts (bench/profile outputs):
    the graftlint-R6-sanctioned replacement for ``open(path, "w")``."""
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(obj, f, indent=indent)
        f.write("\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def npz_bytes(**arrays) -> bytes:
    """Serialize arrays to in-memory ``.npz`` bytes (the store's payload)."""
    buf = io.BytesIO()
    np.savez_compressed(buf, **arrays)
    return buf.getvalue()

"""Bounded retry with exponential backoff and deterministic jitter.

The serve ladder and checkpoint/cache paths use this to absorb TRANSIENT
faults (:class:`~.faults.TransientFault` — which includes every
registry-injected fault) without turning them into dropped requests. The
policy is deliberately small: bounded attempts, capped exponential delay,
seeded jitter so chaos runs replay byte-identically, and an optional wall
budget so a retry loop can never outspend a request's deadline.
Every absorbed fault counts into ``HEALTH.retries``.
"""

from __future__ import annotations

import random
import time
from dataclasses import dataclass
from typing import Callable, Optional, Tuple, Type

from .faults import TransientFault
from .health import HEALTH


@dataclass
class RetryPolicy:
    #: total attempts (1 = no retry)
    max_attempts: int = 3
    base_delay_s: float = 0.01
    max_delay_s: float = 0.25
    #: fraction of each delay randomized away (0 = full deterministic
    #: backoff; 0.5 = delays land in [0.5x, 1.0x])
    jitter: float = 0.5
    #: seeds the jitter RNG (default 0: every retry schedule in this repo
    #: replays byte-identically, which chaos runs and tests rely on);
    #: pass None for nondeterministic jitter if thundering-herd spreading
    #: across processes ever matters more than replayability
    seed: Optional[int] = 0
    retry_on: Tuple[Type[BaseException], ...] = (TransientFault,)

    def delay_s(self, attempt: int, rng: random.Random) -> float:
        """Backoff before retry number ``attempt`` (1-based)."""
        raw = min(self.base_delay_s * (2 ** (attempt - 1)), self.max_delay_s)
        return raw * (1.0 - self.jitter * rng.random())

    def call(
        self,
        fn: Callable,
        *,
        budget_s: Optional[float] = None,
        on_retry: Optional[Callable[[int, BaseException], None]] = None,
    ):
        """Run ``fn`` with up to ``max_attempts`` tries. Re-raises the last
        transient fault when attempts (or the wall ``budget_s``) run out;
        non-retryable exceptions propagate immediately."""
        if self.max_attempts < 1:
            raise ValueError(f"max_attempts must be >= 1, got {self.max_attempts}")
        # the jitter RNG is built lazily, on the first retry: the no-fault
        # fast path (every request's cache lookup and rung attempt goes
        # through here) must not pay the Mersenne seeding, and the retry
        # schedule stays byte-identical — the first delay still comes from
        # a fresh Random(seed)
        rng: Optional[random.Random] = None
        t0 = time.monotonic()
        attempt = 0
        while True:
            attempt += 1
            try:
                return fn()
            except self.retry_on as exc:
                if attempt >= self.max_attempts:
                    raise
                if rng is None:
                    rng = random.Random(self.seed)
                delay = self.delay_s(attempt, rng)
                if (
                    budget_s is not None
                    and time.monotonic() - t0 + delay > budget_s
                ):
                    raise
                HEALTH.incr("retries")
                if on_retry is not None:
                    on_retry(attempt, exc)
                time.sleep(delay)

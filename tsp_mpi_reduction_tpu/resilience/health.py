"""Process-global self-healing telemetry (the ``health`` counter block).

Every recovery action the resilience subsystem takes — a serve worker
restarted by the watchdog, a transient fault absorbed by a retry, a
corrupt checkpoint skipped in favor of an older valid snapshot, a fault
actually injected by the registry — increments a counter here.
``utils.reporting.service_stats_json`` and ``tools/bnb_solve.py`` surface
the block, so a chaos run (or a production incident) leaves a
machine-readable trace of what self-healed, not just a green exit code.
"""

from __future__ import annotations

import threading
from typing import Dict


class HealthCounters:
    """Thread-safe named counters + a per-seam injected-fault tally."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counts: Dict[str, int] = {}
        self._faults: Dict[str, int] = {}

    def incr(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counts[name] = self._counts.get(name, 0) + n

    def incr_fault(self, seam: str) -> None:
        with self._lock:
            self._faults[seam] = self._faults.get(seam, 0) + 1

    def get(self, name: str) -> int:
        with self._lock:
            return self._counts.get(name, 0)

    def snapshot(self) -> Dict:
        """One JSON-ready dict: the standard counters (always present, so
        scrapers see explicit zeros) plus any ad-hoc ones and the per-seam
        injected-fault map."""
        with self._lock:
            out: Dict = {
                "worker_restarts": 0,
                "stuck_restarts": 0,
                "retries": 0,
                "fallback_restores": 0,
            }
            out.update(self._counts)
            out["faults_injected"] = dict(self._faults)
        return out

    def reset(self) -> None:
        with self._lock:
            self._counts.clear()
            self._faults.clear()


#: the process-global instance every layer reports into.
HEALTH = HealthCounters()

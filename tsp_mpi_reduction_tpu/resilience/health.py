"""Process-global self-healing telemetry (the ``health`` counter block).

Every recovery action the resilience subsystem takes — a serve worker
restarted by the watchdog, a transient fault absorbed by a retry, a
corrupt checkpoint skipped in favor of an older valid snapshot, a fault
actually injected by the registry — increments a counter here.
``utils.reporting.service_stats_json`` and ``tools/bnb_solve.py`` surface
the block, so a chaos run (or a production incident) leaves a
machine-readable trace of what self-healed, not just a green exit code.

Since ISSUE 6 the counters are REGISTRY-BACKED: :class:`HealthCounters`
is a view over the process-global ``obs.metrics.REGISTRY`` series
``health_events_total{event=…}`` / ``health_faults_injected_total{seam=…}``
rather than its own dict, which buys snapshot/delta semantics for free —
``SolveService`` reports :meth:`HealthCounters.delta_since` its own start
baseline, so back-to-back serve sessions in one process no longer see
each other's counts, and the per-test reset fixture in
``tests/conftest.py`` gives repeated tests the same boundary.
"""

from __future__ import annotations

from typing import Dict

from ..obs import metrics as _metrics

#: always-present counter names (scrapers see explicit zeros)
STANDARD_COUNTERS = (
    "worker_restarts",
    "stuck_restarts",
    "retries",
    "fallback_restores",
    # fleet self-healing (ISSUE 11): replica processes restarted by the
    # supervisor, in-flight requests re-dispatched off a dead/wedged
    # replica, and requests the front answered locally (cache / greedy)
    # because the fleet was degraded. Mirrored as the registry metrics
    # ``fleet_replica_restarts_total`` / ``fleet_redispatches_total`` /
    # ``fleet_degraded_answers_total{reason=}`` by fleet.front.
    "fleet_replica_restarts",
    "fleet_redispatches",
    "fleet_degraded_answers",
    # iteration-level serving (ISSUE 13): B&B rungs preempted at a slice
    # boundary via the donated checkpoint path, those slices resumed
    # bit-identically, and new admissions shed/degraded by the live SLO
    # burn signal. Mirrored as ``serve_bnb_preemptions_total`` /
    # ``serve_bnb_resumes_total`` / ``serve_flushes_total{cause=slo_shed}``
    # by serve.scheduler.
    "bnb_preemptions",
    "bnb_resumes",
    "slo_sheds",
)

EVENTS_METRIC = "health_events_total"
FAULTS_METRIC = "health_faults_injected_total"


class HealthCounters:
    """Named self-healing counters + a per-seam injected-fault tally,
    stored in the obs metrics registry (thread-safe there)."""

    def __init__(self, registry: _metrics.MetricsRegistry = _metrics.REGISTRY):
        self._reg = registry
        registry.declare(
            EVENTS_METRIC, "counter",
            "self-healing actions taken, by event kind",
        )
        registry.declare(
            FAULTS_METRIC, "counter",
            "faults actually injected by the TSP_FAULTS registry, by seam",
        )

    def incr(self, name: str, n: int = 1) -> None:
        self._reg.inc(EVENTS_METRIC, n, event=name)

    def incr_fault(self, seam: str) -> None:
        self._reg.inc(FAULTS_METRIC, 1, seam=seam)

    def get(self, name: str) -> int:
        return int(self._reg.value(EVENTS_METRIC, event=name))

    def snapshot(self) -> Dict:
        """One JSON-ready dict: the standard counters (always present, so
        scrapers see explicit zeros) plus any ad-hoc ones and the per-seam
        injected-fault map."""
        out: Dict = {k: 0 for k in STANDARD_COUNTERS}
        for key, v in self._reg.series(EVENTS_METRIC).items():
            out[dict(key).get("event", "?")] = int(v)
        out["faults_injected"] = {
            dict(key).get("seam", "?"): int(v)
            for key, v in self._reg.series(FAULTS_METRIC).items()
        }
        return out

    def delta_since(self, baseline: Dict) -> Dict:
        """The same shape as :meth:`snapshot`, minus ``baseline`` (a prior
        snapshot). Clamped at zero so a mid-window reset cannot report
        negative healing. This is what a serve session's stats JSON
        carries: the session's OWN recovery actions, not the process's."""
        now = self.snapshot()
        out: Dict = {
            k: max(int(v) - int(baseline.get(k, 0)), 0)
            for k, v in now.items()
            if k != "faults_injected"
        }
        base_faults = baseline.get("faults_injected", {})
        out["faults_injected"] = {
            seam: max(int(v) - int(base_faults.get(seam, 0)), 0)
            for seam, v in now["faults_injected"].items()
        }
        return out

    def reset(self) -> None:
        self._reg.clear_metric(EVENTS_METRIC)
        self._reg.clear_metric(FAULTS_METRIC)

    #: the per-test boundary hook (tests/conftest.py autouse fixture)
    reset_for_testing = reset


#: the process-global instance every layer reports into.
HEALTH = HealthCounters()

"""resilience/: fault injection, crash-safe checkpoints, self-healing.

The north star is a service under heavy traffic, where preemption,
truncated writes, dead worker threads, and flaky transfers are routine —
this package makes those failures (a) survivable and (b) *testable*:

- ``faults``      deterministic ``TSP_FAULTS`` injection registry with a
                  named seam at every durability/transfer boundary
- ``checkpoint``  atomic-publish checkpoint store: integrity header,
                  instance fingerprint, last-N rotation, fallback restore
- ``retry``       bounded exponential backoff + seeded jitter for
                  transient faults
- ``health``      process-global self-healing counters (worker restarts,
                  retries, fallback restores, injected faults)

Everything here is numpy/stdlib-only — importable by lint-stage tooling
and light drivers (``tools/bnb_chunked.py``) without dragging in JAX.
"""

from .checkpoint import (
    CheckpointError,
    instance_fingerprint,
    read_header,
    read_with_fallback,
    write_atomic,
    write_json_atomic,
)
from .faults import SEAMS, FaultInjected, FaultRegistry, TransientFault, registry
from .health import HEALTH, HealthCounters
from .retry import RetryPolicy

__all__ = [
    "CheckpointError",
    "instance_fingerprint",
    "read_header",
    "read_with_fallback",
    "write_atomic",
    "write_json_atomic",
    "SEAMS",
    "FaultInjected",
    "FaultRegistry",
    "TransientFault",
    "registry",
    "HEALTH",
    "HealthCounters",
    "RetryPolicy",
]

"""Deterministic fault-injection registry: the ``TSP_FAULTS`` env hook.

The resilience subsystem's claims (crash-safe checkpoints, self-healing
serve loop) are only as good as the failures they were tested against, so
every durability/transfer boundary in the codebase carries a NAMED
injection seam, and this registry decides — deterministically — whether a
given crossing of a seam fails, and how. The chaos suite
(``tests/test_chaos.py``) is written entirely against this machinery: one
seam per run, seeded, reproducible.

Spec grammar (``TSP_FAULTS`` or :func:`configure`)::

    spec    = clause (";" clause)*
    clause  = seam ":" mode ("," key "=" value)*
    seam    = one of SEAMS (e.g. ckpt.write, sched.flush)
    mode    = raise | delay | truncate | corrupt
    keys    = nth=N       first seam hit to inject on (1-based, default 1)
              count=C     how many consecutive hits inject (default 1;
                          0 = every hit from nth on)
              at=K        byte offset for truncate/corrupt (default:
                          seeded pseudo-random per hit)
              seed=S      seeds the offset/byte choices (default 0)
              delay_ms=D  sleep for delay mode (default 50)

Examples::

    TSP_FAULTS="ckpt.write:truncate,nth=2,at=100"
    TSP_FAULTS="sched.flush:raise;cache.get:raise,count=3"

Modes:

``raise``
    raise :class:`FaultInjected` at the seam, before any work is done —
    models a crash/exception at the boundary (a killed writer, a failed
    readback, a dead worker thread).
``delay``
    sleep ``delay_ms`` then continue — models a stall (slow disk, a
    wedged device dispatch) for stuck-worker watchdog testing.
``truncate`` (byte seams only)
    the bytes crossing the seam are cut at a deterministic offset AND the
    crossing then raises — models a writer killed mid-write whose torn
    bytes still reached the final path (the legacy ``np.savez`` symptom).
    On pure control seams it degrades to ``raise``.
``corrupt`` (byte seams only)
    a few deterministically-chosen bytes are flipped and the crossing
    continues silently — models bit rot / a torn page that only a
    checksum can catch. On pure control seams it degrades to ``raise``.

Seams are crossed via :func:`fire` (control seams) or
:func:`filter_bytes` (byte seams); both count one hit per crossing, so
``nth`` is stable regardless of mode.
"""

from __future__ import annotations

import os
import random
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from ..obs import tracing as _tracing
from .health import HEALTH


def _trace_injection(seam: str, mode: str, hit: int) -> None:
    """Annotate the firing thread's active span (no-op without one): a
    chaos run's injected faults then appear as span events inside the
    very request/trace they hit, with matching trace IDs."""
    _tracing.add_event("fault_injected", seam=seam, mode=mode, hit=hit)

#: every registered injection seam — one per durability/transfer boundary.
SEAMS = frozenset(
    {
        "ckpt.write",   # checkpoint store: atomic publish of a snapshot
        "ckpt.read",    # checkpoint store: candidate read during restore
        "spill.fetch",  # reservoir spill: live-prefix device->host readback
        "ladder.rung",  # serve: one deadline-ladder rung attempt
        "cache.get",    # serve: solution-cache lookup
        "cache.put",    # serve: solution-cache insert
        "sched.flush",  # serve: micro-batch scheduler flush (worker body)
        # fleet seams (ISSUE 11) — crossed by the FRONT once per dispatch
        # attempt, so ``nth`` counts dispatches fleet-wide. ``raise`` at
        # the replica seams is translated by the front into the real
        # action (SIGKILL / SIGSTOP of the target replica process) rather
        # than propagating — the injected failure is a dead/wedged
        # REPLICA, not a front crash; front.dispatch stays a normal
        # transient-fault seam absorbed by the dispatch retry.
        "replica.kill",   # fleet: kill the dispatch target mid-flight
        "replica.hang",   # fleet: wedge (SIGSTOP) the dispatch target
        "front.dispatch", # fleet: one front->replica dispatch attempt
        # adaptive balance (ISSUE 15): crossed host-side each time the
        # controller escalates to the steal collective; an injected fault
        # degrades that round to the base action (the solve stays exact —
        # balance only moves rows) and is counted in obs.balance
        "balance.steal",
    }
)

MODES = ("raise", "delay", "truncate", "corrupt")


class TransientFault(RuntimeError):
    """Base class for faults a bounded retry is allowed to absorb."""


class FaultInjected(TransientFault):
    """Raised by an armed seam; carries where/why for chaos assertions."""

    def __init__(self, seam: str, mode: str, hit: int):
        super().__init__(f"injected fault: seam={seam} mode={mode} hit={hit}")
        self.seam = seam
        self.mode = mode
        self.hit = hit


@dataclass
class FaultClause:
    seam: str
    mode: str
    nth: int = 1
    count: int = 1  # 0 = unbounded
    at: Optional[int] = None
    seed: int = 0
    delay_ms: float = 50.0

    def armed_for(self, hit: int) -> bool:
        """Does this clause inject on the ``hit``-th crossing (1-based)?"""
        if hit < self.nth:
            return False
        return self.count == 0 or hit < self.nth + self.count


def parse_spec(spec: str) -> List[FaultClause]:
    """Parse the ``TSP_FAULTS`` grammar; raises ValueError on any typo —
    a chaos run with a silently-ignored clause would test nothing."""
    clauses: List[FaultClause] = []
    for raw in spec.split(";"):
        raw = raw.strip()
        if not raw:
            continue
        head, _, tail = raw.partition(",")
        seam, sep, mode = head.partition(":")
        seam, mode = seam.strip(), mode.strip()
        if not sep or seam not in SEAMS or mode not in MODES:
            raise ValueError(
                f"bad TSP_FAULTS clause {raw!r}: want seam:mode[,k=v...] "
                f"with seam in {sorted(SEAMS)} and mode in {MODES}"
            )
        clause = FaultClause(seam=seam, mode=mode)
        if tail:
            for kv in tail.split(","):
                key, sep, val = kv.partition("=")
                key = key.strip()
                try:
                    if not sep:
                        raise ValueError("missing '='")
                    if key == "nth":
                        clause.nth = int(val)
                    elif key == "count":
                        clause.count = int(val)
                    elif key == "at":
                        clause.at = int(val)
                    elif key == "seed":
                        clause.seed = int(val)
                    elif key == "delay_ms":
                        clause.delay_ms = float(val)
                    else:
                        raise ValueError(f"unknown key {key!r}")
                except ValueError as e:
                    raise ValueError(
                        f"bad TSP_FAULTS clause {raw!r}: {e}"
                    ) from None
        if clause.nth < 1:
            raise ValueError(f"bad TSP_FAULTS clause {raw!r}: nth must be >= 1")
        clauses.append(clause)
    return clauses


class FaultRegistry:
    """Thread-safe seam hit counting + clause matching."""

    def __init__(self, spec: Optional[str] = None):
        self._lock = threading.Lock()
        self._hits: Dict[str, int] = {}
        self._clauses: List[FaultClause] = parse_spec(spec) if spec else []

    def configure(self, spec: Optional[str]) -> None:
        """Replace the active clauses and reset every hit counter."""
        clauses = parse_spec(spec) if spec else []
        with self._lock:
            self._clauses = clauses
            self._hits = {}

    def clear(self) -> None:
        self.configure(None)

    def hits(self, seam: str) -> int:
        with self._lock:
            return self._hits.get(seam, 0)

    @property
    def active(self) -> bool:
        with self._lock:  # configure() swaps the clause list (graftflow R9)
            return bool(self._clauses)

    def _cross(self, seam: str) -> Tuple[int, Optional[FaultClause]]:
        if seam not in SEAMS:
            raise ValueError(f"unregistered fault seam {seam!r}")
        with self._lock:
            hit = self._hits.get(seam, 0) + 1
            self._hits[seam] = hit
            for clause in self._clauses:
                if clause.seam == seam and clause.armed_for(hit):
                    return hit, clause
        return hit, None

    def fire(self, seam: str) -> None:
        """Cross a control seam: raise/sleep when an armed clause matches.
        ``truncate``/``corrupt`` clauses degrade to ``raise`` here — there
        are no bytes to mangle at a control seam.

        Fast path: with no clauses configured (every production run) the
        crossing is a lock-free membership check — hot serve seams
        (cache.get/put on every request across all threads) must not
        serialize on the registry lock for a no-op. Hit counters
        therefore only accumulate while a chaos spec is active."""
        if not self._clauses:
            if seam not in SEAMS:
                raise ValueError(f"unregistered fault seam {seam!r}")
            return
        hit, clause = self._cross(seam)
        if clause is None:
            return
        HEALTH.incr_fault(seam)
        _trace_injection(seam, clause.mode, hit)
        if clause.mode == "delay":
            time.sleep(clause.delay_ms / 1000.0)
            return
        raise FaultInjected(seam, clause.mode, hit)

    def filter_bytes(self, seam: str, blob: bytes) -> Tuple[bytes, Optional[str]]:
        """Cross a byte seam: returns ``(possibly-mangled blob, mode)``
        where mode is None (clean), "truncate", or "corrupt"; raises for a
        ``raise`` clause; sleeps-then-passes for ``delay``. Same lock-free
        no-clause fast path as :meth:`fire`."""
        if not self._clauses:
            if seam not in SEAMS:
                raise ValueError(f"unregistered fault seam {seam!r}")
            return blob, None
        hit, clause = self._cross(seam)
        if clause is None:
            return blob, None
        HEALTH.incr_fault(seam)
        _trace_injection(seam, clause.mode, hit)
        if clause.mode == "raise":
            raise FaultInjected(seam, clause.mode, hit)
        if clause.mode == "delay":
            time.sleep(clause.delay_ms / 1000.0)
            return blob, None
        rng = random.Random(f"{clause.seed}:{seam}:{hit}")
        if clause.mode == "truncate":
            cut = clause.at if clause.at is not None else rng.randrange(1, max(len(blob), 2))
            return blob[: max(0, min(cut, len(blob) - 1))], "truncate"
        # corrupt: flip a handful of deterministically-chosen bytes
        mutable = bytearray(blob)
        if mutable:
            flips = max(1, len(mutable) // 256)
            positions = (
                [clause.at % len(mutable)]
                if clause.at is not None
                else [rng.randrange(len(mutable)) for _ in range(flips)]
            )
            for pos in positions:
                mutable[pos] ^= 0xFF
        return bytes(mutable), "corrupt"


_REGISTRY: Optional[FaultRegistry] = None
_REGISTRY_LOCK = threading.Lock()


def registry() -> FaultRegistry:
    """The process-global registry, lazily initialized from ``TSP_FAULTS``."""
    global _REGISTRY
    if _REGISTRY is None:
        with _REGISTRY_LOCK:
            if _REGISTRY is None:
                _REGISTRY = FaultRegistry(os.environ.get("TSP_FAULTS"))
    return _REGISTRY


def configure(spec: Optional[str]) -> None:
    """Programmatic chaos hook (tests): replace the global clause set."""
    registry().configure(spec)


def clear() -> None:
    registry().clear()

"""Solver pipelines: blocked Held-Karp+merge pipeline, TSPLIB branch-and-bound."""

"""The blocked TSP pipeline: generate -> per-block Held-Karp -> merge fold.

This is the single-controller TPU equivalent of the reference's whole
``main()`` (tsp.cpp:270-368). Where the reference scatters blocks over MPI
ranks (tsp.cpp:159-195) and folds per-rank results through a hand-rolled
message tree (tsp.cpp:52-134), here:

- the instance is *born blocked* as dense arrays (no scatter);
- all blocks are solved exactly in one vmapped Held-Karp kernel call;
- the rank-local sequential fold (tsp.cpp:348-352) is a ``lax.scan`` over
  the merge operator, gathering distances from a resident global matrix.

Single-rank semantics (numProcs=1) are the default and match the oracle
bit-for-bit in float64; the distributed merge tree over a device mesh lives
in ``parallel.reduce``.

Deviations from the reference (documented, SURVEY.md quirk #6/#8):
- blocks of 1-2 cities raise ``ValueError`` instead of yielding an INT_MAX
  sentinel cost (1 city) or hanging forever in the merge rotate (2 cities);
- block counts/cities are validated up front instead of producing UB.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional

import jax.numpy as jnp
import numpy as np

from ..ops.distance import distance_matrix, distance_matrix_np
from ..ops.generator import generate_instance
from ..ops.held_karp import build_plan, require_x64_if_float64, solve_blocks_from_dists
from ..ops.merge import fold_tours
from ..utils.profiling import PhaseTimer


@dataclass
class PipelineResult:
    """Final solution plus per-phase observability (SURVEY.md §5 rows 1/5)."""

    cost: float
    tour_ids: np.ndarray  # [final_len] global city ids, closed
    num_cities: int
    block_costs: np.ndarray  # [B]
    phase_seconds: Dict[str, float] = field(default_factory=dict)
    dp_states: int = 0
    dp_transitions: int = 0
    #: resident [N, N] device distance matrix (reused by --improve; avoids
    #: recomputing the most expensive host phase)
    dist: Optional[jnp.ndarray] = None


def block_distance_slices(dist: jnp.ndarray, num_blocks: int, n: int) -> jnp.ndarray:
    """``[N, N]`` global matrix -> ``[B, n, n]`` per-block diagonal slices.

    City ids are sequential block-major (tsp.cpp:390,398), so block b owns
    the contiguous id range [b*n, (b+1)*n).
    """
    r = dist.reshape(num_blocks, n, num_blocks, n)
    idx = jnp.arange(num_blocks)
    return r[idx, :, idx, :]


def run_pipeline(
    num_cities_per_block: int,
    num_blocks: int,
    grid_dim_x: int,
    grid_dim_y: int,
    seed: int = 0,
    dtype=jnp.float64,
    xy: Optional[np.ndarray] = None,
) -> PipelineResult:
    """Run the full blocked pipeline for one configuration.

    float64 (default) reproduces the single-rank oracle bit-for-bit: the
    global distance matrix is computed on host (see the FMA note in
    ``ops.distance``) and every downstream op preserves the oracle's
    rounding and tie-break order. float32 is the TPU speed mode (distances
    computed on device).

    ``xy``: optional pre-generated ``[B, n, 2]`` coordinates (skips the
    generator; used by tests and the distributed driver).
    """
    n = num_cities_per_block
    if n < 3:
        raise ValueError(
            f"blocks need >= 3 cities (got {n}): the reference yields an "
            "INT_MAX sentinel for 1 and hangs for 2 (SURVEY.md quirk #6)"
        )
    if num_blocks < 1:
        raise ValueError(f"need >= 1 block, got {num_blocks}")
    dtype = jnp.dtype(dtype)
    require_x64_if_float64(dtype)  # fail fast, before any compute
    build_plan(n)  # validates the block-size cap up front

    timer = PhaseTimer()
    with timer.phase("generate"):
        if xy is None:
            _, xy = generate_instance(n, num_blocks, grid_dim_x, grid_dim_y, seed)

    with timer.phase("distances"):
        if dtype == jnp.float64:
            dist = jnp.asarray(distance_matrix_np(xy.reshape(-1, 2)))
        else:
            dist = distance_matrix(jnp.asarray(xy.reshape(-1, 2), dtype))
        block_d = block_distance_slices(dist, num_blocks, n)

    with timer.phase("solve"):
        costs, local_tours = solve_blocks_from_dists(block_d, dtype)
        costs.block_until_ready()

    with timer.phase("merge_fold"):
        offsets = (jnp.arange(num_blocks, dtype=jnp.int32) * n)[:, None]
        global_tours = local_tours.astype(jnp.int32) + offsets
        ids, length, cost = fold_tours(global_tours, costs, dist)
        cost.block_until_ready()

    plan = build_plan(n)
    final_len = int(length)
    return PipelineResult(
        cost=float(cost),
        tour_ids=np.asarray(ids)[:final_len],
        num_cities=num_blocks * n,
        block_costs=np.asarray(costs),
        phase_seconds=timer.seconds,
        dp_states=plan.dp_states * num_blocks,
        dp_transitions=plan.dp_transitions * num_blocks,
        dist=dist,
    )

"""Mesh-sharded blocked pipeline: the multi-rank run, TPU-native.

What the reference does across MPI processes — scatter blocks (tsp.cpp:159-195),
solve locally, fold locally (tsp.cpp:348-352), then binary-tree reduce
(tsp.cpp:52-134) — runs here as ONE jitted SPMD program over a device mesh:

- blocks are born sharded over the rank axis (no scatter messages);
- the vmapped Held-Karp solve partitions along the block batch dimension
  (data parallelism over the mesh, the reference's only parallelism);
- the per-rank fold and the reference-shaped merge tree run under
  ``shard_map`` with ``ppermute`` collectives riding the ICI.

Block-to-rank assignment replicates the reference's round-robin countdown
(``rank_block_counts``), so the merge ORDER — and hence the final tour, the
operator being non-associative — matches what a p-rank MPI run would produce
(modulo the reference's receive-buffer corruption bug, SURVEY.md quirk #5,
which is deliberately not reproduced).
"""

from __future__ import annotations

import time
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.distance import distance_matrix, distance_matrix_np
from ..ops.generator import generate_instance
from ..ops.held_karp import build_plan, require_x64_if_float64, solve_blocks_from_dists
from ..parallel.mesh import RANK_AXIS, make_rank_mesh
from ..parallel.reduce import (
    compat_capacity,
    rank_block_counts,
    reduce_tours_on_mesh,
    tree_reduce_single_device,
)
from .pipeline import PipelineResult, block_distance_slices


def _rank_block_layout(num_blocks: int, num_ranks: int):
    """Pad the reference block assignment into a dense [P*K] slot array.

    Returns (order, valid): ``order[slot]`` is the global block index owned
    by slot ``slot = rank*K + j`` (padding slots alias block 0), ``valid``
    marks real blocks. Assignment replicates tsp.cpp:167-191.
    """
    counts = rank_block_counts(num_blocks, num_ranks)
    k = max(max(counts), 1)
    order, start = [], 0
    for c in counts:
        order.extend(list(range(start, start + c)) + [-1] * (k - c))
        start += c
    order = np.asarray(order, dtype=np.int32)
    valid = order >= 0
    return np.where(valid, order, 0), valid


@partial(jax.jit, static_argnames=("mesh", "capacity", "dtype"))
def _distributed_step(mesh, block_d, block_offsets, valid, dist, capacity, dtype):
    """One full sharded solve+reduce step (solve -> fold -> tree)."""
    costs, local_tours = solve_blocks_from_dists(block_d, dtype)
    global_tours = local_tours.astype(jnp.int32) + block_offsets[:, None]
    zero_c = jnp.asarray(0, costs.dtype)
    costs = jnp.where(valid, costs, zero_c)
    ids, length, cost = reduce_tours_on_mesh(
        mesh, global_tours, costs, valid, dist, capacity
    )
    return costs, ids, length, cost


def run_pipeline_sharded(
    num_cities_per_block: int,
    num_blocks: int,
    grid_dim_x: int,
    grid_dim_y: int,
    mesh: Optional[jax.sharding.Mesh] = None,
    seed: int = 0,
    dtype=jnp.float64,
    xy: Optional[np.ndarray] = None,
) -> PipelineResult:
    """Run the blocked pipeline sharded over a device mesh.

    With ``P = mesh size`` this emulates the reference's ``mpirun -np P``
    run: same block assignment, same merge-tree shape. ``P=1`` degenerates
    to the oracle-parity single-rank pipeline.
    """
    n = num_cities_per_block
    if n < 3:
        raise ValueError(f"blocks need >= 3 cities, got {n}")
    dtype = jnp.dtype(dtype)
    require_x64_if_float64(dtype)
    build_plan(n)
    if mesh is None:
        mesh = make_rank_mesh()
    num_ranks = int(mesh.devices.size)

    if xy is None:
        _, xy = generate_instance(n, num_blocks, grid_dim_x, grid_dim_y, seed)

    if dtype == jnp.float64:
        dist = jnp.asarray(distance_matrix_np(xy.reshape(-1, 2)))
    else:
        dist = distance_matrix(jnp.asarray(xy.reshape(-1, 2), dtype))

    safe, valid = _rank_block_layout(num_blocks, num_ranks)
    block_d_all = block_distance_slices(dist, num_blocks, n)
    block_d = jnp.asarray(block_d_all)[safe]  # padding reuses block 0 (masked)
    offsets = jnp.asarray(safe * n, jnp.int32)

    capacity = num_blocks * n + 1
    spec_b = jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec(RANK_AXIS))
    block_d = jax.device_put(block_d, spec_b)

    t0 = time.perf_counter()
    costs, ids, length, cost = _distributed_step(
        mesh, block_d, offsets, jnp.asarray(valid), dist, capacity, dtype
    )
    cost.block_until_ready()
    plan = build_plan(n)
    final_len = int(length)
    return PipelineResult(
        cost=float(cost),
        tour_ids=np.asarray(ids)[:final_len],
        num_cities=num_blocks * n,
        block_costs=np.asarray(costs)[valid],
        phase_seconds={"solve_reduce": time.perf_counter() - t0},
        dp_states=plan.dp_states * num_blocks,
        dp_transitions=plan.dp_transitions * num_blocks,
        dist=dist,
    )


@partial(jax.jit, static_argnames=("num_ranks", "capacity", "dtype", "compat_bugs"))
def _emulated_step(
    block_d, block_offsets, valid, dist, num_ranks, capacity, dtype,
    compat_bugs=False,
):
    costs, local_tours = solve_blocks_from_dists(block_d, dtype)
    global_tours = local_tours.astype(jnp.int32) + block_offsets[:, None]
    costs = jnp.where(valid, costs, jnp.asarray(0, costs.dtype))
    ids, length, cost = tree_reduce_single_device(
        global_tours, costs, valid, dist, capacity, num_ranks,
        compat_bugs=compat_bugs,
    )
    return costs, ids, length, cost


def run_pipeline_ranks(
    num_cities_per_block: int,
    num_blocks: int,
    grid_dim_x: int,
    grid_dim_y: int,
    num_ranks: int,
    seed: int = 0,
    dtype=jnp.float64,
    xy: Optional[np.ndarray] = None,
    compat_bugs: bool = False,
) -> PipelineResult:
    """Rank-emulated multi-rank run on a single device.

    Computes exactly what ``run_pipeline_sharded`` over ``num_ranks``
    devices computes (same assignment, same tree order), without needing the
    devices — the CLI's ``--ranks`` path and the sweep harness's
    ``numProcs`` axis both use this.

    ``compat_bugs``: replicate the reference's reduce-side corruption
    (SURVEY.md quirk #5) so the result matches a real p-rank MPI run of the
    unmodified reference bit-for-bit; see parallel.reduce.
    """
    n = num_cities_per_block
    if n < 3:
        raise ValueError(f"blocks need >= 3 cities, got {n}")
    dtype = jnp.dtype(dtype)
    require_x64_if_float64(dtype)
    build_plan(n)

    if xy is None:
        _, xy = generate_instance(n, num_blocks, grid_dim_x, grid_dim_y, seed)
    if dtype == jnp.float64:
        dist = jnp.asarray(distance_matrix_np(xy.reshape(-1, 2)))
    else:
        dist = distance_matrix(jnp.asarray(xy.reshape(-1, 2), dtype))

    safe, valid = _rank_block_layout(num_blocks, num_ranks)
    block_d = jnp.asarray(block_distance_slices(dist, num_blocks, n))[safe]
    offsets = jnp.asarray(safe * n, jnp.int32)
    if compat_bugs:
        capacity = compat_capacity(num_blocks, n, num_ranks)
    else:
        capacity = num_blocks * n + 1

    t0 = time.perf_counter()
    costs, ids, length, cost = _emulated_step(
        block_d, offsets, jnp.asarray(valid), dist, num_ranks, capacity, dtype,
        compat_bugs
    )
    cost.block_until_ready()
    plan = build_plan(n)
    final_len = int(length)
    return PipelineResult(
        cost=float(cost),
        tour_ids=np.asarray(ids)[:final_len],
        num_cities=num_blocks * n,
        block_costs=np.asarray(costs)[valid],
        phase_seconds={"solve_reduce": time.perf_counter() - t0},
        dp_states=plan.dp_states * num_blocks,
        dp_transitions=plan.dp_transitions * num_blocks,
        dist=dist,
    )

"""Branch-and-bound TSP engine: padded device frontier + vmapped expansion.

The north star (BASELINE.json) describes the target architecture: "the
per-rank subtree expansion becomes a vmap'd batched partial-tour evaluator,
and the MPI_Allreduce(MPI_MIN) that broadcasts the incumbent becomes
jax.lax.pmin over the ICI mesh ... with the distance matrix held read-only
in HBM and the B&B frontier kept as a padded device array." The reference
itself contains no B&B (SURVEY.md §0 discrepancy note) — this engine is the
north-star extension built on the same framework substrate.

Design (TPU-first):

- The frontier is a fixed-capacity struct-of-arrays stack in HBM
  (prefix paths, visited bitmasks, costs, bounds, validity, count).
- One ``expand_step`` jit pops the top K nodes (LIFO -> depth-first memory
  behavior), expands all K*n children as a single vmapped evaluation
  against the resident distance matrix, prunes against the incumbent,
  detects completed tours, and pushes survivors sorted worst-bound-first so
  the next pop explores best-bound-first. No data-dependent shapes: pruned
  lanes are masked, the push uses a prefix-sum scatter.
- Admissible lower bound: every city still to be left contributes at least
  its cheapest outgoing edge; the running sum over unvisited cities is
  carried in the state so the child bound is one add. By default the edge
  metric is reshaped by Held-Karp 1-tree potentials (``bound="one-tree"``,
  ops.one_tree) — typically orders of magnitude fewer nodes at identical
  kernel cost; ``bound="min-out"`` is the plain metric.
- The incumbent starts from the best of a multistart nearest-neighbor
  batch, each polished by the device 2-opt kernel (``strong_incumbent``),
  so pruning is strong from step one.
- The host loop only reads back two scalars per iteration (frontier count,
  incumbent) — the expansion itself never syncs.
- Multi-rank: ``expand_step`` composes with ``shard_map`` by giving each
  rank its own frontier shard and sharing the incumbent with ``lax.pmin``
  (``parallel.reduce.pmin_incumbent``); see ``solve_sharded``.
- Checkpoint/resume: the frontier + incumbent are plain arrays; ``save``/
  ``restore`` round-trips them through an .npz (SURVEY.md §5 checkpoint
  row: incumbent + frontier give restart for long runs).
"""

from __future__ import annotations

import hashlib
import time
from collections import OrderedDict
from dataclasses import dataclass
from functools import partial
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..analysis import contracts as _contracts
from ..obs import anomaly as _obs_anomaly
from ..obs import metrics as _obs_metrics
from ..obs import rankview as _obs_rank
from ..obs import timeseries as _obs_series
from ..obs import tracing as _obs_tracing
from ..parallel import balance as _par_balance
from ..perf import compile_cache as _perf_cache
from ..perf import donation as _donation
from ..resilience import checkpoint as _ckpt_store
from ..resilience.faults import TransientFault as _TransientFault
from ..resilience.faults import registry as _fault_registry
from ..resilience.retry import RetryPolicy as _RetryPolicy

#: what the spill-readback retry absorbs: injected faults (chaos) AND the
#: errors a real flaky device->host transfer raises — XlaRuntimeError from
#: the runtime, OSError from the remote-relay transport. Bounded at 3
#: attempts, so a genuine programming error still surfaces in ~15 ms.
try:
    from jaxlib.xla_extension import XlaRuntimeError as _XlaRuntimeError

    _TRANSFER_ERRORS: tuple = (_TransientFault, OSError, _XlaRuntimeError)
except ImportError:  # jaxlib layout drift: keep the portable subset
    _TRANSFER_ERRORS = (_TransientFault, OSError)

INF = jnp.inf

#: frontier node-row layout version (v2 = int8-packed tour prefix). The
#: canonical constant lives in perf.compile_cache so the AOT executable
#: key can include it without importing the engine; re-exported here
#: because the layout is defined by THIS module.
FRONTIER_LAYOUT_VERSION = _perf_cache.FRONTIER_LAYOUT_VERSION

#: city ids packed per int32 path word. 4 = int8 lanes — exact for every
#: supported instance (city ids < MAX_BNB_CITIES = 200 < 256); a future
#: n > 255 engine would drop to 2 (int16 lanes) per the same scheme.
PATH_PACK = 4


def _path_words(n: int) -> int:
    """int32 words holding the packed [n]-city tour prefix (P)."""
    return (n + PATH_PACK - 1) // PATH_PACK


def _layout(cols: int) -> Tuple[int, int]:
    """Invert the packed-row width ``cols = P + W + 4`` (P = ceil(n/4)
    path words, W = ceil(n/32) mask words) to ``(n, W)``.

    Byte-packing makes the exact n ambiguous within one (P, W) cell —
    n and n+1 share a path word for 3 of every 4 values — but the
    OFFSETS (P, W) are unique: the map n -> (P(n), W(n)) is monotone and
    P+W strictly increases across cell boundaries, so a given width
    admits exactly one consistent cell. The returned ``n`` is the cell's
    MAXIMUM (4P, clipped to the mask words): enough for every offset/
    view computation. Code that needs the exact n (checkpoint unpack,
    value-level contracts) threads it explicitly.
    """
    for n_hi in range(min((cols - 5) * PATH_PACK, 32 * (cols - 5)), 0, -1):
        w = (n_hi + 31) // 32
        if _path_words(n_hi) + w + 4 == cols:
            return n_hi, w
    raise ValueError(f"no valid (n, W) layout for packed row width {cols}")


def _layout_n_range(cols: int) -> Tuple[int, int]:
    """The inclusive [n_lo, n_hi] range of city counts consistent with a
    packed row width (see _layout's ambiguity note)."""
    n_hi, w = _layout(cols)
    n_lo = n_hi
    while n_lo > 1 and _path_words(n_lo - 1) + ((n_lo - 1 + 31) // 32) + 4 == cols:
        n_lo -= 1
    return n_lo, n_hi


def _pack_path_np(path: np.ndarray, n: int) -> np.ndarray:
    """Host-side path packing: [..., n] city ids -> [..., P] int32 words,
    4 uint8 lanes per word (byte j of word w holds city 4w+j). Explicit
    shifts, not a dtype view, so the byte order is endian-independent
    and matches the kernel's shift arithmetic bit-for-bit."""
    p = _path_words(n)
    padded = np.zeros(path.shape[:-1] + (p * PATH_PACK,), np.uint32)
    padded[..., :n] = np.asarray(path, np.int64) & 0xFF
    lanes = padded.reshape(path.shape[:-1] + (p, PATH_PACK))
    words = (
        lanes[..., 0]
        | (lanes[..., 1] << 8)
        | (lanes[..., 2] << 16)
        | (lanes[..., 3] << 24)
    )
    return words.astype(np.uint32).view(np.int32)


def _unpack_path_np(words: np.ndarray, n: int) -> np.ndarray:
    """Host-side inverse of ``_pack_path_np``: [..., P] words -> [..., n]."""
    u = np.ascontiguousarray(words).view(np.uint32)
    shifts = np.arange(PATH_PACK, dtype=np.uint32) * 8
    lanes = (u[..., :, None] >> shifts) & np.uint32(0xFF)
    return (
        lanes.reshape(words.shape[:-1] + (-1,))[..., :n].astype(np.int32)
    )


def _f32(words: jnp.ndarray) -> jnp.ndarray:
    """Bitcast int32 words back to the float32 they store."""
    return jax.lax.bitcast_convert_type(words, jnp.float32)


def _i32(vals: jnp.ndarray) -> jnp.ndarray:
    """Bitcast float32 values to int32 words for packed storage."""
    return jax.lax.bitcast_convert_type(vals, jnp.int32)


class Frontier(NamedTuple):
    """Packed frontier: ONE ``[F, P + W + 4]`` int32 node buffer.

    Row column layout v2 (P = ceil(n/4) path words, W = ceil(n/32)
    visited-bitmask words — FRONTIER_LAYOUT_VERSION):

        [0, P)      path    int8-packed city prefix: 4 uint8 ids per
                            int32 word, byte j of word w = city 4w+j
                            (undefined past depth; pad bytes past n stay 0)
        [P, P+W)    mask    visited bitmask words (uint32 bit patterns)
        P+W         depth   int32
        P+W+1       cost    float32 prefix cost (bitcast)
        P+W+2       bound   float32 admissible lower bound (bitcast)
        P+W+3       sum_min float32 sum of min_out over unvisited (bitcast)

    Why one buffer instead of the round-3 six-array SoA: every operation
    that moves nodes (the push scatter, reorder/compact gathers, ring-
    balance ppermutes, reservoir spills) paid SIX gather/scatter ops, and
    on TPU the cost is per-op, not per-byte — the on-chip A/B
    (SCATTER_PROFILE_TPU.json, live-carry harness) measured the
    six-scatter push at 6.86 ms vs 2.32 ms for one packed scatter
    (gather+DUS variant: 1.46 ms). The logical fields remain available
    as read-only property views (cheap slices, fused by XLA).

    Why int8-packed path words (v2, ISSUE 8): the tour prefix dominated
    the row — n full int32 lanes carrying values < 256. Packing 4 ids
    per word shrinks every cost that scales with row bytes by ~3x at
    n=100 (row 432 -> 132 bytes): the push write, reorder/compact
    gathers, balance slabs, reservoir spill traffic, checkpoint size.
    Bounds stay f32 bitcast columns (screened in f32; f64 only at the
    certification boundaries in _bound_setup), exactly as before.

    The trailing four scalar columns sit at FIXED offsets from the row
    END (cols-4..cols-1), so width-only consumers (the bound column
    slice in parallel.reduce, host spill partitioning) never need n.
    """

    nodes: jnp.ndarray  # [F, P + W + 4] int32 packed rows (see layout above)
    count: jnp.ndarray  # scalar int32: stack height
    #: scalar bool: a push batch overran capacity INSIDE the kernel (children
    #: silently dropped -> exactness lost). solve()'s spill-to-reservoir keeps
    #: headroom so this is unreachable when inner_steps*k*(n-1) <= capacity/2
    #: (and rare otherwise); proven_optimal always checks it, so exactness is
    #: never silently lost.
    overflow: jnp.ndarray

    @property
    def _pw(self) -> Tuple[int, int]:
        n_hi, w = _layout(self.nodes.shape[-1])
        return _path_words(n_hi), w

    @property
    def path_words(self) -> jnp.ndarray:
        """The raw [..., P] int8-packed path words."""
        return self.nodes[..., : self._pw[0]]

    def path_view(self, n: int) -> jnp.ndarray:
        """Unpacked [..., n] int32 city prefix (device op: byte extract).
        Needs the exact ``n`` — the width only bounds it (see _layout)."""
        return _unpack_path(self.path_words, n)

    @property
    def path(self) -> jnp.ndarray:
        """Unpacked [..., n_hi] city prefix for host/test convenience,
        where n_hi is the layout-maximum n for this width (true-n callers
        slice ``[..., :n]`` or use :meth:`path_view`; pad lanes are 0)."""
        return _unpack_path(self.path_words, _layout(self.nodes.shape[-1])[0])

    @property
    def mask(self) -> jnp.ndarray:
        p, w = self._pw
        # int32 -> uint32 is a modular convert == bitcast: same words
        return self.nodes[..., p : p + w].astype(jnp.uint32)

    @property
    def depth(self) -> jnp.ndarray:
        return self.nodes[..., -4]

    @property
    def cost(self) -> jnp.ndarray:
        return _f32(self.nodes[..., -3])

    @property
    def bound(self) -> jnp.ndarray:
        return _f32(self.nodes[..., -2])

    @property
    def sum_min(self) -> jnp.ndarray:
        return _f32(self.nodes[..., -1])


#: the logical per-node fields, in packed-column order — the checkpoint
#: format (save/restore serialize these, NOT the packed buffer, so the
#: .npz layout is stable across engine-internal layout changes — a v1
#: unpacked-path-era snapshot restores into the v2 packed layout)
CKPT_NODE_FIELDS = ("path", "mask", "depth", "cost", "bound", "sum_min")


def _unpack_path(words: jnp.ndarray, n: int) -> jnp.ndarray:
    """Device-side path unpack: [..., P] int32 words -> [..., n] city ids.
    Arithmetic >> sign-extends negative words; the & 0xFF mask restores
    the unsigned byte, so every id round-trips exactly."""
    shifts = (jnp.arange(PATH_PACK, dtype=jnp.int32) * 8)[None, :]
    lanes = (words[..., :, None] >> shifts) & 0xFF
    return lanes.reshape(words.shape[:-1] + (-1,))[..., :n]


def _path_byte_get(words: jnp.ndarray, pos: jnp.ndarray) -> jnp.ndarray:
    """City id at prefix position ``pos`` per row: words [..., P] int32,
    pos [...] int32 -> [...] int32."""
    word = jnp.take_along_axis(words, (pos // PATH_PACK)[..., None], axis=-1)[
        ..., 0
    ]
    return (word >> ((pos % PATH_PACK) * 8)) & 0xFF


def _unpack_rows_np(rows: np.ndarray, n: Optional[int] = None) -> dict:
    """Host-side inverse of ``_pack_rows_np``: packed int32 rows -> the
    logical field arrays (pure numpy, no device work). ``n``: the exact
    city count; None takes the layout-maximum for the width (path then
    carries up to 3 all-zero pad lanes — fine for width-only consumers,
    NOT for checkpoint payloads, whose shape is the format)."""
    n_hi, w = _layout(rows.shape[-1])
    if n is None:
        n = n_hi
    p = _path_words(n_hi)
    rows = np.ascontiguousarray(rows)

    def fcol(c):
        return np.ascontiguousarray(rows[..., c]).view(np.float32)

    return {
        "path": _unpack_path_np(rows[..., :p], n),
        "mask": np.ascontiguousarray(rows[..., p : p + w]).view(np.uint32),
        "depth": rows[..., -4],
        "cost": fcol(-3),
        "bound": fcol(-2),
        "sum_min": fcol(-1),
    }


def _pack_rows_np(path, mask, depth, cost, bound, sum_min) -> np.ndarray:
    """Host-side inverse of the property views: six logical field arrays
    (leading dims arbitrary) -> one packed int32 row array."""

    def fbits(a):
        return np.ascontiguousarray(np.asarray(a, np.float32)).view(np.int32)

    return np.concatenate(
        [
            _pack_path_np(np.asarray(path), np.shape(path)[-1]),
            np.ascontiguousarray(np.asarray(mask, np.uint32)).view(np.int32),
            np.asarray(depth, np.int32)[..., None],
            fbits(cost)[..., None],
            fbits(bound)[..., None],
            fbits(sum_min)[..., None],
        ],
        axis=-1,
    )


@dataclass
class SpillStats:
    """Per-solve reservoir transfer accounting — the evidence that spills
    move only live-prefix bytes (ADVICE r5 items 2-3), surfaced through
    ``BnBResult`` and the bench/driver JSON so the invariant is measured,
    not asserted.

    ``rounds`` counts spill/refill synchronization points (one per
    ``spill_refill`` call that did work, or per single-device
    exchange/refill); ``events`` counts per-rank exchanges within them.
    ``full_merges`` counts the events that actually concatenated the host
    reservoir (the slow path — only taken when the reservoir owns the
    rank's alive minimum). The byte counters measure actual host<->device
    traffic: live-prefix fetches down, kept-slice writes up.
    """

    rounds: int = 0
    events: int = 0
    full_merges: int = 0
    bytes_to_host: int = 0
    bytes_to_device: int = 0


@dataclass
class BnBResult:
    cost: float
    tour: np.ndarray  # [n+1] closed tour of city indices, starts/ends at 0
    nodes_expanded: int
    iterations: int
    proven_optimal: bool
    wall_seconds: float
    nodes_per_sec: float
    time_to_best: float
    #: proven lower bound at the root (1-tree value; min-out sum otherwise) —
    #: reported so callers can state the optimality gap when stopping early
    root_lower_bound: float = -np.inf
    #: final certified global lower bound: min bound over all still-open
    #: nodes (device frontier + host reservoir) at stop, or the proven
    #: cost. Node bounds are monotone down the tree (children inherit the
    #: parent's strong bound), so this is >= root_lower_bound — on an
    #: unproven run it shows how far the search actually closed the gap
    lower_bound: float = -np.inf
    #: per-rank expansion counts (solve_sharded only) — load-balance evidence
    nodes_per_rank: Optional[np.ndarray] = None
    #: seconds spent before the search loop (bound setup + incumbent/ILS
    #: construction) — wall_seconds/time_to_best measure the search only,
    #: so an incumbent found during setup shows time_to_best=0 and this
    #: field carries the honest cost of getting it
    setup_seconds: float = 0.0
    #: setup_seconds split: the f64 root ascent + bound tables
    #: (_bound_setup; runs on every solve, resumed or fresh) and the ILS
    #: incumbent (_initial_incumbent; zero on resume — the checkpoint
    #: carries the incumbent). The remainder — setup_seconds - ascent -
    #: ils — is backend/compile overhead, the actionable part on TPU
    ascent_seconds: float = 0.0
    ils_seconds: float = 0.0
    #: min bound over this RUN SEGMENT's still-open nodes alone (the
    #: un-clamped value); ``lower_bound`` is clamped to the running max
    #: across resumed chunks (the checkpoint carries the floor), so the
    #: reported certified LB is monotone over a chunked campaign
    lower_bound_raw: float = -np.inf
    #: reservoir transfer accounting (see SpillStats): spill/refill sync
    #: rounds, per-rank exchange events, full reservoir merges among them,
    #: and the actual bytes moved host-ward/device-ward by those events
    spill_rounds: int = 0
    spill_events: int = 0
    spill_full_merges: int = 0
    spill_bytes_to_host: int = 0
    spill_bytes_to_device: int = 0
    #: per-dispatch telemetry time series (obs.timeseries.StepSampler:
    #: nodes/sec, frontier occupancy, spill bytes each way, incumbent /
    #: certified-floor trajectory), flushed into the driver JSON; None
    #: under ``TSP_OBS=off``
    series: Optional[dict] = None
    #: stall-sentinel verdicts (obs.anomaly.StallSentinel: nodes/sec
    #: collapse, certified-LB stagnation — each also fired as a health
    #: event + registry counter at detection time); None under
    #: ``TSP_OBS=off``. Sharded runs merge ``rank_starvation`` events
    #: (obs.anomaly.RankStarvationSentinel) onto the same timeline
    anomalies: Optional[dict] = None
    #: per-rank telemetry ring (obs.rankview.RankSampler): occupancy /
    #: alive rows / nodes / reservoir depth / spill events+bytes each
    #: way / best open bound per rank, one row per sampling window;
    #: sharded solves only, None under ``TSP_OBS=off``
    rank_series: Optional[dict] = None
    #: imbalance accounting over the whole run (obs.rankview.rank_balance:
    #: occupancy CV, straggler rank/score, starved ranks + episode
    #: counts, per-rank spill totals); sharded solves only, None under
    #: ``TSP_OBS=off``
    rank_balance: Optional[dict] = None
    #: adaptive-balance controller accounting (parallel.balance, ISSUE 15):
    #: per-dispatch action mix (skip/ring/pair/steal), mode switches,
    #: moved rows/bytes, CV trajectory, steal-fault degrades. Sharded
    #: solves only; present even under ``TSP_OBS=off`` — the controller
    #: is an optimization, not telemetry, and its signal (the spill
    #: path's counts readback + the alive-counts collective) never goes
    #: through the obs switch
    balance: Optional[dict] = None


def nearest_neighbor_tour(d: np.ndarray, start: int = 0) -> np.ndarray:
    n = d.shape[0]
    visited = np.zeros(n, bool)
    tour = [start]
    visited[start] = True
    for _ in range(n - 1):
        cur = tour[-1]
        cand = np.where(visited, np.inf, d[cur])
        nxt = int(np.argmin(cand))
        tour.append(nxt)
        visited[nxt] = True
    return np.asarray(tour + [tour[0]], dtype=np.int32)


def _double_bridge(rng, open_tour: np.ndarray, n: int) -> np.ndarray:
    """Cut an open tour at 3 random interior points and reconnect the 4
    segments in A-C-B-D order — the classic ILS kick 2-opt cannot undo.
    Shared by the device and host incumbent builders so their kick
    distribution stays identical."""
    i, j, kk = np.sort(rng.choice(np.arange(1, n), size=3, replace=False))
    return np.concatenate(
        [open_tour[:i], open_tour[j:kk], open_tour[i:j], open_tour[kk:]]
    )


def _close_from_zero(open_tour: np.ndarray) -> np.ndarray:
    """Rotate an open tour to start at city 0 and append the closing 0."""
    rot = int(np.argwhere(open_tour == 0)[0, 0])
    open0 = np.roll(open_tour, -rot)
    return np.concatenate([open0, open0[:1]]).astype(np.int32)


_VPOLISH = None


def _vpolish():
    """Process-global jitted batch polish, built once. The distance matrix
    is an ARGUMENT, not a closure capture: the old per-call
    ``jax.jit(jax.vmap(lambda t: polish(t, d32)[0]))`` baked d32 into the
    jaxpr as a constant, so every strong_incumbent call was a fresh cache
    entry — ~100 ms of retrace + MLIR lowering per solve at n=12 (the
    exact R11 recompile hazard graftflow flags), dominating warm-solve
    wall. jit now caches on (batch, n, device) like every other entry."""
    global _VPOLISH
    if _VPOLISH is None:
        from ..ops.local_search import polish

        _VPOLISH = jax.jit(
            jax.vmap(lambda t, dd: polish(t, dd)[0], in_axes=(0, None))
        )
    return _VPOLISH


def strong_incumbent(
    d: np.ndarray,
    starts: int = 8,
    perturbations: Optional[int] = None,
    device=None,
) -> np.ndarray:
    """Best of ``starts`` nearest-neighbor tours, each polished by the
    2-opt + Or-opt kernels in one vmapped batch (ops.local_search),
    followed by ``perturbations`` rounds of iterated local search (batched
    double-bridge kicks + re-polish — the classic escape from 2-opt local
    minima). ``perturbations=None`` auto-selects: 30 rounds for n >= 30
    (a few seconds that routinely land the published TSPLIB optimum),
    else 0.

    ``device``: pin the polish kernels to a specific jax device. The
    transfer-free accelerator path passes the CPU backend's device here:
    CPU-client buffers never touch the remote relay, so reading results
    back does NOT trip its slow dispatch mode, while keeping the full
    2-opt + Or-opt polish quality (the numpy twin strong_incumbent_host
    has no Or-opt and is measurably weaker at n >= 100).

    Returns a closed [n+1] tour rotated to start at city 0. Costs are
    re-measured on host in float64, so the incumbent fed to the pruner is
    a true tour cost regardless of the f32 polish.
    """
    n = d.shape[0]
    if perturbations is None:
        perturbations = 30 if n >= 30 else 0
    if n < 4:
        perturbations = 0  # double-bridge needs 3 distinct interior cuts
    d64 = np.asarray(d, np.float64)

    def put(x, dtype):
        arr = np.asarray(x, dtype)
        if device is not None:
            return jax.device_put(arr, device)
        return jnp.asarray(arr)

    d32 = put(d, np.float32)
    vpolish = lambda tours: _vpolish()(tours, d32)  # noqa: E731

    ss = sorted(set(np.linspace(0, n - 1, min(starts, n)).astype(int).tolist()))
    opens = np.stack([nearest_neighbor_tour(d64, s)[:-1] for s in ss])
    polished = np.asarray(vpolish(put(opens, np.int32)))
    costs = [tour_cost(d64, np.concatenate([t, t[:1]])) for t in polished]
    best = polished[int(np.argmin(costs))]
    best_cost = float(np.min(costs))

    rng = np.random.default_rng(0)
    batch = polished.shape[0]
    for _ in range(perturbations):
        kicks = [_double_bridge(rng, best, n) for _ in range(batch)]
        repolished = np.asarray(vpolish(put(np.stack(kicks), np.int32)))
        rcosts = [
            tour_cost(d64, np.concatenate([t, t[:1]])) for t in repolished
        ]
        rbest = int(np.argmin(rcosts))
        if rcosts[rbest] < best_cost:
            best_cost = rcosts[rbest]
            best = repolished[rbest]

    return _close_from_zero(best)


def two_opt(d: np.ndarray, tour: np.ndarray, max_rounds: int = 200) -> np.ndarray:
    """Host-side best-improvement 2-opt (vectorized delta matrix)."""
    t = tour[:-1].copy()  # open tour, implicit return edge
    n = len(t)
    for _ in range(max_rounds):
        pos = np.concatenate([t, t[:1]])
        a, b = pos[:-1], pos[1:]  # edges (a_i, b_i)
        # delta of reversing segment between edge i and edge j (i < j):
        # d[a_i, a_j] + d[b_i, b_j] - d[a_i, b_i] - d[a_j, b_j]
        da = d[a[:, None], a[None, :]] + d[b[:, None], b[None, :]]
        db = d[a, b][:, None] + d[a, b][None, :]
        delta = da - db
        iu = np.triu_indices(n, k=2)
        flat = delta[iu]
        k = int(np.argmin(flat))
        if flat[k] >= -1e-9:
            break
        i, j = iu[0][k], iu[1][k]
        t[i + 1 : j + 1] = t[i + 1 : j + 1][::-1]
    return np.concatenate([t, t[:1]]).astype(np.int32)


def tour_cost(d: np.ndarray, tour: np.ndarray) -> float:
    return float(d[tour[:-1], tour[1:]].sum())


#: 7 uint32 mask words; covers kroA100/pr124 and the BASELINE stretch
#: config (200-city random + 1-tree root bound on TPU)
MAX_BNB_CITIES = 200

#: device_loop mode, when time_limit_s is set: the first dispatch's step
#: budget before any measured steps/sec exists. ~50 s on a 1-vCPU host at
#: eil51 rates, trivial on a TPU; subsequent dispatches scale to the
#: measured rate so the host can re-check the clock near the limit.
_FIRST_DISPATCH_STEPS = 5_000


def _mask_consts(n: int):
    """Static per-``n`` helpers for the [W]-word visited bitmask.

    Returns (W, word_idx[n], bit[n], set_bit[n, W]): city j lives in word
    ``word_idx[j]`` at bit ``bit[j]``; OR-ing ``set_bit[j]`` into a mask
    visits j. All become jaxpr constants under jit (n is static).
    """
    w = (n + 31) // 32
    word_idx = np.arange(n) // 32
    bit = np.arange(n) % 32
    set_bit = np.zeros((n, w), np.uint32)
    set_bit[np.arange(n), word_idx] = np.uint32(1) << bit.astype(np.uint32)
    return (
        w,
        jnp.asarray(word_idx, jnp.int32),
        jnp.asarray(bit, jnp.uint32),
        jnp.asarray(set_bit),
    )


class BoundData(NamedTuple):
    """Device arrays + flags driving the expansion kernel's pruning."""

    min_out: jnp.ndarray  # [n] f32 per-city weight (incremental bound)
    bound_adj: jnp.ndarray  # [n] f32 per-child adjustment
    dbar: jnp.ndarray  # [n, n] f32 reduced metric d + pi_i + pi_j (MST bound)
    pi: jnp.ndarray  # [n] f32 potentials (zeros in min-out mode)
    slack: jnp.ndarray  # scalar f32 rounding slack for the MST bound (0 if exact)
    ascent_step: jnp.ndarray  # scalar f32 per-node mini-ascent step (grid multiple)
    lam_budget: jnp.ndarray  # scalar f32 clamp on per-node ascent deltas
    root_lb: float  # certified global lower bound (f64-evaluated)
    integral: bool  # metric is integer-valued; bounds are fixed-point exact


def strong_incumbent_host(
    d: np.ndarray, starts: int = 8, perturbations: Optional[int] = None
) -> np.ndarray:
    """Pure-numpy twin of ``strong_incumbent``: multistart NN + numpy
    2-opt + sequential double-bridge ILS. Same contract (closed [n+1]
    tour from city 0), zero jax work. NOTE: no Or-opt — measurably weaker
    than ``strong_incumbent`` at n >= 100; the solvers' transfer-free
    paths therefore use ``strong_incumbent(device=<cpu backend>)``
    instead (CPU-client readbacks don't trip the relay's slow mode), and
    this twin remains as the jax-free fallback/reference."""
    n = d.shape[0]
    if perturbations is None:
        perturbations = 30 if n >= 30 else 0
    if n < 4:
        perturbations = 0
    d64 = np.asarray(d, np.float64)
    ss = sorted(set(np.linspace(0, n - 1, min(starts, n)).astype(int).tolist()))
    best, best_cost = None, np.inf
    for s in ss:
        t = two_opt(d64, nearest_neighbor_tour(d64, s))
        c = tour_cost(d64, t)
        if c < best_cost:
            best, best_cost = t[:-1].copy(), c
    rng = np.random.default_rng(0)
    n_kicks = len(ss)  # match the device twin's per-round batch of kicks
    for _ in range(perturbations):
        round_best, round_cost = None, np.inf
        for _ in range(n_kicks):
            kick = _double_bridge(rng, best, n)
            t = two_opt(d64, np.concatenate([kick, kick[:1]]))
            c = tour_cost(d64, t)
            if c < round_cost:
                round_best, round_cost = t[:-1].copy(), c
        if round_cost < best_cost:
            best, best_cost = round_best, round_cost
    return _close_from_zero(best)


def _bound_setup(
    d, bound: str, ascent_steps: int = 400, node_ascent: int = 0,
    ascent: str = "host",
) -> BoundData:
    """Build the bound machinery for a metric + bound mode -> ``BoundData``.

    "min-out": pi = 0 — weights are the plain cheapest outgoing edge.
    "one-tree": Held-Karp subgradient ascent (ops.one_tree) supplies
    potentials pi; weights become the min reduced outgoing edge - 2*pi with
    a per-child adjustment pi[child] - pi[0]. The same pi also defines the
    reduced metric ``dbar`` for the strong per-node MST bound
    (_batched_mst_bound). The Held-Karp bound is valid for ARBITRARY pi, so
    pi may be quantized freely; only the f64 re-evaluation of the root
    bound must be (and is) certified.

    Float32 safety is handled in one of two ways:

    - **Integral metric** (all distances integers — every TSPLIB instance):
      pi is snapped onto a power-of-two grid chosen so every intermediate
      value in the expansion kernel is an exact multiple of the grid below
      2^24 grid units — f32 fixed-point arithmetic with ZERO rounding
      error, so bounds certify pruning with no slack. ``root_lb`` is the
      certified f64 1-tree value raised to the next integer (the optimum is
      an integer).
    - **Float metric**: a slack sized to the worst-case accumulated f32
      rounding of a root-to-leaf bound chain (~3n operations — prefix-cost
      accumulation, carried weight sums, MST edges, pi corrections — on
      values up to the magnitude cap, each contributing <= spacing(mag)/2)
      is shaved off the per-child adjustment and subtracted whole from the
      per-node MST bound, so rounding can never prune the true optimum.
      Applied in BOTH bound modes.
    """
    n = d.shape[0]
    d64 = np.asarray(d, np.float64)
    integral = _is_integral(d64)
    eye = np.eye(n, dtype=bool)
    if bound == "one-tree":
        if ascent == "host":
            # f64 numpy ascent, zero device work — keeps the process in
            # the relay's fast (transfer-free) dispatch mode for the
            # device search that follows. Deterministic in (d, steps), so
            # the compile-once layer memoizes it on disk: a resumed chunk
            # process re-pays a file read, not 400 subgradient steps
            # (byte-identical potentials — results cannot drift)
            pi64 = _perf_cache.ascent_memo_get(d64, bound, ascent_steps)
            if pi64 is None:
                from ..ops.one_tree import held_karp_potentials_np

                pi64, _ = held_karp_potentials_np(d64, steps=ascent_steps)
                _perf_cache.ascent_memo_put(d64, bound, ascent_steps, pi64)
        else:
            from ..ops.one_tree import held_karp_potentials

            d32 = jnp.asarray(d64, jnp.float32)
            pi_dev, _ = held_karp_potentials(d32, steps=ascent_steps)
            pi64 = np.asarray(pi_dev, np.float64)
    elif bound == "min-out":
        pi64 = np.zeros(n)
    else:
        raise ValueError(f"bound must be 'one-tree' or 'min-out', got {bound!r}")

    # magnitude cap over every kernel intermediate: prefix costs (<= n*max d),
    # MST sums over the reduced metric, carried weight sums, pi corrections
    max_d = float(np.abs(d64).max())
    max_pi = float(np.abs(pi64).max())
    # the + 2*n*max_d term covers per-node mini-ascent lambda drift: lam is
    # clamped to +-max_d/4 (lam_budget below), so each of <= n+1 structure
    # edges shifts by <= max_d/2 and the degree-weighted correction by
    # <= 2(n+1)*max_d/4 — both under n*max_d apiece
    mag = (
        n * (max_d + 4.0 * max_pi)
        + 4.0 * float(np.abs(pi64).sum())
        + 2.0 * n * max_d
        + 1.0
    )

    # a negative grid exponent would make the grid coarser than 1, so integer
    # distances would no longer be exact grid multiples — fall back to the
    # slack path (only reachable for distances ~> 2^24/n, far beyond TSPLIB)
    g_cap = int(np.floor(np.log2(2.0**24 / mag)))
    if integral and g_cap < 0:
        integral = False
    if integral:
        # finest power-of-two grid keeping all values exact in f32 (cap 2^-10)
        grid = 2.0 ** (-min(10, g_cap))
        pi64 = np.round(pi64 / grid) * grid
        slack = 0.0
    else:
        # sized for ~3n ops per bound chain, plus one more full Prim chain
        # (~3n ops) per mini-ascent step actually requested
        slack = 3.0 * (1 + node_ascent) * n * float(np.spacing(np.float32(mag)))

    # derive everything from the (possibly quantized) pi in f64: for the
    # integral path all results are exact grid multiples, hence exact in f32
    dbar64 = d64 + pi64[:, None] + pi64[None, :]
    dbar_inf = np.where(eye, np.inf, dbar64)
    w = dbar_inf.min(1) - 2.0 * pi64
    adj = pi64 - pi64[0]

    if bound == "one-tree":
        from ..ops.one_tree import one_tree_value_np

        root_lb = one_tree_value_np(d64, pi64)
    else:
        root_lb = float(w.sum())  # every city is left once

    if integral:
        root_lb = float(np.ceil(root_lb - 1e-6))
    else:
        root_lb = root_lb - slack
        adj = adj - slack
    # per-node mini-ascent step + lambda clamp: small fractions of the edge
    # scale; snapped to grid multiples on the integral path so lambda stays
    # exact (the clamp also guarantees the mag headroom above)
    raw_step = max_d / (8.0 * n)
    lam_budget = max_d / 4.0
    if integral:
        raw_step = max(grid, np.floor(raw_step / grid) * grid)
        lam_budget = max(grid, np.floor(lam_budget / grid) * grid)
    return BoundData(
        jnp.asarray(w, jnp.float32),
        jnp.asarray(adj, jnp.float32),
        jnp.asarray(dbar64, jnp.float32),
        jnp.asarray(pi64, jnp.float32),
        jnp.asarray(slack, jnp.float32),
        jnp.asarray(raw_step, jnp.float32),
        jnp.asarray(lam_budget, jnp.float32),
        root_lb,
        integral,
    )


def _conn_edges(dbar, unvis, cur, n, lam=None):
    """Connection-edge value + degree bumps -> (conn, bump).

    The path relaxation closes MST(U) with one edge cur->U and one edge
    0->U (root lanes ``cur == 0``: the two cheapest 0-incident edges).
    Shared by the Prim and Boruvka MST kernels so the two bounds differ
    ONLY in how the spanning-tree value is computed.
    """
    big = jnp.asarray(jnp.inf, dbar.dtype)
    cities_row = jnp.arange(n, dtype=jnp.int32)[None, :]

    def edge_rows(u):  # [k, n] reduced costs from each lane's vertex u
        base = dbar[u]
        if lam is None:
            return base
        return base + jnp.take_along_axis(lam, u[:, None], axis=1) + lam

    def onehot(idx):
        return cities_row == idx[:, None].astype(jnp.int32)

    row_cur = jnp.where(unvis, edge_rows(cur), big)
    row_0 = jnp.where(unvis, edge_rows(jnp.zeros_like(cur)), big)
    a_cur = jnp.argmin(row_cur, axis=1)
    min_cur = jnp.take_along_axis(row_cur, a_cur[:, None], axis=1)[:, 0]
    neg2, idx2 = jax.lax.top_k(-row_0, 2)
    is_root = cur == 0
    conn = jnp.where(is_root, -neg2[:, 0] - neg2[:, 1], min_cur + (-neg2[:, 0]))
    conn = jnp.where(jnp.isfinite(conn), conn, big)
    # connection-edge degree bumps (one-hot adds: TPU lowers per-lane
    # scatters to serialized stores, a broadcast compare is one op)
    zero_i = jnp.zeros_like(cur)
    bump = (
        onehot(jnp.where(is_root, idx2[:, 1], a_cur)).astype(jnp.int32)
        + onehot(idx2[:, 0]).astype(jnp.int32)
        + onehot(jnp.where(is_root, zero_i, cur)).astype(jnp.int32)
        + onehot(zero_i).astype(jnp.int32)
    )
    return conn, bump


def _mst_conn(dbar, unvis, cur, n, lam=None):
    """One MST(U) + connection-edges evaluation -> (value, degrees).

    ``lam``: optional [k, n] per-node potential deltas added edge-wise
    (lam_i + lam_j) on top of ``dbar`` — used by the per-node mini-ascent.
    Degrees count MST + connection edges per vertex (cur/0 included), the
    subgradient of the path relaxation (targets: cur/0 -> 1, U -> 2).
    """
    big = jnp.asarray(jnp.inf, dbar.dtype)
    k = unvis.shape[0]
    cities_row = jnp.arange(n, dtype=jnp.int32)[None, :]

    def edge_rows(u):  # [k, n] reduced costs from each lane's vertex u
        base = dbar[u]
        if lam is None:
            return base
        return base + jnp.take_along_axis(lam, u[:, None], axis=1) + lam

    # one-hot lane updates throughout: TPU lowers per-lane scatters
    # (.at[lanes, idx].add/.set) to serialized stores, whereas a broadcast
    # compare + select is one vectorized op over the [k, n] tile
    def onehot(idx):
        return cities_row == idx[:, None].astype(jnp.int32)

    start = jnp.argmax(unvis, axis=1)
    intree0 = onehot(start)
    mind0 = jnp.where(unvis, edge_rows(start), big)
    closest0 = jnp.broadcast_to(start[:, None], (k, n))
    # zero carries derived from varying inputs so their varying-axis types
    # match the body outputs under shard_map
    deg0 = (unvis * 0).astype(jnp.int32)

    def body(_, carry):
        intree, mind, closest, deg, tot = carry
        cand = jnp.where(intree, big, mind)
        u = jnp.argmin(cand, axis=1)
        oh_u = onehot(u)
        wu = jnp.min(cand, axis=1)
        fin = jnp.isfinite(wu)
        tot = tot + jnp.where(fin, wu, 0.0)
        par = jnp.take_along_axis(closest, u[:, None], axis=1)[:, 0]
        one = fin[:, None].astype(jnp.int32)
        deg = deg + (oh_u.astype(jnp.int32) + onehot(par).astype(jnp.int32)) * one
        intree = intree | oh_u
        row = jnp.where(unvis, edge_rows(u), big)
        better = row < mind
        closest = jnp.where(better, u[:, None], closest)
        mind = jnp.minimum(mind, row)
        return intree, mind, closest, deg, tot

    zero = (cur * 0).astype(dbar.dtype)
    _, _, _, deg, mst = jax.lax.fori_loop(
        0, n - 1, body, (intree0, mind0, closest0, deg0, zero)
    )

    conn, bump = _conn_edges(dbar, unvis, cur, n, lam)
    return mst + conn, deg + bump


def _mst_conn_boruvka(dbar, unvis, cur, n, lam=None):
    """Log-depth Boruvka MST(U) + connection edges -> (value, degrees).

    Same contract as ``_mst_conn`` (Prim), rebuilt for the TPU's latency
    profile: Prim's critical path is n-1 sequential fori iterations of
    small [k, n] ops (per-iteration overhead dominates the expansion step
    on-chip — BENCHMARKS.md round-4 step analysis), while Boruvka runs
    ceil(log2 n) rounds of batched [k, n, n] reductions that the VPU can
    actually fill.

    Exactness: every MST of a graph has the same total weight (all MSTs
    share one sorted weight multiset), so the VALUE this kernel certifies
    equals Prim's — bit-exactly under the fixed-point integral grid,
    where sums of grid multiples are exact in f32. Ties are broken by the
    global lexicographic order (weight, canonical edge id), which makes
    each round's component choices cycle-free (two components that both
    see minimum-weight edges between them necessarily choose the SAME
    edge, which is then counted once). DEGREES may differ from Prim's
    when ties admit multiple MSTs; any MST's degrees are an equally valid
    subgradient for the per-node mini-ascent (the bound is certified for
    arbitrary potentials — see _batched_mst_bound).

    Rounding: the value accumulates <= n-1 real edge additions plus one
    round-total per Boruvka round (zeros added exactly), i.e. fewer
    error-carrying ops than the ~3n budget _bound_setup's non-integral
    ``slack`` is sized for, so the Prim slack certifies this kernel too.
    """
    big = jnp.asarray(jnp.inf, dbar.dtype)
    k = unvis.shape[0]
    slots = jnp.arange(n, dtype=jnp.int32)[None, :]  # [1, n] root slots
    eid_sentinel = jnp.int32(n * n)

    # symmetric [k, n, n] edge weights over U x U (diagonal excluded)
    w = jnp.broadcast_to(dbar[None, :, :], (k, n, n))
    if lam is not None:
        w = w + lam[:, :, None] + lam[:, None, :]
    pair_ok = (
        unvis[:, :, None]
        & unvis[:, None, :]
        & ~jnp.eye(n, dtype=bool)[None, :, :]
    )
    wm = jnp.where(pair_ok, w, big)

    comp = jnp.broadcast_to(slots, (k, n))  # component label per vertex
    deg = jnp.zeros((k, n), jnp.int32)
    total = jnp.zeros((k,), dbar.dtype)
    rounds = int(np.ceil(np.log2(max(n, 2))))  # components at least halve
    jumps = int(np.ceil(np.log2(max(n, 2))))

    # log-depth Boruvka: `rounds` is a static O(log n) bound and each
    # round's dependence is sequential  # graftlint: disable=R4
    for _ in range(rounds):
        # per-vertex cheapest outgoing edge (crossing components); argmin's
        # first-index rule picks the smallest partner u among ties, which
        # is exactly the smallest canonical edge id for fixed v
        cross = comp[:, :, None] != comp[:, None, :]
        wr = jnp.where(cross, wm, big)
        wv = jnp.min(wr, axis=2)  # [k, n]
        pu = jnp.argmin(wr, axis=2).astype(jnp.int32)  # [k, n] partner
        v = jnp.broadcast_to(slots, (k, n))
        eid = jnp.where(v < pu, v * n + pu, pu * n + v)  # canonical id
        eid = jnp.where(jnp.isfinite(wv), eid, eid_sentinel)

        # per-component lex-min (weight, edge id) over member vertices;
        # root-slot layout: component c's result lives at slot c
        member = comp[:, None, :] == slots[:, :, None]  # [k, root, v]
        mw = jnp.min(jnp.where(member, wv[:, None, :], big), axis=2)
        elig = member & (wv[:, None, :] == mw[:, :, None])
        me = jnp.min(
            jnp.where(elig, eid[:, None, :], eid_sentinel), axis=2
        )  # [k, root] chosen canonical edge id
        has = jnp.isfinite(mw) & (me < eid_sentinel)

        # endpoints + partner component of each root's chosen edge
        a = jnp.where(has, me // n, 0)
        b = jnp.where(has, me % n, 0)
        ca = jnp.take_along_axis(comp, a, axis=1)
        cb = jnp.take_along_axis(comp, b, axis=1)
        partner = jnp.where(has, ca + cb - slots, slots)  # the other root
        # count each edge once: when both endpoint components chose the
        # same edge, the smaller root id keeps it
        me_p = jnp.take_along_axis(me, partner, axis=1)
        dup = has & (me_p == me) & (partner < slots)
        add = has & ~dup
        total = total + jnp.sum(jnp.where(add, mw, 0.0), axis=1)
        a_oh = (slots[:, None, :] == a[:, :, None]) & add[:, :, None]
        b_oh = (slots[:, None, :] == b[:, :, None]) & add[:, :, None]
        deg = deg + jnp.sum(a_oh, axis=1) + jnp.sum(b_oh, axis=1)

        # contract: hook each root onto its partner, break 2-cycles by
        # letting the smaller root own the star, then pointer-jump
        hook = jnp.where(has, partner, jnp.broadcast_to(slots, (k, n)))
        hp = jnp.take_along_axis(hook, hook, axis=1)
        star = jnp.where((hp == slots) & (slots < hook), slots, hook)
        # static O(log n) pointer-jumping chain  # graftlint: disable=R4
        for _ in range(jumps):
            star = jnp.take_along_axis(star, star, axis=1)
        comp = jnp.take_along_axis(star, comp, axis=1)

    conn, bump = _conn_edges(dbar, unvis, cur, n, lam)
    # a lane whose U has 0/1 vertices has MST 0 and an infinite connection
    # value — same shape Prim produces; callers turn non-finite into big
    return total + conn, deg + bump


#: expansion-time MST kernels (static ``mst_kernel`` selects one): "prim"
#: is the [k, n] fori-loop chain (the default everywhere), "boruvka" the
#: log-depth batched variant built for the TPU's latency profile — select
#: it explicitly (--mst-kernel / TSP_BENCH_MST_KERNEL); it is NOT chosen
#: automatically on any backend (and is ~10x slower on a scalar CPU)
def _mst_conn_prim_pallas(dbar, unvis, cur, n, lam=None):
    """Prim MST(U) with the n-1 step chain fused into ONE Pallas kernel
    (ops/prim_pallas — the chain is op-issue-latency-bound as XLA ops;
    see BENCHMARKS.md round-4 step attribution). Bit-identical (tot, deg)
    to _mst_conn; the connection edges stay in jnp, shared with every
    kernel."""
    from ..ops.prim_pallas import prim_chain

    tot, deg = prim_chain(dbar, unvis, n, lam)
    conn, bump = _conn_edges(dbar, unvis, cur, n, lam)
    return tot + conn, deg + bump


_MST_CONN = {
    "prim": _mst_conn,
    "boruvka": _mst_conn_boruvka,
    "prim_pallas": _mst_conn_prim_pallas,
}


def _batched_mst_bound(
    dbar: jnp.ndarray,
    pi: jnp.ndarray,
    unvis: jnp.ndarray,
    cur: jnp.ndarray,
    p_cost: jnp.ndarray,
    n: int,
    node_ascent: int = 0,
    ascent_step=None,
    lam_budget=None,
    mst_kernel: str = "prim",
):
    """Reduced-cost MST + connection-edges lower bound for a batch of nodes.

    For a node with prefix ending at ``cur`` and unvisited set U, the
    remaining tour is a path cur -> (all of U) -> 0. Such a path uses
    exactly one edge from cur into U, a spanning path of U (>= its MST),
    and one edge from U to 0 — it can never take a direct cur->0 edge, so

        MST_dbar(U) + min_u dbar[cur, u] + min_u dbar[0, u]

    lower-bounds its reduced cost; for ``cur == 0`` (the root) the two
    connection edges become the two cheapest 0-incident edges, making this
    exactly the Held-Karp 1-tree. In the reduced metric
    ``dbar = d + pi_i + pi_j`` the path's d-cost is its dbar-cost minus
    ``pi[cur] + pi[0] + 2*sum(pi[U])``, giving the final bound

        prefix_cost + MST_dbar(U) + conn - pi[cur] - pi[0] - 2*sum(pi[U]).

    ``node_ascent > 0`` adds that many per-node subgradient steps on TOP of
    the global potentials: per-lane deltas ``lam`` move along
    ``deg - target`` (targets: cur/0 -> 1, U -> 2; the relaxation is valid
    for ARBITRARY potentials, so any lam yields a certified bound and the
    best over steps is kept). Each step costs one more vmapped Prim.

    This is typically FAR stronger than the incremental min-out sum, at the
    cost of vmapped dense Prims (n-1 fori steps over [k, n] lanes — tiny
    per-step work that pipelines fine under the inner while_loop). With
    quantized pi and a grid-multiple ``ascent_step`` (_bound_setup) every
    value is fixed-point-exact in f32, so the bound certifies pruning with
    no slack.
    """
    k = unvis.shape[0]
    big = jnp.asarray(jnp.inf, dbar.dtype)
    mst_conn = _MST_CONN[mst_kernel]

    val, deg = mst_conn(dbar, unvis, cur, n)
    val = jnp.where(jnp.isfinite(val), val, big)
    sum_pi_u = jnp.sum(jnp.where(unvis, pi[None, :], 0.0), axis=1)
    best = p_cost + val - pi[cur] - pi[0] - 2.0 * sum_pi_u

    if node_ascent > 0:
        cities = jnp.arange(n, dtype=cur.dtype)
        icur = cities[None, :] == cur[:, None]
        i0 = cities[None, :] == 0
        in_s = unvis | icur | i0
        # degree targets: U -> 2, endpoints -> 1 (cur==0 lanes: 0 -> 2,
        # which icur+i0 double-counting yields automatically)
        target = 2 * unvis.astype(jnp.int32) + icur.astype(jnp.int32) + i0.astype(jnp.int32)
        lam = jnp.zeros((k, n), dbar.dtype) + (p_cost[:, None] * 0)
        step = jnp.asarray(ascent_step, dbar.dtype)
        budget = jnp.asarray(lam_budget, dbar.dtype)
        # node_ascent is a static handful of sequential ascent steps
        # (default 2) — unrolling is intended  # graftlint: disable=R4
        for _ in range(node_ascent):
            g = jnp.where(in_s, deg - target, 0).astype(dbar.dtype)
            # the clamp bounds lambda drift to the magnitude headroom
            # budgeted in _bound_setup (any clamped lam is still a valid
            # potential, so the bound stays certified)
            lam = jnp.clip(lam + step * g, -budget, budget)
            val, deg = mst_conn(dbar, unvis, cur, n, lam)
            val = jnp.where(jnp.isfinite(val), val, big)
            lam_cur = jnp.take_along_axis(lam, cur[:, None].astype(jnp.int32), axis=1)[:, 0]
            corr = (
                pi[cur] + lam_cur + pi[0] + lam[:, 0]
                + 2.0 * (sum_pi_u + jnp.sum(jnp.where(unvis, lam, 0.0), axis=1))
            )
            best = jnp.maximum(best, p_cost + val - corr)
    return best


@partial(
    jax.jit,
    static_argnames=(
        "k", "n", "integral", "use_mst", "node_ascent", "mst_kernel",
        "push_order", "push_block", "step_kernel",
    ),
    # the popped frontier is dead after every call (callers rebind the
    # returned one) — donating it lets XLA alias the multi-hundred-MB
    # node buffer in place instead of copying it per top-level dispatch
    # (under _expand_loop's trace the inner donation is simply inlined;
    # the fused step kernel's input_output_aliases rides the same
    # donation — the Pallas store IS the in-place push)
    donate_argnames=("fr",),
)
def _expand_step(
    fr: Frontier,
    inc_cost: jnp.ndarray,
    inc_tour: jnp.ndarray,
    d: jnp.ndarray,
    min_out: jnp.ndarray,
    bound_adj: jnp.ndarray,
    dbar: jnp.ndarray,
    pi: jnp.ndarray,
    mst_slack: jnp.ndarray,
    ascent_step: jnp.ndarray,
    lam_budget: jnp.ndarray,
    k: int,
    n: int,
    integral: bool = False,
    use_mst: bool = True,
    node_ascent: int = 0,
    mst_kernel: str = "prim",
    push_order: str = "best-first",
    push_block: int = 0,
    step_kernel: str = "reference",
):
    """Pop <=K nodes, expand, prune, push. Returns (frontier', inc', stats).

    ``step_kernel``: "reference" (XLA cand-block materialize + compacting
    gather + contiguous block write — the default everywhere) or "fused"
    (ops.expand_pallas: child rows built and stored in-place by one
    Pallas kernel, the candidate block never materialized — the
    bandwidth-bound form; opt-in, interpret-mode off TPU). Both paths
    share every screen/flag/ordering computation, so results are
    bit-identical; only dead rows past ``count`` can differ.

    ``integral``: the metric is integer-valued and the bound arrays are
    fixed-point-exact (_bound_setup), so a node with ``bound > inc - 1``
    cannot yield a strictly better integer tour — prune at ``<= inc - 1``
    instead of ``< inc``. This collapses the optimal-cost plateau (nodes
    whose bound equals the incumbent) that plain strict pruning floods the
    frontier with.

    ``use_mst``: re-bound every popped node with the much stronger
    reduced-cost MST bound (_batched_mst_bound) before expanding it; nodes
    that fail are discarded without spawning children.
    """
    # the buffer carries k*n rows of write padding beyond the logical
    # capacity (make_root_frontier pad_rows): the push lands as ONE
    # contiguous dynamic_update_slice of the whole candidate block at the
    # stack top, which always fits while count <= logical capacity. A
    # caller that didn't pad just loses k*n slots of usable capacity.
    f_phys = fr.nodes.shape[0]
    if f_phys <= k * n:
        # the k*n-row block write cannot fit at all — an opaque XLA shape
        # error otherwise; surface the actionable config problem instead
        raise ValueError(
            f"frontier buffer has {f_phys} rows but the push block needs "
            f"k*n = {k * n} (+>=1 logical slot); lower k or raise capacity"
        )
    if push_order not in ("best-first", "natural"):
        # a typo'd value would otherwise silently run best-first while
        # benchmark JSON records the bogus label — fail loudly like the
        # sibling bound/balance/mst_kernel options do
        raise ValueError(
            f"unknown push_order {push_order!r} (expected best-first|natural)"
        )
    if push_block < 0:
        # a negative cap would silently behave as uncapped (the cond
        # predicate never fires) while compiling a mis-shaped dead branch
        # and mislabeling the A/B artifact
        raise ValueError(f"push_block must be >= 0, got {push_block}")
    if step_kernel not in ("reference", "fused"):
        raise ValueError(
            f"unknown step_kernel {step_kernel!r} (expected reference|fused)"
        )
    if step_kernel == "fused" and push_block:
        # push_block is an A/B knob on the reference block write; the
        # fused kernel writes exactly the pushed rows, so a cap is
        # meaningless — mislabeling the artifact silently would be worse
        raise ValueError("push_block is a reference-kernel knob; "
                         "step_kernel='fused' writes pushed rows only")
    f_cap = f_phys - k * n  # logical capacity
    w = (n + 31) // 32
    pw = _path_words(n)
    lanes = jnp.arange(k, dtype=jnp.int32)
    # pop the top-of-stack K entries (stack grows upward): ONE row gather
    # of the packed buffer, then column views
    take = jnp.minimum(fr.count, k)
    idx = jnp.maximum(fr.count - 1 - lanes, 0)  # top-first
    live = lanes < take
    p = fr.nodes[idx]  # [k, P + W + 4]
    p_pathw = p[:, :pw]  # int8-packed prefix words
    p_mask = p[:, pw : pw + w].astype(jnp.uint32)
    p_depth = p[:, pw + w]
    p_cost = _f32(p[:, pw + w + 1])
    p_bound = _f32(p[:, pw + w + 2])
    p_sum = _f32(p[:, pw + w + 3])
    # pop-side re-prune: the incumbent may have improved since these nodes
    # were pushed — discard (already-popped) nodes that can no longer win
    if integral:
        live = live & (p_bound <= inc_cost - 1.0)
    else:
        live = live & (p_bound < inc_cost)
    cur = _path_byte_get(p_pathw, jnp.maximum(p_depth - 1, 0))

    _, word_idx, bit, set_bit = _mask_consts(n)
    cities = jnp.arange(n, dtype=jnp.int32)
    # p_mask is [k, W]; gather each city's word, then test its bit
    unvis = (p_mask[:, word_idx] >> bit[None, :]) & 1 == 0

    if use_mst:
        # the full rounding slack comes off the strong bound itself (it must
        # cover the prefix-cost accumulation too, not just the MST edges);
        # zero on the fixed-point-exact integral path
        strong = (
            _batched_mst_bound(
                dbar, pi, unvis, cur, p_cost, n, node_ascent, ascent_step,
                lam_budget, mst_kernel
            )
            - mst_slack
        )
        if integral:
            live = live & (strong <= inc_cost - 1.0)
        else:
            live = live & (strong < inc_cost)

    feasible = unvis & live[:, None]
    ccost = p_cost[:, None] + d[cur]  # d[cur] is the [k, n] outgoing-edge block
    # child bound: ccost + sum over must-leave cities (child + remaining),
    # plus the per-child potential correction (zeros in plain min-out mode,
    # pi[child] - pi[0] under the 1-tree bound — see _bound_setup)
    cbound = ccost + p_sum[:, None] + bound_adj[None, :]
    if use_mst:
        # a parent's MST bound lower-bounds every child too (the child's
        # completions are a subset of the parent's) — inherit the tighter one
        cbound = jnp.maximum(cbound, strong[:, None])
    cdepth = p_depth[:, None] + 1

    # completions: child is the last unvisited city -> close to 0
    is_complete = (cdepth == n) & feasible
    total = ccost + d[cities, 0][None, :]
    comp_total = jnp.where(is_complete, total, INF)
    best_flat = jnp.argmin(comp_total.reshape(-1))
    best_total = comp_total.reshape(-1)[best_flat]
    bi = (best_flat // n).astype(jnp.int32)
    bc = (best_flat % n).astype(jnp.int32)
    new_inc_cost = jnp.minimum(inc_cost, best_total)
    best_path = (
        _unpack_path(p_pathw[bi], n)
        .at[jnp.minimum(p_depth[bi], n - 1)]
        .set(bc)
    )
    # closed tour layout [n+1]: prefix + final city + return-to-0
    cand_tour = jnp.zeros(n + 1, jnp.int32).at[:n].set(best_path)
    new_inc_tour = jnp.where(best_total < inc_cost, cand_tour, inc_tour)

    # pushable children: feasible, not complete, bound under incumbent
    # (integral metric: a child with ceil(bound) >= inc can't strictly
    # improve — with exact fixed-point bounds that is bound > inc - 1)
    if integral:
        push = feasible & ~is_complete & (cbound <= new_inc_cost - 1.0)
    else:
        push = feasible & ~is_complete & (cbound < new_inc_cost)
    child_sum = p_sum[:, None] - min_out[None, :]

    # order pushes bound-DESC so the stack top is best-first. A single flat
    # argsort over all k*n keys is the dominant cost of the whole step on
    # TPU (1-D sorts are slow there); a two-level sort — children within
    # each parent along the minor axis, parents by their best child bound —
    # yields the same best-on-top stack discipline with two much smaller
    # sorts. Ordering only steers search priority; compaction correctness
    # is independent of it (dest slots come from the push-flag prefix sum).
    #
    # push_order="natural" skips the ordering entirely (pushes land in
    # candidate order): cheaper steps, but pop order steers the search,
    # so the tree can GROW when the incumbent still improves mid-run
    # (measured on eil51 CPU: 258k nodes natural vs ~222k best-first —
    # the ILS start there is NOT optimal). Only when the incumbent is
    # already optimal is the proof tree pop-order-invariant. Whether the
    # per-step saving beats the extra nodes is an on-chip A/B question
    # (BENCH_BNB_TPU_R5_NOSORT.json); gap-closing runs (LB climb) should
    # keep "best-first" — the pop order steers the certified ascent.
    kn = k * n
    flat_push = push.reshape(-1)
    if push_order == "natural":
        rank = jnp.cumsum(flat_push.astype(jnp.int32)) - 1
    else:
        keys = jnp.where(push, cbound, -INF)
        child_ord = jnp.argsort(-keys, axis=1)  # [k, n] DESC, non-push last
        best_child = jnp.min(jnp.where(push, cbound, INF), axis=1)
        # parents DESC by best child (worst parent first, childless last), so
        # the final pushes — the stack top — are the best parent's best child
        parent_key = jnp.where(jnp.isfinite(best_child), best_child, -INF)
        parent_ord = jnp.argsort(-parent_key)

        # destination slots computed in UNORDERED candidate space via the
        # analytic inverse of the two-level permutation — no 52k-row reorder
        # gathers (on-chip A/B: they cost ~2.3 ms/step, SCATTER_PROFILE_TPU):
        # prio[(p, c)] = the position candidate (p, c) holds in the ordered
        # push sequence; its slot is base + (pushed candidates before it).
        inv_parent = jnp.zeros(k, jnp.int32).at[parent_ord].set(
            jnp.arange(k, dtype=jnp.int32)
        )
        inv_child = jnp.zeros((k, n), jnp.int32).at[
            jnp.arange(k, dtype=jnp.int32)[:, None], child_ord
        ].set(jnp.broadcast_to(jnp.arange(n, dtype=jnp.int32), (k, n)))
        prio = (inv_parent[:, None] * n + inv_child).reshape(-1)  # [kn]
        flags_in_order = (
            jnp.zeros(kn, jnp.int32).at[prio].set(flat_push.astype(jnp.int32))
        )
        csum = jnp.cumsum(flags_in_order)
        rank = csum[prio] - 1  # rank among pushed candidates, priority order
    n_push = flat_push.sum()
    base = fr.count - take

    if step_kernel == "fused":
        # fused Pallas push (ops.expand_pallas, ISSUE 8): destination
        # slots from the SAME rank/prefix-sum as the reference path;
        # the kernel builds each pushed child's packed row in VMEM and
        # stores it in place — the [kn, cols] candidate block below is
        # never materialized. Pruned candidates park at f_phys (skipped).
        from ..ops.expand_pallas import push_rows

        dest = jnp.where(
            flat_push, base + rank, jnp.asarray(f_phys, jnp.int32)
        ).reshape(k, n)
        new_nodes = push_rows(
            fr.nodes, p, dest, ccost, cbound, child_sum, n
        )
    else:
        # reference path: materialize the candidate block, compact, one
        # contiguous block write. Child rows are built in the packed
        # layout: path words [k, n, P] with the child id byte-set at the
        # prefix position (v1 built full [k, n, n] int32 lanes — 4x the
        # traffic of this form at n=100).
        dpos = jnp.minimum(p_depth, n - 1)
        wsel = (dpos // PATH_PACK)[:, None, None]
        shift = ((dpos % PATH_PACK) * 8)[:, None, None]
        pwb = jnp.broadcast_to(p_pathw[:, None, :], (k, n, pw))
        widx = jnp.arange(pw, dtype=jnp.int32)[None, None, :]
        neww = (pwb & ~(0xFF << shift)) | (cities[None, :, None] << shift)
        child_pathw = jnp.where(widx == wsel, neww, pwb)
        child_mask = p_mask[:, None, :] | set_bit[None, :, :]  # [k, n, W]

        # the payload columns mirror the Frontier layout
        cand = jnp.concatenate(
            [
                child_pathw.reshape(-1, pw),
                child_mask.reshape(-1, w).astype(jnp.int32),
                jnp.broadcast_to(cdepth, (k, n)).reshape(-1)[:, None],
                _i32(ccost.reshape(-1))[:, None],
                _i32(cbound.reshape(-1))[:, None],
                _i32(child_sum.reshape(-1))[:, None],
            ],
            axis=1,
        )
        # push = compacting gather + ONE contiguous block write (on-chip
        # live-carry A/B: 1.46 ms vs 2.32 ms for the row scatter and 6.9 ms
        # for the round-3 six-scatter form): gather the pushed candidates to
        # the block prefix in priority order, then dynamic_update_slice the
        # whole k*n block at the stack top. Rows past n_push are garbage —
        # they land beyond the new count and every consumer masks by count.
        comp_idx = jnp.zeros(kn, jnp.int32).at[
            jnp.where(flat_push, rank, kn)
        ].set(jnp.arange(kn, dtype=jnp.int32), mode="drop")

        def _block_write(nodes, rows: int):
            # while the count<=f_cap invariant holds, base+rows <= f_phys
            # and the clamp is a no-op; if a caller breaks it (e.g.
            # resuming a checkpoint with a larger k), the clamped write
            # overlaps live rows — flagged below so exactness loss is
            # never silent (same honesty as scatter-drop was)
            block = cand[comp_idx[:rows]]
            start = jnp.minimum(base, f_phys - rows)
            # literal 0 would trace int64 under x64 mode; match start dtype
            return jax.lax.dynamic_update_slice(
                nodes, block, (start, jnp.zeros((), start.dtype))
            )

        if push_block and push_block < kn:
            # capped block write (scatter_profile v4): typical steps push
            # ~k rows, so gathering/writing the full k*n block
            # materializes ~92% garbage; cap the common case at
            # push_block rows and lax.cond to the full block on the
            # (counted-rare) steps where n_push exceeds it — both
            # branches write every pushed row, so exactness is
            # unconditional
            new_nodes = jax.lax.cond(
                n_push <= push_block,
                lambda nodes: _block_write(nodes, push_block),
                lambda nodes: _block_write(nodes, kn),
                fr.nodes,
            )
        else:
            new_nodes = _block_write(fr.nodes, kn)

    new_count = base + n_push.astype(jnp.int32)
    overflow = fr.overflow | (new_count > f_cap) | (base > f_phys - kn)
    new_count = jnp.minimum(new_count, f_cap)

    stats = {"popped": take, "pushed": n_push, "completions": is_complete.sum()}
    return (
        Frontier(new_nodes, new_count, overflow),
        new_inc_cost,
        new_inc_tour,
        stats,
    )


def _expand_loop_impl(
    fr: Frontier,
    inc_cost: jnp.ndarray,
    inc_tour: jnp.ndarray,
    d: jnp.ndarray,
    min_out: jnp.ndarray,
    bound_adj: jnp.ndarray,
    dbar: jnp.ndarray,
    pi: jnp.ndarray,
    mst_slack: jnp.ndarray,
    ascent_step: jnp.ndarray,
    lam_budget: jnp.ndarray,
    k: int,
    n: int,
    inner_steps: int,
    integral: bool = False,
    use_mst: bool = True,
    node_ascent: int = 0,
    mst_kernel: str = "prim",
    push_order: str = "best-first",
    push_block: int = 0,
    step_kernel: str = "reference",
):
    """Run up to ``inner_steps`` expansion steps in ONE device program.

    The host only syncs once per call — essential on TPU, where a per-step
    host round-trip would dominate the (microseconds) expansion kernel.
    """

    def cond(carry):
        fr, _, _, _, i = carry
        return (i < inner_steps) & (fr.count > 0)

    def body(carry):
        fr, ic, itour, nodes, i = carry
        fr, ic, itour, stats = _expand_step(
            fr, ic, itour, d, min_out, bound_adj, dbar, pi, mst_slack,
            ascent_step, lam_budget, k, n, integral, use_mst, node_ascent,
            mst_kernel, push_order, push_block, step_kernel
        )
        return fr, ic, itour, nodes + stats["popped"], i + 1

    # derive the zero carries from fr.count so their varying-axis type
    # matches the body outputs under shard_map (see shard_map vma docs)
    zero = fr.count * 0
    fr, inc_cost, inc_tour, nodes, _ = jax.lax.while_loop(
        cond, body, (fr, inc_cost, inc_tour, zero, zero)
    )
    return fr, inc_cost, inc_tour, nodes


_EXPAND_LOOP_STATICS = (
    "k", "n", "inner_steps", "integral", "use_mst", "node_ascent",
    "mst_kernel", "push_order", "push_block", "step_kernel",
)

#: the production entry: the frontier argument is DONATED — the caller's
#: buffer is consumed and the output aliases it in place (solve() rebinds
#: on every dispatch, so the old handle is dead by construction; the
#: contracts layer checks the consumption after each dispatch)
_expand_loop = partial(
    jax.jit,
    static_argnames=_EXPAND_LOOP_STATICS,
    donate_argnames=("fr",),
)(_expand_loop_impl)

#: non-donating twin for harnesses that legitimately re-dispatch the SAME
#: frontier (tools/step_profile.py's chained-dispatch A/B reuses one warm
#: state per dispatch) — the engine itself must use ``_expand_loop``
_expand_loop_ref = partial(jax.jit, static_argnames=_EXPAND_LOOP_STATICS)(  # graftlint: disable=R7 — profiler twin re-dispatches one warm frontier
    _expand_loop_impl
)


def _reorder_frontier(fr: Frontier, rows=None) -> Frontier:
    """Globally re-sort the live stack so the LOWEST-bound node sits on
    top (popped next): one argsort + gather turns the depth-first stack
    into best-bound-first search until dives re-bury it.

    Why: the certified global lower bound is the min over open-node
    bounds, and a DFS stack leaves the lowest-bound nodes buried for
    most of the run — the LB only moves at the very end. Periodic
    re-sorts (``reorder_every``) pay one [capacity]-argsort plus a
    full-frontier gather to keep expanding the bound-critical nodes,
    which is what raises the certified LB on gap-reporting runs
    (kroA100, VERDICT r3 item 7). Ordering is search priority only;
    exactness is unaffected.

    ``rows``: static logical-prefix length — sort only the slots that
    can ever be counted and skip the k*n push-padding tail (callers that
    know k pass ``buffer_rows - k*n``; None sorts the whole buffer)."""
    rows = fr.nodes.shape[0] if rows is None else rows
    live_nodes = fr.nodes[:rows]
    pos = jnp.arange(rows, dtype=jnp.int32)
    live = pos < fr.count
    # DESC by bound: worst live node at index 0, best at count-1 (stack
    # top), dead entries (-inf keys) pushed past the live prefix (the
    # bound column is always the second-to-last packed column)
    key = _f32(live_nodes[:, -2])
    perm = jnp.argsort(-jnp.where(live, key, -INF))
    return Frontier(
        fr.nodes.at[:rows].set(live_nodes[perm]), fr.count, fr.overflow
    )


#: host-loop callers re-sort between dispatches (device_loop mode sorts
#: inside the kernel instead); the frontier is donated — the permuted
#: buffer aliases the old allocation instead of doubling it per re-sort
_reorder_frontier_jit = jax.jit(
    _reorder_frontier, static_argnames=("rows",), donate_argnames=("fr",)
)


def _compact_frontier(fr: Frontier, inc_cost, integral: bool, rows=None) -> Frontier:
    """Drop pruned nodes from the device stack IN PLACE (stable order).

    The on-device replacement for most host-reservoir spills: as the
    incumbent improves, the stack bottom fills with nodes whose bound can
    no longer win; a prefix-sum scatter squeezes them out without any
    host round trip. Exactness is preserved — only certified-prunable
    nodes are discarded. ``rows``: as in :func:`_reorder_frontier`.
    """
    rows = fr.nodes.shape[0] if rows is None else rows
    live_nodes = fr.nodes[:rows]
    pos = jnp.arange(rows, dtype=jnp.int32)
    live = pos < fr.count
    bound = _f32(live_nodes[:, -2])
    if integral:
        alive = live & (bound <= inc_cost - 1.0)
    else:
        alive = live & (bound < inc_cost)
    dest = jnp.where(alive, jnp.cumsum(alive.astype(jnp.int32)) - 1, rows)
    return Frontier(
        fr.nodes.at[:rows].set(
            live_nodes.at[dest].set(live_nodes, mode="drop")
        ),
        alive.sum().astype(jnp.int32),
        fr.overflow,
    )


@partial(
    jax.jit,
    static_argnames=(
        "k", "n", "integral", "use_mst", "node_ascent", "reorder_every",
        "mst_kernel", "push_order", "push_block", "step_kernel",
    ),
    # one whole-search dispatch per call; the input frontier is dead the
    # moment the kernel starts — donate it so the reservoir-scale buffer
    # is aliased, not copied, on every (re)dispatch
    donate_argnames=("fr",),
)
def _solve_device(
    fr: Frontier,
    inc_cost: jnp.ndarray,
    inc_tour: jnp.ndarray,
    d: jnp.ndarray,
    min_out: jnp.ndarray,
    bound_adj: jnp.ndarray,
    dbar: jnp.ndarray,
    pi: jnp.ndarray,
    mst_slack: jnp.ndarray,
    ascent_step: jnp.ndarray,
    lam_budget: jnp.ndarray,
    max_steps: jnp.ndarray,
    step0: jnp.ndarray,
    k: int,
    n: int,
    integral: bool = False,
    use_mst: bool = True,
    node_ascent: int = 0,
    reorder_every: int = 0,
    mst_kernel: str = "prim",
    push_order: str = "best-first",
    push_block: int = 0,
    step_kernel: str = "reference",
):
    """Run the ENTIRE search (up to ``max_steps`` expansion steps) in one
    device dispatch, with on-device stack compaction under capacity
    pressure. Returns ``(frontier', inc_cost', inc_tour', nodes, steps,
    best_step)`` — see ``_guarded_expand_steps``.

    This is the transfer-free fast path: on this image's remote-TPU relay
    the first device->host transfer permanently degrades every later
    dispatch (~65 ms per while-loop iteration — measured 660x slowdown on
    this kernel), so the host must not read anything back until the search
    is over. Setup must therefore also be host-only (``ascent="host"``
    bounds, ``strong_incumbent_host``). ``max_steps`` is traced, so budget
    changes don't recompile.

    If compaction cannot free enough space (every resident node still
    certified-alive), the loop stops with the stack intact BEFORE any
    lossy push — the caller's host-reservoir spill then takes over, so
    capacity pressure never converts into the overflow flag here.
    """
    return _guarded_expand_steps(
        fr, inc_cost, inc_tour, d, min_out, bound_adj, dbar, pi, mst_slack,
        ascent_step, lam_budget, max_steps, k, n, integral, use_mst,
        node_ascent, reorder_every, step0, mst_kernel, push_order,
        push_block, step_kernel
    )


def _guarded_expand_steps(
    fr, inc_cost, inc_tour, d, min_out, bound_adj, dbar, pi, mst_slack,
    ascent_step, lam_budget, max_steps, k, n, integral, use_mst, node_ascent,
    reorder_every: int = 0, step0=0, mst_kernel: str = "prim",
    push_order: str = "best-first", push_block: int = 0,
    step_kernel: str = "reference",
):
    """Up to ``max_steps`` expansion steps with a PER-STEP capacity guard:
    compact under pressure, and if compaction cannot get below the
    pressure line, stop stack-intact (never an overflow-dropping push).
    Returns ``(frontier', inc_cost', inc_tour', popped, steps_done,
    best_step)`` where ``best_step`` is the 0-based in-dispatch step index
    of the LAST incumbent improvement (-1 if none) — the host converts it
    to a time via the dispatch's measured rate, so ``time_to_best`` stays
    step-accurate even when the whole search is one dispatch.

    Shared by ``_solve_device`` (single device; ``max_steps`` = whole
    budget) and the sharded device-resident loop (``max_steps`` =
    ``inner_steps`` between ring-balance / incumbent collectives). The
    guard must be per step — a single step grows the stack by up to
    ``k*(n-1)``, which is exactly the headroom the caller's
    ``capacity >= 4*k*(n-1)`` precondition reserves.
    """
    # logical capacity: the buffer's trailing k*n rows are the push
    # block's write padding (see _expand_step), never counted slots
    f_cap = max(fr.nodes.shape[0] - k * n, 1)
    headroom = min(f_cap // 4, k * (n - 1))

    def cond(carry):
        fr, _, _, _, i, full, _ = carry
        return (i < max_steps) & (fr.count > 0) & ~fr.overflow & ~full

    def body(carry):
        fr, ic, itour, nodes, i, full, best_step = carry
        ic_before = ic
        if reorder_every:
            # periodic best-bound-first re-sort (gap-closing runs); the
            # Python-level guard keeps the argsort+gather out of the
            # compiled program entirely when the knob is off. step0
            # carries the run-global step count across dispatches — a
            # per-dispatch counter would reset each dispatch and never
            # fire when budgets (checkpoint/clock-capped) are smaller
            # than the period
            fr = jax.lax.cond(
                ((step0 + i) % reorder_every) == (reorder_every - 1),
                lambda f: _reorder_frontier(f, rows=f_cap),
                lambda f: f,
                fr,
            )
        fr = jax.lax.cond(
            fr.count > f_cap - headroom,
            lambda f, c: _compact_frontier(f, c, integral, rows=f_cap),
            lambda f, c: f,
            fr,
            ic,
        )
        # if compaction could not get below the pressure line, stop the
        # loop WITHOUT expanding (an expansion here could overflow-drop
        # children); the host spills to its reservoir and redispatches
        still_full = fr.count > f_cap - headroom

        def do_expand(args):
            fr, ic, itour = args
            fr, ic, itour, stats = _expand_step(
                fr, ic, itour, d, min_out, bound_adj, dbar, pi, mst_slack,
                ascent_step, lam_budget, k, n, integral, use_mst,
                node_ascent, mst_kernel, push_order, push_block, step_kernel
            )
            return fr, ic, itour, stats["popped"]

        def skip(args):
            fr, ic, itour = args
            return fr, ic, itour, fr.count * 0

        fr, ic, itour, popped = jax.lax.cond(
            still_full, skip, do_expand, (fr, ic, itour)
        )
        best_step = jnp.where(ic < ic_before, i, best_step)
        return fr, ic, itour, nodes + popped, i + 1, still_full, best_step

    zero = fr.count * 0
    fr, inc_cost, inc_tour, nodes, steps, _, best_step = jax.lax.while_loop(
        cond, body,
        (fr, inc_cost, inc_tour, zero, zero, fr.overflow & False, zero - 1),
    )
    return fr, inc_cost, inc_tour, nodes, steps, best_step


def _np_bound_col(rows: np.ndarray) -> np.ndarray:
    """The float32 bound column of packed host rows: always the
    second-to-last packed column (see Frontier layout)."""
    return np.ascontiguousarray(rows[..., -2]).view(np.float32)


def _fetch_live_rows(nodes: jnp.ndarray, cnt: int) -> np.ndarray:
    """The ONE accepted per-spill device->host fetch: only the LIVE PREFIX
    of a frontier node buffer. Rows past ``count`` are dead, and the
    physical buffer also carries k*n push-padding rows — hundreds of MB at
    kroA100 scale, which the pre-PR-2 paths round-tripped whole on every
    spill (ADVICE r5 items 2-3). Every reservoir path (single-device
    ``exchange``, the sharded ``spill_refill``) funnels through here so
    the transfer invariant lives at one site; the function is listed in
    graftlint's DEFAULT_HOT_PATHS and carries the repo's one explicit R1
    waiver, marking the accepted transfer exactly where it happens. The
    ``.copy()`` decouples from any zero-copy CPU-backend view so
    reservoir rows never pin the device buffer alive.

    The readback is also the ``spill.fetch`` fault seam: a transient
    transfer failure (or an injected one) is absorbed by a bounded retry
    instead of killing a multi-hour campaign mid-spill."""

    def pull() -> np.ndarray:
        _fault_registry().fire("spill.fetch")
        return np.asarray(nodes[:cnt]).copy()  # graftlint: disable=R1 — the one minimal per-spill fetch

    return _RetryPolicy(
        max_attempts=3, base_delay_s=0.005, seed=0, retry_on=_TRANSFER_ERRORS
    ).call(pull)


class _Reservoir:
    """Host-side overflow store for frontier nodes (packed numpy chunks,
    rows in the Frontier layout).

    When the device stack nears capacity, the worst-bound bottom half is
    spilled here instead of tripping the kernel's lossy overflow flag; when
    the device frontier empties, nodes flow back (filtered against the
    current incumbent). Exactness is preserved: a node is only ever
    discarded by a certified bound check.
    """

    def __init__(self, stats: Optional[SpillStats] = None):
        self.chunks: list = []  # each: [m, n + W + 4] int32 packed rows
        #: transfer accounting; solvers share ONE SpillStats across all
        #: rank reservoirs so BnBResult reports whole-run totals
        self.stats = stats if stats is not None else SpillStats()

    def __len__(self) -> int:
        return sum(int(c.shape[0]) for c in self.chunks)

    def min_bound(self) -> float:
        """Min bound over every spilled node (inf when empty)."""
        mins = [
            float(_np_bound_col(c).min()) for c in self.chunks if c.shape[0]
        ]
        return min(mins) if mins else float("inf")

    def prune(self, inc_cost: float, integral: bool) -> None:
        """Drop incumbent-closed rows chunk-by-chunk (O(R) scan, no
        concatenate). Called when the incumbent improves: the exchange
        fast path partitions live rows only, so without this the
        reservoir would retain dead rows until the next full merge."""
        out = []
        for c in self.chunks:
            b = _np_bound_col(c)
            alive = b <= inc_cost - 1.0 if integral else b < inc_cost
            if alive.all():
                out.append(c)
            elif alive.any():
                out.append(c[alive])
        self.chunks = out

    def refill_rows(self, inc_cost: float, integral: bool, capacity: int):
        """Host core of ``refill``: the best-bound ``capacity // 2``
        reservoir rows (stack order) for an EMPTY device stack, with
        incumbent-closed nodes dropped; None when nothing survives."""
        return self._partition(None, inc_cost, integral, capacity)

    def refill(
        self, fr: Frontier, inc_cost: float, integral: bool, capacity: int
    ) -> Frontier:
        """Reload up to half the LOGICAL capacity from the reservoir onto
        an empty device stack, dropping nodes the incumbent has since
        closed. ``capacity`` is the logical slot count, REQUIRED — the
        buffer's own row count includes push-padding rows and would
        over-fill (eroding the spill-headroom invariant). The stack is
        empty (count 0), so nothing is fetched: the refilled rows are
        written in place over the dead buffer with a sliced device write."""
        keep = self.refill_rows(inc_cost, integral, capacity)
        if keep is None:
            return fr
        take = keep.shape[0]
        _contracts.check_exchange_count(take, capacity, where="_Reservoir.refill")
        self.stats.rounds += 1
        self.stats.events += 1
        self.stats.bytes_to_device += keep.nbytes
        # donating write: the refilled rows land in the EXISTING device
        # allocation (the out-of-jit .at[].set form copied the whole
        # physical buffer per refill just to change the prefix)
        nodes = _donation.set_rows_donated(fr.nodes, jnp.asarray(keep))
        return Frontier(nodes, jnp.asarray(take, jnp.int32), fr.overflow)

    def _partition(self, extra, inc_cost, integral, capacity: int):
        """Shared core of exchange/refill: merge ``extra`` rows (may be
        None) with every spilled chunk, drop incumbent-closed nodes, keep
        the best-bound ``min(alive, capacity // 2)`` rows (returned in
        stack order, worst at the bottom) and re-spill the remainder.
        Selection uses argpartition (O(R)), sorting only the kept rows."""
        chunks = self.chunks if extra is None else self.chunks + [extra]
        self.chunks = []
        chunks = [c for c in chunks if c.shape[0]]
        if not chunks:
            # nothing to drop: only empty chunks existed, so this early
            # return loses no state  # graftlint: disable=R5
            return None
        merged = chunks[0] if len(chunks) == 1 else np.concatenate(chunks)
        bounds = _np_bound_col(merged)
        alive = bounds <= inc_cost - 1.0 if integral else bounds < inc_cost
        merged = merged[alive]
        bounds = bounds[alive]
        m = merged.shape[0]
        take = min(m, capacity // 2)
        if take == 0:
            if m:
                # capacity < 2 leaves zero on-device slots: the alive rows
                # must stay SPILLED, not vanish — self.chunks was cleared
                # above, so dropping ``merged`` here would discard open
                # nodes and let a degenerate run claim proven_optimal with
                # subtrees unexplored (ADVICE r5 item 1)
                self.chunks.append(merged)
            return None
        if take < m:
            sel = np.argpartition(bounds, take - 1)[:take]
            rest = np.ones(m, bool)
            rest[sel] = False
            self.chunks.append(merged[rest])
            merged = merged[sel]
            bounds = bounds[sel]
        # stack order: worst bound at the bottom, best on top
        order = np.argsort(-bounds, kind="stable")
        return merged[order]

    def exchange(
        self, fr: Frontier, inc_cost: float, integral: bool, capacity: int
    ) -> Frontier:
        """Re-partition open nodes so the certified LB can never stay
        pinned in the reservoir: if the reservoir holds the global ALIVE
        minimum, merge everything (device stack + every spilled chunk)
        and put the best-bound ``capacity // 2`` back on-device (best on
        top); otherwise keep the cheap live-rows-only best-half spill.
        Incumbent-closed nodes are dropped from whatever is partitioned.

        This fixes the DFS-with-spill inversion the round-5 kroA100
        campaign measured: nodes spilled early (shallow, low bound) end up
        BETTER than the device's current deep working set — at the
        flattening point the reservoir's min bound was 21128.4 vs the
        frontier's 21212.5, with 2.65M reservoir nodes better than the
        frontier's best, so the certified LB sat pinned in the reservoir
        for four straight chunks while the device expanded worse subtrees
        (plain ``refill`` only fires on a DRAINED frontier, which never
        came). The full merge is paid only while the reservoir owns the
        global minimum — an earlier any-overlap guard merged the
        (multi-GB) reservoir on every spill and slowed chunks 2-3x.
        In the fast-path regime reservoir nodes better than SOME live
        nodes legitimately stay spilled; the LB lag is at most one
        exchange period.
        """
        _contracts.check_frontier(fr, where="_Reservoir.exchange")
        cnt = int(fr.count)
        # transfer ONLY the live prefix (the physical buffer carries
        # capacity + k*n push-padding rows — hundreds of MB at kroA100
        # scale; see _fetch_live_rows)
        live = _fetch_live_rows(fr.nodes, cnt)
        lb = _np_bound_col(live)
        alive_lb = lb[lb <= inc_cost - 1.0] if integral else lb[lb < inc_cost]
        live_min = float(alive_lb.min()) if alive_lb.size else float("inf")
        # compare ALIVE minima: a dead live row below the reservoir's min
        # must not mask a reservoir node that holds the true certified LB
        merge = not (cnt and self.min_bound() >= live_min)
        self.stats.rounds += 1
        self.stats.events += 1
        self.stats.full_merges += int(merge)
        self.stats.bytes_to_host += live.nbytes
        keep = self.exchange_rows(live, inc_cost, integral, capacity, merge=merge)
        if keep is None:
            return Frontier(fr.nodes, jnp.asarray(0, jnp.int32), fr.overflow)
        # upload only the kept slice, written in place — rows past ``take``
        # are dead (``count`` is authoritative), so nothing else moves
        take = keep.shape[0]
        _contracts.check_exchange_count(take, capacity, where="_Reservoir.exchange")
        self.stats.bytes_to_device += keep.nbytes
        # donating write-in-place (see refill): only the kept slice moves
        nodes = _donation.set_rows_donated(fr.nodes, jnp.asarray(keep))
        return Frontier(nodes, jnp.asarray(take, jnp.int32), fr.overflow)

    def exchange_rows(
        self, live: np.ndarray, inc_cost, integral, capacity: int,
        merge: bool = True,
    ):
        """Host core of the exchange, shared by the single-device path and
        the sharded ``spill_refill``: partition the ``live`` packed rows —
        plus the whole reservoir when ``merge`` — against the incumbent
        and return the rows to place on-device (stack order, worst at the
        bottom), or None when nothing survives. ``merge=False`` is the
        fast path for the common no-inversion regime (the frontier already
        holds the alive minimum): best-half-select over the live rows
        only, the cut joining the reservoir with the (possibly multi-GB)
        spilled chunks never touched, let alone concatenated."""
        if merge:
            return self._partition(live, inc_cost, integral, capacity)
        return self._keep_live_only(live, inc_cost, integral, capacity)

    def _keep_live_only(self, live, inc_cost, integral, capacity: int):
        """exchange()'s fast path (global alive minimum is on-device):
        best-half select over the live rows only; the cut rows join the
        reservoir."""
        saved, self.chunks = self.chunks, []
        keep = self._partition(live, inc_cost, integral, capacity)
        saved.extend(self.chunks)  # the cut remainder
        self.chunks = saved
        return keep



def make_root_frontier(
    n: int, capacity: int, min_out: np.ndarray, dtype=jnp.float32,
    pad_rows: int = 0,
) -> Frontier:
    """Root frontier with ``capacity`` logical slots plus ``pad_rows``
    extra buffer rows (callers pass ``k*n`` so _expand_step's contiguous
    block write always fits — see the push comment there)."""
    if dtype != jnp.float32:
        raise ValueError("the packed frontier stores float32 fields only")
    w = (n + 31) // 32
    pw = _path_words(n)
    # packed rows: all-zero == {path 0, mask 0, depth 0, cost/bound/sum 0.0}.
    # Built ON DEVICE (zeros + one tiny row write): materializing the
    # buffer host-side would push capacity*(P+W+4)*4 bytes (tens of MB)
    # through the relay tunnel — measured ~2.7 s of the eil51 solve
    row0 = np.zeros(pw + w + 4, np.int32)
    row0[pw] = 1  # mask word 0: city 0 visited
    row0[pw + w] = 1  # depth
    row0[pw + w + 3] = np.float32(min_out[1:].sum()).view(np.int32)
    nodes = jnp.zeros((capacity + pad_rows, pw + w + 4), jnp.int32).at[0].set(row0)
    return Frontier(nodes, jnp.asarray(1, jnp.int32), jnp.asarray(False))


def _resolve_device_loop(
    device_loop: bool, auto: bool, capacity: int, k: int, n: int,
    what: str = "capacity", source: str = "",
) -> bool:
    """Enforce the device-loop compaction floor ``capacity >= 4*k*(n-1)``
    (one push batch of headroom per step). Auto mode falls back to the
    host loop; an explicit request raises. Re-run after restore() — the
    checkpoint's array width overrides the caller's capacity argument."""
    if device_loop and capacity < 4 * k * (n - 1):
        if auto:
            return False
        raise ValueError(
            f"device_loop needs {what} >= 4*k*(n-1) = {4 * k * (n - 1)} "
            f"(got {capacity}{source}); lower k or raise capacity"
        )
    return device_loop


def _acquire_cpu_polish_device(device_loop: bool):
    """CPU device for host-pinned setup compute, or None. Must run BEFORE
    the first jax array op (it may still widen the platform pin)."""
    if not device_loop:
        return None
    from ..utils.backend import cpu_fallback_device

    return cpu_fallback_device()


def _initial_incumbent(
    d, ils_rounds, device_loop: bool, cpu_dev
) -> np.ndarray:
    """The ILS incumbent for a fresh solve: a few seconds of setup that
    routinely lands the published TSPLIB optimum, which the ceil-aware
    pruner converts into massive savings. On the transfer-free paths the
    polish kernels are pinned to the CPU backend (its readbacks don't
    trip the relay's slow mode); if no CPU backend can exist, fall back
    to the (Or-opt-less) numpy twin rather than poisoning."""
    if device_loop and cpu_dev is None:
        return strong_incumbent_host(d, starts=16, perturbations=ils_rounds)
    return strong_incumbent(
        d, starts=16, perturbations=ils_rounds,
        device=cpu_dev if device_loop else None,
    )


def _dispatch_budget(
    remaining_units: int,
    int32_cap_units: int,
    until_ckpt_units,
    rate_units: float,
    remaining_s,
    first_units: int,
) -> int:
    """Per-dispatch budget for device_loop mode, in dispatch units
    (expansion steps for the single-device loop, inner_steps-rounds for
    the sharded loop). Caps, in order:

    - the remaining iteration budget;
    - the unit count at which the device-side int32 node counter could
      overflow within one dispatch;
    - units until the next periodic checkpoint (steps-since-last-save,
      NOT a modulo — early-stopping dispatches would drift off any
      modulo grid and silently disable saving; ``None`` = no cap);
    - CPU backends only (``remaining_s`` is None otherwise): an estimate
      of how many units fit in the remaining clock, from the previous
      dispatch's measured rate (``first_units`` before any rate exists)
      so the host can re-check the limit near it. On the remote-TPU
      relay this splitting would be a bug, not a feature: the readback
      after the first dispatch flips the relay into its
      permanently-slow mode (~660x) and the fast-mode rate would size
      the next dispatch into a multi-hour overshoot — there, the search
      stays ONE dispatch and clock-bounded runs use the chunked driver
      (tools/bnb_chunked.py) with its hard per-chunk kill.
    """
    b = min(remaining_units, int32_cap_units)
    if until_ckpt_units is not None:
        b = min(b, until_ckpt_units)
    if remaining_s is not None:
        b = min(
            b,
            int(rate_units * max(remaining_s, 0.0)) + 1
            if rate_units > 0
            else first_units,
        )
    return max(b, 1)


def warm_compile_device_solver(
    n: int,
    capacity: int,
    k: int,
    integral: bool = True,
    mst_prune: bool = True,
    node_ascent: int = 2,
    reorder_every: int = 0,
    mst_kernel: str = "prim",
    push_order: str = "best-first",
    push_block: int = 0,
    step_kernel: str = "reference",
) -> None:
    """AOT-compile ``_solve_device`` for the given static shapes WITHOUT
    executing anything on the device.

    Benchmarks need compile time out of the timed run, but a warmup RUN
    would read results back and permanently poison the relay's fast
    dispatch mode (module docstring). ``jit.lower(...).compile()`` only
    compiles; with the persistent compilation cache enabled the real
    dispatch then hits the cache instead of recompiling.
    """
    w = (n + 31) // 32
    pw = _path_words(n)
    sd = jax.ShapeDtypeStruct
    f32, i32 = jnp.float32, jnp.int32
    # + k*n push-padding rows, matching solve()'s make_root_frontier call
    fr = Frontier(
        sd((capacity + k * n, pw + w + 4), i32), sd((), i32), sd((), jnp.bool_)
    )
    _solve_device.lower(
        fr, sd((), f32), sd((n + 1,), i32), sd((n, n), f32), sd((n,), f32),
        sd((n,), f32), sd((n, n), f32), sd((n,), f32), sd((), f32),
        sd((), f32), sd((), f32), sd((), i32), sd((), i32), k, n, integral,
        mst_prune, node_ascent, reorder_every, mst_kernel, push_order,
        push_block, step_kernel
    ).compile()


def solve(
    d: np.ndarray,
    capacity: int = 1 << 17,
    k: int = 256,
    inner_steps: int = 32,
    max_iters: int = 200_000,
    time_limit_s: Optional[float] = None,
    target_cost: Optional[float] = None,
    checkpoint_path: Optional[str] = None,
    checkpoint_every: int = 0,
    resume_from: Optional[str] = None,
    bound: str = "one-tree",
    mst_prune: bool = True,
    ils_rounds: Optional[int] = None,
    node_ascent: int = 2,
    device_loop: Optional[bool] = None,
    ascent: str = "host",
    reorder_every: int = 0,
    mst_kernel: str = "prim",
    push_order: str = "best-first",
    push_block: int = 0,
    step_kernel: str = "reference",
) -> BnBResult:
    """Exact B&B on one device. ``d`` is a dense [n, n] distance matrix.

    ``step_kernel``: "reference" (default — the XLA candidate-block
    push) or "fused" (ops.expand_pallas: one Pallas kernel builds and
    stores pushed child rows in place; opt-in like --mst-kernel, with
    interpret-mode fallback off TPU). Results are bit-identical — the
    two kernels share every screen/ordering computation.

    ``push_order``: "best-first" (default — two-level sort keeps the
    stack top on the best child) or "natural" (no per-step sort: cheaper
    steps but a possibly larger tree when the incumbent improves
    mid-search; always certifies the same optimum).

    ``push_block``: cap the per-step push block write at this many rows,
    lax.cond-falling back to the full k*n block on steps that push more
    (exactness unconditional). 0 (default) = always the full block; the
    scatter_profile v4 experiment sizes the win before adoption.

    ``mst_kernel``: "prim" (sequential [k, n] chain — the default on
    every backend) or "boruvka" (log-depth batched variant built for the
    TPU's latency profile; opt in explicitly); both certify the identical
    MST value, so node counts can differ only through ascent-degree tie
    effects (see _mst_conn_boruvka).

    ``reorder_every``: every N expansion steps, globally re-sort the
    live stack best-bound-on-top (see _reorder_frontier) — best-bound-
    first search for gap-closing runs; 0 (default) keeps the pure DFS
    stack discipline.

    ``ascent``: where the root Held-Karp subgradient ascent runs —
    "host" (default; f64 numpy, zero device work — required by the
    transfer-free device_loop path and slightly stronger thanks to f64)
    or "device" (the f32 jit ascent of ops.one_tree — the round-2
    behavior; its readback degrades the remote-TPU relay, so only use it
    with device_loop=False).

    ``bound``: "one-tree" (default — Held-Karp potentials sharpen every
    node bound, usually orders of magnitude fewer nodes) or "min-out"
    (the plain cheapest-outgoing-edge bound).

    ``mst_prune``: re-bound every popped node with the reduced-cost MST
    bound before expansion (strong pruning; see _batched_mst_bound).

    ``device_loop``: run the whole search as ONE device dispatch
    (_solve_device) with on-device compaction, reading back only when it
    finishes — the transfer-free fast path for the remote-TPU relay,
    where the first device->host transfer permanently degrades dispatch
    latency (measured 660x on this kernel). Setup (bounds + incumbent)
    then runs host-side so nothing touches the device beforehand.
    Default: auto — on for accelerator backends, off for CPU (where the
    per-batch host loop costs nothing and gives finer-grained spill /
    time-limit checks). ``time_limit_s``/``target_cost`` are checked
    between dispatches in this mode; when ``time_limit_s`` is set, each
    dispatch's step budget is bounded by the previous dispatch's measured
    steps/sec (first dispatch: ``_FIRST_DISPATCH_STEPS``) so the host
    re-checks the clock near the limit. ``time_to_best`` is step-accurate:
    the kernel returns the in-dispatch step index of the last incumbent
    improvement, converted to time via the dispatch's measured rate.

    Stops when the frontier empties (proven optimal), or at
    ``max_iters``/``time_limit_s``/``target_cost`` (then best-so-far).
    """
    t_setup = time.perf_counter()
    n = d.shape[0]
    if not 3 <= n <= MAX_BNB_CITIES:
        # ceil(MAX_BNB_CITIES/32) mask words; 1-tree needs >= 3 vertices
        raise ValueError(
            f"B&B engine supports 3 <= n <= {MAX_BNB_CITIES} cities, got {n}"
        )
    auto_device_loop = device_loop is None
    if auto_device_loop:
        device_loop = jax.default_backend() not in ("cpu",)
    device_loop = _resolve_device_loop(
        device_loop, auto_device_loop, capacity, k, n
    )
    cpu_dev = _acquire_cpu_polish_device(device_loop)
    d32 = jnp.asarray(d, jnp.float32)
    t_asc = time.perf_counter()
    bd = _bound_setup(d, bound, node_ascent=node_ascent, ascent=ascent)
    ascent_s = time.perf_counter() - t_asc
    min_out, bound_adj, root_lb, integral = bd.min_out, bd.bound_adj, bd.root_lb, bd.integral
    min_out_np = np.asarray(min_out, np.float64)

    ils_s = 0.0
    spill_stats = SpillStats()
    reservoir = _Reservoir(stats=spill_stats)
    lb_floor = -np.inf  # best certified LB carried across resumed chunks
    if resume_from:
        fr, inc_cost, inc_tour, reservoir, lb_floor = restore(
            resume_from, expect_d=d, expect_bound=bound
        )
        reservoir.stats = spill_stats
        # the restored arrays define the true LOGICAL capacity (buffer
        # rows minus the k*n push padding _expand_step reserves) — the
        # caller's argument must not disarm the spill trigger below (and
        # the device_loop guard must re-check against THIS capacity)
        capacity = max(int(fr.nodes.shape[0]) - k * n, 1)
        if len(reservoir) or int(fr.count) > capacity - _spill_headroom(
            capacity, inner_steps, k, n
        ):
            # (a) a non-empty reservoir may hold the globally best open
            # nodes (the spill-inversion measured by the r5 campaign —
            # see _Reservoir.exchange, which merges exactly when the
            # reservoir owns the global alive minimum); (b) a checkpoint
            # written with a smaller k (or pre-padding layout) can
            # restore a count inside the spill band, which would let the
            # FIRST (unguarded, host-loop) batch overflow the logical
            # capacity and trip the sticky exactness-lost flag — the
            # exchange's take <= capacity//2 sheds that overhang too
            fr = reservoir.exchange(fr, float(inc_cost), integral, capacity)
        device_loop = _resolve_device_loop(
            device_loop, auto_device_loop, capacity, k, n,
            source=f" from checkpoint {resume_from!r}",
        )
    else:
        t_ils = time.perf_counter()
        inc_tour_np = _initial_incumbent(d, ils_rounds, device_loop, cpu_dev)
        ils_s = time.perf_counter() - t_ils
        inc_cost = jnp.asarray(
            tour_cost(np.asarray(d, np.float64), inc_tour_np), jnp.float32
        )
        inc_tour = jnp.asarray(inc_tour_np, jnp.int32)
        fr = make_root_frontier(n, capacity, min_out_np, pad_rows=k * n)

    _contracts.check_frontier_packed(fr, n, where="solve")
    headroom = _spill_headroom(capacity, inner_steps, k, n)

    # compile-once dispatch (perf.compile_cache): when the cache is
    # enabled, the hot entry comes from the AOT serialized-executable
    # store — a warm process skips BOTH the XLA compile and the Python
    # re-trace. Every failure path falls back to the plain jit dispatch
    # (which itself rides the persistent compilation cache), and the
    # loaded executable bakes in the identical jaxpr — donation included
    # — so results cannot differ. Cache disabled (the library default):
    # aot_load_or_compile returns None and only the jit path runs.
    bound_args = (d32, min_out, bound_adj, bd.dbar, bd.pi, bd.slack,
                  bd.ascent_step, bd.lam_budget)
    aot_state: dict = {}

    def _aot_dispatch(entry, jit_fn, args, statics):
        if entry not in aot_state:
            aot_state[entry] = _perf_cache.aot_load_or_compile(
                entry, jit_fn, args, statics
            )
        loaded = aot_state[entry]
        if loaded is not None:
            try:
                return loaded(*args)
            except TypeError:
                # aval drift vs the stored executable (arg validation
                # happens before execution, so nothing was consumed) —
                # the jit path is authoritative; degrade for this solve
                aot_state[entry] = None
                _perf_cache.STATS.record(entry, "error")
        return jit_fn(*args, **statics)

    _sd_statics = dict(
        k=k, n=n, integral=integral, use_mst=mst_prune,
        node_ascent=node_ascent, reorder_every=reorder_every,
        mst_kernel=mst_kernel, push_order=push_order, push_block=push_block,
        step_kernel=step_kernel,
    )
    _el_statics = dict(
        k=k, n=n, inner_steps=max(1, inner_steps), integral=integral,
        use_mst=mst_prune, node_ascent=node_ascent, mst_kernel=mst_kernel,
        push_order=push_order, push_block=push_block,
        step_kernel=step_kernel,
    )
    t0 = time.perf_counter()
    setup_s = t0 - t_setup
    t_best = 0.0
    last_inc = float(inc_cost)
    last_pruned = last_inc  # reservoir GC high-water mark
    nodes = 0
    it = 0
    inner = max(1, inner_steps)
    last_ckpt = 0
    last_reorder = 0
    steps_rate = 0.0  # measured in-kernel steps/sec of the last dispatch
    # per-dispatch telemetry (obs): one ring-buffer row per host-loop
    # iteration — host-side values the loop already has, zero extra
    # device traffic; None (one is-None check per iteration) when off
    sampler = _obs_series.StepSampler.maybe()
    if sampler is not None:
        # spill byte columns count packed rows — record the divisor
        sampler.row_bytes = int(fr.nodes.shape[-1]) * 4
        sampler.frontier_layout = FRONTIER_LAYOUT_VERSION
    # stall sentinel rides the same per-dispatch feed (ISSUE 9): nodes/s
    # collapse + certified-LB stagnation fire health events mid-solve.
    # Attached to the sampler so the hot loop makes ONE telemetry call
    # per dispatch (sample() forwards), not two keyword calls — measured
    # difference on the TSP_BENCH=obs <= 2% budget.
    sentinel = _obs_anomaly.StallSentinel.maybe()
    if sampler is not None:
        sampler.sentinel = sentinel
    # certified floor fed to telemetry/checkpoints: loop-invariant (both
    # terms are fixed before the loop), so hoist the max() out of it
    lbf = float(max(lb_floor, root_lb))
    # profiler step annotation, resolved ONCE (shared nullcontext unless
    # a device_trace capture is live around this solve)
    step_ann = _obs_tracing.step_annotation_factory()
    while it < max_iters:
        t_iter = time.perf_counter()
        sp_h0, sp_d0 = spill_stats.bytes_to_host, spill_stats.bytes_to_device
        iter_nodes = 0
        if device_loop:
            # all caps (int32 node-counter overflow, checkpoint cadence,
            # CPU-only clock re-check) live in _dispatch_budget
            budget = _dispatch_budget(
                max_iters - it,
                (2**31 - 1) // max(k, 1),
                (checkpoint_every - (it - last_ckpt))
                if (checkpoint_every and checkpoint_path)
                else None,
                steps_rate,
                (time_limit_s - (time.perf_counter() - t0))
                if (
                    time_limit_s is not None
                    and jax.default_backend() == "cpu"
                )
                else None,
                _FIRST_DISPATCH_STEPS,
            )
            t_disp = time.perf_counter()
            prev_nodes = fr.nodes if _contracts.level() != "off" else None
            # StepTraceAnnotation segments the profiler timeline by B&B
            # step while a device_trace capture is active (no-op otherwise)
            with step_ann(it):
                fr, inc_cost, inc_tour, popped, steps, best_step = _aot_dispatch(
                    "solve_device",
                    _solve_device,
                    (fr, inc_cost, inc_tour) + bound_args
                    + (jnp.asarray(budget, jnp.int32), jnp.asarray(it, jnp.int32)),
                    _sd_statics,
                )
            if prev_nodes is not None:
                # the donated frontier must be CONSUMED by the dispatch
                # (in-place aliasing, not a per-dispatch buffer copy)
                _contracts.check_donated(prev_nodes, where="solve._solve_device")
            # first readback of the run — everything before this line ran
            # in the relay's fast mode
            iter_nodes = int(popped)
            nodes += iter_nodes
            disp_s = time.perf_counter() - t_disp
            if disp_s > 0 and int(steps) > 0:
                steps_rate = int(steps) / disp_s
            if float(inc_cost) < last_inc and int(best_step) >= 0:
                # convert the in-dispatch improvement step into a time:
                # dispatch start offset + the step's fraction of the
                # dispatch's wall. Step-accurate even when the whole
                # search is one dispatch (the generic readback-time path
                # below could be minutes late on multi-minute dispatches).
                last_inc = float(inc_cost)
                t_best = (
                    (t_disp - t0)
                    + (int(best_step) + 1) / max(int(steps), 1) * disp_s
                )
            it += max(int(steps), 1)
            # one scalar flag readback per dispatch, not per step
            if bool(np.asarray(fr.overflow)):  # graftlint: disable=R1
                # exactness already lost in-kernel (unreachable unless the
                # capacity guard was bypassed); stop instead of spinning
                # no-op dispatches — proven_optimal will report False
                break
        else:
            prev_nodes = fr.nodes if _contracts.level() != "off" else None
            with step_ann(it):
                fr, inc_cost, inc_tour, popped = _aot_dispatch(
                    "expand_loop",
                    _expand_loop,
                    (fr, inc_cost, inc_tour) + bound_args,
                    _el_statics,
                )
            if prev_nodes is not None:
                _contracts.check_donated(prev_nodes, where="solve._expand_loop")
            iter_nodes = int(popped)
            nodes += iter_nodes
            it += inner
        cnt = int(fr.count)
        ic = float(inc_cost)
        if ic < last_inc:
            last_inc = ic
            t_best = time.perf_counter() - t0
        if len(reservoir) and last_inc < last_pruned:
            # GC the reservoir when the incumbent improves: the exchange
            # fast path partitions live rows only, so dead spilled rows
            # would otherwise persist (and weaken min_bound) until the
            # next full merge
            reservoir.prune(last_inc, integral)
            last_pruned = last_inc
        if cnt == 0 and len(reservoir):
            fr = reservoir.refill(fr, ic, integral, capacity=capacity)
            cnt = int(fr.count)
        elif cnt > capacity - headroom:
            # exchange, not plain spill: the same host fetch the spill
            # pays, plus a global best-half re-partition with the
            # reservoir, so spilled-early low-bound nodes can't pin the
            # certified LB while the device expands worse subtrees
            fr = reservoir.exchange(fr, ic, integral, capacity)
            cnt = int(fr.count)
        if (
            reorder_every
            and not device_loop
            and it - last_reorder >= reorder_every
        ):
            fr = _reorder_frontier_jit(fr, rows=capacity)
            last_reorder = it
        # checkpoint AFTER the spill/refill: a pre-spill snapshot could be
        # resumed into an immediate in-kernel overflow
        if (
            checkpoint_every
            and checkpoint_path
            and it - last_ckpt >= checkpoint_every
        ):
            save(checkpoint_path, fr, inc_cost, inc_tour, d=d, bound=bound,
                 reservoir=reservoir, lb_floor=lbf)
            last_ckpt = it
        if sampler is not None:
            # positional on purpose: the kwarg spelling costs ~1 us more
            # per dispatch in situ (column order = timeseries.COLUMNS)
            now = time.perf_counter()
            sampler.sample(
                it,
                now - t0,
                iter_nodes,
                iter_nodes / max(now - t_iter, 1e-9),
                cnt,
                spill_stats.bytes_to_host - sp_h0,
                spill_stats.bytes_to_device - sp_d0,
                ic,
                lbf,
                len(reservoir),
            )
        if cnt == 0:
            break
        if time_limit_s is not None and time.perf_counter() - t0 > time_limit_s:
            break
        if target_cost is not None and ic <= target_cost:
            break
    wall = time.perf_counter() - t0
    proven = (
        int(fr.count) == 0 and len(reservoir) == 0 and not bool(fr.overflow)
    )
    if checkpoint_path and not proven:
        # always leave a resumable snapshot when stopping early (time limit,
        # iteration cap, target reached)
        save(checkpoint_path, fr, inc_cost, inc_tour, d=d, bound=bound,
             reservoir=reservoir, lb_floor=lbf)
    lb_raw = _final_lower_bound(
        proven, float(inc_cost), root_lb,
        [np.asarray(fr.bound[: int(fr.count)])], reservoir,
        overflow=bool(fr.overflow),
    )
    _obs_metrics.fold_bnb_solve(nodes, wall, spill_stats)
    return BnBResult(
        cost=float(inc_cost),
        tour=np.asarray(inc_tour),
        nodes_expanded=nodes,
        iterations=it,
        proven_optimal=proven,
        wall_seconds=wall,
        nodes_per_sec=nodes / wall if wall > 0 else 0.0,
        time_to_best=t_best,
        root_lower_bound=root_lb,
        # clamp to the resumed floor: both are certified, so the max is —
        # the reported LB can then never regress across chunked resumes
        lower_bound=min(max(lb_raw, lb_floor), float(inc_cost)),
        lower_bound_raw=lb_raw,
        setup_seconds=setup_s,
        ascent_seconds=ascent_s,
        ils_seconds=ils_s,
        spill_rounds=spill_stats.rounds,
        spill_events=spill_stats.events,
        spill_full_merges=spill_stats.full_merges,
        spill_bytes_to_host=spill_stats.bytes_to_host,
        spill_bytes_to_device=spill_stats.bytes_to_device,
        series=sampler.series() if sampler is not None else None,
        anomalies=sentinel.summary() if sentinel is not None else None,
    )


@dataclass
class ResumeHandle:
    """Continuation token for a step-sliced :func:`solve` (the serve
    scheduler's preemption handle, ISSUE 13).

    A handle means the search stopped UNPROVEN with a resumable snapshot
    at ``checkpoint_path`` (solve() always saves one when it stops early
    with a checkpoint path set). Passing the handle back to
    :func:`solve_slice` continues the identical search: the frontier,
    incumbent, reservoir and certified-LB floor restore bit-for-bit, the
    ILS seeding is skipped, and the DFS expansion order is deterministic
    — so a sliced solve converges to the same incumbent, tour and
    certified bound as one uninterrupted call (tests/test_serve_preempt).

    The progress fields feed the ladder's partial-latency estimator:
    ``first_lower_bound`` is the root bound after the first slice, so
    ``(lower_bound - first_lower_bound) / (incumbent - first_lower_bound)``
    measures how much of the certification gap the search has closed.
    """

    checkpoint_path: str
    slices: int
    elapsed_s: float
    incumbent: float
    lower_bound: float
    first_lower_bound: float

    def gap_progress(self) -> float:
        """Fraction of the certification gap closed so far, in [0, 1]."""
        span = self.incumbent - self.first_lower_bound
        if not np.isfinite(span) or span <= 0:
            return 0.0
        return float(
            min(max((self.lower_bound - self.first_lower_bound) / span, 0.0), 1.0)
        )


def solve_slice(
    d: np.ndarray,
    slice_s: float,
    handle: Optional[ResumeHandle] = None,
    *,
    checkpoint_path: Optional[str] = None,
    **solve_kw,
) -> Tuple[BnBResult, Optional[ResumeHandle]]:
    """Run at most ``slice_s`` seconds of :func:`solve`, preemptibly.

    First slice: pass ``checkpoint_path`` (where the donated snapshot
    lives between slices). Later slices: pass the returned handle back.
    Returns ``(result, handle)`` — ``handle is None`` means the search
    PROVED optimality and the result is final; otherwise ``result`` is
    the best-so-far (cost + certified ``lower_bound``) and ``handle``
    resumes exactly where this slice stopped. ``solve_kw`` is forwarded
    to :func:`solve` verbatim and must be identical across slices (the
    checkpoint pins ``d`` and ``bound``; the rest shapes the search and
    a mid-flight change would fork the trajectory)."""
    path = handle.checkpoint_path if handle is not None else checkpoint_path
    if not path:
        raise ValueError("solve_slice needs a checkpoint_path for its first slice")
    t0 = time.perf_counter()
    res = solve(
        d,
        time_limit_s=max(float(slice_s), 1e-3),
        checkpoint_path=path,
        resume_from=path if handle is not None else None,
        **solve_kw,
    )
    elapsed = time.perf_counter() - t0
    if res.proven_optimal:
        return res, None
    return res, ResumeHandle(
        checkpoint_path=path,
        slices=(handle.slices if handle is not None else 0) + 1,
        elapsed_s=(handle.elapsed_s if handle is not None else 0.0) + elapsed,
        incumbent=float(res.cost),
        lower_bound=float(res.lower_bound),
        first_lower_bound=(
            handle.first_lower_bound
            if handle is not None
            else float(res.root_lower_bound)
        ),
    )


def _rank_counts(count) -> np.ndarray:
    """Host copy of a sharded frontier's per-rank count vector — [R] int32,
    tens of bytes: the one per-round scalar-class readback the sharded
    host loop needs (the multi-rank analog of solve()'s ``int(fr.count)``
    scalar sync)."""
    return np.asarray(count)


def _apply_keeps(
    fr: Frontier, keeps: dict, new_counts: np.ndarray, spec,
    stats: SpillStats,
) -> Frontier:
    """Write every exchanged rank's kept rows back into the stacked sharded
    buffer with ONE sliced scatter (rank-index rows, column prefix), plus
    the [R] count vector. Shorter keeps are zero-padded to the widest one
    so the write is a single rectangular block: the padded rows land
    strictly past that rank's new count, i.e. in dead slots every consumer
    masks out (the same argument as _expand_step's push-padding block
    write). Only the kept slices ride the host->device path — never the
    physical buffer, whose untouched ranks keep their device contents
    bit-for-bit."""
    nodes = fr.nodes
    if keeps:
        ridx = sorted(keeps)
        mt = max(k.shape[0] for k in keeps.values())
        block = np.zeros((len(ridx), mt, int(nodes.shape[-1])), np.int32)
        for i, r in enumerate(ridx):
            block[i, : keeps[r].shape[0]] = keeps[r]
        stats.bytes_to_device += block.nbytes
        # donating rectangular scatter: the stacked physical buffer stays
        # the SAME allocation — untouched ranks keep their device contents
        # without a copy riding along per spill round
        nodes = _donation.set_rank_rows_donated(
            nodes, jnp.asarray(ridx, jnp.int32), jnp.asarray(block)
        )
    counts_dev = jax.device_put(new_counts.astype(np.int32), spec)
    return Frontier(nodes, counts_dev, fr.overflow)


# the pair matching now lives with the other balance math in
# parallel.balance (ISSUE 15); re-exported under the old name for the
# existing mesh-free property tests
_pair_assignment = _par_balance.pair_assignment


#: cross-solve cache of the per-action sharded step entries, LRU-bounded.
#: Keyed on (mesh device ids, config signature): the per-action bodies
#: close over statics only, so two solves with the same mesh/config can
#: share one traced+compiled executable set — repeated sharded solves
#: (serve sessions, chunked campaigns, test suites) stop paying a fresh
#: trace per call, and the no-retrace acceptance test wraps a SECOND
#: solve in analysis.contracts.RecompilationGuard over these very jits.
_SHARD_ENTRIES: "OrderedDict[tuple, dict]" = OrderedDict()
_SHARD_ENTRIES_MAX = 8


def solve_sharded(
    d: np.ndarray,
    mesh,
    capacity_per_rank: int = 1 << 15,
    k: int = 128,
    inner_steps: int = 32,
    max_iters: int = 200_000,
    time_limit_s: Optional[float] = None,
    bound: str = "one-tree",
    mst_prune: bool = True,
    transfer: Optional[int] = None,
    seed_mode: str = "round-robin",
    checkpoint_path: Optional[str] = None,
    checkpoint_every: int = 0,
    resume_from: Optional[str] = None,
    ils_rounds: Optional[int] = None,
    node_ascent: int = 2,
    ascent: str = "host",
    device_loop: Optional[bool] = None,
    reorder_every: int = 0,
    mst_kernel: str = "prim",
    balance: str = "pair",
    push_order: str = "best-first",
    push_block: int = 0,
    step_kernel: str = "reference",
) -> BnBResult:
    """Mesh-parallel B&B: per-rank frontiers, collective incumbent sharing.

    ``device_loop``: as in :func:`solve` — run MANY expansion rounds
    (expand + ring balance + incumbent all_gather + per-rank on-device
    compaction) inside ONE dispatch, syncing to the host only when the
    mesh drains, a rank is irreducibly full, or the step budget runs out.
    Default auto: on for accelerator backends (per-batch host syncs are
    ruinous through the remote relay), off for CPU meshes.

    The north star's architecture realized: each rank expands its own
    padded frontier shard (seeded with a round-robin split of the root's
    children), and after every inner batch the incumbent cost/tour is
    shared across the mesh with ``all_gather`` + argmin — the collective
    form of the reference-era ``MPI_Allreduce(MPI_MIN)`` incumbent
    broadcast, riding the ICI.

    Load balance (``balance``): after every inner batch ranks exchange up
    to ``transfer`` top-of-stack nodes inside the compiled program
    (amounts are data-dependent but shapes are static; the collectives
    live in :mod:`..parallel.balance`). ``"pair"`` (default) matches
    richest with poorest from the all-gathered counts and donates half
    the gap directly — flattens any skew in O(1) rounds. ``"ring"``
    donates to the ring successor via ``ppermute`` (the ICI version of
    MPI work-stealing) — cheaper per round but needs ~num_ranks diffusion
    hops and measurably strands ranks (VERDICT r4 weak #4: 12,554x
    max/min node imbalance on eil51 ranks=8). ``"steal"`` globally
    repartitions surplus live rows from the richest ranks to the starved
    ones in one collective. ``"adaptive"`` (ISSUE 15) closes the loop: a
    host-side controller reads the per-rank occupancy counts between
    dispatches and picks skip / pair / steal per round with hysteresis —
    each action is its own precompiled fixed-shape entry, so mode
    switches never retrace; decisions, moved rows, and the CV trajectory
    land in ``BnBResult.balance`` (the ``obs.balance`` payload block).

    ``seed_mode``: "round-robin" (default) splits the root's children over
    ranks; "single-rank" piles them all on rank 0 — the adversarial case
    used to test that balancing works.
    """
    t_setup = time.perf_counter()
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..utils.backend import pcast_varying, shard_map

    from ..parallel.mesh import RANK_AXIS

    n = d.shape[0]
    if not 3 <= n <= MAX_BNB_CITIES:
        raise ValueError(
            f"B&B engine supports 3 <= n <= {MAX_BNB_CITIES} cities, got {n}"
        )
    num_ranks = int(mesh.devices.size)
    auto_device_loop = device_loop is None
    if auto_device_loop:
        device_loop = jax.default_backend() not in ("cpu",)
    device_loop = _resolve_device_loop(
        device_loop, auto_device_loop, capacity_per_rank, k, n,
        what="capacity_per_rank",
    )
    cpu_dev = _acquire_cpu_polish_device(device_loop)
    d32 = jnp.asarray(d, jnp.float32)
    d_np = np.asarray(d, np.float64)
    t_asc = time.perf_counter()
    bd = _bound_setup(d, bound, node_ascent=node_ascent, ascent=ascent)
    ascent_s = time.perf_counter() - t_asc
    min_out, bound_adj, root_lb, integral = bd.min_out, bd.bound_adj, bd.root_lb, bd.integral
    min_out_np = np.asarray(min_out, np.float64)

    # seed: depth-2 children of the root, round-robin over ranks (skipped
    # when resuming — the checkpoint carries the per-rank stacks)
    sum_min0 = float(min_out_np[1:].sum())
    seed_nodes, seed_counts = [], []
    n_words = (n + 31) // 32
    for r in range(num_ranks if not resume_from else 0):
        # s_-prefixed locals: do NOT shadow the `bound`/`cost` parameters
        s_path = np.zeros((capacity_per_rank, n), np.int32)
        s_mask = np.zeros((capacity_per_rank, n_words), np.uint32)
        s_depth = np.zeros(capacity_per_rank, np.int32)
        s_cost = np.zeros(capacity_per_rank, np.float32)
        s_bound = np.zeros(capacity_per_rank, np.float32)
        s_sum = np.zeros(capacity_per_rank, np.float32)
        if seed_mode == "round-robin":
            mine = [c for c in range(1, n) if (c - 1) % num_ranks == r]
        elif seed_mode == "single-rank":
            mine = list(range(1, n)) if r == 0 else []
        else:
            raise ValueError(f"unknown seed_mode {seed_mode!r}")
        for slot, c in enumerate(mine):
            s_path[slot, 0] = 0
            s_path[slot, 1] = c
            s_mask[slot, 0] = np.uint32(1)  # city 0
            s_mask[slot, c // 32] |= np.uint32(1) << np.uint32(c % 32)
            s_depth[slot] = 2
            s_cost[slot] = d_np[0, c]
            s_bound[slot] = d_np[0, c] + sum_min0 + float(bound_adj[c])
            s_sum[slot] = sum_min0 - min_out_np[c]
        rows = _pack_rows_np(s_path, s_mask, s_depth, s_cost, s_bound, s_sum)
        # + k*n push-padding rows per rank (see _expand_step's block write)
        seed_nodes.append(
            np.concatenate([rows, np.zeros((k * n, rows.shape[1]), np.int32)])
        )
        seed_counts.append(np.int32(len(mine)))
    spec = NamedSharding(mesh, P(RANK_AXIS))
    resumed_reservoir = None
    ils_s = 0.0
    lb_floor = -np.inf  # best certified LB carried across resumed chunks
    if resume_from:
        fr_h, ic_h, itour_h, resumed_reservoir, lb_floor = restore(
            resume_from, expect_d=d, expect_bound=bound, expect_ranks=num_ranks
        )
        fr = Frontier(
            *(jax.device_put(np.asarray(x), spec) for x in fr_h)
        )
        ic = jax.device_put(np.asarray(ic_h), spec)
        itour = jax.device_put(np.asarray(itour_h), spec)
        inc_cost0 = float(np.asarray(ic_h)[0])
        # the restored arrays define the true per-rank LOGICAL capacity
        # (buffer rows minus the k*n push padding) — the caller's argument
        # must not disarm the spill trigger below (and the device_loop
        # floor must re-check against THIS capacity). Static shape only —
        # never materialize the packed buffer for this
        capacity_per_rank = max(int(fr_h.nodes.shape[1]) - k * n, 1)
        device_loop = _resolve_device_loop(
            device_loop, auto_device_loop, capacity_per_rank, k, n,
            what="capacity_per_rank",
            source=f" from checkpoint {resume_from!r}",
        )
    else:
        t_ils = time.perf_counter()
        inc_tour_np = _initial_incumbent(d, ils_rounds, device_loop, cpu_dev)
        ils_s = time.perf_counter() - t_ils
        inc_cost0 = tour_cost(d_np, inc_tour_np)
        fr = Frontier(
            jax.device_put(np.stack(seed_nodes), spec),
            jax.device_put(np.asarray(seed_counts, np.int32), spec),
            jax.device_put(np.zeros(num_ranks, bool), spec),
        )
        ic = jax.device_put(np.full(num_ranks, inc_cost0, np.float32), spec)
        itour = jax.device_put(
            np.broadcast_to(inc_tour_np, (num_ranks, n + 1)).copy(), spec
        )

    _contracts.check_frontier_packed(fr, n, where="solve_sharded")
    t_slots = int(transfer) if transfer is not None else max(k, 64)
    t_slots = min(t_slots, capacity_per_rank // 4)
    perm_fwd = [(r, (r + 1) % num_ranks) for r in range(num_ranks)]
    perm_back = [((r + 1) % num_ranks, r) for r in range(num_ranks)]
    # physical per-rank rows = logical capacity + the k*n push-padding block;
    # dead lanes park HERE so .at[...].set(mode="drop") actually drops them
    # (parking at capacity_per_rank would write garbage into padding row 0)
    phys_rows = int(fr.nodes.shape[-2])

    # ---- adaptive load balance (ISSUE 15) -------------------------------
    # The balance collectives themselves live in parallel.balance (ring /
    # pair kept verbatim, steal new); this block builds ONE sharded step
    # executable PER ACTION so the host-side controller can switch modes
    # between dispatches without ever retracing: the action is folded into
    # the AOT entry name, every needed entry is precompiled at setup, and
    # dispatch just selects among ready executables.
    if balance not in ("ring", "pair", "steal", "adaptive"):
        raise ValueError(
            f"unknown balance {balance!r} (expected ring|pair|steal|adaptive)"
        )
    adaptive_balance = balance == "adaptive"
    base_action = "pair" if adaptive_balance else balance
    _bal_kw = dict(
        num_ranks=num_ranks, t_slots=t_slots, capacity=capacity_per_rank,
        phys_rows=phys_rows, perm_fwd=perm_fwd, perm_back=perm_back,
    )

    def _apply_balance(action, f2, round_i):
        nodes2, cnt2, m_out = _par_balance.apply(
            action, f2.nodes, f2.count, round_i, **_bal_kw
        )
        return Frontier(nodes2, cnt2, f2.overflow), m_out

    def _make_rank_body(action):
        # host-loop mode: one inner batch per dispatch, balance after it.
        # New vs the pre-adaptive body: the per-rank donated-row counts
        # come back as a sixth output so the host can account moved
        # rows/bytes per dispatch.
        def rank_body(fr_stacked, ic_l, itour_l, d_rep, mo_rep, ba_rep,
                      dbar_rep, pi_rep, slack_rep, step_rep, budget_rep,
                      it_rep):
            local = Frontier(*(x[0] for x in fr_stacked))
            f2, c2, t2, nodes = _expand_loop(
                local, ic_l[0], itour_l[0], d_rep, mo_rep, ba_rep, dbar_rep,
                pi_rep, slack_rep, step_rep, budget_rep, k, n, inner_steps,
                integral, mst_prune, node_ascent, mst_kernel, push_order,
                push_block, step_kernel
            )
            mv = c2 * 0
            if num_ranks > 1 and action != "skip":
                f2, m_out = _apply_balance(action, f2, it_rep)
                mv = mv + m_out
            all_c = jax.lax.all_gather(c2, RANK_AXIS)
            all_t = jax.lax.all_gather(t2, RANK_AXIS)
            b = jnp.argmin(all_c)
            total_nodes = jax.lax.psum(nodes, RANK_AXIS)
            rank_nodes = jax.lax.all_gather(nodes, RANK_AXIS)
            rank_moved = jax.lax.all_gather(mv, RANK_AXIS)
            return (
                jax.tree.map(lambda x: x[None], tuple(f2)),
                all_c[b][None],
                all_t[b][None],
                total_nodes[None],
                rank_nodes[None],
                rank_moved[None],
            )

        return rank_body

    # the device-resident outer loop (device_loop mode): MANY rounds of
    # [inner_steps guarded expansion steps -> balance -> incumbent
    # all_gather] run inside ONE dispatch. Each round's expansion is
    # _guarded_expand_steps — the same per-step compaction/full-stop
    # machinery as _solve_device, so a rank can never overflow-drop
    # (growth per step <= k*(n-1) = the reserved headroom). A round also
    # computes a `done` flag (mesh drained, a rank irreducibly full ->
    # host must spill, or overflow tripped) consumed by the while cond
    # NEXT iteration, keeping collectives out of cond. The controller's
    # action holds for every round of the dispatch (decisions live at
    # guarded-step boundaries); donated-row counts accumulate in the
    # while carry so the host still sees the dispatch's full total.
    loop_headroom = min(capacity_per_rank // 4, k * (n - 1))

    def _make_rank_body_loop(action):
        def rank_body_loop(fr_stacked, ic_l, itour_l, d_rep, mo_rep, ba_rep,
                           dbar_rep, pi_rep, slack_rep, step_rep, budget_rep,
                           max_rounds_rep, it0_rep):
            local = Frontier(*(x[0] for x in fr_stacked))

            def cond(c):
                _, _, _, _, i, done, _ = c
                return (i < max_rounds_rep) & ~done

            def body(c):
                fr, icc, itc, nds, i, _, mv = c
                fr, icc, itc, dn, _, _ = _guarded_expand_steps(
                    fr, icc, itc, d_rep, mo_rep, ba_rep, dbar_rep, pi_rep,
                    slack_rep, step_rep, budget_rep, jnp.asarray(inner_steps),
                    k, n, integral, mst_prune, node_ascent,
                    reorder_every=reorder_every,
                    step0=it0_rep + i * inner_steps,
                    mst_kernel=mst_kernel,
                    push_order=push_order,
                    push_block=push_block,
                    step_kernel=step_kernel,
                )
                if num_ranks > 1 and action != "skip":
                    # round_i counts BALANCE EVENTS, not steps: step counts
                    # advance by inner_steps, and inner_steps % num_ranks == 0
                    # would freeze the pair tie rotation
                    fr, m_out = _apply_balance(
                        action, fr, it0_rep // max(inner_steps, 1) + i
                    )
                    mv = mv + m_out
                all_c = jax.lax.all_gather(icc, RANK_AXIS)
                all_t = jax.lax.all_gather(itc, RANK_AXIS)
                sel = jnp.argmin(all_c)
                icc, itc = all_c[sel], all_t[sel]
                full = fr.count > capacity_per_rank - loop_headroom
                stop = full | fr.overflow
                any_stop = jax.lax.psum(stop.astype(jnp.int32), RANK_AXIS) > 0
                total = jax.lax.psum(fr.count, RANK_AXIS)
                # psum/all-reduce results are axis-invariant; the carry slot
                # was initialized from a varying value, so re-mark it varying
                # (identity on jax builds without VMA tracking — backend
                # compat)
                done = pcast_varying((total == 0) | any_stop, RANK_AXIS)
                return fr, icc, itc, nds + dn, i + 1, done, mv

            zero = local.count * 0
            fr, icc, itc, nds, steps, _, mv = jax.lax.while_loop(
                cond, body,
                (local, ic_l[0], itour_l[0], zero, zero, local.count < 0,
                 zero),
            )
            total_nodes = jax.lax.psum(nds, RANK_AXIS)
            rank_nodes = jax.lax.all_gather(nds, RANK_AXIS)
            rank_moved = jax.lax.all_gather(mv, RANK_AXIS)
            return (
                jax.tree.map(lambda x: x[None], tuple(fr)),
                icc[None],
                itc[None],
                total_nodes[None],
                rank_nodes[None],
                steps[None],
                rank_moved[None],
            )

        return rank_body_loop

    _in_specs_step = (
        tuple(P(RANK_AXIS) for _ in Frontier._fields),
        P(RANK_AXIS),
        P(RANK_AXIS),
        P(None, None),
        P(None),
        P(None),
        P(None, None),
        P(None),
        P(),
        P(),
        P(),
        P(),
    )
    _out_specs_step = (
        tuple(P(RANK_AXIS) for _ in Frontier._fields),
        P(RANK_AXIS),
        P(RANK_AXIS),
        P(RANK_AXIS),
        P(RANK_AXIS),
        P(RANK_AXIS),
    )
    _in_specs_loop = _in_specs_step + (P(),)
    _out_specs_loop = _out_specs_step + (P(RANK_AXIS),)

    # per-(mesh, config) entry set, shared ACROSS solves: the bodies close
    # over static config only, so a repeated same-config solve (serve
    # sessions, chunked campaigns, the test suite) reuses the already
    # traced/compiled executables — the no-retrace acceptance test wraps a
    # second solve in RecompilationGuard over exactly these jits
    mode_tag = "loop" if device_loop else "step"
    entry_cfg = (
        mode_tag, num_ranks, capacity_per_rank, phys_rows, k, n,
        inner_steps, bool(integral), bool(mst_prune), node_ascent,
        mst_kernel, push_order, push_block, step_kernel, reorder_every,
        t_slots, FRONTIER_LAYOUT_VERSION,
    )
    cfg_sig = hashlib.blake2b(
        repr(entry_cfg).encode(), digest_size=6
    ).hexdigest()
    dev_key = tuple(int(dv.id) for dv in mesh.devices.flat)
    entries = _SHARD_ENTRIES.get((dev_key, cfg_sig))
    if entries is None:
        entries = {"jit": {}, "aot": {}}
        _SHARD_ENTRIES[(dev_key, cfg_sig)] = entries
        while len(_SHARD_ENTRIES) > _SHARD_ENTRIES_MAX:
            _SHARD_ENTRIES.popitem(last=False)
    else:
        _SHARD_ENTRIES.move_to_end((dev_key, cfg_sig))

    def _entry(action):
        # the stacked per-rank frontier (arg 0) is donated on every
        # sharded dispatch — same in-place aliasing as the single-device
        # entries; the host loop rebinds it from the output immediately
        fn = entries["jit"].get(action)
        if fn is None:
            fn = jax.jit(
                shard_map(
                    (_make_rank_body_loop if device_loop
                     else _make_rank_body)(action),
                    mesh=mesh,
                    in_specs=(
                        _in_specs_loop if device_loop else _in_specs_step
                    ),
                    out_specs=(
                        _out_specs_loop if device_loop else _out_specs_step
                    ),
                ),
                donate_argnums=(0,),
            )
            entries["jit"][action] = fn
        return fn

    # precompile every action this run can pick, at setup, through the
    # AOT store (paid/saved seconds land in the compile_cache stats
    # block): a mid-solve action switch must select a READY executable,
    # never pay a trace/compile inside the timed loop. load_or_build
    # returns a Compiled even with the cache disabled.
    if num_ranks <= 1:
        needed_actions = ("skip",)
    elif adaptive_balance:
        needed_actions = tuple(dict.fromkeys(("skip", base_action, "steal")))
    else:
        needed_actions = (base_action,)
    if device_loop:
        example_tail = (jnp.asarray(1, jnp.int32), jnp.asarray(0, jnp.int32))
    else:
        example_tail = (jnp.asarray(0, jnp.int32),)
    example_args = (
        tuple(fr), ic, itour, d32, min_out, bound_adj, bd.dbar, bd.pi,
        bd.slack, bd.ascent_step, bd.lam_budget,
    ) + example_tail
    entry_prefix = f"shard_{mode_tag}"
    for _a in needed_actions:
        if entries["aot"].get(_a) is not None:
            continue
        try:
            entries["aot"][_a] = _perf_cache.load_or_build(
                f"{entry_prefix}.{_a}.{cfg_sig}", _entry(_a), example_args
            )
        except Exception:
            # precompile is an optimization, never a correctness gate:
            # the plain jit dispatch below stays authoritative
            entries["aot"][_a] = None

    def _dispatch(action, args):
        aot = entries["aot"].get(action)
        if aot is not None:
            try:
                return aot(*args)
            except TypeError:
                # aval drift vs the stored executable (arg validation
                # happens before execution, so nothing was consumed) —
                # the jit path is authoritative; degrade this entry for
                # the rest of the process
                entries["aot"][action] = None
                _perf_cache.STATS.record(
                    f"{entry_prefix}.{action}.{cfg_sig}", "error"
                )
        return _entry(action)(*args)

    # the host-side controller: picks each dispatch's action from the
    # per-rank occupancy counts the spill path already reads back — no
    # telemetry dependency, so it keeps working under TSP_OBS=off
    controller = _par_balance.BalanceController(
        num_ranks=num_ranks, k=k, t_slots=t_slots, base=base_action,
        adaptive=adaptive_balance,
        row_bytes=int(fr.nodes.shape[-1]) * 4,
    )
    rank_alive_counts = None
    if adaptive_balance and num_ranks > 1:
        # escalation confirmation probe: device-side ALIVE counts
        # (parallel.reduce collective — solver machinery, not telemetry)
        from ..parallel.reduce import make_rank_alive_counts

        rank_alive_counts = make_rank_alive_counts(mesh, integral=integral)

    # per-rank best-bound-first re-sort (host-loop mode; the device loop
    # does it in-kernel via step0 cadence): one shard-mapped
    # argsort+gather per rank shard — see _reorder_frontier. The stacked
    # frontier is donated: the re-sort permutes in place
    reorder_ranks = jax.jit(
        shard_map(
            lambda fr_stacked: jax.tree.map(
                lambda x: x[None],
                tuple(
                    _reorder_frontier(
                        Frontier(*(x[0] for x in fr_stacked)),
                        rows=capacity_per_rank,
                    )
                ),
            ),
            mesh=mesh,
            in_specs=(tuple(P(RANK_AXIS) for _ in Frontier._fields),),
            out_specs=tuple(P(RANK_AXIS) for _ in Frontier._fields),
        ),
        donate_argnums=(0,),
    )

    # per-rank host reservoirs: the sharded analog of solve()'s overflow
    # spill — a rank whose stack nears capacity sheds its worst-bound
    # bottom half to the host; when the whole mesh drains, spilled nodes
    # flow back (incumbent-filtered), so capacity pressure never converts
    # into the terminal exactness-lost flag. All ranks share ONE transfer
    # accounting object (BnBResult reports whole-run totals).
    spill_stats = SpillStats()
    reservoirs = [_Reservoir(stats=spill_stats) for _ in range(num_ranks)]
    # per-rank spill attribution (ISSUE 10): spill_refill already walks
    # ranks one by one, so splitting the SpillStats totals per rank costs
    # three host int adds per event — the rank-resolved series and the
    # obs.rank_balance block read these, and their sums must equal the
    # aggregate counters (regression-tested)
    rank_spill_events = np.zeros(num_ranks, np.int64)
    rank_spill_to_host = np.zeros(num_ranks, np.int64)
    rank_spill_to_device = np.zeros(num_ranks, np.int64)
    if resumed_reservoir is not None and len(resumed_reservoir):
        # a resumed checkpoint's spilled nodes land on rank 0; the ring
        # balance spreads them once they flow back onto the device
        resumed_reservoir.stats = spill_stats
        reservoirs[0] = resumed_reservoir
    headroom = _spill_headroom(capacity_per_rank, inner_steps, k, n)
    # the reusable per-rank alive-min collective (parallel.reduce): the
    # spill fast-path predicate input, computed ON DEVICE so the decision
    # costs one [R]-floats readback instead of any buffer fetch
    from ..parallel.reduce import make_rank_alive_min

    rank_alive_min = make_rank_alive_min(mesh, integral=integral)

    def spill_refill(fr, inc_best):
        counts = _rank_counts(fr.count)
        spilling = counts > capacity_per_rank - headroom
        refilling = (counts == 0) & np.asarray(
            [len(rv) > 0 for rv in reservoirs]
        )
        if not (spilling.any() or refilling.any()):
            # the counts ride back in every return: the balance
            # controller's decision signal is this same readback (no
            # second device->host fetch per dispatch)
            return fr, int(counts.sum()), counts
        # the device-resident exchange (this PR's tentpole): per-rank
        # frontier alive-minima come from the on-device collective; each
        # affected rank then fetches ONLY its live prefix, best-half
        # selects on host, and writes back only the kept slice — the
        # stacked physical buffer (capacity + k*n padding rows per rank)
        # never round-trips. The full reservoir merge — the only path
        # that concatenates the (possibly multi-GB) spilled chunks — runs
        # solely for ranks whose reservoir owns their alive minimum (the
        # spill inversion _Reservoir.exchange documents); otherwise the
        # spilled chunks are never touched.
        # the spill round is one collective span: per-rank participation
        # (mode, merge verdict, rows kept, bytes moved) rides as events,
        # so a campaign trace attributes the round to the ranks that
        # actually paid for it (null span when tracing is off)
        with _obs_tracing.span(
            "bnb.spill_round",
            ranks=num_ranks,
            spilling=int(spilling.sum()),
            refilling=int(refilling.sum()),
        ) as _sp:
            live_min = None
            if spilling.any():  # refill-only rounds never read the minima
                # the packed buffer goes in whole; the bound column is
                # sliced in-kernel (no eager [R, F] f32 materialization
                # per round)
                live_min = np.asarray(
                    rank_alive_min(
                        fr.nodes, fr.count, jnp.asarray(inc_best, jnp.float32)
                    )
                )
                _sp.event(
                    "reduce.rank_alive_min",
                    ranks=[int(x) for x in np.flatnonzero(spilling)],
                )
            spill_stats.rounds += 1
            keeps = {}
            new_counts = counts.copy()
            for r in range(num_ranks):
                if not (spilling[r] or refilling[r]):
                    continue
                rv = reservoirs[r]
                if refilling[r]:
                    keep = rv.refill_rows(inc_best, integral, capacity_per_rank)
                    mode, merge = "refill", False
                    if keep is not None:
                        rv.stats.events += 1
                        rank_spill_events[r] += 1
                else:
                    cnt = int(counts[r])
                    live = _fetch_live_rows(fr.nodes[r], cnt)
                    # compare ALIVE minima, exactly as the single-device
                    # exchange does: merge the reservoir only when it
                    # holds a strictly better open node than the rank's
                    # live frontier
                    merge = not (cnt and rv.min_bound() >= float(live_min[r]))
                    mode = "exchange"
                    rv.stats.events += 1
                    rv.stats.full_merges += int(merge)
                    rv.stats.bytes_to_host += live.nbytes
                    rank_spill_events[r] += 1
                    rank_spill_to_host[r] += live.nbytes
                    keep = rv.exchange_rows(
                        live, inc_best, integral, capacity_per_rank, merge=merge
                    )
                new_counts[r] = 0 if keep is None else keep.shape[0]
                if keep is not None:
                    keeps[r] = keep
                _sp.event(
                    "rank_spill", rank=int(r), mode=mode, merge=bool(merge),
                    kept=int(new_counts[r]), reservoir=len(rv),
                )
                _contracts.check_exchange_count(
                    int(new_counts[r]), capacity_per_rank,
                    where="solve_sharded.spill_refill",
                )
            if keeps:
                # device-ward attribution: _apply_keeps pads every kept
                # slice to the widest one before its single rectangular
                # scatter — attribute the PADDED share per rank so the
                # per-rank vector sums to the aggregate byte counter
                row_bytes = (
                    max(kk.shape[0] for kk in keeps.values())
                    * int(fr.nodes.shape[-1]) * 4
                )
                for r in keeps:
                    rank_spill_to_device[r] += row_bytes
            stacked = _apply_keeps(fr, keeps, new_counts, spec, spill_stats)
            _contracts.check_frontier(
                stacked, n=n, where="solve_sharded.spill_refill"
            )
            return stacked, int(new_counts.sum()), new_counts

    if resume_from:
        # a checkpoint written with a smaller k (or the pre-padding
        # layout) can restore counts above this run's logical capacity;
        # shed the overhang to the reservoirs BEFORE the first dispatch
        # (the unguarded host-loop expand would otherwise be forced to
        # clamp its block write and flag exactness lost)
        fr, _, _ = spill_refill(fr, inc_cost0)

    # the controller's first decision reads the same per-rank occupancy
    # counts the spill path uses; paid once here in setup, then refreshed
    # for free from spill_refill's per-dispatch readback
    counts_now = _rank_counts(fr.count)

    t0 = time.perf_counter()
    setup_s = t0 - t_setup
    t_best = 0.0
    last_inc = inc_cost0
    nodes = 0
    it = 0
    rank_nodes = np.zeros(num_ranks, np.int64)
    total0 = 1
    last_ckpt = 0
    last_reorder = 0
    rounds_rate = 0.0  # measured in-dispatch rounds/sec of the last dispatch
    sampler = _obs_series.StepSampler.maybe()
    if sampler is not None:
        sampler.row_bytes = int(fr.nodes.shape[-1]) * 4
        sampler.frontier_layout = FRONTIER_LAYOUT_VERSION
    # stall sentinel (ISSUE 9): same per-dispatch feed as the sampler —
    # attached so the loop makes one telemetry call per dispatch
    sentinel = _obs_anomaly.StallSentinel.maybe()
    if sampler is not None:
        sampler.sentinel = sentinel
    # rank-resolved sampler (ISSUE 10): one [R, K] device stats row per
    # sampling window (parallel.reduce.make_rank_stats — same
    # single-readback pattern as the spill path's rank_alive_min), host
    # columns from the per-rank accounting this loop already owns. The
    # per-dispatch cost is due()'s counter compare; the gather amortizes
    # over the window (TSP_BENCH=shard meters the whole hook, <= 2%)
    rank_sampler = _obs_rank.RankSampler.maybe(num_ranks)
    if rank_sampler is not None:
        from ..parallel.reduce import make_rank_stats

        rank_stats_row = make_rank_stats(mesh, integral=integral)
        # pay the collective's trace+compile HERE, in setup, not inside
        # the first sampling window — the TSP_BENCH=shard meter gates the
        # steady-state hook cost, and a compile billed to it would be
        # measuring XLA, not telemetry
        rank_stats_row(
            fr.nodes, fr.count, jnp.asarray(inc_cost0, jnp.float32)
        )

    def _rank_sample(step_now: int, inc_now: float) -> None:
        # the whole window hook in one place so the sharded loop and the
        # end-of-run tail flush cannot drift apart — and BOTH call sites
        # bill the gather + readback + ring append to METER_NS (the
        # TSP_BENCH=shard gate must price the tail flush too, not just
        # the in-loop windows)
        m = _obs_rank.RankSampler.METER_NS
        if m is not None:
            t_meter = time.perf_counter_ns()
        row = np.asarray(
            rank_stats_row(fr.nodes, fr.count, jnp.asarray(inc_now, jnp.float32))
        )
        _obs_tracing.add_event(
            "reduce.rank_stats", step=step_now, ranks=num_ranks
        )
        rank_sampler.sample(
            step_now, row[:, 0], row[:, 1], rank_nodes,
            [len(rv) for rv in reservoirs],
            rank_spill_events, rank_spill_to_host, rank_spill_to_device,
            row[:, 2],
        )
        if m is not None:
            m[0] += time.perf_counter_ns() - t_meter

    # loop-invariant certified floor for telemetry/checkpoints
    lbf = float(max(lb_floor, root_lb))
    step_ann = _obs_tracing.step_annotation_factory()
    while it < max_iters:
        t_iter = time.perf_counter()
        sp_h0, sp_d0 = spill_stats.bytes_to_host, spill_stats.bytes_to_device
        # pick THIS dispatch's balance action from the current occupancy
        # counts (hysteresis + escalation live in the controller); the
        # chaos seam fires host-side on escalation and an injected fault
        # degrades the round to the base action — the search stays exact
        # either way, balance only moves rows
        prev_action = controller.last_action
        action = controller.decide(
            counts_now,
            alive_probe=(
                (lambda: np.asarray(rank_alive_counts(
                    fr.nodes, fr.count, jnp.asarray(last_inc, jnp.float32)
                )))
                if rank_alive_counts is not None
                else None
            ),
        )
        if action == "steal":
            try:
                _fault_registry().fire("balance.steal")
            except _TransientFault:
                action = controller.degrade()
        controller.count_action(action)
        if action != prev_action:
            # a span per decision would drown the trace; stamp SWITCHES,
            # with the donor/receiver sets the new action will see
            with _obs_tracing.span(
                "bnb.balance", step=it, action=action, cv=controller.cv,
            ) as _bsp:
                _bsp.event(
                    "rank_participation",
                    **controller.participation(counts_now),
                )
        if device_loop:
            # one in-dispatch round = inner_steps expansion steps; all
            # caps (psum'd int32 counters, checkpoint cadence, CPU-only
            # clock re-check) live in _dispatch_budget
            unit = max(inner_steps, 1)
            rounds = _dispatch_budget(
                (max_iters - it) // unit,
                (2**31 - 1) // max(k * unit * num_ranks, 1),
                (checkpoint_every - (it - last_ckpt)) // unit
                if (checkpoint_every and checkpoint_path)
                else None,
                rounds_rate,
                (time_limit_s - (time.perf_counter() - t0))
                if (
                    time_limit_s is not None
                    and jax.default_backend() == "cpu"
                )
                else None,
                max(_FIRST_DISPATCH_STEPS // unit, 1),
            )
            t_disp = time.perf_counter()
            prev_nodes = fr.nodes if _contracts.level() != "off" else None
            with step_ann(it):
                out = _dispatch(action, (
                    tuple(fr), ic, itour, d32, min_out, bound_adj,
                    bd.dbar, bd.pi, bd.slack, bd.ascent_step,
                    bd.lam_budget, jnp.asarray(rounds, jnp.int32),
                    jnp.asarray(it, jnp.int32),
                ))
            rounds_done = max(int(out[5][0]), 1)
            disp_s = time.perf_counter() - t_disp
            if disp_s > 0:
                rounds_rate = rounds_done / disp_s
        else:
            prev_nodes = fr.nodes if _contracts.level() != "off" else None
            with step_ann(it):
                out = _dispatch(action, (
                    tuple(fr), ic, itour, d32, min_out, bound_adj, bd.dbar,
                    bd.pi, bd.slack, bd.ascent_step, bd.lam_budget,
                    jnp.asarray(it // max(inner_steps, 1), jnp.int32),
                ))
            rounds_done = 1
        fr = Frontier(*out[0])
        if prev_nodes is not None:
            # the stacked frontier is donated into every sharded dispatch
            _contracts.check_donated(prev_nodes, where="solve_sharded.step")
        ic, itour, step_nodes = out[1], out[2], out[3]
        rank_nodes = rank_nodes + np.asarray(out[4][0])
        # the dispatch's per-rank donated-row counts (loop mode: summed
        # over its in-dispatch rounds) — obs.balance accounting
        controller.record(
            it, action, np.asarray(out[6 if device_loop else 5][0])
        )
        nodes += int(step_nodes[0])
        it += rounds_done * inner_steps
        best = float(ic[0])
        if best < last_inc:
            last_inc = best
            t_best = time.perf_counter() - t0
            # GC per-rank reservoirs against the improved incumbent (the
            # per-rank exchange only touches ranks that spill/refill)
            for rv in reservoirs:
                if len(rv):
                    rv.prune(best, integral)
        fr, total0, counts_now = spill_refill(fr, best)
        if (
            reorder_every
            and not device_loop
            and it - last_reorder >= reorder_every
        ):
            # one collective span per re-sort: every rank participates
            # (the dispatch is a full-mesh shard_map), named explicitly
            # so rank attribution survives trace aggregation
            with _obs_tracing.span(
                "bnb.reorder", step=it, ranks=num_ranks
            ) as _rsp:
                _rsp.event(
                    "rank_participation", ranks=list(range(num_ranks))
                )
                fr = Frontier(*reorder_ranks(tuple(fr)))
            last_reorder = it
        if (
            checkpoint_every
            and checkpoint_path
            and it - last_ckpt >= checkpoint_every
        ):
            save(checkpoint_path, fr, ic, itour, d=d, bound=bound,
                 num_ranks=num_ranks, reservoir=_merge_reservoirs(reservoirs),
                 lb_floor=lbf)
            last_ckpt = it
        if sampler is not None:
            # positional on purpose (column order = timeseries.COLUMNS)
            now = time.perf_counter()
            step_n = int(step_nodes[0])
            sampler.sample(
                it,
                now - t0,
                step_n,
                step_n / max(now - t_iter, 1e-9),
                int(total0),
                spill_stats.bytes_to_host - sp_h0,
                spill_stats.bytes_to_device - sp_d0,
                best,
                lbf,
                sum(len(rv) for rv in reservoirs),
            )
        if rank_sampler is not None:
            # the rank hook: one counter compare per dispatch (billed
            # here), one [R, K] gather + ring append per window (billed
            # inside _rank_sample, so the end-of-run tail flush meters
            # identically) — together the whole METER_NS figure the
            # TSP_BENCH=shard gate prices
            _rkm = _obs_rank.RankSampler.METER_NS
            if _rkm is not None:
                _t_rk = time.perf_counter_ns()
            _rk_due = rank_sampler.due()
            if _rkm is not None:
                _rkm[0] += time.perf_counter_ns() - _t_rk
            if _rk_due:
                _rank_sample(it, best)
        if int(total0) == 0:
            break
        if time_limit_s is not None and time.perf_counter() - t0 > time_limit_s:
            break
    wall = time.perf_counter() - t0
    overflow = bool(np.asarray(fr.overflow).any())
    proven = (
        int(total0) == 0
        and all(len(rv) == 0 for rv in reservoirs)
        and not overflow
    )
    if checkpoint_path and not proven:
        save(checkpoint_path, fr, ic, itour, d=d, bound=bound,
             num_ranks=num_ranks, reservoir=_merge_reservoirs(reservoirs),
             lb_floor=lbf)
    counts = np.asarray(fr.count)
    bounds_h = np.asarray(fr.bound)
    merged_res = _merge_reservoirs(reservoirs) or _Reservoir()
    lb_raw = _final_lower_bound(
        proven, float(ic[0]), root_lb,
        [bounds_h[r, : int(counts[r])] for r in range(num_ranks)],
        merged_res,
        overflow=overflow,
    )
    _obs_metrics.fold_bnb_solve(nodes, wall, spill_stats)
    rank_series = rank_bal = None
    if rank_sampler is not None:
        if rank_sampler.pending():
            # cover the tail: the last window's deltas must reach the
            # series even when the loop exits between sample cadences
            _rank_sample(it, last_inc)
        rank_series = rank_sampler.series()
        rank_bal = _obs_rank.rank_balance(
            rank_series, rank_nodes,
            spill_events=rank_spill_events,
            spill_bytes_to_host=rank_spill_to_host,
            spill_bytes_to_device=rank_spill_to_device,
            reservoir=[len(rv) for rv in reservoirs],
            events=rank_sampler.watch.events,
        )
        _obs_rank.fold_rank_view(rank_bal)
    return BnBResult(
        cost=float(ic[0]),
        tour=np.asarray(itour)[0],
        nodes_expanded=nodes,
        iterations=it,
        proven_optimal=proven,
        wall_seconds=wall,
        nodes_per_sec=nodes / wall if wall > 0 else 0.0,
        time_to_best=t_best,
        root_lower_bound=root_lb,
        # clamped to the resumed floor — monotone across chunked resumes
        lower_bound=min(max(lb_raw, lb_floor), float(ic[0])),
        lower_bound_raw=lb_raw,
        nodes_per_rank=rank_nodes,
        setup_seconds=setup_s,
        ascent_seconds=ascent_s,
        ils_seconds=ils_s,
        spill_rounds=spill_stats.rounds,
        spill_events=spill_stats.events,
        spill_full_merges=spill_stats.full_merges,
        spill_bytes_to_host=spill_stats.bytes_to_host,
        spill_bytes_to_device=spill_stats.bytes_to_device,
        series=sampler.series() if sampler is not None else None,
        # stall + rank-starvation verdicts on one step-ordered timeline
        anomalies=_obs_anomaly.merge_summaries(
            sentinel, rank_sampler.watch if rank_sampler is not None else None
        ),
        rank_series=rank_series,
        rank_balance=rank_bal,
        balance=controller.summary(),
    )


def _is_integral(d) -> bool:
    """True iff every distance is integer-valued — the predicate that
    selects the fixed-point-exact f32 path (_bound_setup) and the static
    ``integral`` kernel config. Single source of truth: benches that
    AOT-compile the kernel must derive the flag the same way."""
    d64 = np.asarray(d, np.float64)
    return bool(np.all(d64 == np.rint(d64)))


def _final_lower_bound(
    proven: bool, cost: float, root_lb: float, open_bounds, reservoir,
    overflow: bool = False,
) -> float:
    """Certified global lower bound at stop: the proven cost, or the min
    bound over every still-open node (device frontier slices passed in
    ``open_bounds`` + host reservoir), floored at the root bound and
    capped at the incumbent.

    ``overflow``: the in-kernel overflow flag tripped, i.e. children were
    DROPPED — the surviving open set no longer covers the search space,
    so min-over-survivors is not a valid bound; fall back to the root
    bound (always certified)."""
    if proven:
        return cost
    if overflow:
        return min(root_lb, cost)
    mins = [float(b.min()) for b in open_bounds if b.size]
    if len(reservoir):
        mins.append(reservoir.min_bound())
    lb = min(mins) if mins else cost
    return min(max(lb, root_lb), cost)


def _spill_headroom(capacity: int, inner_steps: int, k: int, n: int) -> int:
    """Spill before a single inner batch could possibly overflow the stack
    (each of the ``inner_steps`` steps pushes at most k*(n-1) children);
    for small capacities fall back to keeping the top half."""
    return min(capacity // 2, max(1, inner_steps) * k * (n - 1))


def _merge_reservoirs(reservoirs) -> Optional["_Reservoir"]:
    """Concatenate per-rank reservoirs into one (for checkpointing)."""
    merged = _Reservoir()
    for rv in reservoirs:
        merged.chunks.extend(rv.chunks)
    return merged if len(merged) else None


def _norm_ckpt_path(path: str) -> str:
    # np.savez appends ".npz" when missing; normalize so save/restore agree
    return path if path.endswith(".npz") else path + ".npz"


def _d_fingerprint(d) -> np.ndarray:
    d = np.asarray(d, np.float64)
    return np.asarray([d.shape[0], float(d.sum()), float(d.std())])


def save(
    path: str,
    fr: Frontier,
    inc_cost,
    inc_tour,
    d=None,
    bound=None,
    reservoir=None,
    num_ranks: Optional[int] = None,
    lb_floor: Optional[float] = None,
) -> None:
    """Checkpoint frontier + incumbent (+ instance fingerprint + any
    host-spilled reservoir nodes) to ``.npz``.

    ``num_ranks``: set for a sharded checkpoint (stacked [R, ...] frontier
    arrays); restore() then refuses to resume it on a different rank count
    (per-rank stacks can't be re-split without re-sorting the search order).

    ``lb_floor``: the caller's best certified lower bound so far (root
    bound, or a floor restored from an earlier chunk). When set, the
    checkpoint records ``lb_certified = max(floor, min bound over every
    still-open node)`` — both operands are certified, so the max is — and
    resuming solvers clamp their reported LB to it. This is what makes
    the reported certified LB MONOTONE across a chunked campaign
    (VERDICT r5: the per-chunk LB used to regress between chunks).

    The .npz stores the LOGICAL node fields (path/mask/...), not the
    packed buffer — the format predates the packed layout and stays
    stable across engine-internal layout changes.
    """
    payload = _ckpt_payload(
        fr, inc_cost, inc_tour, d=d, bound=bound, reservoir=reservoir,
        num_ranks=num_ranks, lb_floor=lb_floor,
    )
    # crash-safe publish: npz serialized in memory, then atomically
    # replaced into the rotation chain with an integrity header — a
    # writer killed at ANY byte offset can no longer destroy the campaign
    # (the legacy direct np.savez_compressed could; see resilience/)
    # the TSPCKPT header records which engine-internal row layout wrote
    # this snapshot (diagnostics only: the payload stores LOGICAL fields,
    # so any layout version restores any snapshot; legacy headerless /
    # pre-key snapshots read fine — see restore())
    _ckpt_store.write_atomic(
        _norm_ckpt_path(path),
        _ckpt_store.npz_bytes(**payload),
        fingerprint=(
            _ckpt_store.instance_fingerprint(d) if d is not None else None
        ),
        extra_header={"frontier_layout": FRONTIER_LAYOUT_VERSION},
    )


def _ckpt_payload(
    fr: Frontier,
    inc_cost,
    inc_tour,
    d=None,
    bound=None,
    reservoir=None,
    num_ranks: Optional[int] = None,
    lb_floor: Optional[float] = None,
) -> dict:
    """The checkpoint's npz-ready array dict (see :func:`save`). Split out
    so the faults bench can time the LEGACY direct-write path against the
    atomic store on byte-identical payloads."""
    # ONE device->host transfer of the packed buffer, then host-side
    # column unpacking (the property views would issue six separate
    # slice/bitcast kernels + transfers through the relay). The exact n
    # comes from the closed incumbent tour ([n+1] ids) — the byte-packed
    # path words alone only bound it (see _layout)
    n_exact = int(np.shape(inc_tour)[-1]) - 1
    payload = {
        "inc_cost": np.asarray(inc_cost),
        "inc_tour": np.asarray(inc_tour),
        "count": np.asarray(fr.count),
        "overflow": np.asarray(fr.overflow),
        **_unpack_rows_np(np.asarray(fr.nodes), n=n_exact),
    }
    if d is not None:
        payload["d_fingerprint"] = _d_fingerprint(d)
    if bound is not None:
        payload["bound_mode"] = np.asarray(bound)
    if num_ranks is not None:
        payload["num_ranks"] = np.asarray(num_ranks)
    if lb_floor is not None:
        # min over open nodes, from the ALREADY-transferred payload (no
        # extra device work): frontier live prefixes + reservoir chunks.
        # UNLESS overflow tripped — children were dropped in-kernel, the
        # surviving open set no longer covers the search space and its min
        # is NOT a valid bound (same guard as _final_lower_bound): then
        # only the caller's floor (certified before the loss) is stored.
        if bool(np.asarray(payload["overflow"]).any()):
            open_min = float("-inf")
        else:
            bnd, cnt = payload["bound"], payload["count"]
            if cnt.ndim == 0:
                mins = [bnd[: int(cnt)].min()] if int(cnt) else []
            else:
                mins = [
                    bnd[r, : int(c)].min()
                    for r, c in enumerate(cnt.tolist())
                    if int(c)
                ]
            if reservoir is not None and len(reservoir):
                mins.append(reservoir.min_bound())
            open_min = float(min(mins)) if mins else float("inf")
        inc = float(np.asarray(inc_cost).reshape(-1)[0])
        payload["lb_certified"] = np.asarray(
            min(max(float(lb_floor), open_min), inc)
        )
    if reservoir is not None and len(reservoir):
        # pure host-side unpack — the reservoir exists precisely because
        # device memory ran out, so it must never round-trip the device
        res_fields = _unpack_rows_np(
            np.concatenate(reservoir.chunks), n=n_exact
        )
        for f in CKPT_NODE_FIELDS:
            payload[f"res_{f}"] = res_fields[f]
    return payload


def restore(
    path: str, expect_d=None, expect_bound=None, expect_ranks: Optional[int] = None
) -> Tuple[Frontier, jnp.ndarray, jnp.ndarray, "_Reservoir", float]:
    """Load a checkpoint; refuses one written for a different instance or
    (the frontier's carried sums are bound-specific) a different bound.

    ``expect_ranks``: None for a single-device checkpoint, else the mesh
    size a sharded checkpoint must have been written with.

    Returns ``(frontier, inc_cost, inc_tour, reservoir, lb_certified)`` —
    the reservoir is empty unless the checkpoint carried spilled nodes;
    ``lb_certified`` is the saved certified-LB floor (-inf for
    checkpoints predating the key), which resuming solvers clamp their
    reported lower bound to.

    Integrity failures (truncation, checksum mismatch) do NOT raise: the
    store falls back to the newest VALID snapshot in the rotation chain
    (``path.1``, ``path.2``, ...), counting ``HEALTH.fallback_restores``.
    SEMANTIC mismatches (different instance / ranks / bound) still raise —
    those checkpoints are intact, just wrong to resume."""
    import io as _io

    header, payload, _src, _fallbacks = _ckpt_store.read_with_fallback(
        _norm_ckpt_path(path)
    )
    if (
        expect_d is not None
        and header is not None
        and header.get("fingerprint")
        and header["fingerprint"] != _ckpt_store.instance_fingerprint(expect_d)
    ):
        raise ValueError(
            f"checkpoint {path!r} was written for a different instance "
            "(header fingerprint mismatch)"
        )
    z = np.load(_io.BytesIO(payload))
    saved_ranks = int(z["num_ranks"]) if "num_ranks" in z else None
    if saved_ranks != expect_ranks:
        raise ValueError(
            f"checkpoint {path!r} was written for "
            f"{'a single device' if saved_ranks is None else f'{saved_ranks} ranks'}"
            f"; cannot resume with "
            f"{'a single device' if expect_ranks is None else f'{expect_ranks} ranks'}"
        )
    want_mask_dims = 2 if expect_ranks is None else 3
    if z["mask"].ndim != want_mask_dims:
        raise ValueError(
            f"checkpoint {path!r} uses the pre-multi-word mask layout "
            "([F] uint32); it cannot be resumed by this version — rerun "
            "from scratch"
        )
    if expect_d is not None and "d_fingerprint" in z:
        if not np.allclose(z["d_fingerprint"], _d_fingerprint(expect_d)):
            raise ValueError(
                f"checkpoint {path!r} was written for a different instance "
                "(distance-matrix fingerprint mismatch)"
            )
    if expect_bound is not None:
        # checkpoints predating the bound_mode key could only be min-out
        saved = str(z["bound_mode"]) if "bound_mode" in z else "min-out"
        if saved != expect_bound:
            raise ValueError(
                f"checkpoint {path!r} was written with bound={saved!r}; "
                f"resume with the same bound (got {expect_bound!r})"
            )
    fr = Frontier(
        jnp.asarray(_pack_rows_np(*(z[f] for f in CKPT_NODE_FIELDS))),
        jnp.asarray(z["count"]),
        jnp.asarray(z["overflow"]),
    )
    reservoir = _Reservoir()
    if "res_depth" in z:
        reservoir.chunks.append(
            _pack_rows_np(*(z[f"res_{f}"] for f in CKPT_NODE_FIELDS))
        )
    lb = float(z["lb_certified"]) if "lb_certified" in z else -np.inf
    return (
        fr, jnp.asarray(z["inc_cost"]), jnp.asarray(z["inc_tour"]), reservoir,
        lb,
    )

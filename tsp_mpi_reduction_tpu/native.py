"""ctypes binding to the native C++ runtime (``native/libtsp_native.so``).

The native layer (``native/src/``) is the framework's C++ host runtime —
bit-exact instance generator, dense Held-Karp, merge operator, and the
rank-emulated pipeline with the reference's tree-reduction shape. This
module loads it, building it on demand with the in-tree Makefile (g++ is
part of the supported toolchain; no pip deps).

All functions return numpy arrays/python scalars and are cross-checked
against both the goldens and the JAX path in ``tests/test_native.py``.
"""

from __future__ import annotations

import ctypes
import pathlib
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

_NATIVE_DIR = pathlib.Path(__file__).resolve().parent.parent / "native"
_LIB_PATH = _NATIVE_DIR / "libtsp_native.so"
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None

_i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
_f64p = np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS")


def _stale() -> bool:
    if not _LIB_PATH.exists():
        return True
    lib_mtime = _LIB_PATH.stat().st_mtime
    src = list((_NATIVE_DIR / "src").glob("*")) + [_NATIVE_DIR / "Makefile"]
    return any(p.stat().st_mtime > lib_mtime for p in src)


def build(force: bool = False) -> pathlib.Path:
    """Build the shared library if missing or out of date."""
    if force or _stale():
        proc = subprocess.run(
            ["make", "-C", str(_NATIVE_DIR)],
            capture_output=True,
            text=True,
        )
        if proc.returncode != 0:
            raise RuntimeError(
                f"native build failed (exit {proc.returncode}):\n{proc.stderr}"
            )
    return _LIB_PATH


def load() -> ctypes.CDLL:
    """Load (building if needed) and memoize the native library."""
    global _lib
    with _lock:
        if _lib is not None:
            return _lib
        build()
        lib = ctypes.CDLL(str(_LIB_PATH))

        lib.tsp_rand_stream.argtypes = [ctypes.c_uint32, ctypes.c_int64, _i32p]
        lib.tsp_rand_stream.restype = None
        lib.tsp_blocks_per_dim.argtypes = [
            ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32),
        ]
        lib.tsp_blocks_per_dim.restype = None
        lib.tsp_generate.argtypes = [ctypes.c_int32] * 4 + [ctypes.c_uint32, _f64p]
        lib.tsp_generate.restype = ctypes.c_int32
        lib.tsp_distance_matrix.argtypes = [ctypes.c_int32, _f64p, _f64p]
        lib.tsp_distance_matrix.restype = None
        lib.tsp_solve_block.argtypes = [ctypes.c_int32, _f64p, _i32p]
        lib.tsp_solve_block.restype = ctypes.c_double
        lib.tsp_merge_tours.argtypes = [
            _f64p,
            _i32p,
            ctypes.c_int32,
            ctypes.c_double,
            _i32p,
            ctypes.c_int32,
            ctypes.c_double,
            _i32p,
            ctypes.POINTER(ctypes.c_int32),
        ]
        lib.tsp_merge_tours.restype = ctypes.c_double
        lib.tsp_run_pipeline.argtypes = [ctypes.c_int32] * 4 + [
            ctypes.c_uint32,
            ctypes.c_int32,
            ctypes.POINTER(ctypes.c_double),
            _i32p,
            ctypes.POINTER(ctypes.c_int32),
            _f64p,
        ]
        lib.tsp_run_pipeline.restype = ctypes.c_int32

        _lib = lib
        return lib


def rand_stream(seed: int, count: int) -> np.ndarray:
    """First ``count`` glibc ``rand()`` outputs after ``srand(seed)``."""
    out = np.empty(count, np.int32)
    load().tsp_rand_stream(seed, count, out)
    return out


def blocks_per_dim(num_blocks: int) -> Tuple[int, int]:
    if num_blocks < 1:
        raise ValueError(f"num_blocks must be >= 1, got {num_blocks}")
    rows, cols = ctypes.c_int32(), ctypes.c_int32()
    load().tsp_blocks_per_dim(num_blocks, ctypes.byref(rows), ctypes.byref(cols))
    return rows.value, cols.value


def generate(
    num_cities_per_block: int,
    num_blocks: int,
    grid_dim_x: int,
    grid_dim_y: int,
    seed: int = 0,
) -> np.ndarray:
    """Instance coordinates ``[B, n, 2]`` float64, bit-exact vs the oracle."""
    xy = np.empty((num_blocks, num_cities_per_block, 2), np.float64)
    rc = load().tsp_generate(
        num_cities_per_block, num_blocks, grid_dim_x, grid_dim_y, seed,
        xy.reshape(-1),
    )
    if rc:
        raise ValueError("tsp_generate: bad arguments")
    return xy


def solve_block(dist: np.ndarray) -> Tuple[float, np.ndarray]:
    """Exact tour for one block from its ``[n, n]`` distance matrix."""
    dist = np.ascontiguousarray(dist, np.float64)
    n = dist.shape[0]
    tour = np.empty(n + 1, np.int32)
    cost = load().tsp_solve_block(n, dist.reshape(-1), tour)
    if cost < 0:
        raise ValueError(f"tsp_solve_block: unsupported n={n}")
    return float(cost), tour


def merge_tours(
    xy: np.ndarray,
    ids1: np.ndarray,
    cost1: float,
    ids2: np.ndarray,
    cost2: float,
) -> Tuple[float, np.ndarray]:
    """Merge closed tour 2 into tour 1 (global ids, coords ``[N, 2]``)."""
    xy = np.ascontiguousarray(xy, np.float64)
    ids1 = np.ascontiguousarray(ids1, np.int32)
    ids2 = np.ascontiguousarray(ids2, np.int32)
    if len(ids1) < 4 or len(ids2) < 4:
        # closed tour of k cities has length k+1; the merge's rotate-splice
        # needs >= 3 distinct cities per operand (SURVEY.md quirk #6)
        raise ValueError(
            f"both operands need >= 3 cities (closed length >= 4), got "
            f"{len(ids1)} and {len(ids2)}"
        )
    out = np.empty(len(ids1) + len(ids2) - 1, np.int32)
    out_len = ctypes.c_int32()
    cost = load().tsp_merge_tours(
        xy.reshape(-1), ids1, len(ids1), cost1, ids2, len(ids2), cost2,
        out, ctypes.byref(out_len),
    )
    return float(cost), out[: out_len.value]


def run_pipeline(
    num_cities_per_block: int,
    num_blocks: int,
    grid_dim_x: int,
    grid_dim_y: int,
    seed: int = 0,
    ranks: int = 1,
) -> Tuple[float, np.ndarray, np.ndarray]:
    """Full native pipeline. Returns (cost, closed global tour, block costs)."""
    n, nb = num_cities_per_block, num_blocks
    tour = np.empty(nb * n + 1, np.int32)
    block_costs = np.empty(nb, np.float64)
    cost = ctypes.c_double()
    tour_len = ctypes.c_int32()
    rc = load().tsp_run_pipeline(
        n, nb, grid_dim_x, grid_dim_y, seed, ranks,
        ctypes.byref(cost), tour, ctypes.byref(tour_len), block_costs,
    )
    if rc:
        raise ValueError("tsp_run_pipeline: bad arguments")
    return float(cost.value), tour[: tour_len.value], block_costs

"""CLI compat surface, TSPLIB parsing, timing, and reporting."""

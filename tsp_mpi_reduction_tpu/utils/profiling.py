"""Tracing & profiling (SURVEY.md §5 row 1).

The reference's only observability is one wall-clock timer wrapped around
everything including ``MPI_Init`` (tsp.cpp:275-276,360-363). Here every
pipeline reports per-phase seconds (``PipelineResult.phase_seconds``), DP
state/transition counts (the north-star nodes/sec metric), and — via
``device_trace`` — full ``jax.profiler`` traces viewable in TensorBoard /
Perfetto for kernel-level TPU timing, segmented per B&B expansion step by
``obs.tracing.step_annotation`` while a capture is active.

Phase timers optionally MIRROR into the obs metrics registry
(``obs.metrics.REGISTRY``): construct with ``mirror_metric="…"`` and every
accumulated phase also lands as a labeled counter series, so scrapers and
the stats JSON read phases from the same source of truth as every other
signal. Mirroring is skipped under ``TSP_OBS=off``.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Dict, Iterator, Optional


class PhaseTimer:
    """Accumulates wall-clock seconds per named phase.

    >>> timer = PhaseTimer()
    >>> with timer.phase("solve"):
    ...     ...
    >>> timer.seconds  # {"solve": 0.123}

    Re-entering a phase name accumulates (useful across B&B iterations).

    Thread-safe: the serve scheduler's worker thread and its request
    threads record into one shared timer, so the read-modify-write merge
    into ``seconds`` holds a lock (the measurement window itself does not —
    overlapping phases from different threads accumulate independently and
    can legitimately sum past wall-clock time).

    ``mirror_metric``: when set, every :meth:`add` also increments the
    counter series ``<mirror_metric>{phase=<name>}`` in the process-global
    obs registry — the phase table then has registry-backed snapshot/delta
    semantics alongside the local dict.
    """

    def __init__(self, mirror_metric: Optional[str] = None) -> None:
        self.seconds: Dict[str, float] = {}
        self.mirror_metric = mirror_metric
        self._lock = threading.Lock()

    @contextlib.contextmanager
    def phase(self, name: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.add(name, time.perf_counter() - t0)

    def snapshot(self) -> Dict[str, float]:
        """Copy of the phase table taken under the lock — reporting paths
        must use this instead of reading ``.seconds`` while other threads
        :meth:`add` into it (graftflow R9)."""
        with self._lock:
            return dict(self.seconds)

    def add(self, name: str, seconds: float) -> None:
        """Accumulate an externally-measured duration into a phase — for
        costs measured by another layer (the compile cache times its own
        ``lower().compile()`` calls) that still belong in one phase table."""
        with self._lock:
            self.seconds[name] = self.seconds.get(name, 0.0) + seconds
        if self.mirror_metric:
            from ..obs import enabled as _obs_enabled
            from ..obs.metrics import REGISTRY

            if _obs_enabled():
                REGISTRY.inc(self.mirror_metric, max(seconds, 0.0), phase=name)


#: process-global timer for compile/AOT-load costs (perf.compile_cache
#: records into it; drivers fold it into their phase output) — compile
#: seconds are process-scoped, not per-request, so they get one shared
#: accumulator rather than riding any single request's PhaseTimer.
#: perf.compile_cache ADDITIONALLY records per-entry compile seconds into
#: the obs registry (``compile_phase_seconds_total{entry=…, phase=…}``),
#: which is what chunked campaigns read to attribute compile cost per
#: chunk — a destructive "whoever reads the timer first" fold is gone.
COMPILE_TIMER = PhaseTimer(mirror_metric="phase_seconds_total")

#: is a ``device_trace`` capture currently running? (obs.tracing's
#: ``step_annotation`` checks this so per-dispatch StepTraceAnnotations
#: exist exactly when there is a profiler to consume them)
_TRACE_ACTIVE = False


def trace_active() -> bool:
    return _TRACE_ACTIVE


@contextlib.contextmanager
def device_trace(trace_dir: Optional[str]) -> Iterator[None]:
    """``jax.profiler.trace`` scoped to the block; no-op when dir is None.

    The dump is TensorBoard-loadable (``tensorboard --logdir <dir>``) and
    includes XLA kernel timelines on TPU. While the capture is active,
    the B&B host loops wrap every dispatch in ``StepTraceAnnotation`` (via
    ``obs.tracing.step_annotation``), so the timeline segments by
    expansion step.
    """
    global _TRACE_ACTIVE
    if not trace_dir:
        yield
        return
    import jax

    _TRACE_ACTIVE = True
    try:
        with jax.profiler.trace(trace_dir):
            yield
    finally:
        _TRACE_ACTIVE = False

"""Tracing & profiling (SURVEY.md §5 row 1).

The reference's only observability is one wall-clock timer wrapped around
everything including ``MPI_Init`` (tsp.cpp:275-276,360-363). Here every
pipeline reports per-phase seconds (``PipelineResult.phase_seconds``), DP
state/transition counts (the north-star nodes/sec metric), and — via
``device_trace`` — full ``jax.profiler`` traces viewable in TensorBoard /
Perfetto for kernel-level TPU timing.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Dict, Iterator, Optional


class PhaseTimer:
    """Accumulates wall-clock seconds per named phase.

    >>> timer = PhaseTimer()
    >>> with timer.phase("solve"):
    ...     ...
    >>> timer.seconds  # {"solve": 0.123}

    Re-entering a phase name accumulates (useful across B&B iterations).

    Thread-safe: the serve scheduler's worker thread and its request
    threads record into one shared timer, so the read-modify-write merge
    into ``seconds`` holds a lock (the measurement window itself does not —
    overlapping phases from different threads accumulate independently and
    can legitimately sum past wall-clock time).
    """

    def __init__(self) -> None:
        self.seconds: Dict[str, float] = {}
        self._lock = threading.Lock()

    @contextlib.contextmanager
    def phase(self, name: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.add(name, time.perf_counter() - t0)

    def add(self, name: str, seconds: float) -> None:
        """Accumulate an externally-measured duration into a phase — for
        costs measured by another layer (the compile cache times its own
        ``lower().compile()`` calls) that still belong in one phase table."""
        with self._lock:
            self.seconds[name] = self.seconds.get(name, 0.0) + seconds


#: process-global timer for compile/AOT-load costs (perf.compile_cache
#: records into it; drivers fold it into their phase output) — compile
#: seconds are process-scoped, not per-request, so they get one shared
#: accumulator rather than riding any single request's PhaseTimer
COMPILE_TIMER = PhaseTimer()


@contextlib.contextmanager
def device_trace(trace_dir: Optional[str]) -> Iterator[None]:
    """``jax.profiler.trace`` scoped to the block; no-op when dir is None.

    The dump is TensorBoard-loadable (``tensorboard --logdir <dir>``) and
    includes XLA kernel timelines on TPU.
    """
    if not trace_dir:
        yield
        return
    import jax

    with jax.profiler.trace(trace_dir):
        yield

"""TSPLIB95 instance loader (north-star extension, SURVEY.md §7 step 7).

The reference has no file-based instances — its only input is the random
generator (tsp.cpp:373-403). The north star asks for TSPLIB B&B mode
(BASELINE.json configs: burma14, ulysses22, eil51, berlin52, kroA100,
pr124), so this implements the TSPLIB95 format: NODE_COORD_SECTION /
EDGE_WEIGHT_SECTION parsing and the spec's distance functions (EUC_2D,
CEIL_2D, MAX_2D, MAN_2D, GEO, ATT, EXPLICIT full/triangular matrices).

Distance semantics follow the TSPLIB95 spec (integer-valued metrics via
nint/ceil as specified). No instance files ship with this zero-egress
environment except the embedded ``burma14`` fixture below, whose optimum
(3323) is re-derived — not assumed — by the exact solver in tests.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

#: Known optimal tour lengths (TSPLIB95 published results) — used for
#: time-to-optimal reporting, never as inputs to the solver.
KNOWN_OPTIMA: Dict[str, int] = {
    "burma14": 3323,
    "ulysses16": 6859,
    "ulysses22": 7013,
    "gr17": 2085,
    "gr21": 2707,
    "gr24": 1272,
    "fri26": 937,
    "bayg29": 1610,
    "bays29": 2020,
    "dantzig42": 699,
    "att48": 10628,
    "eil51": 426,
    "berlin52": 7542,
    "st70": 675,
    "eil76": 538,
    "kroA100": 21282,
    "kroB100": 22141,
    "pr124": 59030,
}


@dataclass
class TSPLIBInstance:
    name: str
    dimension: int
    edge_weight_type: str
    comment: str = ""
    coords: Optional[np.ndarray] = None  # [n, 2] raw file coordinates
    matrix: Optional[np.ndarray] = None  # explicit weights, if given
    meta: Dict[str, str] = field(default_factory=dict)

    def distance_matrix(self) -> np.ndarray:
        """Dense [n, n] integer distance matrix per the TSPLIB95 metric."""
        if self.matrix is not None:
            return self.matrix.astype(np.int64)
        if self.coords is None:
            raise ValueError(f"{self.name}: no coords and no explicit matrix")
        fn = _METRICS.get(self.edge_weight_type)
        if fn is None:
            raise ValueError(f"unsupported EDGE_WEIGHT_TYPE {self.edge_weight_type}")
        return fn(self.coords)

    @property
    def known_optimum(self) -> Optional[int]:
        return KNOWN_OPTIMA.get(self.name)


def _nint(x: np.ndarray) -> np.ndarray:
    return np.floor(x + 0.5).astype(np.int64)


def _euc_2d(c: np.ndarray) -> np.ndarray:
    d = c[:, None, :] - c[None, :, :]
    return _nint(np.sqrt((d * d).sum(-1)))


def _ceil_2d(c: np.ndarray) -> np.ndarray:
    d = c[:, None, :] - c[None, :, :]
    return np.ceil(np.sqrt((d * d).sum(-1))).astype(np.int64)


def _max_2d(c: np.ndarray) -> np.ndarray:
    d = np.abs(c[:, None, :] - c[None, :, :])
    return np.maximum(_nint(d[..., 0]), _nint(d[..., 1]))


def _man_2d(c: np.ndarray) -> np.ndarray:
    d = np.abs(c[:, None, :] - c[None, :, :])
    return _nint(d.sum(-1))


def _att(c: np.ndarray) -> np.ndarray:
    d = c[:, None, :] - c[None, :, :]
    r = np.sqrt((d * d).sum(-1) / 10.0)
    t = _nint(r)
    return np.where(t < r, t + 1, t).astype(np.int64)


def _geo(c: np.ndarray) -> np.ndarray:
    # TSPLIB95: coordinates are DDD.MM (degrees.minutes)
    pi = 3.141592
    deg = np.trunc(c)
    minutes = c - deg
    rad = pi * (deg + 5.0 * minutes / 3.0) / 180.0
    lat, lon = rad[:, 0], rad[:, 1]
    rrr = 6378.388
    q1 = np.cos(lon[:, None] - lon[None, :])
    q2 = np.cos(lat[:, None] - lat[None, :])
    q3 = np.cos(lat[:, None] + lat[None, :])
    arg = np.clip(0.5 * ((1.0 + q1) * q2 - (1.0 - q1) * q3), -1.0, 1.0)
    m = (rrr * np.arccos(arg) + 1.0).astype(np.int64)
    np.fill_diagonal(m, 0)  # the formula yields int(0 + 1.0) = 1 on i == j
    return m


_METRICS = {
    "EUC_2D": _euc_2d,
    "CEIL_2D": _ceil_2d,
    "MAX_2D": _max_2d,
    "MAN_2D": _man_2d,
    "ATT": _att,
    "GEO": _geo,
}


def parse(text: str) -> TSPLIBInstance:
    """Parse a .tsp file's contents."""
    meta: Dict[str, str] = {}
    lines = [ln.strip() for ln in text.splitlines()]
    i = 0
    coords = None
    weights: List[float] = []
    while i < len(lines):
        ln = lines[i]
        if not ln or ln == "EOF":
            i += 1
            continue
        if ":" in ln and not ln.split(":")[0].strip().endswith("SECTION"):
            key, _, val = ln.partition(":")
            meta[key.strip().upper()] = val.strip()
            i += 1
            continue
        section = ln.split(":")[0].strip().upper()
        if section == "NODE_COORD_SECTION" or section == "DISPLAY_DATA_SECTION":
            n = int(meta["DIMENSION"])
            rows = []
            for j in range(n):
                parts = lines[i + 1 + j].split()
                rows.append((float(parts[1]), float(parts[2])))
            if section == "NODE_COORD_SECTION":
                coords = np.asarray(rows, dtype=np.float64)
            i += n + 1
            continue
        if section == "EDGE_WEIGHT_SECTION":
            i += 1
            while i < len(lines) and lines[i] and not lines[i][0].isalpha():
                weights.extend(float(x) for x in lines[i].split())
                i += 1
            continue
        i += 1  # unknown section/keyword lines are skipped

    n = int(meta["DIMENSION"])
    ewt = meta.get("EDGE_WEIGHT_TYPE", "EUC_2D").upper()
    matrix = None
    if ewt == "EXPLICIT":
        matrix = _assemble_matrix(
            np.asarray(weights), n, meta.get("EDGE_WEIGHT_FORMAT", "FULL_MATRIX").upper()
        )
    return TSPLIBInstance(
        name=meta.get("NAME", "unnamed"),
        dimension=n,
        edge_weight_type=ewt,
        comment=meta.get("COMMENT", ""),
        coords=coords,
        matrix=matrix,
        meta=meta,
    )


def _assemble_matrix(w: np.ndarray, n: int, fmt: str) -> np.ndarray:
    m = np.zeros((n, n), dtype=np.int64)
    wi = iter(w.astype(np.int64))
    if fmt == "FULL_MATRIX":
        m = w.astype(np.int64).reshape(n, n)
    elif fmt in ("UPPER_ROW", "UPPER_DIAG_ROW"):
        diag = fmt == "UPPER_DIAG_ROW"
        for r in range(n):
            for c in range(r if diag else r + 1, n):
                m[r, c] = next(wi)
        m = m + m.T - np.diag(np.diag(m))
    elif fmt in ("LOWER_ROW", "LOWER_DIAG_ROW"):
        diag = fmt == "LOWER_DIAG_ROW"
        for r in range(n):
            for c in range(0, (r + 1) if diag else r):
                m[r, c] = next(wi)
        m = m + m.T - np.diag(np.diag(m))
    else:
        raise ValueError(f"unsupported EDGE_WEIGHT_FORMAT {fmt}")
    return m


def load(path) -> TSPLIBInstance:
    with open(path) as f:
        return parse(f.read())


# --- embedded fixture: burma14 (smallest classic GEO instance) ---
# 14 Burmese cities, optimum 3323; the only instance small enough to embed
# from public knowledge and self-validate via the exact solver in tests.
BURMA14 = """NAME: burma14
TYPE: TSP
COMMENT: 14-Staedte in Burma (Zaw Win)
DIMENSION: 14
EDGE_WEIGHT_TYPE: GEO
EDGE_WEIGHT_FORMAT: FUNCTION
DISPLAY_DATA_TYPE: COORD_DISPLAY
NODE_COORD_SECTION
   1  16.47       96.10
   2  16.47       94.44
   3  20.09       92.54
   4  22.39       93.37
   5  25.23       97.24
   6  22.00       96.05
   7  20.47       97.02
   8  17.20       96.29
   9  16.30       97.38
  10  14.05       98.12
  11  16.53       97.38
  12  21.52       95.59
  13  19.41       97.13
  14  20.09       94.55
EOF
"""


def burma14() -> TSPLIBInstance:
    return parse(BURMA14)


def embedded(name: str) -> TSPLIBInstance:
    """Load an embedded instance by TSPLIB name (see utils.tsplib_data).

    Every embedded coordinate set is validated against its published
    optimum by tests/test_tsplib.py (exact proof or bound bracketing) —
    see the tsplib_data module docstring.
    """
    try:
        return parse(EMBEDDED[name])
    except KeyError:
        raise KeyError(
            f"no embedded instance {name!r}; available: {sorted(EMBEDDED)}"
        ) from None


def resolve_instance(spec: str) -> TSPLIBInstance:
    """One instance-spec resolver for every driver (``tools/bnb_solve.py``,
    ``tools/bnb_chunked.py``): an embedded name, a ``random:N[:SEED]``
    synthetic spec, or a TSPLIB file path. Raises ValueError for a
    malformed random spec, OSError for an unreadable path — callers turn
    both into usage errors. Critically, the SAME resolver in the chunk
    driver and the chunk solver means fingerprint pre-flight checks
    (resilience.checkpoint) compare byte-identical distance matrices."""
    if spec in EMBEDDED:
        return embedded(spec)
    if spec.startswith("random:"):
        parts = spec.split(":")
        try:
            n_cities = int(parts[1])
            seed = int(parts[2]) if len(parts) > 2 else 0
        except (ValueError, IndexError):
            raise ValueError(
                f"bad random instance spec {spec!r}: want random:N[:SEED]"
            ) from None
        if n_cities < 3:
            raise ValueError(f"bad random instance spec {spec!r}: need at least 3 cities")
        rng = np.random.default_rng(seed)
        xy = rng.uniform(0, 1000, (n_cities, 2))
        return TSPLIBInstance(
            name=f"random{n_cities}s{seed}",
            dimension=n_cities,
            edge_weight_type="EUC_2D",
            comment=f"uniform random {n_cities} cities, seed {seed}",
            coords=xy,
        )
    return load(spec)


def _ulysses16_text() -> str:
    """ulysses16 is, by TSPLIB construction, the first 16 ulysses22 cities."""
    from . import tsplib_data

    lines = tsplib_data.ULYSSES22.splitlines()
    head = [
        "NAME: ulysses16",
        "TYPE: TSP",
        "COMMENT: Odyssey of Ulysses (Groetschel/Padberg)",
        "DIMENSION: 16",
        "EDGE_WEIGHT_TYPE: GEO",
        "NODE_COORD_SECTION",
    ]
    coords = [ln for ln in lines if ln.strip() and ln.strip()[0].isdigit()][:16]
    return "\n".join(head + coords + ["EOF", ""])


def _build_embedded() -> Dict[str, str]:
    from . import tsplib_data

    return {
        "burma14": BURMA14,
        "ulysses16": _ulysses16_text(),
        "ulysses22": tsplib_data.ULYSSES22,
        "eil51": tsplib_data.EIL51,
        "berlin52": tsplib_data.BERLIN52,
        "kroA100": tsplib_data.KROA100,
    }


EMBEDDED: Dict[str, str] = _build_embedded()

"""Backend selection: the ``--backend={cpu,tpu}`` dispatch surface.

The north star keeps the reference's argv contract and adds a backend
switch. ``cpu`` forces the host platform (and, in images where a remote-TPU
plugin is pre-registered at interpreter startup, deregisters it so no jax op
can hang on an accelerator tunnel); ``tpu`` requires an accelerator
platform; ``auto`` prefers the accelerator when present.
"""

from __future__ import annotations

import jax

ACCELERATOR_PLATFORMS = ("tpu", "axon")


def _registered_platforms() -> set:
    from jax._src import xla_bridge as xb

    return set(xb._backend_factories.keys())


def select_backend(name: str = "auto") -> str:
    """Pin the jax platform. Returns the chosen platform name.

    Must run before the first jax array op of the process.
    """
    name = name.lower()
    regs = _registered_platforms()
    accel = [p for p in ACCELERATOR_PLATFORMS if p in regs]
    if name == "auto":
        name = "tpu" if accel else "cpu"
    if name == "cpu":
        jax.config.update("jax_platforms", "cpu")
        from jax._src import xla_bridge as xb

        for p in ACCELERATOR_PLATFORMS:  # never dial a tunnel from CPU mode
            xb._backend_factories.pop(p, None)
        return "cpu"
    if name == "tpu":
        if not accel:
            raise RuntimeError("no TPU platform registered in this process")
        jax.config.update("jax_platforms", ",".join(accel))
        return "tpu"
    raise ValueError(f"unknown backend {name!r} (expected cpu|tpu|auto)")

"""Backend selection: the ``--backend={cpu,tpu}`` dispatch surface.

The north star keeps the reference's argv contract and adds a backend
switch. ``cpu`` forces the host platform (and, in images where a remote-TPU
plugin is pre-registered at interpreter startup, deregisters it so no jax op
can hang on an accelerator tunnel); ``tpu`` requires an accelerator
platform; ``auto`` prefers the accelerator when present.
"""

from __future__ import annotations

import jax

try:  # jax >= 0.5 re-exports shard_map at the top level
    from jax import shard_map  # noqa: F401  (compat re-export)
except ImportError:  # older jax: experimental home, check_rep still on
    from jax.experimental.shard_map import shard_map as _shard_map_impl

    def shard_map(f, /, **kwargs):  # noqa: F401  (compat re-export)
        # the experimental version's check_rep=True has no replication
        # rule for lax.while_loop (used by the 2-opt sweep bodies); the
        # top-level export this repo targets doesn't check, so match it
        kwargs.setdefault("check_rep", False)
        return _shard_map_impl(f, **kwargs)

def pcast_varying(x, axis_name: str):
    """Mark a cross-rank-invariant value as varying over ``axis_name``.

    ``jax.lax.pcast(..., to="varying")`` only exists on jax builds with
    varying-manual-axes (VMA) tracking; older builds don't track VMA under
    ``check_rep=False`` shard_map, so the cast is the identity there.
    """
    pcast = getattr(jax.lax, "pcast", None)
    if pcast is None:
        return x
    return pcast(x, axis_name, to="varying")


ACCELERATOR_PLATFORMS = ("tpu", "axon")
#: Out-of-tree remote plugins whose factory init dials a network tunnel (and
#: can hang). Builtin platforms ("tpu") must never be deregistered: jax's
#: MLIR lowering registry treats registered factories as the known-platform
#: set, so popping "tpu" breaks pallas/checkify imports.
REMOTE_PLATFORMS = ("axon",)


def _registered_platforms() -> set:
    from jax._src import xla_bridge as xb

    return set(xb._backend_factories.keys())


#: code run by the accelerator probe subprocess (module-level so tests can
#: substitute a mock hang); must print the platform of the first device
_PROBE_CODE = (
    "import jax; d = jax.devices(); print('PLATFORM=' + d[0].platform)"
)


def accelerator_usable(timeout_s: float | None = None) -> bool:
    """Probe accelerator init in a subprocess (it can hang on a dead tunnel).

    The remote-TPU ("axon") backend's first client creation performs a
    claim/grant handshake that blocks INDEFINITELY when no chip is currently
    granted to this container (round-4 claim log: >4 h of retries, each
    hanging past any patience) — and jax gives the caller no timeout hook.
    A subprocess probe turns that hang into a bounded, clean failure; the
    probe's exit releases its claim so the caller can immediately take it.
    True only if a non-CPU backend actually initialized in the subprocess.

    Timeout: ``timeout_s`` arg, else ``TSP_BACKEND_PROBE_TIMEOUT`` env,
    else 180 s. ``TSP_BACKEND_PROBED=1`` skips the probe entirely (set by a
    parent that already probed — each probe costs a jax import).
    """
    import os
    import subprocess
    import sys

    if os.environ.get("TSP_BACKEND_PROBED") == "1":
        return True
    if timeout_s is None:
        timeout_s = float(os.environ.get("TSP_BACKEND_PROBE_TIMEOUT", "180"))
    try:
        r = subprocess.run(
            [sys.executable, "-c", _PROBE_CODE],
            timeout=timeout_s,
            capture_output=True,
            text=True,
        )
    except subprocess.TimeoutExpired:
        print(
            f"backend: accelerator init timed out after {timeout_s:.0f}s "
            "(claim/grant handshake never completed)",
            file=sys.stderr,
        )
        return False
    platform = ""
    for line in r.stdout.splitlines():
        if line.startswith("PLATFORM="):
            platform = line[len("PLATFORM="):].strip()
    if r.returncode == 0 and platform and platform != "cpu":
        os.environ["TSP_BACKEND_PROBED"] = "1"  # children skip the re-probe
        return True
    if r.returncode == 0:
        print(
            f"backend: accelerator probe found only {platform or 'no'} "
            "devices", file=sys.stderr,
        )
    else:
        print(
            f"backend: accelerator probe exited rc={r.returncode}: "
            f"{(r.stderr or r.stdout).strip()[-300:]}",
            file=sys.stderr,
        )
    return False


def force_host_platform(n_devices: int | None = None) -> None:
    """Pin this process to the CPU platform, optionally with ``n_devices``
    virtual devices (``--xla_force_host_platform_device_count``).

    In this image, sitecustomize imports jax at interpreter startup with a
    remote-TPU ("axon") plugin, so caller env edits are read too late; this
    forces the platform through jax.config (still honored post-import,
    pre-backend-init) and deregisters accelerator factories so no jax op can
    dial the tunnel. Must run before the first jax array op of the process.
    """
    import os

    if n_devices is not None:
        # Drop any existing count rather than relying on append-wins: a stale
        # `=2` inherited from the environment must not shadow the request.
        flags = [
            f
            for f in os.environ.get("XLA_FLAGS", "").split()
            if not f.startswith("--xla_force_host_platform_device_count")
        ]
        flags.append(f"--xla_force_host_platform_device_count={n_devices}")
        os.environ["XLA_FLAGS"] = " ".join(flags)
    os.environ["JAX_PLATFORMS"] = "cpu"
    jax.config.update("jax_platforms", "cpu")
    from jax._src import xla_bridge as xb

    for p in REMOTE_PLATFORMS:  # never dial a tunnel from CPU mode
        xb._backend_factories.pop(p, None)
    # Mirror select_backend's phantom check: a backend cached before this
    # call wins over every edit above, so pinning "cpu" now would be a lie.
    if xb._backends:
        devs = jax.devices()
        if devs and devs[0].platform != "cpu":
            raise RuntimeError(
                f"jax backend already initialized to {devs[0].platform!r}; "
                "force_host_platform must run before the first jax array op"
            )


def select_backend(name: str = "auto") -> str:
    """Pin the jax platform. Returns the chosen platform name.

    Must run before the first jax array op of the process.
    """
    name = name.lower()
    regs = _registered_platforms()
    accel = [p for p in ACCELERATOR_PLATFORMS if p in regs]
    if name == "auto":
        import os

        if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
            # the caller's environment pinned CPU explicitly — don't let a
            # merely-registered (possibly uninitializable) accelerator
            # plugin override that pin
            name = "cpu"
        elif not accel:
            name = "cpu"
        elif any(p in accel for p in REMOTE_PLATFORMS) and not accelerator_usable():
            # a registered remote plugin whose chip grant is dead hangs
            # jax.devices() forever (VERDICT r4 weak #1: bnb_solve sat
            # >300 s on --backend=auto); the bounded subprocess probe
            # downgrades that to a clean CPU fallback
            import sys

            print(
                "backend: no usable accelerator; falling back to CPU",
                file=sys.stderr,
            )
            name = "cpu"
        else:
            name = "tpu"
    if name == "cpu":
        force_host_platform()
        return "cpu"
    if name == "tpu":
        if not accel:
            raise RuntimeError("no TPU platform registered in this process")
        if any(p in accel for p in REMOTE_PLATFORMS) and not accelerator_usable():
            # don't enter the in-process candidate loop: a dead remote
            # grant would hang jax.devices() with no way to time out
            raise RuntimeError(
                "no accelerator platform initialized: the remote-TPU probe "
                "timed out or found no non-CPU devices (chip grant dead?); "
                "use --backend=cpu or retry when the chip is granted"
            )
        # A platform can be registered yet fail to initialize (e.g. the stock
        # "tpu" plugin in images where the chip is reachable only through the
        # remote "axon" plugin) — and jax does not fall through on a hard
        # plugin-init error. Probe candidates until one actually yields
        # devices, preferring the environment's own pin.
        import os

        env = os.environ.get("JAX_PLATFORMS", "")
        candidates = [env] if env in accel else []
        candidates += [p for p in accel if p not in candidates]
        prev_platforms = jax.config.jax_platforms
        last_err: Exception | None = None
        for p in candidates:
            # keep the CPU backend available alongside the accelerator
            # (first entry = default platform): host-pinned compute like
            # the incumbent polish needs jax.local_devices(backend="cpu"),
            # which raises if jax_platforms filtered CPU out at init
            jax.config.update("jax_platforms", f"{p},cpu")
            try:
                devs = jax.devices()
            except Exception as e:  # plugin registered but chip unreachable
                last_err = e
                continue
            # jax caches the first-initialized backend: if this process
            # already ran on CPU, devices() "succeeds" with CPU devices no
            # matter what jax_platforms says. Don't report a phantom TPU.
            if devs and devs[0].platform != "cpu":
                return "tpu"
            last_err = RuntimeError(
                "jax backend already initialized to CPU in this process; "
                "select the backend before the first jax array op"
            )
            break
        jax.config.update("jax_platforms", prev_platforms)
        raise RuntimeError(
            f"no accelerator platform initialized (tried {candidates}): {last_err}"
        )
    raise ValueError(f"unknown backend {name!r} (expected cpu|tpu|auto)")


def cpu_fallback_device():
    """The CPU backend's first device, or None if this process's platform
    pin excluded CPU and backends are already initialized.

    Called BEFORE the first jax array op, it can still widen the platform
    list (``jax_platforms`` is only consumed at backend init), so callers
    that want host-pinned compute should acquire the device early.
    """
    try:
        return jax.local_devices(backend="cpu")[0]
    except RuntimeError:
        pass
    try:
        # private API (jax._src.xla_bridge._backends): a jax upgrade may
        # rename it — degrade to the numpy-incumbent fallback, not a crash
        from jax._src import xla_bridge as xb

        backends_inited = bool(xb._backends)
    except (ImportError, AttributeError):
        return None

    cur = jax.config.jax_platforms
    if cur and "cpu" not in str(cur).split(",") and not backends_inited:
        try:
            jax.config.update("jax_platforms", f"{cur},cpu")
            return jax.local_devices(backend="cpu")[0]
        except RuntimeError:
            jax.config.update("jax_platforms", cur)
    return None


def enable_persistent_cache(platform: str) -> None:
    """Point jax at the shared on-disk compilation cache — now a thin
    shim over ``perf.compile_cache.enable`` (the compile-once subsystem:
    repo-managed dir via ``TSP_COMPILE_CACHE``, AOT executable store,
    hit/miss counters). Enabled on CPU too: reload was measured 13x
    faster than the cold ``_expand_loop`` compile, and the chunk relay
    re-pays the compile per process precisely on CPU fallbacks."""
    from ..perf import compile_cache

    compile_cache.enable(platform)

"""CLI: the reference's argv contract plus backend/rank/metrics flags.

Compatibility surface (SURVEY.md §7 step 6):

- same 4 positional ints: ``numCitiesPerBlock numBlocks gridDimX gridDimY``
  (tsp.cpp:282-288);
- wrong arity -> same usage string, exit 1 (tsp.cpp:280-284);
- ``numCitiesPerBlock > 16`` -> same scold message, ``exit(1337)``
  (tsp.cpp:289-295; observed as status 57 = 1337 & 0xFF — same here);
- stdout: banner line, dims line, and the machine-parsed final line
  ``TSP ran in <ms> ms for <n> cities and the trip cost <cost>``
  (tsp.cpp:307,377,363) so ``test.sh``-style scrapers work unchanged.

Extensions (flags, all optional):
  --backend={auto,cpu,tpu}   device dispatch (north-star ``--backend`` flag)
  --ranks=P                  emulate a P-rank MPI run (same merge tree)
  --dtype={float64,float32}  parity vs speed mode
  --metrics                  print structured JSON metrics line to stderr
  --seed=S                   instance seed (reference hardwires srand(0))

Deviations: the timer starts at CLI entry rather than before MPI_Init
(tsp.cpp:276 — there is no MPI to init); degenerate blocks (n < 3) exit 2
with a clear error instead of the reference's sentinel cost / infinite loop
(SURVEY.md quirk #6).
"""

from __future__ import annotations

import argparse
import sys

import numpy as np
import time
from typing import List, Optional

from . import reporting
from .backend import select_backend


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tsp-tpu",
        usage=reporting.usage_line(),
        add_help=True,
        description="TPU-native blocked TSP solver (JZHeadley/TSP-MPI-Reduction capabilities)",
    )
    p.add_argument("numCitiesPerBlock", type=int)
    p.add_argument("numBlocks", type=int)
    p.add_argument("gridDimX", type=int)
    p.add_argument("gridDimY", type=int)
    p.add_argument(
        "--backend", default="auto", choices=["auto", "cpu", "tpu", "native"]
    )
    p.add_argument("--ranks", type=int, default=1, metavar="P")
    p.add_argument("--dtype", default=None, choices=["float64", "float32"])
    p.add_argument("--metrics", action="store_true")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument(
        "--trace",
        default=None,
        metavar="DIR",
        help="dump a jax.profiler trace (TensorBoard/Perfetto) to DIR",
    )
    p.add_argument(
        "--improve",
        action="store_true",
        help="polish the merged tour with device 2-opt and report its TRUE "
        "re-measured cost (a deliberate deviation from the reference's "
        "formulaic merge cost, SURVEY.md quirk #4)",
    )
    p.add_argument(
        "--compat-bugs",
        action="store_true",
        help="byte-parity bug emulation for --ranks > 1: replicate the "
        "reference's reduce-side path-accumulation corruption (SURVEY.md "
        "quirk #5) so the printed cost matches a real p-rank MPI run of "
        "the unmodified reference",
    )
    return p


def _emit_result(
    args,
    *,
    backend: str,
    dtype: str,
    cost: float,
    num_cities: int,
    t_start: float,
    phase_seconds=None,
    dp_states: int = 0,
    dp_transitions: int = 0,
) -> None:
    """Shared epilogue: the machine-parsed final line + optional metrics."""
    elapsed_ms = int((time.perf_counter() - t_start) * 1000)
    print(reporting.final_line(elapsed_ms, num_cities, cost))
    if args.metrics:
        print(
            reporting.metrics_json(
                config={
                    "numCitiesPerBlock": args.numCitiesPerBlock,
                    "numBlocks": args.numBlocks,
                    "gridDimX": args.gridDimX,
                    "gridDimY": args.gridDimY,
                    "ranks": args.ranks,
                    "backend": backend,
                    "dtype": dtype,
                },
                elapsed_ms=elapsed_ms,
                cost=cost,
                phase_seconds=phase_seconds,
                dp_states=dp_states,
                dp_transitions=dp_transitions,
            ),
            file=sys.stderr,
        )


def main(argv: Optional[List[str]] = None) -> int:
    t_start = time.perf_counter()
    argv = list(sys.argv[1:] if argv is None else argv)

    if argv and argv[0] == "serve":
        # serving mode (README "Serving"): JSONL requests in, JSONL
        # responses out. Dispatched before the reference argv contract —
        # "serve" can never collide with the 4-positional-ints surface.
        from ..serve.service import serve_cli

        return serve_cli(argv[1:])

    if argv and argv[0] == "fleet":
        # fleet mode (README "Fleet serving"): a front process
        # dispatching the same JSONL contract across N supervised serve
        # replica subprocesses over a shared cache tier. Same
        # non-colliding dispatch as "serve".
        from ..fleet.front import fleet_cli

        return fleet_cli(argv[1:])

    try:
        args = build_parser().parse_args(argv)
    except SystemExit as e:
        if e.code in (0, None):  # -h/--help
            return 0
        # same behavior as the reference's arity check (tsp.cpp:280-284)
        print(reporting.usage_line())
        return 1

    if args.numCitiesPerBlock > 16:
        print(reporting.too_many_cities_line())
        sys.exit(1337)  # truncated by the OS to 57, as the reference's is

    n, nb = args.numCitiesPerBlock, args.numBlocks
    if args.backend == "native":
        # pure C++ host path (native/): no jax import, double precision only
        if args.improve:
            print(
                "error: --improve needs a jax backend (not --backend=native)",
                file=sys.stderr,
            )
            return 2
        if args.trace:
            print(
                "error: --trace needs a jax backend (not --backend=native)",
                file=sys.stderr,
            )
            return 2
        if args.dtype == "float32":
            print(
                "error: --backend=native runs float64 only (drop --dtype)",
                file=sys.stderr,
            )
            return 2
        if args.compat_bugs:
            print(
                "error: --compat-bugs is implemented by the jax rank "
                "emulation (use --backend=cpu/tpu)",
                file=sys.stderr,
            )
            return 2
        from .. import native

        print(reporting.banner_line(n, nb))
        rows, cols = native.blocks_per_dim(nb)
        print(reporting.dims_line(rows, cols))
        try:
            cost, tour, _ = native.run_pipeline(
                n, nb, args.gridDimX, args.gridDimY, seed=args.seed,
                ranks=args.ranks,
            )
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        _emit_result(
            args, backend="native", dtype="float64", cost=cost,
            num_cities=nb * n, t_start=t_start,
        )
        return 0

    platform = select_backend(args.backend)
    dtype = args.dtype or ("float64" if platform == "cpu" else "float32")
    import jax

    if dtype == "float64":
        jax.config.update("jax_enable_x64", True)
    from .backend import enable_persistent_cache

    enable_persistent_cache(platform)

    from ..models.distributed import run_pipeline_ranks
    from ..models.pipeline import run_pipeline
    from ..ops.generator import get_blocks_per_dim

    print(reporting.banner_line(n, nb))
    rows, cols = get_blocks_per_dim(nb)
    print(reporting.dims_line(rows, cols))

    from .profiling import device_trace

    try:
        with device_trace(args.trace):
            if args.ranks > 1:
                res = run_pipeline_ranks(
                    n, nb, args.gridDimX, args.gridDimY, args.ranks,
                    seed=args.seed, dtype=dtype, compat_bugs=args.compat_bugs,
                )
            else:
                res = run_pipeline(
                    n, nb, args.gridDimX, args.gridDimY,
                    seed=args.seed, dtype=dtype,
                )
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    if args.improve:
        import jax.numpy as jnp

        from ..parallel.seq_improve import improve_tour

        mesh = None
        if args.ranks > 1 and len(jax.devices()) >= args.ranks:
            # real multi-device run: polish with the ring improver
            from ..parallel.mesh import make_rank_mesh

            mesh = make_rank_mesh(args.ranks)
        order = jnp.asarray(res.tour_ids[:-1], jnp.int32)
        new_order, true_len = improve_tour(order, res.dist.astype(dtype), mesh)
        new_open = np.asarray(new_order)
        res.tour_ids = np.concatenate([new_open, new_open[:1]])  # keep closed
        res.cost = float(true_len)

    _emit_result(
        args, backend=platform, dtype=dtype, cost=res.cost,
        num_cities=res.num_cities, t_start=t_start,
        phase_seconds=res.phase_seconds, dp_states=res.dp_states,
        dp_transitions=res.dp_transitions,
    )
    return 0

"""Output compatibility surface + structured metrics.

The reference's final stdout line IS its machine interface — ``test.sh:16-17``
scrapes cost and time from it with grep. Byte-compatible formatting here:

- banner:    ``We have %i cities for each of our %i blocks`` (tsp.cpp:307)
- dims line: ``%i blocks in X %i in Y``                      (tsp.cpp:377)
- final:     ``TSP ran in %llu ms for %lu cities and the trip cost %f``
                                                              (tsp.cpp:363)

Alongside the compat lines, runs can emit structured JSON/CSV metrics —
the observability layer the reference lacks (SURVEY.md §5 row 5).
"""

from __future__ import annotations

import json
from typing import Dict, Optional


def banner_line(num_cities_per_block: int, num_blocks: int) -> str:
    return f"We have {num_cities_per_block} cities for each of our {num_blocks} blocks"


def dims_line(rows: int, cols: int) -> str:
    return f"{rows} blocks in X {cols} in Y"


def final_line(elapsed_ms: int, num_cities: int, cost: float) -> str:
    # printf "%f" == fixed 6 decimals
    return f"TSP ran in {elapsed_ms} ms for {num_cities} cities and the trip cost {cost:f}"


def usage_line() -> str:
    # argv[0]-independent replica of tsp.cpp:282
    return "Usage:  ./tsp numCitiesPerBlock numBlocks gridDimX gridDimY"


def too_many_cities_line() -> str:
    return (
        "Come on... We don't want to wait forever so lets just have you "
        "retry that with less than 16 cities per block..."
    )


CSV_HEADER = "numCities,numBlocks,numProcs,time,cost"  # test.sh:4


def csv_row(num_cities: int, num_blocks: int, num_procs: int, time_ms: int, cost: float) -> str:
    return f"{num_cities},{num_blocks},{num_procs},{time_ms},{cost:f}"


def metrics_json(
    *,
    config: Dict,
    elapsed_ms: float,
    cost: float,
    phase_seconds: Optional[Dict[str, float]] = None,
    dp_states: int = 0,
    dp_transitions: int = 0,
) -> str:
    payload = {
        "config": config,
        "elapsed_ms": elapsed_ms,
        "cost": cost,
        "phases_s": phase_seconds or {},
        "dp_states": dp_states,
        "dp_transitions": dp_transitions,
    }
    if elapsed_ms > 0 and dp_transitions:
        payload["dp_transitions_per_sec"] = dp_transitions / (elapsed_ms / 1000.0)
    return json.dumps(payload)


def obs_block(
    *,
    trace_path: Optional[str] = None,
    metrics_port: Optional[int] = None,
) -> Dict:
    """The ``obs`` sub-block shared by driver and serve stats JSON: where
    the telemetry layer is writing (trace sink, metrics endpoint), whether
    the optional-overhead half is enabled, and the per-entry compile phase
    attribution read back from the metrics registry — the single source
    of truth the bespoke builders now assemble FROM (ISSUE 6)."""
    from ..obs import costs as obs_costs
    from ..obs import enabled as obs_enabled
    from ..perf.compile_cache import compile_phase_seconds

    return {
        "enabled": obs_enabled(),
        "trace": trace_path,
        "metrics_port": metrics_port,
        "compile_phases_s": compile_phase_seconds(),
        # XLA cost attribution per compiled hot entry (obs.costs): flops,
        # bytes accessed, peak memory, arithmetic intensity + roofline
        # utilization estimate vs the per-backend peak table — captured
        # at compile/AOT-load time, memoized on disk for warm processes
        "device_costs": obs_costs.device_costs_block(),
    }


def service_stats_json(
    *,
    responses: int,
    errors: int,
    deadline_misses: int,
    tier_counts: Dict[str, int],
    cache: Dict[str, int],
    scheduler: Dict[str, float],
    phases_s: Optional[Dict[str, float]] = None,
    refreshes: int = 0,
    rung_failures: Optional[Dict[str, int]] = None,
    health: Optional[Dict] = None,
    compile_cache: Optional[Dict] = None,
    slo: Optional[Dict] = None,
    obs: Optional[Dict] = None,
    admission: Optional[Dict] = None,
) -> str:
    """Machine-readable serve-layer counters (SpillStats-style): per-tier
    answer counts, cache hit/miss/eviction totals plus the derived hit
    rate, the scheduler's batching evidence (queue-depth high-water
    mark, batch occupancy, flush causes), the self-healing ``health``
    block (worker restarts, absorbed retries, fallback restores, injected
    faults — see ``resilience.health``), and the compile-once evidence
    (``compile_cache``: AOT store hits/misses, compile seconds paid vs
    saved, canonicalization sorts skipped — see ``perf.compile_cache``).
    One JSON line so log scrapers and the serve bench consume it the
    same way as ``metrics_json``."""
    lookups = cache.get("hits", 0) + cache.get("misses", 0)
    payload = {
        "responses": responses,
        "errors": errors,
        "deadline_misses": deadline_misses,
        "refreshes": refreshes,
        "rung_failures": rung_failures or {},
        "tiers": tier_counts,
        "cache": dict(cache, hit_rate=(cache.get("hits", 0) / lookups) if lookups else 0.0),
        "scheduler": scheduler,
        "phases_s": phases_s or {},
        "health": health or {},
        "compile_cache": compile_cache or {},
        # per-tier latency SLO verdicts (obs.slo): session-window
        # attainment vs each tier's objective + error-budget burn rate
        "slo": slo or {},
        # iteration-level admission control (ISSUE 13): live per-tier
        # burn-rate snapshot, sheds/preemptions/resumes it caused, and
        # queue-age percentiles (how long work waited for a device gap)
        "admission": admission or {},
        "obs": obs or {},
    }
    return json.dumps(payload)


def fleet_stats_json(
    *,
    responses: int,
    errors: int,
    deadline_misses: int,
    tier_counts: Dict[str, int],
    fleet: Dict,
    cache: Dict,
    health: Optional[Dict] = None,
    slo: Optional[Dict] = None,
    obs: Optional[Dict] = None,
) -> str:
    """The fleet front's stats line (ISSUE 11): the serve-stats shape
    minus the per-process scheduler internals, plus the ``fleet`` block —
    per-replica state rows (pid, liveness, restarts, dispatched/answered,
    last ``/metrics.json`` scrape totals), supervision totals (restarts,
    re-dispatches, degraded answers by reason, suppressed duplicates),
    and the shared disk cache tier's counters. ``tools/obs_report.py
    --fleet`` renders it; a payload WITHOUT the ``fleet`` block is that
    renderer's exit-2 error."""
    lookups = cache.get("hits", 0) + cache.get("misses", 0)
    payload = {
        "responses": responses,
        "errors": errors,
        "deadline_misses": deadline_misses,
        "tiers": tier_counts,
        "fleet": fleet,
        "cache": dict(
            cache, hit_rate=(cache.get("hits", 0) / lookups) if lookups else 0.0
        ),
        "health": health or {},
        "slo": slo or {},
        "obs": obs or {},
    }
    return json.dumps(payload)

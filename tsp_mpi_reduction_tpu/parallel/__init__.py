"""Mesh construction, block sharding, and on-mesh merge-tree reduction."""

"""Adaptive load balance for the sharded B&B search (ISSUE 15 tentpole).

The reference's whole point is parallel speedup from domain decomposition,
and the sharded engine inherited its worst scaling pathology: a static
balance policy chosen before the solve starts. VERDICT r4 measured a
12,554x max/min per-rank node imbalance under the ring on eil51 ranks=8,
and PR 10 built the instrument panel (``obs.rank_balance`` occupancy CV,
starvation sentinel) without acting on it. This module closes the loop in
the Orca style (PAPERS.md): the scheduling decision is made BETWEEN device
dispatches, at guarded-step granularity, from telemetry the host loop
already holds — instead of committing to one collective for the whole run.

Three layers, deliberately separated so each is testable without the
engine:

- **Pure assignment math** (:func:`pair_assignment`,
  :func:`steal_assignment`): who donates how many rows to whom, as a pure
  function of the all-gathered counts. Conservation and overflow safety
  are properties of these functions alone (tests/test_balance.py fuzzes
  them mesh-free).
- **Shard-local collective steps** (:func:`ring_step`, :func:`pair_step`,
  :func:`steal_step`, dispatched via :func:`apply`): the in-kernel row
  exchange, written to run inside ``solve_sharded``'s per-action
  ``shard_map`` bodies. All shapes are static (fixed ``t_slots`` donation
  slabs); only the amounts are data-dependent. Every step returns the
  per-rank donated-row count so the host can account moved rows/bytes.
- **The host-side controller** (:class:`BalanceController`): picks the
  next dispatch's action with hysteresis from the per-round ``[R]``
  occupancy counts readback the host loop already performs for the spill
  path — which is why the controller keeps working under ``TSP_OBS=off``:
  the signal is the device-side alive counts, not the telemetry layer.

Action ladder (cheapest first):

``skip``
    No collective at all. Chosen when every rank is saturated for the
    next pop (occupancy CV under the dead-band / nothing worth moving) —
    before this existed, a perfectly balanced mesh still paid ring/pair
    ppermutes on every round.
``ring`` / ``pair``
    The existing cheap diffusion collectives, kept for mild skew.
``steal``
    Global repartition for starvation: surplus live rows are routed from
    the most-loaded ranks to the starved ones in one collective, with
    donor/receiver destinations computed from an exclusive prefix-sum
    over the all-gathered counts (fixed-size donation slabs keep shapes
    static; slabs ride ``all_gather`` because the rich->starved routing
    is data-dependent and ``ppermute`` permutations must be static).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..obs.metrics import REGISTRY as _REGISTRY
from .mesh import RANK_AXIS

#: every balance action the controller can pick; each is its own
#: fixed-shape jitted entry in solve_sharded (mode switches never retrace)
ACTIONS = ("skip", "ring", "pair", "steal")


# -- pure assignment math -----------------------------------------------------


def pair_assignment(all_c, round_i, num_ranks: int, t_slots: int):
    """The pair-balance matching, as a pure function of the (invariant)
    all-gathered counts: richest donates to poorest, 2nd-richest to
    2nd-poorest, ... with a tie-break that rotates with ``round_i``.

    Returns ``(m_of, partner_of)``: per-rank donation size and mirror
    partner. Extracted from the shard_map closure so the starvation
    properties are unit-testable without a mesh (tests/test_bnb.py).
    """
    rot = (jnp.arange(num_ranks, dtype=jnp.int32) + round_i) % num_ranks
    order = jnp.lexsort((rot, -all_c))  # count desc, rotating ties
    pos = jnp.argsort(order)  # pos[r] = rank r's position in that order
    partner_of = order[num_ranks - 1 - pos]  # [R]: my mirror rank
    donor = pos < (num_ranks // 2)  # odd R: middle rank pairs itself
    gap = all_c - all_c[partner_of]
    m_of = jnp.where(donor, jnp.clip(gap // 2, 0, t_slots), 0)  # [R]
    return m_of, partner_of


def steal_assignment(all_c, t_slots: int):
    """The steal repartition plan, as a pure function of the (invariant)
    all-gathered counts: ranks above the mean donate their surplus into a
    global pool, ranks below the mean take their deficit out of it, both
    capped at ``t_slots`` rows per rank so shapes stay static.

    Donor rank r owns global pool positions
    ``[pool_off[r], pool_off[r] + m_out[r])`` and receiver rank r takes
    positions ``[take_off[r], take_off[r] + m_in[r])`` — both sides are
    exclusive prefix-sums over the same moved total ``M``, so they
    partition ``[0, M)`` exactly: conservation (no row duplicated or
    dropped) holds by construction, not by coincidence
    (tests/test_balance.py fuzzes it over arbitrary skew patterns).

    Overflow-safe: surplus and deficit are disjoint (no rank is both
    donor and receiver), a receiver ends at most at the mean, a donor at
    least at the mean, and mean <= capacity while every count <= capacity.

    Returns ``(m_out, m_in, pool_off, take_off)``, all ``[R]`` int32.
    """
    num_ranks = all_c.shape[0]
    # dtype-pinned reductions: under x64, jnp.sum/cumsum of int32 promote
    # to int64, which would leak into the frontier count and break the
    # while-loop carry / AOT aval match
    dt = all_c.dtype
    mean = jnp.sum(all_c, dtype=dt) // num_ranks
    don = jnp.minimum(jnp.maximum(all_c - mean, 0), t_slots)
    need = jnp.minimum(jnp.maximum(mean - all_c, 0), t_slots)
    moved = jnp.minimum(jnp.sum(don, dtype=dt), jnp.sum(need, dtype=dt))
    don_off = jnp.cumsum(don, dtype=dt) - don  # exclusive prefix
    need_off = jnp.cumsum(need, dtype=dt) - need
    m_out = jnp.clip(moved - don_off, 0, don)
    m_in = jnp.clip(moved - need_off, 0, need)
    pool_off = jnp.minimum(don_off, moved)
    take_off = jnp.minimum(need_off, moved)
    return m_out, m_in, pool_off, take_off


# -- shard-local collective steps ---------------------------------------------
#
# Each step runs INSIDE a per-rank shard_map body over the existing
# RANK_AXIS: (nodes [F_phys, cols], count scalar, round_i scalar) ->
# (nodes, count, m_out). ``capacity`` is the logical per-rank row count,
# ``phys_rows`` the physical one (capacity + push padding): dead receive
# lanes park AT phys_rows so ``.at[...].set(mode="drop")`` drops them
# instead of corrupting padding row 0.


def ring_step(
    nodes,
    cnt,
    round_i,
    *,
    t_slots: int,
    capacity: int,
    phys_rows: int,
    perm_fwd,
    perm_back,
):
    """Diffuse work around the ring: donate top-of-stack rows to the
    successor while I hold more than it. Donation is capped so the
    receiver can never overflow (recv + m <= (donor + recv)/2 <= capacity
    while donor <= capacity). ``round_i`` unused (the ring route is
    fixed)."""
    nb_cnt = jax.lax.ppermute(cnt, RANK_AXIS, perm_back)  # successor's count
    m_out = jnp.clip((cnt - nb_cnt) // 2, 0, t_slots)
    lanes = jnp.arange(t_slots, dtype=jnp.int32)
    src = jnp.clip(cnt - m_out + lanes, 0, capacity - 1)
    m_in = jax.lax.ppermute(m_out, RANK_AXIS, perm_fwd)
    base = cnt - m_out
    dest = jnp.where(lanes < m_in, base + lanes, phys_rows)
    recv = jax.lax.ppermute(nodes[src], RANK_AXIS, perm_fwd)
    return nodes.at[dest].set(recv, mode="drop"), base + m_in, m_out


def pair_step(
    nodes, cnt, round_i, *, num_ranks: int, t_slots: int,
    capacity: int, phys_rows: int,
):
    """Pair the richest rank with the poorest (2nd-richest with
    2nd-poorest, ...) and donate half the count gap directly — O(1)
    rounds to flatten any skew where the ring needs O(num_ranks)
    diffusion hops. The pairing is computed identically on every rank
    from the all-gathered counts (axis-invariant data), then each rank
    plays its own (varying) role in it. Slabs move via ``all_gather`` +
    local select: ``ppermute`` cannot route them because its permutation
    must be static and the rich->poor matching is data-dependent. The
    tie-break among equal counts rotates with ``round_i`` so a drained
    rank is never parked unfed forever (see pair_assignment)."""
    all_c = jax.lax.all_gather(cnt, RANK_AXIS)  # [R], invariant
    m_of, partner_of = pair_assignment(all_c, round_i, num_ranks, t_slots)
    me = jax.lax.axis_index(RANK_AXIS)
    m_out = m_of[me]
    partner = partner_of[me]
    m_in = m_of[partner]  # 0 unless my partner donates (to me)
    lanes = jnp.arange(t_slots, dtype=jnp.int32)
    src = jnp.clip(cnt - m_out + lanes, 0, capacity - 1)
    slabs = jax.lax.all_gather(nodes[src], RANK_AXIS)  # [R, t, width]
    base = cnt - m_out
    dest = jnp.where(lanes < m_in, base + lanes, phys_rows)
    return nodes.at[dest].set(slabs[partner], mode="drop"), base + m_in, m_out


def steal_step(
    nodes, cnt, round_i, *, num_ranks: int, t_slots: int,
    capacity: int, phys_rows: int,
):
    """Global repartition for starvation: every rank above the mean
    donates its surplus (capped at ``t_slots``) into a pooled slab set,
    every rank below the mean takes its deficit out of it — the whole
    rich->starved flattening in ONE collective, where pair moves along a
    single matching and the ring needs O(num_ranks) hops.

    Routing: receiver lane ``j`` holds global pool position
    ``take_off[me] + j``; its donor is found with a right-side
    ``searchsorted`` over the donor prefix offsets (the last rank whose
    slab starts at or before the position — robust to zero-width
    donors), and the row index inside that donor's slab is the
    remainder. Slabs ride ``all_gather`` for the same reason pair's do:
    the permutation is data-dependent, so ``ppermute`` cannot carry it.
    ``round_i`` unused (the plan is a pure function of the counts)."""
    all_c = jax.lax.all_gather(cnt, RANK_AXIS)  # [R], invariant
    m_out_of, m_in_of, pool_off, take_off = steal_assignment(all_c, t_slots)
    me = jax.lax.axis_index(RANK_AXIS)
    m_out = m_out_of[me]
    m_in = m_in_of[me]
    lanes = jnp.arange(t_slots, dtype=jnp.int32)
    src = jnp.clip(cnt - m_out + lanes, 0, capacity - 1)
    slabs = jax.lax.all_gather(nodes[src], RANK_AXIS)  # [R, t, width]
    pos = take_off[me] + lanes  # my lanes' global pool positions
    donor = jnp.clip(
        jnp.searchsorted(pool_off, pos, side="right").astype(jnp.int32) - 1,
        0, num_ranks - 1,
    )
    row = jnp.clip(pos - pool_off[donor], 0, t_slots - 1)
    base = cnt - m_out
    dest = jnp.where(lanes < m_in, base + lanes, phys_rows)
    return (
        nodes.at[dest].set(slabs[donor, row], mode="drop"),
        base + m_in,
        m_out,
    )


def apply(
    action: str, nodes, cnt, round_i, *, num_ranks: int, t_slots: int,
    capacity: int, phys_rows: int, perm_fwd, perm_back,
):
    """Dispatch one shard-local balance step by action name. ``skip``
    returns the frontier untouched with a zero moved count — the
    controller's dead-band outcome is a real (cheapest) action, not a
    missing dispatch."""
    if action == "skip":
        return nodes, cnt, jnp.zeros((), jnp.int32)
    if action == "ring":
        return ring_step(
            nodes, cnt, round_i, t_slots=t_slots, capacity=capacity,
            phys_rows=phys_rows, perm_fwd=perm_fwd, perm_back=perm_back,
        )
    if action == "pair":
        return pair_step(
            nodes, cnt, round_i, num_ranks=num_ranks, t_slots=t_slots,
            capacity=capacity, phys_rows=phys_rows,
        )
    if action == "steal":
        return steal_step(
            nodes, cnt, round_i, num_ranks=num_ranks, t_slots=t_slots,
            capacity=capacity, phys_rows=phys_rows,
        )
    raise ValueError(f"unknown balance action {action!r} (one of {ACTIONS})")


# -- the host-side controller -------------------------------------------------


@dataclass
class BalanceController:
    """Picks the next dispatch's balance action from the per-round ``[R]``
    occupancy counts, with hysteresis.

    The decision signal is utilization, not aesthetics: imbalance only
    costs wall time when some rank will pop fewer than ``k`` rows next
    round while another holds spare rows above its own pop. The
    dead-band therefore has two gates — occupancy CV under
    ``dead_band``, or a worthwhile-transfer floor: the donatable surplus
    (rows above ``k`` per rank, capped at ``t_slots``) matched against
    the saturation deficit of the hungry ranks must reach
    ``max(1, k // 2)`` rows, else the collective cannot pay for itself.
    Escalation to ``steal`` fires on starvation (some rank at or below
    ``starve_frac`` of the mean, or CV past ``escalate_cv``) and is
    confirmed against the device-side ALIVE counts when a probe is
    available (rows the incumbent already closed are not worth moving).
    The probe is itself a collective readback, so a STANDING escalation
    does not re-pay it every round: it is consulted on entry into
    ``steal`` and every ``probe_every``-th consecutive steal round (a
    long starvation episode re-checks that the donors still hold live
    rows without turning the confirmation into per-round traffic).
    Entering ``skip`` from an active action requires ``settle``
    consecutive calm decisions (flap damping); leaving it is immediate.

    ``adaptive=False`` degrades to the static policy (the fixed ``base``
    action, still skipping only when the mesh is fully drained) — used
    for the explicit ``balance="ring"|"pair"|"steal"`` modes so every
    sharded solve shares one accounting/telemetry path.

    Works under ``TSP_OBS=off``: the inputs are the spill path's own
    counts readback and an optional alive-counts collective, neither
    gated by the telemetry switch.
    """

    num_ranks: int
    k: int
    t_slots: int
    base: str = "pair"
    adaptive: bool = True
    dead_band: float = 0.25
    escalate_cv: float = 1.25
    starve_frac: float = 0.10
    settle: int = 2
    probe_every: int = 16
    max_rows: int = 512
    row_bytes: int = 0

    # trajectory / accounting state (summary() folds these into the
    # driver payload's obs.balance block)
    _last: str = "skip"
    _calm: int = 0
    _steal_streak: int = 0
    _switches: int = 0
    _degraded: int = 0
    _probes: int = 0
    _cv_last: float = 0.0
    _cv_max: float = 0.0
    _actions: Dict[str, int] = field(default_factory=dict)
    _moved_rows: int = 0
    _rows: List[list] = field(default_factory=list)
    _rows_dropped: int = 0

    @property
    def last_action(self) -> str:
        """The action committed by the most recent decision (host-loop
        span code stamps switches by comparing against this BEFORE the
        next ``decide``)."""
        return self._last

    @property
    def cv(self) -> float:
        """Occupancy CV seen by the most recent decision."""
        return self._cv_last

    def decide(
        self,
        counts: np.ndarray,
        alive_probe: Optional[Callable[[], np.ndarray]] = None,
    ) -> str:
        """Pick the action for the NEXT dispatch from the current per-rank
        occupancy ``counts`` ([R] ints). ``alive_probe``, when given, is
        called (lazily, only to confirm an escalation) and must return
        the per-rank ALIVE row counts ([R])."""
        c = np.asarray(counts, np.float64)
        total = float(c.sum())
        mean = total / max(self.num_ranks, 1)
        cv = float(c.std() / mean) if mean > 0 else 0.0
        self._cv_last = cv
        self._cv_max = max(self._cv_max, cv)
        if self.num_ranks <= 1 or total <= 0:
            # nothing to exchange: a 1-rank mesh and a drained frontier
            # both skip unconditionally, in every mode
            return self._commit("skip", forced=True)
        if not self.adaptive:
            return self._commit(self.base)
        pool = float(np.minimum(np.maximum(c - self.k, 0), self.t_slots).sum())
        need = float(np.maximum(self.k - c, 0).sum())
        worth = min(pool, need)
        if cv < self.dead_band or worth < max(1, self.k // 2):
            return self._commit("skip")
        starved = float(c.min()) <= self.starve_frac * mean
        if starved or cv >= self.escalate_cv:
            # the probe is a collective readback: pay it on ENTRY into
            # steal and every probe_every-th standing round, never per
            # round of a persistent starvation episode
            due = self._last != "steal" or (
                self.probe_every > 0
                and self._steal_streak % self.probe_every == 0
            )
            if alive_probe is not None and due:
                alive = np.asarray(alive_probe(), np.float64)
                self._probes += 1
                # donors whose rows are all incumbent-closed have nothing
                # worth routing — the next pop prunes them for free
                if float(alive[c > mean].sum()) < 1.0:
                    return self._commit(self.base)
            return self._commit("steal")
        return self._commit(self.base)

    def _commit(self, action: str, forced: bool = False) -> str:
        if not forced and action == "skip" and self._last != "skip":
            # flap damping: an active collective only stands down after
            # `settle` consecutive calm decisions
            self._calm += 1
            if self._calm < self.settle:
                action = self.base
        elif action == "skip":
            self._calm += 1
        else:
            self._calm = 0
        if action != self._last:
            self._switches += 1
        self._steal_streak = self._steal_streak + 1 if action == "steal" else 0
        self._last = action
        return action

    def degrade(self) -> str:
        """A ``balance.steal`` fault was injected at the escalation seam:
        absorb it by falling back to the base action for this round (the
        search stays exact either way — balance only moves rows)."""
        self._degraded += 1
        return self._commit(self.base)

    def record(self, step: int, action: str, moved_per_rank) -> None:
        """Account one dispatch's outcome: the action that actually ran
        and the per-rank donated-row counts the kernel reported."""
        moved = int(np.asarray(moved_per_rank).sum())
        self._actions[action] = self._actions.get(action, 0) + 1
        self._moved_rows += moved
        if len(self._rows) >= self.max_rows:
            # bounded trajectory (the samplers' ring posture): totals
            # stay exact, only the per-round rows are capped
            self._rows.pop(0)
            self._rows_dropped += 1
        self._rows.append([int(step), action, round(self._cv_last, 4), moved])

    def participation(self, counts: np.ndarray) -> Dict[str, list]:
        """Donor/receiver rank sets implied by the current counts — the
        per-rank participation payload for the ``bnb.balance`` span."""
        c = np.asarray(counts, np.float64)
        mean = c.mean() if c.size else 0.0
        return {
            "donors": [int(r) for r in np.flatnonzero(c > mean)],
            "receivers": [int(r) for r in np.flatnonzero(c < mean)],
        }

    def count_action(self, action: str) -> None:
        """Registry counter, incremented host-side per dispatch decision
        (never inside traced code — graftlint R8)."""
        _REGISTRY.inc("bnb_balance_actions_total", action=action)

    def collective_dispatches(self) -> int:
        return sum(v for a, v in self._actions.items() if a != "skip")

    def summary(self) -> dict:
        """The ``obs.balance`` block: config, decision mix, moved
        rows/bytes, and the (bounded) per-round decision/CV trajectory."""
        return {
            "mode": "adaptive" if self.adaptive else self.base,
            "base": self.base,
            "ranks": self.num_ranks,
            "k": self.k,
            "t_slots": self.t_slots,
            "dead_band": self.dead_band,
            "escalate_cv": self.escalate_cv,
            "starve_frac": self.starve_frac,
            "settle": self.settle,
            "actions": dict(self._actions),
            "collective_dispatches": self.collective_dispatches(),
            "switches": self._switches,
            "steal_degraded": self._degraded,
            "alive_probes": self._probes,
            "moved_rows_total": self._moved_rows,
            "moved_bytes_total": self._moved_rows * self.row_bytes,
            "cv_last": round(self._cv_last, 4),
            "cv_max": round(self._cv_max, 4),
            "rows": [list(r) for r in self._rows],
            "rows_dropped": self._rows_dropped,
        }

"""Device-mesh construction: the ICI-torus analog of MPI_Cart_create.

The reference builds a 2D *torus* communicator over ranks with near-square
dims and wraparound (``MPI_Cart_create`` with ``wrap={1,1}``, reorder=0,
tsp.cpp:297-304), using ``getBlocksPerDim(numProcs)`` (tsp.cpp:136-157) for
the factorization. That is the one place its process shape literally matches
TPU hardware: the ICI fabric *is* a torus. Here the same factorization lays
a ``jax.sharding.Mesh`` over the device torus; the reduction itself runs on
a flattened 1D view (axis "ranks") because the reference never routes by
coordinates either (coords are computed then unused, tsp.cpp:304-305).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np

from ..ops.generator import get_blocks_per_dim

RANK_AXIS = "ranks"


def torus_dims(num_devices: int) -> Tuple[int, int]:
    """Near-square 2D factorization, exactly the reference's rank layout."""
    return get_blocks_per_dim(num_devices)


def make_torus_mesh(
    devices: Optional[Sequence[jax.Device]] = None,
    axis_names: Tuple[str, str] = ("x", "y"),
) -> jax.sharding.Mesh:
    """2D mesh over the device torus (MPI_Cart_create analog)."""
    devices = list(devices if devices is not None else jax.devices())
    rows, cols = torus_dims(len(devices))
    arr = np.asarray(devices).reshape(rows, cols)
    return jax.sharding.Mesh(arr, axis_names)


def initialize_multihost(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> int:
    """Join a multi-host run (``jax.distributed``) — the DCN analog of
    ``mpirun`` launching ranks on several nodes.

    The reference scales across nodes by letting ``mpirun`` place ranks
    anywhere and routing every message through MPI (SURVEY.md §2). The JAX
    equivalent is one controller process per host: after this call,
    ``jax.devices()`` spans every host's chips, meshes built by
    :func:`make_rank_mesh`/:func:`make_torus_mesh` cover the whole slice,
    and XLA routes collectives over ICI within a pod and DCN between pods —
    no application-code changes, the same ``shard_map`` programs run.

    All three arguments default to the standard cluster environment
    (``JAX_COORDINATOR_ADDRESS`` etc. / TPU pod metadata), so on Cloud TPU
    pods a bare ``initialize_multihost()`` suffices. Returns the global
    device count. No-op (returning the current count) when jax.distributed
    is already initialized.
    """
    is_init = getattr(jax.distributed, "is_initialized", None)
    if is_init is not None:
        if is_init():
            return len(jax.devices())  # already joined
    else:  # older jax without the public predicate
        from jax._src import distributed as _dist

        if getattr(_dist.global_state, "client", None) is not None:
            return len(jax.devices())
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    return len(jax.devices())


def make_rank_mesh(
    num_devices: Optional[int] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> jax.sharding.Mesh:
    """Flat 1D mesh (axis ``"ranks"``) used by the merge-tree reduction."""
    if devices is None:
        devices = jax.devices()
        if num_devices is not None:
            if len(devices) < num_devices:
                raise RuntimeError(
                    f"asked for a {num_devices}-rank mesh but only "
                    f"{len(devices)} devices exist; a smaller mesh would "
                    "silently change the merge-tree result"
                )
            devices = devices[:num_devices]
    arr = np.asarray(list(devices))
    return jax.sharding.Mesh(arr, (RANK_AXIS,))

"""Device-mesh construction: the ICI-torus analog of MPI_Cart_create.

The reference builds a 2D *torus* communicator over ranks with near-square
dims and wraparound (``MPI_Cart_create`` with ``wrap={1,1}``, reorder=0,
tsp.cpp:297-304), using ``getBlocksPerDim(numProcs)`` (tsp.cpp:136-157) for
the factorization. That is the one place its process shape literally matches
TPU hardware: the ICI fabric *is* a torus. Here the same factorization lays
a ``jax.sharding.Mesh`` over the device torus; the reduction itself runs on
a flattened 1D view (axis "ranks") because the reference never routes by
coordinates either (coords are computed then unused, tsp.cpp:304-305).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np

from ..ops.generator import get_blocks_per_dim

RANK_AXIS = "ranks"


def torus_dims(num_devices: int) -> Tuple[int, int]:
    """Near-square 2D factorization, exactly the reference's rank layout."""
    return get_blocks_per_dim(num_devices)


def make_torus_mesh(
    devices: Optional[Sequence[jax.Device]] = None,
    axis_names: Tuple[str, str] = ("x", "y"),
) -> jax.sharding.Mesh:
    """2D mesh over the device torus (MPI_Cart_create analog)."""
    devices = list(devices if devices is not None else jax.devices())
    rows, cols = torus_dims(len(devices))
    arr = np.asarray(devices).reshape(rows, cols)
    return jax.sharding.Mesh(arr, axis_names)


def make_rank_mesh(
    num_devices: Optional[int] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> jax.sharding.Mesh:
    """Flat 1D mesh (axis ``"ranks"``) used by the merge-tree reduction."""
    if devices is None:
        devices = jax.devices()
        if num_devices is not None:
            if len(devices) < num_devices:
                raise RuntimeError(
                    f"asked for a {num_devices}-rank mesh but only "
                    f"{len(devices)} devices exist; a smaller mesh would "
                    "silently change the merge-tree result"
                )
            devices = devices[:num_devices]
    arr = np.asarray(list(devices))
    return jax.sharding.Mesh(arr, (RANK_AXIS,))

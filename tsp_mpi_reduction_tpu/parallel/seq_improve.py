"""Ring sequence-parallel tour improvement over the device mesh.

The long-context analog for this workload: a merged tour grows with
``numBlocks * numCitiesPerBlock`` (SURVEY.md §5 "long-context" row) — far
beyond what one device should sweep alone. This module shards the tour
into contiguous segments over the rank mesh, improves each segment's
interior with the jitted 2-opt kernel (ops.local_search, endpoints
pinned so inter-segment edges stay intact), then rotates the cyclic tour
by half a segment with ``ppermute`` so every boundary becomes some
segment's interior on a later round — the same neighbor-shift pattern as
ring attention, riding the ICI.

Cost is monotonically non-increasing: local sweeps only apply improving
reversals and rotation is a relabeling of the same cyclic tour.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ..ops.local_search import two_opt_sweep
from .mesh import RANK_AXIS


#: Above this tour length the final single-device polish is skipped: an
#: O(N^2) full-tour sweep would dominate exactly the long-tour regime the
#: ring decomposition exists to shard (the rotation rounds already move
#: every boundary into some segment's interior).
FINAL_POLISH_MAX = 4096


def ring_two_opt(
    tour: jnp.ndarray,
    d: jnp.ndarray,
    mesh,
    rounds: Optional[int] = None,
    max_iters_per_sweep: int = 256,
    final_polish_max: int = FINAL_POLISH_MAX,
) -> jnp.ndarray:
    """Improve a closed tour (given as [N] open order) on a device mesh.

    ``N`` must be divisible by the mesh size. Returns the improved [N]
    order (cyclically shifted — the start city is not preserved, which is
    irrelevant for a closed tour).
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..utils.backend import shard_map

    n = int(tour.shape[0])
    num_ranks = int(mesh.devices.size)
    if n % num_ranks:
        raise ValueError(f"tour length {n} not divisible by {num_ranks} ranks")
    seg_len = n // num_ranks
    if seg_len < 4:
        raise ValueError(f"segments of {seg_len} cities are too short to sweep")
    shift = seg_len // 2
    if rounds is None:
        rounds = 2 * num_ranks
    perm = [(r, (r - 1) % num_ranks) for r in range(num_ranks)]

    def body(seg, d_rep):
        seg = seg[0]  # [L]

        def one_round(s, _):
            s, _ = two_opt_sweep(
                s, d_rep, closed=False, max_iters=max_iters_per_sweep
            )
            # rotate the cyclic tour left by `shift`: my head goes to the
            # previous rank; I append my successor's head
            head = jax.lax.ppermute(s[:shift], RANK_AXIS, perm)
            return jnp.concatenate([s[shift:], head]), None

        seg, _ = jax.lax.scan(one_round, seg, None, length=rounds)
        return seg[None]

    sharded = jax.device_put(
        tour.reshape(num_ranks, seg_len),
        NamedSharding(mesh, P(RANK_AXIS)),
    )
    out = jax.jit(
        shard_map(
            body,
            mesh=mesh,
            in_specs=(P(RANK_AXIS), P(None, None)),
            out_specs=P(RANK_AXIS),
        )
    )(sharded, d)
    assembled = out.reshape(-1)
    if n > final_polish_max:
        return assembled
    # one final closed polish (2-opt + Or-opt) on the assembled tour catches
    # any remaining cross-boundary move; only worthwhile while the O(N^2)
    # sweep is cheap relative to the ring phase (see FINAL_POLISH_MAX)
    from ..ops.local_search import polish

    improved, _ = polish(assembled, d)
    return improved


def improve_tour(
    tour: jnp.ndarray, d: jnp.ndarray, mesh=None
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Polish a closed tour; mesh-parallel when a multi-device mesh is given.

    Returns (order', length') with the length re-measured from ``d`` —
    unlike the reference's formulaic merge cost (SURVEY.md quirk #4), this
    is the true cost of the returned tour.
    """
    from ..ops.local_search import polish, tour_length

    if mesh is not None and int(mesh.devices.size) > 1 and (
        tour.shape[0] % int(mesh.devices.size) == 0
        and tour.shape[0] // int(mesh.devices.size) >= 4
    ):
        order = ring_two_opt(tour, d, mesh)
    else:
        order, _ = polish(tour, d)
    return order, tour_length(order, d, closed=True)

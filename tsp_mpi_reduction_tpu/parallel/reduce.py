"""On-mesh merge-tree reduction: the collective rewrite of MPI_ManualReduce.

The reference hand-rolls a binary-tree reduction over ranks out of blocking
point-to-point messages because its operator (``mergeBlocks``) is
non-commutative, non-associative, and variable-length — builtin
``MPI_Reduce`` can't express it (tsp.cpp:52-134). Tree shape:

- phase 1 ("downshift", tsp.cpp:72-100): ranks >= lastpower = 2^floor(log2 p)
  send their solution to ``rank - lastpower``, receiver merges;
- phase 2 (tsp.cpp:102-132): log2(lastpower) rounds with receiver ``k``,
  sender ``k + 2^d``, stride ``2^(d+1)``; receiver merges (mine, received).

This module reproduces that exact tree shape on a device mesh: each round is
one ``lax.ppermute`` over the 1D rank axis under ``shard_map`` — tours ride
the ICI as fixed-width padded buffers instead of 3-message variable-length
sequences (count/cities/cost with magic tags, tsp.cpp:109-112). Ranks not
targeted by a round receive zeros; a zero-length operand means "no data" and
the combine keeps the local solution, which also covers idle ranks
(``procNum > numBlocks`` early-exits in the reference, tsp.cpp:326-330).

Deviation (documented + emulatable): the reference's receive path
accumulates received cities into a never-cleared vector, so any rank that
receives twice merges a corrupted operand (SURVEY.md quirk #5). The
default implementation merges the actual operands; single-rank parity
(the oracle-verifiable case) is unaffected. ``compat_bugs=True`` on the
rank-emulated reduce (the ``--compat-bugs`` CLI flag) replicates the
corruption faithfully — per-rank accumulation buffers grow across rounds
exactly like the reference's ``path`` vector, so a p-rank result matches
what a real p-rank MPI run of the unmodified reference would print (no
MPI toolchain exists here to capture goldens; the emulation is validated
against a literal host-side simulation of the reference semantics in
tests/test_distributed.py).

The scalar-incumbent analog (``lax.pmin`` over the mesh) used by the B&B
engine lives here too.
"""

from __future__ import annotations

import math
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..analysis import contracts
from ..obs.metrics import REGISTRY as _REGISTRY
from ..ops.merge import PaddedTour, merge_tours
from ..utils.backend import shard_map
from .mesh import RANK_AXIS


def tree_schedule(num_ranks: int):
    """The reference's tree as a list of per-round ppermute pairs.

    Returns ``[(round_name, [(src, dst), ...]), ...]`` in execution order.
    """
    lastpower = 1 << (num_ranks.bit_length() - 1)
    if lastpower > num_ranks:
        lastpower >>= 1
    rounds = []
    if num_ranks > lastpower:
        rounds.append(
            ("downshift", [(i, i - lastpower) for i in range(lastpower, num_ranks)])
        )
    for d in range(int(math.log2(lastpower))):
        pairs = [(k + (1 << d), k) for k in range(0, lastpower, 1 << (d + 1))]
        rounds.append((f"tree_d{d}", pairs))
    return rounds


def _combine(mine: PaddedTour, recv: PaddedTour, dist: jnp.ndarray) -> PaddedTour:
    """Merge ``recv`` into ``mine``; zero-length operands mean "no data"."""
    merged = merge_tours(mine, recv, dist)
    keep_mine = recv.length == 0
    take_recv = (mine.length == 0) & (recv.length > 0)

    def sel(m, r, g):
        return jnp.where(keep_mine, m, jnp.where(take_recv, r, g))

    return PaddedTour(
        sel(mine.ids, recv.ids, merged.ids),
        sel(mine.length, recv.length, merged.length),
        sel(mine.cost, recv.cost, merged.cost),
    )


def _local_fold(
    tours: jnp.ndarray, costs: jnp.ndarray, valid: jnp.ndarray, dist: jnp.ndarray, capacity: int
) -> PaddedTour:
    """Rank-local sequential fold over (possibly padded-out) blocks.

    The shard_map-local analog of the reference's per-rank loop
    (tsp.cpp:348-352), with a validity mask so every rank runs the same
    static scan even when block counts are uneven.
    """
    k, l = tours.shape

    def embed(ids, ok):
        buf = jnp.pad(ids.astype(jnp.int32), (0, capacity - l))
        return buf * ok.astype(jnp.int32)

    acc = PaddedTour(
        embed(tours[0], valid[0]),
        jnp.where(valid[0], l, 0).astype(jnp.int32),
        jnp.where(valid[0], costs[0], jnp.asarray(0, costs.dtype)),
    )
    if k == 1:
        return acc

    def step(carry, xs):
        ids2, cost2, ok = xs
        # merge with the [l]-sized operand (keeps the swap matrix [cap, l]);
        # the empty/invalid selects happen at carry size
        t2 = PaddedTour(
            ids2.astype(jnp.int32), jnp.where(ok, l, 0).astype(jnp.int32), cost2
        )
        merged = merge_tours(carry, t2, dist)
        take_t2 = (carry.length == 0) & ok  # first valid block on this rank
        keep = ~ok

        def sel(mine, alone, grown):
            return jnp.where(keep, mine, jnp.where(take_t2, alone, grown))

        nxt = PaddedTour(
            sel(carry.ids, embed(ids2, ok), merged.ids),
            sel(carry.length, jnp.asarray(l, jnp.int32), merged.length),
            sel(carry.cost, cost2, merged.cost),
        )
        return nxt, None

    acc, _ = jax.lax.scan(step, acc, (tours[1:], costs[1:], valid[1:]))
    return acc


def reduce_tours_on_mesh(
    mesh: jax.sharding.Mesh,
    tours: jnp.ndarray,
    costs: jnp.ndarray,
    valid: jnp.ndarray,
    dist: jnp.ndarray,
    capacity: int,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Fold + tree-reduce sharded block solutions down to one global tour.

    Args:
      mesh: 1D mesh with axis ``"ranks"`` (``make_rank_mesh``).
      tours: ``[P*K, L]`` per-block closed tours (global city ids), sharded
        so rank r owns rows [r*K, (r+1)*K) — the reference's block
        assignment layout (tsp.cpp:173-191).
      costs: ``[P*K]`` per-block costs.
      valid: ``[P*K]`` bool, False for padding blocks.
      dist: ``[N, N]`` global distance matrix (replicated).
      capacity: padded tour buffer size (>= total tour length).

    Returns:
      (ids ``[capacity]``, length, cost) of the rank-0 result — the only
      rank whose value is meaningful, as in the reference (tsp.cpp:133).
    """
    num_ranks = mesh.devices.size
    schedule = tree_schedule(num_ranks)
    _check_reduce_inputs(tours, costs, valid, dist, capacity, num_ranks)

    def body(tours_blk, costs_blk, valid_blk, dist_rep):
        acc = _local_fold(tours_blk, costs_blk, valid_blk, dist_rep, capacity)
        # the tree schedule is static and log2(p) rounds deep — unrolling
        # IS the reduction; each round's ppermute pairs differ, so no scan
        for _name, pairs in schedule:  # graftlint: disable=R4
            recv = jax.tree.map(
                lambda x: jax.lax.ppermute(x, RANK_AXIS, pairs), acc
            )
            acc = _combine(acc, PaddedTour(*recv), dist_rep)
        return jax.tree.map(lambda x: x[None], tuple(acc))

    out = shard_map(
        body,
        mesh=mesh,
        in_specs=(P(RANK_AXIS), P(RANK_AXIS), P(RANK_AXIS), P(None, None)),
        out_specs=P(RANK_AXIS),
    )(tours, costs, valid, dist)
    ids, length, cost = out
    return ids[0], length[0], cost[0]


def _check_reduce_inputs(tours, costs, valid, dist, capacity, num_ranks):
    """Boundary contract for the mesh/rank-emulated reductions: the shard
    layout assumptions below are silent data corruption when violated
    (rows land on the wrong rank; a short capacity truncates the splice)."""
    if contracts.level() == "off":
        return
    if tours.ndim != 2:
        raise contracts.ContractError(
            f"reduce: tours must be [P*K, L] block tours, got {tours.shape}"
        )
    pk, l = tours.shape
    if costs.shape != (pk,) or valid.shape != (pk,):
        raise contracts.ContractError(
            f"reduce: costs {costs.shape} / valid {valid.shape} must both be "
            f"[{pk}] to match the {pk} block rows"
        )
    if pk % num_ranks:
        raise contracts.ContractError(
            f"reduce: {pk} block rows not divisible by {num_ranks} ranks"
        )
    if dist.ndim != 2 or dist.shape[0] != dist.shape[1]:
        raise contracts.ContractError(
            f"reduce: dist must be a square [N, N] matrix, got {dist.shape}"
        )
    if valid.dtype != jnp.bool_:
        raise contracts.ContractError(
            f"reduce: valid must be bool, got {valid.dtype}"
        )
    if capacity < l:
        raise contracts.ContractError(
            f"reduce: capacity {capacity} below block tour length {l}"
        )


def pmin_incumbent(value: jnp.ndarray, axis_name: str = RANK_AXIS) -> jnp.ndarray:
    """Broadcast the best (minimum) incumbent across the mesh.

    The collective replacement for the north star's
    ``MPI_Allreduce(MPI_MIN)`` incumbent sharing: one ``lax.pmin`` riding
    the ICI instead of a host round-trip.
    """
    return jax.lax.pmin(value, axis_name)


def make_rank_alive_min(mesh: jax.sharding.Mesh, integral: bool = False):
    """Build the per-rank ALIVE-minimum bound collective for ``mesh``.

    The sharded B&B engine's spill decision needs, per rank, the minimum
    lower bound over that rank's OPEN nodes (live rows the incumbent has
    not yet closed): the reservoir-vs-frontier comparison that selects
    the device-resident fast path in ``solve_sharded``'s ``spill_refill``
    (merge the host reservoir only when it actually owns better nodes
    than the live frontier). Computing the minima on device keeps the
    per-spill readback at [R] floats — the full bound columns never
    leave the device.

    Returns a jitted callable ``(nodes [R, F, cols] i32 packed rows,
    counts [R] i32, inc scalar f32) -> [R] f32`` where element r is rank
    r's alive minimum (+inf when the rank holds no open node). The bound
    column is sliced and bitcast INSIDE the kernel (it is always the
    second-to-last packed column), so XLA fuses slice + bitcast + masked
    min into one pass over the resident buffer — the pre-PR-5 form took
    ``fr.bound``, an eager out-of-jit property slice that materialized a
    whole [R, F] f32 copy of the column per spill round just to feed it
    back in. Each rank's min is computed shard-locally under
    ``shard_map`` — no cross-rank traffic; the buffer is NOT donated (the
    spill path reads it again right after). ``integral`` selects the
    fixed-point alive predicate (``bound <= inc - 1``) matching the
    engine's ceil-aware pruning.
    """

    def body(nodes, counts, inc):
        rows = nodes[0]  # [F, cols] packed int32 rows
        # bound lives at column cols-2 (= n + W + 2) in the packed layout
        b = jax.lax.bitcast_convert_type(rows[:, -2], jnp.float32)
        pos = jnp.arange(rows.shape[0], dtype=jnp.int32)
        alive = pos < counts[0]
        if integral:
            alive = alive & (b <= inc - 1.0)
        else:
            alive = alive & (b < inc)
        return jnp.min(jnp.where(alive, b, jnp.inf))[None]

    # counted HERE, at build time on the host — never inside ``body``,
    # which is jit-traced (graftlint R8): each (mesh, integral) config
    # should build its collective once per process; a growing series is
    # recompile evidence the obs registry makes scrapable
    _REGISTRY.inc(
        "collectives_built_total", kind="rank_alive_min",
        ranks=mesh.devices.size, integral=integral,
    )
    return jax.jit(
        shard_map(
            body,
            mesh=mesh,
            in_specs=(P(RANK_AXIS), P(RANK_AXIS), P()),
            out_specs=P(RANK_AXIS),
        )
    )


def make_rank_alive_counts(mesh: jax.sharding.Mesh, integral: bool = False):
    """Build the per-rank ALIVE row-count collective for ``mesh``.

    The adaptive balance controller (parallel.balance, ISSUE 15) confirms
    a steal escalation against rows the incumbent has NOT yet closed:
    occupancy counts alone can nominate a donor whose whole stack is dead
    weight (pruned for free at the next pop, not worth a collective).
    This is the controller's dedicated probe — the ``alive`` column of
    :func:`make_rank_stats` without the bound minimum, so the readback is
    [R] ints and the decision works with telemetry fully off
    (``TSP_OBS=off`` gates the rankview sampler, never this).

    Returns a jitted callable ``(nodes [R, F, cols] i32 packed rows,
    counts [R] i32, inc scalar f32) -> [R] i32`` where element r is rank
    r's open-row count. Shard-local like its siblings: bound column
    sliced + bitcast in-kernel, buffer not donated, no cross-rank
    traffic. ``integral`` selects the fixed-point alive predicate.
    """

    def body(nodes, counts, inc):
        rows = nodes[0]  # [F, cols] packed int32 rows
        # bound lives at column cols-2 (see make_rank_alive_min)
        b = jax.lax.bitcast_convert_type(rows[:, -2], jnp.float32)
        pos = jnp.arange(rows.shape[0], dtype=jnp.int32)
        alive = pos < counts[0]
        if integral:
            alive = alive & (b <= inc - 1.0)
        else:
            alive = alive & (b < inc)
        return jnp.sum(alive.astype(jnp.int32))[None]

    # counted at build time on the host, never in the traced body (R8):
    # one build per (mesh, integral) config per solve is the expectation
    _REGISTRY.inc(
        "collectives_built_total", kind="rank_alive_counts",
        ranks=mesh.devices.size, integral=integral,
    )
    return jax.jit(
        shard_map(
            body,
            mesh=mesh,
            in_specs=(P(RANK_AXIS), P(RANK_AXIS), P()),
            out_specs=P(RANK_AXIS),
        )
    )


#: column order of the [R, K] row ``make_rank_stats`` returns — kept next
#: to the builder so the rankview consumer (obs.rankview.RankSampler) and
#: any future column rider agree on indices by name, not by magic number
RANK_STAT_COLUMNS = ("count", "alive", "best_bound")


def make_rank_stats(mesh: jax.sharding.Mesh, integral: bool = False):
    """Build the per-rank frontier stats collective for ``mesh``.

    The rank-resolved telemetry layer (obs.rankview) needs, once per
    sampling window, a small per-rank view of the sharded search: how
    many rows each rank holds (``count``), how many of those the
    incumbent has not yet closed (``alive``), and each rank's best open
    bound (``best_bound`` — +inf when the rank is drained). Same
    single-readback pattern as :func:`make_rank_alive_min`: everything
    is computed shard-locally on device over the resident packed buffer
    (bound column sliced + bitcast in-kernel, no eager [R, F] f32
    materialization) and the host reads back ONE [R, K] f32 row — tens
    of bytes, never the buffer.

    Returns a jitted callable ``(nodes [R, F, cols] i32 packed rows,
    counts [R] i32, inc scalar f32) -> [R, K] f32`` with K =
    ``len(RANK_STAT_COLUMNS)``. The buffer is NOT donated (the host
    loop keeps expanding it). ``integral`` selects the fixed-point
    alive predicate, matching the engine's ceil-aware pruning.
    """

    def body(nodes, counts, inc):
        rows = nodes[0]  # [F, cols] packed int32 rows
        # bound lives at column cols-2 (see make_rank_alive_min)
        b = jax.lax.bitcast_convert_type(rows[:, -2], jnp.float32)
        pos = jnp.arange(rows.shape[0], dtype=jnp.int32)
        live = pos < counts[0]
        if integral:
            alive = live & (b <= inc - 1.0)
        else:
            alive = live & (b < inc)
        return jnp.stack(
            [
                counts[0].astype(jnp.float32),
                jnp.sum(alive.astype(jnp.int32)).astype(jnp.float32),
                jnp.min(jnp.where(alive, b, jnp.inf)),
            ]
        )[None]

    # counted at build time on the host, never in the traced body (R8):
    # one build per (mesh, integral) config per solve is the expectation;
    # a growing series is recompile evidence
    _REGISTRY.inc(
        "collectives_built_total", kind="rank_stats",
        ranks=mesh.devices.size, integral=integral,
    )
    return jax.jit(
        shard_map(
            body,
            mesh=mesh,
            in_specs=(P(RANK_AXIS), P(RANK_AXIS), P()),
            out_specs=P(RANK_AXIS),
        )
    )


def compat_capacity(num_blocks: int, n: int, num_ranks: int) -> int:
    """Buffer size needed by the ``compat_bugs`` reduce (host simulation).

    Under quirk #5 the operand a receiver merges is its ACCUMULATED receive
    buffer, so solution lengths inflate beyond ``num_blocks*n + 1``; this
    walks the tree schedule with pure integer arithmetic to bound them.
    """
    counts = rank_block_counts(num_blocks, num_ranks)
    sol = [c * n + 1 if c else 0 for c in counts]
    acc = [0] * num_ranks
    peak = max(sol)
    for _name, pairs in tree_schedule(num_ranks):
        for s, dd in pairs:
            acc[dd] += sol[s]
            rb = acc[dd]
            if rb and sol[dd]:
                sol[dd] = sol[dd] + rb - 1
            elif rb:
                sol[dd] = rb
            peak = max(peak, sol[dd], acc[dd])
    return peak


def tree_reduce_single_device(
    tours: jnp.ndarray,
    costs: jnp.ndarray,
    valid: jnp.ndarray,
    dist: jnp.ndarray,
    capacity: int,
    num_ranks: int,
    compat_bugs: bool = False,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Rank-emulated reduction on ONE device: same tree, vmapped rounds.

    Lets any machine reproduce what a p-rank MPI run computes (the merge
    order, hence the exact result) without p devices — the single-chip
    analog of the p=1 MPI-stub trick (SURVEY.md §4). Virtual-rank folds run
    as one vmap over the rank dimension; each tree round is one vmapped
    pairwise merge over that round's (receiver, sender) pairs.

    ``compat_bugs``: replicate SURVEY.md quirk #5 — each receiver merges
    its ACCUMULATED (never-cleared) receive buffer instead of the actual
    operand, with the latest received cost; ``capacity`` must come from
    ``compat_capacity`` (lengths inflate).
    """
    pk, l = tours.shape
    if pk % num_ranks:
        # hard precondition for the reshape below, NOT an optional
        # contract: must hold (with a targeted error) even under
        # TSP_CONTRACTS=off, where _check_reduce_inputs is a no-op
        raise ValueError(f"{pk} block slots not divisible by {num_ranks} ranks")
    _check_reduce_inputs(tours, costs, valid, dist, capacity, num_ranks)
    k = pk // num_ranks
    tours_r = tours.reshape(num_ranks, k, l)
    costs_r = costs.reshape(num_ranks, k)
    valid_r = valid.reshape(num_ranks, k)

    folds = jax.vmap(lambda t, c, v: _local_fold(t, c, v, dist, capacity))(
        tours_r, costs_r, valid_r
    )  # PaddedTour of stacked [P, ...] leaves

    combine_v = jax.vmap(_combine, in_axes=(0, 0, None))
    if compat_bugs:
        acc_ids = jnp.zeros((num_ranks, capacity), jnp.int32)
        acc_len = jnp.zeros(num_ranks, jnp.int32)
    # static log2(p)-round tree, one vmapped merge per round (see body()
    # above) — the unroll is the algorithm  # graftlint: disable=R4
    for _name, pairs in tree_schedule(num_ranks):
        src = jnp.asarray([s for s, _ in pairs])
        dst = jnp.asarray([d for _, d in pairs])
        mine = jax.tree.map(lambda x: x[dst], folds)
        recv = PaddedTour(*jax.tree.map(lambda x: x[src], folds))
        if compat_bugs:
            # append the sender's cities onto the receiver's never-cleared
            # buffer (tsp.cpp:67,93-95,114-117) and merge THAT, with the
            # latest received cost
            lanes = jnp.arange(capacity)

            def append(buf, alen, ids, ln):
                dest = jnp.where(lanes < ln, alen + lanes, capacity)
                return buf.at[dest].set(ids, mode="drop"), alen + ln

            new_acc, new_len = jax.vmap(append)(
                acc_ids[dst], acc_len[dst], recv.ids, recv.length
            )
            acc_ids = acc_ids.at[dst].set(new_acc)
            acc_len = acc_len.at[dst].set(new_len)
            recv = PaddedTour(new_acc, new_len, recv.cost)
        merged = combine_v(PaddedTour(*mine), recv, dist)
        folds = PaddedTour(
            *jax.tree.map(lambda x, m: x.at[dst].set(m), tuple(folds), tuple(merged))
        )
    return folds.ids[0], folds.length[0], folds.cost[0]


def rank_block_counts(num_blocks: int, num_ranks: int) -> list[int]:
    """Blocks-per-rank, replicating the reference's round-robin countdown.

    ``blocksToSend[blocksLeft % numProcs]++`` for blocksLeft = numBlocks..1
    (tsp.cpp:167-171): rank r gets #{b in 1..numBlocks : b % numRanks == r}.
    Rank 0 gets zero blocks when numRanks > numBlocks — the configuration
    whose empty-solution UB the reference hits (SURVEY.md §5); here idle
    ranks are first-class (zero-length solutions).
    """
    counts = [0] * num_ranks
    for b in range(1, num_blocks + 1):
        counts[b % num_ranks] += 1
    return counts


def assign_blocks_to_ranks(num_blocks: int, num_ranks: int) -> list[list[int]]:
    """Contiguous block index ranges per rank, in the reference's send order
    (tsp.cpp:173-191: rank 0 keeps the first ``counts[0]`` blocks as
    leftovers, rank 1 receives the next ``counts[1]``, ...)."""
    counts = rank_block_counts(num_blocks, num_ranks)
    out, start = [], 0
    for c in counts:
        out.append(list(range(start, start + c)))
        start += c
    return out

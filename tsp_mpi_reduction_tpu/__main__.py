"""``python -m tsp_mpi_reduction_tpu`` — the reference's ``./tsp`` CLI."""

import sys

from .utils.cli import main

if __name__ == "__main__":
    sys.exit(main())

"""serve/: the batched solve service layer (ISSUE 3 tentpole).

Turns the strictly batch-oriented solver stack into a request-serving
system, following Clipper's prediction-cache + adaptive-batching design
and ORCA's continuous batching (PAPERS.md):

- ``canonical``  translation/permutation/jitter-invariant instance keys
- ``cache``      bounded LRU of canonical solutions with certificates
- ``scheduler``  micro-batching: N pending solves -> one padded vmap call
- ``ladder``     deadline-aware degradation: bnb -> pipeline -> greedy
- ``service``    JSONL request/response loop + ``serve`` CLI mode
"""

from .cache import CacheEntry, SolutionCache
from .canonical import CanonicalInstance, canonicalize
from .ladder import TIERS, DeadlineLadder, LadderConfig, LadderResult
from .scheduler import MicroBatchScheduler
from .service import ServiceConfig, SolveService, run_jsonl, serve_cli

__all__ = [
    "CacheEntry",
    "SolutionCache",
    "CanonicalInstance",
    "canonicalize",
    "TIERS",
    "DeadlineLadder",
    "LadderConfig",
    "LadderResult",
    "MicroBatchScheduler",
    "ServiceConfig",
    "SolveService",
    "run_jsonl",
    "serve_cli",
]

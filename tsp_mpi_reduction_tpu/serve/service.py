"""The batched solve service: JSONL requests in, JSONL responses out.

Request schema (one JSON object per line):

    {"id": "r1",                      # optional, echoed back
     "xy": [[x0, y0], [x1, y1], ...], # [n, 2] city coordinates (required)
     "deadline_ms": 250.0}            # optional latency budget

Response schema (one JSON object per line, same order as requests):

    {"id": "r1", "n": 12,
     "cost": 123.4,                  # measured cost of the returned tour
     "tour": [0, 5, ..., 0],         # CLOSED tour in the request's city ids
     "tier": "bnb|pipeline|greedy",  # which ladder rung answered
     "certified_gap": 0.0,           # 0 proven/exact, >0 certified, null none
     "cache": "hit|miss|refresh",    # refresh = cached non-exact answer
                                     #   re-solved by a stronger rung
                                     #   because this budget allowed it
     "latency_ms": 1.9,
     "deadline_ms": 250.0,
     "deadline_missed": false}

or ``{"id": ..., "error": "..."}`` for malformed requests (never for a
tight deadline — the greedy rung answers those).

Per request: canonicalize (``serve.canonical``) -> LRU lookup
(``serve.cache``; a hit relabels the cached canonical tour into this
request's city order and re-measures its true cost) -> on miss, the
deadline ladder (``serve.ladder``) solves it, micro-batching exact
Held-Karp work across concurrent requests (``serve.scheduler``), and the
canonical solution is cached for every future translation/permutation of
the same instance.
"""

from __future__ import annotations

import json
import sys
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Dict, IO, Iterable, List, Optional

import numpy as np

from ..obs import metrics as _metrics
from ..obs import slo as _slo
from ..obs import tracing as _tracing
from ..obs.metrics import REGISTRY as _REGISTRY
from ..resilience.faults import TransientFault
from ..resilience.health import HEALTH
from ..resilience.retry import RetryPolicy
from ..utils import reporting
from ..utils.profiling import PhaseTimer
from . import canonical as canon
from .cache import CacheEntry, SolutionCache
from .ladder import DeadlineLadder, LadderConfig
from .scheduler import MicroBatchScheduler


@dataclass
class ServiceConfig:
    cache_capacity: int = 4096
    quant_step: float = canon.DEFAULT_STEP
    max_batch: int = 64
    max_wait_ms: float = 2.0
    dtype: str = "float32"
    default_deadline_ms: float = 1000.0
    threads: int = 8
    #: scheduler-worker watchdog cadence and the silence threshold past
    #: which an alive-but-wedged worker is abandoned and replaced
    watchdog_interval_s: float = 0.2
    stuck_timeout_s: float = 30.0
    #: block sizes to precompile at service start (scheduler.precompile):
    #: every (n, bucket) pair is AOT-warmed before the first request, so
    #: no request ever pays a cold XLA compile inside its flush. Empty =
    #: no warmup (the pre-PR-5 behavior)
    warm_shapes: tuple = ()
    #: the bound metrics endpoint port (set by serve_cli when
    #: --metrics-port is given) — surfaced in the stats ``obs`` block so
    #: a log line names its own scrape target
    metrics_port: Optional[int] = None
    #: shared disk-backed cache tier directory (ISSUE 11): when set, the
    #: instance cache becomes a two-level tier — the in-proc LRU over
    #: atomic-publish entry files every fleet replica shares, so a
    #: resubmission hits regardless of which replica solved it and a
    #: restarted replica warm-fills from the fleet's collective work
    #: (``fleet.shared_cache.TieredSolutionCache``)
    shared_cache_dir: Optional[str] = None
    #: per-tier latency objectives (ISSUE 9): tier -> {"target_ms",
    #: "goal"}. Evaluated over THIS session's tier-labeled latency
    #: histograms into the stats ``slo`` block (attainment + error-budget
    #: burn rate — obs.slo). Empty dict = no objectives (block still
    #: present, tiers listed unjudged).
    slos: Dict[str, Dict[str, float]] = field(
        default_factory=lambda: {k: dict(v) for k, v in _slo.DEFAULT_SLOS.items()}
    )
    ladder: LadderConfig = field(default_factory=LadderConfig)


class SolveService:
    """Thread-safe request handler; owns the scheduler worker and cache."""

    def __init__(self, cfg: Optional[ServiceConfig] = None) -> None:
        self.cfg = cfg or ServiceConfig()
        # shared across worker + request threads; phases mirror into the
        # obs registry alongside every other serve signal
        self.timer = PhaseTimer(mirror_metric="phase_seconds_total")
        if self.cfg.shared_cache_dir:
            from ..fleet.shared_cache import TieredSolutionCache

            self.cache: SolutionCache = TieredSolutionCache(
                self.cfg.cache_capacity, self.cfg.shared_cache_dir
            )
        else:
            self.cache = SolutionCache(self.cfg.cache_capacity)
        #: the LIVE admission-control signal (ISSUE 13): windowed per-tier
        #: error-budget burn over recent answers, shared by the scheduler
        #: (burning-tier priority) and the ladder (shed/degrade new
        #: admissions) — not static queue depth
        self.burn = _slo.BurnMeter(self.cfg.slos)
        self.scheduler = MicroBatchScheduler(
            max_batch=self.cfg.max_batch,
            max_wait_ms=self.cfg.max_wait_ms,
            dtype=self.cfg.dtype,
            timer=self.timer,
            watchdog_interval_s=self.cfg.watchdog_interval_s,
            stuck_timeout_s=self.cfg.stuck_timeout_s,
            burn_meter=self.burn,
        )
        self.ladder = DeadlineLadder(
            self.scheduler, self.cfg.ladder, burn_meter=self.burn
        )
        #: canonicalization memo: skips the per-request lexsort for
        #: byte-identical (post-quantization) resubmissions — the trimmed
        #: host path around the frozen kernel (see canonical.CanonicalCache)
        self.canon_cache = canon.CanonicalCache(self.cfg.cache_capacity)
        if self.cfg.warm_shapes:
            self.scheduler.precompile(self.cfg.warm_shapes)
        #: health baseline at service start: the stats JSON reports the
        #: DELTA, so back-to-back sessions in one process (tests, bench
        #: legs, embedded services) stop seeing each other's recoveries
        self._health0 = HEALTH.snapshot()
        #: latency-histogram baseline: the SLO window is this session
        #: (same delta discipline as health — a prior session's misses
        #: must not burn this session's error budget)
        self._latency0 = _REGISTRY.snapshot(prefix="serve_request_seconds")
        #: queue-age baseline (same delta discipline): the admission block
        #: reports THIS session's wait-time percentiles
        self._queue_age0 = _REGISTRY.snapshot(prefix="serve_queue_age_seconds")
        self.responses = 0
        self.errors = 0
        self.deadline_misses = 0
        self.refreshes = 0  #: cache entries upgraded by a stronger rung
        # the counters above are read-modify-written from every request
        # thread (and errors from run_jsonl's reader thread too) — same
        # lost-update hazard the PhaseTimer lock exists for
        self._stats_lock = threading.Lock()

    def _record_error(self) -> None:
        with self._stats_lock:
            self.errors += 1
        _REGISTRY.inc("serve_errors_total")

    # a transient cache fault (the cache.get/cache.put seams) must never
    # cost a request its answer: retry briefly, then degrade — a failed
    # lookup becomes a miss (re-solve), a failed insert is dropped (the
    # next request for the instance just misses too)
    _cache_retry = RetryPolicy(max_attempts=2, base_delay_s=0.005, seed=0)

    def _cache_get(self, key: str) -> Optional[CacheEntry]:
        try:
            return self._cache_retry.call(lambda: self.cache.get(key))
        except TransientFault:
            return None

    def _cache_put(self, key: str, entry: CacheEntry) -> None:
        try:
            self._cache_retry.call(lambda: self.cache.put(key, entry))
        except TransientFault:
            pass

    # -- one request ---------------------------------------------------------

    def handle(self, request: Dict) -> Dict:
        # root span of this request's trace: every stage below (cache
        # lookup, ladder rung, queue wait, the worker's flush) parents
        # back to it, so one serve request = one complete span tree —
        # error/degraded paths included (the finally-emitted root closes
        # the tree either way). A fleet front threads its per-request
        # ``trace_parent`` token (the TSP_TRACE_PARENT encoding) through
        # the request line; the root then joins the front's trace, and
        # is ANNOUNCED at open so a replica killed mid-request cannot
        # orphan its already-closed child spans (obs.tracing.span).
        parent = _tracing.parse_parent_token(request.get("trace_parent"))
        with _tracing.span(
            "serve.request",
            parent=parent,
            announce=parent is not None,
            id=request.get("id"),
        ) as root:
            resp = self._handle_traced(request, root)
            root.set("tier", resp.get("tier"))
            if "error" in resp:
                root.set("error", resp["error"])
            return resp

    def _handle_traced(self, request: Dict, root) -> Dict:
        t0 = time.monotonic()
        req_id = request.get("id")
        try:
            xy = np.asarray(request["xy"], np.float64)
            deadline_ms = float(
                request.get("deadline_ms", self.cfg.default_deadline_ms)
            )
            with self.timer.phase("serve.canonicalize"), _tracing.span(
                "canonicalize"
            ):
                ci = canon.canonicalize_cached(
                    xy, self.canon_cache, self.cfg.quant_step
                )
        except (KeyError, TypeError, ValueError) as e:
            self._record_error()
            return {"id": req_id, "error": str(e)}

        with _tracing.span("cache.lookup") as csp:
            entry = self._cache_get(ci.key)
            csp.set("result", "miss" if entry is None else "hit")
        # a non-exact cached answer does not pin the instance forever: a
        # request whose budget fits a STRONGER rung re-solves ("refresh")
        # and the cache's better-entry policy keeps whichever tour wins
        upgrade = entry is not None and self.ladder.upgrade_eligible(
            ci.n, deadline_ms / 1000.0, entry.tier, entry.certified_gap
        )
        if entry is not None and not upgrade:
            tour = canon.from_canonical_tour(entry.tour, ci)
            cost = canon.tour_length_np(tour, xy)
            tier, gap, provenance = entry.tier, entry.certified_gap, "hit"
        else:
            with self.timer.phase("serve.solve"):
                res = self.ladder.solve(xy, deadline_ms / 1000.0)
            tour = res.tour
            # report (and cache) the re-measured f64 length of the actual
            # tour, not the solver's f32 device value — the response cost
            # is then consistent between miss and hit paths
            cost = canon.tour_length_np(tour, xy)
            new_entry = CacheEntry(
                cost=cost,
                tour=canon.to_canonical_tour(tour, ci),
                certified_gap=res.certified_gap,
                tier=res.tier,
            )
            self._cache_put(ci.key, new_entry)
            if entry is not None and entry.better_than(new_entry):
                # the upgrade attempt lost (e.g. bnb timed out worse than
                # the cached tour) — serve the cached answer, honestly
                tour = canon.from_canonical_tour(entry.tour, ci)
                cost = canon.tour_length_np(tour, xy)
                tier, gap, provenance = entry.tier, entry.certified_gap, "hit"
            else:
                tier, gap = res.tier, res.certified_gap
                provenance = "refresh" if upgrade else "miss"
            if upgrade:
                with self._stats_lock:
                    self.refreshes += 1

        latency_ms = (time.monotonic() - t0) * 1000.0
        missed = latency_ms > deadline_ms
        with self._stats_lock:
            self.responses += 1
            if missed:
                self.deadline_misses += 1
        _REGISTRY.inc("serve_responses_total", cache=provenance)
        if missed:
            _REGISTRY.inc("serve_deadline_misses_total")
        # tier-labeled: the SLO evaluator judges each rung against ITS
        # objective (a greedy answer in 40 ms is healthy; a bnb one is
        # suspicious). Tier values come from the fixed ladder set — never
        # from request fields (graftlint R13 bounds label cardinality).
        _REGISTRY.observe(
            "serve_request_seconds", latency_ms / 1000.0, tier=tier
        )
        # feed the LIVE burn meter: this answer immediately moves the
        # admission/priority signal the scheduler and ladder read
        self.burn.observe(tier, latency_ms / 1000.0)
        with _tracing.span("respond"):
            return {
                "id": req_id,
                "n": int(xy.shape[0]),
                "cost": float(cost),
                "tour": [int(c) for c in tour],
                "tier": tier,
                "certified_gap": None if gap is None else float(gap),
                "cache": provenance,
                "latency_ms": round(latency_ms, 3),
                "deadline_ms": deadline_ms,
                "deadline_missed": bool(missed),
            }

    # -- stats / lifecycle ---------------------------------------------------

    def stats_json(self) -> str:
        with self._stats_lock:
            responses, errors = self.responses, self.errors
            misses, refreshes = self.deadline_misses, self.refreshes
        from ..perf import compile_cache as perf_cache

        # the canonicalization memo rides in the cache block: its saved
        # sorts are the host-path half of the serve cache story
        cache_stats = dict(self.cache.stats(), **{
            f"canonical_{k}": v for k, v in self.canon_cache.stats().items()
        })
        # locked snapshots (graftflow R9): request threads increment the
        # ladder counts and timer phases while this reporting path runs
        tier_counts, rung_failures = self.ladder.counts_snapshot()
        # SLO verdicts over THIS session's tier-labeled latency
        # histograms (delta vs the service-start snapshot): attainment
        # against each tier's target + error-budget burn rate (obs.slo)
        lat = _REGISTRY.delta(self._latency0, prefix="serve_request_seconds")
        hists_by_tier = {
            dict(key).get("tier", "?"): v
            for key, v in lat.data.get(
                "serve_request_seconds", {}
            ).get("series", {}).items()
            if isinstance(v, dict)
        }
        slo_block = _slo.evaluate(hists_by_tier, self.cfg.slos)
        sched_stats = self.scheduler.stats()
        # admission/preemption block (ISSUE 13): the live burn signal the
        # scheduler steered by, what it cost (sheds, preemptions), and how
        # long work actually queued — the continuous-batching story in one
        # place for tools/obs_report.py --serve
        qage = _REGISTRY.delta(self._queue_age0, prefix="serve_queue_age_seconds")
        qage_hist = None
        for v in qage.data.get(
            "serve_queue_age_seconds", {}
        ).get("series", {}).values():
            if isinstance(v, dict):
                qage_hist = v
                break
        admission = {
            "burn": self.burn.snapshot(),
            "slo_sheds": sched_stats.get("slo_sheds", 0),
            "preemptions": sched_stats.get("bnb_preemptions", 0),
            "resumes": sched_stats.get("bnb_resumes", 0),
            "admit_flushes": sched_stats.get("admit_flushes", 0),
            "queue_age_s": {
                "count": int(qage_hist.get("count", 0)) if qage_hist else 0,
                "p50": _metrics.hist_quantile(qage_hist, 0.50) if qage_hist else None,
                "p90": _metrics.hist_quantile(qage_hist, 0.90) if qage_hist else None,
                "p99": _metrics.hist_quantile(qage_hist, 0.99) if qage_hist else None,
            },
        }
        return reporting.service_stats_json(
            responses=responses,
            errors=errors,
            deadline_misses=misses,
            refreshes=refreshes,
            rung_failures=rung_failures,
            tier_counts=tier_counts,
            cache=cache_stats,
            scheduler=sched_stats,
            admission=admission,
            phases_s=self.timer.snapshot(),
            # THIS session's recoveries, not the process's lifetime count
            # (registry-backed delta; see resilience.health)
            health=HEALTH.delta_since(self._health0),
            compile_cache=perf_cache.stats_dict(),
            slo=slo_block,
            obs=reporting.obs_block(
                trace_path=_tracing.TRACER.path,
                metrics_port=self.cfg.metrics_port,
            ),
        )

    def close(self) -> None:
        self.scheduler.close()
        # drop the per-session B&B checkpoint directory (preempted-slice
        # snapshots are worthless once their jobs are resolved)
        self.ladder.cleanup()

    def __enter__(self) -> "SolveService":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def run_jsonl(
    lines: Iterable[str],
    out: IO[str],
    cfg: Optional[ServiceConfig] = None,
    service: Optional[SolveService] = None,
) -> SolveService:
    """Drive a service over a JSONL request stream.

    Requests are submitted to a thread pool as they are read (concurrency
    is what lets the scheduler batch them); a dedicated writer thread
    emits responses in INPUT order, flushed per line, AS they complete —
    an interactive client on a pipe sees each response without waiting
    for the input stream to end, and memory stays bounded (in-flight
    requests are capped, written responses are not retained). Returns the
    (closed) service so callers can read final stats.
    """
    import queue as _queue

    svc = service or SolveService(cfg)
    own = service is None
    #: (future, ready_response) pairs in input order; None = end of stream
    pending: "_queue.Queue" = _queue.Queue()
    # bound the in-flight window so an unbounded input stream cannot pile
    # up futures faster than the workers drain them
    window = threading.Semaphore(max(4 * svc.cfg.threads, 16))

    def _resolve(item) -> Dict:
        fut, ready = item
        if fut is None:
            return ready
        try:
            return fut.result()
        except Exception as e:  # noqa: BLE001 — the stream survives
            return {"id": None, "error": f"internal: {e}"}
        finally:
            window.release()

    def _writer() -> None:
        carried = None  # drained-but-unresolved item: next batch's head
        while True:
            item = carried if carried is not None else pending.get()
            carried = None
            if item is None:
                return
            # batch the JSONL encode: after blocking on the IN-ORDER head
            # response, opportunistically drain every further item whose
            # future is ALREADY resolved, so a burst costs ONE write+flush
            # instead of one syscall pair per response. An unresolved item
            # ends the batch (it becomes the next head) — batching must
            # never hold an already-ready response behind a pending
            # future, and a lone response still flushes immediately, so
            # interactive pipes keep their per-response latency.
            stop = False
            batch = [json.dumps(_resolve(item))]
            while True:
                try:
                    nxt = pending.get_nowait()
                except _queue.Empty:
                    break
                if nxt is None:
                    stop = True
                    break
                fut, _ready = nxt
                if fut is not None and not fut.done():
                    carried = nxt
                    break
                batch.append(json.dumps(_resolve(nxt)))
            try:
                out.write("\n".join(batch) + "\n")
                out.flush()
            except Exception:  # noqa: BLE001 — broken sink: keep draining
                pass  # the queue must drain or the reader deadlocks on window
            if stop:
                return

    writer = threading.Thread(target=_writer, name="serve-writer", daemon=True)
    writer.start()
    try:
        with ThreadPoolExecutor(
            max_workers=svc.cfg.threads, thread_name_prefix="serve-req"
        ) as pool:
            for line in lines:
                line = line.strip()
                if not line:
                    continue
                try:
                    req = json.loads(line)
                    if not isinstance(req, dict):
                        raise ValueError(f"request must be a JSON object, got {type(req).__name__}")
                except (json.JSONDecodeError, ValueError) as e:
                    svc._record_error()
                    pending.put((None, {"id": None, "error": f"bad request: {e}"}))
                    continue
                window.acquire()
                pending.put((pool.submit(svc.handle, req), None))
    finally:
        pending.put(None)
        writer.join()
        if own:
            svc.close()
    return svc


def serve_cli(argv: Optional[List[str]] = None) -> int:
    """``python -m tsp_mpi_reduction_tpu serve`` — see README "Serving"."""
    import argparse

    ap = argparse.ArgumentParser(
        prog="tsp-tpu serve",
        description="batched TSP solve service: JSONL requests -> JSONL responses",
    )
    ap.add_argument("--in", dest="inp", default="-", metavar="FILE",
                    help="JSONL request file ('-' = stdin)")
    ap.add_argument("--out", dest="outp", default="-", metavar="FILE",
                    help="JSONL response file ('-' = stdout)")
    ap.add_argument("--backend", default="auto", choices=["auto", "cpu", "tpu"])
    ap.add_argument("--max-batch", type=int, default=64)
    ap.add_argument("--max-wait-ms", type=float, default=2.0)
    ap.add_argument("--cache-size", type=int, default=4096)
    ap.add_argument("--threads", type=int, default=8)
    ap.add_argument("--default-deadline-ms", type=float, default=1000.0)
    ap.add_argument("--shared-cache", default=None, metavar="DIR",
                    help="shared disk-backed cache tier directory (ISSUE "
                    "11): layers atomic-publish entry files under the "
                    "in-proc LRU so fleet replicas share one instance "
                    "cache and restarts warm-fill from it")
    ap.add_argument("--warm", default="",
                    help="comma-separated block sizes to precompile before "
                    "serving (e.g. 8,12,16): every (size, bucket) pair is "
                    "AOT-warmed so no request pays a cold XLA compile")
    ap.add_argument("--stats", action="store_true",
                    help="print the service stats JSON line to stderr on exit")
    ap.add_argument("--trace", default=None, metavar="FILE",
                    help="span-trace JSONL sink: every request emits a "
                    "span tree (request -> canonicalize -> cache lookup "
                    "-> queue wait -> flush -> rung -> respond); render "
                    "with tools/obs_report.py (env: TSP_TRACE)")
    ap.add_argument("--metrics-port", type=int, default=None, metavar="PORT",
                    help="serve the obs metrics registry over HTTP on "
                    "127.0.0.1:PORT (/metrics = Prometheus text "
                    "exposition, /metrics.json = structured snapshot)")
    args = ap.parse_args(argv)

    from ..utils.backend import enable_persistent_cache, select_backend

    platform = select_backend(args.backend)
    enable_persistent_cache(platform)

    try:
        warm_shapes = tuple(
            int(tok) for tok in args.warm.split(",") if tok.strip()
        )
    except ValueError:
        print(f"error: --warm expects comma-separated ints, got {args.warm!r}",
              file=sys.stderr)
        return 2
    cfg = ServiceConfig(
        cache_capacity=args.cache_size,
        max_batch=args.max_batch,
        max_wait_ms=args.max_wait_ms,
        threads=args.threads,
        default_deadline_ms=args.default_deadline_ms,
        warm_shapes=warm_shapes,
        shared_cache_dir=args.shared_cache,
    )
    # ExitStack closes BOTH handles deterministically on every path — with
    # the old two-bare-open form, a failing open of the output leaked the
    # already-open input, and a mid-stream exception could drop buffered
    # output lines. The flush in the finally covers the stdout case (not
    # closed) AND the error path of a file sink before its close.
    from contextlib import ExitStack

    if args.trace:
        _tracing.configure(args.trace)
    metrics_server = None
    if args.metrics_port is not None:
        from ..obs.metrics import serve_metrics_http

        try:
            # port 0 = ephemeral (multi-instance runs stop colliding);
            # the BOUND port is what the stats obs block reports
            metrics_server = serve_metrics_http(args.metrics_port)
        except OSError as e:
            print(f"error: cannot bind metrics port: {e}", file=sys.stderr)
            return 2
        cfg.metrics_port = metrics_server.port
        print(
            f"metrics: http://127.0.0.1:{cfg.metrics_port}/metrics",
            file=sys.stderr,
        )
    with ExitStack() as stack:
        inp = sys.stdin if args.inp == "-" else stack.enter_context(open(args.inp))
        outp = (
            sys.stdout
            if args.outp == "-"
            # a live JSONL response stream, flushed per line by the writer
            # thread — atomic publish would defeat its purpose
            else stack.enter_context(open(args.outp, "w"))  # graftlint: disable=R6
        )
        try:
            svc = run_jsonl(inp, outp, cfg)
        finally:
            try:
                outp.flush()
            except (OSError, ValueError):
                pass  # broken pipe / already closed: nothing left to save
            if metrics_server is not None:
                # graceful: stop the loop AND release the socket, so a
                # follow-up instance can rebind the port immediately
                metrics_server.close()
    if args.stats:
        print(svc.stats_json(), file=sys.stderr)
    return 0

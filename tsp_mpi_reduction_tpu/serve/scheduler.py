"""Iteration-level micro-batch scheduler: one device loop, preemptible units.

The ORCA idea (OSDI '22, PAPERS.md) applied to the repo's exact solvers:
the worker thread runs a device LOOP whose unit of scheduling is one
bounded device interaction — a padded Held-Karp vmap flush, or one
time-sliced chunk of a B&B proof — never a whole request. Between units
the loop re-reads the queues, so newly arrived work is admitted and
finished work retired at iteration granularity instead of cohort
granularity.

Two lanes feed the loop:

- **HK tickets** (:meth:`~MicroBatchScheduler.submit`): ``[B, n, n]``
  block distance stacks; same-shape groups are concatenated, padded up to
  a compile bucket, and solved in ONE ``solve_blocks_from_dists`` call.
- **B&B jobs** (:meth:`~MicroBatchScheduler.submit_bnb`): certified
  branch & bound proofs, run ``slice_s`` seconds at a time through
  ``models.branch_bound.solve_slice``. A slice that ends unproven with
  budget remaining is **preempted**: the search state persists through
  the crash-safe donated checkpoint path (``branch_bound.save`` /
  ``restore``) and the job re-queues behind its peers (round-robin
  fairness), to be **resumed bit-identically** later — a long proof can
  no longer monopolize the device (BENCH_SERVE's all-greedy tight-
  deadline failure mode).

Latency discipline (the "max-wait knob"): an HK group flushes as soon as
``max_batch`` blocks are pending, and otherwise no later than
``max_wait_ms`` after the OLDEST pending submission arrived — batching can
add at most ``max_wait_ms`` to any request, never unbounded queueing
delay. While B&B work holds the device, pending HK tickets flush into the
next gap immediately (cause ``admit``) rather than sitting out the knob.

Admission signal: an optional ``obs.slo.BurnMeter`` feeds the loop's
tie-break — when the ``bnb`` tier's error budget burns hot, a ready B&B
slice takes priority over a not-yet-due HK flush (alternation still
bounds either lane's wait to one unit); the ladder uses the same meter to
shed/degrade NEW admissions (:meth:`~MicroBatchScheduler.note_shed`).
Every flush/preempt/shed lands in ``serve_flushes_total{cause=}`` and
every flushed ticket's queue wait in ``serve_queue_age_seconds``, so the
loop's scheduling decisions are diagnosable after the fact.

Compile discipline: batch sizes are padded up to fixed power-of-two
``buckets`` (pad lanes replicate the first real block; vmap lanes are
independent, so real lanes are bit-identical to an unpadded run). Without
bucketing every distinct batch size would trigger a fresh XLA compile —
the classic serving recompile storm.

Device work and host readbacks happen ONLY in :meth:`_run_batch`, called
once per flush from the worker loop — the loop body itself stays free of
per-iteration device traffic (graftlint R1/R4 discipline).

Self-healing (the resilience layer): the worker thread is supervised by a
WATCHDOG. A worker that dies (an exception escaping the flush — e.g. the
``sched.flush`` fault seam) or goes silent mid-batch past
``stuck_timeout_s`` is replaced: unresolved in-flight tickets are
re-queued at the FRONT of the queue (arrival order preserved) and a fresh
worker generation takes over; an abandoned-but-alive worker notices its
stale generation at the next queue interaction and exits. Restarts count
into the scheduler stats and the global ``HEALTH`` block — a dead worker
can no longer silently strand every queued request.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Deque, Dict, List, Optional, Tuple

import numpy as np

from ..obs import tracing as _tracing
from ..obs.metrics import REGISTRY as _REGISTRY
from ..ops.held_karp import MAX_BLOCK_CITIES
from ..resilience.faults import registry as _fault_registry
from ..resilience.health import HEALTH
from ..utils.profiling import PhaseTimer

_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128)


class Ticket:
    """One pending submission: request threads block on :meth:`wait`.

    Outcomes are FIRST-WRITER-WINS: after a watchdog revive, a ticket can
    be re-solved by the successor generation while the abandoned worker
    still holds a reference — whichever outcome lands first sticks, so a
    stale worker's late failure can never mask a valid replacement result
    (nor vice versa)."""

    __slots__ = (
        "dists", "arrived", "ctx", "queue_age_s", "_event", "_costs",
        "_tours", "_error", "_claim", "_done",
    )

    def __init__(self, dists: np.ndarray) -> None:
        self.dists = dists
        #: the submitting thread's span context (trace_id, span_id) — the
        #: worker parents its flush span here, so the device work a
        #: request waited on lands in that request's own trace
        self.ctx = _tracing.current_context()
        self.arrived = time.monotonic()
        #: queue wait stamped when the worker takes the ticket into a
        #: flush — lets the ladder's latency estimator learn SERVICE time
        #: (queueing is transient congestion, not a property of the rung)
        self.queue_age_s: Optional[float] = None
        self._event = threading.Event()
        self._costs: Optional[np.ndarray] = None
        self._tours: Optional[np.ndarray] = None
        self._error: Optional[BaseException] = None
        self._claim = threading.Lock()
        self._done = False

    def _take_outcome(self) -> bool:
        with self._claim:
            if self._done:
                return False
            self._done = True
            return True

    def _resolve(self, costs: np.ndarray, tours: np.ndarray) -> None:
        if not self._take_outcome():
            return
        self._costs, self._tours = costs, tours
        self._event.set()

    def _fail(self, exc: BaseException) -> None:
        if not self._take_outcome():
            return
        self._error = exc
        self._event.set()

    def wait(self, timeout: Optional[float] = None):
        """Block until solved. Returns ``(costs [B], tours [B, n+1])`` as
        numpy arrays, raises the worker's exception if the batch failed,
        or returns ``None`` on timeout (the caller degrades to a lower
        ladder rung; the batch still completes and is simply discarded)."""
        if not self._event.wait(timeout):
            return None
        if self._error is not None:
            raise self._error
        return self._costs, self._tours


class BnBJob:
    """One step-sliced B&B proof owned by the scheduler's device loop.

    Request threads block on :meth:`wait` exactly like a :class:`Ticket`;
    the worker runs the proof ``slice_s`` seconds at a time and re-queues
    the job between slices (``handle`` carries the checkpoint-backed
    continuation). Outcomes are first-writer-wins for the same reason as
    tickets: after a watchdog revive, an abandoned worker can race its
    successor on the same job."""

    __slots__ = (
        "dists", "solve_kw", "slice_s", "deadline", "checkpoint_path",
        "arrived", "ctx", "handle", "last_result", "preemptions", "resumes",
        "first_pickup", "_event", "_result", "_error", "_claim", "_done",
    )

    def __init__(
        self,
        dists: np.ndarray,
        slice_s: float,
        budget_s: float,
        checkpoint_path: str,
        solve_kw: Optional[Dict] = None,
    ) -> None:
        self.dists = dists
        self.solve_kw = dict(solve_kw or {})
        self.slice_s = slice_s
        self.deadline = time.monotonic() + budget_s
        self.checkpoint_path = checkpoint_path
        self.arrived = time.monotonic()
        self.ctx = _tracing.current_context()
        self.handle = None  #: branch_bound.ResumeHandle between slices
        self.last_result = None  #: best-so-far BnBResult (deadline answer)
        self.preemptions = 0
        self.resumes = 0
        self.first_pickup: Optional[float] = None
        self._event = threading.Event()
        self._result = None
        self._error: Optional[BaseException] = None
        self._claim = threading.Lock()
        self._done = False

    def _take_outcome(self) -> bool:
        with self._claim:
            if self._done:
                return False
            self._done = True
            return True

    def _resolve(self, result) -> None:
        if not self._take_outcome():
            return
        self._result = result
        self._event.set()

    def _fail(self, exc: BaseException) -> None:
        if not self._take_outcome():
            return
        self._error = exc
        self._event.set()

    def wait(self, timeout: Optional[float] = None):
        """Block until the proof finishes or its budget lapses. Returns
        the final ``BnBResult`` (proven, or best-so-far with its certified
        bound at the deadline), raises the worker's exception on failure,
        or returns ``None`` on timeout (the caller degrades)."""
        if not self._event.wait(timeout):
            return None
        if self._error is not None:
            raise self._error
        return self._result


class MicroBatchScheduler:
    def __init__(
        self,
        max_batch: int = 64,
        max_wait_ms: float = 2.0,
        dtype: str = "float32",
        buckets: Tuple[int, ...] = _BUCKETS,
        timer: Optional[PhaseTimer] = None,
        watchdog_interval_s: float = 0.2,
        stuck_timeout_s: float = 30.0,
        burn_meter=None,
    ) -> None:
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        self.max_batch = max_batch
        self.max_wait_s = max_wait_ms / 1000.0
        self.dtype = dtype
        self.buckets = tuple(sorted(set(buckets) | {max_batch}))
        self.timer = timer or PhaseTimer()
        self.watchdog_interval_s = watchdog_interval_s
        self.stuck_timeout_s = stuck_timeout_s
        #: optional obs.slo.BurnMeter — the live admission-control signal
        self.burn_meter = burn_meter
        self._cv = threading.Condition()
        self._queue: Deque[Ticket] = deque()
        #: round-robin queue of step-sliced B&B proofs (fairness: one
        #: slice each, preempted jobs go to the back)
        self._bnb_queue: Deque[BnBJob] = deque()
        #: the job the worker is slicing right now — what the watchdog
        #: re-queues alongside ``_inflight`` when that worker dies (the
        #: slice re-runs from the last donated checkpoint: crash-safe)
        self._inflight_bnb: Optional[BnBJob] = None
        #: alternation guard: a burning bnb tier may take priority over a
        #: ready HK flush, but never twice in a row (neither lane starves)
        self._last_was_bnb = False
        self._thread: Optional[threading.Thread] = None
        self._watchdog: Optional[threading.Thread] = None
        self._stop = False
        #: tickets popped by the current worker but not yet resolved — what
        #: the watchdog re-queues when that worker dies or wedges
        self._inflight: List[Ticket] = []
        #: worker generation: bumped on every (re)start; a worker whose
        #: generation is stale has been replaced and must stand down
        self._gen = 0
        self._heartbeat = time.monotonic()
        #: current stuck threshold: doubles after every stuck-revive and
        #: resets when a batch completes cleanly. The watchdog cannot
        #: tell a wedged worker from a legitimately long batch (a cold
        #: XLA compile blocks _run_batch well past any fixed timeout), so
        #: successive generations get exponentially more patience — a
        #: genuine wedge is still caught fast, while a long first compile
        #: costs at most a logarithmic number of duplicate dispatches
        #: (idempotent tickets keep results correct either way)
        self._stuck_allowance = stuck_timeout_s
        # -- counters (reported via utils.reporting.service_stats_json) --
        self.batches = 0  #: device calls issued
        self.blocks_solved = 0  #: real (non-padding) blocks solved
        self.padded_blocks = 0  #: total lanes dispatched incl. padding
        self.queue_depth_hwm = 0  #: max pending blocks ever queued
        self.full_flushes = 0  #: flushes triggered by max_batch
        self.wait_flushes = 0  #: flushes triggered by the max-wait knob
        self.worker_restarts = 0  #: dead workers replaced by the watchdog
        self.stuck_restarts = 0  #: wedged workers abandoned + replaced
        self.precompiled_buckets = 0  #: shape buckets warmed by precompile()
        self.precompile_seconds = 0.0  #: wall spent in precompile()
        # -- iteration-level loop counters (ISSUE 13) --
        self.admit_flushes = 0  #: HK flushes pulled forward into a B&B gap
        self.bnb_jobs = 0  #: B&B proofs submitted
        self.bnb_slices = 0  #: device slices run (>= jobs when preempting)
        self.bnb_preemptions = 0  #: slices preempted with budget remaining
        self.bnb_resumes = 0  #: preempted proofs resumed from checkpoint
        self.slo_sheds = 0  #: admissions shed/degraded by the burn signal

    # -- warmup --------------------------------------------------------------

    def precompile(self, block_sizes, max_blocks: Optional[int] = None) -> int:
        """AOT-compile the solve kernel for every (block size, bucket)
        this scheduler can dispatch, BEFORE traffic arrives.

        Without this, the first flush of each shape pays the full XLA
        compile inside the worker's ``_run_batch`` — the serving
        recompile storm bucketing was designed to bound, but the FIRST
        request of each bucket still ate it (BENCH_SERVE's 3.76x->1.56x
        service gap is mostly this cold flush plus host path). Compiles
        go through ``ops.held_karp.warm_blocks``: the AOT store when the
        perf cache is enabled, jax's persistent compilation cache
        regardless — so a restarted service warms from disk in ms.

        ``block_sizes``: iterable of block city counts n to warm.
        ``max_blocks``: warm buckets up to this many blocks (default
        ``max_batch``). Returns the number of (n, bucket) entries warmed;
        failures are counted and skipped, never raised (warmup must not
        take the service down).
        """
        from ..ops.held_karp import MAX_BLOCK_CITIES, warm_blocks

        cap = self.max_batch if max_blocks is None else max_blocks
        buckets = [b for b in self.buckets if b <= cap] or [self.buckets[0]]
        warmed = 0
        t0 = time.monotonic()
        for n in block_sizes:
            n = int(n)
            if not 3 <= n <= MAX_BLOCK_CITIES:
                continue
            for b in buckets:
                try:
                    warm_blocks(n, b, self.dtype)
                    warmed += 1
                except Exception:  # noqa: BLE001 — warmup is best-effort
                    continue
        with self._cv:  # stats() snapshots these under the same lock
            self.precompiled_buckets += warmed
            self.precompile_seconds += time.monotonic() - t0
        return warmed

    # -- submission ----------------------------------------------------------

    def submit(self, block_dists: np.ndarray) -> Ticket:
        """Enqueue a ``[B, n, n]`` stack of block distance matrices.

        Validation errors raise HERE, synchronously, so a malformed request
        can never poison a shared batch."""
        d = np.asarray(block_dists)
        if d.ndim != 3 or d.shape[1] != d.shape[2]:
            raise ValueError(f"expected [B, n, n] block dists, got {d.shape}")
        n = int(d.shape[1])
        if not 3 <= n <= MAX_BLOCK_CITIES:
            raise ValueError(
                f"block size must be in [3, {MAX_BLOCK_CITIES}], got {n}"
            )
        if d.shape[0] < 1:
            raise ValueError("empty block stack")
        ticket = Ticket(d)
        with self._cv:
            if self._stop:
                raise RuntimeError("scheduler is closed")
            self._ensure_threads_locked()
            self._queue.append(ticket)
            depth = sum(t.dists.shape[0] for t in self._queue)
            self.queue_depth_hwm = max(self.queue_depth_hwm, depth)
            self._cv.notify_all()
        _REGISTRY.set_gauge("serve_queue_depth_blocks", depth)
        return ticket

    def submit_bnb(
        self,
        d: np.ndarray,
        *,
        budget_s: float,
        slice_s: float,
        checkpoint_path: str,
        solve_kw: Optional[Dict] = None,
    ) -> BnBJob:
        """Enqueue one certified B&B proof on the iteration-level loop.

        ``d``: dense [n, n] distance matrix. ``budget_s``: wall budget
        from NOW — at the deadline the job resolves with its best-so-far
        result and certified bound. ``slice_s``: preemption granularity
        (a slice that ends unproven re-queues behind other work).
        ``checkpoint_path``: where the between-slice snapshot lives; must
        be unique per job. ``solve_kw`` forwards to
        ``models.branch_bound.solve`` (identical across slices).
        Validation raises here, synchronously, like :meth:`submit`."""
        d = np.asarray(d)
        if d.ndim != 2 or d.shape[0] != d.shape[1]:
            raise ValueError(f"expected [n, n] distance matrix, got {d.shape}")
        if d.shape[0] < 3:
            raise ValueError(f"B&B needs n >= 3 cities, got {d.shape[0]}")
        if not budget_s > 0 or not slice_s > 0:
            raise ValueError(
                f"budget_s and slice_s must be > 0, got {budget_s}, {slice_s}"
            )
        if not checkpoint_path:
            raise ValueError("submit_bnb needs a checkpoint_path")
        job = BnBJob(d, slice_s, budget_s, checkpoint_path, solve_kw)
        with self._cv:
            if self._stop:
                raise RuntimeError("scheduler is closed")
            self._ensure_threads_locked()
            self._bnb_queue.append(job)
            self.bnb_jobs += 1
            self._cv.notify_all()
        return job

    def note_shed(self, tier: str) -> None:
        """Record one admission shed/degraded by the SLO burn signal (the
        ladder calls this when it skips a burning tier for a new request
        — scheduling accounting lives here with the other causes)."""
        with self._cv:
            self.slo_sheds += 1
        _REGISTRY.inc("serve_flushes_total", cause="slo_shed")
        _REGISTRY.inc("serve_slo_sheds_total", tier=tier)
        HEALTH.incr("slo_sheds")

    def close(self) -> None:
        """Stop the worker + watchdog; pending tickets are failed, not
        dropped (in-flight tickets the worker abandoned included)."""
        with self._cv:
            self._stop = True
            self._cv.notify_all()
            # snapshot the thread handles UNDER the lock (graftflow R9):
            # a concurrent submit()'s revive path swaps them, and a stale
            # handle here would join a replaced worker while the live one
            # keeps running past close
            threads = (self._thread, self._watchdog)
        for th in threads:
            if th is not None:
                th.join(timeout=30.0)
        with self._cv:
            self._thread = None
            self._watchdog = None
            pending = [t for t in self._inflight if not t._event.is_set()]
            pending += list(self._queue)
            self._inflight = []
            self._queue.clear()
            jobs: List[BnBJob] = list(self._bnb_queue)
            if self._inflight_bnb is not None:
                jobs.append(self._inflight_bnb)
            self._bnb_queue.clear()
            self._inflight_bnb = None
        for t in pending:
            t._fail(RuntimeError("scheduler closed before solve"))
        for j in jobs:
            # a job that already ran slices resolves with its best-so-far
            # certified result — closing mid-proof degrades, never errors
            if j.last_result is not None:
                j._resolve(j.last_result)
            else:
                j._fail(RuntimeError("scheduler closed before solve"))
        _REGISTRY.set_gauge("serve_queue_depth_blocks", 0)

    # -- supervision ---------------------------------------------------------

    def _ensure_threads_locked(self) -> None:
        """Under ``self._cv``: make sure a live worker generation and the
        watchdog exist. A dead worker found HERE (between watchdog ticks)
        is revived immediately — submission must never race the interval."""
        if self._thread is not None and not self._thread.is_alive():
            self._revive_locked(stuck=False)
        elif self._thread is None:
            self._spawn_worker_locked()
        if self._watchdog is None or not self._watchdog.is_alive():
            self._watchdog = threading.Thread(
                target=self._watchdog_loop, name="serve-watchdog", daemon=True
            )
            self._watchdog.start()

    def _spawn_worker_locked(self) -> None:
        self._gen += 1
        self._heartbeat = time.monotonic()
        self._thread = threading.Thread(
            target=self._worker,
            args=(self._gen,),
            name=f"serve-microbatch-g{self._gen}",
            daemon=True,
        )
        self._thread.start()

    def _revive_locked(self, stuck: bool) -> None:
        """Replace the current worker generation: re-queue its unresolved
        in-flight tickets at the FRONT (arrival order preserved) and spawn
        a successor. For a STUCK (alive but silent) worker the old thread
        is abandoned — it exits at its next queue interaction when it sees
        its stale generation; any late result it still resolves is
        harmless (tickets resolve idempotently)."""
        live = [t for t in self._inflight if not t._event.is_set()]
        self._inflight = []
        self._queue.extendleft(reversed(live))
        # the slice the dead worker held re-runs from the last donated
        # checkpoint — deterministic search makes the re-run bit-identical,
        # so a crash mid-slice costs at most one slice of wall time
        job = self._inflight_bnb
        self._inflight_bnb = None
        if job is not None and not job._event.is_set():
            self._bnb_queue.appendleft(job)
        if stuck:
            self.stuck_restarts += 1
            HEALTH.incr("stuck_restarts")
            # see __init__: compile-vs-wedge. Capped so a PERSISTENTLY
            # wedging backend can't grow the allowance until stuck
            # detection is effectively disabled
            self._stuck_allowance = min(
                self._stuck_allowance * 2, 8 * self.stuck_timeout_s
            )
        else:
            self.worker_restarts += 1
            HEALTH.incr("worker_restarts")
        self._spawn_worker_locked()
        self._cv.notify_all()

    def _watchdog_loop(self) -> None:
        with self._cv:
            while not self._stop:
                self._cv.wait(self.watchdog_interval_s)
                if self._stop:
                    return
                worker = self._thread
                if worker is None:
                    continue
                if not worker.is_alive():
                    if (
                        self._queue
                        or self._inflight
                        or self._bnb_queue
                        or self._inflight_bnb is not None
                    ):
                        self._revive_locked(stuck=False)
                elif (
                    (self._inflight or self._inflight_bnb is not None)
                    and time.monotonic() - self._heartbeat > self._stuck_allowance
                ):
                    self._revive_locked(stuck=True)

    def __enter__(self) -> "MicroBatchScheduler":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- worker --------------------------------------------------------------

    def _prefer_bnb_locked(self) -> bool:
        """Under ``self._cv``: should a ready B&B slice jump a ready HK
        flush? Only when the burn meter says the bnb tier is eating its
        error budget faster than allowed AND faster than pipeline, and
        the PREVIOUS unit wasn't bnb (alternation bounds the HK lane's
        extra wait to one slice)."""
        if self.burn_meter is None or self._last_was_bnb:
            return False
        b = self.burn_meter.burn("bnb")
        if b is None or b <= 1.0:
            return False
        return b > (self.burn_meter.burn("pipeline") or 0.0)

    def _take_hk_locked(self, n: int, cause: str):
        """Under ``self._cv``: count the flush cause, pop the same-shape
        group, mark it in flight, and record each ticket's queue age."""
        if cause == "full":
            self.full_flushes += 1
        elif cause == "admit":
            self.admit_flushes += 1
        else:
            self.wait_flushes += 1
        _REGISTRY.inc("serve_flushes_total", cause=cause)
        group = self._pop_group(n)
        self._inflight = list(group)
        self._last_was_bnb = False
        now = time.monotonic()
        for t in group:
            t.queue_age_s = now - t.arrived
            _REGISTRY.observe("serve_queue_age_seconds", t.queue_age_s)
        return ("hk", group)

    def _collect(self, gen: int):
        """Under the condition lock: wait for the next schedulable unit.

        Returns ``("hk", [Ticket, ...])`` — the oldest submission plus
        every later pending ticket of the same block size, up to
        ``max_batch`` total blocks — or ``("bnb", BnBJob)`` — the next
        proof slice in round-robin order; None when shutting down with
        empty queues, or when this worker's generation has been
        superseded by the watchdog (stand down).

        An HK group is due on the classic conditions (full / max-wait /
        shutdown) and ADDITIONALLY whenever B&B work holds the loop —
        waiting out the knob while proof slices own the device would add
        a slice of latency for nothing (cause ``admit``). A due group
        normally goes first (HK units are the latency-sensitive lane);
        a burning bnb tier may take one slice of priority
        (:meth:`_prefer_bnb_locked`)."""
        with self._cv:
            while True:
                if self._gen != gen:
                    return None
                self._heartbeat = time.monotonic()
                bnb_pending = bool(self._bnb_queue)
                hk_cause = None
                waited = 0.0
                if self._queue:
                    head = self._queue[0]
                    pending = sum(
                        t.dists.shape[0]
                        for t in self._queue
                        if t.dists.shape[1] == head.dists.shape[1]
                    )
                    waited = time.monotonic() - head.arrived
                    if pending >= self.max_batch:
                        hk_cause = "full"
                    elif self._stop or waited >= self.max_wait_s:
                        hk_cause = "wait"
                    elif bnb_pending:
                        hk_cause = "admit"
                if bnb_pending and (
                    hk_cause is None or self._prefer_bnb_locked()
                ):
                    job = self._bnb_queue.popleft()
                    self._inflight_bnb = job
                    self._last_was_bnb = True
                    self.bnb_slices += 1
                    return ("bnb", job)
                if hk_cause is not None:
                    return self._take_hk_locked(
                        self._queue[0].dists.shape[1], hk_cause
                    )
                if self._stop:
                    return None
                if self._queue:
                    # batch still filling: sleep until the oldest request's
                    # wait budget lapses (or a new submission wakes us)
                    self._cv.wait(self.max_wait_s - waited)
                else:
                    self._cv.wait()

    def _pop_group(self, n: int) -> List[Ticket]:
        group: List[Ticket] = []
        total = 0
        keep: Deque[Ticket] = deque()
        while self._queue:
            t = self._queue.popleft()
            fits = total + t.dists.shape[0] <= self.max_batch
            # the head ticket is taken even when it alone exceeds max_batch
            # (an oversized submission must flush, not starve the queue)
            if t.dists.shape[1] == n and (fits or not group):
                group.append(t)
                total += t.dists.shape[0]
            else:
                keep.append(t)
        self._queue.extendleft(reversed(keep))
        # keep the depth gauge honest on the DRAIN side too: submit()
        # alone would leave the last pre-flush depth standing forever on
        # an idle service (phantom backlog on every dashboard)
        _REGISTRY.set_gauge(
            "serve_queue_depth_blocks",
            sum(t.dists.shape[0] for t in self._queue),
        )
        return group

    def _worker(self, gen: int) -> None:
        while True:
            work = self._collect(gen)
            if work is None:
                return
            kind, item = work
            if kind == "hk":
                self._run_batch(item)
            else:
                self._run_bnb_slice(item, gen)
            with self._cv:
                if self._gen == gen:
                    if kind == "hk":
                        self._inflight = []
                    elif self._inflight_bnb is item:
                        self._inflight_bnb = None
                    # a clean unit proves the worker healthy: restore
                    # the base stuck threshold for future batches
                    self._stuck_allowance = self.stuck_timeout_s

    def _run_bnb_slice(self, job: BnBJob, gen: int) -> None:
        """One preemptible slice of a certified proof, outside the lock.

        Runs at most ``job.slice_s`` seconds of ``branch_bound.solve``
        through the donated-checkpoint continuation (``solve_slice``). A
        slice that PROVES optimality (or exhausts the job's budget)
        resolves the job with its final/best-so-far certified result; a
        slice that ends unproven with budget remaining is a PREEMPTION —
        the job re-queues at the back (round-robin fairness) and its next
        pickup is a RESUME. Every outcome lands in the counters, the
        ``serve_flushes_total{cause=preempt}`` series, the ``HEALTH``
        block, and a ``bnb.slice`` span parented to the submitting
        request's trace."""
        from ..models.branch_bound import solve_slice

        now = time.monotonic()
        if job.first_pickup is None:
            job.first_pickup = now
            _REGISTRY.observe("serve_queue_age_seconds", now - job.arrived)
        remaining = job.deadline - now
        if remaining <= 0 and job.last_result is not None:
            # budget lapsed while queued: answer with the certified
            # best-so-far rather than spending device time past deadline
            job._resolve(job.last_result)
            return
        slice_s = min(job.slice_s, max(remaining, 0.05))
        resumed = job.handle is not None
        if resumed:
            job.resumes += 1
            with self._cv:
                self.bnb_resumes += 1
            _REGISTRY.inc("serve_bnb_resumes_total")
            HEALTH.incr("bnb_resumes")
        t0, ts0 = time.perf_counter(), time.time()
        error: Optional[str] = None
        preempted = proven = False
        try:
            res, handle = solve_slice(
                job.dists, slice_s, job.handle,
                checkpoint_path=job.checkpoint_path, **job.solve_kw,
            )
            job.last_result = res
            job.handle = handle
            proven = handle is None
            if proven or job.deadline - time.monotonic() <= 0:
                job._resolve(res)
            else:
                preempted = True
                job.preemptions += 1
                with self._cv:
                    self.bnb_preemptions += 1
                    if self._gen == gen and not self._stop:
                        self._bnb_queue.append(job)
                        self._cv.notify_all()
                _REGISTRY.inc("serve_flushes_total", cause="preempt")
                _REGISTRY.inc("serve_bnb_preemptions_total")
                HEALTH.incr("bnb_preemptions")
        except BaseException as exc:  # noqa: BLE001 — jobs must not hang
            error = f"{type(exc).__name__}: {exc}"
            job._fail(exc)
        finally:
            events = _tracing.drain_pending()
            if _tracing.TRACER.active:
                attrs = {
                    "slice_s": round(slice_s, 4),
                    "resumed": resumed,
                    "preempted": preempted,
                    "proven": proven,
                    "slices": job.resumes + 1,
                }
                if job.last_result is not None:
                    attrs["incumbent"] = float(job.last_result.cost)
                    attrs["lower_bound"] = float(job.last_result.lower_bound)
                if error is not None:
                    attrs["error"] = error
                _tracing.emit_span(
                    "bnb.slice", job.ctx, ts0,
                    time.perf_counter() - t0, attrs, events,
                )

    def _bucket(self, total: int) -> int:
        for b in self.buckets:
            if b >= total:
                return b
        return total  # above every bucket: dispatch exact (rare by config)

    def _run_batch(self, group: List[Ticket]) -> None:
        """ONE device call for the whole same-shape group, then scatter the
        results back to each ticket. All jnp work and the single host
        readback of the service's hot path live here."""
        import jax.numpy as jnp

        from ..ops.held_karp import solve_blocks_from_dists

        # the sched.flush fault seam sits OUTSIDE the main try: an
        # injected raise escapes and kills the worker thread with the
        # group still in flight — exactly the failure the watchdog must
        # recover from. The injection EVENT must still reach the traces:
        # this thread has no active span (worker spans are emitted
        # retrospectively), so the event parks in the tracing pending
        # buffer and is attached to the flush span — including a
        # zero-duration tombstone flush when the injection kills us.
        try:
            _fault_registry().fire("sched.flush")
        except BaseException:
            evs = _tracing.drain_pending()
            ts = time.time()
            for t in group:
                _tracing.emit_span(
                    "sched.flush", t.ctx, ts, 0.0,
                    {"error": "fault: sched.flush"}, evs,
                )
            raise
        fault_events = _tracing.drain_pending()  # delay-mode injections
        t_flush0, ts_flush0 = time.perf_counter(), time.time()
        dev_s = 0.0
        error: Optional[str] = None
        try:
            stacked = np.concatenate([t.dists for t in group], axis=0)
            total = stacked.shape[0]
            bucket = self._bucket(total)
            if bucket > total:
                pad = np.broadcast_to(
                    stacked[:1], (bucket - total,) + stacked.shape[1:]
                )
                stacked = np.concatenate([stacked, pad], axis=0)
            dtype = jnp.dtype(self.dtype)
            t_dev0 = time.perf_counter()
            with self.timer.phase("serve.batch_solve"):
                costs, tours = solve_blocks_from_dists(
                    jnp.asarray(stacked, dtype), dtype
                )
                costs_np = np.asarray(costs)
                tours_np = np.asarray(tours)
            dev_s = time.perf_counter() - t_dev0
            # counter updates take the lock: after a stuck-revive an
            # abandoned generation can run _run_batch concurrently with
            # its successor, and stats() snapshots under the same lock
            with self._cv:
                self.batches += 1
                self.blocks_solved += total
                self.padded_blocks += bucket
            _REGISTRY.inc("serve_batches_total")
            _REGISTRY.inc("serve_blocks_solved_total", total)
            _REGISTRY.inc("serve_padded_lanes_total", bucket)
            off = 0
            for t in group:
                b = t.dists.shape[0]
                t._resolve(costs_np[off : off + b], tours_np[off : off + b])
                off += b
        except BaseException as exc:  # noqa: BLE001 — tickets must not hang
            error = f"{type(exc).__name__}: {exc}"
            for t in group:
                t._fail(exc)
        finally:
            self._emit_flush_spans(
                group, ts_flush0, t_flush0, dev_s, error, fault_events
            )

    def _emit_flush_spans(
        self, group, ts0: float, t0: float, dev_s: float, error,
        events=None,
    ) -> None:
        """One ``sched.flush`` span (+ a ``device.dispatch`` child) per
        ticket that carried a trace context — the flush is shared device
        work, but each request's trace must stand alone, so it is
        recorded once per participating trace (attrs carry the shared
        batch shape so a reader can re-correlate them)."""
        if not _tracing.TRACER.active:
            return
        dur_s = time.perf_counter() - t0
        total = sum(t.dists.shape[0] for t in group)
        for t in group:
            attrs = {
                "batch_blocks": total,
                "batch_tickets": len(group),
                "ticket_blocks": int(t.dists.shape[0]),
            }
            if error is not None:
                attrs["error"] = error
            fctx = _tracing.emit_span(
                "sched.flush", t.ctx, ts0, dur_s, attrs, events
            )
            if fctx is not None and dev_s > 0.0:
                _tracing.emit_span(
                    "device.dispatch", fctx, ts0 + (dur_s - dev_s), dev_s,
                    {"seconds": round(dev_s, 6)},
                )

    # -- stats ---------------------------------------------------------------

    def stats(self) -> Dict[str, float]:
        # snapshot under the condition lock (graftflow R9): every counter
        # below is mutated by the worker/watchdog/request threads holding
        # ``_cv`` — an unlocked read here races those updates
        with self._cv:
            return {
                "batches": self.batches,
                "blocks_solved": self.blocks_solved,
                "padded_blocks": self.padded_blocks,
                # occupancy: real blocks per dispatched lane (1.0 = none)
                "batch_occupancy": (
                    self.blocks_solved / self.padded_blocks
                    if self.padded_blocks
                    else 0.0
                ),
                # mean real blocks per device call (the micro-batching win)
                "mean_batch_blocks": (
                    self.blocks_solved / self.batches if self.batches else 0.0
                ),
                "queue_depth_hwm": self.queue_depth_hwm,
                "full_flushes": self.full_flushes,
                "wait_flushes": self.wait_flushes,
                "admit_flushes": self.admit_flushes,
                "bnb_jobs": self.bnb_jobs,
                "bnb_slices": self.bnb_slices,
                "bnb_preemptions": self.bnb_preemptions,
                "bnb_resumes": self.bnb_resumes,
                "slo_sheds": self.slo_sheds,
                "worker_restarts": self.worker_restarts,
                "stuck_restarts": self.stuck_restarts,
                "precompiled_buckets": self.precompiled_buckets,
                "precompile_seconds": round(self.precompile_seconds, 3),
            }

"""Async micro-batching scheduler: N pending solves -> one padded vmap call.

The ORCA/Clipper idea (PAPERS.md) applied to the repo's exact block solver:
request threads :meth:`~MicroBatchScheduler.submit` ``[B, n, n]`` block
distance stacks and park on a ticket; a single worker thread drains the
queue, groups pending submissions of the SAME block size ``n`` (oldest
first — mixed shapes are served in arrival order, never starved), pads the
concatenated batch up to a compile bucket, and runs ONE
``solve_blocks_from_dists`` device call for the whole group instead of one
dispatch per request.

Latency discipline (the "max-wait knob"): the worker flushes as soon as
``max_batch`` blocks are pending, and otherwise no later than
``max_wait_ms`` after the OLDEST pending submission arrived — batching can
add at most ``max_wait_ms`` to any request, never unbounded queueing delay.

Compile discipline: batch sizes are padded up to fixed power-of-two
``buckets`` (pad lanes replicate the first real block; vmap lanes are
independent, so real lanes are bit-identical to an unpadded run). Without
bucketing every distinct batch size would trigger a fresh XLA compile —
the classic serving recompile storm.

Device work and host readbacks happen ONLY in :meth:`_run_batch`, called
once per flush from the worker loop — the loop body itself stays free of
per-iteration device traffic (graftlint R1/R4 discipline).

Self-healing (the resilience layer): the worker thread is supervised by a
WATCHDOG. A worker that dies (an exception escaping the flush — e.g. the
``sched.flush`` fault seam) or goes silent mid-batch past
``stuck_timeout_s`` is replaced: unresolved in-flight tickets are
re-queued at the FRONT of the queue (arrival order preserved) and a fresh
worker generation takes over; an abandoned-but-alive worker notices its
stale generation at the next queue interaction and exits. Restarts count
into the scheduler stats and the global ``HEALTH`` block — a dead worker
can no longer silently strand every queued request.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Deque, Dict, List, Optional, Tuple

import numpy as np

from ..obs import tracing as _tracing
from ..obs.metrics import REGISTRY as _REGISTRY
from ..ops.held_karp import MAX_BLOCK_CITIES
from ..resilience.faults import registry as _fault_registry
from ..resilience.health import HEALTH
from ..utils.profiling import PhaseTimer

_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128)


class Ticket:
    """One pending submission: request threads block on :meth:`wait`.

    Outcomes are FIRST-WRITER-WINS: after a watchdog revive, a ticket can
    be re-solved by the successor generation while the abandoned worker
    still holds a reference — whichever outcome lands first sticks, so a
    stale worker's late failure can never mask a valid replacement result
    (nor vice versa)."""

    __slots__ = (
        "dists", "arrived", "ctx", "_event", "_costs", "_tours", "_error",
        "_claim", "_done",
    )

    def __init__(self, dists: np.ndarray) -> None:
        self.dists = dists
        #: the submitting thread's span context (trace_id, span_id) — the
        #: worker parents its flush span here, so the device work a
        #: request waited on lands in that request's own trace
        self.ctx = _tracing.current_context()
        self.arrived = time.monotonic()
        self._event = threading.Event()
        self._costs: Optional[np.ndarray] = None
        self._tours: Optional[np.ndarray] = None
        self._error: Optional[BaseException] = None
        self._claim = threading.Lock()
        self._done = False

    def _take_outcome(self) -> bool:
        with self._claim:
            if self._done:
                return False
            self._done = True
            return True

    def _resolve(self, costs: np.ndarray, tours: np.ndarray) -> None:
        if not self._take_outcome():
            return
        self._costs, self._tours = costs, tours
        self._event.set()

    def _fail(self, exc: BaseException) -> None:
        if not self._take_outcome():
            return
        self._error = exc
        self._event.set()

    def wait(self, timeout: Optional[float] = None):
        """Block until solved. Returns ``(costs [B], tours [B, n+1])`` as
        numpy arrays, raises the worker's exception if the batch failed,
        or returns ``None`` on timeout (the caller degrades to a lower
        ladder rung; the batch still completes and is simply discarded)."""
        if not self._event.wait(timeout):
            return None
        if self._error is not None:
            raise self._error
        return self._costs, self._tours


class MicroBatchScheduler:
    def __init__(
        self,
        max_batch: int = 64,
        max_wait_ms: float = 2.0,
        dtype: str = "float32",
        buckets: Tuple[int, ...] = _BUCKETS,
        timer: Optional[PhaseTimer] = None,
        watchdog_interval_s: float = 0.2,
        stuck_timeout_s: float = 30.0,
    ) -> None:
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        self.max_batch = max_batch
        self.max_wait_s = max_wait_ms / 1000.0
        self.dtype = dtype
        self.buckets = tuple(sorted(set(buckets) | {max_batch}))
        self.timer = timer or PhaseTimer()
        self.watchdog_interval_s = watchdog_interval_s
        self.stuck_timeout_s = stuck_timeout_s
        self._cv = threading.Condition()
        self._queue: Deque[Ticket] = deque()
        self._thread: Optional[threading.Thread] = None
        self._watchdog: Optional[threading.Thread] = None
        self._stop = False
        #: tickets popped by the current worker but not yet resolved — what
        #: the watchdog re-queues when that worker dies or wedges
        self._inflight: List[Ticket] = []
        #: worker generation: bumped on every (re)start; a worker whose
        #: generation is stale has been replaced and must stand down
        self._gen = 0
        self._heartbeat = time.monotonic()
        #: current stuck threshold: doubles after every stuck-revive and
        #: resets when a batch completes cleanly. The watchdog cannot
        #: tell a wedged worker from a legitimately long batch (a cold
        #: XLA compile blocks _run_batch well past any fixed timeout), so
        #: successive generations get exponentially more patience — a
        #: genuine wedge is still caught fast, while a long first compile
        #: costs at most a logarithmic number of duplicate dispatches
        #: (idempotent tickets keep results correct either way)
        self._stuck_allowance = stuck_timeout_s
        # -- counters (reported via utils.reporting.service_stats_json) --
        self.batches = 0  #: device calls issued
        self.blocks_solved = 0  #: real (non-padding) blocks solved
        self.padded_blocks = 0  #: total lanes dispatched incl. padding
        self.queue_depth_hwm = 0  #: max pending blocks ever queued
        self.full_flushes = 0  #: flushes triggered by max_batch
        self.wait_flushes = 0  #: flushes triggered by the max-wait knob
        self.worker_restarts = 0  #: dead workers replaced by the watchdog
        self.stuck_restarts = 0  #: wedged workers abandoned + replaced
        self.precompiled_buckets = 0  #: shape buckets warmed by precompile()
        self.precompile_seconds = 0.0  #: wall spent in precompile()

    # -- warmup --------------------------------------------------------------

    def precompile(self, block_sizes, max_blocks: Optional[int] = None) -> int:
        """AOT-compile the solve kernel for every (block size, bucket)
        this scheduler can dispatch, BEFORE traffic arrives.

        Without this, the first flush of each shape pays the full XLA
        compile inside the worker's ``_run_batch`` — the serving
        recompile storm bucketing was designed to bound, but the FIRST
        request of each bucket still ate it (BENCH_SERVE's 3.76x->1.56x
        service gap is mostly this cold flush plus host path). Compiles
        go through ``ops.held_karp.warm_blocks``: the AOT store when the
        perf cache is enabled, jax's persistent compilation cache
        regardless — so a restarted service warms from disk in ms.

        ``block_sizes``: iterable of block city counts n to warm.
        ``max_blocks``: warm buckets up to this many blocks (default
        ``max_batch``). Returns the number of (n, bucket) entries warmed;
        failures are counted and skipped, never raised (warmup must not
        take the service down).
        """
        from ..ops.held_karp import MAX_BLOCK_CITIES, warm_blocks

        cap = self.max_batch if max_blocks is None else max_blocks
        buckets = [b for b in self.buckets if b <= cap] or [self.buckets[0]]
        warmed = 0
        t0 = time.monotonic()
        for n in block_sizes:
            n = int(n)
            if not 3 <= n <= MAX_BLOCK_CITIES:
                continue
            for b in buckets:
                try:
                    warm_blocks(n, b, self.dtype)
                    warmed += 1
                except Exception:  # noqa: BLE001 — warmup is best-effort
                    continue
        with self._cv:  # stats() snapshots these under the same lock
            self.precompiled_buckets += warmed
            self.precompile_seconds += time.monotonic() - t0
        return warmed

    # -- submission ----------------------------------------------------------

    def submit(self, block_dists: np.ndarray) -> Ticket:
        """Enqueue a ``[B, n, n]`` stack of block distance matrices.

        Validation errors raise HERE, synchronously, so a malformed request
        can never poison a shared batch."""
        d = np.asarray(block_dists)
        if d.ndim != 3 or d.shape[1] != d.shape[2]:
            raise ValueError(f"expected [B, n, n] block dists, got {d.shape}")
        n = int(d.shape[1])
        if not 3 <= n <= MAX_BLOCK_CITIES:
            raise ValueError(
                f"block size must be in [3, {MAX_BLOCK_CITIES}], got {n}"
            )
        if d.shape[0] < 1:
            raise ValueError("empty block stack")
        ticket = Ticket(d)
        with self._cv:
            if self._stop:
                raise RuntimeError("scheduler is closed")
            self._ensure_threads_locked()
            self._queue.append(ticket)
            depth = sum(t.dists.shape[0] for t in self._queue)
            self.queue_depth_hwm = max(self.queue_depth_hwm, depth)
            self._cv.notify_all()
        _REGISTRY.set_gauge("serve_queue_depth_blocks", depth)
        return ticket

    def close(self) -> None:
        """Stop the worker + watchdog; pending tickets are failed, not
        dropped (in-flight tickets the worker abandoned included)."""
        with self._cv:
            self._stop = True
            self._cv.notify_all()
            # snapshot the thread handles UNDER the lock (graftflow R9):
            # a concurrent submit()'s revive path swaps them, and a stale
            # handle here would join a replaced worker while the live one
            # keeps running past close
            threads = (self._thread, self._watchdog)
        for th in threads:
            if th is not None:
                th.join(timeout=30.0)
        with self._cv:
            self._thread = None
            self._watchdog = None
            pending = [t for t in self._inflight if not t._event.is_set()]
            pending += list(self._queue)
            self._inflight = []
            self._queue.clear()
        for t in pending:
            t._fail(RuntimeError("scheduler closed before solve"))
        _REGISTRY.set_gauge("serve_queue_depth_blocks", 0)

    # -- supervision ---------------------------------------------------------

    def _ensure_threads_locked(self) -> None:
        """Under ``self._cv``: make sure a live worker generation and the
        watchdog exist. A dead worker found HERE (between watchdog ticks)
        is revived immediately — submission must never race the interval."""
        if self._thread is not None and not self._thread.is_alive():
            self._revive_locked(stuck=False)
        elif self._thread is None:
            self._spawn_worker_locked()
        if self._watchdog is None or not self._watchdog.is_alive():
            self._watchdog = threading.Thread(
                target=self._watchdog_loop, name="serve-watchdog", daemon=True
            )
            self._watchdog.start()

    def _spawn_worker_locked(self) -> None:
        self._gen += 1
        self._heartbeat = time.monotonic()
        self._thread = threading.Thread(
            target=self._worker,
            args=(self._gen,),
            name=f"serve-microbatch-g{self._gen}",
            daemon=True,
        )
        self._thread.start()

    def _revive_locked(self, stuck: bool) -> None:
        """Replace the current worker generation: re-queue its unresolved
        in-flight tickets at the FRONT (arrival order preserved) and spawn
        a successor. For a STUCK (alive but silent) worker the old thread
        is abandoned — it exits at its next queue interaction when it sees
        its stale generation; any late result it still resolves is
        harmless (tickets resolve idempotently)."""
        live = [t for t in self._inflight if not t._event.is_set()]
        self._inflight = []
        self._queue.extendleft(reversed(live))
        if stuck:
            self.stuck_restarts += 1
            HEALTH.incr("stuck_restarts")
            # see __init__: compile-vs-wedge. Capped so a PERSISTENTLY
            # wedging backend can't grow the allowance until stuck
            # detection is effectively disabled
            self._stuck_allowance = min(
                self._stuck_allowance * 2, 8 * self.stuck_timeout_s
            )
        else:
            self.worker_restarts += 1
            HEALTH.incr("worker_restarts")
        self._spawn_worker_locked()
        self._cv.notify_all()

    def _watchdog_loop(self) -> None:
        with self._cv:
            while not self._stop:
                self._cv.wait(self.watchdog_interval_s)
                if self._stop:
                    return
                worker = self._thread
                if worker is None:
                    continue
                if not worker.is_alive():
                    if self._queue or self._inflight:
                        self._revive_locked(stuck=False)
                elif (
                    self._inflight
                    and time.monotonic() - self._heartbeat > self._stuck_allowance
                ):
                    self._revive_locked(stuck=True)

    def __enter__(self) -> "MicroBatchScheduler":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- worker --------------------------------------------------------------

    def _collect(self, gen: int) -> Optional[List[Ticket]]:
        """Under the condition lock: wait for a flushable group and pop it.

        Returns the oldest submission plus every later pending ticket of
        the same block size, up to ``max_batch`` total blocks; None when
        shutting down with an empty queue, or when this worker's
        generation has been superseded by the watchdog (stand down)."""
        with self._cv:
            while True:
                if self._gen != gen:
                    return None
                self._heartbeat = time.monotonic()
                if self._queue:
                    head = self._queue[0]
                    pending = sum(
                        t.dists.shape[0]
                        for t in self._queue
                        if t.dists.shape[1] == head.dists.shape[1]
                    )
                    waited = time.monotonic() - head.arrived
                    if self._stop or pending >= self.max_batch or waited >= self.max_wait_s:
                        if pending >= self.max_batch:
                            self.full_flushes += 1
                            _REGISTRY.inc("serve_flushes_total", cause="full")
                        else:
                            self.wait_flushes += 1
                            _REGISTRY.inc("serve_flushes_total", cause="wait")
                        group = self._pop_group(head.dists.shape[1])
                        self._inflight = list(group)
                        return group
                    # batch still filling: sleep until the oldest request's
                    # wait budget lapses (or a new submission wakes us)
                    self._cv.wait(self.max_wait_s - waited)
                elif self._stop:
                    return None
                else:
                    self._cv.wait()

    def _pop_group(self, n: int) -> List[Ticket]:
        group: List[Ticket] = []
        total = 0
        keep: Deque[Ticket] = deque()
        while self._queue:
            t = self._queue.popleft()
            fits = total + t.dists.shape[0] <= self.max_batch
            # the head ticket is taken even when it alone exceeds max_batch
            # (an oversized submission must flush, not starve the queue)
            if t.dists.shape[1] == n and (fits or not group):
                group.append(t)
                total += t.dists.shape[0]
            else:
                keep.append(t)
        self._queue.extendleft(reversed(keep))
        # keep the depth gauge honest on the DRAIN side too: submit()
        # alone would leave the last pre-flush depth standing forever on
        # an idle service (phantom backlog on every dashboard)
        _REGISTRY.set_gauge(
            "serve_queue_depth_blocks",
            sum(t.dists.shape[0] for t in self._queue),
        )
        return group

    def _worker(self, gen: int) -> None:
        while True:
            group = self._collect(gen)
            if group is None:
                return
            self._run_batch(group)
            with self._cv:
                if self._gen == gen:
                    self._inflight = []
                    # a clean batch proves the worker healthy: restore
                    # the base stuck threshold for future batches
                    self._stuck_allowance = self.stuck_timeout_s

    def _bucket(self, total: int) -> int:
        for b in self.buckets:
            if b >= total:
                return b
        return total  # above every bucket: dispatch exact (rare by config)

    def _run_batch(self, group: List[Ticket]) -> None:
        """ONE device call for the whole same-shape group, then scatter the
        results back to each ticket. All jnp work and the single host
        readback of the service's hot path live here."""
        import jax.numpy as jnp

        from ..ops.held_karp import solve_blocks_from_dists

        # the sched.flush fault seam sits OUTSIDE the main try: an
        # injected raise escapes and kills the worker thread with the
        # group still in flight — exactly the failure the watchdog must
        # recover from. The injection EVENT must still reach the traces:
        # this thread has no active span (worker spans are emitted
        # retrospectively), so the event parks in the tracing pending
        # buffer and is attached to the flush span — including a
        # zero-duration tombstone flush when the injection kills us.
        try:
            _fault_registry().fire("sched.flush")
        except BaseException:
            evs = _tracing.drain_pending()
            ts = time.time()
            for t in group:
                _tracing.emit_span(
                    "sched.flush", t.ctx, ts, 0.0,
                    {"error": "fault: sched.flush"}, evs,
                )
            raise
        fault_events = _tracing.drain_pending()  # delay-mode injections
        t_flush0, ts_flush0 = time.perf_counter(), time.time()
        dev_s = 0.0
        error: Optional[str] = None
        try:
            stacked = np.concatenate([t.dists for t in group], axis=0)
            total = stacked.shape[0]
            bucket = self._bucket(total)
            if bucket > total:
                pad = np.broadcast_to(
                    stacked[:1], (bucket - total,) + stacked.shape[1:]
                )
                stacked = np.concatenate([stacked, pad], axis=0)
            dtype = jnp.dtype(self.dtype)
            t_dev0 = time.perf_counter()
            with self.timer.phase("serve.batch_solve"):
                costs, tours = solve_blocks_from_dists(
                    jnp.asarray(stacked, dtype), dtype
                )
                costs_np = np.asarray(costs)
                tours_np = np.asarray(tours)
            dev_s = time.perf_counter() - t_dev0
            # counter updates take the lock: after a stuck-revive an
            # abandoned generation can run _run_batch concurrently with
            # its successor, and stats() snapshots under the same lock
            with self._cv:
                self.batches += 1
                self.blocks_solved += total
                self.padded_blocks += bucket
            _REGISTRY.inc("serve_batches_total")
            _REGISTRY.inc("serve_blocks_solved_total", total)
            _REGISTRY.inc("serve_padded_lanes_total", bucket)
            off = 0
            for t in group:
                b = t.dists.shape[0]
                t._resolve(costs_np[off : off + b], tours_np[off : off + b])
                off += b
        except BaseException as exc:  # noqa: BLE001 — tickets must not hang
            error = f"{type(exc).__name__}: {exc}"
            for t in group:
                t._fail(exc)
        finally:
            self._emit_flush_spans(
                group, ts_flush0, t_flush0, dev_s, error, fault_events
            )

    def _emit_flush_spans(
        self, group, ts0: float, t0: float, dev_s: float, error,
        events=None,
    ) -> None:
        """One ``sched.flush`` span (+ a ``device.dispatch`` child) per
        ticket that carried a trace context — the flush is shared device
        work, but each request's trace must stand alone, so it is
        recorded once per participating trace (attrs carry the shared
        batch shape so a reader can re-correlate them)."""
        if not _tracing.TRACER.active:
            return
        dur_s = time.perf_counter() - t0
        total = sum(t.dists.shape[0] for t in group)
        for t in group:
            attrs = {
                "batch_blocks": total,
                "batch_tickets": len(group),
                "ticket_blocks": int(t.dists.shape[0]),
            }
            if error is not None:
                attrs["error"] = error
            fctx = _tracing.emit_span(
                "sched.flush", t.ctx, ts0, dur_s, attrs, events
            )
            if fctx is not None and dev_s > 0.0:
                _tracing.emit_span(
                    "device.dispatch", fctx, ts0 + (dur_s - dev_s), dev_s,
                    {"seconds": round(dev_s, 6)},
                )

    # -- stats ---------------------------------------------------------------

    def stats(self) -> Dict[str, float]:
        # snapshot under the condition lock (graftflow R9): every counter
        # below is mutated by the worker/watchdog/request threads holding
        # ``_cv`` — an unlocked read here races those updates
        with self._cv:
            return {
                "batches": self.batches,
                "blocks_solved": self.blocks_solved,
                "padded_blocks": self.padded_blocks,
                # occupancy: real blocks per dispatched lane (1.0 = none)
                "batch_occupancy": (
                    self.blocks_solved / self.padded_blocks
                    if self.padded_blocks
                    else 0.0
                ),
                # mean real blocks per device call (the micro-batching win)
                "mean_batch_blocks": (
                    self.blocks_solved / self.batches if self.batches else 0.0
                ),
                "queue_depth_hwm": self.queue_depth_hwm,
                "full_flushes": self.full_flushes,
                "wait_flushes": self.wait_flushes,
                "worker_restarts": self.worker_restarts,
                "stuck_restarts": self.stuck_restarts,
                "precompiled_buckets": self.precompiled_buckets,
                "precompile_seconds": round(self.precompile_seconds, 3),
            }

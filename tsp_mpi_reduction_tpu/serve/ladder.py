"""Deadline-aware degradation ladder: certified -> exact-blocked -> greedy.

Every request carries a latency budget. Rather than a fixed solver (and
either blown deadlines or uniformly weak answers), the service walks a
ladder of rungs from strongest to cheapest and picks the strongest rung
whose *estimated* latency fits the remaining budget:

  ``bnb``      certified branch & bound (``models.branch_bound.solve``,
               time-limited to the budget) — proven optimum or a certified
               gap from the search's global lower bound;
  ``pipeline`` the exact vmapped Held-Karp path through the micro-batch
               scheduler: single block for n <= 16 (exact, gap 0), blocked
               decomposition + merge fold + device 2-opt/Or-opt polish for
               larger n (heuristic, no certificate);
  ``greedy``   host nearest-neighbor — microseconds at serving sizes, the
               rung that guarantees a valid closed tour for ANY deadline.

Rung latencies are learned online (per-rung, per-size EWMA seeded with
conservative priors), so the first cold-compile hit teaches the ladder to
stop promising that rung under tight budgets. A rung that misses its
budget mid-flight still returns (the response is marked late) — but the
ladder design keeps that rare: ``greedy`` never misses, and ``pipeline``
waits on the scheduler only as long as the budget allows before degrading.

Every result records which rung answered (``tier``) and the achieved
certificate (``certified_gap``) — the cache stores both, so a certified
answer is never clobbered by a later deadline-degraded one.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, Optional, Tuple

import numpy as np

from ..obs import tracing as _tracing
from ..obs.metrics import REGISTRY as _REGISTRY
from ..ops.distance import distance_matrix_np
from ..ops.held_karp import MAX_BLOCK_CITIES
from ..resilience.faults import registry as _fault_registry
from ..resilience.retry import RetryPolicy
from .scheduler import MicroBatchScheduler

TIERS = ("bnb", "pipeline", "greedy")
#: strength order for cache-upgrade decisions (higher = stronger rung)
_TIER_RANK = {"greedy": 0, "pipeline": 1, "bnb": 2}


@dataclass
class LadderResult:
    cost: float
    tour: np.ndarray  # [n+1] CLOSED tour in request-space city ids
    tier: str
    #: 0.0 = proven/exact; >0 = certified-but-unproven B&B gap; None = no
    #: certificate (heuristic rung)
    certified_gap: Optional[float]
    lower_bound: float = -np.inf


@dataclass
class LadderConfig:
    #: largest instance the bnb rung will attempt (search is exponential;
    #: past this the rung is skipped regardless of budget)
    bnb_max_n: int = 64
    #: never attempt bnb with less than this many seconds of budget
    bnb_min_budget_s: float = 1.0
    #: fraction of the remaining budget handed to bnb's time_limit_s (the
    #: rest covers setup + response assembly)
    bnb_budget_fraction: float = 0.6
    #: conservative cold-start latency priors, refined by the EWMA
    prior_s: Dict[str, float] = field(
        default_factory=lambda: {"bnb": 5.0, "pipeline": 0.5, "greedy": 0.0}
    )
    #: B&B knobs sized for serving (small instances, bounded memory)
    bnb_capacity: int = 1 << 14
    bnb_k: int = 64
    #: injectable certified solver (tests); signature (d, time_limit_s) ->
    #: (cost, closed_tour, lower_bound, proven)
    bnb_solver: Optional[Callable] = None
    #: 2-opt/Or-opt polish rounds for the blocked-pipeline rung
    polish_rounds: int = 6
    #: transient-fault retries per rung attempt (the self-healing knob:
    #: a TransientFault/FaultInjected from a rung is re-tried this many
    #: times with exponential backoff before the ladder degrades)
    rung_retries: int = 1
    #: first-retry backoff; doubles per retry, deterministic jitter
    retry_base_delay_s: float = 0.01


class LatencyEstimator:
    """Per-(tier, n-bucket) EWMA of observed rung latencies."""

    def __init__(self, alpha: float = 0.3) -> None:
        self.alpha = alpha
        self._ewma: Dict[Tuple[str, int], float] = {}
        self._lock = threading.Lock()

    @staticmethod
    def _bucket(n: int) -> int:
        b = 4
        while b < n:
            b *= 2
        return b

    def observe(self, tier: str, n: int, seconds: float) -> None:
        key = (tier, self._bucket(n))
        with self._lock:
            old = self._ewma.get(key)
            self._ewma[key] = (
                seconds if old is None else (1 - self.alpha) * old + self.alpha * seconds
            )

    def estimate(self, tier: str, n: int, default: float) -> float:
        with self._lock:
            return self._ewma.get((tier, self._bucket(n)), default)


def _trivial_tour(n: int, d: np.ndarray) -> Tuple[float, np.ndarray]:
    """n < 3: the only closed tours there are."""
    if n == 1:
        return 0.0, np.asarray([0, 0], np.int32)
    return float(d[0, 1] + d[1, 0]), np.asarray([0, 1, 0], np.int32)


def _greedy(d: np.ndarray) -> Tuple[float, np.ndarray]:
    from ..models.branch_bound import nearest_neighbor_tour

    tour = nearest_neighbor_tour(d)
    cost = float(d[tour[:-1], tour[1:]].sum())
    return cost, tour


def _largest_block_divisor(n: int) -> Optional[int]:
    """Largest b in [3, 16] (hard HK cap per SURVEY.md) dividing n."""
    for b in range(min(n, MAX_BLOCK_CITIES, 16), 2, -1):
        if n % b == 0:
            return b
    return None


def _default_bnb_solver(cfg: LadderConfig) -> Callable:
    from ..models import branch_bound as bb

    def run(d: np.ndarray, time_limit_s: float):
        res = bb.solve(
            d,
            capacity=cfg.bnb_capacity,
            k=cfg.bnb_k,
            time_limit_s=max(time_limit_s, 0.05),
            device_loop=False,  # fine-grained time-limit checks
        )
        return res.cost, res.tour, res.lower_bound, bool(res.proven_optimal)

    return run


class DeadlineLadder:
    """Stateful rung dispatcher shared by all request threads."""

    def __init__(
        self,
        scheduler: MicroBatchScheduler,
        cfg: Optional[LadderConfig] = None,
        estimator: Optional[LatencyEstimator] = None,
    ) -> None:
        self.scheduler = scheduler
        self.cfg = cfg or LadderConfig()
        self.estimator = estimator or LatencyEstimator()
        self.tier_counts: Dict[str, int] = {t: 0 for t in TIERS}
        #: rungs that raised (device OOM, failed batch, solver bug) instead
        #: of answering — each such request still got a greedy tour
        self.rung_failures: Dict[str, int] = {t: 0 for t in TIERS}
        self._count_lock = threading.Lock()

    def _attempt(
        self, tier: str, n: int, run, budget_s: Optional[float] = None
    ) -> Optional[LadderResult]:
        """Run one rung; None on timeout OR exception (the caller degrades).

        The elapsed time is observed in BOTH cases — a rung that burned its
        budget and failed must teach the estimator, or the ladder will keep
        promising it to every request (the cold-compile trap). TRANSIENT
        faults (``resilience.faults``, incl. the ``ladder.rung`` injection
        seam) are absorbed by a bounded backoff retry first — capped by
        ``budget_s`` so a retry can never outspend the request's deadline
        (``run`` must re-read the remaining budget itself, not capture a
        stale value, or the retry re-runs with time that no longer
        exists). Exhausted retries and real exceptions are counted, not
        propagated: the ladder's contract is that a well-formed instance
        always gets a tour from SOME rung."""
        t0 = time.monotonic()

        def attempt_once():
            _fault_registry().fire("ladder.rung")
            return run()

        policy = RetryPolicy(
            max_attempts=1 + max(self.cfg.rung_retries, 0),
            base_delay_s=self.cfg.retry_base_delay_s,
            seed=0,
        )
        with _tracing.span("ladder.rung", tier=tier, n=n) as sp:
            try:
                result = policy.call(attempt_once, budget_s=budget_s)
                sp.set("outcome", "ok" if result is not None else "timeout")
                return result
            except Exception as e:  # noqa: BLE001 — degrade, never error
                sp.set("outcome", "failed")
                sp.set("error", f"{type(e).__name__}: {e}")
                with self._count_lock:
                    self.rung_failures[tier] += 1
                _REGISTRY.inc(
                    "serve_rung_failures_total", tier=tier
                )
                return None
            finally:
                elapsed = time.monotonic() - t0
                self.estimator.observe(tier, n, elapsed)
                _REGISTRY.inc("serve_rung_attempts_total", tier=tier)
                _REGISTRY.inc(
                    "serve_rung_seconds_total", max(elapsed, 0.0), tier=tier
                )

    def counts_snapshot(self) -> Tuple[Dict[str, int], Dict[str, int]]:
        """``(tier_counts, rung_failures)`` copied under the count lock —
        the accessor stats reporting must use instead of reaching into
        the dicts while request threads increment them (graftflow R9)."""
        with self._count_lock:
            return dict(self.tier_counts), dict(self.rung_failures)

    def upgrade_eligible(
        self, n: int, deadline_s: float, entry_tier: str, certified_gap
    ) -> bool:
        """Should a cached entry be RE-SOLVED for this request instead of
        served as a hit? True when the entry is not already exact/proven
        (``certified_gap == 0.0``) and a STRONGER rung than the one that
        produced it fits this request's budget — so a greedy answer cached
        under a tight deadline doesn't pin the instance to greedy forever.
        (A timed-out bnb certificate with gap > 0 is only re-attempted by
        a request whose budget fits bnb again.)"""
        if certified_gap == 0.0:
            return False
        if n < 3:
            return False
        cfg, est = self.cfg, self.estimator
        rank = _TIER_RANK.get(entry_tier, 0)
        if (
            rank <= _TIER_RANK["bnb"]
            and n <= cfg.bnb_max_n
            and deadline_s >= cfg.bnb_min_budget_s
            and deadline_s >= est.estimate("bnb", n, cfg.prior_s["bnb"])
        ):
            return True
        return rank < _TIER_RANK["pipeline"] and deadline_s >= est.estimate(
            "pipeline", n, cfg.prior_s["pipeline"]
        )

    # -- rung implementations ------------------------------------------------

    def _run_bnb(self, d: np.ndarray, budget_s: float) -> LadderResult:
        solver = self.cfg.bnb_solver or _default_bnb_solver(self.cfg)
        cost, tour, lb, proven = solver(d, budget_s * self.cfg.bnb_budget_fraction)
        if proven or cost <= lb:
            gap = 0.0
        else:
            gap = float(max(cost - lb, 0.0) / max(lb, 1e-12)) if np.isfinite(lb) else None
        return LadderResult(
            cost=float(cost),
            tour=np.asarray(tour, np.int32),
            tier="bnb",
            certified_gap=gap,
            lower_bound=float(lb),
        )

    def _run_pipeline(
        self, xy: np.ndarray, d: np.ndarray, budget_s: float
    ) -> Optional[LadderResult]:
        """Exact HK for one block; blocked HK + merge + polish for larger n.
        Returns None when the scheduler wait outlives the budget (the
        caller degrades to greedy; the batch result is discarded)."""
        n = d.shape[0]
        if n <= MAX_BLOCK_CITIES:
            # the sched.wait span is the queue-wait stage of the request
            # trace; the worker parents its flush span to it (the ticket
            # captures this span's context at submit)
            with _tracing.span("sched.wait", blocks=1) as sp:
                ticket = self.scheduler.submit(d[None])
                got = ticket.wait(timeout=max(budget_s, 1e-3))
                if got is None:
                    sp.set("outcome", "timeout")
                    return None
            costs, tours = got
            return LadderResult(
                cost=float(costs[0]),
                tour=np.asarray(tours[0], np.int32),
                tier="pipeline",
                certified_gap=0.0,  # Held-Karp is exact for a single block
            )
        return self._run_blocked(xy, d, budget_s)

    def _run_blocked(
        self, xy: np.ndarray, d: np.ndarray, budget_s: float
    ) -> Optional[LadderResult]:
        """n > 16: spatially-sorted blocked decomposition, the batched HK
        kernel per block, the repo's merge fold, then device polish. No
        certificate — the block decomposition is heuristic."""
        import jax.numpy as jnp

        from ..ops.local_search import polish, tour_length
        from ..ops.merge import fold_tours

        n = d.shape[0]
        t0 = time.monotonic()
        b = _largest_block_divisor(n)
        if b is None:
            # prime-ish n: greedy seed + device polish is still a real
            # improvement rung over raw greedy
            seed_cost, seed = _greedy(d)
            order = seed[:-1]
        else:
            # block-major spatial order (sort by x, then y) gives blocks
            # the merge operator can stitch with short splices
            order = np.lexsort((xy[:, 1], xy[:, 0])).astype(np.int64)
            blocks = order.reshape(n // b, b)
            block_d = d[blocks[:, :, None], blocks[:, None, :]]
            with _tracing.span("sched.wait", blocks=int(n // b)) as sp:
                ticket = self.scheduler.submit(block_d)
                got = ticket.wait(timeout=max(budget_s, 1e-3))
                if got is None:
                    sp.set("outcome", "timeout")
                    return None
            costs, tours = got
            # fold in global (request-space) ids via the resident matrix
            global_tours = np.asarray(blocks)[
                np.arange(blocks.shape[0])[:, None], np.asarray(tours, np.int64)
            ]
            ids, length, _cost = fold_tours(
                jnp.asarray(global_tours, jnp.int32),
                jnp.asarray(costs),
                jnp.asarray(d, jnp.float32),
            )
            order = np.asarray(ids)[: int(length)][:-1].astype(np.int64)
        remaining = budget_s - (time.monotonic() - t0)
        d32 = jnp.asarray(d, jnp.float32)
        t = jnp.asarray(order, jnp.int32)
        if remaining > 0:
            t, _ = polish(t, d32, max_rounds=self.cfg.polish_rounds)
        cost = float(tour_length(t, d32))
        open_t = np.asarray(t, np.int64)
        closed = np.concatenate([open_t, open_t[:1]]).astype(np.int32)
        return LadderResult(cost=cost, tour=closed, tier="pipeline", certified_gap=None)

    # -- dispatch ------------------------------------------------------------

    def solve(self, xy: np.ndarray, deadline_s: float) -> LadderResult:
        """Answer one request within ``deadline_s`` (measured from now)."""
        t_start = time.monotonic()
        xy = np.asarray(xy, np.float64)
        n = xy.shape[0]
        d = distance_matrix_np(xy)
        cfg, est = self.cfg, self.estimator

        def budget() -> float:
            return deadline_s - (time.monotonic() - t_start)

        result: Optional[LadderResult] = None
        if n >= 3:
            rem = budget()
            # a rung that throws (device OOM, failed batch, solver bug) must
            # degrade like a rung that timed out — the ladder's contract is
            # that a well-formed instance ALWAYS gets a tour, never an error
            if (
                n <= cfg.bnb_max_n
                and rem >= cfg.bnb_min_budget_s
                and rem >= est.estimate("bnb", n, cfg.prior_s["bnb"])
            ):
                # budget() is re-read INSIDE the lambda: a retry after a
                # late transient fault must run with the time actually
                # left, not the full original rem (which would land the
                # response at ~2x the deadline)
                result = self._attempt(
                    "bnb", n,
                    lambda: self._run_bnb(d, max(budget(), 0.05)),
                    budget_s=rem,
                )
            elif budget() >= est.estimate("pipeline", n, cfg.prior_s["pipeline"]):
                result = self._attempt(
                    "pipeline", n,
                    lambda: self._run_pipeline(xy, d, budget()),
                    budget_s=budget(),
                )
        if result is None:
            # the unconditional rung: valid closed tour at ANY deadline
            with _tracing.span("ladder.rung", tier="greedy", n=n) as sp:
                if n < 3:
                    cost, tour = _trivial_tour(n, d)
                else:
                    cost, tour = _greedy(d)
                sp.set("outcome", "ok")
            result = LadderResult(
                cost=cost,
                tour=tour,
                tier="greedy",
                certified_gap=0.0 if n < 3 else None,
            )
        with self._count_lock:
            self.tier_counts[result.tier] += 1
        _REGISTRY.inc("serve_tier_answers_total", tier=result.tier)
        return result

"""Deadline-aware degradation ladder: certified -> exact-blocked -> greedy.

Every request carries a latency budget. Rather than a fixed solver (and
either blown deadlines or uniformly weak answers), the service walks a
ladder of rungs from strongest to cheapest and picks the strongest rung
whose *estimated* latency fits the remaining budget:

  ``bnb``      certified branch & bound (``models.branch_bound.solve``,
               time-limited to the budget) — proven optimum or a certified
               gap from the search's global lower bound. Runs PREEMPTIBLY
               through the scheduler's iteration-level loop (ISSUE 13):
               ``bnb_slice_s``-second slices that yield the device between
               checkpointed continuations, so a long proof coexists with
               the latency-sensitive pipeline traffic;
  ``pipeline`` the exact vmapped Held-Karp path through the micro-batch
               scheduler: single block for n <= 16 (exact, gap 0), blocked
               decomposition + merge fold + device 2-opt/Or-opt polish for
               larger n (heuristic, no certificate);
  ``greedy``   host nearest-neighbor — microseconds at serving sizes, the
               rung that guarantees a valid closed tour for ANY deadline.

Rung latencies are learned online (per-rung, per-size EWMA seeded with
conservative priors), so the first cold-compile hit teaches the ladder to
stop promising that rung under tight budgets. A rung that misses its
budget mid-flight still returns (the response is marked late) — but the
ladder design keeps that rare: ``greedy`` never misses, and ``pipeline``
waits on the scheduler only as long as the budget allows before degrading.

Every result records which rung answered (``tier``) and the achieved
certificate (``certified_gap``) — the cache stores both, so a certified
answer is never clobbered by a later deadline-degraded one.
"""

from __future__ import annotations

import os
import tempfile
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, Optional, Tuple

import numpy as np

from ..obs import tracing as _tracing
from ..obs.metrics import REGISTRY as _REGISTRY
from ..ops.distance import distance_matrix_np
from ..ops.held_karp import MAX_BLOCK_CITIES
from ..resilience.faults import registry as _fault_registry
from ..resilience.retry import RetryPolicy
from .scheduler import MicroBatchScheduler

TIERS = ("bnb", "pipeline", "greedy")
#: strength order for cache-upgrade decisions (higher = stronger rung)
_TIER_RANK = {"greedy": 0, "pipeline": 1, "bnb": 2}


@dataclass
class LadderResult:
    cost: float
    tour: np.ndarray  # [n+1] CLOSED tour in request-space city ids
    tier: str
    #: 0.0 = proven/exact; >0 = certified-but-unproven B&B gap; None = no
    #: certificate (heuristic rung)
    certified_gap: Optional[float]
    lower_bound: float = -np.inf


@dataclass
class LadderConfig:
    #: largest instance the bnb rung will attempt (search is exponential;
    #: past this the rung is skipped regardless of budget)
    bnb_max_n: int = 64
    #: never attempt bnb with less than this many seconds of budget
    bnb_min_budget_s: float = 1.0
    #: fraction of the remaining budget handed to bnb's time_limit_s (the
    #: rest covers setup + response assembly)
    bnb_budget_fraction: float = 0.6
    #: conservative cold-start latency priors, refined by the EWMA
    prior_s: Dict[str, float] = field(
        default_factory=lambda: {"bnb": 5.0, "pipeline": 0.5, "greedy": 0.0}
    )
    #: B&B knobs sized for serving (small instances, bounded memory)
    bnb_capacity: int = 1 << 14
    bnb_k: int = 64
    #: injectable certified solver (tests); signature (d, time_limit_s) ->
    #: (cost, closed_tour, lower_bound, proven). When set, the rung runs
    #: the solver inline; the default rung instead rides the scheduler's
    #: iteration-level loop (``submit_bnb``) in preemptible slices
    bnb_solver: Optional[Callable] = None
    #: preemption granularity of the default bnb rung: each device slice
    #: runs at most this long before yielding to other queued work
    bnb_slice_s: float = 0.25
    #: where between-slice checkpoints live (None: a private temp dir,
    #: removed by ``DeadlineLadder.cleanup``)
    bnb_checkpoint_dir: Optional[str] = None
    #: shed/degrade NEW admissions to a tier whose live error-budget burn
    #: rate (obs.slo.BurnMeter) exceeds this (1.0 = exactly on budget)
    slo_shed_burn: float = 2.0
    #: 2-opt/Or-opt polish rounds for the blocked-pipeline rung
    polish_rounds: int = 6
    #: transient-fault retries per rung attempt (the self-healing knob:
    #: a TransientFault/FaultInjected from a rung is re-tried this many
    #: times with exponential backoff before the ladder degrades)
    rung_retries: int = 1
    #: first-retry backoff; doubles per retry, deterministic jitter
    retry_base_delay_s: float = 0.01


class LatencyEstimator:
    """Per-(tier, n-bucket) EWMA of observed rung latencies."""

    def __init__(self, alpha: float = 0.3) -> None:
        self.alpha = alpha
        self._ewma: Dict[Tuple[str, int], float] = {}
        self._lock = threading.Lock()

    @staticmethod
    def _bucket(n: int) -> int:
        b = 4
        while b < n:
            b *= 2
        return b

    def observe(self, tier: str, n: int, seconds: float) -> None:
        key = (tier, self._bucket(n))
        with self._lock:
            old = self._ewma.get(key)
            self._ewma[key] = (
                seconds if old is None else (1 - self.alpha) * old + self.alpha * seconds
            )

    def estimate(self, tier: str, n: int, default: float) -> float:
        with self._lock:
            return self._ewma.get((tier, self._bucket(n)), default)

    def observe_partial(
        self,
        tier: str,
        n: int,
        elapsed_s: float,
        progress: float,
        cap_factor: float = 64.0,
    ) -> None:
        """Learn from a PREEMPTED / unfinished rung (ISSUE 13 satellite).

        A rung cut off at its deadline used to be recorded at its capped
        elapsed time — systematically UNDER-estimating the tier's true
        cost, so the ladder kept over-promising it. This projects the
        full cost from the partial evidence: ``elapsed / progress``
        (progress = fraction of the work done, e.g. the B&B gap closure
        from ``ResumeHandle.gap_progress``), clamped to at most
        ``cap_factor`` x elapsed so a rung with no measurable progress
        teaches a strong-but-bounded penalty instead of infinity."""
        if elapsed_s <= 0:
            return
        p = min(max(progress, 1.0 / cap_factor), 1.0)
        self.observe(tier, n, min(elapsed_s / p, elapsed_s * cap_factor))


def _trivial_tour(n: int, d: np.ndarray) -> Tuple[float, np.ndarray]:
    """n < 3: the only closed tours there are."""
    if n == 1:
        return 0.0, np.asarray([0, 0], np.int32)
    return float(d[0, 1] + d[1, 0]), np.asarray([0, 1, 0], np.int32)


def _greedy(d: np.ndarray) -> Tuple[float, np.ndarray]:
    from ..models.branch_bound import nearest_neighbor_tour

    tour = nearest_neighbor_tour(d)
    cost = float(d[tour[:-1], tour[1:]].sum())
    return cost, tour


def _largest_block_divisor(n: int) -> Optional[int]:
    """Largest b in [3, 16] (hard HK cap per SURVEY.md) dividing n."""
    for b in range(min(n, MAX_BLOCK_CITIES, 16), 2, -1):
        if n % b == 0:
            return b
    return None


class DeadlineLadder:
    """Stateful rung dispatcher shared by all request threads."""

    def __init__(
        self,
        scheduler: MicroBatchScheduler,
        cfg: Optional[LadderConfig] = None,
        estimator: Optional[LatencyEstimator] = None,
        burn_meter=None,
    ) -> None:
        self.scheduler = scheduler
        self.cfg = cfg or LadderConfig()
        self.estimator = estimator or LatencyEstimator()
        #: optional obs.slo.BurnMeter shared with the scheduler — the
        #: admission-control signal (None: never shed)
        self.burn_meter = burn_meter
        self.tier_counts: Dict[str, int] = {t: 0 for t in TIERS}
        #: rungs that raised (device OOM, failed batch, solver bug) instead
        #: of answering — each such request still got a greedy tour
        self.rung_failures: Dict[str, int] = {t: 0 for t in TIERS}
        self._count_lock = threading.Lock()
        #: per-request-thread scratch: the scheduler queue wait of the
        #: current rung attempt, so the estimator can learn SERVICE time
        #: (see :meth:`_attempt`)
        self._tls = threading.local()
        self._ckpt_dir: Optional[str] = None
        self._ckpt_dir_owned = False
        self._job_seq = 0

    def _job_checkpoint_path(self) -> str:
        """A unique per-job snapshot path under the (lazily created)
        checkpoint dir — preempted proofs park their donated state here
        between slices."""
        with self._count_lock:
            if self._ckpt_dir is None:
                self._ckpt_dir = self.cfg.bnb_checkpoint_dir
                if self._ckpt_dir is None:
                    self._ckpt_dir = tempfile.mkdtemp(prefix="tsp-serve-bnb-")
                    self._ckpt_dir_owned = True
                else:
                    os.makedirs(self._ckpt_dir, exist_ok=True)
            self._job_seq += 1
            seq = self._job_seq
        return os.path.join(self._ckpt_dir, f"job-{os.getpid()}-{seq}")

    def cleanup(self) -> None:
        """Best-effort removal of the auto-created checkpoint dir (the
        service calls this from ``close``; an explicitly configured
        ``bnb_checkpoint_dir`` is the operator's to manage)."""
        import shutil

        with self._count_lock:
            path, owned = self._ckpt_dir, self._ckpt_dir_owned
            self._ckpt_dir = None
            self._ckpt_dir_owned = False
        if path and owned:
            shutil.rmtree(path, ignore_errors=True)

    def _attempt(
        self, tier: str, n: int, run, budget_s: Optional[float] = None
    ) -> Optional[LadderResult]:
        """Run one rung; None on timeout OR exception (the caller degrades).

        The elapsed time is observed in BOTH cases — a rung that burned its
        budget and failed must teach the estimator, or the ladder will keep
        promising it to every request (the cold-compile trap). TRANSIENT
        faults (``resilience.faults``, incl. the ``ladder.rung`` injection
        seam) are absorbed by a bounded backoff retry first — capped by
        ``budget_s`` so a retry can never outspend the request's deadline
        (``run`` must re-read the remaining budget itself, not capture a
        stale value, or the retry re-runs with time that no longer
        exists). Exhausted retries and real exceptions are counted, not
        propagated: the ladder's contract is that a well-formed instance
        always gets a tour from SOME rung.

        The estimator is fed SERVICE time — elapsed minus the scheduler
        queue wait the rung's ticket reported (``_tls.queue_wait``).
        Queueing is transient congestion the iteration-level loop and
        admission control are responsible for; folding it into the EWMA
        would let one head-of-line episode (a long proof slicing through)
        pin every later tight-deadline request to greedy long after the
        queue drained, because greedy answers never update the pipeline
        series again. Timeouts keep the full elapsed: a rung that burned
        its whole budget must still teach the estimator the cost of
        promising it."""
        t0 = time.monotonic()
        self._tls.queue_wait = 0.0

        def attempt_once():
            _fault_registry().fire("ladder.rung")
            return run()

        policy = RetryPolicy(
            max_attempts=1 + max(self.cfg.rung_retries, 0),
            base_delay_s=self.cfg.retry_base_delay_s,
            seed=0,
        )
        with _tracing.span("ladder.rung", tier=tier, n=n) as sp:
            try:
                result = policy.call(attempt_once, budget_s=budget_s)
                sp.set("outcome", "ok" if result is not None else "timeout")
                return result
            except Exception as e:  # noqa: BLE001 — degrade, never error
                sp.set("outcome", "failed")
                sp.set("error", f"{type(e).__name__}: {e}")
                with self._count_lock:
                    self.rung_failures[tier] += 1
                _REGISTRY.inc(
                    "serve_rung_failures_total", tier=tier
                )
                return None
            finally:
                elapsed = time.monotonic() - t0
                service = max(
                    elapsed - getattr(self._tls, "queue_wait", 0.0), 0.0
                )
                self.estimator.observe(tier, n, service)
                _REGISTRY.inc("serve_rung_attempts_total", tier=tier)
                # the wall metric keeps the FULL elapsed (what the
                # request experienced); only the estimator gets the
                # queue-corrected service time
                _REGISTRY.inc(
                    "serve_rung_seconds_total", max(elapsed, 0.0), tier=tier
                )

    def counts_snapshot(self) -> Tuple[Dict[str, int], Dict[str, int]]:
        """``(tier_counts, rung_failures)`` copied under the count lock —
        the accessor stats reporting must use instead of reaching into
        the dicts while request threads increment them (graftflow R9)."""
        with self._count_lock:
            return dict(self.tier_counts), dict(self.rung_failures)

    def _shed(self, tier: str) -> bool:
        """SLO-burn admission control: True when ``tier``'s live error
        budget is burning past ``cfg.slo_shed_burn`` — the request is
        degraded to the next rung DOWN and the shed is accounted
        (``serve_flushes_total{cause=slo_shed}``). Shedding new
        admissions is what lets the burning tier's existing queue drain
        back inside its objective."""
        bm = self.burn_meter
        if bm is None:
            return False
        b = bm.burn(tier)
        if b is None or b <= self.cfg.slo_shed_burn:
            return False
        self.scheduler.note_shed(tier)
        return True

    def upgrade_eligible(
        self, n: int, deadline_s: float, entry_tier: str, certified_gap
    ) -> bool:
        """Should a cached entry be RE-SOLVED for this request instead of
        served as a hit? True when the entry is not already exact/proven
        (``certified_gap == 0.0``) and a STRONGER rung than the one that
        produced it fits this request's budget — so a greedy answer cached
        under a tight deadline doesn't pin the instance to greedy forever.
        (A timed-out bnb certificate with gap > 0 is only re-attempted by
        a request whose budget fits bnb again.)"""
        if certified_gap == 0.0:
            return False
        if n < 3:
            return False
        cfg, est = self.cfg, self.estimator
        rank = _TIER_RANK.get(entry_tier, 0)
        if (
            rank <= _TIER_RANK["bnb"]
            and n <= cfg.bnb_max_n
            and deadline_s >= cfg.bnb_min_budget_s
            and deadline_s >= est.estimate("bnb", n, cfg.prior_s["bnb"])
        ):
            return True
        return rank < _TIER_RANK["pipeline"] and deadline_s >= est.estimate(
            "pipeline", n, cfg.prior_s["pipeline"]
        )

    # -- rung implementations ------------------------------------------------

    def _bnb_result(self, cost, tour, lb, proven: bool) -> LadderResult:
        if proven or cost <= lb:
            gap = 0.0
        else:
            gap = float(max(cost - lb, 0.0) / max(lb, 1e-12)) if np.isfinite(lb) else None
        return LadderResult(
            cost=float(cost),
            tour=np.asarray(tour, np.int32),
            tier="bnb",
            certified_gap=gap,
            lower_bound=float(lb),
        )

    def _run_bnb(self, d: np.ndarray, budget_s: float) -> Optional[LadderResult]:
        """The certified rung. An injected ``bnb_solver`` runs inline
        (tests pin that call shape); the default rung rides the
        scheduler's iteration-level loop in ``bnb_slice_s`` chunks, so a
        long proof yields the device between slices instead of
        monopolizing it. Returns None when the wait outlives the budget
        (the caller degrades; the job keeps slicing until ITS deadline
        and is simply discarded — its partial evidence still teaches the
        estimator)."""
        limit = budget_s * self.cfg.bnb_budget_fraction
        solver = self.cfg.bnb_solver
        if solver is not None:
            cost, tour, lb, proven = solver(d, limit)
            return self._bnb_result(cost, tour, lb, proven)
        job = self.scheduler.submit_bnb(
            d,
            budget_s=max(limit, 0.05),
            slice_s=self.cfg.bnb_slice_s,
            checkpoint_path=self._job_checkpoint_path(),
            solve_kw=dict(
                capacity=self.cfg.bnb_capacity,
                k=self.cfg.bnb_k,
                device_loop=False,  # fine-grained time-limit checks
            ),
        )
        res = job.wait(timeout=max(budget_s, 1e-3))
        n = d.shape[0]
        handle = job.handle
        if res is not None and not res.proven_optimal and handle is not None:
            # the rung finished UNPROVEN at its deadline (after >= 1
            # preemption): project the full proof cost from the partial
            # gap closure so tier selection stops over-promising bnb
            self.estimator.observe_partial(
                "bnb", n, handle.elapsed_s, handle.gap_progress()
            )
        if res is None:
            if handle is not None:
                self.estimator.observe_partial(
                    "bnb", n, handle.elapsed_s, handle.gap_progress()
                )
            return None
        return self._bnb_result(
            res.cost, res.tour, res.lower_bound, bool(res.proven_optimal)
        )

    def _run_pipeline(
        self, xy: np.ndarray, d: np.ndarray, budget_s: float
    ) -> Optional[LadderResult]:
        """Exact HK for one block; blocked HK + merge + polish for larger n.
        Returns None when the scheduler wait outlives the budget (the
        caller degrades to greedy; the batch result is discarded)."""
        n = d.shape[0]
        if n <= MAX_BLOCK_CITIES:
            # the sched.wait span is the queue-wait stage of the request
            # trace; the worker parents its flush span to it (the ticket
            # captures this span's context at submit)
            with _tracing.span("sched.wait", blocks=1) as sp:
                ticket = self.scheduler.submit(d[None])
                got = ticket.wait(timeout=max(budget_s, 1e-3))
                if got is None:
                    sp.set("outcome", "timeout")
                    return None
            self._tls.queue_wait = ticket.queue_age_s or 0.0
            costs, tours = got
            return LadderResult(
                cost=float(costs[0]),
                tour=np.asarray(tours[0], np.int32),
                tier="pipeline",
                certified_gap=0.0,  # Held-Karp is exact for a single block
            )
        return self._run_blocked(xy, d, budget_s)

    def _run_blocked(
        self, xy: np.ndarray, d: np.ndarray, budget_s: float
    ) -> Optional[LadderResult]:
        """n > 16: spatially-sorted blocked decomposition, the batched HK
        kernel per block, the repo's merge fold, then device polish. No
        certificate — the block decomposition is heuristic."""
        import jax.numpy as jnp

        from ..ops.local_search import polish, tour_length
        from ..ops.merge import fold_tours

        n = d.shape[0]
        t0 = time.monotonic()
        b = _largest_block_divisor(n)
        if b is None:
            # prime-ish n: greedy seed + device polish is still a real
            # improvement rung over raw greedy
            seed_cost, seed = _greedy(d)
            order = seed[:-1]
        else:
            # block-major spatial order (sort by x, then y) gives blocks
            # the merge operator can stitch with short splices
            order = np.lexsort((xy[:, 1], xy[:, 0])).astype(np.int64)
            blocks = order.reshape(n // b, b)
            block_d = d[blocks[:, :, None], blocks[:, None, :]]
            with _tracing.span("sched.wait", blocks=int(n // b)) as sp:
                ticket = self.scheduler.submit(block_d)
                got = ticket.wait(timeout=max(budget_s, 1e-3))
                if got is None:
                    sp.set("outcome", "timeout")
                    return None
            self._tls.queue_wait = ticket.queue_age_s or 0.0
            costs, tours = got
            # fold in global (request-space) ids via the resident matrix
            global_tours = np.asarray(blocks)[
                np.arange(blocks.shape[0])[:, None], np.asarray(tours, np.int64)
            ]
            ids, length, _cost = fold_tours(
                jnp.asarray(global_tours, jnp.int32),
                jnp.asarray(costs),
                jnp.asarray(d, jnp.float32),
            )
            order = np.asarray(ids)[: int(length)][:-1].astype(np.int64)
        remaining = budget_s - (time.monotonic() - t0)
        d32 = jnp.asarray(d, jnp.float32)
        t = jnp.asarray(order, jnp.int32)
        if remaining > 0:
            t, _ = polish(t, d32, max_rounds=self.cfg.polish_rounds)
        cost = float(tour_length(t, d32))
        open_t = np.asarray(t, np.int64)
        closed = np.concatenate([open_t, open_t[:1]]).astype(np.int32)
        return LadderResult(cost=cost, tour=closed, tier="pipeline", certified_gap=None)

    # -- dispatch ------------------------------------------------------------

    def solve(self, xy: np.ndarray, deadline_s: float) -> LadderResult:
        """Answer one request within ``deadline_s`` (measured from now)."""
        t_start = time.monotonic()
        xy = np.asarray(xy, np.float64)
        n = xy.shape[0]
        d = distance_matrix_np(xy)
        cfg, est = self.cfg, self.estimator

        def budget() -> float:
            return deadline_s - (time.monotonic() - t_start)

        result: Optional[LadderResult] = None
        if n >= 3:
            rem = budget()
            # a rung that throws (device OOM, failed batch, solver bug) must
            # degrade like a rung that timed out — the ladder's contract is
            # that a well-formed instance ALWAYS gets a tour, never an error
            if (
                n <= cfg.bnb_max_n
                and rem >= cfg.bnb_min_budget_s
                and rem >= est.estimate("bnb", n, cfg.prior_s["bnb"])
                and not self._shed("bnb")
            ):
                # budget() is re-read INSIDE the lambda: a retry after a
                # late transient fault must run with the time actually
                # left, not the full original rem (which would land the
                # response at ~2x the deadline)
                result = self._attempt(
                    "bnb", n,
                    lambda: self._run_bnb(d, max(budget(), 0.05)),
                    budget_s=rem,
                )
            elif budget() >= est.estimate(
                "pipeline", n, cfg.prior_s["pipeline"]
            ) and not self._shed("pipeline"):
                result = self._attempt(
                    "pipeline", n,
                    lambda: self._run_pipeline(xy, d, budget()),
                    budget_s=budget(),
                )
        if result is None:
            # the unconditional rung: valid closed tour at ANY deadline
            with _tracing.span("ladder.rung", tier="greedy", n=n) as sp:
                if n < 3:
                    cost, tour = _trivial_tour(n, d)
                else:
                    cost, tour = _greedy(d)
                sp.set("outcome", "ok")
            result = LadderResult(
                cost=cost,
                tour=tour,
                tier="greedy",
                certified_gap=0.0 if n < 3 else None,
            )
        with self._count_lock:
            self.tier_counts[result.tier] += 1
        _REGISTRY.inc("serve_tier_answers_total", tier=result.tier)
        return result

"""Canonical instance keys: dedupe resubmitted TSP instances.

A serving cache (Clipper's prediction cache, PAPERS.md) is only as good as
its key function. Raw coordinate bytes miss the two resubmission patterns
that actually occur: the same instance *translated* in the plane (Euclidean
TSP is translation-invariant) and the same instance with its cities listed
in a different *order* (the tour relabels trivially). This module maps an
instance to a canonical form that is invariant under both, plus float
jitter below half the quantization step:

1. quantize: ``q = rint(xy / step)`` snaps coordinates to an integer grid,
   absorbing sub-step noise (invariance holds for jitter strictly below
   ``step/2`` around a grid point — at exactly ``step/2`` rounding ties);
2. translate: ``q -= q.min(axis=0)`` pins the bounding-box corner to the
   origin (uniform for all cities, so any common shift cancels);
3. reorder: cities sort lexicographically by ``(qx, qy)`` — the unique
   minimal relabeling, so every permutation of the same city list lands on
   the same array (``np.lexsort`` is stable: quantization-tied cities keep
   their relative submission order; such cities are geometrically
   indistinguishable at the key's resolution, so either assignment maps a
   cached tour onto an equally-valid tour of the resubmitted instance);
4. hash: blake2b over ``n`` and the canonical int64 array.

The returned :class:`CanonicalInstance` keeps the sort permutation so a
tour cached in canonical city ids can be relabeled into any later
submission's city order (:func:`from_canonical_tour`) and vice versa.
"""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict
from dataclasses import dataclass
from typing import Optional

import numpy as np

#: default quantization step in coordinate units. The repo's workloads put
#: cities on [0, 1000]^2 grids (tsp.cpp:373-403 scale), so 1e-3 keeps ~6
#: significant digits — far below any distance that changes a tour — while
#: absorbing float32<->float64 round-trip noise (~1e-5 at that scale).
DEFAULT_STEP = 1e-3


@dataclass(frozen=True)
class CanonicalInstance:
    """Canonical form of one instance plus the maps back to request space."""

    key: str  #: hex digest — the cache key
    n: int
    #: [n] canonical position -> original city index (``xy[perm]`` is sorted)
    perm: np.ndarray
    #: [n] original city index -> canonical position (``perm``'s inverse)
    inv_perm: np.ndarray
    #: [n, 2] int64 quantized, origin-pinned, sorted coordinates
    qxy: np.ndarray


def canonicalize(xy, step: float = DEFAULT_STEP) -> CanonicalInstance:
    """Build the canonical key for an ``[n, 2]`` coordinate array.

    Raises ``ValueError`` on malformed input (wrong shape, empty, or
    non-finite coordinates) — the service turns that into an error
    response rather than a cache poisoning.
    """
    xy = np.asarray(xy, np.float64)
    _validate(xy, step)
    return _canonicalize_validated(xy, step)


def _validate(xy: np.ndarray, step: float) -> None:
    if xy.ndim != 2 or xy.shape[-1] != 2 or xy.shape[0] < 1:
        raise ValueError(f"expected [n>=1, 2] coordinates, got shape {xy.shape}")
    if not np.all(np.isfinite(xy)):
        raise ValueError("coordinates must be finite")
    if not step > 0:
        raise ValueError(f"quantization step must be > 0, got {step}")


def _canonicalize_validated(xy: np.ndarray, step: float) -> CanonicalInstance:
    return _canonicalize_from_q(int(xy.shape[0]), _quantize_pin(xy, step))


def _canonicalize_from_q(n: int, q: np.ndarray) -> CanonicalInstance:
    """Steps 3-4 (the lexsort + hash) from quantized pinned coordinates."""
    # lexicographic-minimal city order: primary qx, secondary qy (np.lexsort
    # keys are listed least-significant first)
    perm = np.lexsort((q[:, 1], q[:, 0])).astype(np.int64)
    qs = np.ascontiguousarray(q[perm])
    h = hashlib.blake2b(digest_size=16)
    h.update(np.int64(qs.shape[0]).tobytes())
    h.update(qs.tobytes())
    inv = np.empty_like(perm)
    inv[perm] = np.arange(perm.shape[0], dtype=np.int64)
    return CanonicalInstance(
        key=h.hexdigest(), n=n, perm=perm, inv_perm=inv, qxy=qs
    )


class CanonicalCache:
    """Bounded memo of the canonicalization itself: raw-order digest ->
    :class:`CanonicalInstance`.

    The service's host path used to pay the full ``canonicalize`` —
    including the O(n log n) lexsort and the inverse-permutation build —
    on every request, cache HITS included (the solution cache can only be
    consulted after the key exists). But the dominant resubmission pattern
    is byte-identical-after-quantization: the same instance re-sent in the
    same city order, possibly translated and jittered. For those, the
    quantized origin-pinned (UNSORTED) coordinates are already identical,
    so a digest of that array is enough to recall the stored perm map and
    key without re-sorting anything. Translation/jitter invariance is
    inherited from the quantize+pin steps; a *permuted* resubmission has
    different raw bytes and pays the one lexsort that genuinely cannot be
    skipped (the permutation is unknown until sorted), landing on the same
    final key via the slow path.

    ``sorts_saved`` counts fast-path hits — surfaced in the service cache
    stats so the trimmed host path is measured, not asserted. Thread-safe;
    entries are immutable (CanonicalInstance is frozen and its arrays are
    never mutated by consumers).
    """

    def __init__(self, capacity: int = 4096) -> None:
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self._entries: "OrderedDict[str, CanonicalInstance]" = OrderedDict()
        self._lock = threading.Lock()
        self.sorts_saved = 0
        self.raw_misses = 0

    @staticmethod
    def _raw_key(q: np.ndarray) -> str:
        h = hashlib.blake2b(digest_size=16)
        h.update(np.int64(q.shape[0]).tobytes())
        h.update(np.ascontiguousarray(q).tobytes())
        return h.hexdigest()

    def get(self, q: np.ndarray) -> Optional[CanonicalInstance]:
        key = self._raw_key(q)
        with self._lock:
            ci = self._entries.get(key)
            if ci is None:
                self.raw_misses += 1
                return None
            self._entries.move_to_end(key)
            self.sorts_saved += 1
            from ..perf.compile_cache import STATS as _PERF_STATS

            _PERF_STATS.incr("canonical_sorts_saved")
            return ci

    def put(self, q: np.ndarray, ci: CanonicalInstance) -> None:
        key = self._raw_key(q)
        with self._lock:
            self._entries[key] = ci
            self._entries.move_to_end(key)
            while len(self._entries) > self.capacity:
                self._entries.popitem(last=False)

    def stats(self) -> dict:
        with self._lock:
            return {
                "sorts_saved": self.sorts_saved,
                "raw_misses": self.raw_misses,
                "size": len(self._entries),
            }


def _quantize_pin(xy: np.ndarray, step: float) -> np.ndarray:
    """Steps 1-2 of the canonicalization (quantize + origin-pin), shared
    by the full path and the sort-skipping fast path."""
    q = np.rint(xy / step).astype(np.int64)
    q -= q.min(axis=0)  # translation invariance: pin bbox corner to origin
    return q


def canonicalize_cached(
    xy, cache: Optional[CanonicalCache], step: float = DEFAULT_STEP
) -> CanonicalInstance:
    """:func:`canonicalize` with the lexsort short-circuited through
    ``cache`` for byte-identical (post-quantization) resubmissions. With
    ``cache=None`` this IS ``canonicalize``. Validation always runs — a
    malformed request must fail identically on both paths."""
    xy = np.asarray(xy, np.float64)
    _validate(xy, step)
    if cache is None:
        return _canonicalize_validated(xy, step)
    q = _quantize_pin(xy, step)
    ci = cache.get(q)
    if ci is None:
        ci = _canonicalize_from_q(xy.shape[0], q)
        cache.put(q, ci)
    return ci


def to_canonical_tour(tour: np.ndarray, ci: CanonicalInstance) -> np.ndarray:
    """Relabel a tour of request-space city ids into canonical ids."""
    return ci.inv_perm[np.asarray(tour, np.int64)].astype(np.int32)


def from_canonical_tour(tour: np.ndarray, ci: CanonicalInstance) -> np.ndarray:
    """Relabel a cached canonical-id tour into this request's city ids."""
    return ci.perm[np.asarray(tour, np.int64)].astype(np.int32)


def tour_length_np(tour: np.ndarray, xy: np.ndarray) -> float:
    """True Euclidean length of a CLOSED tour under the request's own
    (unquantized) coordinates — re-measured on every cache hit so the
    reported cost is honest for *this* submission, not the one that
    populated the cache (they can differ by sub-step jitter).

    Edge lengths use the repo-wide ``sqrt(sum(diff*diff))`` form
    (``ops.distance.distance_matrix_np``), NOT ``np.hypot`` — hypot rounds
    differently at the ULP level, and service costs must be comparable
    bit-for-bit with every other entry point's."""
    t = np.asarray(tour, np.int64)
    p = np.asarray(xy, np.float64)[t]
    diff = p[1:] - p[:-1]
    return float(np.sqrt(np.sum(diff * diff, axis=-1)).sum())

"""Canonical instance keys: dedupe resubmitted TSP instances.

A serving cache (Clipper's prediction cache, PAPERS.md) is only as good as
its key function. Raw coordinate bytes miss the two resubmission patterns
that actually occur: the same instance *translated* in the plane (Euclidean
TSP is translation-invariant) and the same instance with its cities listed
in a different *order* (the tour relabels trivially). This module maps an
instance to a canonical form that is invariant under both, plus float
jitter below half the quantization step:

1. quantize: ``q = rint(xy / step)`` snaps coordinates to an integer grid,
   absorbing sub-step noise (invariance holds for jitter strictly below
   ``step/2`` around a grid point — at exactly ``step/2`` rounding ties);
2. translate: ``q -= q.min(axis=0)`` pins the bounding-box corner to the
   origin (uniform for all cities, so any common shift cancels);
3. reorder: cities sort lexicographically by ``(qx, qy)`` — the unique
   minimal relabeling, so every permutation of the same city list lands on
   the same array (``np.lexsort`` is stable: quantization-tied cities keep
   their relative submission order; such cities are geometrically
   indistinguishable at the key's resolution, so either assignment maps a
   cached tour onto an equally-valid tour of the resubmitted instance);
4. hash: blake2b over ``n`` and the canonical int64 array.

The returned :class:`CanonicalInstance` keeps the sort permutation so a
tour cached in canonical city ids can be relabeled into any later
submission's city order (:func:`from_canonical_tour`) and vice versa.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass

import numpy as np

#: default quantization step in coordinate units. The repo's workloads put
#: cities on [0, 1000]^2 grids (tsp.cpp:373-403 scale), so 1e-3 keeps ~6
#: significant digits — far below any distance that changes a tour — while
#: absorbing float32<->float64 round-trip noise (~1e-5 at that scale).
DEFAULT_STEP = 1e-3


@dataclass(frozen=True)
class CanonicalInstance:
    """Canonical form of one instance plus the maps back to request space."""

    key: str  #: hex digest — the cache key
    n: int
    #: [n] canonical position -> original city index (``xy[perm]`` is sorted)
    perm: np.ndarray
    #: [n] original city index -> canonical position (``perm``'s inverse)
    inv_perm: np.ndarray
    #: [n, 2] int64 quantized, origin-pinned, sorted coordinates
    qxy: np.ndarray


def canonicalize(xy, step: float = DEFAULT_STEP) -> CanonicalInstance:
    """Build the canonical key for an ``[n, 2]`` coordinate array.

    Raises ``ValueError`` on malformed input (wrong shape, empty, or
    non-finite coordinates) — the service turns that into an error
    response rather than a cache poisoning.
    """
    xy = np.asarray(xy, np.float64)
    if xy.ndim != 2 or xy.shape[-1] != 2 or xy.shape[0] < 1:
        raise ValueError(f"expected [n>=1, 2] coordinates, got shape {xy.shape}")
    if not np.all(np.isfinite(xy)):
        raise ValueError("coordinates must be finite")
    if not step > 0:
        raise ValueError(f"quantization step must be > 0, got {step}")
    q = np.rint(xy / step).astype(np.int64)
    q -= q.min(axis=0)  # translation invariance: pin bbox corner to origin
    # lexicographic-minimal city order: primary qx, secondary qy (np.lexsort
    # keys are listed least-significant first)
    perm = np.lexsort((q[:, 1], q[:, 0])).astype(np.int64)
    qs = np.ascontiguousarray(q[perm])
    h = hashlib.blake2b(digest_size=16)
    h.update(np.int64(qs.shape[0]).tobytes())
    h.update(qs.tobytes())
    inv = np.empty_like(perm)
    inv[perm] = np.arange(perm.shape[0], dtype=np.int64)
    return CanonicalInstance(
        key=h.hexdigest(), n=int(xy.shape[0]), perm=perm, inv_perm=inv, qxy=qs
    )


def to_canonical_tour(tour: np.ndarray, ci: CanonicalInstance) -> np.ndarray:
    """Relabel a tour of request-space city ids into canonical ids."""
    return ci.inv_perm[np.asarray(tour, np.int64)].astype(np.int32)


def from_canonical_tour(tour: np.ndarray, ci: CanonicalInstance) -> np.ndarray:
    """Relabel a cached canonical-id tour into this request's city ids."""
    return ci.perm[np.asarray(tour, np.int64)].astype(np.int32)


def tour_length_np(tour: np.ndarray, xy: np.ndarray) -> float:
    """True Euclidean length of a CLOSED tour under the request's own
    (unquantized) coordinates — re-measured on every cache hit so the
    reported cost is honest for *this* submission, not the one that
    populated the cache (they can differ by sub-step jitter).

    Edge lengths use the repo-wide ``sqrt(sum(diff*diff))`` form
    (``ops.distance.distance_matrix_np``), NOT ``np.hypot`` — hypot rounds
    differently at the ULP level, and service costs must be comparable
    bit-for-bit with every other entry point's."""
    t = np.asarray(tour, np.int64)
    p = np.asarray(xy, np.float64)[t]
    diff = p[1:] - p[:-1]
    return float(np.sqrt(np.sum(diff * diff, axis=-1)).sum())

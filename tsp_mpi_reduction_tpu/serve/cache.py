"""Bounded LRU solution cache (Clipper-style prediction cache, PAPERS.md).

Keys are canonical instance digests (``serve.canonical``); values are
:class:`CacheEntry` — the solved cost, the CLOSED tour in *canonical* city
ids (so one cached solution serves every translated/permuted resubmission),
the certified optimality gap when a certificate exists, and the ladder tier
that produced it. Hit/miss/eviction counters feed the service's
machine-readable stats (``utils.reporting.service_stats_json``).

Thread-safe: every request thread of the service touches this cache
concurrently, so all state mutation happens under one lock (the critical
sections are O(1) dict operations — no solver work is ever done inside).
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from dataclasses import dataclass
from typing import Dict, Optional

import numpy as np

from ..obs.metrics import REGISTRY as _REGISTRY
from ..resilience.faults import registry as _fault_registry


@dataclass(frozen=True)
class CacheEntry:
    cost: float
    #: [n+1] CLOSED tour in canonical city ids (tour[0] == tour[-1])
    tour: np.ndarray
    #: certified optimality gap: 0.0 for a proven-optimal / exact answer,
    #: (cost - lower_bound) / lower_bound for a timed-out B&B certificate,
    #: None when the answering tier carries no certificate (heuristic rungs)
    certified_gap: Optional[float]
    tier: str

    def better_than(self, other: "CacheEntry") -> bool:
        """Replacement policy: a strictly cheaper tour always wins; at equal
        cost, an entry WITH a certificate beats one without, and a tighter
        certificate beats a looser one."""
        if self.cost < other.cost:
            return True
        if self.cost > other.cost:
            return False
        if self.certified_gap is None:
            return False
        return other.certified_gap is None or self.certified_gap < other.certified_gap


class SolutionCache:
    """Bounded LRU: ``get`` refreshes recency, ``put`` evicts the coldest
    entry past ``capacity``. A ``put`` for an existing key only replaces
    the stored entry when the new one is :meth:`CacheEntry.better_than`
    the old — a later greedy answer (tight deadline) must never clobber a
    cached certified optimum for the same instance."""

    def __init__(self, capacity: int = 4096) -> None:
        if capacity < 1:
            raise ValueError(f"cache capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self._entries: "OrderedDict[str, CacheEntry]" = OrderedDict()
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def get(self, key: str) -> Optional[CacheEntry]:
        # fault seam OUTSIDE the lock (a delay-mode fault must not wedge
        # every other request thread); the service absorbs transient
        # lookup faults by retrying, then degrades to a cache miss
        _fault_registry().fire("cache.get")
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                self.misses += 1
            else:
                self._entries.move_to_end(key)
                self.hits += 1
        _REGISTRY.inc(
            "serve_cache_lookups_total",
            result="miss" if entry is None else "hit",
        )
        return entry

    def put(self, key: str, entry: CacheEntry) -> None:
        _fault_registry().fire("cache.put")
        evicted = 0
        with self._lock:
            old = self._entries.get(key)
            if old is None or entry.better_than(old):
                self._entries[key] = entry
            self._entries.move_to_end(key)
            while len(self._entries) > self.capacity:
                self._entries.popitem(last=False)
                self.evictions += 1
                evicted += 1
        if evicted:
            _REGISTRY.inc("serve_cache_evictions_total", evicted)

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "size": len(self._entries),
                "capacity": self.capacity,
            }

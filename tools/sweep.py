"""Benchmark sweep: the reference's test.sh, re-hosted.

The reference sweeps cities/block 5-10 x blocks 10..200 step 10 x
procs 2..20 step 2 on a 1000x1000 grid and greps time/cost from the last
stdout line into ``results.csv`` with header
``numCities,numBlocks,numProcs,time,cost`` (test.sh:1-24). This driver
emits the identical CSV schema, with the ``numProcs`` axis served by the
rank-emulated merge tree (same assignment, same tree order as a p-rank MPI
run) so the sweep runs on any machine.

Usage:
    python tools/sweep.py [--out FILE] [--quick] [--backend=...]
                          [--dtype=float64|float32]

``--out`` defaults to ``results.csv``, or ``results_quick.csv`` under
``--quick`` so smoke runs never clobber the committed full-sweep
artifact; overwriting a ≥100-row file additionally requires ``--force``.
``--quick`` restricts to a small config subset (smoke-test mode). The full
1200-config sweep compiles one XLA program per distinct shape; with the
persistent compilation cache later sweeps are much faster.
"""

from __future__ import annotations

import argparse
import sys
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])  # repo root

from tsp_mpi_reduction_tpu.utils import reporting  # noqa: E402
from tsp_mpi_reduction_tpu.utils.backend import select_backend  # noqa: E402


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None)
    ap.add_argument("--grid", type=int, default=1000)  # test.sh:2
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--backend", default="auto", choices=["auto", "cpu", "tpu"])
    ap.add_argument("--dtype", default=None, choices=["float64", "float32"])
    ap.add_argument(
        "--resume", action="store_true",
        help="append to --out, skipping configs it already contains",
    )
    ap.add_argument(
        "--force", action="store_true",
        help="allow overwriting an existing large (non-quick) results file",
    )
    # axis overrides (comma lists) for stated-subset sweeps — e.g. the TPU
    # sweep runs all cities x all blocks at procs=8 (the north-star rank
    # count) because 1200 distinct shapes = 1200 XLA compiles through the
    # relay, which would eat the whole chip-grant window
    ap.add_argument("--cities", default=None,
                    help="comma list overriding the cities/block axis")
    ap.add_argument("--blocks", default=None,
                    help="comma list overriding the blocks axis")
    ap.add_argument("--procs", default=None,
                    help="comma list overriding the procs axis")
    args = ap.parse_args()
    if args.out is None:
        # quick smoke runs must not clobber the committed 1200-row artifact
        # (that happened once: a --quick run overwrote results.csv and the
        # truncation was committed unnoticed)
        args.out = "results_quick.csv" if args.quick else "results.csv"
    if not args.resume and not args.force:
        try:
            with open(args.out) as f:
                existing = sum(1 for _ in f) - 1
        except OSError:
            existing = 0
        if existing >= 100:
            ap.error(
                f"{args.out} holds {existing} data rows; refusing to "
                "overwrite a full sweep artifact (use --resume, --force, "
                "or a different --out)"
            )

    # lint gate before burning hours of sweep: a hot-loop host pull or
    # re-jitting loop (graftlint R1-R5, README) invalidates the timing
    # columns this harness exists to produce
    from tsp_mpi_reduction_tpu.analysis.__main__ import main as graftlint
    if graftlint(["--quiet"]) != 0:
        print("sweep: graftlint found new violations; fix or baseline "
              "them first (python -m tsp_mpi_reduction_tpu.analysis)",
              file=sys.stderr)
        return 2

    platform = select_backend(args.backend)
    from tsp_mpi_reduction_tpu.utils.backend import enable_persistent_cache

    enable_persistent_cache(platform)  # re-sweeps skip the slow compiles
    dtype = args.dtype or ("float64" if platform == "cpu" else "float32")
    import jax

    if dtype == "float64":
        jax.config.update("jax_enable_x64", True)

    from tsp_mpi_reduction_tpu.models.distributed import run_pipeline_ranks

    if args.quick:
        cities = [5, 8]
        blocks = [10, 50]
        procs = [2, 8]
    else:  # test.sh:5,9,12
        cities = range(5, 11)
        blocks = range(10, 201, 10)
        procs = range(2, 21, 2)
    if args.cities:
        cities = [int(x) for x in args.cities.split(",")]
    if args.blocks:
        blocks = [int(x) for x in args.blocks.split(",")]
    if args.procs:
        procs = [int(x) for x in args.procs.split(",")]

    # resume: skip configs already in the CSV (a full sweep is hours; the
    # process may be restarted), identified by their first three columns
    done = set()
    if args.resume:
        try:
            with open(args.out) as f:
                for ln in f:
                    parts = ln.strip().split(",")
                    if len(parts) == 5 and parts[0].isdigit():
                        done.add((int(parts[0]), int(parts[1]), int(parts[2])))
        except OSError:
            pass

    # resume must never truncate: even if no existing row parsed (foreign
    # schema, partial file), append rather than clobber
    exists = False
    if args.resume:
        import os

        try:
            exists = os.path.getsize(args.out) > 0
        except OSError:
            pass
    mode = "a" if (args.resume and exists) else "w"
    # a killed sweep can leave a partial (unterminated) last line — appending
    # straight onto it would corrupt the row; terminate it first. The partial
    # row was never counted as done (it doesn't parse as 5 fields), so its
    # config reruns.
    needs_nl = False
    if mode == "a":
        try:
            with open(args.out, "rb") as f:
                f.seek(-1, 2)
                needs_nl = f.read(1) != b"\n"
        except OSError:
            pass
    since_clear = 0
    with open(args.out, mode) as f:
        if mode == "w":
            f.write(reporting.CSV_HEADER + "\n")
        elif needs_nl:
            f.write("\n")
        for n in cities:
            for nb in blocks:
                for p in procs:
                    if (n, nb, p) in done:
                        continue
                    t0 = time.perf_counter()
                    res = run_pipeline_ranks(n, nb, args.grid, args.grid, p, dtype=dtype)
                    ms = int((time.perf_counter() - t0) * 1000)
                    row = reporting.csv_row(n, nb, p, ms, res.cost)
                    print(row)
                    f.write(row + "\n")
                    f.flush()
                    # every distinct (n, nb, p) shape compiles a fresh XLA
                    # program; dropping the caches periodically keeps a
                    # 1200-config sweep from exhausting host memory
                    since_clear += 1
                    if since_clear >= 40:
                        jax.clear_caches()
                        since_clear = 0
    return 0


if __name__ == "__main__":
    sys.exit(main())

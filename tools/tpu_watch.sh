#!/usr/bin/env bash
# TPU chip-grant watcher (round 4).
#
# The remote-TPU relay's claim/grant handshake blocks indefinitely when no
# chip is granted to this container (round 3: grant lapsed mid-round and
# never returned). This loop probes the claim on an interval, appends a
# timestamped record per attempt to TPU_CLAIM_LOG.jsonl (the auditable
# evidence trail VERDICT.md round-3 item 1 asks for if the outage
# persists), and the moment a probe succeeds runs tools/tpu_bench.sh to
# capture every TPU artifact in one shot.
#
# Usage: tools/tpu_watch.sh [interval_seconds] [probe_timeout_seconds]
set -uo pipefail
cd "$(dirname "$0")/.."

INTERVAL="${1:-480}"
PROBE_TIMEOUT="${2:-180}"
LOG=TPU_CLAIM_LOG.jsonl

while true; do
    ts="$(date -u +%Y-%m-%dT%H:%M:%SZ)"
    out="$(timeout "$PROBE_TIMEOUT" python -c \
        'import jax; d=jax.devices(); print("PLATFORM="+d[0].platform)' 2>&1)"
    rc=$?
    platform="$(printf '%s' "$out" | sed -n 's/^PLATFORM=//p' | tail -1)"
    if [ $rc -eq 0 ] && [ -n "$platform" ] && [ "$platform" != "cpu" ]; then
        echo "{\"ts\": \"$ts\", \"ok\": true, \"platform\": \"$platform\"}" >> "$LOG"
        echo "tpu_watch: chip granted ($platform) at $ts — capturing artifacts" >&2
        bash tools/tpu_bench.sh > tpu_bench_run.log 2>&1
        brc=$?
        ts2="$(date -u +%Y-%m-%dT%H:%M:%SZ)"
        if [ $brc -eq 0 ]; then
            echo "{\"ts\": \"$ts2\", \"capture\": \"complete\"}" >> "$LOG"
        else
            echo "{\"ts\": \"$ts2\", \"capture\": \"FAILED rc=$brc\"}" >> "$LOG"
        fi
        exit 0
    fi
    reason="timeout after ${PROBE_TIMEOUT}s (claim/grant handshake never completed)"
    [ $rc -ne 124 ] && reason="probe rc=$rc: $(printf '%s' "$out" | tail -c 200 | tr '"\n' ' ' )"
    echo "{\"ts\": \"$ts\", \"ok\": false, \"reason\": \"$reason\"}" >> "$LOG"
    sleep "$INTERVAL"
done

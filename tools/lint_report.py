#!/usr/bin/env python
"""CI lint digest: per-rule counts + baseline deltas for the combined
graftlint (R1-R8, R13) + graftflow (R9-R12) run.

``make lint`` already fails the build on new findings; this tool exists
for the CI LOG — one table a human can read in the job output (and one
optional SARIF artifact for inline annotations) without re-running the
passes locally:

    python tools/lint_report.py [paths...] [--sarif out.sarif]

Prints, per rule: new findings, baseline-accepted sites, and the rule's
one-line hazard description; then the baseline delta block (stale entries
= fixed-but-still-listed, dead entries = scope gone, the ratchet's fail
condition). Exit code mirrors the gate: 0 clean, 1 new/dead, 2 usage.

Stdout-only (plus the explicit --sarif artifact): a report tool must not
write surprise files into a CI workspace.
"""

from __future__ import annotations

import argparse
import pathlib
import sys

_REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(_REPO))

from tsp_mpi_reduction_tpu.analysis.__main__ import (  # noqa: E402
    ALL_RULES,
    _DEFAULT_BASELINE,
    _DEFAULT_TARGETS,
    _REPO_ROOT,
    run_analyses,
)
from tsp_mpi_reduction_tpu.analysis.graftlint import (  # noqa: E402
    apply_baseline,
    find_dead_scopes,
    load_baseline,
)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="lint_report", description=__doc__.splitlines()[0]
    )
    ap.add_argument("paths", nargs="*", type=pathlib.Path)
    ap.add_argument("--baseline", type=pathlib.Path, default=_DEFAULT_BASELINE)
    ap.add_argument("--sarif", type=pathlib.Path, default=None,
                    help="also write NEW findings as a SARIF 2.1.0 log")
    args = ap.parse_args(argv)

    if args.paths:
        missing = [p for p in args.paths if not p.exists()]
        if missing:
            print("lint_report: no such path(s): "
                  + ", ".join(str(p) for p in missing))
            return 2
        targets = list(args.paths)
    else:
        targets = [p for p in _DEFAULT_TARGETS if p.exists()]

    violations = run_analyses(targets, set(ALL_RULES))
    baseline = load_baseline(args.baseline)
    res = apply_baseline(violations, baseline)
    dead = find_dead_scopes(baseline, _REPO_ROOT)
    stale = [fp for fp in res.stale if fp not in set(dead)]

    if args.sarif is not None:
        from tsp_mpi_reduction_tpu.analysis.sarif import write_sarif

        write_sarif(args.sarif, res.new, ALL_RULES)

    per_rule = {rid: [0, 0] for rid in ALL_RULES}
    for v in res.new:
        per_rule.setdefault(v.rule, [0, 0])[0] += 1
    for v in res.accepted:
        per_rule.setdefault(v.rule, [0, 0])[1] += 1

    print(f"lint report — {len(targets)} target(s), rules R1-R12 "
          "(graftlint syntactic + graftflow dataflow)")
    print(f"{'rule':<5} {'new':>4} {'base':>5}  hazard")
    for rid in sorted(per_rule, key=lambda r: int(r[1:])):
        new_n, base_n = per_rule[rid]
        marker = " <-- FIX OR DISABLE" if new_n else ""
        print(f"{rid:<5} {new_n:>4} {base_n:>5}  {ALL_RULES[rid]}{marker}")
    for v in res.new:
        print(f"  {v.path}:{v.line}: {v.rule} [{v.scope}] {v.message}")

    print(
        f"baseline: {len(baseline)} entries, {len(res.accepted)} matched, "
        f"{len(stale)} stale, {len(dead)} dead"
    )
    for fp in stale:
        print(f"  stale (fixed? regenerate): {fp}")
    for fp in dead:
        print(f"  DEAD (scope gone — delete or regenerate): {fp}")
    if args.sarif is not None:
        print(f"sarif: {len(res.new)} result(s) -> {args.sarif}")
    verdict = "FAIL" if (res.new or dead) else "ok"
    print(f"verdict: {verdict} ({len(res.new)} new, {len(dead)} dead)")
    return 1 if (res.new or dead) else 0


if __name__ == "__main__":
    sys.exit(main())

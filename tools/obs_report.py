"""Render a human-readable run report from obs telemetry artifacts.

Inputs (any combination):

- ``--trace FILE``    span-trace JSONL (serve ``--trace`` / ``TSP_TRACE``)
- ``--series FILE``   a ``bnb_solve.py`` JSON line (or a file of lines —
                      the chunked driver's stdout) whose ``series`` block
                      carries the per-dispatch sampler rows
- ``--metrics FILE``  a ``/metrics.json`` snapshot dump

Output is plain text on stdout: per-trace span trees with durations,
per-column series statistics with a coarse text sparkline, and the top
metric series. No third-party deps, no file writes.

Usage:
    python tools/obs_report.py --trace traces/serve.jsonl
    python tools/obs_report.py --series solve_out.json
    python tools/obs_report.py --trace t.jsonl --series s.json --limit 5
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from tsp_mpi_reduction_tpu.obs import tracing  # noqa: E402

_SPARK = "▁▂▃▄▅▆▇█"


def _sparkline(values: List[float], width: int = 48) -> str:
    vals = [v for v in values if v is not None]
    if not vals:
        return ""
    if len(vals) > width:  # decimate to the display width, preserving shape
        stride = len(vals) / width
        vals = [vals[int(i * stride)] for i in range(width)]
    lo, hi = min(vals), max(vals)
    span = (hi - lo) or 1.0
    return "".join(
        _SPARK[min(int((v - lo) / span * (len(_SPARK) - 1)), len(_SPARK) - 1)]
        for v in vals
    )


def _fmt_attrs(attrs: Dict) -> str:
    if not attrs:
        return ""
    inner = ", ".join(f"{k}={v}" for k, v in sorted(attrs.items()))
    return f" [{inner}]"


def _render_node(node: Dict, depth: int, out: List[str]) -> None:
    sp = node["span"]
    out.append(
        f"{'  ' * depth}{sp['name']}  {sp['dur_ms']:.2f} ms"
        f"{_fmt_attrs(sp.get('attrs', {}))}"
    )
    for ev in sp.get("events", []):
        out.append(
            f"{'  ' * (depth + 1)}! event {ev['name']}"
            f"{_fmt_attrs(ev.get('attrs', {}))}"
        )
    for child in node["children"]:
        _render_node(child, depth + 1, out)


def render_trace(paths, limit: Optional[int] = None) -> str:
    """Render one or more trace JSONL sinks as span trees. Several paths
    are STITCHED before reconstruction (ISSUE 9: a chunked campaign's
    parent + chunk subprocesses may leave spans across files — the union
    reconstructs as one tree per trace_id, exactly like a single file)."""
    if isinstance(paths, str):
        paths = [paths]
    for p in paths:
        # every path here was EXPLICITLY named by the caller — a typo'd
        # or never-created sink must be an error, not a healthy-looking
        # "0 spans, 0 orphans" (read_traces' skip-unreadable lenience is
        # for programmatic stitching, where sinks may legitimately be
        # partial)
        if not os.path.exists(p):
            raise OSError(f"trace sink not found: {p!r}")
    spans = tracing.read_traces(list(paths))
    trees = tracing.build_trees(spans)
    orphans = tracing.orphan_spans(spans)
    label = ", ".join(paths)
    out: List[str] = [
        f"== trace {label}: {len(spans)} spans, {len(trees)} traces, "
        f"{len(orphans)} orphans =="
    ]
    items = sorted(
        trees.items(),
        key=lambda kv: min(
            (n["span"]["ts"] for n in kv[1]["roots"]), default=0.0
        ),
    )
    shown = items if limit is None else items[:limit]
    for trace_id, tree in shown:
        out.append(f"- trace {trace_id}")
        for root in tree["roots"]:
            _render_node(root, 1, out)
        for orphan in tree["orphans"]:
            out.append(
                f"  ?? ORPHAN {orphan['name']} "
                f"(parent {orphan.get('parent_id')} missing)"
            )
    if limit is not None and len(items) > limit:
        out.append(f"... {len(items) - limit} more traces")
    return "\n".join(out)


def render_series(path: str) -> str:
    out: List[str] = []
    with open(path, encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                doc = json.loads(line)
            except json.JSONDecodeError:
                continue
            series = doc.get("series") if isinstance(doc, dict) else None
            if not series or not series.get("rows"):
                continue
            cols, rows = series["columns"], series["rows"]
            name = doc.get("instance", "?")
            out.append(
                f"== series {path} [{name}]: {series['samples_total']} "
                f"samples ({series['samples_dropped']} rolled off) =="
            )
            by_col = {c: [r[i] for r in rows] for i, c in enumerate(cols)}
            for col in cols:
                vals = [v for v in by_col[col] if v is not None]
                if not vals:
                    out.append(f"  {col:>16}: (no finite samples)")
                    continue
                out.append(
                    f"  {col:>16}: min {min(vals):.3f}  "
                    f"mean {sum(vals) / len(vals):.3f}  max {max(vals):.3f}  "
                    f"{_sparkline(by_col[col])}"
                )
    if not out:
        out.append(f"== series {path}: no series block found ==")
    return "\n".join(out)


def render_metrics(path: str, top: int = 20) -> str:
    with open(path, encoding="utf-8") as fh:
        data = json.load(fh)
    out: List[str] = [f"== metrics {path}: {len(data)} metrics =="]
    for name in sorted(data):
        m = data[name]
        out.append(f"  {name} ({m['kind']})")
        for entry in m["series"][:top]:
            labels = ",".join(f"{k}={v}" for k, v in sorted(entry["labels"].items()))
            if "hist" in entry:
                h = entry["hist"]
                mean = h["sum"] / h["count"] if h["count"] else 0.0
                val = f"count {h['count']}  mean {mean:.4f}s"
            else:
                val = f"{entry['value']:g}"
            out.append(f"    {{{labels}}} {val}")
    return "\n".join(out)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="render obs trace/series/metrics artifacts as text"
    )
    ap.add_argument("--trace", default=None, action="append",
                    help="span JSONL path (repeatable: several sinks are "
                    "stitched into one reconstruction — multi-file "
                    "campaign traces)")
    ap.add_argument("--series", default=None,
                    help="bnb_solve JSON (line file ok) with a series block")
    ap.add_argument("--metrics", default=None, help="/metrics.json dump")
    ap.add_argument("--limit", type=int, default=None,
                    help="max traces to render")
    args = ap.parse_args(argv)
    if not (args.trace or args.series or args.metrics):
        ap.error("give at least one of --trace / --series / --metrics")
    sections = []
    try:
        if args.trace:
            sections.append(render_trace(args.trace, args.limit))
        if args.series:
            sections.append(render_series(args.series))
        if args.metrics:
            sections.append(render_metrics(args.metrics))
    except OSError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    try:
        print("\n\n".join(sections))
    except BrokenPipeError:
        return 0  # `| head` closed the pipe: normal CLI behavior
    return 0


if __name__ == "__main__":
    sys.exit(main())
